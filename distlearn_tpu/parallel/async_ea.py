"""Asynchronous EASGD over a hub-and-spoke parameter server — the TPU-native
rebuild of lua/AsyncEA.lua.

Three roles (reference export surface lua/AsyncEA.lua:294-303):

* **server** — holds the authoritative center variable pinned host-side, does
  no training; admits ONE client at a time through the ``Enter?``/``Enter``
  critical section (lua :163-177), streams the center, receives the elastic
  delta, applies ``center += delta`` (lua :198-228).
* **client** — trains locally; every ``tau``-th step runs the sync handshake:
  ``Enter?`` → fetch center → local elastic move ``delta=(p-c)*alpha;
  p-=delta`` (lua :109-119) → push delta.
* **tester** — a dedicated evaluation process the server pushes the center to
  every ``testTime`` syncs (lua :239-292).

Socket topology (examples/EASGD_server.lua:67-77): broadcast channel on
``port`` (all clients), one dedicated per-client channel on ``port + i``,
test channel on ``port + numNodes + 1``.

TPU-native stance: genuinely asynchronous point-to-point against a live
center does not fit the SPMD/XLA model, so this is the one subsystem built on
the host-side transport (C++ framing hot path, distlearn_tpu.comm) rather
than ICI collectives — exactly mirroring where the reference was native
(SURVEY.md §7 "hard parts").  Device↔host staging happens only at the
``tau``-spaced sync points, so the hot local-step loop stays on-device.

Params cross this API as pytrees; leaves are converted with ``np.asarray`` /
left as numpy — callers using jax arrays get numpy back and re-place onto
device (see examples/easgd_client.py).
"""

from __future__ import annotations

import select
import time
from typing import Any

import jax
import numpy as np

from distlearn_tpu import obs
from distlearn_tpu.comm import Conn, ProtocolError, Server, connect, wire
from distlearn_tpu.utils.logging import print_client, print_server, print_tester

PyTree = Any

ENTER_Q = "Enter?"
ENTER = "Enter"
REJOIN_Q = "Rejoin?"
REJOIN = "Rejoin"
CENTER_Q = "Center?"
DELTA_Q = "delta?"
DELTA = "delta"
TEST_Q = "Test?"
ACK = "Ack"

# ---------------------------------------------------------------------------
# Wire negotiation (packed 'P' frames + codecs, comm/wire.py).
#
# A new client advertises {"wire": {"v": 1, "codec": ...}} inside its
# Enter?/Rejoin? request; extra keys are invisible to an old server (it only
# reads "q"/"clientID" and replies the plain "Enter" string), so the client
# detects a legacy peer from the STRING reply and falls back to per-leaf
# 'T' frames.  A new server replies {"a": "Enter", "wire": {...}} — a dict
# — ONLY to clients that advertised, so old clients keep getting the plain
# string they expect.  Both directions of a negotiated handshake (center
# down, delta up) then use ONE packed frame with the agreed codec.  An
# unsupported codec is answered with a wire error and an eviction — mixed
# fleets fail loudly (ProtocolError at the client) instead of silently
# corrupting tensors.


def _parse_wire_request(msg) -> tuple[str | None, str | None]:
    """(codec, error) from an admission-family message's "wire" key.
    ``(None, None)`` = legacy peer; ``(codec, None)`` = negotiated;
    ``(codec, error)`` = advertised but unusable (answer loudly)."""
    spec = msg.get("wire") if isinstance(msg, dict) else None
    if spec is None:
        return None, None
    if not isinstance(spec, dict):
        return None, f"malformed wire spec {spec!r}"
    codec = spec.get("codec")
    if codec not in wire.CODECS:
        return codec, (f"unsupported wire codec {codec!r} "
                       f"(supported: {', '.join(wire.CODECS)})")
    return codec, None


def _check_wire_reply(reply, want: str, codec: str) -> bool:
    """Client-side half of the negotiation: True when the server agreed to
    the packed wire, False when it answered with the legacy plain string
    (fall back to per-leaf frames), ProtocolError on desync or rejection."""
    if reply == want:
        return False                      # legacy server: per-leaf 'T' wire
    if isinstance(reply, dict) and reply.get("a") == want:
        w = reply.get("wire")
        if isinstance(w, dict) and w.get("error"):
            raise ProtocolError(
                f"server rejected wire codec {codec!r}: {w['error']}")
        if not isinstance(w, dict) or w.get("codec") != codec:
            raise ProtocolError(
                f"wire negotiation desync: requested codec {codec!r}, "
                f"server answered {w!r}")
        return True
    raise ProtocolError(f"protocol desync: expected {want!r}, got {reply!r}")


def _leaves(tree: PyTree) -> list[np.ndarray]:
    return [np.asarray(x) for x in jax.tree_util.tree_leaves(tree)]


def _rebuild(tree: PyTree, leaves: list[np.ndarray]) -> PyTree:
    treedef = jax.tree_util.tree_structure(tree)
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _expect(conn: Conn, want: str):
    """Protocol step check — explicit (never stripped under ``python -O``,
    unlike the reference's asserts) and diagnostic on desync."""
    got = conn.recv_msg()
    if got != want:
        raise ProtocolError(f"protocol desync: expected {want!r}, got {got!r}")


class AsyncEAServer:
    """Parameter-server role (ref initServer/syncServer/testNet)."""

    def __init__(self, host: str, port: int, num_nodes: int,
                 with_tester: bool = False, accept_timeout: float = 120.0,
                 handshake_timeout: float | None = 30.0):
        self.num_nodes = num_nodes
        # Per-handshake IO timeout on the dedicated channels: a client that
        # dies or hangs mid-sync (after Enter?) must not wedge the serve loop
        # — it gets EVICTED and the server keeps serving the others.  The
        # reference wedges here (lua/AsyncEA.lua:163-228 has no timeouts);
        # "match the reference's fragility" is not the bar (VERDICT r1).
        self.handshake_timeout = handshake_timeout
        self.evicted: set[int] = set()
        self._cid_to_broadcast: dict[int, int] = {}
        # negotiated wire codec per client id (None = legacy per-leaf 'T'
        # frames), refreshed on every Enter?/Rejoin? — see _admit
        self._wire_cid: dict[int, str | None] = {}
        # broadcast conns accepted for a possible rejoin that have not yet
        # spoken, with a speak-by deadline — a dialed-but-silent socket
        # must not keep the serve/dispatch loop alive forever
        self._rejoin_pending: list = []
        # Broadcast channel: all clients connect here (EASGD_server.lua:67-68).
        self.broadcast = Server(host, port)
        # Dedicated per-client channels on port+i (EASGD_server.lua:71-77).
        self.dedicated_servers = [Server(host, port + i + 1)
                                  for i in range(num_nodes)]
        # Test channel on port+numNodes+1 (EASGD_server.lua:69-70).
        self.test_server = Server(host, port + num_nodes + 1) \
            if with_tester else None
        self.broadcast.accept(num_nodes, timeout=accept_timeout)
        self.dedicated: list[Conn] = []
        for s in self.dedicated_servers:
            self.dedicated.append(s.accept(1, timeout=accept_timeout)[0])
        self.test_conn = self.test_server.accept(1, timeout=accept_timeout)[0] \
            if with_tester else None
        self.center: list[np.ndarray] | None = None
        self.current_client: int | None = None
        # Telemetry handles (obs.NULL when DISTLEARN_OBS=0) resolve once
        # per server; ``_obs_on`` gates only work the null sink cannot
        # absorb (perf_counter pairs).
        self._obs_on = obs.enabled()
        self._c_syncs = obs.counter(
            "async_ea_syncs_total", "deltas applied to the center")
        self._c_evict = obs.counter(
            "async_ea_evictions_total", "clients evicted mid-handshake")
        self._c_rejoin = obs.counter(
            "async_ea_rejoins_total", "evicted clients re-admitted")
        self._h_handshake = obs.histogram(
            "async_ea_handshake_seconds",
            "full sync handshake (Enter sent to delta validated)")
        self._h_apply = obs.histogram(
            "async_ea_center_apply_seconds",
            "center += delta apply time (host or device path)")

    def init_server(self, params: PyTree):
        """Clone params as center, broadcast it to every client
        (ref lua :150-160)."""
        self.center = [x.copy() for x in _leaves(params)]
        for conn in self.broadcast.conns:
            try:
                # per-leaf 'T' frames: the initial broadcast happens BEFORE
                # any client has spoken, so there is no capability
                # advertisement to negotiate against — old-wire clients
                # must be able to read it (new clients auto-detect either)
                conn.send_tensors(self.center, packed=False)
            except (TimeoutError, ConnectionError, OSError) as e:
                # Dead before the first broadcast: drop it; it is evicted for
                # real when it never completes a handshake.
                print_server(f"initial broadcast to a client failed: {e!r}")
                conn.close()

    def _check_delta(self, deltas: list[np.ndarray]):
        """Reject a structurally wrong delta BEFORE any leaf is applied, so
        the center never takes a torn update (a mismatched client config
        becomes an eviction, not a corrupted center).  Dtype skew is config
        skew too: an int or f64 delta of the right shape must not be
        silently cast into the center (ADVICE r3)."""
        for t, d in zip(self.center, deltas):
            if tuple(d.shape) != tuple(t.shape):
                raise ProtocolError(
                    f"delta leaf shape {tuple(d.shape)} != center "
                    f"{tuple(t.shape)} — client/server model config skew")
            if d.dtype != t.dtype:
                raise ProtocolError(
                    f"delta leaf dtype {d.dtype} != center {t.dtype} — "
                    "client/server model config skew")

    def _apply_delta(self, deltas: list[np.ndarray]):
        """Fold a fully-received, validated delta into the center.  The
        serial server mutates in place; the concurrent subclass overrides
        this with its immutable-publish version (so the serial
        ``sync_server`` API keeps working on a concurrent server, whose
        center leaves are frozen)."""
        t0 = time.perf_counter() if self._obs_on else 0.0
        for t, d in zip(self.center, deltas):
            t += d              # dtypes equal (checked) — no astype copy
        self._c_syncs.inc()
        if self._obs_on:
            self._h_apply.observe(time.perf_counter() - t0)

    def _evict(self, cid: int, why: Exception):
        """Drop a dead/hung client: close both its channels so recv_any stops
        selecting it; remaining clients keep syncing."""
        self.evicted.add(cid)
        self._c_evict.inc()
        print_server(f"evicting client #{cid}: {why!r}")
        try:
            self.dedicated[cid - 1].close()
        except OSError:
            pass
        idx = self._cid_to_broadcast.get(cid)
        if idx is not None:
            try:
                self.broadcast.conns[idx].close()
            except OSError:
                pass

    @property
    def live_clients(self) -> int:
        return self.num_nodes - len(self.evicted)

    # -- re-admission --------------------------------------------------------
    #
    # The reference has no recovery at all (lua/AsyncEA.lua wedges on a dead
    # peer); eviction alone made failure survivable but terminal — a
    # transiently-hung worker was dead forever (VERDICT r4 next #8).  Rejoin
    # completes the elastic story: an evicted client re-dials BOTH channels
    # (its old sockets are closed server-side), announces itself with
    # ``Rejoin?`` on the fresh broadcast conn, receives the CURRENT center
    # over the fresh dedicated conn (its own copy is stale by definition),
    # acks, and is a full participant again.
    def _accept_rejoiners(self):
        """Accept pending broadcast re-connections (non-blocking poll of the
        listening socket).  Only meaningful while somebody is evicted — the
        fast path is one set-emptiness check.  Accepted conns get a
        speak-by deadline: a rejoiner that dials in but never sends its
        ``Rejoin?`` (the same hang that got it evicted) is closed when the
        deadline passes, so a silent socket cannot keep the dispatcher
        alive past its rejoin grace or wedge ``drained`` forever."""
        self._prune_broadcast()
        now = time.monotonic()
        kept = []
        for c, dl in self._rejoin_pending:
            if c.sock.fileno() < 0:
                continue                      # spoke (or died) — tracked out
            if now > dl:
                try:
                    c.close()
                except OSError:
                    pass
                continue
            kept.append((c, dl))
        self._rejoin_pending = kept
        if not self.evicted:
            return
        while True:
            r, _, _ = select.select([self.broadcast.sock], [], [], 0.0)
            if not r:
                return
            try:
                new = self.broadcast.accept(
                    1, timeout=self.handshake_timeout or 30.0)
            except (TimeoutError, OSError):
                return
            self._rejoin_pending.append(
                (new[0], now + (self.handshake_timeout or 30.0)))

    def _prune_broadcast(self):
        """Closed broadcast conns accumulate forever once rejoin dials
        re-open the listener (``Server.accept`` only appends): drop them
        and remap the cid -> index table.  The concurrent server overrides
        to run under its dispatcher lock (workers read the map during
        eviction)."""
        if all(c.sock.fileno() >= 0 for c in self.broadcast.conns):
            return
        mapping = self.broadcast.prune_closed()
        self._cid_to_broadcast = {
            cid: mapping[i] for cid, i in self._cid_to_broadcast.items()
            if i in mapping}

    def _note_spoke(self, idx: int):
        """A broadcast conn delivered a message: it is no longer a silent
        rejoin candidate — drop it from the speak-by watch list (its fate
        now follows the normal admit/readmit paths)."""
        conn = self.broadcast.conns[idx]
        self._rejoin_pending = [(c, dl) for c, dl in self._rejoin_pending
                                if c is not conn]

    def _evict_dropped(self, idx: int, why: Exception):
        """``recv_any``'s frame-timeout drop closed a broadcast conn at
        transport level.  If that conn belonged to an admitted client,
        record a REAL eviction (closing its dedicated channel too) so the
        bookkeeping stays true and the client can later ``rejoin()`` —
        a transport-level close with no eviction record was permanently
        unrecoverable (r5 review)."""
        for cid, i in self._cid_to_broadcast.items():
            if i == idx and cid not in self.evicted:
                self._evict(cid, why)
                return

    def _rejoin_center(self) -> list[np.ndarray]:
        """Center leaves to stream to a rejoiner (concurrent server
        overrides with its atomic snapshot)."""
        return self.center

    def _finish_readmit(self, cid: int, idx: int, conn: Conn):
        """Swap in the fresh channels and clear the evicted bit (concurrent
        server overrides to also respawn the client's worker)."""
        self.evicted.discard(cid)
        self._cid_to_broadcast[cid] = idx
        self.dedicated[cid - 1] = conn
        self._c_rejoin.inc()

    def _readmit(self, idx: int, msg) -> None:
        """Complete one ``Rejoin?`` handshake: validate the claimed id is
        actually evicted, accept the client's fresh dedicated connection,
        stream the current center down it, and re-admit on the client's
        ``Ack``.  Any failure leaves the client evicted (it can try again);
        the center is never touched."""
        cid = self._parse_cid(msg)
        conn_b = self.broadcast.conns[idx]
        if cid < 0 or cid not in self.evicted:
            self._drop_peer(idx, f"dropping rejoin with bad clientID "
                                 f"{msg.get('clientID')!r}")
            return
        codec, wire_err = _parse_wire_request(msg)
        try:
            # SHORT bound: the rejoin protocol dials the dedicated channel
            # BEFORE announcing Rejoin?, so a legit dial is already in the
            # listen backlog — a long wait here would let one half-rejoin
            # (announce without dial) stall serving for every live client
            # by handshake_timeout per attempt.
            new = self.dedicated_servers[cid - 1].accept(
                1, timeout=min(self.handshake_timeout or 2.0, 2.0))[0]
        except (TimeoutError, OSError) as e:
            print_server(f"rejoin of client #{cid} failed at dedicated "
                         f"accept: {e!r}")
            try:
                conn_b.close()
            except OSError:
                pass
            return
        try:
            with obs.span("async_ea.rejoin", cid=cid):
                new.set_timeout(self.handshake_timeout)
                if wire_err is not None:
                    # same loud rejection as _reject_wire, on the rejoin leg
                    new.send_msg({"a": REJOIN, "wire": {"error": wire_err}})
                    raise ProtocolError(wire_err)
                self._wire_cid[cid] = codec
                if codec is not None:
                    new.send_msg({"a": REJOIN,
                                  "wire": {"v": wire.WIRE_V, "codec": codec}})
                else:
                    new.send_msg(REJOIN)
                new.send_tensors(self._rejoin_center(),
                                 codec=codec or "raw", packed=codec is not None)
                _expect(new, ACK)
                new.set_timeout(None)
        except (TimeoutError, ConnectionError, ProtocolError, OSError,
                ValueError) as e:
            print_server(f"rejoin of client #{cid} failed mid-handshake: "
                         f"{e!r}")
            for c in (new, conn_b):
                try:
                    c.close()
                except OSError:
                    pass
            return
        self._finish_readmit(cid, idx, new)
        print_server(f"client #{cid} re-admitted")

    def _parse_cid(self, msg) -> int:
        """The clientID an admission-family message claims, or -1 when
        absent/unparseable/out of range — shared by ``_admit`` and
        ``_readmit`` so the id rules cannot drift between the two paths."""
        try:
            cid = int(msg.get("clientID", -1))
        except (TypeError, ValueError):
            return -1
        return cid if 1 <= cid <= self.num_nodes else -1

    def _drop_peer(self, idx: int, why: str):
        """Close one broadcast conn and log why (bad request/id)."""
        try:
            self.broadcast.conns[idx].close()
        except OSError:
            pass
        print_server(why)

    def _admit(self, idx: int, msg) -> int | None:
        """Validate one broadcast-channel request (``Enter?`` + a sane,
        non-evicted clientID).  Returns the client id, or ``None`` after
        dropping the broken peer — shared by the serial serve loop and the
        concurrent dispatcher so admission rules cannot drift."""
        if not isinstance(msg, dict) or msg.get("q") != ENTER_Q:
            self._drop_peer(idx, f"dropping peer with bad request {msg!r}")
            return None
        cid = self._parse_cid(msg)
        if cid < 0 or cid in self.evicted:
            self._drop_peer(idx, f"dropping peer with bad clientID "
                                 f"{msg.get('clientID')!r}")
            return None
        self._cid_to_broadcast[cid] = idx
        codec, wire_err = _parse_wire_request(msg)
        if wire_err is not None:
            self._reject_wire(cid, wire_err)
            return None
        self._wire_cid[cid] = codec
        return cid

    def _reject_wire(self, cid: int, err: str):
        """A client advertised a wire codec this server cannot speak:
        answer LOUDLY on the dedicated channel (where the client blocks
        waiting for Enter — it raises ProtocolError on the error reply)
        and evict.  Silently falling back would ship fp32 to a client
        that asked for compression; silently proceeding would corrupt."""
        conn = self.dedicated[cid - 1]
        try:
            conn.set_timeout(self.handshake_timeout)
            conn.send_msg({"a": ENTER, "wire": {"error": err}})
        except (TimeoutError, ConnectionError, OSError):
            pass
        self._evict(cid, ProtocolError(err))

    def sync_server(self, params: PyTree,
                    timeout: float | None = None) -> PyTree:
        """One full server-side sync round (ref ``syncServer``, lua :230-237):
        admit one client, send center, receive delta, apply it, and copy the
        center into the server-local params (returned).

        A client that fails mid-handshake (EOF, hang past
        ``handshake_timeout``, protocol desync) is evicted and the round
        retries with the next requester — the center never takes a partial
        delta (updates apply leaf-by-leaf only after every leaf arrived).

        ``timeout`` bounds the wait for ANY sync request (``None`` = wait
        forever, the reference's behavior).

        While any client is evicted the wait is sliced so pending
        ``Rejoin?`` re-connections get accepted (see :meth:`_readmit`); a
        rejoin round admits no sync — the loop continues to the next
        request.  If ALL clients are evicted/closed this still raises
        ``RuntimeError`` (no open connections); a caller that wants to
        wait out a full outage catches it and calls ``sync_server`` again.
        """
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while True:
            self._accept_rejoiners()
            if deadline is None:
                slice_t = 0.5 if self.evicted else None
            else:
                slice_t = max(0.0, deadline - time.monotonic())
                if self.evicted:
                    slice_t = min(slice_t, 0.5)
            # serverEnterSync (lua :163-177): critical section — one client.
            try:
                idx, msg = self.broadcast.recv_any(
                    timeout=slice_t, frame_timeout=self.handshake_timeout,
                    on_drop=self._evict_dropped)
            except TimeoutError:
                if deadline is not None and time.monotonic() >= deadline:
                    raise
                continue
            self._note_spoke(idx)
            if isinstance(msg, dict) and msg.get("q") == REJOIN_Q:
                self._readmit(idx, msg)
                continue
            cid = self._admit(idx, msg)
            if cid is None:
                continue
            self.current_client = cid
            conn = self.dedicated[cid - 1]  # 1-based ids (ref)
            t0 = time.perf_counter() if self._obs_on else 0.0
            codec = self._wire_cid.get(cid)
            try:
                with obs.span("async_ea.handshake", cid=cid):
                    conn.set_timeout(self.handshake_timeout)
                    if codec is not None:
                        conn.send_msg({"a": ENTER,
                                       "wire": {"v": wire.WIRE_V,
                                                "codec": codec}})
                    else:
                        conn.send_msg(ENTER)
                    print_server(f"current client is #{self.current_client}")

                    # serverSendCenter (lua :180-196): ONE packed frame on
                    # a negotiated wire, per-leaf 'T' frames for legacy
                    _expect(conn, CENTER_Q)
                    conn.send_tensors(self.center, codec=codec or "raw",
                                      packed=codec is not None)

                    # serverGetUpdateDiff (lua :198-228): receive the FULL
                    # delta before applying any of it, so an eviction
                    # mid-stream leaves the center untouched.  The monotonic
                    # deadline covers the WHOLE delta stream: a client
                    # trickling payload bytes re-arms the kernel timeout
                    # forever, the exact wedge the frame deadline closes for
                    # control frames.
                    _expect(conn, DELTA_Q)
                    conn.send_msg(DELTA)
                    dl = (None if self.handshake_timeout is None
                          else time.monotonic() + self.handshake_timeout)
                    # auto-detects packed vs per-leaf, so a legacy client
                    # needs no branch here; quantized deltas decode into
                    # fresh center-dtype arrays
                    deltas = conn.recv_tensors(n=len(self.center),
                                               deadline=dl)
                    self._check_delta(deltas)
                    conn.set_timeout(None)
            except (TimeoutError, ConnectionError, ProtocolError, OSError,
                    ValueError) as e:   # ValueError: undecodable JSON frame
                self._evict(cid, e)
                continue
            if self._obs_on:
                self._h_handshake.observe(time.perf_counter() - t0)
            self._apply_delta(deltas)
            print_server(f"received delta from client #{self.current_client}")
            return _rebuild(params, [t.copy() for t in self.center])

    def test_net(self, tensors: list[np.ndarray] | None = None) -> bool:
        """Push the center to the tester (ref ``testNet``, lua :239-258).

        A dead/hung tester must not stall training: the handshake runs
        under ``handshake_timeout`` and a failed tester is dropped (later
        calls no-op, returning False).  ``tensors`` overrides the pushed
        leaves (the concurrent server passes an atomic snapshot)."""
        conn = self.test_conn
        if conn is None:
            return False
        try:
            conn.set_timeout(self.handshake_timeout)
            conn.send_msg(TEST_Q)
            # the tester's Center? may carry a wire advertisement (a dict,
            # like Enter?) — negotiate the packed frame the same way
            msg = conn.recv_msg()
            codec = None
            if isinstance(msg, dict) and msg.get("q") == CENTER_Q:
                codec, wire_err = _parse_wire_request(msg)
                if wire_err is not None:
                    conn.send_msg({"a": TEST_Q, "wire": {"error": wire_err}})
                    raise ProtocolError(wire_err)
            elif msg != CENTER_Q:
                raise ProtocolError(
                    f"protocol desync: expected {CENTER_Q!r}, got {msg!r}")
            conn.send_tensors(tensors if tensors is not None else self.center,
                              codec=codec or "raw", packed=codec is not None)
            _expect(conn, ACK)
            conn.set_timeout(None)
            return True
        except (TimeoutError, ConnectionError, ProtocolError, OSError,
                ValueError) as e:
            print_server(f"dropping tester: {e!r}")
            conn.close()
            self.test_conn = None
            return False

    def close(self):
        self.broadcast.close()
        for s in self.dedicated_servers:
            s.close()
        if self.test_server:
            self.test_server.close()


class AsyncEAServerConcurrent(AsyncEAServer):
    """Concurrent parameter-server: same wire protocol (clients and testers
    connect unchanged), but handshakes for different clients OVERLAP — the
    north-star scaling the reference's one-at-a-time critical section
    (lua/AsyncEA.lua:163-177) rules out.

    Structure: a dispatcher thread drains ``Enter?`` requests from the
    broadcast channel and routes a token to the requesting client's worker
    thread; each worker owns that client's dedicated channel exclusively
    (the framed transport separates channels, so streams never interleave)
    and runs the full center-down/delta-up handshake concurrently with the
    other workers.  The center itself stays atomic: workers SNAPSHOT it
    under a lock (then stream without blocking appliers) and APPLY deltas
    under the same lock — a client never receives a torn center, and
    ``center += delta`` remains serialized.  Relaxation vs the serial
    server: two overlapping clients may both fetch the pre-update center
    and push deltas computed against it — the standard stale-gradient
    asynchrony EASGD is built to tolerate (arXiv:1412.6651 §4), traded for
    N-way IO overlap.

    ``pin_device`` pins the center on a jax device with a jitted donated
    ``center += delta`` apply (the BASELINE.json north-star "one-sided
    update against a pinned center replica"); host numpy otherwise.
    Note: worth it when the accelerator is locally attached — on a
    remote-tunneled chip the per-sync device round trip dominates.
    """

    def __init__(self, host: str, port: int, num_nodes: int,
                 with_tester: bool = False, accept_timeout: float = 120.0,
                 handshake_timeout: float | None = 30.0,
                 pin_device=None, rejoin_grace: float = 10.0):
        super().__init__(host, port, num_nodes, with_tester=with_tester,
                         accept_timeout=accept_timeout,
                         handshake_timeout=handshake_timeout)
        # How long the dispatcher keeps polling for a Rejoin? after every
        # broadcast conn has closed WHILE somebody is evicted — bounded so
        # a permanently-dead evictee cannot hold up shutdown/drained.
        self.rejoin_grace = float(rejoin_grace)
        import queue
        import threading
        self._lock = threading.Lock()
        # serializes APPLIERS (the center += delta semantics stay ordered)
        # separately from the pointer lock, so snapshot readers never wait
        # behind an O(P) apply — they grab the current immutable center
        # list under self._lock in O(1)
        self._apply_lock = threading.Lock()
        self._queues = [queue.Queue() for _ in range(num_nodes)]
        self._threads: list = []
        self._workers: dict[int, Any] = {}
        self._stop = threading.Event()
        self._dispatch_closed = threading.Event()
        self._inflight = 0
        self._sync_count = 0
        self._device = pin_device
        self._dev_center = None
        self._dev_apply = None
        # mirrors _inflight (same lock holds) so /metrics and /healthz see
        # the dispatcher's view without taking the dispatcher lock
        self._g_inflight = obs.gauge(
            "async_ea_inflight", "sync handshakes currently in flight")

    # -- center storage ------------------------------------------------------
    #
    # Host path: the center is an IMMUTABLE published version — every apply
    # builds fresh leaves (one fused ``t + d`` pass, no astype copy) and
    # swaps the list pointer under the lock.  Snapshots are therefore a
    # pointer grab, not the O(P) memcpy-under-lock the r3 profile showed
    # dominating 100 MB-scale syncs; workers stream straight from the
    # frozen arrays.  Published leaves are marked read-only so a caller
    # mutating ``current_center``'s result fails loudly instead of
    # corrupting what concurrent workers are streaming.
    def init_server(self, params: PyTree):
        super().init_server(params)
        if self._device is not None:
            self._pin()
        else:
            for t in self.center:
                t.flags.writeable = False

    def _pin(self):
        """Move the center to the device; build the donated fused apply."""
        self._dev_center = [jax.device_put(t, self._device)
                            for t in self.center]

        def _apply(center, deltas):
            return [c + d.astype(c.dtype) for c, d in zip(center, deltas)]

        self._dev_apply = jax.jit(_apply, donate_argnums=(0,))

    def _snapshot(self) -> list[np.ndarray]:
        with self._lock:
            if self._dev_center is not None:
                return [np.asarray(jax.device_get(t))
                        for t in self._dev_center]
            return self.center      # immutable published version: no copy

    def _apply_delta(self, deltas: list[np.ndarray]):
        t0 = time.perf_counter() if self._obs_on else 0.0
        if self._dev_center is not None:
            with self._lock:
                self._dev_center = self._dev_apply(
                    self._dev_center,
                    [jax.device_put(d, self._device) for d in deltas])
                self._sync_count += 1
        else:
            with self._apply_lock:  # appliers serialize; readers do not wait
                new = [t + d for t, d in zip(self.center, deltas)]
                for t in new:
                    t.flags.writeable = False
                with self._lock:
                    self.center = new
                    self._sync_count += 1
        self._c_syncs.inc()
        if self._obs_on:
            self._h_apply.observe(time.perf_counter() - t0)

    @property
    def syncs_completed(self) -> int:
        with self._lock:
            return self._sync_count

    @property
    def drained(self) -> bool:
        """True once no further syncs can arrive: every broadcast channel
        has closed (the dispatcher exited) and no handshake is in flight —
        the concurrent counterpart of the serial loop's
        RuntimeError-from-recv_any stop condition (a serve loop polling
        ``syncs_completed`` must also stop on this, or finished clients
        would leave it spinning forever)."""
        if not self._dispatch_closed.is_set():
            return False
        with self._lock:
            inflight = self._inflight
        return inflight == 0 and all(q.empty() for q in self._queues)

    def current_center(self, params: PyTree) -> PyTree:
        """Snapshot of the center as a pytree shaped like ``params``."""
        return _rebuild(params, self._snapshot())

    def test_net(self, tensors: list[np.ndarray] | None = None) -> bool:
        """Tester push from an atomic snapshot (the live host list may be
        mid-apply on a worker thread; the device copy is authoritative when
        pinned).  The snapshot is passed down explicitly — NEVER by
        swapping ``self.center``, which a concurrent ``_apply_delta``
        iterates."""
        if self.test_conn is None:
            return False
        return super().test_net(tensors if tensors is not None
                                else self._snapshot())

    def _evict(self, cid: int, why: Exception):
        """Concurrent eviction: mark + drain the client's token queue under
        the SAME lock the dispatcher enqueues under, so no token can land
        after the drain — otherwise a token issued in the
        admit-then-enqueue window would never be consumed, ``_inflight``
        would leak, and ``drained`` could never become true (ADVICE r3
        TOCTOU)."""
        with self._lock:
            self._evict_locked(cid, why)

    def _evict_locked(self, cid: int, why: Exception):
        """Eviction body; caller holds ``self._lock`` (the worker's
        stale-conn check needs check+evict ATOMIC against a concurrent
        rejoin's state flip — two separate acquisitions let a rejoin land
        in between and get its fresh conn closed by a stale decision)."""
        import queue as _q
        super()._evict(cid, why)
        while True:
            try:
                token = self._queues[cid - 1].get_nowait()
            except _q.Empty:
                break
            if token is not None:     # the None stop sentinel never
                self._inflight -= 1   # incremented _inflight
                self._g_inflight.dec()

    # -- threads -------------------------------------------------------------
    def _health(self) -> dict:
        """The ``/healthz`` payload (obs.export): liveness an external
        prober needs to tell serving from draining from dead.  Reads are
        lock-free — telemetry tolerates a torn view."""
        return {"live_clients": self.live_clients,
                "inflight": self._inflight,
                "drained": self.drained}

    def start(self):
        """Spawn the dispatcher + one worker per client.  Returns self."""
        import threading
        obs.set_health_source(self._health)
        self._threads = [threading.Thread(target=self._dispatch, daemon=True)]
        self._workers = {
            cid: threading.Thread(target=self._worker, args=(cid,),
                                  daemon=True)
            for cid in range(1, self.num_nodes + 1)}
        self._threads += list(self._workers.values())
        for t in self._threads:
            t.start()
        return self

    def stop(self):
        self._stop.set()
        for q in self._queues:
            q.put(None)
        for t in self._threads:
            t.join(timeout=10.0)
        obs.set_health_source(None)

    def _rejoin_grace_poll(self) -> bool:
        """True once a re-connection landed (a fresh broadcast conn is
        open); False when the grace expires or the server is stopping."""
        deadline = time.monotonic() + self.rejoin_grace
        while time.monotonic() < deadline and not self._stop.is_set():
            self._accept_rejoiners()
            if any(c.sock.fileno() >= 0 for c in self.broadcast.conns):
                return True
            time.sleep(0.05)
        return False

    def _dispatch(self):
        try:
            self._dispatch_loop()
        finally:
            self._dispatch_closed.set()

    def _prune_broadcast(self):
        with self._lock:        # workers read the cid map during eviction
            super()._prune_broadcast()

    def _rejoin_center(self) -> list[np.ndarray]:
        return self._snapshot()

    def _finish_readmit(self, cid: int, idx: int, conn: Conn):
        """Re-admit and make sure the client has a live worker.  A worker
        that evicted its OWN client has returned and needs a respawn; a
        worker whose client was evicted by the DISPATCHER (frame-timeout /
        reset on the broadcast conn) is still parked on the queue — it
        re-reads ``self.dedicated[cid-1]`` per token, so it serves the
        fresh channel as-is and spawning a second worker on the same
        queue would race it.  State flips under the dispatcher lock —
        _admit's evicted re-check and the queue-drain in _evict both run
        under it."""
        import threading
        with self._lock:
            super()._finish_readmit(cid, idx, conn)
            # a worker that self-evicted DEREGISTERED itself in the same
            # lock hold as its eviction, so presence here means parked
            # and serviceable (is_alive() alone races the exiting thread)
            need = self._workers.get(cid) is None
            if need:
                t = threading.Thread(target=self._worker, args=(cid,),
                                     daemon=True)
                self._workers[cid] = t
                # drop exited threads while appending: a flaky client
                # cycling evict->rejoin must not grow this list forever
                self._threads = [th for th in self._threads
                                 if th.is_alive()] + [t]
        if need:
            t.start()

    def _dispatch_loop(self):
        while not self._stop.is_set():
            self._accept_rejoiners()
            try:
                idx, msg = self.broadcast.recv_any(
                    timeout=0.5, frame_timeout=self.handshake_timeout,
                    on_drop=self._evict_dropped)
            except TimeoutError:
                continue
            except RuntimeError:
                # every broadcast conn closed.  With nobody evicted that
                # is terminal (all clients finished) — dispatch is done.
                # With an evicted client a Rejoin? can still arrive on
                # the listening socket: poll for one for a bounded grace
                # before giving up.
                if not self.evicted or not self._rejoin_grace_poll():
                    return
                continue
            except (ConnectionError, OSError, ValueError):
                # a worker EVICTING its client closes that client's
                # broadcast conn while this thread is blocked in select on
                # it — EBADF/negative-fd surfaces here.  That is one dead
                # conn, not the end of dispatch: keep serving the others
                # (exiting here orphaned the live clients' Enter? requests
                # — observed as a full-suite wedge)
                continue
            self._note_spoke(idx)
            if isinstance(msg, dict) and msg.get("q") == REJOIN_Q:
                # rejoin handshakes are rare; blocking dispatch for one
                # bounded (handshake_timeout) center push is acceptable
                self._readmit(idx, msg)
                continue
            cid = self._admit(idx, msg)
            if cid is None:
                continue
            with self._lock:
                # re-check under the lock: the client's worker may have
                # evicted it (and drained its queue) since _admit's
                # unlocked check — enqueueing now would leak the token
                if cid in self.evicted:
                    continue
                self._inflight += 1     # token issued; worker will settle it
                self._g_inflight.inc()
                self._queues[cid - 1].put(ENTER)

    def _worker(self, cid: int):
        bufs = None     # reusable delta recv buffers (host path): no 100 MB
        #                 allocation + page-fault pass per sync
        while not self._stop.is_set():
            token = self._queues[cid - 1].get()
            if token is None:
                return
            # re-read per token: a rejoin swaps the dedicated conn while
            # this thread is parked on the queue (dispatcher-side
            # evictions never unpark it)
            conn = self.dedicated[cid - 1]
            codec = self._wire_cid.get(cid)
            t0 = time.perf_counter() if self._obs_on else 0.0
            try:
                try:
                    with obs.span("async_ea.handshake", cid=cid):
                        conn.set_timeout(self.handshake_timeout)
                        if codec is not None:
                            conn.send_msg({"a": ENTER,
                                           "wire": {"v": wire.WIRE_V,
                                                    "codec": codec}})
                        else:
                            conn.send_msg(ENTER)
                        _expect(conn, CENTER_Q)
                        # stream OUTSIDE the lock; one packed frame on a
                        # negotiated wire
                        conn.send_tensors(self._snapshot(),
                                          codec=codec or "raw",
                                          packed=codec is not None)
                        _expect(conn, DELTA_Q)
                        conn.send_msg(DELTA)
                        # whole-delta-stream deadline: see sync_server
                        dl = (None if self.handshake_timeout is None
                              else time.monotonic() + self.handshake_timeout)
                        if self._dev_center is None:
                            if bufs is None:
                                bufs = [np.empty_like(t)
                                        for t in self.center]
                            # recv_tensors(out=...) itself rejects shape/
                            # dtype skew (ProtocolError -> eviction below)
                            # and auto-detects packed vs per-leaf frames
                            deltas = conn.recv_tensors(out=bufs, deadline=dl)
                        else:
                            deltas = conn.recv_tensors(n=len(self.center),
                                                       deadline=dl)
                        self._check_delta(deltas)   # before ANY apply: a
                        # config-skewed client is an eviction, never a torn
                        # or silently-dead worker (the serve loop polls
                        # drained)
                        conn.set_timeout(None)
                except (TimeoutError, ConnectionError, ProtocolError,
                        OSError, ValueError) as e:
                    # only evict if OUR conn is still the client's current
                    # channel — failing on a conn a rejoin already
                    # replaced must not evict the re-admitted client.
                    # Check + evict + deregister under ONE lock hold: a
                    # rejoin flipping the conn between them would get its
                    # fresh channel closed by the stale decision, and a
                    # rejoin landing between the evict and this thread's
                    # exit would see is_alive()==True and skip the
                    # respawn, stranding the client's tokens forever.
                    with self._lock:
                        current = self.dedicated[cid - 1] is conn
                        if current:
                            self._evict_locked(cid, e)  # drains queue too
                            self._workers.pop(cid, None)
                    if current:
                        return
                    continue                   # stale-conn failure: park
                if self._obs_on:
                    self._h_handshake.observe(time.perf_counter() - t0)
                self._apply_delta(deltas)      # full delta only, atomically
            finally:
                with self._lock:
                    self._inflight -= 1
                    self._g_inflight.dec()


class _DeltaSender:
    """Depth-1 background sender for the compute/communication overlap
    path: ``submit(job)`` hands the previous round's delta transmit to a
    worker thread and returns immediately, so the next round's τ local
    steps overlap the delta's wire round-trip.  The bounded queue (at most
    ONE in-flight job — ``submit`` flushes the previous one first)
    preserves the EASGD staleness bound: a client can never be more than
    one un-acknowledged delta ahead of the center it last fetched.

    A background failure is stored and re-raised at the next ``flush``
    (the top of the next sync), where the caller's eviction/rejoin
    handling already lives; ``drain`` discards it (the rejoin path is
    about to replace the connection the error came from)."""

    def __init__(self):
        import queue
        import threading
        self._q: Any = queue.Queue(maxsize=1)
        self._idle = threading.Event()
        self._idle.set()
        self._err: BaseException | None = None
        self._t = threading.Thread(target=self._loop, daemon=True)
        self._t.start()

    def _loop(self):
        while True:
            job = self._q.get()
            if job is None:
                self._idle.set()
                return
            try:
                job()
            except BaseException as e:  # noqa: BLE001 — surfaced at flush
                self._err = e
            finally:
                self._idle.set()

    def flush(self):
        """Wait out the in-flight job; re-raise its failure, if any."""
        self._idle.wait()
        if self._err is not None:
            err, self._err = self._err, None
            raise err

    def submit(self, job):
        self.flush()            # depth 1: at most one delta in flight
        self._idle.clear()
        self._q.put(job)

    def drain(self):
        """Wait for idle and DISCARD any stored failure (eviction/rejoin
        cleanup — the conn the failure came from is being replaced)."""
        self._idle.wait()
        self._err = None

    def close(self):
        self._idle.wait()
        self._q.put(None)
        self._t.join(timeout=5.0)
        self._err = None


class AsyncEAClient:
    """Worker role (ref initClient/syncClient).

    ``codec`` selects the wire format for the sync handshake: ``"raw"``
    (default) coalesces each direction into one packed frame, ``"fp16"``/
    ``"int8"`` additionally quantize (deltas carry client-side
    error-feedback residuals so the quantization error is re-injected
    into later rounds, 1-bit-SGD style); ``None`` speaks the legacy
    per-leaf wire unconditionally.  The codec is negotiated per handshake
    — against an old server the client silently falls back to the legacy
    frames (the server never sees the advertisement's extra keys).

    ``overlap=True`` pushes each round's delta from a background sender
    (depth-1 queue) so local training overlaps the transmit round-trip;
    failures surface at the NEXT sync, where eviction handling already
    lives.
    """

    def __init__(self, host: str, port: int, node: int, tau: int,
                 alpha: float, codec: str | None = "raw",
                 overlap: bool = False):
        if node < 1:
            raise ValueError("node is 1-based (reference convention)")
        if codec is not None and codec not in wire.CODECS:
            raise ValueError(f"unknown wire codec {codec!r} "
                             f"(supported: {', '.join(wire.CODECS)})")
        self.node = node
        self.tau = int(tau)
        self.alpha = float(alpha)
        self.codec = codec
        self.step = 0
        self.host, self.port = host, port
        # clientBroadcast -> port; dedicated client -> port+node
        # (EASGD_client.lua:58-61).
        self.broadcast = connect(host, port)
        self.conn = connect(host, port + node)
        self.center: list[np.ndarray] | None = None
        # None until the first handshake; False pins legacy once a plain-
        # string reply proves the server predates the packed wire
        self._packed: bool | None = None
        self._residuals: list[np.ndarray] | None = None
        self._sender = _DeltaSender() if overlap else None

    def _announce(self, q: str, want: str) -> bool:
        """Send an admission request (with the wire advertisement unless a
        previous reply proved the server legacy) and parse the reply.
        Returns True when this handshake uses the packed wire."""
        adv = self.codec is not None and self._packed is not False
        msg: dict[str, Any] = {"q": q, "clientID": self.node}
        if adv:
            msg["wire"] = {"v": wire.WIRE_V, "codec": self.codec}
        self.broadcast.send_msg(msg)
        reply = self.conn.recv_msg()
        if not adv:
            if reply != want:
                raise ProtocolError(
                    f"protocol desync: expected {want!r}, got {reply!r}")
            return False
        self._packed = _check_wire_reply(reply, want, self.codec)
        return self._packed

    def init_client(self, params: PyTree) -> PyTree:
        """Receive the initial center from the server's broadcast; params :=
        center (ref lua :64-78).  The initial broadcast is always per-leaf
        (nothing has been negotiated yet) but ``recv_tensors`` auto-detects
        either framing."""
        leaves = _leaves(params)
        self.center = self.broadcast.recv_tensors(n=len(leaves))
        return _rebuild(params, [c.copy() for c in self.center])

    def sync_client(self, params: PyTree) -> tuple[PyTree, bool]:
        """Every ``tau``-th call: full sync handshake (ref ``syncClient``,
        lua :134-146).  Returns ``(new_params, synced)``."""
        self.step += 1
        if self.step % self.tau != 0:   # isSyncNeeded (lua :47-57)
            return params, False

        if self._sender is not None:
            # previous round's delta must be fully on the wire before the
            # next Enter? — also where a background failure surfaces
            self._sender.flush()
        # clientEnterSync (lua :82-92)
        print_client(self.node, "waiting to sync")
        packed = self._announce(ENTER_Q, ENTER)
        # clientGetCenter (lua :95-106): one packed frame (negotiated) or
        # per-leaf, auto-detected — either way into the preallocated
        # center buffers
        self.conn.send_msg(CENTER_Q)
        self.center = self.conn.recv_tensors(out=self.center)
        # calculateUpdateDiff (lua :109-119): local EA math.  The scale is
        # folded in-place into the one (p - c) temporary — at 100 MB-leaf
        # scale a second full-size allocation per leaf is measurable on the
        # sync path.
        leaves = _leaves(params)
        deltas = []
        for p, c in zip(leaves, self.center):
            # deltas go over the wire in the CENTER's dtype: the server
            # rejects dtype skew as config skew, and a client whose local
            # params drifted wider (e.g. f64 promotion) still interops —
            # its delta is representable either way
            d = np.asarray(p - c, dtype=c.dtype)
            d *= np.asarray(self.alpha, d.dtype)
            deltas.append(d)
        new_leaves = [p - d for p, d in zip(leaves, deltas)]
        payload = None
        if packed:
            if self.codec != "raw":
                # error feedback (Seide et al. 2014): quantize delta +
                # carried residual, keep the quantization error for the
                # next round — without it the bias accumulates and
                # quantized-EA walks away from the fp32 fixed point
                if (self._residuals is None
                        or len(self._residuals) != len(deltas)):
                    self._residuals = [np.zeros_like(d) for d in deltas]
                for d, r in zip(deltas, self._residuals):
                    d += r
                payload = wire.encode_leaves(deltas, self.codec)
                for r, d, dec in zip(self._residuals, deltas,
                                     payload.decoded()):
                    np.subtract(d, dec, out=r)
            else:
                payload = wire.encode_leaves(deltas, "raw")
        # clientSendDiff (lua :122-132)
        conn = self.conn

        def _push_delta():
            conn.send_msg(DELTA_Q)
            _expect(conn, DELTA)
            if payload is not None:
                conn.send_packed(payload)
            else:
                for d in deltas:
                    conn.send_tensor(d)

        if self._sender is not None:
            # overlap: the transmit/apply round-trip runs behind the next
            # τ local steps; params for those steps are already computed
            self._sender.submit(_push_delta)
        else:
            _push_delta()
        print_client(self.node, "synced")
        return _rebuild(params, new_leaves), True

    def rejoin(self, params: PyTree, retries: int = 60,
               retry_interval: float = 0.25,
               handshake_timeout: float | None = 60.0) -> PyTree:
        """Recover from an eviction: re-dial both channels, announce
        ``Rejoin?``, and take the server's CURRENT center as params (the
        local copy is stale by definition — rejoining with drifted params
        would push a delta against a center the client never saw).

        The server must be serving (its serve loop accepts rejoiners
        whenever any client is evicted).  Raises the underlying transport
        error if the server is gone; safe to call again.  Local state
        (``step``, ``tau``) is preserved so the sync cadence continues.
        """
        if self._sender is not None:
            # wait out (and discard the failure of) any in-flight delta —
            # it was riding the connection being replaced
            self._sender.drain()
        # the center we quantized against is gone; carrying a residual
        # across an eviction would re-inject error from a stale round
        self._residuals = None
        for c in (self.broadcast, self.conn):
            try:
                c.close()
            except OSError:
                pass
        # dedicated BEFORE the Rejoin? announce: the server completes the
        # handshake by accepting on port+node and must find us dialed in
        self.broadcast = connect(self.host, self.port, retries=retries,
                                 retry_interval=retry_interval)
        self.conn = connect(self.host, self.port + self.node,
                            retries=retries, retry_interval=retry_interval)
        # bounded: a server that never re-admits (e.g. this client was
        # transport-dropped without an eviction record) must surface a
        # TimeoutError here, not wedge the worker forever
        self.conn.set_timeout(handshake_timeout)
        self._announce(REJOIN_Q, REJOIN)
        leaves = _leaves(params)
        # deadline over the WHOLE center stream: a server stalling
        # mid-tensor must surface here too, not only on control frames
        dl = (None if handshake_timeout is None
              else time.monotonic() + handshake_timeout)
        self.center = self.conn.recv_tensors(n=len(leaves), deadline=dl)
        self.conn.send_msg(ACK)
        self.conn.set_timeout(None)
        print_client(self.node, "re-admitted")
        return _rebuild(params, [c.copy() for c in self.center])

    def close(self):
        if self._sender is not None:
            self._sender.close()
        self.broadcast.close()
        self.conn.close()


class AsyncEATester:
    """Evaluation role (ref initTester/startTest/finishTest).

    ``codec`` opts into the packed wire for center fetches.  Unlike the
    client, the tester's advertisement rides its OWN ``Center?`` request
    (there is no prior Enter? leg), so an advertising tester against an
    old server desyncs — leave ``codec=None`` in mixed fleets.
    """

    def __init__(self, host: str, port: int, num_nodes: int,
                 codec: str | None = None):
        if codec is not None and codec not in wire.CODECS:
            raise ValueError(f"unknown wire codec {codec!r} "
                             f"(supported: {', '.join(wire.CODECS)})")
        self.codec = codec
        # test channel on port+numNodes+1 (EASGD_tester.lua:64)
        self.conn = connect(host, port + num_nodes + 1)

    def start_test(self, params: PyTree) -> PyTree:
        """Block until the server pushes ``Test?``; fetch center into params
        (ref lua :268-285)."""
        _expect(self.conn, TEST_Q)
        if self.codec is not None:
            self.conn.send_msg({"q": CENTER_Q,
                                "wire": {"v": wire.WIRE_V,
                                         "codec": self.codec}})
        else:
            self.conn.send_msg(CENTER_Q)
        leaves = _leaves(params)
        new = self.conn.recv_tensors(n=len(leaves))
        print_tester("received center for evaluation")
        return _rebuild(params, new)

    def finish_test(self):
        """Ack the round so the server resumes (ref lua :287-292)."""
        self.conn.send_msg(ACK)

    def close(self):
        self.conn.close()
