"""Synchronous elastic averaging (EASGD) as a single fused collective.

Reference: lua/AllReduceEA.lua + the math note lua/AllReduceEA.md:12-24 —
EASGD (arXiv:1412.6651) recast so one allreduce per round suffices: every node
keeps a replica of the center point; every ``tau``-th local step each node

    delta  = (params - center) * alpha
    params = params - delta                 # elastic pull toward center
    all_d  = allreduce_sum(delta)
    center = center + all_d                 # center moves toward the nodes

TPU-native design: center/delta live as a state pytree; the whole round —
elastic update, psum, center update — is ONE jitted function, so XLA schedules
the ICI collective overlapped with the elementwise math (the BASELINE.json
"north star" fused collective).  The ``tau - 1`` intermediate steps are
communication-free by construction: the host only invokes the fused round when
a node's local step count hits a ``tau`` boundary, exactly like the reference
(lua :31).

**Every round is full-participation.**  In the reference, a node at its own
``tau`` boundary blocks in ``tree.allReduce`` until every other node reaches
its *own* next allreduce call — so averaging rounds pair up by ordinal, and
nodes that finished their (uneven) epoch keep serving stragglers' rounds with
*real* elastic contributions via the inline flush callback (lua :58-68: apply
center update, compute fresh delta, move, contribute it).  On a gang-scheduled
mesh this is the natural semantics: whenever any node is due, ALL nodes run the
elastic round.  This also matters numerically: the inter-node contraction
factor ``(1 - alpha)`` only applies uniformly under full participation (the
reference's own EA test passes at 8 nodes, alpha=0.4 — where the center
recursion factor ``|1 - alpha - N*alpha|`` exceeds 1 — precisely because every
round contracts the *inter-node* gap even while the center wanders).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from distlearn_tpu.parallel import mesh as mesh_lib
from distlearn_tpu.parallel.mesh import DEFAULT_AXIS, MeshTree

PyTree = Any


class EAState(NamedTuple):
    """Elastic-averaging state carried across steps (functional equivalent of
    the reference's lazily-cloned ``center``/``delta`` locals, lua :11-22;
    ``delta`` needs no slot — it is a value, not a buffer, under XLA)."""
    center: PyTree     # per-node replica of the center point
    step: jax.Array    # i32 — this node's local step count (ref ``step``, lua :5)


def init_state(params: PyTree) -> EAState:
    """Clone params as the initial center (ref ``oneTimeInit``, lua :11-22)."""
    return EAState(center=jax.tree_util.tree_map(jnp.array, params),
                   step=jnp.zeros((), jnp.int32))


# ---------------------------------------------------------------------------
# In-step pure functions
# ---------------------------------------------------------------------------

def elastic_round(params: PyTree, state: EAState, alpha: float,
                  axis_name: str = DEFAULT_AXIS) -> tuple[PyTree, EAState]:
    """One fused elastic-averaging round (ref lua :35-45 / md :12-24):
    elastic pull, psum of deltas, center move — a single XLA program."""
    a = alpha

    delta = jax.tree_util.tree_map(
        lambda p, c: (p - c) * jnp.asarray(a, p.dtype), params, state.center)
    new_params = jax.tree_util.tree_map(lambda p, d: p - d, params, delta)
    sum_delta = jax.tree_util.tree_map(lambda d: lax.psum(d, axis_name), delta)
    new_center = jax.tree_util.tree_map(lambda c, d: c + d, state.center, sum_delta)
    return new_params, EAState(center=new_center, step=state.step)


def average_parameters(params: PyTree, state: EAState, tau: int, alpha: float,
                       contrib: jax.Array | None = None,
                       axis_name: str = DEFAULT_AXIS) -> tuple[PyTree, EAState]:
    """Per-step entry point (ref ``averageParameters``, lua :25-47).

    Bumps this node's step count; when ANY node's count hits a ``tau``
    boundary, runs the full-participation fused round (see module docstring).
    The branch is a ``lax.cond`` so one compiled program serves both cases —
    but NOTE: for peak throughput call :func:`elastic_round` from the host only
    on averaging steps and keep the other ``tau - 1`` steps collective-free
    (what the example trainers do; a skipped psum is not free under cond).
    """
    c = jnp.ones((), jnp.int32) if contrib is None else jnp.asarray(contrib, jnp.int32)
    step = state.step + c
    my_due = jnp.logical_and(c > 0, (step % tau) == 0)
    any_due = lax.psum(my_due.astype(jnp.int32), axis_name) > 0

    st = EAState(center=state.center, step=step)

    def _avg(p, s):
        return elastic_round(p, s, alpha, axis_name=axis_name)

    def _skip(p, s):
        return p, s

    new_params, new_state = lax.cond(any_due, _avg, _skip, params, st)
    return new_params, new_state


def synchronize_center(params: PyTree, state: EAState,
                       axis_name: str = DEFAULT_AXIS
                       ) -> tuple[PyTree, EAState]:
    """End-of-epoch center sync (ref ``synchronizeCenter``, lua :77-84).

    Straggler rounds have already been served full-participation inside
    :func:`average_parameters`; what remains of the reference's
    ``handleUnevenSteps`` is its terminal zero-contribution flush — a no-op —
    so this reduces to the ``scatter(center)`` drift repair (lua :74-76):
    broadcast node 0's center replica and reset the step counter.
    Deterministic XLA psums keep replicas bitwise-identical already, but the
    broadcast preserves the reference contract under multi-host drift.
    """
    center = mesh_lib.broadcast_from(state.center, 0, axis_name)
    return params, EAState(center=center, step=jnp.zeros((), jnp.int32))


def synchronize_parameters(params: PyTree, state: EAState,
                           axis_name: str = DEFAULT_AXIS
                           ) -> tuple[PyTree, EAState]:
    """Force identical params on all nodes (ref lua :87-100): broadcast params
    from root, reset center := params."""
    synced = mesh_lib.broadcast_from(params, 0, axis_name)
    center = jax.tree_util.tree_map(jnp.array, synced)
    return synced, EAState(center=center, step=jnp.zeros((), jnp.int32))


# ---------------------------------------------------------------------------
# Host-level factory mirroring AllReduceEA(tree, tau, alpha) (lua :2)
# ---------------------------------------------------------------------------

class AllReduceEA:
    """Host-level API mirroring the reference closures, over any
    :class:`~distlearn_tpu.comm.backend.CollectiveBackend`.

    On a whole-view handle (:class:`MeshTree`/``MeshBackend``) the center
    lives on device as a stacked node array and every elastic round is one
    jitted shard_map over the mesh (the fused fast path).  On a partial-view
    handle (``HostBackend``: one node per process; ``HybridBackend``: this
    host's slice) the round is the generic delta/allreduce/center-move over
    the protocol — and, like the reference (lua :31: a due node *blocks* in
    ``tree.allReduce`` until every peer reaches its own next call), rounds
    pair up by ordinal across handles: every process must hit its ``tau``
    boundaries on the same calls (uniform stepping), or drive the full
    uneven-step flush protocol of
    :mod:`distlearn_tpu.parallel.host_algorithms` instead.

    Per-node step counts are host-side (the host drives round cadence,
    ref lua :5,31).
    """

    def __init__(self, tree: MeshTree, tau: int, alpha: float):
        self.tree = tree
        self.tau = int(tau)
        self.alpha = float(alpha)
        self._axis = getattr(tree, "axis_name", None)
        stacked = getattr(tree, "stacked_nodes", tree.num_nodes)
        self._local = 1 if stacked is None else int(stacked)
        self._offset = int(getattr(tree, "node_offset", 0))
        self._fused = (self._local == tree.num_nodes
                       and hasattr(tree, "spmd"))
        self._center = None     # pytree, handle's value convention
        self._steps = None      # host-side per-node counts (ref lua :5)
        self._round_jit = None

    def _one_time_init(self, params: PyTree):
        """Ref ``oneTimeInit`` (lua :11-22): clone params as the center."""
        if self._center is None:
            if self._fused:
                self._center = jax.tree_util.tree_map(jnp.array, params)
            else:
                self._center = jax.tree_util.tree_map(
                    lambda p: np.array(np.asarray(p), copy=True), params)
            self._steps = np.zeros(self.tree.num_nodes, dtype=np.int64)

    def _round(self, params, center):
        """Jitted full-participation fused elastic round over stacked arrays."""
        if self._round_jit is None:
            axis = self._axis

            def _fn(p, c):
                st = EAState(center=mesh_lib.squeeze_node(c),
                             step=jnp.zeros((), jnp.int32))
                np_, ns = elastic_round(mesh_lib.squeeze_node(p), st,
                                        self.alpha, axis_name=axis)
                return mesh_lib.expand_node(np_), mesh_lib.expand_node(ns.center)

            self._round_jit = self.tree.spmd(
                _fn,
                in_specs=(self.tree.node_spec(),) * 2,
                out_specs=(self.tree.node_spec(), self.tree.node_spec()))
        return self._round_jit(params, center)

    def _round_generic(self, params: PyTree) -> PyTree:
        """One full-participation elastic round over the protocol (host /
        hybrid handles): host-side delta math, one backend allreduce.
        Same three assignments as :func:`elastic_round`, so with
        order-insensitive (dyadic-exact) arithmetic the trajectory is
        bitwise the fused mesh path's."""
        a = self.alpha

        def _delta(p, c):
            p = np.asarray(p)
            return (p - np.asarray(c)) * np.asarray(a, p.dtype)

        delta = jax.tree_util.tree_map(_delta, params, self._center)
        new_params = jax.tree_util.tree_map(
            lambda p, d: np.asarray(p) - d, params, delta)
        sum_d, _ = self.tree.all_reduce(delta)
        self._center = jax.tree_util.tree_map(
            lambda c, d: np.asarray(c) + np.asarray(d),
            self._center, sum_d)
        return new_params

    def average_parameters(self, params: PyTree, contrib=None) -> PyTree:
        """Ref lua :25-47: bump local steps; when any node's count hits a tau
        boundary, run the full-participation elastic round.  On a
        partial-view handle the due-check sees only this handle's nodes
        (the reference's ordinal pairing — class docstring)."""
        self._one_time_init(params)
        lo, hi = self._offset, self._offset + self._local
        if contrib is None or contrib is True:
            c = np.ones(self._local, dtype=np.int64)
        elif contrib is False:
            c = np.zeros(self._local, dtype=np.int64)
        else:
            c = np.asarray(contrib, dtype=np.int64)
        self._steps[lo:hi] += c
        due = (c > 0) & (self._steps[lo:hi] % self.tau == 0)
        if not due.any():
            return params
        if self._fused:
            new_params, self._center = self._round(params, self._center)
            return new_params
        return self._round_generic(params)

    def synchronize_center(self, params: PyTree) -> PyTree:
        """Ref lua :77-84: scatter(center) drift repair + step reset (the
        uneven-step rounds were already served full-participation)."""
        self._one_time_init(params)
        self._center = self.tree.scatter(self._center, src=0)
        self._steps[:] = 0
        return params

    def synchronize_parameters(self, params: PyTree) -> PyTree:
        """Ref lua :87-100: scatter(params) + center := params."""
        if self._steps is None:
            self._steps = np.zeros(self.tree.num_nodes, dtype=np.int64)
        params = self.tree.scatter(params, src=0)
        if self._fused:
            self._center = jax.tree_util.tree_map(jnp.array, params)
        else:
            self._center = jax.tree_util.tree_map(
                lambda p: np.array(np.asarray(p), copy=True), params)
        self._steps[:] = 0
        return params
