"""Static lockset race detection (rules DL111/DL112).

An Eraser-style analysis (Savage et al., SOSP '97) done statically: for
every ``self._field`` access in the audited classes, compute the set of
locks GUARANTEED held on every path from each thread entry point to the
access, then intersect locksets across entry points.  A field written
with an empty write-lockset intersection while another thread can touch
it is DL111 (error); a field whose writes all share a guard that some
cross-thread read skips is DL112 (warning — the torn-read hazard class).
This extends the DL102/DL103 lock-order audit in ``lint/protocol.py``
from *locks* to the *data* they protect.

How locksets are computed
-------------------------
The analysis is per class, purely on the AST (so it accepts raw source
strings — the seeded-mutation tests strip a ``with self._lock:`` from
the real ``async_ea.py`` source and feed the result back in):

* ``with self._lock:`` blocks (any name containing ``lock``, matching
  the DL102 auditor) push a lock lexically; ``with locks[i]:`` pushes
  the striped form ``locks[]``.  A ``try:`` whose ``finally`` calls
  ``X.release()`` is treated as holding ``X`` for its body (the
  ``acquire(blocking=False)`` idiom).
* Intra-class ``self.method()`` calls propagate the caller's held set
  into the callee (BFS over ``(method, lockset)`` states).
* Thread entry points are discovered from ``threading.Thread(target=
  self.m)`` call sites and from nested ``def``s that close over
  ``self`` (the ``_fanout`` leg pattern — a closure may run on another
  thread, and locks held lexically outside it are NOT held when it
  runs).  :data:`THREAD_API` adds the documented cross-thread public
  surface (health probes, signal-handler checkpoints, ``stop``).
* Writes in ``__init__`` and the per-class :data:`SETUP_METHODS`
  (``init_server``/``start``/... — code that runs before the threads
  exist) are initialization, not races (Eraser's virgin state).

Fields in :data:`BENIGN_FIELDS` are excluded with a recorded reason —
each entry cites the in-code documentation of WHY the unlocked access
is deliberate (GIL-atomic latches, torn-view-tolerant telemetry).  The
list is the audit's reviewable artifact: adding to it is a conscious
decision in a diff, not a silent pass.
"""

from __future__ import annotations

import ast
import inspect
from dataclasses import dataclass, field as dc_field
from typing import Iterable

from distlearn_tpu.lint.core import Finding

__all__ = ["lint_races", "analyze_source", "core_targets", "fleet_targets",
           "THREAD_API", "SETUP_METHODS", "BENIGN_FIELDS"]


#: Documented cross-thread public surface per class: methods callable
#: from a thread OTHER than the one(s) the class spawns.
THREAD_API: dict = {
    # concurrent center: telemetry + HA surface is called from the obs
    # export thread, signal handlers, and the operator's main thread
    "AsyncEAServerConcurrent": {
        "checkpoint_now", "adopt_ha_meta", "stop", "test_net",
        "_health", "drained", "syncs_completed", "live_clients",
    },
    # serial center: single-threaded serve loop, but the SIGTERM flush
    # (ha.install_signal_flush) interrupts it with checkpoint_now
    "AsyncEAServer": {"checkpoint_now"},
    "_ShardEndpoint": {"get_conn", "drop", "drop_if", "drop_if_dead",
                       "close"},
    "_DeltaSender": {"submit", "flush", "drain", "close"},
    "ServeServer": {"health", "checkpoint_now", "stop"},
    # obs: metric mutators run on every instrumented thread; sample()
    # runs on the export thread
    "_Counter": {"inc", "sample"},
    "_Gauge": {"inc", "dec", "set", "sample"},
    "_Histogram": {"observe", "sample"},
    "Family": {"labels", "inc", "dec", "set", "observe", "value",
               "sample"},
    "Registry": {"counter", "gauge", "histogram", "snapshot",
                 "render_prometheus", "reset"},
    # -- fleet-era scope (PRs 13-15) --------------------------------------
    # router: generate() runs on every caller thread; health probes run
    # on the refresher cadence; membership mutators run on the
    # autoscaler's control thread
    "Router": {"generate", "health", "add_replica", "remove_replica",
               "replica_names", "close"},
    # collector: poll() runs on the autoscaler loop; endpoint membership
    # is mutated by operator/actuator threads
    "Collector": {"poll", "add_endpoint", "remove_endpoint"},
    "FleetRegistry": {"ingest", "forget", "sources", "merged", "total",
                      "histogram", "breakdown"},
    # fault plan: the chaos script mutates link state while wrapped
    # connections consult it from every transport thread
    "FaultPlan": {"partition", "heal", "delay", "bandwidth", "cut_after",
                  "fail_dials", "flaky_dials", "connect", "wrap",
                  "dropped_bytes", "decisions"},
}

#: Initialization phase per class: writes here happen before the
#: threads that could race exist (Eraser's virgin->exclusive states).
SETUP_METHODS: dict = {
    "AsyncEAServer": {"init_server", "enable_checkpoint"},
    "AsyncEAServerConcurrent": {"init_server", "enable_checkpoint",
                                "start", "_pin"},
    "AsyncEAClient": {"init_client"},
    "ServeServer": {"start"},
}

#: (class, field) -> reason.  Every entry cites the code's own
#: documentation of why the unlocked access is deliberate.  This list is
#: exactly the set of raw findings on the audited tree — removing an
#: entry must either produce a finding or the entry is stale.
BENIGN_FIELDS: dict = {
    # -- parallel/async_ea.py ----------------------------------------------
    ("AsyncEAServer", "_applied_seq"):
        "serial server legs write disjoint (cid, stripe) ledger keys; the "
        "signal-handler checkpoint only reads, and _record_applied "
        "documents the publish+ledger critical-section discipline the "
        "concurrent subclass enforces with locks",
    ("AsyncEAServerConcurrent", "_dev_center"):
        "unlocked reads are `is (not) None` mode checks: pinned-ness is "
        "fixed at _pin() time; the array contents only swap under _lock",
    ("AsyncEAServerConcurrent", "_inflight"):
        "_health reads are documented lock-free: 'telemetry tolerates a "
        "torn view' (async_ea.py _health)",
    ("AsyncEAServerConcurrent", "_workers"):
        "stop() rewrites the map only AFTER joining the worker threads "
        "that mutate it — the race window is closed by join, not a lock",
    ("AsyncEAServerConcurrent", "center"):
        "immutable publish: the pointer swaps under _lock, readers take "
        "lock-free snapshots of frozen (writeable=False) leaves; "
        "stripe-range reads under only the stripe lock are stable because "
        "entries [lo, hi) change under that lock (_apply_stripe docstring)",
    ("_DeltaSender", "_err"):
        "ordered by the _idle Event, not a lock: _loop writes it only "
        "while _idle is cleared; flush/drain read only after _idle.wait() "
        "(class docstring: failure surfaced at the next flush)",
    ("_DeltaSender", "_idle"):
        "threading.Event is internally synchronized; set/clear/wait are "
        "its API, not raw shared-state mutation",
    # -- serve/server.py ----------------------------------------------------
    ("ServeServer", "_draining"):
        "GIL-atomic one-way bool latch: set by checkpoint_now, polled by "
        "the loop and health(); documented in checkpoint_now",
    ("ServeServer", "_failed"):
        "write-once failure latch published by the dying loop for "
        "health() readers ('record it, flip health'); a str attribute "
        "store is GIL-atomic",
    ("ServeServer", "epoch"):
        "written only by the serve loop's _maybe_swap; health() probes "
        "take GIL-atomic int snapshots — 'a probe racing a swap sees "
        "either epoch, both valid' (server.py epoch docstring)",
    ("ServeServer", "ckpt_step"):
        "same single-writer discipline as epoch: loop-only writes, "
        "GIL-atomic health() reads (server.py epoch docstring)",
    ("ServeServer", "_swap_pending"):
        "loop-only two-phase swap latch; health() reads only its "
        "None-ness for the swap_pending flag — a tuple attribute "
        "store is GIL-atomic (server.py epoch docstring)",
    ("ServeServer", "prefix_cache"):
        "the attribute itself is fixed at __init__ (None or the cache); "
        "the loop's clear() mutates cache internals and health() reads "
        "only the pages_held int — a GIL-atomic snapshot, and "
        "'telemetry tolerates a torn view' like the other gauges",
    # -- obs/core.py --------------------------------------------------------
    ("_Counter", "value"):
        "documented lock-cheap metric path: plain attribute increments "
        "are GIL-atomic and the export sample tolerates a torn view",
    ("_Gauge", "value"):
        "documented lock-cheap metric path: plain attribute increments "
        "are GIL-atomic and the export sample tolerates a torn view",
    ("Family", "_children"):
        "double-checked locking: lock-free fast-path dict read, create + "
        "re-check under the module _lock (labels())",
    ("Registry", "_families"):
        "double-checked locking: lock-free fast-path dict read, create + "
        "re-check under the module _lock (_get())",
    # -- serve/router.py ---------------------------------------------------
    ("Router", "_replicas"):
        "copy-on-write list: membership mutators rebuild and swap the "
        "whole list under _lock, so generate()'s lock-free availability "
        "scan only ever sees a complete list (router.py add_replica)",
}

_MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "remove", "discard",
    "pop", "popleft", "popitem", "clear", "update", "setdefault",
    "add", "sort",
})


@dataclass
class _Access:
    field: str
    kind: str              # 'r' | 'w'
    locks: frozenset
    line: int


@dataclass
class _Method:
    name: str
    accesses: list = dc_field(default_factory=list)
    calls: list = dc_field(default_factory=list)   # (callee, locks, line)
    is_nested: bool = False


def _norm_lock(expr) -> str | None:
    """Canonical name for a lock-ish with-item / release target."""
    if isinstance(expr, ast.Subscript):
        base = _norm_lock(expr.value)
        return f"{base}[]" if base else None
    name = None
    if isinstance(expr, ast.Attribute):
        name = expr.attr
    elif isinstance(expr, ast.Name):
        name = expr.id
    if name and "lock" in name.lower():
        return name.lstrip("_")
    return None


class _ClassVisitor(ast.NodeVisitor):
    """Collect per-method field accesses, held locksets, intra-class
    calls, and thread entry points for ONE class body."""

    def __init__(self, class_name: str):
        self.class_name = class_name
        self.methods: dict[str, _Method] = {}
        self.entries: set[str] = set()
        self._cur: list[_Method] = []
        self._locks: list[str] = []
        self._outer: list[str] = []

    # -- structure ----------------------------------------------------------
    def visit_FunctionDef(self, node):
        nested = bool(self._cur)
        name = ".".join(self._outer + [node.name]) if nested else node.name
        m = _Method(name, is_nested=nested)
        self.methods[name] = m
        if nested:
            # a closure may run on another thread (_fanout legs); locks
            # held lexically outside it are NOT held when it runs
            self.entries.add(name)
        self._cur.append(m)
        self._outer.append(node.name)
        saved, self._locks = self._locks, []
        for stmt in node.body:
            self.visit(stmt)
        self._locks = saved
        self._outer.pop()
        self._cur.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass                       # no lock/alias tracking inside lambdas

    # -- lock scopes --------------------------------------------------------
    def visit_With(self, node):
        got = []
        for item in node.items:
            lk = _norm_lock(item.context_expr)
            if lk is not None:
                self._locks.append(lk)
                got.append(lk)
        for stmt in node.body:
            self.visit(stmt)
        for _ in got:
            self._locks.pop()

    def visit_Try(self, node):
        # acquire()/try/finally release() idiom: the body holds the lock
        held = []
        for stmt in node.finalbody:
            if (isinstance(stmt, ast.Expr)
                    and isinstance(stmt.value, ast.Call)
                    and isinstance(stmt.value.func, ast.Attribute)
                    and stmt.value.func.attr == "release"):
                lk = _norm_lock(stmt.value.func.value)
                if lk is not None:
                    held.append(lk)
        self._locks.extend(held)
        for stmt in node.body + node.orelse:
            self.visit(stmt)
        for _ in held:
            self._locks.pop()
        for h in node.handlers:
            self.visit(h)
        for stmt in node.finalbody:
            self.visit(stmt)

    # -- accesses -----------------------------------------------------------
    def _record(self, fieldname: str, kind: str, line: int):
        if self._cur:
            self._cur[-1].accesses.append(_Access(
                fieldname, kind, frozenset(self._locks), line))

    @staticmethod
    def _self_attr(node) -> str | None:
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            return node.attr
        return None

    def visit_Attribute(self, node):
        fieldname = self._self_attr(node)
        if fieldname is not None:
            kind = "w" if isinstance(node.ctx, (ast.Store, ast.Del)) else "r"
            self._record(fieldname, kind, node.lineno)
        self.generic_visit(node)

    def visit_Subscript(self, node):
        # self._x[i] = v / del self._x[i]: container mutation -> write
        fieldname = self._self_attr(node.value)
        if fieldname is not None and isinstance(node.ctx,
                                                (ast.Store, ast.Del)):
            self._record(fieldname, "w", node.lineno)
        self.generic_visit(node)

    def visit_Call(self, node):
        fn = node.func
        if isinstance(fn, ast.Attribute):
            # self.m(...) -> intra-class call edge
            callee = self._self_attr(fn)
            if callee is not None and self._cur:
                self._cur[-1].calls.append(
                    (callee, frozenset(self._locks), node.lineno))
            # self._x.append(...) -> container mutation -> write
            if fn.attr in _MUTATORS:
                owner = self._self_attr(fn.value)
                if owner is not None:
                    self._record(owner, "w", node.lineno)
            # threading.Thread(target=self.m) -> thread entry point
            if fn.attr == "Thread":
                for kw in node.keywords:
                    if kw.arg == "target":
                        tgt = self._self_attr(kw.value)
                        if tgt is not None:
                            self.entries.add(tgt)
        self.generic_visit(node)


def _effective_accesses(cv: _ClassVisitor, entry: str,
                        setup: set) -> "list[tuple[str, _Access, frozenset]]":
    """All (method, access, path-lockset) reachable from ``entry``,
    propagating guaranteed-held locks through intra-class calls."""
    out = []
    seen: set = set()
    work = [(entry, frozenset())]
    while work:
        mname, held = work.pop()
        if (mname, held) in seen:
            continue
        seen.add((mname, held))
        m = cv.methods.get(mname)
        if m is None:
            continue
        for acc in m.accesses:
            out.append((mname, acc, held | acc.locks))
        for callee, at_site, _line in m.calls:
            if callee in cv.methods and callee != "__init__":
                work.append((callee, held | at_site))
    return out


def _audit_class(cv: _ClassVisitor, modname: str) -> list[Finding]:
    entries = set(cv.entries)
    entries |= {m for m in THREAD_API.get(cv.class_name, ())
                if m in cv.methods}
    setup = {"__init__"} | set(SETUP_METHODS.get(cv.class_name, ()))
    entries -= setup
    if len(entries) < 2:
        return []            # no cross-thread surface to intersect
    # field -> list of (entry, method, access, lockset)
    per_field: dict[str, list] = {}
    for entry in sorted(entries):
        for mname, acc, locks in _effective_accesses(cv, entry, setup):
            if acc.kind == "w" and mname in setup:
                continue     # initialization writes (virgin state)
            per_field.setdefault(acc.field, []).append(
                (entry, mname, acc, locks))
    findings = []
    for fieldname in sorted(per_field):
        if (cv.class_name, fieldname) in BENIGN_FIELDS:
            continue
        accs = per_field[fieldname]
        touched_by = {e for e, _m, _a, _l in accs}
        if len(touched_by) < 2:
            continue         # single thread role: no race surface
        writes = [(e, m, a, l) for e, m, a, l in accs if a.kind == "w"]
        if not writes:
            continue         # read-only after init
        wcommon = frozenset.intersection(*[l for _e, _m, _a, l in writes])
        where = f"{modname}.{cv.class_name}.{fieldname}"

        def _ev(rows, n=3):
            return ", ".join(
                f"{m}:{a.line} [{e}]"
                + (f" holds {{{', '.join(sorted(l))}}}" if l
                   else " holds no lock")
                for e, m, a, l in rows[:n])

        if not wcommon:
            # least-guarded writes first: the offending row must survive
            # the evidence truncation
            writes.sort(key=lambda row: len(row[3]))
            findings.append(Finding(
                "DL111",
                f"field written with NO lock common to all writers while "
                f"{len(touched_by)} thread roles "
                f"({', '.join(sorted(touched_by))}) touch it — "
                f"writes: {_ev(writes)}; "
                f"other accesses: "
                f"{_ev([r for r in accs if r[2].kind == 'r'])}",
                where=where))
            continue
        naked = [(e, m, a, l) for e, m, a, l in accs if not (wcommon & l)]
        if naked:
            findings.append(Finding(
                "DL112",
                f"writes are consistently guarded by "
                f"{{{', '.join(sorted(wcommon))}}} but cross-thread "
                f"access(es) skip the guard (torn-read hazard): "
                f"{_ev(naked)}",
                where=where, severity="warning"))
    return findings


def analyze_source(src: str, modname: str = "<string>") -> list[Finding]:
    """Run the lockset audit over one module's source text."""
    tree = ast.parse(src)
    findings: list[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            cv = _ClassVisitor(node.name)
            for stmt in node.body:
                cv.visit(stmt)
            findings += _audit_class(cv, modname)
    return findings


def core_targets() -> list:
    """The original audit scope: the training/HA/serve-core threaded
    modules (plus the obs metric primitives they instrument)."""
    from distlearn_tpu import obs  # noqa: F401  (import side-effects)
    from distlearn_tpu.obs import core as obs_core
    from distlearn_tpu.obs import export as obs_export
    from distlearn_tpu.obs import trace as obs_trace
    from distlearn_tpu.parallel import async_ea, ha
    from distlearn_tpu.serve import scheduler, server
    return [async_ea, ha, server, scheduler,
            obs_core, obs_export, obs_trace]


def fleet_targets() -> list:
    """The fleet-era scope (PRs 13-15): the serve router, the obs fleet
    collector, the fault plan, and the autoscaler.  ``tools/`` is not a
    package, so the autoscaler rides along as a ``(source, modname)``
    pair read straight off disk."""
    import os
    from distlearn_tpu.comm import faults
    from distlearn_tpu.obs import agg as obs_agg
    from distlearn_tpu.serve import router
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    with open(os.path.join(repo, "tools", "autoscaler.py")) as fh:
        autoscaler_src = fh.read()
    return [router, obs_agg, faults, (autoscaler_src, "tools.autoscaler")]


def lint_races(targets: Iterable | None = None) -> list[Finding]:
    """DL111/DL112 audit.  ``targets``: modules, raw source strings, or
    ``(source, modname)`` pairs; defaults to :func:`core_targets` +
    :func:`fleet_targets` (the full threaded surface)."""
    if targets is None:
        targets = core_targets() + fleet_targets()
    findings: list[Finding] = []
    for t in targets:
        if isinstance(t, tuple):
            src, modname = t
        elif isinstance(t, str):
            src, modname = t, "<string>"
        else:
            src, modname = inspect.getsource(t), t.__name__
        findings += analyze_source(src, modname)
    return findings
