"""Jaxpr-level SPMD/collective linter (rules DL001-DL005).

The linter abstractly traces a step function to a closed jaxpr
(:func:`jax.make_jaxpr`) and walks it, descending through every
higher-order primitive the repo emits (``pjit``, ``shard_map``, ``cond``,
``while``, ``scan``, ``remat``, custom-derivative calls).  Two pieces of
state thread through the walk:

* ``bound`` — the set of mesh axis names the current code is executing
  under, one entry per device along that axis.  Extended by ``shard_map``
  equations (their ``mesh`` param) and seeded at the top level from the
  trace ``axis_env`` intersected with the deployment mesh, so an axis
  bound at trace time but absent from the real mesh is *not* considered
  bound — that is exactly rule DL001.

* per-value **taint** — the set of bound axes across which a value may
  differ between devices.  Sources: ``axis_index`` output and
  ``shard_map`` inputs sharded along an axis (``in_names``).  A reducing
  collective over axes ``A`` makes its result identical along ``A`` and
  subtracts ``A`` from the taint; everything else unions its operands.
  Taint is what lets DL002 stay quiet on the repo's
  ``lax.cond(any_due, ...)`` pattern (predicate derived from a ``psum``
  is device-uniform, so divergent branches are safe) while still firing
  when the predicate genuinely varies per device, and what lets DL003
  recognise ``fold_in(key, axis_index(...))`` as per-device randomness.

Entry points: :func:`lint_step` (trace a callable and lint it, including
the DL005 donation audit when the callable is jitted) and
:func:`lint_jaxpr` (lint an already-closed jaxpr).
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import jax
from jax import core

from distlearn_tpu.lint.core import Finding, filter_suppressed

__all__ = ["lint_step", "lint_jaxpr", "lint_donation"]

# Cross-device communication primitives: a mismatched sequence of these
# across devices is a hang.  ``axis_index`` is checked for DL001 but is
# not a synchronization point, so it stays out of this set.
_COLLECTIVES = frozenset({
    "psum", "pmax", "pmin", "ppermute", "pbroadcast", "pgather",
    "all_gather", "all_to_all", "reduce_scatter",
})
# Collectives that *accumulate* across devices: low-precision operands
# lose mantissa once the reduction fan-in grows (DL004).  pmax/pmin are
# exact in any dtype and exempt.
_ACCUMULATING = frozenset({"psum", "reduce_scatter"})
# Collectives whose result is identical along the reduced/gathered axes.
_UNIFORMIZING = frozenset({"psum", "pmax", "pmin", "all_gather"})
# PRNG consumption points (typed-key and raw-uint32 paths).
_RNG_CONSUMERS = frozenset({"random_bits", "threefry2x32"})


def _collective_axes(eqn) -> tuple[str, ...]:
    """Mesh axis names a collective equation communicates over."""
    if eqn.primitive.name in ("psum", "pmax", "pmin"):
        axes = eqn.params.get("axes", ())
    else:
        axes = eqn.params.get("axis_name", ())
    if isinstance(axes, str):
        axes = (axes,)
    return tuple(a for a in axes if isinstance(a, str))


def _sub_jaxpr(params):
    """Best-effort: the single sub-jaxpr of a call-like equation."""
    for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
        v = params.get(key)
        if isinstance(v, (core.Jaxpr, core.ClosedJaxpr)):
            return v
    return None


class _WalkResult(NamedTuple):
    out_taints: list          # frozenset per outvar
    seq: tuple                # ordered collective signature ((prim, axes), ...)
    findings: list            # list[Finding]


def _walk_closed(cj, in_taints, bound, path):
    if isinstance(cj, core.ClosedJaxpr):
        return _walk(cj.jaxpr, in_taints, bound, path)
    return _walk(cj, in_taints, bound, path)


def _walk(jaxpr: core.Jaxpr, in_taints, bound: frozenset, path: str) -> _WalkResult:
    env: dict = {}
    findings: list[Finding] = []
    seq: list = []

    def taint_of(atom):
        if isinstance(atom, core.Literal):
            return frozenset()
        return env.get(atom, frozenset())

    for v, t in zip(jaxpr.invars, in_taints):
        env[v] = t
    for v in jaxpr.constvars:
        env[v] = frozenset()

    for i, eqn in enumerate(jaxpr.eqns):
        prim = eqn.primitive.name
        here = f"{path}/{prim}#{i}"
        in_ts = [taint_of(a) for a in eqn.invars]
        default_out = frozenset().union(*in_ts) if in_ts else frozenset()

        if prim == "shard_map":
            mesh_axes = frozenset(str(a) for a in eqn.params["mesh"].axis_names)
            inner_bound = bound | mesh_axes
            body_in = []
            for t, names in zip(in_ts, eqn.params["in_names"]):
                sharded = frozenset(
                    str(a) for axes in dict(names).values()
                    for a in (axes if isinstance(axes, (tuple, list)) else (axes,)))
                body_in.append(t | sharded)
            sub = _walk_closed(eqn.params["jaxpr"], body_in, inner_bound,
                               f"{here}")
            findings += sub.findings
            seq += sub.seq
            # Leaving the region the per-device shards are reassembled into
            # global arrays: variance along this shard_map's axes is spent.
            for v, t in zip(eqn.outvars, sub.out_taints):
                env[v] = t - mesh_axes
            continue

        if prim == "cond":
            pred_t = in_ts[0]
            branches = eqn.params["branches"]
            subs = [_walk_closed(br, in_ts[1:], bound,
                                 f"{here}[branch {k}]")
                    for k, br in enumerate(branches)]
            for s in subs:
                findings += s.findings
            sigs = {s.seq for s in subs}
            if len(sigs) > 1 and pred_t:
                findings.append(Finding(
                    "DL002",
                    "collective sequences differ across cond branches "
                    f"({' vs '.join(_fmt_seq(s.seq) for s in subs)}) and the "
                    f"predicate varies across mesh axes {sorted(pred_t)}; "
                    "devices taking different branches will issue mismatched "
                    "collectives and hang",
                    where=here))
            seq += subs[0].seq
            for k, v in enumerate(eqn.outvars):
                t = frozenset().union(*(s.out_taints[k] for s in subs))
                env[v] = t | pred_t
            continue

        if prim == "while":
            cn, bn = eqn.params["cond_nconsts"], eqn.params["body_nconsts"]
            cond_consts, body_consts = in_ts[:cn], in_ts[cn:cn + bn]
            carry = list(in_ts[cn + bn:])
            body_j = eqn.params["body_jaxpr"]
            cond_j = eqn.params["cond_jaxpr"]
            for _ in range(8):  # taint fixpoint over the carry
                out = _walk_closed(body_j, body_consts + carry, bound, here)
                new = [c | o for c, o in zip(carry, out.out_taints)]
                if new == carry:
                    break
                carry = new
            body = _walk_closed(body_j, body_consts + carry, bound,
                                f"{here}[body]")
            cond = _walk_closed(cond_j, cond_consts + carry, bound,
                                f"{here}[cond]")
            findings += body.findings + cond.findings
            pred_t = cond.out_taints[0] if cond.out_taints else frozenset()
            if pred_t and (body.seq or cond.seq):
                findings.append(Finding(
                    "DL002",
                    "while loop contains collectives "
                    f"({_fmt_seq(body.seq + cond.seq)}) but its predicate "
                    f"varies across mesh axes {sorted(pred_t)}; devices may "
                    "run different trip counts and hang",
                    where=here))
            seq += cond.seq + body.seq
            for v, t in zip(eqn.outvars, carry):
                env[v] = t | pred_t
            continue

        if prim == "scan":
            nc, nk = eqn.params["num_consts"], eqn.params["num_carry"]
            consts, carry, xs = in_ts[:nc], list(in_ts[nc:nc + nk]), in_ts[nc + nk:]
            body_j = eqn.params["jaxpr"]
            for _ in range(8):
                out = _walk_closed(body_j, consts + carry + xs, bound, here)
                new = [c | o for c, o in zip(carry, out.out_taints[:nk])]
                if new == carry:
                    break
                carry = new
            body = _walk_closed(body_j, consts + carry + xs, bound,
                                f"{here}[body]")
            findings += body.findings
            seq += body.seq
            outs = carry + list(body.out_taints[nk:])
            for v, t in zip(eqn.outvars, outs):
                env[v] = t
            continue

        if prim in _COLLECTIVES or prim == "axis_index":
            axes = _collective_axes(eqn)
            unknown = [a for a in axes if a not in bound]
            if unknown:
                findings.append(Finding(
                    "DL001",
                    f"{prim} over axis {unknown!r} but only "
                    f"{sorted(bound) or 'no axes'} are bound by the "
                    "enclosing mesh/shard_map",
                    where=here))
            if prim == "axis_index":
                for v in eqn.outvars:
                    env[v] = frozenset(axes)
                continue
            if prim in _ACCUMULATING:
                for a in eqn.invars:
                    dt = getattr(a.aval, "dtype", None)
                    if (dt is not None and jax.numpy.issubdtype(dt, jax.numpy.floating)
                            and dt.itemsize < 4):
                        findings.append(Finding(
                            "DL004",
                            f"{prim} over {axes!r} accumulates in {dt.name}; "
                            "upcast the operand to >=float32 before the "
                            "reduction and cast back after",
                            where=here))
            seq.append((prim, tuple(sorted(axes))))
            out_t = default_out
            if prim in _UNIFORMIZING:
                out_t = out_t - frozenset(axes)
            for v in eqn.outvars:
                env[v] = out_t
            continue

        if prim in _RNG_CONSUMERS:
            if bound and not default_out:
                findings.append(Finding(
                    "DL003",
                    f"PRNG key consumed ({prim}) inside an SPMD region over "
                    f"axes {sorted(bound)} but the key is identical on every "
                    "device; fold in a per-device value first, e.g. "
                    "random.fold_in(key, lax.axis_index(axis))",
                    where=here))
            for v in eqn.outvars:
                env[v] = default_out
            continue

        sub = _sub_jaxpr(eqn.params)
        if sub is not None:
            body = sub.jaxpr if isinstance(sub, core.ClosedJaxpr) else sub
            if len(body.invars) == len(eqn.invars):
                name = eqn.params.get("name")
                sub_path = f"{here}" + (f"({name})" if name else "")
                s = _walk_closed(sub, in_ts, bound, sub_path)
                findings += s.findings
                seq += s.seq
                if len(s.out_taints) == len(eqn.outvars):
                    for v, t in zip(eqn.outvars, s.out_taints):
                        env[v] = t
                    continue
        # Default transfer: outputs inherit the union of operand taints.
        for v in eqn.outvars:
            env[v] = default_out

    return _WalkResult([taint_of(v) for v in jaxpr.outvars],
                       tuple(seq), findings)


def _fmt_seq(seq) -> str:
    if not seq:
        return "[]"
    return "[" + ", ".join(f"{p}@{','.join(a)}" for p, a in seq) + "]"


def lint_jaxpr(closed_jaxpr: core.ClosedJaxpr, *, mesh=None, axis_env=None,
               name: str = "step") -> list[Finding]:
    """Lint a closed jaxpr.

    ``mesh`` (a :class:`jax.sharding.Mesh` or iterable of axis names) is the
    deployment mesh; ``axis_env`` the ``(name, size)`` bindings the jaxpr
    was traced under, if any.  Axes bound at trace time but missing from
    the deployment mesh are treated as unbound, so collectives over them
    raise DL001.
    """
    env_axes = frozenset(a for a, _ in (axis_env or ()))
    mesh_axes = _mesh_axis_names(mesh)
    bound = env_axes if mesh_axes is None else env_axes & mesh_axes
    in_taints = [frozenset() for _ in closed_jaxpr.jaxpr.invars]
    return _walk(closed_jaxpr.jaxpr, in_taints, bound, name).findings


def _mesh_axis_names(mesh):
    if mesh is None:
        return None
    names = getattr(mesh, "axis_names", mesh)
    return frozenset(str(a) for a in names)


def lint_donation(fn, args, *, name: str = "step") -> list[Finding]:
    """DL005: every donated input leaf must have a shape/dtype-matching
    output leaf to alias; otherwise the donation deletes a buffer XLA can
    never reuse and any later read of it fails."""
    try:
        lowered = fn.lower(*args)
        args_info = jax.tree_util.tree_leaves(lowered.args_info)
        out_info = jax.tree_util.tree_leaves(lowered.out_info)
    except Exception:  # not a jit wrapper, or lowering unsupported here
        return []
    findings = []
    outs = [(tuple(o.shape), jax.numpy.dtype(o.dtype)) for o in out_info]
    for a in args_info:
        if not getattr(a, "donated", False):
            continue
        aval = getattr(a, "aval", None) or a._aval  # private on old jax
        key = (tuple(aval.shape), jax.numpy.dtype(aval.dtype))
        if key in outs:
            outs.remove(key)  # each output aliases at most one input
        else:
            findings.append(Finding(
                "DL005",
                f"donated input {aval.str_short()} has no matching output "
                "to alias; the buffer is invalidated without being reused",
                where=name))
    return findings


def lint_step(fn, args: Sequence, *, mesh=None, axis_env=None,
              suppress=(), name: str = "step",
              check_donation: bool = True) -> list[Finding]:
    """Trace ``fn(*args)`` abstractly and lint the resulting jaxpr.

    ``args`` may be concrete arrays or :class:`jax.ShapeDtypeStruct`s.
    When ``fn`` is a jit wrapper the DL005 donation audit runs as well.
    """
    make = jax.make_jaxpr(fn, axis_env=list(axis_env) if axis_env else None)
    closed = make(*args)
    findings = lint_jaxpr(closed, mesh=mesh, axis_env=axis_env, name=name)
    if check_donation:
        findings += lint_donation(fn, args, name=name)
    return filter_suppressed(findings, suppress)
