"""distlint: static SPMD/collective and host-communication linting.

Three analysis families share the :class:`~distlearn_tpu.lint.core.Finding`
vocabulary:

* :mod:`distlearn_tpu.lint.spmd` — abstractly traces a step function to a
  closed jaxpr and walks it (through ``cond``/``scan``/``while``/
  ``shard_map``/``pjit``) checking the collective rules DL001–DL005.
* :mod:`distlearn_tpu.lint.protocol` — models the host-side send/recv
  schedules of ``comm.tree``/``comm.ring`` and the AsyncEA handshake as
  per-rank message sequences and searches them for wait-for cycles, plus an
  AST audit of lock usage in the threaded paths (DL101–DL104).
* :mod:`distlearn_tpu.lint.cost` — compiles each step on the deployment
  mesh and attributes post-fusion collective bytes/ops per mesh axis and
  peak memory from the HLO (DL201–DL202);
  :mod:`distlearn_tpu.lint.budget` gates those numbers against committed
  per-family lockfiles (DL203–DL205).
* :mod:`distlearn_tpu.lint.model` — explicit-state model checking: BFS
  over ALL interleavings (with crash/drop/FIN faults) of small process
  models of the AsyncEA sync, sharded, replay, failover, and serve
  protocols, checking deadlock-freedom, epoch fencing, exactly-once, and
  resource conservation at every state (DL301–DL304).
* :mod:`distlearn_tpu.lint.races` — Eraser-style static lockset race
  detection over the threaded modules (DL111/DL112).
* :mod:`distlearn_tpu.lint.conformance` — pins the hand-written protocol
  schedules to the wire constants and call sites of the code they model
  (DL310).

``tools/distlint.py`` is the CLI front end; ``lint.registry`` names the
repo's step-function families so CI can lint all of them in one call.
"""

from distlearn_tpu.lint.core import Finding, RULES, format_findings
from distlearn_tpu.lint.spmd import lint_step, lint_jaxpr
from distlearn_tpu.lint.cost import CollectiveOp, CostReport, analyze_step
from distlearn_tpu.lint.budget import check_family, load_budget, save_budget
from distlearn_tpu.lint.conformance import lint_conformance
from distlearn_tpu.lint.model import ModelSpec, check_model, lint_models
from distlearn_tpu.lint.races import lint_races

__all__ = ["Finding", "RULES", "format_findings", "lint_step", "lint_jaxpr",
           "CollectiveOp", "CostReport", "analyze_step",
           "check_family", "load_budget", "save_budget",
           "ModelSpec", "check_model", "lint_models",
           "lint_races", "lint_conformance"]
