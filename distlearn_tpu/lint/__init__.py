"""distlint: static SPMD/collective and host-communication linting.

Two analysis families share the :class:`~distlearn_tpu.lint.core.Finding`
vocabulary:

* :mod:`distlearn_tpu.lint.spmd` — abstractly traces a step function to a
  closed jaxpr and walks it (through ``cond``/``scan``/``while``/
  ``shard_map``/``pjit``) checking the collective rules DL001–DL005.
* :mod:`distlearn_tpu.lint.protocol` — models the host-side send/recv
  schedules of ``comm.tree``/``comm.ring`` and the AsyncEA handshake as
  per-rank message sequences and searches them for wait-for cycles, plus an
  AST audit of lock usage in the threaded paths (DL101–DL104).

``tools/distlint.py`` is the CLI front end; ``lint.registry`` names the
repo's step-function families so CI can lint all of them in one call.
"""

from distlearn_tpu.lint.core import Finding, RULES, format_findings
from distlearn_tpu.lint.spmd import lint_step, lint_jaxpr

__all__ = ["Finding", "RULES", "format_findings", "lint_step", "lint_jaxpr"]
