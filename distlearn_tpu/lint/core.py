"""Shared finding vocabulary for distlint.

A :class:`Finding` is one rule violation at one program point.  Rules are
identified by stable IDs (``DL0xx`` for jaxpr-level SPMD rules, ``DL1xx``
for host-communication rules, ``DL2xx`` for compiled-HLO cost/budget
rules) so they can be suppressed individually —
per call (``suppress={"DL004"}``), per registry entry, or from the CLI
(``--disable DL004``).  docs/LINT.md is the rule catalog.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, Sequence

#: Rule catalog: id -> (title, default severity).
RULES = {
    "DL001": ("collective over an axis name not bound by any enclosing "
              "mesh/shard_map", "error"),
    "DL002": ("collectives diverge across branches of a data-dependent "
              "cond/while (cross-device deadlock hazard)", "error"),
    "DL003": ("PRNG key consumed under shard_map without per-device "
              "fold_in (every device draws identical randomness)", "error"),
    "DL004": ("cross-device reduction accumulates in a <32-bit float "
              "dtype", "error"),
    "DL005": ("donated input buffer has no shape/dtype-compatible output "
              "to alias (donation is wasted or unsafe)", "error"),
    "DL201": ("GSPMD inserted an implicit all-gather with a large operand "
              "(sharding was lost on a hot path)", "error"),
    "DL202": ("parameter-sized buffer materialized replicated despite a "
              "sharded in-spec", "error"),
    "DL203": ("collective traffic exceeds the family's committed budget "
              "lockfile", "error"),
    "DL204": ("compiled peak memory regressed vs. the family's budget "
              "lockfile", "error"),
    "DL205": ("post-fusion collective op count regressed vs. the family's "
              "budget lockfile", "error"),
    "DL206": ("serve-path donation wasted (declared but not aliased by the "
              "compiled program) or missing (large aliasable pool left "
              "undonated)", "error"),
    "DL207": ("distinct-compile count exceeds the family's committed budget "
              "(new bucket or dtype/weak-type retrace adds warmup tail)",
              "error"),
    "DL208": ("compiled program relayouts an entry parameter (host-visible "
              "copy/transpose at jitted-program entry) beyond the committed "
              "budget", "error"),
    "DL209": ("per-tick Python-level tensor math outside the jitted tick "
              "program (serve hot-loop host work)", "error"),
    "DL101": ("host send/recv schedule admits a wait-for cycle "
              "(static deadlock)", "error"),
    "DL102": ("lock acquisition order forms a cycle across threads",
              "error"),
    "DL103": ("blocking network/queue call while holding a lock", "error"),
    "DL104": ("peers disagree on message order (protocol desync)", "error"),
    "DL111": ("field written with no common lock against another thread's "
              "access (lockset race)", "error"),
    "DL112": ("lock-guarded field read without the guard elsewhere "
              "(torn-read hazard)", "warning"),
    "DL301": ("protocol model reaches a state with no enabled action "
              "before completion (deadlock)", "error"),
    "DL302": ("a stale-epoch center applies a delta in some interleaving "
              "(epoch fence violated)", "error"),
    "DL303": ("a (client, seq) delta is applied more than once across "
              "failover (exactly-once violated)", "error"),
    "DL304": ("serve slot/page accounting diverges between scheduler and "
              "engine (resource leak)", "error"),
    "DL310": ("hand-written protocol schedule drifted from the code it "
              "models (conformance)", "error"),
}


@dataclass(frozen=True)
class Finding:
    """One rule violation.

    ``where`` is a human-readable program point: for SPMD rules a path of
    nested jaxprs (``"step/shard_map/cond[branch 1]"``), for protocol rules
    a rank or source location.
    """

    rule: str
    message: str
    where: str = ""
    severity: str = "error"

    def __post_init__(self):
        if self.rule not in RULES:
            raise ValueError(f"unknown rule id {self.rule!r}")

    def __str__(self) -> str:
        loc = f" [{self.where}]" if self.where else ""
        return f"{self.rule}{loc}: {self.message}"


def filter_suppressed(findings: Iterable[Finding],
                      suppress: Iterable[str] = ()) -> list[Finding]:
    """Drop findings whose rule id is suppressed (unknown ids rejected)."""
    suppress = set(suppress)
    bad = suppress - RULES.keys()
    if bad:
        raise ValueError(f"cannot suppress unknown rule(s): {sorted(bad)}")
    return [f for f in findings if f.rule not in suppress]


def format_findings(findings: Sequence[Finding], *, header: str = "") -> str:
    """Render findings for terminal output, one per line."""
    lines = []
    if header:
        lines.append(header)
    if not findings:
        lines.append("  no findings")
    for f in findings:
        lines.append(f"  {f.severity.upper()} {f}")
    return "\n".join(lines)


@dataclass
class LintResult:
    """Findings for one lintable unit (a step function or a protocol).

    ``info`` carries analysis metadata that is not a finding — the model
    checker reports its explored state/transition counts here so the CLI
    can print ``OK (1,234 states)`` and the JSON output stays auditable.
    """

    name: str
    findings: list[Finding] = field(default_factory=list)
    info: dict = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not any(f.severity == "error" for f in self.findings)
