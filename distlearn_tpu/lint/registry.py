"""Named step-function families for distlint.

Each :class:`Entry` knows how to build one family's step functions on a
small mesh over the *available* devices and lint every one of them.  The
registry is what ``tools/distlint.py --family sgd`` and the tier-1 gate
test iterate over, so adding a builder here is how a new train step opts
into CI linting.

Builders return :class:`Unit` objects.  A unit that carries its jitted
callable (``fn``/``args``/``mesh``) additionally goes through the static
cost model (:mod:`distlearn_tpu.lint.cost`): the step is compiled on the
mesh, its post-fusion collective traffic and peak memory are extracted,
and the result is checked against the family's committed budget lockfile
(:mod:`distlearn_tpu.lint.budget`, rules DL201-DL205).  Host-protocol
units (no compilable step) carry ``fn=None`` and skip the cost pass.

Callers must provide >= :data:`MIN_DEVICES` devices (the test conftest and
the CLI both force 8 virtual CPU devices before jax initialises).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Sequence

from distlearn_tpu.lint.core import Finding, LintResult, filter_suppressed

__all__ = ["Entry", "Unit", "MIN_DEVICES", "families", "run_family",
           "run_family_costed", "run_all"]

MIN_DEVICES = 8


@dataclass
class Unit:
    """One lintable unit: findings plus (optionally) the compilable step."""

    name: str
    findings: list[Finding] = field(default_factory=list)
    fn: Callable | None = None
    args: tuple = ()
    mesh: Any = None
    in_specs: Any = None     # pytree of PartitionSpecs matching args (DL202)
    donation: bool = False   # run the DL206 donation audit on this unit
    info: dict = field(default_factory=dict)  # analysis metadata
    # (state counts, ...) surfaced on the LintResult / in --format json


@dataclass(frozen=True)
class Entry:
    name: str
    description: str
    run: Callable[[], list[Unit]]


def _mnist_setup(num_nodes=2):
    import jax
    from jax import random
    from distlearn_tpu.models import mnist_cnn
    from distlearn_tpu.parallel.mesh import MeshTree
    tree = MeshTree(num_nodes=num_nodes)
    model = mnist_cnn()
    return jax, random, model, tree


def _lint_units(units, mesh) -> list[Unit]:
    """Lint ``(name, fn, args)`` triples into step-carrying Units."""
    from distlearn_tpu.lint.spmd import lint_step
    return [Unit(n, lint_step(f, a, mesh=mesh, name=n),
                 fn=f, args=tuple(a), mesh=mesh)
            for n, f, a in units]


def _sgd_family():
    jax, random, model, tree = _mnist_setup()
    from distlearn_tpu.train import (build_eval_step, build_sgd_scan_step,
                                     build_sgd_step, build_sync_step,
                                     init_train_state)
    ts = init_train_state(model, tree, random.PRNGKey(0), 10)
    x = jax.ShapeDtypeStruct((8, 32, 32, 1), "float32")
    y = jax.ShapeDtypeStruct((8,), "int32")
    xs = jax.ShapeDtypeStruct((3, 8, 32, 32, 1), "float32")
    ys = jax.ShapeDtypeStruct((3, 8), "int32")
    units = [
        ("sgd_step", build_sgd_step(model, tree, lr=0.1), (ts, x, y)),
        ("sgd_scan_step", build_sgd_scan_step(model, tree, lr=0.1),
         (ts, xs, ys)),
        ("sync_step", build_sync_step(tree), (ts,)),
        ("eval_step", build_eval_step(model, tree),
         (ts.params, ts.model_state, ts.cm, x, y)),
    ]
    return _lint_units(units, tree.mesh)


def _ea_family():
    jax, random, model, tree = _mnist_setup()
    from distlearn_tpu.train import (build_ea_cycle, build_ea_steps,
                                     init_ea_state)
    ts = init_ea_state(model, tree, random.PRNGKey(0), 10)
    x = jax.ShapeDtypeStruct((8, 32, 32, 1), "float32")
    y = jax.ShapeDtypeStruct((8,), "int32")
    xs = jax.ShapeDtypeStruct((4, 8, 32, 32, 1), "float32")
    ys = jax.ShapeDtypeStruct((4, 8), "int32")
    local_step, ea_round = build_ea_steps(model, tree, lr=0.1, alpha=0.5)
    cycle = build_ea_cycle(model, tree, lr=0.1, alpha=0.5)
    units = [
        ("ea_local_step", local_step, (ts, x, y)),
        ("ea_round", ea_round, (ts,)),
        ("ea_cycle", cycle, (ts, xs, ys)),
    ]
    return _lint_units(units, tree.mesh)


def _lm_family():
    import numpy as np
    import jax
    from jax.sharding import Mesh
    from distlearn_tpu.models.transformer import transformer_lm
    from distlearn_tpu.train import build_lm_step
    dp, sp, tp = 2, 2, 2
    mesh = Mesh(np.array(jax.devices()[:dp * sp * tp]).reshape(dp, sp, tp),
                ("data", "seq", "model"))
    L = 16 * sp
    model = transformer_lm(vocab=32, dim=32, depth=2, heads=4, max_len=L)
    params, _ = model.init(jax.random.PRNGKey(0))
    step = build_lm_step(model, mesh, params, lr=0.1)
    tokens = jax.ShapeDtypeStruct((2 * dp, L), "int32")
    return _lint_units([("lm_step", step, (params, tokens))], mesh)


def _lm_mixed_family():
    import numpy as np
    import jax
    from jax.sharding import Mesh
    from distlearn_tpu.models.transformer import transformer_lm
    from distlearn_tpu.train import build_lm_mixed_step, init_lm_mixed_state
    dp, sp, tp = 2, 2, 2
    mesh = Mesh(np.array(jax.devices()[:dp * sp * tp]).reshape(dp, sp, tp),
                ("data", "seq", "model"))
    L = 16 * sp
    model = transformer_lm(vocab=32, dim=32, depth=2, heads=4, max_len=L)
    params, _ = model.init(jax.random.PRNGKey(0))
    st = init_lm_mixed_state(params)
    # Default grad_dtype=f32 upcasts bf16 grads BEFORE the psum — the
    # DL004-clean scheme docs/PERF.md motivates.
    step = build_lm_mixed_step(model, mesh, params, lr=0.1)
    tokens = jax.ShapeDtypeStruct((2 * dp, L), "int32")
    return _lint_units([("lm_mixed_step", step, (st, tokens))], mesh)


def _pp_family():
    import numpy as np
    import jax
    from jax.sharding import Mesh
    from distlearn_tpu.models.transformer import transformer_lm
    from distlearn_tpu.train import (build_lm_pp_1f1b_step, build_lm_pp_step,
                                     stack_blocks)
    depth = 2
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("data", "pipe"))
    model = transformer_lm(vocab=64, dim=32, depth=depth, heads=2, max_len=16)
    params, _ = model.init(jax.random.PRNGKey(0))
    shared, stacked = stack_blocks(params, depth)
    tokens = jax.ShapeDtypeStruct((8, 16), "int32")
    units = [
        ("lm_pp_step", build_lm_pp_step(mesh, shared, stacked, lr=0.1,
                                        num_microbatches=2),
         (shared, stacked, tokens)),
        ("lm_pp_1f1b_step", build_lm_pp_1f1b_step(mesh, shared, stacked,
                                                  lr=0.1,
                                                  num_microbatches=2),
         (shared, stacked, tokens)),
    ]
    return _lint_units(units, mesh)


def _optax_family():
    jax, random, model, tree = _mnist_setup()
    import optax
    from distlearn_tpu.train import (build_optax_step,
                                     build_zero_optax_step,
                                     init_optax_state, init_zero_state)
    tx = optax.sgd(0.1, momentum=0.9)
    ts = init_optax_state(model, tree, tx, random.PRNGKey(0), 10)
    step = build_optax_step(model, tree, tx)
    adam = optax.adam(1e-3)
    zts = init_zero_state(model, tree, adam, random.PRNGKey(0), 10)
    zstep = build_zero_optax_step(model, tree, adam)
    x = jax.ShapeDtypeStruct((8, 32, 32, 1), "float32")
    y = jax.ShapeDtypeStruct((8,), "int32")
    units = [
        ("optax_step", step, (ts, x, y)),
        ("zero_optax_step", zstep, (zts, x, y)),
    ]
    return _lint_units(units, tree.mesh)


def _ep_family():
    """MoE expert-parallel step: all-to-all dispatch/return over the
    ``expert`` axis plus a psum'd replicated-router update — the
    registry's only all-to-all traffic, so the cost lockfile pins it."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec as P
    from distlearn_tpu.parallel.ep import moe_ffn
    from distlearn_tpu.utils.compat import shard_map
    E, N, D = MIN_DEVICES, 16, 32
    mesh = Mesh(np.array(jax.devices()[:E]), ("expert",))

    def expert(p, h):
        return jnp.tanh(h @ p)

    def fwd(params, x_all):
        ep_w = jnp.squeeze(params["experts"], 0)   # this device's expert
        x = jnp.squeeze(x_all, 0)
        y = moe_ffn(expert, ep_w, params["router"], x, axis_name="expert")
        return y[None]

    def loss(params, x_all):
        return jnp.mean(fwd(params, x_all) ** 2)

    def train(params, x_all):
        l, g = jax.value_and_grad(loss)(params, x_all)
        # expert weights are per-device (owned), the router is replicated:
        # its grad must be reduced across the expert axis before the update
        g_router = lax.psum(g["router"], "expert")
        new = {"experts": params["experts"] - 0.1 * g["experts"],
               "router": params["router"] - 0.1 * g_router}
        return new, lax.pmean(l, "expert")

    specs = ({"experts": P("expert"), "router": P()}, P("expert"))
    mk = lambda f, out: jax.jit(shard_map(
        f, mesh=mesh, in_specs=specs, out_specs=out, check_vma=False))
    params = {"experts": jax.ShapeDtypeStruct((E, D, D), "float32"),
              "router": jax.ShapeDtypeStruct((D, E), "float32")}
    x_all = jax.ShapeDtypeStruct((E, N, D), "float32")
    units = [
        ("moe_fwd", mk(fwd, P("expert")), (params, x_all)),
        ("moe_train_step",
         mk(train, ({"experts": P("expert"), "router": P()}, P())),
         (params, x_all)),
    ]
    return _lint_units(units, mesh)


def _seq_family():
    """Sequence-parallel attention steps: ring (collective-permute per
    hop), the zigzag causal schedule, and the Ulysses all-to-all head
    swap — three distinct traffic shapes over one ``seq`` axis."""
    import numpy as np
    import jax
    from jax.sharding import Mesh, PartitionSpec as P
    from distlearn_tpu.parallel.sequence import (alltoall_attention,
                                                 ring_attention)
    from distlearn_tpu.utils.compat import shard_map
    n = MIN_DEVICES
    mesh = Mesh(np.array(jax.devices()[:n]), ("seq",))
    B, L, H, D = 2, 16 * n, n, 16     # H divisible by n (ulysses), L/n even
    qkv = tuple(jax.ShapeDtypeStruct((B, L, H, D), "float32")
                for _ in range(3))

    def mk(f):
        return jax.jit(shard_map(f, mesh=mesh, in_specs=(P(None, "seq"),) * 3,
                                 out_specs=P(None, "seq"), check_vma=False))
    units = [
        ("ring_attention",
         mk(lambda q, k, v: ring_attention(q, k, v, "seq", causal=True)),
         qkv),
        ("zigzag_ring_attention",
         mk(lambda q, k, v: ring_attention(q, k, v, "seq", causal=True,
                                           layout="zigzag")), qkv),
        ("ulysses_attention",
         mk(lambda q, k, v: alltoall_attention(q, k, v, "seq")), qkv),
    ]
    return _lint_units(units, mesh)


def _decode_family():
    """Serving decode programs (distlearn_tpu.serve): the tp-sharded
    continuous-batching tick, EVERY bucketed prefill AND prefill chunk
    (resumable chunked prefill), and the speculative verify.  The cost
    lockfile pins the two psums per block — a serving regression that
    adds collectives to the per-token path shows up here, not at p99 —
    plus the serve-path DL206-DL209 surface: the engine runs with
    donation on (its production configuration), every unit goes through
    the donation audit, the full bucket set pins the family's
    distinct-compile count (DL207), each unit's entry relayout count is
    budgeted (DL208), and the tick-loop AST pass (DL209) rides along as
    a findings-only unit."""
    import numpy as np
    import jax
    from jax.sharding import Mesh
    from distlearn_tpu.lint.cost import lint_tick_loop
    from distlearn_tpu.models.transformer import transformer_lm
    from distlearn_tpu.serve.engine import DecodeEngine
    tp = 2
    mesh = Mesh(np.array(jax.devices()[:tp]), ("model",))
    model = transformer_lm(vocab=64, dim=32, depth=2, heads=4, max_len=64)
    params, _ = model.init(jax.random.PRNGKey(0))
    eng = DecodeEngine(params, num_slots=4, page=8, mesh=mesh,
                       tp_axis="model", donate=True)
    units = [("decode_tick", eng.tick_program, eng.tick_args())]
    units += [(f"decode_prefill[{b}]", eng.prefill_program,
               eng.prefill_args(b)) for b in eng.buckets]
    units += [(f"decode_chunk[{b}]", eng.chunk_program,
               eng.chunk_args(b)) for b in eng.buckets]
    units += [("decode_verify", eng.verify_program, eng.verify_args())]
    out = _lint_units(units, mesh)
    for u in out:
        u.donation = True
    out.append(Unit("tick_loop", lint_tick_loop()))
    return out


def _wirek_family():
    """Fused wire-codec kernels (ops/wire_kernels): the Pallas int8
    quantize+error-feedback and dequantize+apply calls plus the amax
    reduction, on a wire-stripe-shaped block.  Single-device elementwise
    programs (mesh=None, no collectives) — the lockfile pins their flops
    and peak memory, so a regression back to a multi-pass or
    extra-copy lowering of the codec fails tier-1, mirroring how the
    collective budgets pin the SPMD families."""
    import jax
    from distlearn_tpu.ops import wire_kernels as wk
    from distlearn_tpu.ops.flatten import LANE
    rows = 4 * wk._BLOCK_ROWS           # 4 grid steps of the block spec
    x = jax.ShapeDtypeStruct((rows, LANE), "float32")
    q = jax.ShapeDtypeStruct((rows, LANE), "int8")
    st = jax.ShapeDtypeStruct((1, 1), "float32")
    units = [
        ("quant_ef", wk._quant_ef_call, (x, st)),
        ("dequant_add", wk._dequant_add_call, (x, q, st)),
        ("wire_amax", wk._amax_call, (x,)),
    ]
    return _lint_units(units, None)


def _sync_family():
    """The sync collectives themselves: the MeshBackend allreduce
    programs (plain + contrib-masked) and the two device-side phases of
    the HybridBackend hierarchical allreduce (in-mesh reduce-scatter,
    post-host-leg all-gather) — so the DL2xx cost budgets cover
    cross-node sync, not just the train steps that call it
    (comm/backend.py, lint/budgets/sync.json)."""
    import jax
    from distlearn_tpu.comm.backend import HybridBackend, MeshBackend
    mb = MeshBackend(num_nodes=8)
    # representative mixed payload: a matrix + a bias per node row
    val = {"b": jax.ShapeDtypeStruct((8, 64), "float32"),
           "w": jax.ShapeDtypeStruct((8, 128, 64), "float32")}
    cvec = jax.ShapeDtypeStruct((8,), "int32")
    hb = HybridBackend(0, 1, num_devices=8)
    plan = hb._plan(val)
    rs, ag = hb._programs(*plan)
    chunks = tuple(jax.ShapeDtypeStruct((padded,), dt.name)
                   for dt, _idxs, _total, padded, _chunks in plan[5])
    units = [
        ("sync_mesh_allreduce",
         mb.mesh_tree.all_reduce_program(False), (val,)),
        ("sync_mesh_allreduce_masked",
         mb.mesh_tree.all_reduce_program(True), (val, cvec)),
        ("sync_hybrid_reduce_scatter", rs, (val, cvec)),
        ("sync_hybrid_all_gather", ag, chunks),
    ]
    return _lint_units(units, mb.mesh)


def _protocol_family():
    from distlearn_tpu.lint.protocol import (async_ea_sync_schedule,
                                             check_schedules,
                                             lint_comm_protocols,
                                             ring_allreduce_schedule,
                                             tree_allreduce_schedule)
    units = [Unit("comm_protocols", lint_comm_protocols(num_nodes=7))]
    # Cover the schedule space beyond the default size as well.
    for n in (2, 3, 5, 8):
        units.append(Unit(f"tree[{n}]",
                          check_schedules(tree_allreduce_schedule(n),
                                          name=f"tree[{n}]")))
        units.append(Unit(f"ring[{n}]",
                          check_schedules(ring_allreduce_schedule(n),
                                          name=f"ring[{n}]")))
    units.append(Unit("async_ea[L=5]",
                      check_schedules(async_ea_sync_schedule(num_leaves=5),
                                      name="async_ea[L=5]")))
    return units


def _model_family():
    """Explicit-state model checking (DL301-DL304) + schedule↔code
    conformance (DL310): every process model in ``lint/model.py`` is
    exhaustively explored, with its state/transition counts carried as
    unit info, and every ``async_ea_*`` schedule is diffed against the
    wire constants/call sites in ``async_ea.py``."""
    from distlearn_tpu.lint.conformance import (lint_conformance,
                                                lint_serve_frames)
    from distlearn_tpu.lint.model import lint_models
    units = [Unit(spec.name, rep.findings, info=rep.info)
             for rep, spec in lint_models()]
    units.append(Unit("conformance", lint_conformance()))
    units.append(Unit("serve_frames", lint_serve_frames()))
    return units


def _races_family():
    """Static lockset race detection (DL111/DL112), split into the core
    scope (async_ea, ha, serve server/scheduler, obs core) and the
    fleet-era ``router`` scope (serve router, obs Collector, fault
    plan, autoscaler)."""
    from distlearn_tpu.lint.races import (core_targets, fleet_targets,
                                          lint_races)
    return [Unit("lockset", lint_races(core_targets())),
            Unit("router", lint_races(fleet_targets()))]


_FAMILIES = {
    "sgd": Entry("sgd", "fused AllReduceSGD steps (sgd/scan/sync/eval)",
                 _sgd_family),
    "ea": Entry("ea", "elastic-averaging steps (local/round/cycle)",
                _ea_family),
    "lm": Entry("lm", "3D-parallel LM train step", _lm_family),
    "lm_mixed": Entry("lm_mixed", "bf16-working/f32-master LM step",
                      _lm_mixed_family),
    "pp": Entry("pp", "pipeline-parallel LM steps (GPipe + 1F1B)",
                _pp_family),
    "optax": Entry("optax", "optax-backed data-parallel + ZeRO-sharded steps",
                   _optax_family),
    "ep": Entry("ep", "MoE expert-parallel steps (all-to-all dispatch)",
                _ep_family),
    "seq": Entry("seq", "sequence-parallel attention (ring/zigzag/ulysses)",
                 _seq_family),
    "decode": Entry("decode",
                    "serving decode programs (continuous-batch tick + "
                    "paged prefill)", _decode_family),
    "wirek": Entry("wirek",
                   "fused wire-codec kernels (int8 quantize+EF / "
                   "dequantize+apply / amax)", _wirek_family),
    "sync": Entry("sync",
                  "collective-backend sync programs (mesh allreduce + "
                  "hybrid reduce-scatter/all-gather)", _sync_family),
    "protocol": Entry("protocol",
                      "host comm schedules (tree/ring/AsyncEA) + lock audit",
                      _protocol_family),
    "model": Entry("model",
                   "explicit-state protocol models (sync/sharded/replay/"
                   "failover/serve) + schedule↔code conformance",
                   _model_family),
    "races": Entry("races",
                   "static lockset race detection over the threaded modules",
                   _races_family),
}


def families() -> dict[str, Entry]:
    return dict(_FAMILIES)


def _require_devices():
    import jax
    n = len(jax.devices())
    if n < MIN_DEVICES:
        raise RuntimeError(
            f"distlint needs >= {MIN_DEVICES} devices to build the step "
            f"families (got {n}); set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8 before "
            "importing jax (tools/distlint.py does this)")


# Build+lower+compile output per (family, cost) pair.  Everything a
# family analyses — module sources, step builders, budget inputs — is
# fixed once the process has imported the package, so rebuilding the
# mesh and re-lowering every program on a second run in the same
# process (the tier-1 gate test and the in-process CLI tests both walk
# the decode family) only burns warmup time.  Only the per-unit
# findings/info and the cost reports are retained; the jitted callables
# are dropped so the compiled executables can be collected.
_BUILD_CACHE: dict[tuple[str, bool], tuple[list, dict]] = {}


def _build_family_costed(name: str, cost: bool):
    """Build one family and run its cost pass; memoised per process."""
    key = (name, cost)
    hit = _BUILD_CACHE.get(key)
    if hit is not None:
        return hit
    units = _FAMILIES[name].run()
    reports = {}
    per_unit = []
    for u in units:
        findings = list(u.findings)
        if cost and u.fn is not None:
            from distlearn_tpu.lint import cost as cost_mod
            report, cost_findings = cost_mod.analyze_step(
                u.fn, u.args, mesh=u.mesh, name=f"{name}:{u.name}",
                in_specs=u.in_specs, donation=u.donation)
            reports[u.name] = report
            findings += cost_findings
        per_unit.append((u.name, findings, dict(u.info)))
    _BUILD_CACHE[key] = (per_unit, reports)
    return per_unit, reports


def run_family_costed(name: str, *, suppress: Sequence[str] = (),
                      cost: bool = True, budget_dir: str | None = None):
    """Lint one family AND run its steps through the static cost model.

    Returns ``(results, reports)``: one :class:`LintResult` per unit (plus
    a synthetic ``<family>:budget`` result when lockfile comparison finds
    anything), and a ``{unit_name: CostReport}`` dict for the CLI's cost
    tables / ``--update-budgets``.
    """
    _require_devices()
    per_unit, reports = _build_family_costed(name, cost)
    results = []
    for uname, findings, info in per_unit:
        results.append(LintResult(f"{name}:{uname}",
                                  filter_suppressed(list(findings), suppress),
                                  info=dict(info)))
    if cost:
        from distlearn_tpu.lint import budget as budget_mod
        bfindings = filter_suppressed(
            budget_mod.check_family(name, reports, budget_dir=budget_dir),
            suppress)
        if bfindings:
            results.append(LintResult(f"{name}:budget", bfindings))
        if reports:
            from distlearn_tpu.lint import cost as cost_mod
            cfindings, summary = cost_mod.audit_compiles(name, reports)
            results.append(LintResult(
                f"{name}:compiles",
                filter_suppressed(cfindings, suppress), info=summary))
    return results, reports


def run_family(name: str, *, suppress: Sequence[str] = (),
               cost: bool = True) -> list[LintResult]:
    """Lint one family; returns one :class:`LintResult` per step function."""
    return run_family_costed(name, suppress=suppress, cost=cost)[0]


def run_all(*, suppress: Sequence[str] = (),
            cost: bool = True) -> list[LintResult]:
    out = []
    for name in _FAMILIES:
        out += run_family(name, suppress=suppress, cost=cost)
    return out
