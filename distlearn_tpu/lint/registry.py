"""Named step-function families for distlint.

Each :class:`Entry` knows how to build one family's step functions on a
small mesh over the *available* devices and lint every one of them.  The
registry is what ``tools/distlint.py --family sgd`` and the tier-1 gate
test iterate over, so adding a builder here is how a new train step opts
into CI linting.

Callers must provide >= :data:`MIN_DEVICES` devices (the test conftest and
the CLI both force 8 virtual CPU devices before jax initialises).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Sequence

from distlearn_tpu.lint.core import Finding, LintResult, filter_suppressed

__all__ = ["Entry", "MIN_DEVICES", "families", "run_family", "run_all"]

MIN_DEVICES = 8


@dataclass(frozen=True)
class Entry:
    name: str
    description: str
    run: Callable[[], list[tuple[str, list[Finding]]]]


def _mnist_setup(num_nodes=2):
    import jax
    from jax import random
    from distlearn_tpu.models import mnist_cnn
    from distlearn_tpu.parallel.mesh import MeshTree
    tree = MeshTree(num_nodes=num_nodes)
    model = mnist_cnn()
    return jax, random, model, tree


def _sgd_family():
    from distlearn_tpu.lint.spmd import lint_step
    jax, random, model, tree = _mnist_setup()
    from distlearn_tpu.train import (build_eval_step, build_sgd_scan_step,
                                     build_sgd_step, build_sync_step,
                                     init_train_state)
    ts = init_train_state(model, tree, random.PRNGKey(0), 10)
    x = jax.ShapeDtypeStruct((8, 32, 32, 1), "float32")
    y = jax.ShapeDtypeStruct((8,), "int32")
    xs = jax.ShapeDtypeStruct((3, 8, 32, 32, 1), "float32")
    ys = jax.ShapeDtypeStruct((3, 8), "int32")
    units = [
        ("sgd_step", build_sgd_step(model, tree, lr=0.1), (ts, x, y)),
        ("sgd_scan_step", build_sgd_scan_step(model, tree, lr=0.1),
         (ts, xs, ys)),
        ("sync_step", build_sync_step(tree), (ts,)),
        ("eval_step", build_eval_step(model, tree),
         (ts.params, ts.model_state, ts.cm, x, y)),
    ]
    return [(n, lint_step(f, a, mesh=tree.mesh, name=n)) for n, f, a in units]


def _ea_family():
    from distlearn_tpu.lint.spmd import lint_step
    jax, random, model, tree = _mnist_setup()
    from distlearn_tpu.train import (build_ea_cycle, build_ea_steps,
                                     init_ea_state)
    ts = init_ea_state(model, tree, random.PRNGKey(0), 10)
    x = jax.ShapeDtypeStruct((8, 32, 32, 1), "float32")
    y = jax.ShapeDtypeStruct((8,), "int32")
    xs = jax.ShapeDtypeStruct((4, 8, 32, 32, 1), "float32")
    ys = jax.ShapeDtypeStruct((4, 8), "int32")
    local_step, ea_round = build_ea_steps(model, tree, lr=0.1, alpha=0.5)
    cycle = build_ea_cycle(model, tree, lr=0.1, alpha=0.5)
    units = [
        ("ea_local_step", local_step, (ts, x, y)),
        ("ea_round", ea_round, (ts,)),
        ("ea_cycle", cycle, (ts, xs, ys)),
    ]
    return [(n, lint_step(f, a, mesh=tree.mesh, name=n)) for n, f, a in units]


def _lm_family():
    import numpy as np
    import jax
    from jax.sharding import Mesh
    from distlearn_tpu.lint.spmd import lint_step
    from distlearn_tpu.models.transformer import transformer_lm
    from distlearn_tpu.train import build_lm_step
    dp, sp, tp = 2, 2, 2
    mesh = Mesh(np.array(jax.devices()[:dp * sp * tp]).reshape(dp, sp, tp),
                ("data", "seq", "model"))
    L = 16 * sp
    model = transformer_lm(vocab=32, dim=32, depth=2, heads=4, max_len=L)
    params, _ = model.init(jax.random.PRNGKey(0))
    step = build_lm_step(model, mesh, params, lr=0.1)
    tokens = jax.ShapeDtypeStruct((2 * dp, L), "int32")
    return [("lm_step",
             lint_step(step, (params, tokens), mesh=mesh, name="lm_step"))]


def _lm_mixed_family():
    import numpy as np
    import jax
    from jax.sharding import Mesh
    from distlearn_tpu.lint.spmd import lint_step
    from distlearn_tpu.models.transformer import transformer_lm
    from distlearn_tpu.train import build_lm_mixed_step, init_lm_mixed_state
    dp, sp, tp = 2, 2, 2
    mesh = Mesh(np.array(jax.devices()[:dp * sp * tp]).reshape(dp, sp, tp),
                ("data", "seq", "model"))
    L = 16 * sp
    model = transformer_lm(vocab=32, dim=32, depth=2, heads=4, max_len=L)
    params, _ = model.init(jax.random.PRNGKey(0))
    st = init_lm_mixed_state(params)
    # Default grad_dtype=f32 upcasts bf16 grads BEFORE the psum — the
    # DL004-clean scheme docs/PERF.md motivates.
    step = build_lm_mixed_step(model, mesh, params, lr=0.1)
    tokens = jax.ShapeDtypeStruct((2 * dp, L), "int32")
    return [("lm_mixed_step",
             lint_step(step, (st, tokens), mesh=mesh, name="lm_mixed_step"))]


def _pp_family():
    import numpy as np
    import jax
    from jax.sharding import Mesh
    from distlearn_tpu.lint.spmd import lint_step
    from distlearn_tpu.models.transformer import transformer_lm
    from distlearn_tpu.train import (build_lm_pp_1f1b_step, build_lm_pp_step,
                                     stack_blocks)
    depth = 2
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("data", "pipe"))
    model = transformer_lm(vocab=64, dim=32, depth=depth, heads=2, max_len=16)
    params, _ = model.init(jax.random.PRNGKey(0))
    shared, stacked = stack_blocks(params, depth)
    tokens = jax.ShapeDtypeStruct((8, 16), "int32")
    units = [
        ("lm_pp_step", build_lm_pp_step(mesh, shared, stacked, lr=0.1,
                                        num_microbatches=2)),
        ("lm_pp_1f1b_step", build_lm_pp_1f1b_step(mesh, shared, stacked,
                                                  lr=0.1,
                                                  num_microbatches=2)),
    ]
    return [(n, lint_step(f, (shared, stacked, tokens), mesh=mesh, name=n))
            for n, f in units]


def _optax_family():
    from distlearn_tpu.lint.spmd import lint_step
    jax, random, model, tree = _mnist_setup()
    import optax
    from distlearn_tpu.train import (build_optax_step,
                                     build_zero_optax_step,
                                     init_optax_state, init_zero_state)
    tx = optax.sgd(0.1, momentum=0.9)
    ts = init_optax_state(model, tree, tx, random.PRNGKey(0), 10)
    step = build_optax_step(model, tree, tx)
    adam = optax.adam(1e-3)
    zts = init_zero_state(model, tree, adam, random.PRNGKey(0), 10)
    zstep = build_zero_optax_step(model, tree, adam)
    x = jax.ShapeDtypeStruct((8, 32, 32, 1), "float32")
    y = jax.ShapeDtypeStruct((8,), "int32")
    units = [
        ("optax_step", step, (ts, x, y)),
        ("zero_optax_step", zstep, (zts, x, y)),
    ]
    return [(n, lint_step(f, a, mesh=tree.mesh, name=n)) for n, f, a in units]


def _protocol_family():
    from distlearn_tpu.lint.protocol import (async_ea_sync_schedule,
                                             check_schedules,
                                             lint_comm_protocols,
                                             ring_allreduce_schedule,
                                             tree_allreduce_schedule)
    units = [("comm_protocols", lint_comm_protocols(num_nodes=7))]
    # Cover the schedule space beyond the default size as well.
    for n in (2, 3, 5, 8):
        units.append((f"tree[{n}]",
                      check_schedules(tree_allreduce_schedule(n),
                                      name=f"tree[{n}]")))
        units.append((f"ring[{n}]",
                      check_schedules(ring_allreduce_schedule(n),
                                      name=f"ring[{n}]")))
    units.append(("async_ea[L=5]",
                  check_schedules(async_ea_sync_schedule(num_leaves=5),
                                  name="async_ea[L=5]")))
    return units


_FAMILIES = {
    "sgd": Entry("sgd", "fused AllReduceSGD steps (sgd/scan/sync/eval)",
                 _sgd_family),
    "ea": Entry("ea", "elastic-averaging steps (local/round/cycle)",
                _ea_family),
    "lm": Entry("lm", "3D-parallel LM train step", _lm_family),
    "lm_mixed": Entry("lm_mixed", "bf16-working/f32-master LM step",
                      _lm_mixed_family),
    "pp": Entry("pp", "pipeline-parallel LM steps (GPipe + 1F1B)",
                _pp_family),
    "optax": Entry("optax", "optax-backed data-parallel + ZeRO-sharded steps",
                   _optax_family),
    "protocol": Entry("protocol",
                      "host comm schedules (tree/ring/AsyncEA) + lock audit",
                      _protocol_family),
}


def families() -> dict[str, Entry]:
    return dict(_FAMILIES)


def run_family(name: str, *, suppress: Sequence[str] = ()) -> list[LintResult]:
    """Lint one family; returns one :class:`LintResult` per step function."""
    entry = _FAMILIES[name]
    import jax
    n = len(jax.devices())
    if n < MIN_DEVICES:
        raise RuntimeError(
            f"distlint needs >= {MIN_DEVICES} devices to build the step "
            f"families (got {n}); set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8 before "
            "importing jax (tools/distlint.py does this)")
    return [LintResult(f"{name}:{unit}", filter_suppressed(fs, suppress))
            for unit, fs in entry.run()]


def run_all(*, suppress: Sequence[str] = ()) -> list[LintResult]:
    out = []
    for name in _FAMILIES:
        out += run_family(name, suppress=suppress)
    return out
