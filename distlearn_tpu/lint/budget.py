"""Per-family collective-traffic & memory budget lockfiles (DL203-DL205).

A budget lockfile is a committed JSON snapshot of what one step family is
*allowed* to cost, produced from a real compile on the 8-device CPU mesh
(``python tools/distlint.py --update-budgets``).  The tier-1 gate then
re-derives the numbers on every run and compares:

* **DL203** — collective bytes for any kind exceed the committed figure
  by more than the lockfile's ``tolerance.bytes`` (a *new* collective
  kind with nonzero traffic is always over budget);
* **DL204** — compiled peak memory exceeds the committed figure by more
  than ``tolerance.memory``;
* **DL205** — the post-fusion op count for any kind exceeds the
  committed count (integer, no tolerance: fusion either held or broke);
* **DL207** — the family's distinct-compile count (one per distinct
  dtype/weak-type/shape signature across its units) exceeds the
  committed ``compiles.count`` — a new prefill bucket or an accidental
  retrace adds warmup tail and must land with a conscious re-baseline;
* **DL208** — a unit's entry relayout op count (``copy``/``transpose``
  of an entry parameter in the compiled ENTRY computation) exceeds the
  committed ``relayout_ops`` (integer, no tolerance: the entry layout
  contract either held or broke).

A family with cost-bearing units and *no* committed lockfile — or a unit
missing from the lockfile — is a DL203 error: every perf-relevant change
lands either inside budget or with a conscious re-baseline in the same
diff.  Shrinking is never an error; run ``--update-budgets`` to ratchet
the committed floor down after an optimization.

Lockfiles live in ``distlearn_tpu/lint/budgets/<family>.json``; the
format is one ``units`` object keyed by unit name whose entries mirror
:meth:`distlearn_tpu.lint.cost.CostReport.to_json`.
"""

from __future__ import annotations

import json
import os
from typing import Mapping

from distlearn_tpu.lint.core import Finding
from distlearn_tpu.lint.cost import CostReport

__all__ = ["BUDGET_DIR", "DEFAULT_TOLERANCE", "budget_path", "load_budget",
           "save_budget", "check_family"]

#: Committed lockfile directory (inside the package so sdists carry it).
BUDGET_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "budgets")

#: Relative slack before DL203/DL204 fire.  Bytes are deterministic for a
#: fixed jax pin; the slack absorbs minor-version fusion drift so budgets
#: only need re-baselining when traffic moves for real.
DEFAULT_TOLERANCE = {"bytes": 0.25, "memory": 0.35}


def budget_path(family: str, budget_dir: str | None = None) -> str:
    return os.path.join(budget_dir or BUDGET_DIR, f"{family}.json")


def load_budget(family: str, budget_dir: str | None = None) -> dict | None:
    """The committed lockfile for one family, or None when absent."""
    path = budget_path(family, budget_dir)
    if not os.path.exists(path):
        return None
    with open(path) as fh:
        return json.load(fh)


def save_budget(family: str, reports: Mapping[str, CostReport],
                budget_dir: str | None = None) -> str:
    """Write (or refresh) one family's lockfile from fresh reports."""
    path = budget_path(family, budget_dir)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    doc = {
        "family": family,
        "tolerance": dict(DEFAULT_TOLERANCE),
        "units": {name: rep.to_json() for name, rep in sorted(
            reports.items())},
    }
    signatures = {rep.signature for rep in reports.values()
                  if rep.signature is not None}
    if signatures:
        # DL207 gate: the family's distinct-compile count.  compile_s is
        # wall-clock and nondeterministic, so it never enters the lockfile.
        doc["compiles"] = {"count": len(signatures)}
    with open(path, "w") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return path


def _over(actual: float, allowed: float, tol: float) -> bool:
    return actual > allowed * (1.0 + tol)


def check_family(family: str, reports: Mapping[str, CostReport],
                 budget: dict | None = None,
                 budget_dir: str | None = None) -> list[Finding]:
    """Compare fresh cost reports against the committed lockfile."""
    if budget is None:
        budget = load_budget(family, budget_dir)
    findings: list[Finding] = []
    if not reports:
        return findings
    if budget is None:
        findings.append(Finding(
            "DL203",
            f"family {family!r} has {len(reports)} cost-bearing unit(s) "
            "but no committed budget lockfile; run "
            "`python tools/distlint.py --update-budgets` and commit "
            f"lint/budgets/{family}.json",
            where=family))
        return findings
    tol = {**DEFAULT_TOLERANCE, **budget.get("tolerance", {})}
    units = budget.get("units", {})
    for name, rep in sorted(reports.items()):
        entry = units.get(name)
        if entry is None:
            findings.append(Finding(
                "DL203",
                f"unit {name!r} is not in the committed budget lockfile "
                f"for family {family!r}; re-baseline with --update-budgets",
                where=name))
            continue
        committed_bytes = entry.get("collective_bytes", {})
        for kind, actual in sorted(rep.bytes_by_kind.items()):
            allowed = committed_bytes.get(kind, 0)
            if actual and not allowed:
                findings.append(Finding(
                    "DL203",
                    f"{kind} traffic appeared ({actual} bytes/step) but "
                    "the committed budget has none; either remove the new "
                    "collective or re-baseline with --update-budgets",
                    where=name))
            elif _over(actual, allowed, tol["bytes"]):
                findings.append(Finding(
                    "DL203",
                    f"{kind} traffic {actual} bytes/step exceeds the "
                    f"committed {allowed} bytes/step by more than "
                    f"{tol['bytes']:.0%}",
                    where=name))
        committed_ops = entry.get("collective_ops", {})
        for kind, actual in sorted(rep.ops_by_kind.items()):
            allowed = committed_ops.get(kind, 0)
            if actual > allowed:
                findings.append(Finding(
                    "DL205",
                    f"{actual} post-fusion {kind} op(s) vs {allowed} "
                    "committed — fusion regressed (e.g. a packed update "
                    "degraded to per-tensor collectives); fix the fusion "
                    "or re-baseline with --update-budgets",
                    where=name))
        committed_peak = entry.get("peak_bytes")
        actual_peak = rep.peak_bytes
        if committed_peak and actual_peak and \
                _over(actual_peak, committed_peak, tol["memory"]):
            findings.append(Finding(
                "DL204",
                f"compiled peak memory {actual_peak} bytes exceeds the "
                f"committed {committed_peak} bytes by more than "
                f"{tol['memory']:.0%}",
                where=name))
        committed_relayouts = entry.get("relayout_ops")
        if committed_relayouts is not None and rep.relayout_ops is not None \
                and rep.relayout_ops > committed_relayouts:
            findings.append(Finding(
                "DL208",
                f"{rep.relayout_ops} entry relayout op(s) (copy/transpose "
                f"of an entry parameter) vs {committed_relayouts} committed "
                "— the compiled program re-materializes an argument in a "
                "different layout on every dispatch; fix the caller-side "
                "layout or re-baseline with --update-budgets",
                where=name))
    committed_compiles = budget.get("compiles", {}).get("count")
    if committed_compiles is not None:
        fresh = len({rep.signature for rep in reports.values()
                     if rep.signature is not None})
        if fresh > committed_compiles:
            findings.append(Finding(
                "DL207",
                f"family {family!r} now lowers {fresh} distinct programs "
                f"vs {committed_compiles} committed — a new bucket or a "
                "dtype/weak-type retrace added warmup tail; remove the "
                "extra lowering or re-baseline with --update-budgets",
                where=family))
    return findings
