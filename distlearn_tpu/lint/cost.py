"""Static collective-traffic & memory cost model (rules DL201, DL202).

Where :mod:`distlearn_tpu.lint.spmd` analyzes the program the *author*
wrote (the jaxpr), this module analyzes the program the *compiler* built:
each step function is lowered and compiled on the deployment mesh and the
post-fusion HLO module is walked to attribute

* **bytes per collective kind per mesh axis** — every ``all-reduce``,
  ``all-gather``, ``reduce-scatter``, ``collective-permute`` and
  ``all-to-all`` op is parsed out of the module text with its payload
  shape and replica groups, and the groups are mapped back to the mesh
  axes they span (explicit ``{{0,4},{1,5}}`` lists, iota-form
  ``[2,4]<=[8]`` lists, and permute ``source_target_pairs`` all
  supported);
* **post-fusion collective op counts** — what fusion actually left in the
  module, which is what the wire sees (``ops/fused_update.py`` degrading
  to per-tensor reduces shows up here long before a profile would);
* **compiled peak/temp memory** via
  :func:`distlearn_tpu.utils.compat.compiled_memory_stats`.

The numbers are *per device per step*: the module XLA emits under SPMD
partitioning is the one program every device runs, with local (sharded)
shapes, so a payload byte count is what one device moves through one
step.  Two rules fire directly from the model:

* **DL201** — the compiled module contains more *large* all-gathers
  (payload >= :data:`GATHER_BYTES_THRESHOLD`) than the jaxpr requested
  explicitly: GSPMD sharding propagation lost a sharding on a hot path
  and is rematerializing a full buffer every step.
* **DL202** — the caller declared a sharded in-spec for a large argument
  but the compiled executable materializes that parameter fully
  replicated (>= :data:`REPLICATED_BYTES_THRESHOLD`).

Budget regression rules DL203-DL205 compare a :class:`CostReport` against
the committed per-family lockfiles — see :mod:`distlearn_tpu.lint.budget`.

Serve-path performance rules (DL206-DL209)
------------------------------------------
The serving hot path has failure modes training steps don't, so four
more rules ride the same compile:

* **DL206** — donation audit.  With ``donation=True`` the analyzer
  diffs the *declared* donations (``lowered.args_info``) against the
  ``input_output_alias`` table XLA actually committed to: a donated
  buffer the compiled program does NOT alias silently doubles its
  footprint (the K/V pools are the motivating case), and a large
  (>= :data:`DONATION_BYTES_THRESHOLD`) undonated input whose
  shape/dtype matches an unconsumed output is a donation the author
  forgot.  This is the compiled-program counterpart of the jaxpr-level
  DL005.
* **DL207** — recompile audit.  Every report carries the input
  ``signature`` (dtype + weak-type flag + shape per leaf) and the
  measured ``compile_s``; :func:`audit_compiles` counts distinct
  lowerings per family (the prefill bucket set), estimates the warmup
  tail, and flags two units in one bracketed group (``prefill[8]`` /
  ``prefill[16]``) that lower the *same shapes* under different
  dtype/weak-type signatures — the accidental-retrace class.  The
  distinct-compile *count* is budget-gated in the family lockfile
  (:mod:`distlearn_tpu.lint.budget`), so a new bucket fails tier-1
  until consciously re-baselined.
* **DL208** — entry relayout.  :func:`count_entry_relayouts` counts
  ``copy``/``transpose`` instructions in the ENTRY computation whose
  operand is an entry parameter — the compiler disagreeing with the
  caller about layout and paying a materialized relayout on every
  dispatch.  The count is budget-gated per unit (exact, like DL205).
* **DL209** — non-jitted tick-loop work.  :func:`lint_tick_loop` is a
  pure AST pass over ``serve/engine.py`` and ``serve/scheduler.py``
  flagging numpy/jnp *tensor math* (not bookkeeping) in the per-tick
  host methods (:data:`TICK_HOT_METHODS`) — math there runs once per
  tick on the host and belongs inside the jitted tick program.
"""

from __future__ import annotations

import ast
import math
import re
import time
from collections import Counter
from dataclasses import dataclass, field
from typing import Sequence

import numpy as np

from distlearn_tpu.lint.core import Finding
from distlearn_tpu.utils import compat

__all__ = ["CollectiveOp", "CostReport", "analyze_step", "audit_compiles",
           "count_entry_relayouts", "lint_tick_loop", "parse_collectives",
           "GATHER_BYTES_THRESHOLD", "REPLICATED_BYTES_THRESHOLD",
           "DONATION_BYTES_THRESHOLD", "COLLECTIVE_KINDS",
           "TICK_HOT_METHODS"]

#: HLO opcodes the model attributes traffic to.
COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                    "collective-permute", "all-to-all")

#: DL201 fires only for implicit all-gathers at least this large: tiny
#: gathers (scalars, loop counters, eval metrics) are GSPMD doing its job.
GATHER_BYTES_THRESHOLD = 1 << 20

#: DL202 fires only for replicated parameters at least this large.
REPLICATED_BYTES_THRESHOLD = 1 << 20

#: DL206's *missing*-donation arm only flags undonated inputs at least
#: this large (64 KiB): the K/V pools it exists for are hundreds of KiB
#: even on the lint mesh, while scalars/lens/token vectors that happen
#: to shape-match an output are not worth a donation.  The *wasted* arm
#: (declared donated, not aliased) fires at any size — a wasted donation
#: is a correctness smell, not just a memory one.
DONATION_BYTES_THRESHOLD = 1 << 16

#: Per-tick host methods on the serve hot path that DL209 audits: the
#: decode/admit/step loop bodies in ``serve/engine.py`` and
#: ``serve/scheduler.py``, the per-round prefill/verify/draft paths
#: (chunked prefill + speculative decode), and the per-admission radix
#: walks in ``serve/prefix_cache.py``.  Nested ``def``s inside them are
#: the staged (jitted) program bodies and are exempt.
TICK_HOT_METHODS = frozenset({"tick", "admit", "step", "_tick", "_admit",
                              "_expire", "_dispatch", "verify", "begin",
                              "prefill_step", "_advance_prefills",
                              "_pump_prefill", "propose", "match",
                              "insert", "evict_nodes", "evict_for_free"})

#: numpy/jnp calls DL209 treats as tensor *math* when issued per tick on
#: the host.  Bookkeeping (``asarray``, ``flatnonzero``, ``zeros``,
#: ``arange``, boolean masks) is deliberately absent: marshalling
#: arguments for the jitted program is the host loop's job.
_TENSOR_MATH_FNS = frozenset({
    "exp", "exp2", "expm1", "log", "log2", "log10", "log1p", "sqrt",
    "power", "tanh", "sin", "cos", "sinh", "cosh",
    "matmul", "dot", "vdot", "inner", "outer", "tensordot", "einsum",
    "argmax", "argmin", "softmax", "logsumexp",
    "cumsum", "cumprod", "mean", "std", "var", "median",
    "sort", "argsort", "take_along_axis", "top_k",
})

# f8 variants intentionally coarse; HLO spells dtypes like f32, bf16, s64.
_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}
_DTYPE_BYTES.update({f"f8{suffix}": 1 for suffix in
                     ("e4m3fn", "e5m2", "e4m3b11fnuz", "e4m3fnuz", "e5m2fnuz")})

_SHAPE_RE = re.compile(r"([a-z]+[0-9]+(?:[a-z0-9]*)?|pred)\[([0-9,]*)\]")
# `%name = <shape> <kind>(`: shape is a bare token or a (tuple).  Operand
# references (`%all-gather.3`) never match — they are not preceded by
# `= <shape>` and not followed by `(`.
_OP_RE = re.compile(
    r"=\s*(?P<shape>\([^)]*\)|\S+)\s+"
    r"(?P<kind>" + "|".join(COLLECTIVE_KINDS) + r")(?:-start)?\(")
_GROUPS_RE = re.compile(r"replica_groups=(\{\{[0-9,{} ]*\}\}|\{\}|"
                        r"\[[0-9,]+\]<=\[[0-9,]+\](?:T\([0-9,]+\))?)")
_PAIRS_RE = re.compile(r"source_target_pairs=\{([0-9,{} ]*)\}")


def _shape_bytes(shape_token: str) -> int:
    """Byte size of one HLO shape token (``f32[4,8]{1,0}`` or a tuple)."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(shape_token):
        size = _DTYPE_BYTES.get(dtype)
        if size is None:
            continue  # token dtype (opaque, s32[]-like already matched)
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * size
    return total


def _parse_groups(attr: str) -> list[tuple[int, ...]]:
    """Parse a ``replica_groups=`` payload into device-id groups."""
    if attr.startswith("{"):
        return [tuple(int(x) for x in grp.split(",") if x.strip())
                for grp in re.findall(r"\{([0-9, ]+)\}", attr)]
    # iota form: [G,S]<=[dims](T(perm))? — arange over the flattened device
    # space, reshaped to `dims`, transposed by `perm`, regrouped as G rows.
    m = re.match(r"\[([0-9,]+)\]<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?", attr)
    if not m:
        return []
    out_dims = [int(x) for x in m.group(1).split(",")]
    iota_dims = [int(x) for x in m.group(2).split(",")]
    ids = np.arange(math.prod(iota_dims)).reshape(iota_dims)
    if m.group(3):
        ids = ids.transpose([int(x) for x in m.group(3).split(",")])
    return [tuple(int(x) for x in row)
            for row in ids.reshape(out_dims[0], -1)]


def _mesh_device_ids(mesh) -> tuple[np.ndarray, tuple[str, ...]] | None:
    devices = getattr(mesh, "devices", None)
    names = getattr(mesh, "axis_names", None)
    if devices is None or names is None:
        return None
    ids = np.vectorize(lambda d: getattr(d, "id", -1))(np.asarray(devices))
    return ids, tuple(str(a) for a in names)


def _axes_for_groups(mesh, groups: Sequence[tuple[int, ...]]
                     ) -> tuple[str, ...]:
    """Mesh axes a replica-group list spans (``("?",)`` when unknown).

    A collective grouped along axis subset ``S`` partitions the devices
    into one group per coordinate of the *other* axes; we test every
    non-empty subset (meshes here have <= 4 axes) against the parsed
    groups.  Size-1 groups are the degenerate no-communication case and
    return ``()``.
    """
    if not groups:
        return ("?",)
    if all(len(g) <= 1 for g in groups):
        return ()
    info = _mesh_device_ids(mesh)
    if info is None:
        return ("?",)
    ids, names = info
    want = {frozenset(g) for g in groups}
    for mask in range(1, 1 << len(names)):
        subset = [i for i in range(len(names)) if mask & (1 << i)]
        rest = [i for i in range(len(names)) if i not in subset]
        grouped = ids.transpose(rest + subset).reshape(
            -1, math.prod(ids.shape[i] for i in subset))
        if {frozenset(int(x) for x in row) for row in grouped} == want:
            return tuple(names[i] for i in subset)
    return ("?",)


def _axes_for_pairs(mesh, pairs: Sequence[tuple[int, int]]
                    ) -> tuple[str, ...]:
    """Mesh axes a permute's source->target pairs move along."""
    info = _mesh_device_ids(mesh)
    if info is None or not pairs:
        return ("?",)
    ids, names = info
    where = {int(v): np.unravel_index(i, ids.shape)
             for i, v in enumerate(ids.ravel())}
    axes: set[str] = set()
    for src, dst in pairs:
        if src not in where or dst not in where:
            return ("?",)
        for dim, (a, b) in enumerate(zip(where[src], where[dst])):
            if a != b:
                axes.add(names[dim])
    return tuple(a for a in names if a in axes)


@dataclass(frozen=True)
class CollectiveOp:
    """One post-fusion collective in the compiled module."""

    kind: str            # one of COLLECTIVE_KINDS
    bytes: int           # payload bytes (local/per-device shape)
    axes: tuple          # mesh axes the op communicates over
    shape: str           # the HLO result shape token, for messages

    @property
    def axis_key(self) -> str:
        return f"{self.kind}@{','.join(self.axes) or '-'}"


@dataclass
class CostReport:
    """Static cost of one compiled step function.

    ``bytes_by_kind`` / ``ops_by_kind`` aggregate over mesh axes;
    ``bytes_by_axis`` keeps the per-axis split (keys like
    ``"all-reduce@data"``).  ``memory`` is the
    :func:`~distlearn_tpu.utils.compat.compiled_memory_stats` dict (or
    None where the backend reports nothing); ``flops`` comes from the
    compiler's own cost analysis when available.
    """

    name: str
    collectives: list[CollectiveOp] = field(default_factory=list)
    memory: dict | None = None
    flops: float | None = None
    #: hashable input signature: one (dtype, weak_type, shape) triple per
    #: flat argument leaf — two units with equal signatures share one
    #: compile-cache entry, distinct signatures are distinct lowerings
    #: (the DL207 accounting unit)
    signature: tuple | None = None
    #: measured lowering+compile wall time; feeds the warmup-tail
    #: estimate but stays OUT of the lockfile (nondeterministic)
    compile_s: float | None = None
    #: entry-parameter copy/transpose count in the compiled module
    #: (DL208); None when no HLO was inspected
    relayout_ops: int | None = None

    @property
    def bytes_by_kind(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for op in self.collectives:
            out[op.kind] = out.get(op.kind, 0) + op.bytes
        return out

    @property
    def ops_by_kind(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for op in self.collectives:
            out[op.kind] = out.get(op.kind, 0) + 1
        return out

    @property
    def bytes_by_axis(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for op in self.collectives:
            out[op.axis_key] = out.get(op.axis_key, 0) + op.bytes
        return out

    @property
    def ops_by_axis(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for op in self.collectives:
            out[op.axis_key] = out.get(op.axis_key, 0) + 1
        return out

    @property
    def peak_bytes(self) -> int | None:
        return self.memory.get("peak") if self.memory else None

    def to_json(self) -> dict:
        return {
            "collective_bytes": self.bytes_by_kind,
            "collective_ops": self.ops_by_kind,
            "bytes_by_axis": self.bytes_by_axis,
            "peak_bytes": self.peak_bytes,
            "temp_bytes": self.memory.get("temp") if self.memory else None,
            "flops": self.flops,
            "relayout_ops": self.relayout_ops,
        }


def parse_collectives(hlo_text: str, mesh=None) -> list[CollectiveOp]:
    """Extract every collective op from compiled HLO module text.

    Async pairs are counted once (the ``-start`` op carries the shape and
    groups; ``-done`` never matches).  ``mesh`` enables axis attribution;
    without it every op reports axes ``("?",)``.
    """
    ops = []
    for line in hlo_text.splitlines():
        m = _OP_RE.search(line)
        if not m:
            continue
        kind = m.group("kind")
        nbytes = _shape_bytes(m.group("shape"))
        if kind == "collective-permute":
            pm = _PAIRS_RE.search(line)
            pairs = [tuple(int(x) for x in p.split(","))
                     for p in re.findall(r"\{([0-9, ]+)\}",
                                         pm.group(1))] if pm else []
            axes = _axes_for_pairs(mesh, pairs) if mesh is not None else ("?",)
        else:
            gm = _GROUPS_RE.search(line)
            groups = _parse_groups(gm.group(1)) if gm else []
            axes = (_axes_for_groups(mesh, groups)
                    if mesh is not None else ("?",))
        ops.append(CollectiveOp(kind=kind, bytes=nbytes, axes=axes,
                                shape=m.group("shape")))
    return ops


def _count_explicit_gathers(fn, args) -> int:
    """Author-requested all-gathers: ``all_gather``/``pgather`` equations
    anywhere in the traced jaxpr (the baseline DL201 subtracts)."""
    import jax
    from jax import core as jcore
    try:
        closed = jax.make_jaxpr(fn)(*args)
    except Exception:
        return 0

    def jaxprs_in(v):
        if isinstance(v, (jcore.Jaxpr, jcore.ClosedJaxpr)):
            yield v.jaxpr if isinstance(v, jcore.ClosedJaxpr) else v
        elif isinstance(v, (list, tuple)):
            for item in v:
                yield from jaxprs_in(item)

    count = 0
    stack = [closed.jaxpr]
    while stack:
        j = stack.pop()
        for eqn in j.eqns:
            if eqn.primitive.name in ("all_gather", "pgather"):
                count += 1
            for v in eqn.params.values():
                stack.extend(jaxprs_in(v))
    return count


def _spec_is_sharded(spec) -> bool:
    """True when a PartitionSpec/NamedSharding names at least one axis."""
    inner = getattr(spec, "spec", spec)       # NamedSharding -> its spec
    try:
        parts = tuple(inner)
    except TypeError:
        return False
    for p in parts:
        if p is None:
            continue
        if isinstance(p, (tuple, list)):
            if any(p):
                return True
        else:
            return True
    return False


def _check_replicated_params(lowered, compiled, args, in_specs,
                             name: str) -> list[Finding]:
    """DL202: declared-sharded large arguments compiled fully replicated."""
    import jax
    try:
        actual = compiled.input_shardings[0]
    except Exception:
        return []
    arg_leaves = jax.tree_util.tree_leaves(args)
    spec_leaves = jax.tree_util.tree_leaves(
        in_specs, is_leaf=lambda x: x is None or _is_spec(x))
    if len(arg_leaves) != len(spec_leaves) or \
            len(arg_leaves) != len(actual):
        return []
    findings = []
    for leaf, spec, sharding in zip(arg_leaves, spec_leaves, actual):
        if spec is None or not _spec_is_sharded(spec):
            continue
        size = getattr(leaf, "size", 0) * getattr(
            np.dtype(getattr(leaf, "dtype", "f4")), "itemsize", 4)
        if size < REPLICATED_BYTES_THRESHOLD:
            continue
        if getattr(sharding, "is_fully_replicated", False):
            findings.append(Finding(
                "DL202",
                f"argument declared sharded as {spec} "
                f"({size} bytes) compiles to a fully replicated "
                "parameter; the sharding was dropped between the in-spec "
                "and the executable (check with_sharding_constraint / "
                "jit in_shardings wiring)",
                where=name))
    return findings


def _is_spec(x) -> bool:
    from jax.sharding import NamedSharding, PartitionSpec
    return isinstance(x, (NamedSharding, PartitionSpec))


# --------------------------------------------------------------- DL206 --

def _alias_param_ids(hlo_text: str) -> set[int]:
    """Flat parameter numbers the compiled module's ``input_output_alias``
    table aliases to an output.  The attribute nests braces
    (``{ {0}: (23, {}, may-alias), ... }``), so the payload is isolated
    with a brace scan and the targets read as ``(N, ...)`` tuples."""
    marker = "input_output_alias={"
    i = hlo_text.find(marker)
    if i < 0:
        return set()
    j, depth = i + len(marker), 1
    while j < len(hlo_text) and depth:
        if hlo_text[j] == "{":
            depth += 1
        elif hlo_text[j] == "}":
            depth -= 1
        j += 1
    sub = hlo_text[i + len(marker):j - 1]
    return {int(n) for n in re.findall(r"\((\d+)\s*,", sub)}


def _leaf_bytes(leaf) -> int:
    size = getattr(leaf, "size", None)
    if size is None:
        size = math.prod(getattr(leaf, "shape", ()) or (1,))
    return int(size) * getattr(
        np.dtype(getattr(leaf, "dtype", "f4")), "itemsize", 4)


def _check_donation(lowered, hlo_text: str, name: str) -> list[Finding]:
    """DL206: declared donations vs. the aliases XLA committed to, plus
    large undonated inputs a matching output could have consumed."""
    import jax
    try:
        in_leaves = jax.tree_util.tree_leaves(lowered.args_info)
        out_leaves = jax.tree_util.tree_leaves(lowered.out_info)
    except Exception:
        return []            # pre-args_info jax: nothing to audit
    aliased = _alias_param_ids(hlo_text)
    findings = []
    for i, leaf in enumerate(in_leaves):
        if getattr(leaf, "donated", False) and i not in aliased:
            findings.append(Finding(
                "DL206",
                f"input #{i} ({tuple(leaf.shape)}/{leaf.dtype}, "
                f"{_leaf_bytes(leaf)} bytes) is declared donated but the "
                "compiled program aliases it to NO output — the caller's "
                "buffer is invalidated and no memory is saved; drop the "
                "donation or give the program a shape/dtype-matching "
                "output to reuse it",
                where=name))
    # outputs still available for aliasing: each committed alias consumes
    # one output of the donated input's (shape, dtype) — count-aware so
    # two same-shaped pools can't both claim the same output
    out_count = Counter((tuple(leaf.shape), str(leaf.dtype))
                        for leaf in out_leaves)
    for i in sorted(aliased):
        if i < len(in_leaves):
            leaf = in_leaves[i]
            key = (tuple(leaf.shape), str(leaf.dtype))
            if out_count.get(key):
                out_count[key] -= 1
    for i, leaf in enumerate(in_leaves):
        if getattr(leaf, "donated", False):
            continue
        key = (tuple(leaf.shape), str(leaf.dtype))
        nbytes = _leaf_bytes(leaf)
        if nbytes >= DONATION_BYTES_THRESHOLD and out_count.get(key):
            out_count[key] -= 1
            findings.append(Finding(
                "DL206",
                f"input #{i} ({tuple(leaf.shape)}/{leaf.dtype}, {nbytes} "
                "bytes) is not donated but a shape/dtype-matching output "
                "leaf goes unaliased — the program holds both buffers "
                "live every dispatch; donate the input (engine pools: "
                "DecodeEngine(donate=True)) to halve its footprint",
                where=name))
    return findings


# --------------------------------------------------------------- DL207 --

def _arg_signature(args) -> tuple:
    """Per-leaf (dtype, weak_type, shape) triples — the compile-cache
    key distinct lowerings are counted by (DL207)."""
    import jax
    return tuple(
        (str(getattr(leaf, "dtype", "?")),
         bool(getattr(leaf, "weak_type", False)),
         str(tuple(getattr(leaf, "shape", ()))))
        for leaf in jax.tree_util.tree_leaves(args))


def audit_compiles(family: str, reports) -> tuple[list[Finding], dict]:
    """DL207 drift audit + the family's compile summary.

    Returns ``(findings, summary)``: findings flag two units of one
    bracketed group (``decode_prefill[8]``/``[16]``) whose signatures
    share every shape but differ in dtype or weak-type — the same
    logical program paying two warmup compiles because a host-side cast
    or Python-scalar leak drifted the signature.  ``summary`` is
    ``{"count": distinct lowerings, "warmup_s_estimate": measured
    compile seconds}`` — the count is what the budget lockfile gates.
    """
    findings: list[Finding] = []
    sigs = {name: rep.signature for name, rep in sorted(reports.items())
            if rep.signature is not None}
    groups: dict[str, list] = {}
    for name, sig in sigs.items():
        groups.setdefault(name.split("[", 1)[0], []).append((name, sig))
    for base, members in sorted(groups.items()):
        by_shapes: dict[tuple, tuple] = {}
        for name, sig in members:
            shapes = tuple(s for _dt, _wk, s in sig)
            prev = by_shapes.setdefault(shapes, (name, sig))
            if prev[1] != sig:
                findings.append(Finding(
                    "DL207",
                    f"units {prev[0]!r} and {name!r} lower identical "
                    "shapes under different dtype/weak-type signatures — "
                    "one logical program costs two warmup compiles "
                    "(a dtype cast or weak-typed Python scalar drifted "
                    "the compile-cache key)",
                    where=f"{family}:{base}"))
    count = len(set(sigs.values()))
    warmup = sum(rep.compile_s or 0.0 for rep in reports.values())
    return findings, {"count": count,
                      "warmup_s_estimate": round(warmup, 3)}


# --------------------------------------------------------------- DL208 --

_PARAM_DEF_RE = re.compile(r"%([\w.\-]+)\s*=\s*\S+\s+parameter\(")
_RELAYOUT_RE = re.compile(
    r"=\s*\S+\s+(?:copy|transpose)\("
    r"(?:[a-z0-9_]+\[[0-9,]*\](?:\{[^}]*\})?\s+)?%([\w.\-]+)")


def count_entry_relayouts(hlo_text: str) -> int:
    """``copy``/``transpose`` ops in the ENTRY computation whose operand
    is an entry parameter — the compiler re-materializing an argument in
    a different layout on every dispatch (DL208).  Only the ENTRY block
    is scanned: fusion-region ``parameter()`` lines are computation-local
    and say nothing about the program's entry layout contract."""
    m = re.search(r"^ENTRY\b", hlo_text, re.M)
    if not m:
        return 0
    depth, started, lines = 0, False, []
    for line in hlo_text[m.start():].splitlines():
        lines.append(line)
        depth += line.count("{") - line.count("}")
        if "{" in line:
            started = True
        if started and depth <= 0:
            break
    block = "\n".join(lines)
    params = set(_PARAM_DEF_RE.findall(block))
    return sum(1 for operand in _RELAYOUT_RE.findall(block)
               if operand in params)


# --------------------------------------------------------------- DL209 --

def _scan_hot_method(node, modname: str, clsname: str) -> list[Finding]:
    findings = []

    def walk(n):
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue     # staged closure: runs inside the XLA program
            where = (f"{modname}.{clsname}.{node.name}:"
                     f"{getattr(child, 'lineno', node.lineno)}")
            if isinstance(child, ast.BinOp) and isinstance(child.op,
                                                           ast.MatMult):
                findings.append(Finding(
                    "DL209",
                    f"host-side matrix multiply (@) in per-tick method "
                    f"{clsname}.{node.name}() runs on every tick — it "
                    "belongs inside the jitted tick program",
                    where=where))
            elif (isinstance(child, ast.Call)
                    and isinstance(child.func, ast.Attribute)
                    and isinstance(child.func.value, ast.Name)
                    and child.func.value.id in ("np", "jnp", "numpy")
                    and child.func.attr in _TENSOR_MATH_FNS):
                findings.append(Finding(
                    "DL209",
                    f"per-tick host tensor math "
                    f"{child.func.value.id}.{child.func.attr}(...) in "
                    f"{clsname}.{node.name}() — every call is a Python-"
                    "level pass over tensor data in the serve hot loop; "
                    "move it inside the jitted tick program",
                    where=where))
            walk(child)

    walk(node)
    return findings


def lint_tick_loop(sources=None) -> list[Finding]:
    """DL209: numpy/jnp tensor math in the per-tick host methods.

    ``sources`` is a list of ``(source, modname)`` pairs (or raw source
    strings); defaults to ``serve/engine.py`` + ``serve/scheduler.py`` +
    ``serve/prefix_cache.py`` + ``serve/speculate.py`` (every module
    with per-round host work).  Only methods named in
    :data:`TICK_HOT_METHODS` directly on a class body are scanned —
    nested ``def``s are the staged program bodies the math is SUPPOSED
    to live in, and are skipped both as scan roots and inside a hot
    method."""
    if sources is None:
        import inspect
        from distlearn_tpu.serve import (engine, prefix_cache, scheduler,
                                         speculate)
        sources = [(inspect.getsource(m), m.__name__)
                   for m in (engine, scheduler, prefix_cache, speculate)]
    findings: list[Finding] = []
    for item in sources:
        src, modname = item if isinstance(item, tuple) else (item,
                                                             "<string>")
        for cls in ast.walk(ast.parse(src)):
            if not isinstance(cls, ast.ClassDef):
                continue
            for stmt in cls.body:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                        and stmt.name in TICK_HOT_METHODS:
                    findings += _scan_hot_method(stmt, modname, cls.name)
    return findings


def analyze_step(fn, args: Sequence, *, mesh=None, name: str = "step",
                 in_specs=None,
                 gather_threshold: int = GATHER_BYTES_THRESHOLD,
                 donation: bool = False
                 ) -> tuple[CostReport, list[Finding]]:
    """Compile ``fn(*args)`` and build its :class:`CostReport`.

    Returns ``(report, findings)`` where findings are the compile-level
    rules (DL201 implicit all-gather, DL202 replicated parameter, and —
    with ``donation=True`` — DL206 wasted/missing donation); the
    lockfile rules DL203-DL205/DL207/DL208 are applied by
    :func:`distlearn_tpu.lint.budget.check_family` over a whole family's
    reports.  ``in_specs`` (optional pytree of
    PartitionSpec/NamedSharding leaves matching ``args``) enables DL202.
    The report also carries the unit's compile-cache ``signature``,
    measured ``compile_s``, and entry ``relayout_ops`` for the DL207/
    DL208 budget gates.
    """
    t0 = time.perf_counter()
    lowered, compiled = compat.lower_compiled(fn, args)
    compile_s = time.perf_counter() - t0
    hlo = compiled.as_text()
    report = CostReport(
        name=name,
        collectives=parse_collectives(hlo, mesh),
        memory=compat.compiled_memory_stats(compiled),
        flops=compat.compiled_cost_analysis(compiled).get("flops"),
        signature=_arg_signature(args),
        compile_s=compile_s,
        relayout_ops=count_entry_relayouts(hlo),
    )
    findings = []
    large = [op for op in report.collectives
             if op.kind == "all-gather" and op.bytes >= gather_threshold]
    explicit = _count_explicit_gathers(fn, args) if large else 0
    if len(large) > explicit:
        worst = max(large, key=lambda op: op.bytes)
        findings.append(Finding(
            "DL201",
            f"compiled module contains {len(large)} all-gather op(s) of "
            f">= {gather_threshold} bytes but the jaxpr requests only "
            f"{explicit}; GSPMD inserted a replication gather (largest: "
            f"{worst.shape} over axes {list(worst.axes)}, {worst.bytes} "
            "bytes/step) — re-shard the producer or add a "
            "with_sharding_constraint",
            where=name))
    if in_specs is not None:
        findings += _check_replicated_params(lowered, compiled, args,
                                             in_specs, name)
    if donation:
        findings += _check_donation(lowered, hlo, name)
    return report, findings
