"""Static collective-traffic & memory cost model (rules DL201, DL202).

Where :mod:`distlearn_tpu.lint.spmd` analyzes the program the *author*
wrote (the jaxpr), this module analyzes the program the *compiler* built:
each step function is lowered and compiled on the deployment mesh and the
post-fusion HLO module is walked to attribute

* **bytes per collective kind per mesh axis** — every ``all-reduce``,
  ``all-gather``, ``reduce-scatter``, ``collective-permute`` and
  ``all-to-all`` op is parsed out of the module text with its payload
  shape and replica groups, and the groups are mapped back to the mesh
  axes they span (explicit ``{{0,4},{1,5}}`` lists, iota-form
  ``[2,4]<=[8]`` lists, and permute ``source_target_pairs`` all
  supported);
* **post-fusion collective op counts** — what fusion actually left in the
  module, which is what the wire sees (``ops/fused_update.py`` degrading
  to per-tensor reduces shows up here long before a profile would);
* **compiled peak/temp memory** via
  :func:`distlearn_tpu.utils.compat.compiled_memory_stats`.

The numbers are *per device per step*: the module XLA emits under SPMD
partitioning is the one program every device runs, with local (sharded)
shapes, so a payload byte count is what one device moves through one
step.  Two rules fire directly from the model:

* **DL201** — the compiled module contains more *large* all-gathers
  (payload >= :data:`GATHER_BYTES_THRESHOLD`) than the jaxpr requested
  explicitly: GSPMD sharding propagation lost a sharding on a hot path
  and is rematerializing a full buffer every step.
* **DL202** — the caller declared a sharded in-spec for a large argument
  but the compiled executable materializes that parameter fully
  replicated (>= :data:`REPLICATED_BYTES_THRESHOLD`).

Budget regression rules DL203-DL205 compare a :class:`CostReport` against
the committed per-family lockfiles — see :mod:`distlearn_tpu.lint.budget`.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field
from typing import Sequence

import numpy as np

from distlearn_tpu.lint.core import Finding
from distlearn_tpu.utils import compat

__all__ = ["CollectiveOp", "CostReport", "analyze_step",
           "parse_collectives", "GATHER_BYTES_THRESHOLD",
           "REPLICATED_BYTES_THRESHOLD", "COLLECTIVE_KINDS"]

#: HLO opcodes the model attributes traffic to.
COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                    "collective-permute", "all-to-all")

#: DL201 fires only for implicit all-gathers at least this large: tiny
#: gathers (scalars, loop counters, eval metrics) are GSPMD doing its job.
GATHER_BYTES_THRESHOLD = 1 << 20

#: DL202 fires only for replicated parameters at least this large.
REPLICATED_BYTES_THRESHOLD = 1 << 20

# f8 variants intentionally coarse; HLO spells dtypes like f32, bf16, s64.
_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}
_DTYPE_BYTES.update({f"f8{suffix}": 1 for suffix in
                     ("e4m3fn", "e5m2", "e4m3b11fnuz", "e4m3fnuz", "e5m2fnuz")})

_SHAPE_RE = re.compile(r"([a-z]+[0-9]+(?:[a-z0-9]*)?|pred)\[([0-9,]*)\]")
# `%name = <shape> <kind>(`: shape is a bare token or a (tuple).  Operand
# references (`%all-gather.3`) never match — they are not preceded by
# `= <shape>` and not followed by `(`.
_OP_RE = re.compile(
    r"=\s*(?P<shape>\([^)]*\)|\S+)\s+"
    r"(?P<kind>" + "|".join(COLLECTIVE_KINDS) + r")(?:-start)?\(")
_GROUPS_RE = re.compile(r"replica_groups=(\{\{[0-9,{} ]*\}\}|\{\}|"
                        r"\[[0-9,]+\]<=\[[0-9,]+\](?:T\([0-9,]+\))?)")
_PAIRS_RE = re.compile(r"source_target_pairs=\{([0-9,{} ]*)\}")


def _shape_bytes(shape_token: str) -> int:
    """Byte size of one HLO shape token (``f32[4,8]{1,0}`` or a tuple)."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(shape_token):
        size = _DTYPE_BYTES.get(dtype)
        if size is None:
            continue  # token dtype (opaque, s32[]-like already matched)
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * size
    return total


def _parse_groups(attr: str) -> list[tuple[int, ...]]:
    """Parse a ``replica_groups=`` payload into device-id groups."""
    if attr.startswith("{"):
        return [tuple(int(x) for x in grp.split(",") if x.strip())
                for grp in re.findall(r"\{([0-9, ]+)\}", attr)]
    # iota form: [G,S]<=[dims](T(perm))? — arange over the flattened device
    # space, reshaped to `dims`, transposed by `perm`, regrouped as G rows.
    m = re.match(r"\[([0-9,]+)\]<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?", attr)
    if not m:
        return []
    out_dims = [int(x) for x in m.group(1).split(",")]
    iota_dims = [int(x) for x in m.group(2).split(",")]
    ids = np.arange(math.prod(iota_dims)).reshape(iota_dims)
    if m.group(3):
        ids = ids.transpose([int(x) for x in m.group(3).split(",")])
    return [tuple(int(x) for x in row)
            for row in ids.reshape(out_dims[0], -1)]


def _mesh_device_ids(mesh) -> tuple[np.ndarray, tuple[str, ...]] | None:
    devices = getattr(mesh, "devices", None)
    names = getattr(mesh, "axis_names", None)
    if devices is None or names is None:
        return None
    ids = np.vectorize(lambda d: getattr(d, "id", -1))(np.asarray(devices))
    return ids, tuple(str(a) for a in names)


def _axes_for_groups(mesh, groups: Sequence[tuple[int, ...]]
                     ) -> tuple[str, ...]:
    """Mesh axes a replica-group list spans (``("?",)`` when unknown).

    A collective grouped along axis subset ``S`` partitions the devices
    into one group per coordinate of the *other* axes; we test every
    non-empty subset (meshes here have <= 4 axes) against the parsed
    groups.  Size-1 groups are the degenerate no-communication case and
    return ``()``.
    """
    if not groups:
        return ("?",)
    if all(len(g) <= 1 for g in groups):
        return ()
    info = _mesh_device_ids(mesh)
    if info is None:
        return ("?",)
    ids, names = info
    want = {frozenset(g) for g in groups}
    for mask in range(1, 1 << len(names)):
        subset = [i for i in range(len(names)) if mask & (1 << i)]
        rest = [i for i in range(len(names)) if i not in subset]
        grouped = ids.transpose(rest + subset).reshape(
            -1, math.prod(ids.shape[i] for i in subset))
        if {frozenset(int(x) for x in row) for row in grouped} == want:
            return tuple(names[i] for i in subset)
    return ("?",)


def _axes_for_pairs(mesh, pairs: Sequence[tuple[int, int]]
                    ) -> tuple[str, ...]:
    """Mesh axes a permute's source->target pairs move along."""
    info = _mesh_device_ids(mesh)
    if info is None or not pairs:
        return ("?",)
    ids, names = info
    where = {int(v): np.unravel_index(i, ids.shape)
             for i, v in enumerate(ids.ravel())}
    axes: set[str] = set()
    for src, dst in pairs:
        if src not in where or dst not in where:
            return ("?",)
        for dim, (a, b) in enumerate(zip(where[src], where[dst])):
            if a != b:
                axes.add(names[dim])
    return tuple(a for a in names if a in axes)


@dataclass(frozen=True)
class CollectiveOp:
    """One post-fusion collective in the compiled module."""

    kind: str            # one of COLLECTIVE_KINDS
    bytes: int           # payload bytes (local/per-device shape)
    axes: tuple          # mesh axes the op communicates over
    shape: str           # the HLO result shape token, for messages

    @property
    def axis_key(self) -> str:
        return f"{self.kind}@{','.join(self.axes) or '-'}"


@dataclass
class CostReport:
    """Static cost of one compiled step function.

    ``bytes_by_kind`` / ``ops_by_kind`` aggregate over mesh axes;
    ``bytes_by_axis`` keeps the per-axis split (keys like
    ``"all-reduce@data"``).  ``memory`` is the
    :func:`~distlearn_tpu.utils.compat.compiled_memory_stats` dict (or
    None where the backend reports nothing); ``flops`` comes from the
    compiler's own cost analysis when available.
    """

    name: str
    collectives: list[CollectiveOp] = field(default_factory=list)
    memory: dict | None = None
    flops: float | None = None

    @property
    def bytes_by_kind(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for op in self.collectives:
            out[op.kind] = out.get(op.kind, 0) + op.bytes
        return out

    @property
    def ops_by_kind(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for op in self.collectives:
            out[op.kind] = out.get(op.kind, 0) + 1
        return out

    @property
    def bytes_by_axis(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for op in self.collectives:
            out[op.axis_key] = out.get(op.axis_key, 0) + op.bytes
        return out

    @property
    def ops_by_axis(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for op in self.collectives:
            out[op.axis_key] = out.get(op.axis_key, 0) + 1
        return out

    @property
    def peak_bytes(self) -> int | None:
        return self.memory.get("peak") if self.memory else None

    def to_json(self) -> dict:
        return {
            "collective_bytes": self.bytes_by_kind,
            "collective_ops": self.ops_by_kind,
            "bytes_by_axis": self.bytes_by_axis,
            "peak_bytes": self.peak_bytes,
            "temp_bytes": self.memory.get("temp") if self.memory else None,
            "flops": self.flops,
        }


def parse_collectives(hlo_text: str, mesh=None) -> list[CollectiveOp]:
    """Extract every collective op from compiled HLO module text.

    Async pairs are counted once (the ``-start`` op carries the shape and
    groups; ``-done`` never matches).  ``mesh`` enables axis attribution;
    without it every op reports axes ``("?",)``.
    """
    ops = []
    for line in hlo_text.splitlines():
        m = _OP_RE.search(line)
        if not m:
            continue
        kind = m.group("kind")
        nbytes = _shape_bytes(m.group("shape"))
        if kind == "collective-permute":
            pm = _PAIRS_RE.search(line)
            pairs = [tuple(int(x) for x in p.split(","))
                     for p in re.findall(r"\{([0-9, ]+)\}",
                                         pm.group(1))] if pm else []
            axes = _axes_for_pairs(mesh, pairs) if mesh is not None else ("?",)
        else:
            gm = _GROUPS_RE.search(line)
            groups = _parse_groups(gm.group(1)) if gm else []
            axes = (_axes_for_groups(mesh, groups)
                    if mesh is not None else ("?",))
        ops.append(CollectiveOp(kind=kind, bytes=nbytes, axes=axes,
                                shape=m.group("shape")))
    return ops


def _count_explicit_gathers(fn, args) -> int:
    """Author-requested all-gathers: ``all_gather``/``pgather`` equations
    anywhere in the traced jaxpr (the baseline DL201 subtracts)."""
    import jax
    from jax import core as jcore
    try:
        closed = jax.make_jaxpr(fn)(*args)
    except Exception:
        return 0

    def jaxprs_in(v):
        if isinstance(v, (jcore.Jaxpr, jcore.ClosedJaxpr)):
            yield v.jaxpr if isinstance(v, jcore.ClosedJaxpr) else v
        elif isinstance(v, (list, tuple)):
            for item in v:
                yield from jaxprs_in(item)

    count = 0
    stack = [closed.jaxpr]
    while stack:
        j = stack.pop()
        for eqn in j.eqns:
            if eqn.primitive.name in ("all_gather", "pgather"):
                count += 1
            for v in eqn.params.values():
                stack.extend(jaxprs_in(v))
    return count


def _spec_is_sharded(spec) -> bool:
    """True when a PartitionSpec/NamedSharding names at least one axis."""
    inner = getattr(spec, "spec", spec)       # NamedSharding -> its spec
    try:
        parts = tuple(inner)
    except TypeError:
        return False
    for p in parts:
        if p is None:
            continue
        if isinstance(p, (tuple, list)):
            if any(p):
                return True
        else:
            return True
    return False


def _check_replicated_params(lowered, compiled, args, in_specs,
                             name: str) -> list[Finding]:
    """DL202: declared-sharded large arguments compiled fully replicated."""
    import jax
    try:
        actual = compiled.input_shardings[0]
    except Exception:
        return []
    arg_leaves = jax.tree_util.tree_leaves(args)
    spec_leaves = jax.tree_util.tree_leaves(
        in_specs, is_leaf=lambda x: x is None or _is_spec(x))
    if len(arg_leaves) != len(spec_leaves) or \
            len(arg_leaves) != len(actual):
        return []
    findings = []
    for leaf, spec, sharding in zip(arg_leaves, spec_leaves, actual):
        if spec is None or not _spec_is_sharded(spec):
            continue
        size = getattr(leaf, "size", 0) * getattr(
            np.dtype(getattr(leaf, "dtype", "f4")), "itemsize", 4)
        if size < REPLICATED_BYTES_THRESHOLD:
            continue
        if getattr(sharding, "is_fully_replicated", False):
            findings.append(Finding(
                "DL202",
                f"argument declared sharded as {spec} "
                f"({size} bytes) compiles to a fully replicated "
                "parameter; the sharding was dropped between the in-spec "
                "and the executable (check with_sharding_constraint / "
                "jit in_shardings wiring)",
                where=name))
    return findings


def _is_spec(x) -> bool:
    from jax.sharding import NamedSharding, PartitionSpec
    return isinstance(x, (NamedSharding, PartitionSpec))


def analyze_step(fn, args: Sequence, *, mesh=None, name: str = "step",
                 in_specs=None,
                 gather_threshold: int = GATHER_BYTES_THRESHOLD
                 ) -> tuple[CostReport, list[Finding]]:
    """Compile ``fn(*args)`` and build its :class:`CostReport`.

    Returns ``(report, findings)`` where findings are the compile-level
    rules (DL201 implicit all-gather, DL202 replicated parameter); the
    lockfile rules DL203-DL205 are applied by
    :func:`distlearn_tpu.lint.budget.check_family` over a whole family's
    reports.  ``in_specs`` (optional pytree of
    PartitionSpec/NamedSharding leaves matching ``args``) enables DL202.
    """
    lowered, compiled = compat.lower_compiled(fn, args)
    report = CostReport(
        name=name,
        collectives=parse_collectives(compiled.as_text(), mesh),
        memory=compat.compiled_memory_stats(compiled),
        flops=compat.compiled_cost_analysis(compiled).get("flops"),
    )
    findings = []
    large = [op for op in report.collectives
             if op.kind == "all-gather" and op.bytes >= gather_threshold]
    explicit = _count_explicit_gathers(fn, args) if large else 0
    if len(large) > explicit:
        worst = max(large, key=lambda op: op.bytes)
        findings.append(Finding(
            "DL201",
            f"compiled module contains {len(large)} all-gather op(s) of "
            f">= {gather_threshold} bytes but the jaxpr requests only "
            f"{explicit}; GSPMD inserted a replication gather (largest: "
            f"{worst.shape} over axes {list(worst.axes)}, {worst.bytes} "
            "bytes/step) — re-shard the producer or add a "
            "with_sharding_constraint",
            where=name))
    if in_specs is not None:
        findings += _check_replicated_params(lowered, compiled, args,
                                             in_specs, name)
    return report, findings
