"""Explicit-state protocol model checking (rules DL301-DL304).

``lint/protocol.py`` executes ONE hand-written interleaving per schedule.
This module is the other half of ROADMAP item 4: small nondeterministic
process models of the repo's distributed protocols, explored
EXHAUSTIVELY — breadth-first over every interleaving of process steps and
fault actions (rank crash, silent hang, peer FIN, dropped ack, duplicate
delivery via retry) — with safety invariants checked at every reachable
state, in the TLA+/SPIN tradition (Lamport, *Specifying Systems*).

A model is a :class:`ModelSpec`: a hashable initial state, an
``actions(state) -> [(label, next_state)]`` successor function, an
``invariant(state) -> [(rule, message)]`` safety check, and an
``is_terminal(state)`` predicate.  :func:`check_model` runs BFS from the
initial state; because BFS visits states in depth order, the first
violation found is a SHORTEST counterexample, and the parent-pointer map
turns it into a numbered action trace embedded in the finding message.
A reachable state with no enabled action that is not terminal is a
deadlock (DL301).

Shipped models (:func:`builtin_models`):

* ``sync``            — the unsharded AsyncEA handshake
  (``AsyncEAServer.sync_server`` / ``AsyncEAClient.sync_client``) under
  client hang/FIN faults; deadlock-free ONLY because every server recv is
  handshake_timeout-armed (``mutate_sync(server_timeouts=False)`` is the
  seeded DL301).
* ``sharded``         — the striped handshake (``_serve_striped`` legs +
  client fan-out) under the same fault model; proves eviction drains
  every serving leg.
* ``replay``          — rejoin with exactly-once replay
  (``_readmit``/``_recv_replay``): a dropped final ack forces the client
  to re-run the whole rejoin (at-least-once delivery), and only the
  applied-seq ledger keeps the duplicate from double-applying
  (``mutate_replay(ledger=False)`` is the seeded DL303).
* ``failover``        — HA failover with a zombie primary
  (``docs/HA.md``): pause, promote, resume, re-dial; the epoch fence is
  what stops the resumed stale primary from applying a delta
  (``mutate_failover(fence=False)`` is the seeded DL302).
* ``serve``           — the serve scheduler/engine resource accounting
  (``serve/scheduler.py``): admit/tick/finish/cancel/deadline-expire/
  disconnect in every order; every eviction path must return the slot
  AND its pages to the engine (``mutate_serve(finish_on_evict=False)``
  is the seeded DL304).
* ``membership``      — elastic join/leave/rebalance
  (``_handle_join``/``_handle_leave``/``_delta_weight``): a joiner is
  registered only AFTER it adopts the current center (the join fence —
  ``membership_model(join_fence=False)`` is the seeded DL302), a
  graceful leave waits out the leaver's in-flight apply before reading
  the ledger (``leave_flush=False`` races the leave replay against the
  worker and double-applies, the seeded DL303), and every membership
  change renormalizes the capacity weights so the fleet's total weight
  mass is conserved (``renorm=False`` is the seeded DL304).
* ``router``          — the serving-fleet router
  (``serve/router.py``): dispatch/retry/shed/hedge over dying,
  shedding, hot-swapping replicas — deadlock-free only because dead
  replicas' queued requests are resubmitted (``retry=False`` is the
  seeded DL301), no stream splices two center epochs
  (``fence=False`` is the seeded DL302), and execution stays
  at-most-once per replica (``single_dispatch=False`` is the seeded
  DL303).

State spaces are deliberately tiny (1 client, 2 stripes, 2 requests,
small budgets) so the exhaustive sweep stays well under a second of
tier-1 time; the explored state/transition counts are reported through
``LintResult.info`` so a model that silently stopped exploring is
visible in CI output.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Hashable, Iterable, Mapping, Sequence

from distlearn_tpu.lint.core import Finding

__all__ = [
    "ModelSpec", "ModelReport", "check_model", "builtin_models",
    "sync_model", "sharded_model", "replay_model", "failover_model",
    "serve_model", "membership_model", "router_model",
    "backend_sync_model", "lint_models",
]

State = Hashable
Action = "tuple[str, State]"


@dataclass(frozen=True)
class ModelSpec:
    """One checkable protocol model (see module docstring)."""

    name: str
    init: State
    actions: Callable[[State], "list[tuple[str, State]]"]
    invariant: Callable[[State], "list[tuple[str, str]]"]
    is_terminal: Callable[[State], bool]


@dataclass
class ModelReport:
    """Exhaustive-exploration result for one model."""

    name: str
    states: int = 0
    transitions: int = 0
    findings: list[Finding] = field(default_factory=list)

    @property
    def info(self) -> dict:
        return {"states": self.states, "transitions": self.transitions}


def _trace(parents: Mapping, state: State) -> list[str]:
    """Reconstruct the action-label path init -> ``state``."""
    labels: list[str] = []
    while True:
        prev = parents[state]
        if prev is None:
            break
        state, label = prev
        labels.append(label)
    labels.reverse()
    return labels


def _format_trace(labels: Sequence[str]) -> str:
    if not labels:
        return "counterexample: the initial state"
    steps = "; ".join(f"{i}) {lab}" for i, lab in enumerate(labels, 1))
    return f"counterexample ({len(labels)} step(s)): {steps}"


def check_model(spec: ModelSpec, *, max_states: int = 200_000) -> ModelReport:
    """BFS over every reachable state of ``spec``.

    The invariant runs on every state; a state with no enabled action
    that is not terminal is a DL301 deadlock.  Only the FIRST (therefore
    shortest) counterexample per rule id is reported.  ``max_states``
    is a runaway backstop — exceeding it is itself a DL301-severity
    modeling error, never a silent truncation.
    """
    report = ModelReport(spec.name)
    seen: dict = {spec.init: None}      # state -> (parent_state, label)|None
    queue: deque = deque([spec.init])
    reported: set[str] = set()

    def fire(rule: str, message: str, state: State) -> None:
        if rule in reported:
            return
        reported.add(rule)
        report.findings.append(Finding(
            rule, f"{message}; {_format_trace(_trace(seen, state))}",
            where=f"model:{spec.name}"))

    while queue:
        state = queue.popleft()
        for rule, message in spec.invariant(state):
            fire(rule, message, state)
        acts = spec.actions(state)
        if not acts and not spec.is_terminal(state):
            fire("DL301",
                 "model reaches a non-terminal state with no enabled "
                 "action (deadlock)", state)
        for label, nxt in acts:
            report.transitions += 1
            if nxt not in seen:
                if len(seen) >= max_states:
                    fire("DL301",
                         f"state space exceeded the {max_states}-state "
                         "backstop; the model is unbounded (missing "
                         "budget?)", state)
                    report.states = len(seen)
                    return report
                seen[nxt] = (state, label)
                queue.append(nxt)
    report.states = len(seen)
    return report


# ---------------------------------------------------------------------------
# Generic script machinery: processes executing send/recv scripts over
# FIFO per-pair channels, with hang/FIN faults and timeout-armed evicts.
# Backs the ``sync`` and ``sharded`` models; the semantic models
# (replay/failover/serve) are hand-written below.

#: process-group statuses
_RUN, _HUNG, _FIN, _CLOSED = "run", "hung", "fin", "closed"


def _script_model(name: str, scripts: "dict[str, list]",
                  groups: "dict[str, str]", *,
                  crashable: Iterable[str] = (),
                  timeout_ranks: Iterable[str] = (),
                  fault_budget: int = 1) -> ModelSpec:
    """Build a ModelSpec from per-rank ``(kind, peer, tag)`` scripts.

    ``groups`` maps rank -> process (the crash unit: one client process
    owns all its fanned-out legs).  A ``crashable`` process may, once,
    either HANG (silent stop — partition/GC pause; only a timeout can
    unblock a peer reading from it) or FIN (clean close — a peer's recv
    errors immediately, send raises EPIPE).  Ranks in ``timeout_ranks``
    model handshake_timeout-armed recvs: while blocked they may abort.
    An abort is process-wide (``_evict`` closes every conn of the
    client) and marks the process CLOSED, which errors out its peers in
    turn — exactly the drain path the real eviction machinery takes.
    """
    ranks = sorted(scripts)
    procs = sorted(set(groups.values()))
    crashable = frozenset(crashable)
    timeout_ranks = frozenset(timeout_ranks)
    chan_keys = sorted({(r, op[1]) for r in ranks for op in scripts[r]
                        if op[0] == "send"})
    ci = {k: i for i, k in enumerate(chan_keys)}
    ri = {r: i for i, r in enumerate(ranks)}
    pi = {p: i for i, p in enumerate(procs)}

    init = (tuple(0 for _ in ranks),
            tuple(() for _ in chan_keys),
            tuple(_RUN for _ in procs),
            fault_budget)

    def _abort(pcs, status, proc):
        """Process-wide abort: every rank of ``proc`` jumps to script
        end, its conns close."""
        pcs = list(pcs)
        for r in ranks:
            if groups[r] == proc:
                pcs[ri[r]] = len(scripts[r])
        status = list(status)
        status[pi[proc]] = _CLOSED
        return tuple(pcs), tuple(status)

    def actions(state):
        pcs, chans, status, budget = state
        acts = []
        for r in ranks:
            g = groups[r]
            if status[pi[g]] != _RUN or pcs[ri[r]] >= len(scripts[r]):
                continue
            kind, peer, tag = scripts[r][pcs[ri[r]]]
            pg = groups[peer]
            if kind == "send":
                if status[pi[pg]] in (_FIN, _CLOSED):
                    npcs, nstat = _abort(pcs, status, g)
                    acts.append((f"{r}: send {tag!r} to dead {peer} fails "
                                 f"-> {g} aborts",
                                 (npcs, chans, nstat, budget)))
                else:
                    nch = list(chans)
                    nch[ci[(r, peer)]] = chans[ci[(r, peer)]] + (tag,)
                    npcs = list(pcs)
                    npcs[ri[r]] += 1
                    acts.append((f"{r}: send {tag!r} -> {peer}",
                                 (tuple(npcs), tuple(nch), status, budget)))
            else:  # recv
                key = (peer, r)
                q = chans[ci[key]] if key in ci else ()
                if q:
                    nch = list(chans)
                    nch[ci[key]] = q[1:]
                    npcs = list(pcs)
                    npcs[ri[r]] += 1
                    acts.append((f"{r}: recv {q[0]!r} <- {peer}",
                                 (tuple(npcs), tuple(nch), status, budget)))
                elif status[pi[pg]] in (_FIN, _CLOSED):
                    npcs, nstat = _abort(pcs, status, g)
                    acts.append((f"{r}: recv from closed {peer} errors "
                                 f"-> {g} aborts",
                                 (npcs, chans, nstat, budget)))
                elif r in timeout_ranks:
                    npcs, nstat = _abort(pcs, status, g)
                    acts.append((f"{r}: recv {tag!r} times out -> {g} "
                                 "evicts/aborts",
                                 (npcs, chans, nstat, budget)))
                # else: blocked on a live, silent peer — no action for
                # this rank; global no-progress is the DL301 check.
        if budget > 0:
            for p in procs:
                if p in crashable and status[pi[p]] == _RUN:
                    for fault, lab in ((_HUNG, "hangs (partition)"),
                                       (_FIN, "crashes (FIN)")):
                        nstat = list(status)
                        nstat[pi[p]] = fault
                        acts.append((f"fault: {p} {lab}",
                                     (pcs, chans, tuple(nstat), budget - 1)))
        return acts

    def is_terminal(state):
        pcs, _chans, status, _budget = state
        for r in ranks:
            if status[pi[groups[r]]] == _RUN and pcs[ri[r]] < len(scripts[r]):
                return False
        return True

    return ModelSpec(name, init, actions, lambda s: [], is_terminal)


def _snd(peer, tag):
    return ("send", peer, tag)


def _rcv(peer, tag):
    return ("recv", peer, tag)


def sync_model(*, server_timeouts: bool = True) -> ModelSpec:
    """Unsharded packed AsyncEA sync round, one server + one client,
    under client hang/FIN faults (see module docstring)."""
    scripts = {
        "S": [_rcv("C", "Enter?"), _snd("C", "Enter"),
              _rcv("C", "Center?"), _snd("C", "center_p"),
              _rcv("C", "delta?"), _snd("C", "delta"),
              _rcv("C", "delta_p")],
        "C": [_snd("S", "Enter?"), _rcv("S", "Enter"),
              _snd("S", "Center?"), _rcv("S", "center_p"),
              _snd("S", "delta?"), _rcv("S", "delta"),
              _snd("S", "delta_p")],
    }
    return _script_model(
        "sync", scripts, {"S": "server", "C": "client"},
        crashable=("client",),
        timeout_ranks=("S",) if server_timeouts else ())


def sharded_model(*, server_timeouts: bool = True) -> ModelSpec:
    """Striped sync round: dedicated leg S0/C0 plus one shard leg S1/C1
    (the smallest topology exhibiting the fan-out), client faults at any
    point of any leg."""
    scripts = {
        "S0": [_rcv("C0", "Enter?"), _snd("C0", "Enter"),
               _rcv("C0", "Center?"), _snd("C0", "center_p"),
               _rcv("C0", "delta?"), _snd("C0", "delta"),
               _rcv("C0", "delta_p")],
        "S1": [_rcv("C1", "Shard?"),
               _rcv("C1", "Center?"), _snd("C1", "center_p"),
               _rcv("C1", "delta?"), _snd("C1", "delta"),
               _rcv("C1", "delta_p")],
        "C0": [_snd("S0", "Enter?"), _rcv("S0", "Enter"),
               _snd("C1", "go"),
               _snd("S0", "Center?"), _rcv("S0", "center_p"),
               _snd("S0", "delta?"), _rcv("S0", "delta"),
               _snd("S0", "delta_p")],
        "C1": [_rcv("C0", "go"), _snd("S1", "Shard?"),
               _snd("S1", "Center?"), _rcv("S1", "center_p"),
               _snd("S1", "delta?"), _rcv("S1", "delta"),
               _snd("S1", "delta_p")],
    }
    groups = {"S0": "server", "S1": "server",
              "C0": "client", "C1": "client"}
    return _script_model(
        "sharded", scripts, groups, crashable=("client",),
        timeout_ranks=("S0", "S1") if server_timeouts else ())


def backend_sync_model(*, backend: str = "host",
                       host_timeouts: bool = True) -> ModelSpec:
    """One collective round of a :mod:`distlearn_tpu.comm.backend`
    topology under process faults.

    ``backend="host"``: a base-2 TCP tree root with two kid processes —
    each kid sends its up-phase payload and blocks for the down-phase
    result; the root folds both kids then fans the result back (the
    Tree.all_reduce_ex schedule, one message per phase per link).

    ``backend="hybrid"``: two hosts, each a process with a device-stage
    rank (the in-mesh reduce-scatter/all-gather + D2H/H2D staging,
    modeled as in-process messages that cannot time out) and a host-leg
    rank running the one-TCP-leg-per-host reduction.

    ``host_timeouts`` models ``op_timeout``-armed TCP recvs; with it
    mutated off, a hung peer wedges the collective forever — DL301, the
    reference's documented failure mode (SURVEY.md §5)."""
    if backend == "host":
        scripts = {
            "R": [_rcv("K1", "up"), _rcv("K2", "up"),
                  _snd("K1", "down"), _snd("K2", "down")],
            "K1": [_snd("R", "up"), _rcv("R", "down")],
            "K2": [_snd("R", "up"), _rcv("R", "down")],
        }
        groups = {"R": "root", "K1": "kid1", "K2": "kid2"}
        return _script_model(
            f"backend_sync[{backend}]", scripts, groups,
            crashable=("kid2",),
            timeout_ranks=("R", "K1", "K2") if host_timeouts else ())
    if backend == "hybrid":
        scripts = {
            "D0": [_snd("H0", "shards"), _rcv("H0", "reduced")],
            "H0": [_rcv("D0", "shards"), _rcv("H1", "up"),
                   _snd("H1", "down"), _snd("D0", "reduced")],
            "D1": [_snd("H1", "shards"), _rcv("H1", "reduced")],
            "H1": [_rcv("D1", "shards"), _snd("H0", "up"),
                   _rcv("H0", "down"), _snd("D1", "reduced")],
        }
        groups = {"D0": "host0", "H0": "host0",
                  "D1": "host1", "H1": "host1"}
        return _script_model(
            f"backend_sync[{backend}]", scripts, groups,
            crashable=("host1",),
            timeout_ranks=("H0", "H1") if host_timeouts else ())
    raise ValueError(f"unknown backend {backend!r} (host or hybrid)")


# ---------------------------------------------------------------------------
# Exactly-once replay (DL303).

def replay_model(*, ledger: bool = True, stripes: int = 2) -> ModelSpec:
    """Rejoin-with-replay under a lossy final ack.

    The client holds one pending delta (seq 1) striped over ``stripes``
    stripes; the crash that forced the rejoin landed mid-apply, so
    stripe 0 is nondeterministically already in the server's ledger.
    The final ack may be dropped once — the client then re-runs the
    WHOLE rejoin (at-least-once delivery), and the applied-seq ledger
    (``_record_applied`` / the ``need`` computation in ``_readmit``) is
    the only thing preventing the retry from double-applying.
    ``ledger=False`` models dropping the ``_record_applied`` write.

    State: ``(phase, need, ledger[i], applied_count[i], ack_drops)``.
    Invariant DL303: no stripe's applied count ever exceeds 1.
    """
    n = stripes
    SEQ = 1

    # phase: "announce" | ("send", need-tuple) | "await_ack" | "done"
    init = ("announce", (0,) * n, (0,) * n, 1, False)
    # (phase, ledger, applied_counts, ack_drops_left, forked)
    # ``forked`` False until the initial nondeterministic choice of how
    # far the pre-crash apply got (stripe 0 applied or not).

    def actions(state):
        phase, led, cnt, drops, forked = state
        acts = []
        if not forked:
            # the crash that caused this rejoin: the interrupted apply
            # either never recorded stripe 0, or recorded it durably
            acts.append(("pre-crash apply recorded nothing",
                         ("announce", led, cnt, drops, True)))
            led2 = (SEQ,) + led[1:]
            cnt2 = (1,) + cnt[1:]
            acts.append(("pre-crash apply recorded stripe 0",
                         ("announce", led2, cnt2, drops, True)))
            return acts
        if phase == "announce":
            need = tuple(i for i in range(n) if led[i] < SEQ)
            if need:
                acts.append((f"server: Rejoin reply, need stripes "
                             f"{list(need)}",
                             (("send", need), led, cnt, drops, True)))
            else:
                acts.append(("server: Rejoin reply, ledger already has "
                             "seq 1 -> nothing to replay, ack",
                             ("done", led, cnt, drops, True)))
        elif isinstance(phase, tuple) and phase[0] == "send":
            need = phase[1]
            i = need[0]
            ncnt = cnt[:i] + (cnt[i] + 1,) + cnt[i + 1:]
            nled = (led[:i] + (SEQ,) + led[i + 1:]) if ledger else led
            rest = need[1:]
            nphase = ("send", rest) if rest else "await_ack"
            acts.append((f"client: replay stripe {i}; server applies"
                         + ("" if ledger
                            else " (ledger write DROPPED)"),
                         (nphase, nled, ncnt, drops, True)))
        elif phase == "await_ack":
            acts.append(("server: replay ack delivered",
                         ("done", led, cnt, drops, True)))
            if drops > 0:
                acts.append(("fault: replay ack dropped -> client "
                             "retries the whole rejoin",
                             ("announce", led, cnt, drops - 1, True)))
        return acts

    def invariant(state):
        _phase, _led, cnt, _drops, _forked = state
        out = []
        for i, c in enumerate(cnt):
            if c > 1:
                out.append((
                    "DL303",
                    f"stripe {i} of (client, seq {SEQ}) applied {c} times "
                    "— the replay retry was not deduplicated by the "
                    "applied-seq ledger"))
        return out

    return ModelSpec("replay", init, actions, invariant,
                     lambda s: s[0] == "done")


# ---------------------------------------------------------------------------
# HA failover epoch fence (DL302).

def failover_model(*, fence: bool = True) -> ModelSpec:
    """Zombie-primary failover (docs/HA.md).

    Primary P serves epoch 1; standby T promotes to epoch 2 once P goes
    dark.  P may be a ZOMBIE — paused (GC stall, partition), not dead —
    and resume serving later.  A client that has synced with the
    promoted center announces ``epoch=2`` on every dial; the fence
    (``_refuse_stale``/``StaleCenterError``) is what makes the resumed
    stale primary refuse instead of applying a delta the fleet has moved
    past.  ``fence=False`` models deleting that epoch comparison.

    State: ``(seen_epoch, p_status, t_promoted, p_fenced, stale_applied,
    pause_budget, attempts_left)``.  Invariant DL302: ``stale_applied``
    never becomes True.
    """
    P_EPOCH, T_EPOCH = 1, 2
    # p_status: "serving" | "zombie"
    init = (0, "serving", False, False, False, 1, 3)

    def actions(state):
        seen, p, t_prom, p_fenced, stale, pause, tries = state
        acts = []
        if tries > 0:
            if p == "serving" and not p_fenced:
                if seen > P_EPOCH:
                    if fence:
                        acts.append((
                            "client dials P (epoch 1) announcing epoch "
                            f"{seen}; P refuses stale, client drops P "
                            "from its dial list",
                            (seen, p, t_prom, True, stale, pause,
                             tries - 1)))
                    else:
                        acts.append((
                            "client dials P (epoch 1) announcing epoch "
                            f"{seen}; P has NO fence and applies the "
                            "delta", (seen, p, t_prom, p_fenced, True,
                                      pause, tries - 1)))
                else:
                    acts.append((
                        "client syncs with P; delta applied at epoch 1",
                        (P_EPOCH, p, t_prom, p_fenced, stale, pause,
                         tries - 1)))
            if t_prom:
                acts.append((
                    "client fails over to promoted T; delta applied at "
                    "epoch 2", (T_EPOCH, p, t_prom, p_fenced, stale,
                                pause, tries - 1)))
        if pause > 0 and p == "serving":
            acts.append(("fault: P pauses (zombie)",
                         (seen, "zombie", t_prom, p_fenced, stale,
                          pause - 1, tries)))
        if p == "zombie":
            acts.append(("P resumes from the pause, still epoch 1",
                         (seen, "serving", t_prom, p_fenced, stale,
                          pause, tries)))
            if not t_prom:
                acts.append(("standby T misses P's probe twice and "
                             "promotes to epoch 2",
                             (seen, p, True, p_fenced, stale, pause,
                              tries)))
        return acts

    def invariant(state):
        seen, _p, _t, _fenced, stale, _pause, _tries = state
        if stale:
            return [("DL302",
                     "a center whose epoch is behind the client's newest "
                     f"synced epoch ({seen}) applied a delta — the zombie "
                     "primary mutated state the fleet has moved past")]
        return []

    return ModelSpec("failover", init, actions, invariant,
                     lambda s: s[6] == 0)


# ---------------------------------------------------------------------------
# Serve slot/page accounting (DL304).

def serve_model(*, finish_on_evict: bool = True, slots: int = 2,
                pages: int = 4, need: int = 2,
                max_new: int = 2) -> ModelSpec:
    """Scheduler/engine resource conservation under every event order.

    Two requests flow through submit -> admit -> tick* -> finish, with
    the nondeterministic faults the serve loop must absorb: a deadline
    expiring while queued OR running, and a client disconnect
    (``cancel``) at any point.  The scheduler and the engine keep
    SEPARATE books — scheduler ``running: rid -> slot``, engine
    ``busy slots + free pages`` — and every path that removes a running
    request must call ``engine.finish(slot)`` exactly once.
    ``finish_on_evict=False`` models ``_expire``/``cancel`` forgetting
    that call (the classic slot/page leak).

    State: ``(reqs, queue, running, engine_busy, pages_free)`` where
    ``reqs[i]`` is a per-request status and ``running[i]`` the slot+
    emitted-count when decoding.  Invariant DL304: engine busy slots ==
    scheduler-owned slots, and free pages account for exactly the busy
    slots' pages.
    """
    NREQ = 2
    # per-request status: "new" | "queued" | ("run", slot, emitted)
    #                   | "done" | "evicted"
    # state: (reqs, fifo (queued request ids in order),
    #         engine busy (sorted tuple of (slot, pages)), pages_free)
    init = (("new",) * NREQ, (), (), pages)

    def _set(reqs, i, v):
        return reqs[:i] + (v,) + reqs[i + 1:]

    def actions(state):
        reqs, fifo, busy, free = state
        acts = []
        busy_slots = {s for s, _ in busy}
        for i in range(NREQ):
            st = reqs[i]
            if st == "new":
                acts.append((f"client submits r{i}",
                             (_set(reqs, i, "queued"), fifo + (i,), busy,
                              free)))
            elif st == "queued":
                # deadline expiry while queued: dropped from the queue,
                # engine never involved
                acts.append((f"r{i} deadline expires while queued",
                             (_set(reqs, i, "evicted"),
                              tuple(j for j in fifo if j != i), busy,
                              free)))
                # disconnect == cancel wherever it is
                acts.append((f"client of r{i} disconnects (queued)",
                             (_set(reqs, i, "evicted"),
                              tuple(j for j in fifo if j != i), busy,
                              free)))
            elif isinstance(st, tuple):  # running
                slot = st[1]
                for why in ("deadline expires", "client disconnects"):
                    nbusy = busy
                    nfree = free
                    if finish_on_evict:
                        nbusy = tuple(sorted((s, p) for s, p in busy
                                             if s != slot))
                        nfree = free + need
                    acts.append((
                        f"r{i} {why} while decoding -> evict"
                        + ("" if finish_on_evict
                           else " (engine.finish call MISSING)"),
                        (_set(reqs, i, "evicted"), fifo, nbusy, nfree)))
        # scheduler round pieces, each its own interleavable action:
        if fifo:
            head = fifo[0]
            if reqs[head] == "queued" and free >= need:
                slot = min(set(range(slots)) - busy_slots, default=None)
                if slot is not None:
                    acts.append((
                        f"scheduler admits r{head} into slot {slot}",
                        (_set(reqs, head, ("run", slot, 0)), fifo[1:],
                         tuple(sorted(busy + ((slot, need),))),
                         free - need)))
        running = [(i, reqs[i]) for i in range(NREQ)
                   if isinstance(reqs[i], tuple)]
        if running:
            nreqs, nbusy, nfree = reqs, busy, free
            finished = []
            for i, (_tag, slot, emitted) in running:
                if emitted + 1 >= max_new:
                    nreqs = _set(nreqs, i, "done")
                    nbusy = tuple(sorted((s, p) for s, p in nbusy
                                         if s != slot))
                    nfree += need
                    finished.append(i)
                else:
                    nreqs = _set(nreqs, i, ("run", slot, emitted + 1))
            lab = "engine ticks; every active slot emits one token"
            if finished:
                lab += ("; " + ", ".join(f"r{i}" for i in finished)
                        + " complete(s) -> engine.finish")
            acts.append((lab, (nreqs, fifo, nbusy, nfree)))
        return acts

    def invariant(state):
        reqs, _fifo, busy, free = state
        out = []
        owned = {st[1] for st in reqs if isinstance(st, tuple)}
        busy_slots = {s for s, _ in busy}
        orphans = busy_slots - owned
        if orphans:
            held = sum(p for s, p in busy if s in orphans)
            out.append((
                "DL304",
                f"engine slot(s) {sorted(orphans)} still hold {held} "
                "page(s) but no scheduler-tracked request owns them — "
                "an eviction path skipped engine.finish and the pages "
                "leak forever"))
        if owned - busy_slots:
            out.append((
                "DL304",
                f"scheduler tracks request(s) in slot(s) "
                f"{sorted(owned - busy_slots)} the engine considers "
                "free — double-finish or admission bookkeeping bug"))
        if free + sum(p for _s, p in busy) != pages:
            out.append((
                "DL304",
                f"page conservation broken: {free} free + "
                f"{sum(p for _s, p in busy)} held != {pages} total"))
        return out

    def is_terminal(state):
        reqs, _fifo, _busy, _free = state
        return all(st in ("done", "evicted") for st in reqs)

    return ModelSpec("serve", init, actions, invariant, is_terminal)


# ---------------------------------------------------------------------------
# Elastic membership: join fence (DL302), leave flush (DL303), weight
# renormalization (DL304).

def membership_model(*, join_fence: bool = True, leave_flush: bool = True,
                     renorm: bool = True) -> ModelSpec:
    """Elastic join/leave under every interleaving of a member's last
    in-flight delta, a joiner's handshake, and a graceful leave.

    Two participants: founding member M (weight 2 — the whole mass of a
    ``num_nodes=2`` normalization budget) and joiner J.  M may push one
    delta (seq 1) whose server-side apply is IN FLIGHT — the worker
    thread holds it — and may then leave gracefully; the delta may also
    be LOST to a connection cut before the apply lands, which is what
    makes the leave-replay path (``need=[1]``) real.  J joins, adopts
    the center, and pushes a delta of its own.

    The three guards under test, each with a seeded mutation:

    * ``join_fence``  — J is registered as a member (deltas accepted)
      only AFTER it acked adoption of the streamed center
      (``_handle_join`` calls ``_register_member`` after ``_expect(new,
      ACK)``).  ``join_fence=False`` registers J at the Join? receipt,
      so the server can apply a delta from a client that never adopted
      the center — DL302.  Note J's adopted center legitimately going
      stale later (M's delta lands after J adopted) is NOT a violation;
      that is ordinary EASGD staleness.
    * ``leave_flush`` — ``_handle_leave`` calls ``_wait_cid_idle``
      before reading the applied-seq ledger.  ``leave_flush=False``
      reads the ledger while M's apply is still in flight: the ledger
      says seq 1 never landed, the leave replay applies it, and the
      worker's apply lands too — the delta counts twice, DL303.  (The
      ledger is monotonic-max bookkeeping; workers do NOT consult it
      before applying, so the wait is the only guard.)
    * ``renorm``      — every membership change recomputes
      ``_delta_weight`` denominators so live capacity weights sum to
      the fixed budget.  ``renorm=False`` hands J its raw weight
      without renormalizing the fleet (sum 4 != 2) — DL304.

    State: ``(j_phase, j_member, j_base, j_pushed, m_phase, m_seq,
    m_inflight, m_led, m_cnt, center_v, w_m, w_j, stale)``.
    """
    BUDGET = 2  # total weight mass: num_nodes x capacity 1.0

    # j_phase: "out" | "joining" | "member";  j_base: center version J
    # adopted, -1 = never adopted.  m_phase: "idle" | "leaving" |
    # "flush" | "gone".
    init = ("out", False, -1, False, "idle", 0, False, 0, 0, 0,
            BUDGET, 0, False)

    def _renorm_weights(m_alive: bool, j_member: bool) -> "tuple[int, int]":
        live = int(m_alive) + int(j_member)
        share = BUDGET // live if live else 0
        return (share if m_alive else 0, share if j_member else 0)

    def actions(state):
        (jp, jm, jb, jpu, mp, mseq, minf, mled, mcnt, cv,
         wm, wj, stale) = state
        m_alive = mp != "gone"
        acts = []

        # --- joiner J -----------------------------------------------------
        if jp == "out":
            if join_fence:
                acts.append(("J dials Join?; server assigns cid, streams "
                             "center (registration deferred to ACK)",
                             ("joining", jm, jb, jpu, mp, mseq, minf, mled,
                              mcnt, cv, wm, wj, stale)))
            else:
                nwm, nwj = ((wm if m_alive else 0, BUDGET)
                            if not renorm else
                            _renorm_weights(m_alive, True))
                acts.append(("J dials Join?; server REGISTERS J before the "
                             "center adoption ACK (join fence dropped)",
                             ("joining", True, jb, jpu, mp, mseq, minf, mled,
                              mcnt, cv, nwm, nwj, stale)))
        elif jp == "joining":
            if join_fence:
                if renorm:
                    nwm, nwj = _renorm_weights(m_alive, True)
                else:
                    nwm, nwj = (wm if m_alive else 0), BUDGET
                lab = (f"J ACKs center adoption (version {cv}); server "
                       "registers J"
                       + ("" if renorm
                          else " at RAW weight (renormalization dropped)"))
                acts.append((lab,
                             ("member", True, cv, jpu, mp, mseq, minf, mled,
                              mcnt, cv, nwm, nwj, stale)))
            else:
                acts.append((f"J ACKs center adoption (version {cv})",
                             ("member", jm, cv, jpu, mp, mseq, minf, mled,
                              mcnt, cv, wm, wj, stale)))
        if jm and not jpu:
            nstale = stale or jb < 0
            lab = ("server worker applies J's delta"
                   + (" — J NEVER ADOPTED the center" if jb < 0 else
                      f" (J's base: center version {jb})"))
            acts.append((lab,
                         (jp, jm, jb, True, mp, mseq, minf, mled, mcnt,
                          cv + 1, wm, wj, nstale)))

        # --- member M -----------------------------------------------------
        if mp == "idle" and mseq == 0:
            acts.append(("M pushes delta seq 1; server worker now holds "
                         "it in flight",
                         (jp, jm, jb, jpu, mp, 1, True, mled, mcnt, cv,
                          wm, wj, stale)))
        if minf:
            acts.append(("server worker applies M's in-flight delta "
                         "seq 1; ledger records 1",
                         (jp, jm, jb, jpu, mp, mseq, False, 1, mcnt + 1,
                          cv + 1, wm, wj, stale)))
            acts.append(("fault: M's conn cut before the apply — the "
                         "in-flight delta is lost, ledger unchanged",
                         (jp, jm, jb, jpu, mp, mseq, False, mled, mcnt,
                          cv, wm, wj, stale)))
        if mp == "idle":
            acts.append(("M sends Leave? claiming seq "
                         f"{mseq}", (jp, jm, jb, jpu, "leaving", mseq, minf,
                                     mled, mcnt, cv, wm, wj, stale)))
        elif mp == "leaving":
            if not minf or not leave_flush:
                need = mled < mseq
                if need:
                    lab = ("server reads ledger (applied "
                           f"{mled} < claimed {mseq}) -> need=[1]"
                           + ("" if not minf else
                              " while M's apply is STILL IN FLIGHT "
                              "(leave flush dropped)"))
                    acts.append((lab,
                                 (jp, jm, jb, jpu, "flush", mseq, minf,
                                  mled, mcnt, cv, wm, wj, stale)))
                else:
                    nwm, nwj = _renorm_weights(False, jm)
                    acts.append(("server reads ledger (nothing owed), "
                                 "removes M, renormalizes survivors",
                                 (jp, jm, jb, jpu, "gone", mseq, minf,
                                  mled, mcnt, cv, nwm,
                                  nwj if jm else wj, stale)))
            # else: _wait_cid_idle blocks the leave until the worker or
            # the fault clears the in-flight apply (both enabled above).
        elif mp == "flush":
            nwm, nwj = _renorm_weights(False, jm)
            acts.append(("leave replay applies seq 1; server removes M, "
                         "renormalizes survivors",
                         (jp, jm, jb, jpu, "gone", mseq, minf, 1,
                          mcnt + 1, cv + 1, nwm,
                          nwj if jm else wj, stale)))
        return acts

    def invariant(state):
        (jp, jm, jb, jpu, mp, _mseq, _minf, _mled, mcnt, _cv,
         wm, wj, stale) = state
        out = []
        if stale:
            out.append((
                "DL302",
                "the server applied a delta from a joiner that never "
                "adopted the center — the join fence (register only "
                "after the adoption ACK) is missing"))
        if mcnt > 1:
            out.append((
                "DL303",
                f"M's delta seq 1 applied {mcnt} times — the graceful "
                "leave read the applied-seq ledger without waiting out "
                "the in-flight apply, so the leave replay and the "
                "worker both landed it"))
        live = ([wm] if mp != "gone" else []) + ([wj] if jm else [])
        if live and sum(live) != BUDGET:
            out.append((
                "DL304",
                f"live capacity weights sum to {sum(live)}, not the "
                f"fleet budget {BUDGET} — a membership change skipped "
                "the weight renormalization and the elastic average is "
                "biased"))
        return out

    def is_terminal(state):
        (jp, jm, _jb, jpu, mp, _mseq, minf, _mled, _mcnt, _cv,
         _wm, _wj, _stale) = state
        return mp == "gone" and jp == "member" and jpu and not minf

    return ModelSpec("membership", init, actions, invariant, is_terminal)


# ---------------------------------------------------------------------------
# The serving-fleet router (serve/router.py): dispatch, retry-on-death,
# shed, hedge, epoch fence — deadlock-free (DL301), never splicing two
# center epochs into one stream (DL302), at-most-once per replica
# (DL303).

def router_model(*, retry: bool = True, fence: bool = True,
                 single_dispatch: bool = True) -> ModelSpec:
    """Fleet router request lifecycle (``serve/router.py``): one request
    against two replicas A/B, each of which may die at any point, shed a
    dispatch (queue full), or hot-swap its center epoch mid-run
    (``serve.server._maybe_swap``).  The router moves exactly as
    ``Router.generate`` does: dispatch to an untried live replica,
    resubmit only requests that never produced a token, hedge off a slow
    replica by CANCELLING its queued copy first, surface a clean
    terminal when every replica was tried, and fence the stream on the
    'R'-chunk epoch echo.

    The three guards under test, each with a seeded mutation:

    * ``retry``           — a replica that dies holding a
      queued-not-yet-prefilled request triggers resubmission to a
      survivor.  ``retry=False`` leaves the request parked on the dead
      replica forever: once the environment's remaining actions
      exhaust, the state has no successor and is not terminal — DL301.
    * ``fence``           — the first chunk pins the stream's epoch and
      a later chunk with a different value terminates the stream
      (clean ``failed``).  ``fence=False`` delivers it: one completion
      spliced from two model versions — DL302.
    * ``single_dispatch`` — the tried-set plus hedge-cancel keep
      execution at-most-once per replica.  ``single_dispatch=False``
      hedges WITHOUT cancelling and forgets the replica was tried, so
      a later dispatch hands the same replica a second live copy —
      DL303.

    State: ``(phase, owner, first_ep, mixed, ((up, ep, copies, tried)
    per replica))``.
    """
    names = ("A", "B")
    init = ("new", -1, -1, False, ((True, 0, 0, False),
                                   (True, 0, 0, False)))

    def _set(reps, i, **kw):
        up, ep, cp, tr = reps[i]
        rep = (kw.get("up", up), kw.get("ep", ep),
               kw.get("copies", cp), kw.get("tried", tr))
        return tuple(rep if j == i else reps[j] for j in range(2))

    def actions(state):
        phase, owner, first_ep, mixed, reps = state
        if phase in ("done", "failed", "shed"):
            return []
        acts = []
        # environment: replica deaths and hot swaps, in every order
        for i in range(2):
            up, ep, _cp, _tr = reps[i]
            if up:
                acts.append((f"fault: replica {names[i]} dies",
                             (phase, owner, first_ep, mixed,
                              _set(reps, i, up=False))))
                if ep == 0:
                    acts.append((
                        f"replica {names[i]} hot-swaps to epoch 1",
                        (phase, owner, first_ep, mixed,
                         _set(reps, i, ep=1))))
        if phase == "new":
            cand = [i for i in range(2) if reps[i][0] and not reps[i][3]]
            for i in cand:
                acts.append((
                    f"router dispatches to {names[i]}; it ACCEPTS "
                    "(copy queued)",
                    # copies clamp at 2: one over the at-most-once bound
                    # witnesses the violation; an unbounded counter would
                    # make the mutated model's state space infinite
                    ("queued", i, first_ep, mixed,
                     _set(reps, i, copies=min(reps[i][2] + 1, 2),
                          tried=True))))
                acts.append((
                    f"router dispatches to {names[i]}; it SHEDS "
                    "(queue full, retry_after)",
                    ("new", -1, first_ep, mixed,
                     _set(reps, i, tried=True))))
            if not cand:
                acts.append((
                    "router surfaces RouterBusy/ReplicaDead: every "
                    "replica tried or dead",
                    ("shed", -1, first_ep, mixed, reps)))
        elif phase == "queued":
            up, ep, cp, _tr = reps[owner]
            if up:
                acts.append((
                    f"replica {names[owner]} prefills: first chunk pins "
                    f"stream epoch {ep}",
                    ("streaming", owner, ep, mixed, reps)))
                if single_dispatch:
                    if any(reps[j][0] and not reps[j][3]
                           for j in range(2) if j != owner):
                        acts.append((
                            "hedge: router cancels the queued copy on "
                            f"{names[owner]} (conn close) and resubmits",
                            ("new", -1, first_ep, mixed,
                             _set(reps, owner, copies=cp - 1))))
                else:
                    acts.append((
                        "hedge WITHOUT cancel: router forgets it tried "
                        f"{names[owner]}, old copy still queued there "
                        "(single-dispatch guard dropped)",
                        ("new", -1, first_ep, mixed,
                         _set(reps, owner, tried=False))))
            elif retry:
                acts.append((
                    f"router detects {names[owner]} died before the "
                    "first token: resubmits to a survivor",
                    ("new", -1, first_ep, mixed, reps)))
            # retry dropped: no router action — the request wedges on
            # the dead replica (the seeded DL301)
        elif phase == "streaming":
            up, ep, _cp, _tr = reps[owner]
            if up:
                if ep == first_ep:
                    acts.append((
                        f"replica {names[owner]} streams to completion "
                        "(epoch stable)",
                        ("done", owner, first_ep, mixed, reps)))
                elif fence:
                    acts.append((
                        f"chunk carries epoch {ep} != pinned {first_ep}:"
                        " router fences the stream (clean failed chunk)",
                        ("failed", owner, first_ep, mixed, reps)))
                else:
                    acts.append((
                        f"chunk carries epoch {ep} != pinned {first_ep} "
                        "and the router DELIVERS it (fence dropped)",
                        ("done", owner, first_ep, True, reps)))
            else:
                acts.append((
                    f"replica {names[owner]} died mid-stream: router "
                    "returns a clean terminal failed chunk (no resubmit"
                    " — tokens already flowed)",
                    ("failed", owner, first_ep, mixed, reps)))
        return acts

    def invariant(state):
        _phase, _owner, _first_ep, mixed, reps = state
        out = []
        if mixed:
            out.append((
                "DL302",
                "one stream delivered chunks from two center epochs — "
                "the router's fence over the 'R'-chunk epoch echo is "
                "missing and a completion spliced two model versions"))
        for i in range(2):
            if reps[i][2] > 1:
                out.append((
                    "DL303",
                    f"replica {names[i]} holds {reps[i][2]} live copies "
                    "of one request — a resubmission skipped the "
                    "tried-set/hedge-cancel guard, so execution is no "
                    "longer at-most-once per replica"))
        return out

    def is_terminal(state):
        return state[0] in ("done", "failed", "shed")

    return ModelSpec("router", init, actions, invariant, is_terminal)


# ---------------------------------------------------------------------------
# Repo-facing entries.

def builtin_models() -> list[ModelSpec]:
    """The shipped models in their faithful (unmutated) configuration."""
    return [sync_model(), sharded_model(), replay_model(),
            failover_model(), serve_model(), membership_model(),
            router_model(), backend_sync_model(backend="host"),
            backend_sync_model(backend="hybrid")]


def lint_models() -> "list[tuple[ModelReport, ModelSpec]]":
    """Check every builtin model; returns ``(report, spec)`` pairs."""
    return [(check_model(spec), spec) for spec in builtin_models()]
