"""Schedule↔code conformance (rule DL310).

The ``async_ea_*_schedule`` builders in ``lint/protocol.py`` are
hand-written transcriptions of the blocking send/recv sequences in
``parallel/async_ea.py`` — which means they can silently drift from the
code they claim to model, and every DL101/DL104 verdict downstream of a
drifted schedule is a verdict about a protocol nobody runs.  This module
pins the two together:

* **Tag vocabulary** — every send/recv tag a schedule uses must be bound
  in :data:`TAG_BINDINGS` to its origin: a wire-protocol constant in
  ``async_ea.py`` (existence AND value are checked against the module
  source, so renaming ``DELTA_Q`` or changing its string breaks
  conformance, not just the schedules), a reply-dict key (``stale``), a
  tensor/packed stream leg, or a synthetic scheduling marker (``go``).
  An unbound tag — the classic "edited the schedule, not the code"
  mutation — is DL310.
* **Usage evidence** — each bound constant must actually be *used* (a
  ``Load`` beyond its definition) in ``async_ea.py``, and the handshake
  call sites the schedules transcribe must exist: ``_rejoin_handshake``
  sends ``ACK``, ``_replay_exchange`` opens with a ``REPLAY_Q`` dict
  send, ``_refuse_stale`` sends a reply carrying the ``stale`` key.
* **Question order** — ``sync_client`` sends ``Center?`` before
  ``delta?`` (the fetch-then-push EASGD round).  The first-send order is
  extracted from the code's AST and every schedule rank that sends both
  must agree — swapping ``client_order`` in a schedule (or the code) is
  DL310 here before it is a DL104 desync in the simulator.
* **Coverage** — every ``*_Q`` message-type constant the code defines
  must appear in some schedule, except those in
  :data:`KNOWN_UNMODELED` (with a recorded reason), so a NEW message
  type cannot ship without either a schedule or a conscious exemption.
* **Trace-context field** — the optional cross-process trace context
  (docs/OBSERVABILITY.md) rides dict messages under
  ``obs.trace.TRACE_KEY``; its value is pinned to ``"tc"`` (renaming it
  breaks mixed-fleet interop with peers already on the wire) and
  ``async_ea.py`` must show usage evidence — the ``_announce`` stamp
  and the ``_admit`` adoption read the constant, not a literal.

``lint_conformance(schedules=..., source=...)`` accepts overrides so the
seeded-mutation tests can feed in an edited schedule or edited module
source and assert DL310 fires.
"""

from __future__ import annotations

import ast
import inspect
from typing import Mapping

from distlearn_tpu.lint.core import Finding

__all__ = ["lint_conformance", "TAG_BINDINGS", "KNOWN_UNMODELED"]

#: tag -> (kind, detail).  Kinds:
#:   "const"     — wire constant in async_ea.py; detail = const name;
#:                 value must equal the tag exactly
#:   "const_ci"  — same, but schedules use the wire's lowercase form
#:   "key"       — reply-dict key; detail = the key literal
#:   "stream"    — tensor/packed payload leg, no msg-tag constant
#:   "synthetic" — scheduling marker with no wire message at all
TAG_BINDINGS: dict = {
    "Enter?": ("const", "ENTER_Q"),
    "Enter": ("const", "ENTER"),
    "Center?": ("const", "CENTER_Q"),
    "delta?": ("const", "DELTA_Q"),
    "delta": ("const", "DELTA"),
    "Rejoin?": ("const", "REJOIN_Q"),
    "Rejoin": ("const", "REJOIN"),
    "Shard?": ("const", "SHARD_Q"),
    "Replay": ("const", "REPLAY_Q"),
    "Join?": ("const", "JOIN_Q"),
    "Join": ("const", "JOIN"),
    "Leave?": ("const", "LEAVE_Q"),
    "Leave": ("const", "LEAVE"),
    "ack": ("const_ci", "ACK"),
    "stale": ("key", "stale"),
    "center": ("stream", "per-leaf center tensor leg (send_tensors)"),
    "center_p": ("stream", "packed center frame (send_packed)"),
    "delta_t": ("stream", "per-leaf delta tensor leg"),
    "delta_p": ("stream", "packed delta frame"),
    "replay_p": ("stream", "replay stripe payload frame"),
    "go": ("synthetic", "client-side thread fan-out marker — models the "
                        "stripe-leg spawn order, never hits the wire"),
}

#: ``*_Q`` message types the code defines that no schedule models, each
#: with the reason the gap is deliberate.
KNOWN_UNMODELED: dict = {
    "TEST_Q": "test_net() is a standalone health RPC, not part of any "
              "sync/rejoin/failover round the schedules transcribe",
}

#: (function, constant) send call sites the schedules transcribe.
_CALLSITE_EVIDENCE = (
    ("_rejoin_handshake", "ACK",
     "the rejoin center-stream ack leg (schedules' 'ack' after 'center')"),
    ("_replay_exchange", "REPLAY_Q",
     "the replay announcement (schedules' 'Replay' op)"),
    ("leave", "LEAVE_Q",
     "the graceful-leave announcement (the join/leave schedules' "
     "'Leave?' op)"),
)


class _CodeFacts(ast.NodeVisitor):
    """Module-level constants, per-name Load counts, and per-function
    ``send_msg`` call summaries for one module's AST."""

    def __init__(self):
        self.consts: dict[str, object] = {}
        self.loads: dict[str, int] = {}
        #: attribute-name -> Load count (``obs_trace.TRACE_KEY`` reads
        #: are Attribute nodes, invisible to the Name counter above)
        self.attr_loads: dict[str, int] = {}
        #: function name -> ordered list of send descriptors:
        #:   ("const", NAME) for send_msg(NAME)
        #:   ("keys", frozenset) for send_msg({...literal dict...})
        self.sends: dict[str, list] = {}
        self._func: list[str] = []

    def visit_Assign(self, node):
        if not self._func:
            for t in node.targets:
                if (isinstance(t, ast.Name)
                        and isinstance(node.value, ast.Constant)):
                    self.consts[t.id] = node.value.value
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        self._func.append(node.name)
        self.sends.setdefault(node.name, [])
        self.generic_visit(node)
        self._func.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Load):
            self.loads[node.id] = self.loads.get(node.id, 0) + 1
        self.generic_visit(node)

    def visit_Attribute(self, node):
        if isinstance(node.ctx, ast.Load):
            self.attr_loads[node.attr] = \
                self.attr_loads.get(node.attr, 0) + 1
        self.generic_visit(node)

    def _record_send(self, desc):
        # credit every enclosing scope: sync_client's wire traffic lives
        # in its _fetch/_push closures, and lexical definition order of
        # those closures matches their call order in the round
        for fname in self._func:
            self.sends[fname].append(desc)

    def visit_Call(self, node):
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr == "send_msg" and self._func
                and node.args):
            a = node.args[0]
            if isinstance(a, ast.Name):
                self._record_send(("const", a.id))
            elif isinstance(a, ast.Dict):
                keys, qconst = set(), None
                for k, v in zip(a.keys, a.values):
                    if isinstance(k, ast.Constant):
                        keys.add(k.value)
                        if (k.value == "q" and isinstance(v, ast.Name)):
                            qconst = v.id
                if qconst is not None:
                    self._record_send(("const", qconst))
                self._record_send(("keys", frozenset(keys)))
        self.generic_visit(node)


def _schedule_tags(sched: Mapping):
    """Yield (rank, op) for every op in a schedule dict."""
    for rank, ops in sched.items():
        for op in ops:
            yield rank, op


def _default_schedules() -> dict:
    from distlearn_tpu.lint import protocol
    out = {}
    for name in dir(protocol):
        if name.startswith("async_ea_") and name.endswith("_schedule"):
            out[name] = getattr(protocol, name)()
    return out


def lint_conformance(*, schedules: Mapping | None = None,
                     source: str | None = None) -> list[Finding]:
    """DL310 audit: diff every hand-written ``async_ea_*`` schedule
    against the wire constants and call sites in ``async_ea.py``.

    ``schedules`` maps schedule name -> per-rank op dict (default: every
    ``async_ea_*_schedule`` builder at its default arity); ``source``
    overrides the ``async_ea.py`` module source (mutation tests).
    """
    if schedules is None:
        schedules = _default_schedules()
    if source is None:
        from distlearn_tpu.parallel import async_ea
        source = inspect.getsource(async_ea)
    facts = _CodeFacts()
    facts.visit(ast.parse(source))
    findings: list[Finding] = []

    # -- 1. every schedule tag is bound, and const bindings hold ------------
    used_consts: set[str] = set()
    for sname, sched in schedules.items():
        for rank, op in _schedule_tags(sched):
            tag = op.tag
            binding = TAG_BINDINGS.get(tag)
            where = f"{sname}:{rank}"
            if binding is None:
                findings.append(Finding(
                    "DL310",
                    f"schedule op {op.kind}({op.peer!r}, {tag!r}) uses a "
                    f"tag bound to nothing in async_ea.py — the schedule "
                    f"drifted from the code (or the binding table needs "
                    f"a new entry with evidence)", where=where))
                continue
            kind, detail = binding
            if kind in ("const", "const_ci"):
                used_consts.add(detail)
                val = facts.consts.get(detail)
                if val is None:
                    findings.append(Finding(
                        "DL310",
                        f"tag {tag!r} is bound to constant {detail} which "
                        f"async_ea.py no longer defines", where=where))
                elif (str(val).lower() != tag.lower() if kind == "const_ci"
                      else val != tag):
                    findings.append(Finding(
                        "DL310",
                        f"tag {tag!r} is bound to {detail} but the code's "
                        f"value is {val!r} — schedule and wire protocol "
                        f"disagree", where=where))

    # -- 2. bound constants are actually used by the code -------------------
    for const in sorted(used_consts):
        if const in facts.consts and facts.loads.get(const, 0) < 1:
            findings.append(Finding(
                "DL310",
                f"wire constant {const} is defined but never used — the "
                f"schedules model a message the code no longer sends",
                where=f"async_ea.{const}"))

    # -- 3. transcribed call sites exist ------------------------------------
    for func, const, why in _CALLSITE_EVIDENCE:
        sends = facts.sends.get(func)
        if sends is None:
            findings.append(Finding(
                "DL310",
                f"function {func}() (transcribed by the schedules: {why}) "
                f"no longer exists in async_ea.py", where=f"async_ea.{func}"))
        elif ("const", const) not in sends:
            findings.append(Finding(
                "DL310",
                f"{func}() no longer sends {const} — schedules still "
                f"transcribe it ({why})", where=f"async_ea.{func}"))
    if not any("keys" == k and "stale" in keys
               for sends in facts.sends.values()
               for k, keys in sends):
        findings.append(Finding(
            "DL310",
            "no send_msg call carries the 'stale' reply key — the "
            "stale-epoch refusal the zombie-fence schedule models is gone "
            "from the code (_refuse_stale)", where="async_ea._refuse_stale"))

    # -- 4. question order: Center? before delta? ---------------------------
    # sync_client is a thin tau/trace gate around _sync_once, which owns
    # the round's wire traffic — scan both so the split stays honest
    client_sends = [c for fname in ("sync_client", "_sync_once")
                    for k, c in facts.sends.get(fname, ())
                    if k == "const"]
    code_order_ok = ("CENTER_Q" in client_sends and "DELTA_Q" in client_sends
                     and (client_sends.index("CENTER_Q")
                          < client_sends.index("DELTA_Q")))
    if not code_order_ok:
        findings.append(Finding(
            "DL310",
            "sync_client() no longer sends CENTER_Q before DELTA_Q — the "
            "fetch-then-push round order every schedule transcribes",
            where="async_ea.sync_client"))
    for sname, sched in schedules.items():
        for rank, ops in sched.items():
            tags = [op.tag for op in ops if op.kind == "send"]
            if "Center?" in tags and "delta?" in tags:
                if tags.index("Center?") > tags.index("delta?"):
                    findings.append(Finding(
                        "DL310",
                        f"rank sends delta? before Center? but "
                        f"sync_client() fetches the center first — the "
                        f"schedule models a question order the code "
                        f"never executes", where=f"{sname}:{rank}"))

    # -- 5. coverage: every *_Q message type is modeled or exempted ---------
    modeled = {d for t, (k, d) in TAG_BINDINGS.items()
               if k in ("const", "const_ci")}
    for name in sorted(facts.consts):
        if name.endswith("_Q") and name not in modeled \
                and name not in KNOWN_UNMODELED:
            findings.append(Finding(
                "DL310",
                f"message-type constant {name} has no schedule modeling "
                f"it and no KNOWN_UNMODELED exemption — new wire traffic "
                f"must be modeled or consciously exempted",
                where=f"async_ea.{name}"))

    # -- 6. trace-context frame field (docs/OBSERVABILITY.md) ---------------
    # The optional trace context rides dict messages under TRACE_KEY;
    # the documented wire format (and mixed-fleet interop) pins the key
    # to "tc", and async_ea.py must actually stamp/read it — a schedule
    # can't model an optional field, so the binding is evidence-only.
    from distlearn_tpu.obs import trace as _obs_trace
    if _obs_trace.TRACE_KEY != "tc":
        findings.append(Finding(
            "DL310",
            f"obs.trace.TRACE_KEY is {_obs_trace.TRACE_KEY!r} but the "
            f"documented wire format pins 'tc' — peers already in "
            f"flight would silently drop the renamed field",
            where="obs.trace.TRACE_KEY"))
    if facts.attr_loads.get("TRACE_KEY", 0) < 1:
        findings.append(Finding(
            "DL310",
            "async_ea.py never reads obs.trace.TRACE_KEY — the Enter? "
            "announce no longer stamps (and the admit path no longer "
            "adopts) the trace context the wire format documents",
            where="async_ea._announce"))
    return findings
