"""Schedule↔code conformance (rule DL310).

The ``async_ea_*_schedule`` builders in ``lint/protocol.py`` are
hand-written transcriptions of the blocking send/recv sequences in
``parallel/async_ea.py`` — which means they can silently drift from the
code they claim to model, and every DL101/DL104 verdict downstream of a
drifted schedule is a verdict about a protocol nobody runs.  This module
pins the two together:

* **Tag vocabulary** — every send/recv tag a schedule uses must be bound
  in :data:`TAG_BINDINGS` to its origin: a wire-protocol constant in
  ``async_ea.py`` (existence AND value are checked against the module
  source, so renaming ``DELTA_Q`` or changing its string breaks
  conformance, not just the schedules), a reply-dict key (``stale``), a
  tensor/packed stream leg, or a synthetic scheduling marker (``go``).
  An unbound tag — the classic "edited the schedule, not the code"
  mutation — is DL310.
* **Usage evidence** — each bound constant must actually be *used* (a
  ``Load`` beyond its definition) in ``async_ea.py``, and the handshake
  call sites the schedules transcribe must exist: ``_rejoin_handshake``
  sends ``ACK``, ``_replay_exchange`` opens with a ``REPLAY_Q`` dict
  send, ``_refuse_stale`` sends a reply carrying the ``stale`` key.
* **Question order** — ``sync_client`` sends ``Center?`` before
  ``delta?`` (the fetch-then-push EASGD round).  The first-send order is
  extracted from the code's AST and every schedule rank that sends both
  must agree — swapping ``client_order`` in a schedule (or the code) is
  DL310 here before it is a DL104 desync in the simulator.
* **Coverage** — every ``*_Q`` message-type constant the code defines
  must appear in some schedule, except those in
  :data:`KNOWN_UNMODELED` (with a recorded reason), so a NEW message
  type cannot ship without either a schedule or a conscious exemption.
* **Trace-context field** — the optional cross-process trace context
  (docs/OBSERVABILITY.md) rides dict messages under
  ``obs.trace.TRACE_KEY``; its value is pinned to ``"tc"`` (renaming it
  breaks mixed-fleet interop with peers already on the wire) and
  ``async_ea.py`` must show usage evidence — the ``_announce`` stamp
  and the ``_admit`` adoption read the constant, not a literal.

``lint_conformance(schedules=..., source=...)`` accepts overrides so the
seeded-mutation tests can feed in an edited schedule or edited module
source and assert DL310 fires.

Serve-frame field conformance (:func:`lint_serve_frames`) extends the
same discipline to the serving wire: every field the 'J' health-probe
reply, the 'G' generate request, and the 'R' stream chunk carry must be
bound in :data:`SERVE_FRAME_BINDINGS`, and every binding must still
show producer-or-consumer evidence in ``serve/server.py`` /
``serve/router.py`` / ``serve/client.py``.  The check is bidirectional:
a NEW field shipped without a binding is DL310 (undocumented wire
surface), and a binding whose field vanished from the code is DL310
stale (the table would lie to the next reader).
"""

from __future__ import annotations

import ast
import inspect
from typing import Mapping

from distlearn_tpu.lint.core import Finding

__all__ = ["lint_conformance", "lint_serve_frames", "TAG_BINDINGS",
           "KNOWN_UNMODELED", "SERVE_FRAME_BINDINGS"]

#: tag -> (kind, detail).  Kinds:
#:   "const"     — wire constant in async_ea.py; detail = const name;
#:                 value must equal the tag exactly
#:   "const_ci"  — same, but schedules use the wire's lowercase form
#:   "key"       — reply-dict key; detail = the key literal
#:   "stream"    — tensor/packed payload leg, no msg-tag constant
#:   "synthetic" — scheduling marker with no wire message at all
TAG_BINDINGS: dict = {
    "Enter?": ("const", "ENTER_Q"),
    "Enter": ("const", "ENTER"),
    "Center?": ("const", "CENTER_Q"),
    "delta?": ("const", "DELTA_Q"),
    "delta": ("const", "DELTA"),
    "Rejoin?": ("const", "REJOIN_Q"),
    "Rejoin": ("const", "REJOIN"),
    "Shard?": ("const", "SHARD_Q"),
    "Replay": ("const", "REPLAY_Q"),
    "Join?": ("const", "JOIN_Q"),
    "Join": ("const", "JOIN"),
    "Leave?": ("const", "LEAVE_Q"),
    "Leave": ("const", "LEAVE"),
    "ack": ("const_ci", "ACK"),
    "stale": ("key", "stale"),
    "center": ("stream", "per-leaf center tensor leg (send_tensors)"),
    "center_p": ("stream", "packed center frame (send_packed)"),
    "delta_t": ("stream", "per-leaf delta tensor leg"),
    "delta_p": ("stream", "packed delta frame"),
    "replay_p": ("stream", "replay stripe payload frame"),
    "go": ("synthetic", "client-side thread fan-out marker — models the "
                        "stripe-leg spawn order, never hits the wire"),
}

#: ``*_Q`` message types the code defines that no schedule models, each
#: with the reason the gap is deliberate.
KNOWN_UNMODELED: dict = {
    "TEST_Q": "test_net() is a standalone health RPC, not part of any "
              "sync/rejoin/failover round the schedules transcribe",
}

#: (function, constant) send call sites the schedules transcribe.
_CALLSITE_EVIDENCE = (
    ("_rejoin_handshake", "ACK",
     "the rejoin center-stream ack leg (schedules' 'ack' after 'center')"),
    ("_replay_exchange", "REPLAY_Q",
     "the replay announcement (schedules' 'Replay' op)"),
    ("leave", "LEAVE_Q",
     "the graceful-leave announcement (the join/leave schedules' "
     "'Leave?' op)"),
)


class _CodeFacts(ast.NodeVisitor):
    """Module-level constants, per-name Load counts, and per-function
    ``send_msg`` call summaries for one module's AST."""

    def __init__(self):
        self.consts: dict[str, object] = {}
        self.loads: dict[str, int] = {}
        #: attribute-name -> Load count (``obs_trace.TRACE_KEY`` reads
        #: are Attribute nodes, invisible to the Name counter above)
        self.attr_loads: dict[str, int] = {}
        #: function name -> ordered list of send descriptors:
        #:   ("const", NAME) for send_msg(NAME)
        #:   ("keys", frozenset) for send_msg({...literal dict...})
        self.sends: dict[str, list] = {}
        self._func: list[str] = []

    def visit_Assign(self, node):
        if not self._func:
            for t in node.targets:
                if (isinstance(t, ast.Name)
                        and isinstance(node.value, ast.Constant)):
                    self.consts[t.id] = node.value.value
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        self._func.append(node.name)
        self.sends.setdefault(node.name, [])
        self.generic_visit(node)
        self._func.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Load):
            self.loads[node.id] = self.loads.get(node.id, 0) + 1
        self.generic_visit(node)

    def visit_Attribute(self, node):
        if isinstance(node.ctx, ast.Load):
            self.attr_loads[node.attr] = \
                self.attr_loads.get(node.attr, 0) + 1
        self.generic_visit(node)

    def _record_send(self, desc):
        # credit every enclosing scope: sync_client's wire traffic lives
        # in its _fetch/_push closures, and lexical definition order of
        # those closures matches their call order in the round
        for fname in self._func:
            self.sends[fname].append(desc)

    def visit_Call(self, node):
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr == "send_msg" and self._func
                and node.args):
            a = node.args[0]
            if isinstance(a, ast.Name):
                self._record_send(("const", a.id))
            elif isinstance(a, ast.Dict):
                keys, qconst = set(), None
                for k, v in zip(a.keys, a.values):
                    if isinstance(k, ast.Constant):
                        keys.add(k.value)
                        if (k.value == "q" and isinstance(v, ast.Name)):
                            qconst = v.id
                if qconst is not None:
                    self._record_send(("const", qconst))
                self._record_send(("keys", frozenset(keys)))
        self.generic_visit(node)


def _schedule_tags(sched: Mapping):
    """Yield (rank, op) for every op in a schedule dict."""
    for rank, ops in sched.items():
        for op in ops:
            yield rank, op


def _default_schedules() -> dict:
    from distlearn_tpu.lint import protocol
    out = {}
    for name in dir(protocol):
        if name.startswith("async_ea_") and name.endswith("_schedule"):
            out[name] = getattr(protocol, name)()
    return out


def lint_conformance(*, schedules: Mapping | None = None,
                     source: str | None = None) -> list[Finding]:
    """DL310 audit: diff every hand-written ``async_ea_*`` schedule
    against the wire constants and call sites in ``async_ea.py``.

    ``schedules`` maps schedule name -> per-rank op dict (default: every
    ``async_ea_*_schedule`` builder at its default arity); ``source``
    overrides the ``async_ea.py`` module source (mutation tests).
    """
    if schedules is None:
        schedules = _default_schedules()
    if source is None:
        from distlearn_tpu.parallel import async_ea
        source = inspect.getsource(async_ea)
    facts = _CodeFacts()
    facts.visit(ast.parse(source))
    findings: list[Finding] = []

    # -- 1. every schedule tag is bound, and const bindings hold ------------
    used_consts: set[str] = set()
    for sname, sched in schedules.items():
        for rank, op in _schedule_tags(sched):
            tag = op.tag
            binding = TAG_BINDINGS.get(tag)
            where = f"{sname}:{rank}"
            if binding is None:
                findings.append(Finding(
                    "DL310",
                    f"schedule op {op.kind}({op.peer!r}, {tag!r}) uses a "
                    f"tag bound to nothing in async_ea.py — the schedule "
                    f"drifted from the code (or the binding table needs "
                    f"a new entry with evidence)", where=where))
                continue
            kind, detail = binding
            if kind in ("const", "const_ci"):
                used_consts.add(detail)
                val = facts.consts.get(detail)
                if val is None:
                    findings.append(Finding(
                        "DL310",
                        f"tag {tag!r} is bound to constant {detail} which "
                        f"async_ea.py no longer defines", where=where))
                elif (str(val).lower() != tag.lower() if kind == "const_ci"
                      else val != tag):
                    findings.append(Finding(
                        "DL310",
                        f"tag {tag!r} is bound to {detail} but the code's "
                        f"value is {val!r} — schedule and wire protocol "
                        f"disagree", where=where))

    # -- 2. bound constants are actually used by the code -------------------
    for const in sorted(used_consts):
        if const in facts.consts and facts.loads.get(const, 0) < 1:
            findings.append(Finding(
                "DL310",
                f"wire constant {const} is defined but never used — the "
                f"schedules model a message the code no longer sends",
                where=f"async_ea.{const}"))

    # -- 3. transcribed call sites exist ------------------------------------
    for func, const, why in _CALLSITE_EVIDENCE:
        sends = facts.sends.get(func)
        if sends is None:
            findings.append(Finding(
                "DL310",
                f"function {func}() (transcribed by the schedules: {why}) "
                f"no longer exists in async_ea.py", where=f"async_ea.{func}"))
        elif ("const", const) not in sends:
            findings.append(Finding(
                "DL310",
                f"{func}() no longer sends {const} — schedules still "
                f"transcribe it ({why})", where=f"async_ea.{func}"))
    if not any("keys" == k and "stale" in keys
               for sends in facts.sends.values()
               for k, keys in sends):
        findings.append(Finding(
            "DL310",
            "no send_msg call carries the 'stale' reply key — the "
            "stale-epoch refusal the zombie-fence schedule models is gone "
            "from the code (_refuse_stale)", where="async_ea._refuse_stale"))

    # -- 4. question order: Center? before delta? ---------------------------
    # sync_client is a thin tau/trace gate around _sync_once, which owns
    # the round's wire traffic — scan both so the split stays honest
    client_sends = [c for fname in ("sync_client", "_sync_once")
                    for k, c in facts.sends.get(fname, ())
                    if k == "const"]
    code_order_ok = ("CENTER_Q" in client_sends and "DELTA_Q" in client_sends
                     and (client_sends.index("CENTER_Q")
                          < client_sends.index("DELTA_Q")))
    if not code_order_ok:
        findings.append(Finding(
            "DL310",
            "sync_client() no longer sends CENTER_Q before DELTA_Q — the "
            "fetch-then-push round order every schedule transcribes",
            where="async_ea.sync_client"))
    for sname, sched in schedules.items():
        for rank, ops in sched.items():
            tags = [op.tag for op in ops if op.kind == "send"]
            if "Center?" in tags and "delta?" in tags:
                if tags.index("Center?") > tags.index("delta?"):
                    findings.append(Finding(
                        "DL310",
                        f"rank sends delta? before Center? but "
                        f"sync_client() fetches the center first — the "
                        f"schedule models a question order the code "
                        f"never executes", where=f"{sname}:{rank}"))

    # -- 5. coverage: every *_Q message type is modeled or exempted ---------
    modeled = {d for t, (k, d) in TAG_BINDINGS.items()
               if k in ("const", "const_ci")}
    for name in sorted(facts.consts):
        if name.endswith("_Q") and name not in modeled \
                and name not in KNOWN_UNMODELED:
            findings.append(Finding(
                "DL310",
                f"message-type constant {name} has no schedule modeling "
                f"it and no KNOWN_UNMODELED exemption — new wire traffic "
                f"must be modeled or consciously exempted",
                where=f"async_ea.{name}"))

    # -- 6. trace-context frame field (docs/OBSERVABILITY.md) ---------------
    # The optional trace context rides dict messages under TRACE_KEY;
    # the documented wire format (and mixed-fleet interop) pins the key
    # to "tc", and async_ea.py must actually stamp/read it — a schedule
    # can't model an optional field, so the binding is evidence-only.
    from distlearn_tpu.obs import trace as _obs_trace
    if _obs_trace.TRACE_KEY != "tc":
        findings.append(Finding(
            "DL310",
            f"obs.trace.TRACE_KEY is {_obs_trace.TRACE_KEY!r} but the "
            f"documented wire format pins 'tc' — peers already in "
            f"flight would silently drop the renamed field",
            where="obs.trace.TRACE_KEY"))
    if facts.attr_loads.get("TRACE_KEY", 0) < 1:
        findings.append(Finding(
            "DL310",
            "async_ea.py never reads obs.trace.TRACE_KEY — the Enter? "
            "announce no longer stamps (and the admit path no longer "
            "adopts) the trace context the wire format documents",
            where="async_ea._announce"))
    return findings


# ---------------------------------------------------------------------------
# Serve-frame field conformance ('J' / 'G' / 'R' wire frames)
# ---------------------------------------------------------------------------

#: frame kind -> {field: what it carries}.  The audited evidence is the
#: union of producer writes and consumer reads across server/router/
#: client; both directions are checked (new-field-unbound AND
#: stale-binding fire DL310).
SERVE_FRAME_BINDINGS: dict = {
    "J": {
        "q": "control request verb ('stats') from router._probe / "
             "client.ping",
        "ok": "reply envelope flag stamped by the server's J handler",
        "serving": "loop-alive flag; router._live gates dispatch on it",
        "failed": "death reason latch; router._live treats it as down",
        "draining": "checkpoint drain latch; router skips draining "
                    "replicas",
        "queue_depth": "admission backlog; router load-balances and "
                       "sheds on it",
        "active": "occupied decode slots; router's least-loaded score",
        "free_pages": "KV pool headroom (capacity telemetry)",
        "cached_pages": "pool pages retained by the prefix cache",
        "epoch": "serving weights epoch; router's fleet epoch view",
        "ckpt_step": "checkpoint step of the serving weights",
        "swap_pending": "two-phase hot-swap in progress",
    },
    "G": {
        "prompt": "token ids to prefill",
        "max_new": "decode budget",
        "rid": "caller-chosen request id (optional)",
        "deadline_s": "per-request deadline (optional)",
        "eos": "early-stop token id (optional)",
        "tc": "cross-process trace context (obs.trace.TRACE_KEY)",
        "temperature": "sampling temperature; 0/absent = exact greedy",
        "top_k": "top-k logit filter width (0 = off)",
        "top_p": "nucleus sampling mass (0 = off)",
        "seed": "per-request sampling key seed (reproducible streams)",
        "speculate": "False opts a greedy stream out of speculative "
                     "decoding",
    },
    "R": {
        "rid": "request id echo (stream demux on shared conns)",
        "tokens": "tokens decoded this scheduling round",
        "done": "terminal-chunk flag",
        "epoch": "serving epoch echo — the hot-swap fence witness",
        "reason": "terminal reason (complete/eos/deadline/...)",
        "error": "rejection/abort message (error chunks only)",
        "queue_depth": "backlog at rejection time (shed hint)",
        "retry_after": "shed backoff hint in seconds",
        "accepted": "speculative draft tokens accepted this round",
        "cached_tokens": "prompt tokens adopted from the prefix cache "
                         "(first chunk only)",
    },
}


def _dict_const_keys(d) -> set:
    """Constant keys of one dict literal (``**spread`` keys are None)."""
    return {k.value for k in d.keys
            if isinstance(k, ast.Constant) and isinstance(k.value, str)}


def _get_key(call) -> str | None:
    """``X.get("field")`` / ``X.get(obs_trace.TRACE_KEY)`` -> field."""
    if not (isinstance(call, ast.Call) and isinstance(call.func,
                                                      ast.Attribute)
            and call.func.attr == "get" and call.args):
        return None
    a = call.args[0]
    if isinstance(a, ast.Constant) and isinstance(a.value, str):
        return a.value
    if isinstance(a, ast.Attribute) and a.attr == "TRACE_KEY":
        return "tc"
    return None


def _sub_key(sub) -> str | None:
    """``X["field"]`` / ``X[obs_trace.TRACE_KEY]`` -> field."""
    s = sub.slice
    if isinstance(s, ast.Constant) and isinstance(s.value, str):
        return s.value
    if isinstance(s, ast.Attribute) and s.attr == "TRACE_KEY":
        return "tc"
    return None


def _send_msg_dict_keys(tree) -> set:
    out: set = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "send_msg" and node.args
                and isinstance(node.args[0], ast.Dict)):
            out |= _dict_const_keys(node.args[0])
    return out


def _health_reply_keys(tree) -> set:
    """Constant keys of the ``health()`` return dict — the payload the
    server's J handler spreads into its reply."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == "health":
            for sub in ast.walk(node):
                if isinstance(sub, ast.Return) and isinstance(sub.value,
                                                              ast.Dict):
                    return _dict_const_keys(sub.value)
    return set()


def _stream_chunk_keys(tree) -> set:
    """Fields of every 'R' chunk a server function builds: dict-literal
    keys plus constant subscript stores, in functions that send_stream."""
    out: set = set()
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        sends = any(isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr == "send_stream"
                    for n in ast.walk(node))
        if not sends:
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Dict):
                out |= _dict_const_keys(sub)
            elif (isinstance(sub, ast.Subscript)
                    and isinstance(sub.ctx, ast.Store)
                    and isinstance(sub.value, ast.Name)):
                k = _sub_key(sub)
                if k is not None:
                    out.add(k)
    return out


def _g_request_keys(tree) -> set:
    """Fields of the 'G' request ``msg`` dict a caller builds: the
    literal assignment plus the optional-field subscript stores."""
    out: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if (isinstance(t, ast.Name) and t.id == "msg"
                        and isinstance(node.value, ast.Dict)):
                    out |= _dict_const_keys(node.value)
        elif (isinstance(node, ast.Subscript)
                and isinstance(node.ctx, ast.Store)
                and isinstance(node.value, ast.Name)
                and node.value.id == "msg"):
            k = _sub_key(node)
            if k is not None:
                out.add(k)
    return out


def _name_field_reads(tree, varname: str) -> set:
    """``X.get("f")`` and ``X["f"]`` loads on the local name ``X``."""
    out: set = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == varname):
            k = _get_key(node)
            if k is not None:
                out.add(k)
        elif (isinstance(node, ast.Subscript)
                and isinstance(node.ctx, ast.Load)
                and isinstance(node.value, ast.Name)
                and node.value.id == varname):
            k = _sub_key(node)
            if k is not None:
                out.add(k)
    return out


def _health_snapshot_reads(tree) -> set:
    """'J'-reply fields the router consumes: ``.get("f")`` where the
    receiver mentions a ``health`` attribute (``(rep.health or
    {}).get(...)``) or is the conventional ``h`` local, plus the
    dict-comprehension sweep ``{k: (r.health or {}).get(k) for k in
    ("queue_depth", ...)}``."""
    out: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            k = _get_key(node)
            if k is not None:
                recv = node.func.value
                mentions_health = any(
                    isinstance(n, ast.Attribute) and n.attr == "health"
                    for n in ast.walk(recv))
                if mentions_health or (isinstance(recv, ast.Name)
                                       and recv.id == "h"):
                    out.add(k)
        elif isinstance(node, ast.DictComp):
            # value reads X.get(k) with the comprehension variable
            v = node.value
            if (isinstance(v, ast.Call)
                    and isinstance(v.func, ast.Attribute)
                    and v.func.attr == "get" and v.args
                    and isinstance(v.args[0], ast.Name)
                    and node.generators
                    and isinstance(node.generators[0].iter, ast.Tuple)):
                out |= {e.value for e in node.generators[0].iter.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)}
    return out


def lint_serve_frames(*, server_source: str | None = None,
                      router_source: str | None = None,
                      client_source: str | None = None) -> list[Finding]:
    """DL310 audit of the serving wire frames ('J'/'G'/'R').

    Collects per-frame field evidence — producer writes in
    ``server.py`` (R chunks, J reply) and ``router.py``/``client.py``
    (G request, J probe), consumer reads on the other side — and diffs
    the union against :data:`SERVE_FRAME_BINDINGS` in BOTH directions.
    Source overrides feed the seeded-mutation tests.
    """
    if server_source is None:
        from distlearn_tpu.serve import server
        server_source = inspect.getsource(server)
    if router_source is None:
        from distlearn_tpu.serve import router
        router_source = inspect.getsource(router)
    if client_source is None:
        from distlearn_tpu.serve import client
        client_source = inspect.getsource(client)
    srv = ast.parse(server_source)
    rtr = ast.parse(router_source)
    cli = ast.parse(client_source)

    evidence = {
        "J": (_send_msg_dict_keys(srv) | _health_reply_keys(srv)
              | _send_msg_dict_keys(rtr) | _send_msg_dict_keys(cli)
              | _health_snapshot_reads(rtr)),
        "G": (_g_request_keys(rtr) | _g_request_keys(cli)
              | _name_field_reads(srv, "msg")),
        "R": (_stream_chunk_keys(srv)
              | _name_field_reads(rtr, "chunk")
              | _name_field_reads(cli, "chunk")),
    }
    findings: list[Finding] = []
    for kind in sorted(SERVE_FRAME_BINDINGS):
        bound = SERVE_FRAME_BINDINGS[kind]
        seen = evidence[kind]
        for fieldname in sorted(seen - set(bound)):
            findings.append(Finding(
                "DL310",
                f"'{kind}' frame field {fieldname!r} appears in the serve "
                "wire code but has no SERVE_FRAME_BINDINGS entry — new "
                "wire surface must be bound (with what it carries) or it "
                "ships undocumented",
                where=f"serve_frames.{kind}.{fieldname}"))
        for fieldname in sorted(set(bound) - seen):
            findings.append(Finding(
                "DL310",
                f"'{kind}' frame binding {fieldname!r} has no remaining "
                "producer or consumer in server/router/client — the "
                "binding table drifted from the wire (remove the entry "
                "or restore the field)",
                where=f"serve_frames.{kind}.{fieldname}"))
    return findings
