"""Host-communication protocol checker (rules DL101-DL104).

Two independent analyses:

**Schedule simulation** — the blocking send/recv sequence each rank
executes in ``comm/tree.py`` / ``comm/ring.py`` / the AsyncEA handshake is
written down as a list of :class:`Op` per rank (the schedule builders here
derive topology from the same helpers the implementations use, so they
track the real code).  :func:`check_schedules` then executes all ranks
against each other: an op fires when its counterpart is ready, and when no
rank can make progress the wait-for graph is extracted and reported —
a cycle is DL101 (static deadlock), a rank waiting on a terminated peer is
starvation (also DL101).  ``buffered_sends`` selects the transport model:
``True`` matches the repo's transports (OS socket buffers + the ring's
``_Sender`` thread make sends asynchronous), ``False`` models rendezvous
sends — under which the ring schedule deadlocks, which is exactly why
``ring.py`` owns a sender thread.  Tag mismatches on delivery are DL104:
the peers disagree on message *order*, which on the wire shows up as a
header parsed as payload.

**Lock audit** — an AST walk over the threaded modules
(``comm/transport.py``, ``parallel/async_ea.py``).  Nested ``with
<lock>:`` statements contribute edges to a lock-order graph; a cycle
across the whole audited set is DL102.  A blocking network call
(``recv_msg``/``send_tensor``/``accept``/...) issued while holding a lock
is DL103 — it extends lock hold times by a network round-trip and, when
the peer needs the same lock to answer, deadlocks.
"""

from __future__ import annotations

import ast
import inspect
from collections import deque
from dataclasses import dataclass
from typing import Iterable, Mapping, Sequence

from distlearn_tpu.lint.core import Finding

__all__ = [
    "Op", "send", "recv", "recv_any",
    "tree_allreduce_schedule", "ring_allreduce_schedule",
    "async_ea_sync_schedule", "async_ea_sharded_schedule",
    "async_ea_rejoin_sharded_schedule", "async_ea_failover_schedule",
    "async_ea_promote_rejoin_schedule", "async_ea_stale_epoch_schedule",
    "async_ea_join_schedule", "async_ea_leave_schedule",
    "check_schedules", "lock_order_audit",
]


@dataclass(frozen=True)
class Op:
    """One blocking endpoint operation in a rank's schedule."""

    kind: str           # 'send' | 'recv' | 'recv_any'
    peer: object = None  # rank id; None for recv_any
    tag: str = ""       # message label, checked on delivery (DL104)
    #: op is armed with an IO timeout whose expiry ABORTS the rank's
    #: remaining schedule (the AsyncEA server's handshake_timeout -> evict
    #: path).  The simulator only reports DL101 for ranks stuck on ops
    #: that cannot time out.
    timeout: bool = False


def send(peer, tag="", timeout=False):
    return Op("send", peer, tag, timeout)


def recv(peer, tag="", timeout=False):
    return Op("recv", peer, tag, timeout)


def recv_any(tag="", timeout=False):
    return Op("recv_any", None, tag, timeout)


# ---------------------------------------------------------------------------
# Schedule builders for the repo's protocols.

def tree_allreduce_schedule(num_nodes: int, base: int = 2) -> dict:
    """Per-rank op sequence of ``Tree.all_reduce_ex`` (up fold, parent
    exchange, down fan-out) on the same topology ``comm.tree`` builds."""
    from distlearn_tpu.comm.tree import _children, _parent
    sched = {}
    for r in range(num_nodes):
        ops = []
        for kid in _children(r, base, num_nodes):
            ops.append(recv(kid, "up"))
        if r != 0:
            p = _parent(r, base)
            ops.append(send(p, "up"))
            ops.append(recv(p, "down"))
        for kid in _children(r, base, num_nodes):
            ops.append(send(kid, "down"))
        sched[r] = ops
    return sched


def ring_allreduce_schedule(num_nodes: int) -> dict:
    """Per-rank op sequence of ``Ring._ring_allreduce_flat``: N-1
    reduce-scatter steps then N-1 allgather steps, send-to-successor
    before recv-from-predecessor each step (full duplex on the wire)."""
    n = num_nodes
    sched = {}
    for r in range(n):
        succ, pred = (r + 1) % n, (r - 1) % n
        ops = []
        for phase in ("rs", "ag"):
            for s in range(n - 1):
                ops.append(send(succ, f"{phase}{s}"))
                ops.append(recv(pred, f"{phase}{s}"))
        sched[r] = ops
    return sched


def async_ea_sync_schedule(num_leaves: int = 2, *, client_order=None,
                           packed: bool = False) -> dict:
    """One AsyncEA sync round between the serial server ``S`` and one
    client ``C`` (``AsyncEAServer.sync_server`` / ``AsyncEAClient.sync``).

    ``client_order`` overrides the client's question order — the linter's
    known-bad configuration swaps ``Center?``/``delta?`` to demonstrate the
    DL104 desync such an edit would introduce.

    ``packed=True`` models the negotiated coalesced wire (frame kind
    ``'P'``, comm/wire.py): the per-leaf ``center``/``delta_t`` legs
    collapse into ONE ``center_p`` / ``delta_p`` frame each way, so the
    simulator keeps covering both framings of the handshake.
    """
    L = num_leaves
    if packed:
        # Enter carries the wire ack; each tensor stream is one 'P' frame.
        server = [recv_any("Enter?"), send("C", "Enter"),
                  recv("C", "Center?"), send("C", "center_p"),
                  recv("C", "delta?"), send("C", "delta"),
                  recv("C", "delta_p")]
        order = client_order or ("Center?", "delta?")
        client = [send("S", "Enter?"), recv("S", "Enter"),
                  send("S", order[0]), recv("S", "center_p"),
                  send("S", order[1]), recv("S", "delta"),
                  send("S", "delta_p")]
        return {"S": server, "C": client}
    server = ([recv_any("Enter?"), send("C", "Enter"), recv("C", "Center?")]
              + [send("C", "center")] * L
              + [recv("C", "delta?"), send("C", "delta")]
              + [recv("C", "delta_t")] * L)
    order = client_order or ("Center?", "delta?")
    client = [send("S", "Enter?"), recv("S", "Enter"), send("S", order[0])]
    client += [recv("S", "center")] * L
    client += [send("S", order[1]), recv("S", "delta")]
    client += [send("S", "delta_t")] * L
    return {"S": server, "C": client}


def _stripe_leg_server(c: str, to: bool) -> list:
    """Server half of one stripe leg (``_serve_stripe_leg``): center slice
    down, delta slice up, every recv armed with handshake_timeout."""
    return [recv(c, "Center?", timeout=to), send(c, "center_p"),
            recv(c, "delta?", timeout=to), send(c, "delta"),
            recv(c, "delta_p", timeout=to)]


def _stripe_leg_client(s: str) -> list:
    """Client half of one stripe leg (strict: a client has no timeouts)."""
    return [send(s, "Center?"), recv(s, "center_p"),
            send(s, "delta?"), recv(s, "delta"), send(s, "delta_p")]


def async_ea_sharded_schedule(num_shards: int = 4, *,
                              server_timeouts: bool = False,
                              truncate_tail: int = 0) -> dict:
    """One SHARDED AsyncEA sync round (``AsyncEAServer._serve_striped`` /
    the striped ``AsyncEAClient.sync_client``).

    Ranks ``S0..S{n-1}`` are the server's per-stripe serving legs (S0 the
    dedicated-channel worker, Ss the shard-endpoint workers); ``C0..``
    are the client's fanned-out stripe legs.  Admission rides leg 0 only;
    the synthetic ``go`` messages model the client's thread fan-out (no
    shard leg speaks before leg 0's Enter reply lands), and each shard
    leg opens with its ``Shard?`` hello exactly like a first dial.

    ``server_timeouts=True`` arms every server recv with the
    handshake-timeout abort (the eviction path); ``truncate_tail`` drops
    that many trailing ops from EVERY client leg (a client dying
    mid-stripe).  Together they assert the eviction schedule drains —
    and without the timeouts, that the truncation would be a real DL101.
    """
    n = max(2, int(num_shards))
    to = bool(server_timeouts)
    sched: dict = {"S0": [recv_any("Enter?", timeout=to), send("C0", "Enter")]
                   + _stripe_leg_server("C0", to)}
    for s in range(1, n):
        sched[f"S{s}"] = ([recv(f"C{s}", "Shard?", timeout=to)]
                          + _stripe_leg_server(f"C{s}", to))
    sched["C0"] = ([send("S0", "Enter?"), recv("S0", "Enter")]
                   + [send(f"C{s}", "go") for s in range(1, n)]
                   + _stripe_leg_client("S0"))
    for s in range(1, n):
        sched[f"C{s}"] = ([recv("C0", "go"), send(f"S{s}", "Shard?")]
                          + _stripe_leg_client(f"S{s}"))
    if truncate_tail:
        for r in list(sched):
            if r.startswith("C"):
                sched[r] = sched[r][:-truncate_tail]
    return sched


def async_ea_rejoin_sharded_schedule(num_shards: int = 4) -> dict:
    """An evicted sharded client's readmission (``_readmit`` streams the
    FULL center on the fresh dedicated channel) followed by its first
    striped sync: the Rejoin reply re-advertises the stripe plan, the
    client re-dials every shard endpoint (fresh ``Shard?`` hellos — the
    server dropped its old shard conns at eviction), so every stripe is
    resynced by construction."""
    n = max(2, int(num_shards))
    sched = {"S0": [recv_any("Rejoin?"), send("C0", "Rejoin"),
                    send("C0", "center"), recv("C0", "ack"),
                    recv_any("Enter?"), send("C0", "Enter")]
             + _stripe_leg_server("C0", False)}
    for s in range(1, n):
        sched[f"S{s}"] = ([recv(f"C{s}", "Shard?")]
                          + _stripe_leg_server(f"C{s}", False))
    # _announce parses the reply (re-dialing the shard channels) BEFORE
    # rejoin() receives the center — hence go-then-center on leg 0
    sched["C0"] = ([send("S0", "Rejoin?"), recv("S0", "Rejoin")]
                   + [send(f"C{s}", "go") for s in range(1, n)]
                   + [recv("S0", "center"), send("S0", "ack"),
                      send("S0", "Enter?"), recv("S0", "Enter")]
                   + _stripe_leg_client("S0"))
    for s in range(1, n):
        sched[f"C{s}"] = ([recv("C0", "go"), send(f"S{s}", "Shard?")]
                          + _stripe_leg_client(f"S{s}"))
    return sched


def _rejoin_replay_server(c: str, num_shards: int) -> list:
    """Server half of a Rejoin-with-replay handshake (``_readmit`` +
    ``_recv_replay``): reply, full center down, Ack up, then the pending
    delta's un-applied stripe payloads, acked."""
    return ([recv_any("Rejoin?"), send(c, "Rejoin"),
             send(c, "center"), recv(c, "ack"),
             recv(c, "Replay")]
            + [recv(c, "replay_p")] * num_shards
            + [send(c, "ack")])


def _rejoin_replay_client(s: str, num_shards: int) -> list:
    """Client half (``_rejoin_handshake`` + ``_replay_exchange``), minus
    the shard-fanout ``go`` ops the sharded callers splice in."""
    return ([send(s, "Rejoin?"), recv(s, "Rejoin"),
             recv(s, "center"), send(s, "ack"),
             send(s, "Replay")]
            + [send(s, "replay_p")] * num_shards
            + [recv(s, "ack")])


def async_ea_failover_schedule(num_shards: int = 4, *,
                               strict: bool = False) -> dict:
    """Center failover end to end: the primary ``P*`` dies mid-stripe-leg
    (its serving legs simply STOP — schedules truncated after the center
    slice goes down), the client's first-sync legs ``C*`` abandon the
    ruined sync, and the client then fails over to the promoted standby
    ``T*``: Rejoin with full-stripe replay of the pending delta, then its
    first clean striped sync (``AsyncEAClient.failover``).

    The ``C*`` recvs from the dead primary are timeout-armed: on the real
    wire a dead peer surfaces as ``PeerClosed``/ECONNRESET, which aborts
    the sync attempt exactly like the simulator's timeout abort.
    ``strict=True`` strips that error surfacing — the expected DL101
    starvation it produces is the PROOF the failover path needs transport
    errors to fire, not a crutch hiding a real deadlock."""
    n = max(2, int(num_shards))
    to = not strict
    # the dying primary: Enter handshake completes, every serving leg
    # pushes its center slice, then the process is gone — no delta recv
    sched: dict = {"P0": [recv_any("Enter?"), send("C0", "Enter")]
                   + _stripe_leg_server("C0", True)[:2]}
    for s in range(1, n):
        sched[f"P{s}"] = ([recv(f"C{s}", "Shard?", timeout=True)]
                          + _stripe_leg_server(f"C{s}", True)[:2])
    sched["C0"] = ([send("P0", "Enter?"), recv("P0", "Enter", timeout=to)]
                   + [send(f"C{s}", "go") for s in range(1, n)]
                   + [send("P0", "Center?"),
                      recv("P0", "center_p", timeout=to),
                      send("P0", "delta?"), recv("P0", "delta", timeout=to),
                      send("P0", "delta_p")])
    for s in range(1, n):
        sched[f"C{s}"] = [recv(f"C0", "go"), send(f"P{s}", "Shard?"),
                          send(f"P{s}", "Center?"),
                          recv(f"P{s}", "center_p", timeout=to),
                          send(f"P{s}", "delta?"),
                          recv(f"P{s}", "delta", timeout=to),
                          send(f"P{s}", "delta_p")]
    # the promoted standby: Rejoin + replay on the fresh dedicated
    # channel, then the client's next striped sync — fresh ranks because
    # failover re-dials everything (new conns, new fanned-out legs)
    sched["T0"] = (_rejoin_replay_server("F0", n)
                   + [recv_any("Enter?"), send("F0", "Enter")]
                   + _stripe_leg_server("F0", False))
    for s in range(1, n):
        sched[f"T{s}"] = ([recv(f"F{s}", "Shard?")]
                          + _stripe_leg_server(f"F{s}", False))
    # _announce parses the Rejoin reply (re-dialing the shard endpoints)
    # BEFORE the center streams — hence go-then-center on leg 0
    cf = _rejoin_replay_client("T0", n)
    sched["F0"] = (cf[:2]
                   + [send(f"F{s}", "go") for s in range(1, n)]
                   + cf[2:]
                   + [send("T0", "Enter?"), recv("T0", "Enter")]
                   + _stripe_leg_client("T0"))
    for s in range(1, n):
        sched[f"F{s}"] = ([recv("F0", "go"), send(f"T{s}", "Shard?")]
                          + _stripe_leg_client(f"T{s}"))
    return sched


def async_ea_promote_rejoin_schedule(num_clients: int = 3) -> dict:
    """The rejoin herd after a promotion: every client of the dead
    primary re-dials the promoted standby ``S`` at once, each running a
    Rejoin-with-replay handshake (unsharded: one pending payload).  The
    serial serve loop admits them one at a time; the schedule proves the
    herd drains STRICT — no timeout crutch, any ordering bug is a loud
    DL101/DL104."""
    k = max(1, int(num_clients))
    server: list = []
    for i in range(1, k + 1):
        server += _rejoin_replay_server(f"C{i}", 1)
    sched: dict = {"S": server}
    for i in range(1, k + 1):
        sched[f"C{i}"] = _rejoin_replay_client("S", 1)
    return sched


def async_ea_join_schedule() -> dict:
    """Elastic admission (``AsyncEAClient.join`` / ``_handle_join``): the
    joiner announces ``Join?`` on the broadcast channel, the server
    replies with the assigned cid + ephemeral dedicated port, streams the
    FULL center down the fresh dedicated channel, and the adoption ack
    coming back is the join fence — ``_register_member`` runs only after
    it lands.  Strict: the handshake must drain with no timeout crutch."""
    server = [recv_any("Join?"), send("C", "Join"),
              send("C", "center"), recv("C", "ack")]
    client = [send("S", "Join?"), recv("S", "Join"),
              recv("S", "center"), send("S", "ack")]
    return {"S": server, "C": client}


def async_ea_leave_schedule(num_stripes: int = 1) -> dict:
    """Graceful departure (``AsyncEAClient.leave`` / ``_handle_leave``):
    the leaver announces ``Leave?`` with its last pushed seq, the server
    waits the cid idle, reads the applied-seq ledger and replies with
    what it is still owed; the leaver replays the un-applied stripe
    payloads and the final ack releases it.  Strict — the flush must
    drain without the eviction timeout firing."""
    n = max(1, int(num_stripes))
    server = ([recv_any("Leave?"), send("C", "Leave"),
               recv("C", "Replay")]
              + [recv("C", "replay_p")] * n
              + [send("C", "ack")])
    client = ([send("S", "Leave?"), recv("S", "Leave"),
               send("S", "Replay")]
              + [send("S", "replay_p")] * n
              + [recv("S", "ack")])
    return {"S": server, "C": client}


def async_ea_stale_epoch_schedule() -> dict:
    """The zombie fence: a stale center ``Z`` (paused primary back from
    the dead) answers a client whose epoch is newer with the ``stale``
    refusal and stops; the client drops ``Z`` from its dial list and runs
    a clean Rejoin + packed sync against the promoted center ``S``
    (``_refuse_stale`` / ``StaleCenterError`` -> ``failover``).  Strict —
    the refusal leg must never leave either side mid-stream."""
    zombie = [recv_any("Enter?"), send("C", "stale")]
    promoted = [recv_any("Rejoin?"), send("C", "Rejoin"),
                send("C", "center"), recv("C", "ack"),
                recv_any("Enter?"), send("C", "Enter"),
                recv("C", "Center?"), send("C", "center_p"),
                recv("C", "delta?"), send("C", "delta"),
                recv("C", "delta_p")]
    client = [send("Z", "Enter?"), recv("Z", "stale"),
              send("S", "Rejoin?"), recv("S", "Rejoin"),
              recv("S", "center"), send("S", "ack"),
              send("S", "Enter?"), recv("S", "Enter"),
              send("S", "Center?"), recv("S", "center_p"),
              send("S", "delta?"), recv("S", "delta"),
              send("S", "delta_p")]
    return {"Z": zombie, "S": promoted, "C": client}


# ---------------------------------------------------------------------------
# The simulator.

def check_schedules(schedules: Mapping, *, buffered_sends: bool = True,
                    name: str = "protocol") -> list[Finding]:
    """Execute all ranks' schedules against each other; report DL101 on
    global no-progress (with the wait-for cycle) and DL104 on deliveries
    whose tag differs from what the receiver expects."""
    findings: list[Finding] = []
    pc = {r: 0 for r in schedules}
    chan: dict = {}  # (src, dst) -> deque of tags, buffered mode only

    def cur(r):
        ops = schedules[r]
        return ops[pc[r]] if pc[r] < len(ops) else None

    def deliver(r, op, tag, src):
        if op.tag and tag != op.tag:
            findings.append(Finding(
                "DL104",
                f"rank {r} expected {op.tag!r} from rank {src} but the "
                f"next message is {tag!r}; the peers disagree on message "
                "order and will misparse the stream",
                where=f"{name}/rank {r}"))
        pc[r] += 1

    while True:
        progress = True
        while progress:
            progress = False
            for r in list(schedules):
                op = cur(r)
                if op is None:
                    continue
                if op.kind == "send":
                    if buffered_sends:
                        chan.setdefault((r, op.peer), deque()).append(op.tag)
                        pc[r] += 1
                        progress = True
                    else:
                        peer_op = cur(op.peer)
                        if peer_op is not None and (
                                (peer_op.kind == "recv"
                                 and peer_op.peer == r)
                                or peer_op.kind == "recv_any"):
                            deliver(op.peer, peer_op, op.tag, r)
                            pc[r] += 1
                            progress = True
                elif op.kind == "recv":
                    q = chan.get((op.peer, r))
                    if q:
                        deliver(r, op, q.popleft(), op.peer)
                        progress = True
                elif op.kind == "recv_any":
                    for (src, dst), q in chan.items():
                        if dst == r and q:
                            deliver(r, op, q.popleft(), src)
                            progress = True
                            break

        stuck = {r: cur(r) for r in schedules if cur(r) is not None}
        timed = [r for r, op in stuck.items() if op.timeout]
        if not timed:
            break
        # IO-timeout model: a rank stuck on a timeout-armed op ABORTS its
        # remaining schedule (the AsyncEA server's handshake_timeout fires
        # and the client is evicted — that serving leg abandons the rest
        # of its ops) and the simulation continues; only ranks that can
        # NEVER unblock are a DL101.
        for r in timed:
            pc[r] = len(schedules[r])

    if stuck:
        findings.append(_deadlock_finding(stuck, pc, name))
    return findings


def _deadlock_finding(stuck, pc, name) -> Finding:
    waits = {r: op.peer for r, op in stuck.items()}  # None for recv_any
    cycle = _find_cycle(waits)
    if cycle:
        path = " -> ".join(str(r) for r in cycle + [cycle[0]])
        detail = f"wait-for cycle {path}"
    else:
        detail = ", ".join(
            f"rank {r} blocked at op {pc[r]} ({op.kind} "
            f"{'' if op.peer is None else op.peer} {op.tag!r})"
            for r, op in stuck.items())
    return Finding(
        "DL101",
        f"schedule cannot complete: {detail}; "
        f"{len(stuck)} rank(s) permanently blocked",
        where=name)


def _find_cycle(waits: Mapping):
    for start in waits:
        seen: dict = {}
        r = start
        while r in waits and waits[r] is not None:
            if r in seen:
                cyc = list(seen)[list(seen).index(r):]
                return cyc
            seen[r] = True
            r = waits[r]
    return None


# ---------------------------------------------------------------------------
# Lock audit (AST).

#: Calls that block on the network or another thread.  dict.get / queue
#: get_nowait style accessors are deliberately excluded.
_BLOCKING_CALLS = frozenset({
    "recv_msg", "recv_tensor", "send_msg", "send_tensor",
    "send_tensors", "recv_tensors", "send_packed",
    "accept", "recv_any", "select", "connect",
})


def _lock_name(expr, class_name):
    """A with-item that looks like a lock acquisition, else None."""
    if isinstance(expr, ast.Attribute) and "lock" in expr.attr.lower():
        return (class_name, expr.attr)
    if isinstance(expr, ast.Name) and "lock" in expr.id.lower():
        return (class_name, expr.id)
    return None


class _LockVisitor(ast.NodeVisitor):
    def __init__(self, filename):
        self.filename = filename
        self.class_name = ""
        self.stack: list = []           # locks currently held (lexically)
        self.edges: dict = {}           # (outer, inner) -> first location
        self.blocking: list = []        # (lock, call name, location)

    def visit_ClassDef(self, node):
        prev, self.class_name = self.class_name, node.name
        self.generic_visit(node)
        self.class_name = prev

    def visit_FunctionDef(self, node):
        # A nested def runs on its own thread/later; locks held lexically
        # outside it are not held at its call time.
        prev, self.stack = self.stack, []
        self.generic_visit(node)
        self.stack = prev

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_With(self, node):
        acquired = []
        for item in node.items:
            lock = _lock_name(item.context_expr, self.class_name)
            if lock is not None:
                loc = f"{self.filename}:{node.lineno}"
                for held in self.stack:
                    self.edges.setdefault((held, lock), loc)
                self.stack.append(lock)
                acquired.append(lock)
        self.generic_visit(node)
        for _ in acquired:
            self.stack.pop()

    def visit_Call(self, node):
        if self.stack:
            fn = node.func
            cname = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else "")
            if cname in _BLOCKING_CALLS:
                self.blocking.append(
                    (self.stack[-1], cname, f"{self.filename}:{node.lineno}"))
        self.generic_visit(node)


def lock_order_audit(targets: Iterable, *, name: str = "locks") -> list[Finding]:
    """DL102/DL103 audit over modules (or raw source strings).

    All targets contribute to ONE lock-order graph: a cycle that only
    exists across two modules (thread A in one file, thread B in another)
    is still a deadlock.
    """
    edges: dict = {}
    findings: list[Finding] = []
    for t in targets:
        if isinstance(t, str):
            src, fname = t, "<string>"
        else:
            src, fname = inspect.getsource(t), getattr(t, "__name__", "?")
        v = _LockVisitor(fname)
        v.visit(ast.parse(src))
        edges.update({k: loc for k, loc in v.edges.items() if k not in edges})
        for lock, call, loc in v.blocking:
            findings.append(Finding(
                "DL103",
                f"blocking call {call}() while holding lock "
                f"{'.'.join(filter(None, lock))}; a slow or deadlocked peer "
                "stalls every thread contending for this lock",
                where=loc))
    graph: dict = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
    cycle = _digraph_cycle(graph)
    if cycle:
        path = " -> ".join(".".join(filter(None, l)) for l in cycle)
        locs = sorted({edges[e] for e in zip(cycle, cycle[1:])
                       if e in edges})
        findings.append(Finding(
            "DL102",
            f"lock acquisition order forms a cycle: {path} "
            f"(acquisition sites: {', '.join(locs)}); two threads taking "
            "the locks in opposite order deadlock",
            where=name))
    return findings


def _digraph_cycle(graph: Mapping):
    """First cycle in a digraph as a node path [a, b, ..., a], else None."""
    WHITE, GREY, BLACK = 0, 1, 2
    color = {n: WHITE for n in graph}
    path: list = []

    def dfs(n):
        color[n] = GREY
        path.append(n)
        for m in graph.get(n, ()):
            if color.get(m, WHITE) == GREY:
                return path[path.index(m):] + [m]
            if color.get(m, WHITE) == WHITE:
                got = dfs(m)
                if got:
                    return got
        path.pop()
        color[n] = BLACK
        return None

    for n in list(graph):
        if color[n] == WHITE:
            got = dfs(n)
            if got:
                return got
    return None


# ---------------------------------------------------------------------------
# Repo-facing entry: lint every protocol the comm layer ships.

def lint_comm_protocols(*, num_nodes: int = 7) -> list[Finding]:
    """Check the real tree/ring/AsyncEA schedules (buffered transport, as
    deployed) and audit the threaded modules' lock usage."""
    findings = []
    findings += check_schedules(tree_allreduce_schedule(num_nodes),
                                name="tree.all_reduce")
    findings += check_schedules(ring_allreduce_schedule(num_nodes),
                                name="ring.all_reduce")
    findings += check_schedules(async_ea_sync_schedule(),
                                name="async_ea.sync")
    findings += check_schedules(async_ea_sync_schedule(packed=True),
                                name="async_ea.sync-packed")
    # sharded center: clean round and rejoin must drain STRICT (no
    # timeout crutch); the mid-stripe death drains only because every
    # server recv is handshake_timeout-armed -> evict
    findings += check_schedules(async_ea_sharded_schedule(4),
                                name="async_ea.sync-sharded")
    findings += check_schedules(async_ea_rejoin_sharded_schedule(4),
                                name="async_ea.rejoin-sharded")
    findings += check_schedules(
        async_ea_sharded_schedule(4, server_timeouts=True, truncate_tail=1),
        name="async_ea.evict-mid-stripe")
    # HA failover (docs/HA.md): primary dying mid-stripe-leg + standby
    # promotion + replay, the post-promotion rejoin herd, and the stale-
    # epoch refusal — the latter two strict by construction
    findings += check_schedules(async_ea_failover_schedule(4),
                                name="async_ea.failover-promote")
    findings += check_schedules(async_ea_promote_rejoin_schedule(3),
                                name="async_ea.promote-rejoin-herd")
    findings += check_schedules(async_ea_stale_epoch_schedule(),
                                name="async_ea.stale-epoch-refusal")
    # elastic membership: join admission and the graceful-leave flush,
    # both strict by construction
    findings += check_schedules(async_ea_join_schedule(),
                                name="async_ea.join")
    findings += check_schedules(async_ea_leave_schedule(2),
                                name="async_ea.leave-flush")
    from distlearn_tpu.comm import ring, transport, tree
    from distlearn_tpu.parallel import async_ea
    findings += lock_order_audit([transport, tree, ring, async_ea],
                                 name="comm-threads")
    return findings
