"""Minimal functional NN layer library — the TPU-native stand-in for the
reference's ``grad.nn.*`` primitives (torch-autograd wrapping torch7 nn;
reference call sites: examples/mnist.lua:53-67, examples/Model.lua:19-45).

Design notes (TPU-first):

* **NHWC layout**: XLA's TPU conv emitter prefers NHWC activations with HWIO
  kernels — feature dim last lands on the 128-wide lane axis of the MXU/VPU.
  (The reference uses torch NCHW; layout is an implementation detail the
  framework owns, not API surface.)
* **Functional**: every layer is ``init(key, ...) -> params`` plus a pure
  ``apply``.  Mutable state (batch-norm running stats) is an explicit pytree
  threaded through apply, never hidden module state — this is what lets the
  whole train step jit into one XLA program.
* **dtype policy**: params are stored f32 (or f64 under x64 tests); compute
  dtype is a caller choice — pass ``compute_dtype=jnp.bfloat16`` to run the
  matmuls/convs on the MXU in bf16 with f32 params (master weights).
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax, random

PyTree = Any


# ---------------------------------------------------------------------------
# Initializers (match torch7 defaults: U(-1/sqrt(fanin), 1/sqrt(fanin)),
# which is what the reference's grad.nn layers use via nn.Linear/
# SpatialConvolutionMM reset())
# ---------------------------------------------------------------------------

def _uniform_fanin(key, shape, fan_in, dtype):
    bound = 1.0 / math.sqrt(fan_in)
    return random.uniform(key, shape, dtype, -bound, bound)


# ---------------------------------------------------------------------------
# Dense
# ---------------------------------------------------------------------------

def dense_init(key, in_features: int, out_features: int, dtype=jnp.float32):
    kw, kb = random.split(key)
    return {
        "w": _uniform_fanin(kw, (in_features, out_features), in_features, dtype),
        "b": _uniform_fanin(kb, (out_features,), in_features, dtype),
    }


def dense(params, x, compute_dtype=None):
    w, b = params["w"], params["b"]
    if compute_dtype is not None:
        x, w = x.astype(compute_dtype), w.astype(compute_dtype)
    y = x @ w
    return y + b.astype(y.dtype)


# ---------------------------------------------------------------------------
# Conv2D (NHWC x HWIO -> NHWC)
# ---------------------------------------------------------------------------

def conv2d_init(key, in_ch: int, out_ch: int, kh: int, kw: int,
                dtype=jnp.float32, bias: bool = True, init: str = "uniform"):
    """``init``: 'uniform' (torch7 fanin default) or 'he' (Kaiming normal
    fan-out — the torchvision ResNet init).  ``bias=False`` for convs
    followed by batchnorm."""
    kk, kb = random.split(key)
    fan_in = in_ch * kh * kw
    if init == "he":
        fan_out = out_ch * kh * kw
        w = random.normal(kk, (kh, kw, in_ch, out_ch), dtype) \
            * jnp.asarray(math.sqrt(2.0 / fan_out), dtype)
    else:
        w = _uniform_fanin(kk, (kh, kw, in_ch, out_ch), fan_in, dtype)
    params = {"w": w}
    if bias:
        params["b"] = _uniform_fanin(kb, (out_ch,), fan_in, dtype)
    return params


def conv2d(params, x, stride=(1, 1), padding="VALID", compute_dtype=None):
    """x: [N,H,W,C]; kernel HWIO.  Padding: 'VALID' | 'SAME' | ((ph,ph),(pw,pw))."""
    w = params["w"]
    if compute_dtype is not None:
        x, w = x.astype(compute_dtype), w.astype(compute_dtype)
    y = lax.conv_general_dilated(
        x, w, window_strides=stride, padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    b = params.get("b")
    return y if b is None else y + b.astype(y.dtype)


# ---------------------------------------------------------------------------
# Pooling
# ---------------------------------------------------------------------------

def max_pool2d(x, window=(2, 2), stride=(2, 2), padding="VALID"):
    """``padding``: 'VALID' | ((ph, ph), (pw, pw)) spatial pads."""
    if padding != "VALID":
        (pt, pb), (pl, pr) = padding
        padding = ((0, 0), (pt, pb), (pl, pr), (0, 0))
    return lax.reduce_window(
        x, -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min,
        lax.max,
        window_dimensions=(1, window[0], window[1], 1),
        window_strides=(1, stride[0], stride[1], 1),
        padding=padding)


def avg_pool2d(x, window=(2, 2), stride=(2, 2)):
    s = lax.reduce_window(
        x, jnp.zeros((), x.dtype), lax.add,
        window_dimensions=(1, window[0], window[1], 1),
        window_strides=(1, stride[0], stride[1], 1),
        padding="VALID")
    return s / (window[0] * window[1])


# ---------------------------------------------------------------------------
# BatchNorm (SpatialBatchNormalization parity — examples/Model.lua:20 et al.)
# ---------------------------------------------------------------------------

def batchnorm_init(ch: int, dtype=jnp.float32):
    params = {"scale": jnp.ones((ch,), dtype), "bias": jnp.zeros((ch,), dtype)}
    stats = {"mean": jnp.zeros((ch,), dtype), "var": jnp.ones((ch,), dtype)}
    return params, stats


def batchnorm(params, stats, x, train: bool, eps=1e-3, momentum=0.1,
              axis_name: str | None = None, weight=None):
    """Channel-last batchnorm over (N,H,W) or (N,).

    ``axis_name``: when set, batch statistics are psum'd across that mesh axis
    so every data-parallel replica normalizes with *global* batch stats (sync
    BN) — the TPU-native upgrade over per-replica stats; pass ``None`` for
    per-node stats (the reference's behavior, each process normalizes its own
    shard).  ``weight``: optional per-node scalar 0/1 participation weight —
    non-contributing nodes (uneven data partitions) are excluded from the
    cross-node statistics, mirroring how they are excluded from the gradient
    sum (lua/AllReduceSGD.lua:22-27).  Returns (y, new_stats).
    """
    reduce_axes = tuple(range(x.ndim - 1))
    if train:
        mean = jnp.mean(x, axis=reduce_axes)
        mean2 = jnp.mean(jnp.square(x), axis=reduce_axes)
        if axis_name is not None:
            if weight is None:
                mean = lax.pmean(mean, axis_name)
                mean2 = lax.pmean(mean2, axis_name)
            else:
                w = jnp.asarray(weight, mean.dtype)
                denom = jnp.maximum(lax.psum(w, axis_name), 1)
                mean = lax.psum(mean * w, axis_name) / denom
                mean2 = lax.psum(mean2 * w, axis_name) / denom
        var = mean2 - jnp.square(mean)
        m = jnp.asarray(momentum, stats["mean"].dtype)
        new_stats = {
            "mean": (1 - m) * stats["mean"] + m * mean.astype(stats["mean"].dtype),
            "var": (1 - m) * stats["var"] + m * var.astype(stats["var"].dtype),
        }
    else:
        mean, var = stats["mean"].astype(x.dtype), stats["var"].astype(x.dtype)
        new_stats = stats
    inv = lax.rsqrt(var.astype(x.dtype) + jnp.asarray(eps, x.dtype))
    y = (x - mean.astype(x.dtype)) * inv
    y = y * params["scale"].astype(x.dtype) + params["bias"].astype(x.dtype)
    return y, new_stats


# ---------------------------------------------------------------------------
# Activations / heads
# ---------------------------------------------------------------------------

def log_softmax(x, axis=-1):
    return jax.nn.log_softmax(x, axis=axis)


def nll_loss(log_probs, labels):
    """ClassNLLCriterion parity (examples/Model.lua:52): mean over batch of
    -log p[label].  ``labels``: int [N]."""
    ll = jnp.take_along_axis(log_probs, labels[:, None], axis=-1)[:, 0]
    return -jnp.mean(ll)


def dropout(key, x, rate: float, train: bool):
    if not train or rate == 0.0:
        return x
    keep = 1.0 - rate
    mask = random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, jnp.zeros_like(x))
