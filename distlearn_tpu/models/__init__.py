"""Functional model zoo (the reference ships MNIST CNN + CIFAR convnet as
training-script-local model defs — examples/mnist.lua:53-81,
examples/Model.lua; here they are a first-class module)."""

from distlearn_tpu.models.core import Model, loss_fn, param_count
from distlearn_tpu.models.mnist_cnn import mnist_cnn
from distlearn_tpu.models.cifar_convnet import cifar_convnet
from distlearn_tpu.models.resnet import resnet, resnet50
from distlearn_tpu.models.transformer import (greedy_generate,
                                              transformer_lm)

__all__ = ["Model", "loss_fn", "param_count", "mnist_cnn", "cifar_convnet",
           "resnet", "resnet50", "transformer_lm", "greedy_generate"]
