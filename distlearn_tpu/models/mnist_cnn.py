"""MNIST CNN — TPU-native rebuild of the reference architecture
(examples/mnist.lua:53-81):

    reshape(1,32,32) -> conv5x5(1->16) -> tanh -> maxpool2x2
                     -> conv5x5(16->16) -> tanh -> maxpool2x2
                     -> flatten(400) -> linear(400->10) -> logSoftMax

Here in NHWC: [N,32,32,1] -> 28 -> 14 -> 10 -> 5 -> flatten 400 -> 10.
No batchnorm, so the mutable state pytree is empty.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import random

from distlearn_tpu.models import nn
from distlearn_tpu.models.core import Model


def mnist_cnn(dtype=jnp.float32, compute_dtype=None) -> Model:
    def init(key):
        k1, k2, k3 = random.split(key, 3)
        params = {
            "conv1": nn.conv2d_init(k1, 1, 16, 5, 5, dtype),
            "conv2": nn.conv2d_init(k2, 16, 16, 5, 5, dtype),
            "linear": nn.dense_init(k3, 16 * 5 * 5, 10, dtype),
        }
        return params, {}

    def apply(params, state, x, train=True, rng=None, axis_name=None,
              bn_weight=None):
        h = nn.conv2d(params["conv1"], x, compute_dtype=compute_dtype)
        h = nn.max_pool2d(jnp.tanh(h))
        h = nn.conv2d(params["conv2"], h, compute_dtype=compute_dtype)
        h = nn.max_pool2d(jnp.tanh(h))
        h = h.reshape(h.shape[0], -1)
        logits = nn.dense(params["linear"], h, compute_dtype=compute_dtype)
        return nn.log_softmax(logits.astype(dtype)), state

    return Model(init=init, apply=apply, name="mnist_cnn",
                 input_shape=(32, 32, 1), num_classes=10)
