"""CIFAR-10 convnet — TPU-native rebuild of the reference 5-block VGG-ish net
(examples/Model.lua:19-45 == examples/cifar10.lua:100-163):

    4 x [ conv5x5 pad2 (3->64->128->256->512) -> batchnorm(eps=1e-3) -> ReLU
          -> maxpool2x2 ]
    -> flatten(512*2*2) -> dropout(0.5) -> linear(2048->10) -> logSoftMax

NHWC: 32 -> 16 -> 8 -> 4 -> 2.  Batch-norm running stats live in the state
pytree; pass ``axis_name`` for cross-replica (sync) statistics.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import random

from distlearn_tpu.models import nn
from distlearn_tpu.models.core import Model

_CHANNELS = (64, 128, 256, 512)


def cifar_convnet(dtype=jnp.float32, compute_dtype=None,
                  dropout_rate: float = 0.5) -> Model:
    def init(key):
        keys = random.split(key, len(_CHANNELS) + 1)
        params, state = {}, {}
        in_ch = 3
        for i, ch in enumerate(_CHANNELS):
            bn_p, bn_s = nn.batchnorm_init(ch, dtype)
            params[f"conv{i + 1}"] = nn.conv2d_init(keys[i], in_ch, ch, 5, 5, dtype)
            params[f"bn{i + 1}"] = bn_p
            state[f"bn{i + 1}"] = bn_s
            in_ch = ch
        params["linear"] = nn.dense_init(keys[-1], 512 * 2 * 2, 10, dtype)
        return params, state

    def apply(params, state, x, train=True, rng=None, axis_name=None,
              bn_weight=None):
        h = x
        new_state = {}
        for i in range(1, len(_CHANNELS) + 1):
            h = nn.conv2d(params[f"conv{i}"], h, padding=((2, 2), (2, 2)),
                          compute_dtype=compute_dtype)
            h, new_state[f"bn{i}"] = nn.batchnorm(
                params[f"bn{i}"], state[f"bn{i}"], h, train=train,
                eps=1e-3, axis_name=axis_name, weight=bn_weight)
            h = nn.max_pool2d(jnp.maximum(h, 0))
        h = h.reshape(h.shape[0], -1)
        if train and rng is not None and dropout_rate > 0:
            h = nn.dropout(rng, h, dropout_rate, train=True)
        logits = nn.dense(params["linear"], h, compute_dtype=compute_dtype)
        return nn.log_softmax(logits.astype(dtype)), new_state

    return Model(init=init, apply=apply, name="cifar_convnet",
                 input_shape=(32, 32, 3), num_classes=10)
