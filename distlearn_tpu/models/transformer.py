"""Decoder-only transformer LM — the long-context model family.

Not in the reference (CNN classifiers only — SURVEY.md §2c), but first-class
here: the attention runs as ring attention over a sequence mesh axis
(distlearn_tpu.parallel.sequence) and the MLP/attention projections support
tensor parallelism over a model mesh axis, so one model spans
(data, seq, model) meshes.

Sharding convention (inside ``shard_map``): ``apply`` receives LOCAL param
shards.  With ``tp_axis`` set, the caller shards

* ``wq/wk/wv``:   [E, H, D]  → heads split over tp   (spec P(None, tp))
* ``wo``:         [H, D, E]  → heads split over tp   (spec P(tp))
* ``mlp/w1,b1``:  [E, F], [F] → F split over tp      (spec P(None, tp) / P(tp))
* ``mlp/w2``:     [F, E]    → F split over tp        (spec P(tp))

and ``apply`` inserts the one ``psum`` per block that TP requires (after
``wo`` and ``w2`` — the Megatron pattern: column-parallel then row-parallel).
:func:`param_specs` produces exactly these PartitionSpecs for a param pytree.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax, random

from jax.sharding import PartitionSpec as P

from distlearn_tpu.models.core import Model
from distlearn_tpu.utils import compat
from distlearn_tpu.parallel.sequence import (alltoall_attention,
                                             local_attention, ring_attention)
from distlearn_tpu.parallel.tp import tp_enter, tp_reduce

PyTree = Any


def _norm_init(shape, dtype):
    return {"scale": jnp.ones(shape, dtype)}


def _rmsnorm(params, x, eps=1e-6):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    y = x * lax.rsqrt(var + eps).astype(x.dtype)
    return y * params["scale"].astype(x.dtype)


def attn_qkv(blk: PyTree, x: jax.Array, cd, tp_axis: str | None = None):
    """Pre-norm + q/k/v projections of one block — the ONE home of the
    projection math, shared by :func:`attn_apply` (training forward) and
    :func:`greedy_generate` (prefill + per-tick decode), so a future
    change (bias terms, RoPE, QK-norm) cannot silently diverge between
    training and generation."""
    h = _rmsnorm(blk["ln1"], x)
    if tp_axis is not None:   # enter column-parallel region ("f")
        h = tp_enter(h, tp_axis)
    q = jnp.einsum("ble,ehd->blhd", h, blk["wq"].astype(cd))
    k = jnp.einsum("ble,ehd->blhd", h, blk["wk"].astype(cd))
    v = jnp.einsum("ble,ehd->blhd", h, blk["wv"].astype(cd))
    return q, k, v


def attn_out(blk: PyTree, x: jax.Array, att: jax.Array, cd,
             tp_axis: str | None = None) -> jax.Array:
    """Output projection + residual (the other half shared with the
    decoder)."""
    proj = jnp.einsum("blhd,hde->ble", att, blk["wo"].astype(cd))
    if tp_axis is not None:   # heads were sharded: reduce ("g")
        proj = tp_reduce(proj, tp_axis)
    return x + proj


def attn_apply(blk: PyTree, x: jax.Array, cd, *, seq_attn=None,
               seq_axis: str | None = None, tp_axis: str | None = None,
               attn_impl: str | None = None):
    """Attention half of a transformer block (pre-norm attention residual)
    on a LOCAL param shard — split out of :func:`block_apply` so the
    selective-remat mode can checkpoint the FFN half alone (saving the
    attention output and the flash kernel's softmax residuals instead of
    re-running the attention forward in the backward pass)."""
    q, k, v = attn_qkv(blk, x, cd, tp_axis)
    if seq_axis is not None:
        att = seq_attn(q, k, v, seq_axis, causal=True, impl=attn_impl)
    else:
        att = local_attention(q, k, v, causal=True, impl=attn_impl)
    return attn_out(blk, x, att, cd, tp_axis)


def ffn_apply(blk: PyTree, x: jax.Array, cd, *, tp_axis: str | None = None,
              ep_axis: str | None = None,
              moe_capacity_factor: float = 1.25, moe_top_k: int = 1,
              return_moe_aux: bool = False):
    """FFN/MoE half of a transformer block (see :func:`attn_apply`)."""
    h = _rmsnorm(blk["ln2"], x)
    if "router" in blk:       # routed MoE FFN (parallel/ep.py)
        from distlearn_tpu.parallel.ep import moe_ffn, moe_ffn_local

        Bq, Lq, Dq = h.shape
        flat = h.reshape(Bq * Lq, Dq)

        def expert(p, t):
            u = jax.nn.gelu(t @ p["we1"].astype(cd)
                            + p["wb1"].astype(cd))
            return u @ p["we2"].astype(cd)

        eparams = {k2: blk[k2] for k2 in ("we1", "wb1", "we2")}
        if ep_axis is None:
            y = moe_ffn_local(expert, eparams, blk["router"], flat,
                              moe_capacity_factor, top_k=moe_top_k,
                              return_aux=return_moe_aux)
        else:                 # one expert per device on ep_axis
            n_local = blk["we1"].shape[0]
            if n_local != 1:
                raise ValueError(
                    f"stacked expert leaves hold {n_local} shards on this "
                    "device; expected exactly one per device on ep_axis")
            local = jax.tree_util.tree_map(
                lambda a: jnp.squeeze(a, 0), eparams)
            y = moe_ffn(expert, local, blk["router"], flat,
                        moe_capacity_factor, axis_name=ep_axis,
                        top_k=moe_top_k, return_aux=return_moe_aux)
        if return_moe_aux:
            y, aux = y
            return x + y.reshape(Bq, Lq, Dq).astype(x.dtype), aux
        return x + y.reshape(Bq, Lq, Dq).astype(x.dtype)
    if return_moe_aux:
        raise ValueError("return_moe_aux=True on a dense block (no router)")
    if tp_axis is not None:
        h = tp_enter(h, tp_axis)
    h = h @ blk["w1"].astype(cd) + blk["b1"].astype(cd)
    h = jax.nn.gelu(h)
    h = h @ blk["w2"].astype(cd)
    if tp_axis is not None:   # hidden was sharded: reduce ("g")
        h = tp_reduce(h, tp_axis)
    return x + h + blk["b2"].astype(cd)


def block_apply(blk: PyTree, x: jax.Array, cd, *, seq_attn=None,
                seq_axis: str | None = None, tp_axis: str | None = None,
                ep_axis: str | None = None,
                moe_capacity_factor: float = 1.25, moe_top_k: int = 1,
                return_moe_aux: bool = False,
                attn_impl: str | None = None):
    """One transformer block (pre-norm attention + FFN/MoE residuals) on a
    LOCAL param shard — the single source of truth for the block math,
    shared by :func:`transformer_lm`'s apply and the pipeline-parallel
    stage fn (distlearn_tpu.train.lm.build_lm_pp_step).  ``cd`` is the
    compute dtype; axes as in :func:`transformer_lm`.

    ``return_moe_aux=True`` (MoE blocks only) returns ``(x, aux)`` with
    the routing-health dict from :func:`distlearn_tpu.parallel.ep
    .route_topk` (balance loss + dropped fraction) — an explicit output,
    not a side channel, so it survives ``jax.checkpoint``."""
    x = attn_apply(blk, x, cd, seq_attn=seq_attn, seq_axis=seq_axis,
                   tp_axis=tp_axis, attn_impl=attn_impl)
    return ffn_apply(blk, x, cd, tp_axis=tp_axis, ep_axis=ep_axis,
                     moe_capacity_factor=moe_capacity_factor,
                     moe_top_k=moe_top_k, return_moe_aux=return_moe_aux)


def transformer_lm(vocab: int = 256, dim: int = 128, depth: int = 2,
                   heads: int = 4, mlp_ratio: int = 4, max_len: int = 2048,
                   dtype=jnp.float32, compute_dtype=None,
                   seq_impl: str = "ring", remat: bool = False,
                   attn_impl: str | None = None, scan_blocks: bool = False,
                   moe_experts: int = 0, moe_every: int = 2,
                   moe_capacity_factor: float = 1.25,
                   moe_top_k: int = 1) -> Model:
    """Returns a :class:`Model` whose ``apply(params, state, tokens, ...)``
    maps int tokens [B, L_local] -> next-token logits [B, L_local, vocab].

    ``axis_name`` (data axis) is unused here; sequence and tensor axes are
    passed per-call via ``seq_axis`` / ``tp_axis`` keywords.  ``seq_impl``
    picks the sequence-parallel attention: ``"ring"`` (neighbor-hop K/V
    rotation, unbounded L) or ``"alltoall"`` (Ulysses head-scatter — needs
    heads divisible by the seq axis and the full score block in memory).
    ``attn_impl`` picks the single-device attention kernel
    (``"xla"``/``"flash"``/``"chunked"`` — see
    :func:`distlearn_tpu.parallel.sequence.local_attention`; None = env
    default).  It applies whenever the attention runs locally: no
    ``seq_axis``, or a size-1 sequence axis.  With a real (>1) sequence
    axis the ring/all-to-all blockwise math takes over and the knob is
    inert — see :func:`distlearn_tpu.parallel.sequence.ring_attention`
    for why (and for the zigzag layout that does the causal FLOP cut
    there).

    ``remat=True`` (= ``"full"``) wraps each block in ``jax.checkpoint``:
    activations are recomputed in the backward pass instead of saved — HBM
    drops from O(depth * L * dim) to O(L * dim) at ~1/3 extra FLOPs, the
    standard trade for long-context/deep configs.  ``remat="mlp"`` is the
    selective middle ground (Megatron-style selective activation
    recomputation): only the FFN half of each block is checkpointed, so
    the attention output AND the flash kernel's softmax residuals stay
    saved — the backward pass never re-runs the attention forward, at the
    cost of keeping O(L * dim) attention activations per block live.

    ``scan_blocks=True`` stores the per-block parameters STACKED on a
    leading ``[depth]`` axis (``params["blocks"]``) and runs the depth
    loop as one ``lax.scan`` — the program no longer grows with depth
    (the unrolled loop's ~depth-fold program size is what made very deep
    / very long configs exceed this environment's compile limits).
    Identical math to the unrolled layout (tested); convert between
    layouts with :func:`stack_block_params` / :func:`unstack_block_params`.
    Requires a homogeneous dense stack (no MoE blocks — their routed
    leaves are a different pytree shape).

    ``moe_experts=E`` makes every ``moe_every``-th block's FFN a routed
    top-``moe_top_k`` mixture of ``E`` experts (parallel/ep.py; k=1 is
    Switch, k=2 GShard).  Pass ``ep_axis`` to ``apply`` to shard the
    experts one-per-device over that mesh axis (requires ``E == axis
    size``; the data axis is the usual choice — EP group == DP group);
    with ``ep_axis=None`` all experts run locally.  MoE blocks bypass
    tensor parallelism (their parallelism IS the expert axis); the router
    stays replicated so routing is identical everywhere.

    MoE models return routing-health metrics through the state output:
    ``apply`` yields ``(logits, {"moe_balance_loss", "moe_dropped_frac"})``
    — the mean Switch balance loss and dropped-assignment fraction over
    the MoE blocks.  :func:`lm_loss` folds the balance term into the
    training loss with ``moe_balance_weight`` (the Switch §2.2 auxiliary:
    without it, top-1 routing collapses onto a few experts).
    """
    if seq_impl not in ("ring", "alltoall"):
        raise ValueError(f"seq_impl must be 'ring' or 'alltoall', "
                         f"got {seq_impl!r}")
    if isinstance(remat, str):
        if remat not in ("full", "mlp"):
            raise ValueError(f"remat must be False, True/'full', or 'mlp', "
                             f"got {remat!r}")
    else:
        # any truthy non-string (True, 1, ...) means full remat — int-ish
        # config flags must not silently disable checkpointing
        remat = "full" if remat else False
    if moe_experts < 0 or (moe_experts > 0 and moe_every < 1):
        raise ValueError(f"moe_experts must be >= 0 and moe_every >= 1, "
                         f"got {moe_experts}/{moe_every}")
    if moe_experts > 0 and moe_every > depth:
        raise ValueError(
            f"moe_every={moe_every} > depth={depth}: no block would be MoE "
            f"— the requested {moe_experts}-expert model would silently "
            "train dense")
    if moe_experts > 0 and not 1 <= moe_top_k <= moe_experts:
        raise ValueError(f"moe_top_k={moe_top_k} must be in "
                         f"[1, moe_experts={moe_experts}]")
    if scan_blocks and moe_experts:
        raise ValueError(
            "scan_blocks needs a homogeneous dense stack: MoE blocks hold "
            "routed expert leaves the dense blocks lack, so they cannot "
            "ride one lax.scan — drop scan_blocks or moe_experts")
    seq_attn = ring_attention if seq_impl == "ring" else alltoall_attention

    def _is_moe(i: int) -> bool:
        return moe_experts > 0 and (i % moe_every) == moe_every - 1
    head_dim = dim // heads
    hidden = dim * mlp_ratio
    cd = compute_dtype or dtype

    def init(key):
        keys = iter(random.split(key, 4 + depth * 8))
        scale = 1.0 / math.sqrt(dim)
        params = {
            "embed": random.normal(next(keys), (vocab, dim), dtype) * scale,
            "pos": random.normal(next(keys), (max_len, dim), dtype) * scale,
            "out_norm": _norm_init((dim,), dtype),
        }
        for i in range(depth):
            blk = {
                "ln1": _norm_init((dim,), dtype),
                "wq": random.normal(next(keys), (dim, heads, head_dim), dtype) * scale,
                "wk": random.normal(next(keys), (dim, heads, head_dim), dtype) * scale,
                "wv": random.normal(next(keys), (dim, heads, head_dim), dtype) * scale,
                "wo": random.normal(next(keys), (heads, head_dim, dim), dtype) * scale,
                "ln2": _norm_init((dim,), dtype),
            }
            if _is_moe(i):
                E = moe_experts
                blk["router"] = random.normal(next(keys), (dim, E),
                                              dtype) * scale
                blk["we1"] = random.normal(next(keys), (E, dim, hidden),
                                           dtype) * scale
                blk["wb1"] = jnp.zeros((E, hidden), dtype)
                blk["we2"] = random.normal(next(keys), (E, hidden, dim),
                                           dtype) * (1.0 / math.sqrt(hidden))
            else:
                blk["w1"] = random.normal(next(keys), (dim, hidden),
                                          dtype) * scale
                blk["b1"] = jnp.zeros((hidden,), dtype)
                blk["w2"] = random.normal(next(keys), (hidden, dim), dtype) \
                    * (1.0 / math.sqrt(hidden))
                blk["b2"] = jnp.zeros((dim,), dtype)
            params[f"block{i}"] = blk
        if scan_blocks:
            return stack_block_params(params, depth), {}
        return params, {}

    def apply(params, state, tokens, train=True, rng=None, axis_name=None,
              bn_weight=None, seq_axis=None, tp_axis=None, ep_axis=None,
              seq_layout="contig"):
        B, L = tokens.shape
        sa = seq_attn
        if seq_layout not in ("contig", "zigzag"):
            raise ValueError(f"seq_layout must be 'contig' or 'zigzag', "
                             f"got {seq_layout!r}")
        if seq_layout == "zigzag":
            if seq_axis is None:
                raise ValueError(
                    "seq_layout='zigzag' without a sequence axis: the "
                    "layout permutes data across shards — drop it for "
                    "single-shard runs")
            if seq_impl != "ring":
                raise ValueError(
                    "seq_layout='zigzag' needs seq_impl='ring' (the "
                    "all-to-all path applies its causal mask in natural "
                    "order)")
            import functools
            sa = functools.partial(seq_attn, layout="zigzag")
        if seq_axis is not None:
            my = lax.axis_index(seq_axis)
            if seq_layout == "zigzag":
                # local shard = early stripe my ++ late stripe 2n-1-my
                n_sh = compat.axis_size(seq_axis)
                s_len = L // 2
                pa = lax.dynamic_slice_in_dim(params["pos"], my * s_len,
                                              s_len)
                pb = lax.dynamic_slice_in_dim(
                    params["pos"], (2 * n_sh - 1 - my) * s_len, s_len)
                pos_emb = jnp.concatenate([pa, pb], axis=0)
            else:
                pos_emb = lax.dynamic_slice_in_dim(params["pos"], my * L, L)
        else:
            pos_emb = lax.dynamic_slice_in_dim(params["pos"], 0, L)
        x = params["embed"][tokens].astype(cd)
        x = x + pos_emb.astype(cd)[None]

        def make_block(is_moe):
            if remat == "mlp":
                # selective: attention residuals saved, FFN recomputed
                def ffn(blk, x):
                    return ffn_apply(blk, x, cd, tp_axis=tp_axis,
                                     ep_axis=ep_axis,
                                     moe_capacity_factor=moe_capacity_factor,
                                     moe_top_k=moe_top_k,
                                     return_moe_aux=is_moe)
                ffn_ckpt = jax.checkpoint(ffn)

                def block(blk, x):
                    x = attn_apply(blk, x, cd, seq_attn=sa,
                                   seq_axis=seq_axis, tp_axis=tp_axis,
                                   attn_impl=attn_impl)
                    return ffn_ckpt(blk, x)
                return block

            def block(blk, x):
                return block_apply(blk, x, cd, seq_attn=sa,
                                   seq_axis=seq_axis, tp_axis=tp_axis,
                                   ep_axis=ep_axis,
                                   moe_capacity_factor=moe_capacity_factor,
                                   moe_top_k=moe_top_k,
                                   return_moe_aux=is_moe,
                                   attn_impl=attn_impl)
            return jax.checkpoint(block) if remat == "full" else block

        # ONE wrapper per block kind, reused across the depth loop: a fresh
        # jax.checkpoint closure per block stops XLA deduplicating the remat
        # computation (measured 13% slower on the seq-4096 flash+remat
        # bench); sharing restores it
        blk_dense = make_block(False)
        blk_moe = make_block(True) if moe_experts > 0 else None

        balance = dropped = n_moe = 0
        if scan_blocks:
            x, _ = lax.scan(lambda h, blk: (blk_dense(blk, h), None),
                            x, params["blocks"])
        else:
            for i in range(depth):
                if _is_moe(i):
                    x, aux = blk_moe(params[f"block{i}"], x)
                    balance = balance + aux["balance_loss"]
                    dropped = dropped + aux["dropped_frac"]
                    n_moe += 1
                else:
                    x = blk_dense(params[f"block{i}"], x)
        if n_moe:
            state = dict(state, moe_balance_loss=balance / n_moe,
                         moe_dropped_frac=dropped / n_moe)

        x = _rmsnorm(params["out_norm"], x)
        logits = x @ params["embed"].T.astype(cd)
        return logits.astype(dtype), state

    return Model(init=init, apply=apply, name="transformer_lm",
                 input_shape=(max_len,), num_classes=vocab)


def decode_attend(q: jax.Array, ck: jax.Array, cv: jax.Array,
                  live: jax.Array, cd) -> jax.Array:
    """One decode tick's cached attention: ``[B,1,H,D]`` query against the
    ``[B,T,H,D]`` K/V cache under the boolean ``live`` mask (broadcastable
    to ``[B,H,1,T]``; dead cache positions score ``-inf``).  The ONE home
    of the cached-attention math, shared by :func:`greedy_generate` and
    the slot-addressed serving engine (``distlearn_tpu.serve.engine``) —
    token parity between the two is a tested invariant, so the math must
    not fork."""
    D = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, ck,
                   preferred_element_type=jnp.float32)
    s = s * (1.0 / (D ** 0.5))
    s = jnp.where(live, s, -jnp.inf)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w.astype(cd), cv)


def generate_params(params: PyTree) -> tuple[PyTree, int]:
    """Normalize a :func:`transformer_lm` tree for decoding: unstack the
    scanned layout, reject MoE blocks (per-tick routing would compute
    expert capacity over one token — a different model than the one
    trained), and return ``(per_block_params, depth)``.  Shared by
    :func:`greedy_generate` and the serving engine."""
    # numpy trees (checkpoint loads, device_get'd sharded params) are
    # legal input; the decode scan closes over the leaves, and a numpy
    # leaf indexed by a tracer inside the scan body fails to trace.
    params = jax.tree_util.tree_map(jnp.asarray, params)
    if "blocks" in params:
        d = jax.tree_util.tree_leaves(params["blocks"])[0].shape[0]
        params = unstack_block_params(params, d)
    depth = sum(1 for k in params if k.startswith("block"))
    for i in range(depth):
        if "router" in params[f"block{i}"]:
            raise ValueError(
                "greedy decoding supports dense blocks only: per-tick "
                "MoE routing computes capacity over ONE token, not the "
                "batch the router trained with (block"
                f"{i} has a router)")
    return params, depth


def greedy_generate(params: PyTree, tokens: jax.Array, steps: int,
                    compute_dtype=None,
                    attn_impl: str | None = None,
                    prompt_lens: jax.Array | None = None) -> jax.Array:
    """KV-cached greedy decoding for a :func:`transformer_lm` parameter
    tree (per-block layout): ``[B, P]`` prompt -> ``[B, steps]``
    generated ids.

    The training stack is forward/backward only (the reference is a
    training framework); this is the inference half of the LM family —
    one prefill pass caches every block's K/V (same math as
    :func:`attn_apply`, with the projections exposed so the cache can be
    captured), then a ``lax.scan`` emits one token per tick: each tick
    computes ONE position's q/k/v, appends to the cache with a
    ``dynamic_update_slice``, and attends over the cache under a static
    position mask — static shapes throughout, so the whole decode is one
    compiled program (no per-token retrace, no O(T^2) recompute of the
    naive re-run-the-prefix rollout).  DENSE blocks only: per-tick MoE
    routing would compute expert capacity over one token instead of the
    full batch×length the model trained with — a different model, so it
    is rejected rather than silently approximated.  Scanned-layout trees
    (``"blocks"``) are unstacked automatically.  ``attn_impl`` should
    match the model's kernel (float-level kernel differences can flip
    argmax at near-tie logits).  Greedy (argmax) sampling.

    ``prompt_lens`` (``[B]`` ints) lifts the equal-length restriction:
    row ``b`` holds ``prompt_lens[b]`` real tokens LEFT-padded to ``P``
    (pad ids are arbitrary — they are masked out of the attention and
    get position 0's embedding).  Left padding keeps the decode loop
    uniform: every row's last prompt token sits at column ``P-1``, so
    the first generated position is column ``P`` for all rows and each
    row's logical positions are ``column - (P - prompt_lens[b])``.
    ``prompt_lens=None`` is the original equal-length path, bit-for-bit
    unchanged (tested).

    Equivalence to the no-cache rollout is tested
    (tests/test_transformer.py).
    """
    params, depth = generate_params(params)
    cd = compute_dtype or params["embed"].dtype
    B, P = tokens.shape
    T = P + steps
    if T > params["pos"].shape[0]:
        raise ValueError(f"prompt + steps = {T} exceeds max_len "
                         f"{params['pos'].shape[0]}")
    if prompt_lens is not None:
        plens = jnp.asarray(prompt_lens, jnp.int32).reshape(B)
        pad = (P - plens)[:, None]                 # [B,1] left-pad widths

    # ---- prefill: full causal pass, caches seeded with the prompt K/V
    if prompt_lens is None:
        x = params["embed"][tokens].astype(cd)
        x = x + params["pos"][:P].astype(cd)[None]
    else:
        # logical position of column j in row b: j - pad_b (pads clamp to
        # 0 — they never contribute: masked out of every attention below)
        pos_idx = jnp.maximum(jnp.arange(P)[None, :] - pad, 0)   # [B,P]
        x = params["embed"][tokens].astype(cd)
        x = x + params["pos"][pos_idx].astype(cd)
    caches = []
    for i in range(depth):
        blk = params[f"block{i}"]
        q, k, v = attn_qkv(blk, x, cd)
        ck = jnp.zeros((B, T) + k.shape[2:], k.dtype)
        cv = jnp.zeros((B, T) + v.shape[2:], v.dtype)
        caches.append((lax.dynamic_update_slice_in_dim(ck, k, 0, 1),
                       lax.dynamic_update_slice_in_dim(cv, v, 0, 1)))
        if prompt_lens is None:
            att = local_attention(q, k, v, causal=True, impl=attn_impl)
        else:
            # causal AND key-not-pad: same einsum shape as the decode
            # tick, applied over all P query positions at once
            D = q.shape[-1]
            s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                           preferred_element_type=jnp.float32)
            s = s * (1.0 / (D ** 0.5))
            cols = jnp.arange(P)
            # [B,1,q,k]: key k visible to query q iff k <= q (causal) and
            # k is past row b's left padding.  Pad queries additionally
            # see themselves: an all-masked softmax is NaN, and 0*NaN
            # poisons the value einsum for the REAL queries too — self
            # attention keeps pad lanes finite (their K/V stay masked
            # out of every real lane, here and in the decode ticks).
            mask = ((cols[None, None, None, :] <= cols[None, None, :, None])
                    & (cols[None, :] >= pad)[:, None, None, :]) \
                | jnp.eye(P, dtype=bool)[None, None]
            s = jnp.where(mask, s, -jnp.inf)
            w = jax.nn.softmax(s, axis=-1)
            att = jnp.einsum("bhqk,bkhd->bqhd", w.astype(cd), v)
        x = attn_out(blk, x, att, cd)
        x = ffn_apply(blk, x, cd)
    x = _rmsnorm(params["out_norm"], x)
    logits = (x[:, -1] @ params["embed"].T.astype(cd)).astype(jnp.float32)
    first = jnp.argmax(logits, axis=-1)            # [B]

    def decode(carry, _):
        tok, pos, caches = carry                   # tok [B], pos scalar
        x = params["embed"][tok].astype(cd)[:, None]
        if prompt_lens is None:
            x = x + lax.dynamic_slice_in_dim(params["pos"], pos, 1,
                                             0).astype(cd)[None]
        else:
            # row b decodes logical position plens_b + (pos - P)
            x = x + params["pos"][plens + (pos - P)].astype(cd)[:, None]
        new_caches = []
        for i in range(depth):
            blk = params[f"block{i}"]
            ck, cv = caches[i]
            q, k1, v1 = attn_qkv(blk, x, cd)       # [B,1,H,D]
            ck = lax.dynamic_update_slice_in_dim(ck, k1, pos, 1)
            cv = lax.dynamic_update_slice_in_dim(cv, v1, pos, 1)
            new_caches.append((ck, cv))
            live = jnp.arange(T)[None, None, None, :] <= pos
            if prompt_lens is not None:
                live = live & (jnp.arange(T)[None, :]
                               >= pad)[:, None, None, :]
            x = attn_out(blk, x, decode_attend(q, ck, cv, live, cd), cd)
            x = ffn_apply(blk, x, cd)
        x = _rmsnorm(params["out_norm"], x)
        lg = (x[:, 0] @ params["embed"].T.astype(cd)).astype(jnp.float32)
        nxt = jnp.argmax(lg, axis=-1)
        return (nxt, pos + 1, new_caches), tok

    (_, _, _), out = lax.scan(decode, (first, jnp.int32(P), caches),
                              None, length=steps)
    return jnp.swapaxes(out, 0, 1)                 # [B, steps]


def stack_block_params(params: PyTree, depth: int) -> PyTree:
    """Per-block layout (``block0..block{depth-1}``) -> scanned layout
    (the per-block leaves stacked on a leading ``[depth]`` axis under
    ``"blocks"``).  The ``scan_blocks=True`` parameter layout."""
    blocks = [params[f"block{i}"] for i in range(depth)]
    out = {k: v for k, v in params.items() if not k.startswith("block")}
    out["blocks"] = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs),
                                           *blocks)
    return out


def unstack_block_params(params: PyTree, depth: int) -> PyTree:
    """Inverse of :func:`stack_block_params`."""
    out = {k: v for k, v in params.items() if k != "blocks"}
    for i in range(depth):
        out[f"block{i}"] = jax.tree_util.tree_map(lambda a, i=i: a[i],
                                                  params["blocks"])
    return out


def param_specs(params: PyTree, tp_axis: str | None,
                ep_axis: str | None = None) -> PyTree:
    """PartitionSpecs for shard_map in_specs: TP shards heads / MLP hidden
    over ``tp_axis``; EP shards the expert-stacked MoE leaves over
    ``ep_axis`` (router replicated); everything else replicated.  Leaves
    under the scanned ``"blocks"`` layout get the same spec shifted one
    axis right (their leading axis is depth)."""
    def spec_for(path, leaf):
        names = [getattr(p, "key", getattr(p, "name", "")) for p in path]
        leafname = names[-1]
        if leafname in ("we1", "wb1", "we2"):
            spec = P(ep_axis) if ep_axis else P()   # leading expert axis
        elif tp_axis is None:
            spec = P()
        elif leafname in ("wq", "wk", "wv"):
            spec = P(None, tp_axis)          # [E, H, D]: split heads
        elif leafname == "wo":
            spec = P(tp_axis)                # [H, D, E]: split heads
        elif leafname in ("w1",):
            spec = P(None, tp_axis)          # [E, F]: split hidden
        elif leafname in ("b1",):
            spec = P(tp_axis)                # [F]
        elif leafname == "w2":
            spec = P(tp_axis)                # [F, E]: split hidden
        else:
            spec = P()
        if "blocks" in names[:-1]:           # scanned layout: depth axis
            spec = P(None, *spec)
        return spec

    return jax.tree_util.tree_map_with_path(spec_for, params)


def lm_loss(model: Model, params, tokens, seq_axis=None, tp_axis=None,
            ep_axis=None, reduce: bool = True,
            moe_balance_weight: float = 0.0, seq_layout: str = "contig"):
    """Next-token cross-entropy.  With a sequence axis, the final position's
    target lives on the next shard — the shift rides a ppermute so the loss
    is exact across shard boundaries.

    ``reduce=False`` returns the LOCAL shard's share of the global-mean loss
    (local masked sum / global token count) WITHOUT the cross-shard psum —
    the form to differentiate inside shard_map: ``psum`` transposes to
    ``psum`` there, so differentiating the psum'd global loss would scale
    gradients by the seq-axis size; differentiate the local share and psum
    the resulting partial gradients instead (distlearn_tpu.train.lm).

    ``moe_balance_weight`` adds that multiple of the model's Switch
    load-balancing loss (state output ``moe_balance_loss``) — required for
    stable MoE training; ignored for dense models."""
    logits, st = model.apply(params, {}, tokens, train=True,
                             seq_axis=seq_axis, tp_axis=tp_axis,
                             ep_axis=ep_axis, seq_layout=seq_layout)
    bal = (moe_balance_weight * st["moe_balance_loss"]
           if moe_balance_weight and isinstance(st, dict)
           and "moe_balance_loss" in st else None)
    if seq_axis is None:
        targets = tokens[:, 1:]
        lp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32))
        nll = -jnp.take_along_axis(lp, targets[..., None], -1)[..., 0]
        loss = nll.mean()
        return loss + bal if bal is not None else loss
    n = compat.axis_size(seq_axis)
    my = lax.axis_index(seq_axis)
    L = tokens.shape[1]
    if seq_layout == "zigzag":
        # local shard = early stripe a=my ++ late stripe b=2n-1-my.  Each
        # stripe's boundary target is the HEAD of the globally-next
        # stripe: stripe a+1 is rank my+1's early stripe (except a+1 == n,
        # which is rank n-1's own LATE stripe), and stripe b+1 = 2n-my is
        # rank my-1's late stripe (except b == 2n-1 on rank 0 — the
        # global end, masked below).  Two neighbor ppermutes deliver both.
        s_len = L // 2
        ta, tb = tokens[:, :s_len], tokens[:, s_len:]
        early_head, late_head = tokens[:, :1], tokens[:, s_len:s_len + 1]
        from_next = lax.ppermute(early_head, seq_axis,
                                 [(j, (j - 1) % n) for j in range(n)])
        from_prev = lax.ppermute(late_head, seq_axis,
                                 [(j, (j + 1) % n) for j in range(n)])
        bound_a = jnp.where(my == n - 1, late_head, from_next)
        targets = jnp.concatenate([ta[:, 1:], bound_a, tb[:, 1:],
                                   from_prev], axis=1)
        # only the global last position (rank 0's late-stripe tail) has
        # no target
        w = jnp.ones((L,), jnp.float32).at[-1].set(
            jnp.where(my == 0, 0.0, 1.0))
    else:
        # first token of the NEXT shard (ring shift by -1)
        perm = [(j, (j - 1) % n) for j in range(n)]
        nxt_first = lax.ppermute(tokens[:, :1], seq_axis, perm)  # [B,1]
        targets = jnp.concatenate([tokens[:, 1:], nxt_first], axis=1)
        pos = my * L + jnp.arange(L)
        w = (pos < n * L - 1).astype(jnp.float32)
    lp = jax.nn.log_softmax(logits.astype(jnp.float32))
    nll = -jnp.take_along_axis(lp, targets[..., None], -1)[..., 0]
    # mask the target-less global last position; normalize by the GLOBAL
    # token count (a constant — no gradient flows through it)
    count = lax.psum(jnp.sum(w) * tokens.shape[0], seq_axis)
    local = jnp.sum(nll * w[None, :]) / jnp.maximum(count, 1.0)
    if bal is not None:
        # each shard routes its own tokens: 1/n of the balance term per
        # shard makes the psum'd total the cross-shard mean
        local = local + bal / n
    return lax.psum(local, seq_axis) if reduce else local
