"""ResNet v1.5 — the ImageNet-scale stretch model (BASELINE.md "Benchmark
configs to reproduce" row 5; SURVEY.md §7 build order item 8).

The reference never ships a model this size — its largest is the 5-block
CIFAR convnet (examples/Model.lua:19-45) — but the BASELINE configs call for
ResNet-50/ImageNet-class data-parallel training, which is where gradient
bucketing (distlearn_tpu.ops.flatten.make_bucket_spec) earns its keep: the
~25.6M-parameter pytree has 161 leaves, and bucketed psum + fused update
stream over HBM a few times instead of 161.

TPU-first choices:

* NHWC activations / HWIO kernels (MXU-friendly, see models/nn.py).
* v1.5 variant: the stride-2 lives on the 3x3 conv of downsampling
  bottlenecks (better accuracy AND better MXU utilization than v1's
  strided 1x1, which wastes 3/4 of its window positions).
* Kaiming-normal conv init, zero-init of each block's last BN gamma
  (torchvision defaults — the config the BASELINE numbers assume).
* ``compute_dtype=jnp.bfloat16`` runs convs on the MXU in bf16 with f32
  master weights.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import random

from distlearn_tpu.models import nn
from distlearn_tpu.models.core import Model

# depth -> (block counts per stage); bottleneck expansion is 4.
_DEPTHS = {50: (3, 4, 6, 3), 101: (3, 4, 23, 3), 152: (3, 8, 36, 3)}
_WIDTHS = (64, 128, 256, 512)
_EXPANSION = 4


def _bottleneck_init(key, in_ch: int, width: int, dtype, downsample: bool,
                     norm: str = "batch"):
    k = random.split(key, 4)
    out_ch = width * _EXPANSION
    p, s = {}, {}
    use_bn = norm == "batch"
    p["conv1"] = nn.conv2d_init(k[0], in_ch, width, 1, 1, dtype,
                                bias=not use_bn, init="he")
    p["conv2"] = nn.conv2d_init(k[1], width, width, 3, 3, dtype,
                                bias=not use_bn, init="he")
    p["conv3"] = nn.conv2d_init(k[2], width, out_ch, 1, 1, dtype,
                                bias=not use_bn, init="he")
    if use_bn:
        p["bn1"], s["bn1"] = nn.batchnorm_init(width, dtype)
        p["bn2"], s["bn2"] = nn.batchnorm_init(width, dtype)
        p["bn3"], s["bn3"] = nn.batchnorm_init(out_ch, dtype)
        # zero-init the residual branch's last gamma: each block starts as
        # identity, the torchvision zero_init_residual recipe
        p["bn3"]["scale"] = jnp.zeros_like(p["bn3"]["scale"])
    else:
        # SkipInit (De & Smith 2020): the branch is scaled by a learnable
        # scalar initialized to ZERO, so every block starts as identity —
        # the same start-as-identity property zero-gamma BN provides,
        # without any channel-statistics reductions
        p["alpha"] = jnp.zeros((), dtype)
    if downsample or in_ch != out_ch:
        p["conv_proj"] = nn.conv2d_init(k[3], in_ch, out_ch, 1, 1, dtype,
                                        bias=not use_bn, init="he")
        if use_bn:
            p["bn_proj"], s["bn_proj"] = nn.batchnorm_init(out_ch, dtype)
    return p, s


def _bottleneck_apply(p, s, x, stride, train, axis_name, bn_weight,
                      compute_dtype):
    ns = {}

    def bn(name, h):
        y, ns[name] = nn.batchnorm(p[name], s[name], h, train=train,
                                   eps=1e-5, momentum=0.1,
                                   axis_name=axis_name, weight=bn_weight)
        return y

    norm_free = "alpha" in p
    h = nn.conv2d(p["conv1"], x, compute_dtype=compute_dtype)
    h = jnp.maximum(h if norm_free else bn("bn1", h), 0)
    # v1.5: the 3x3 carries the stride
    h = nn.conv2d(p["conv2"], h, stride=(stride, stride),
                  padding=((1, 1), (1, 1)), compute_dtype=compute_dtype)
    h = jnp.maximum(h if norm_free else bn("bn2", h), 0)
    h = nn.conv2d(p["conv3"], h, compute_dtype=compute_dtype)
    if not norm_free:
        h = bn("bn3", h)
    if "conv_proj" in p:
        sc = nn.conv2d(p["conv_proj"], x, stride=(stride, stride),
                       compute_dtype=compute_dtype)
        if not norm_free:
            sc = bn("bn_proj", sc)
    else:
        sc = x.astype(h.dtype)
    if norm_free:
        h = h * p["alpha"].astype(h.dtype)
    return jnp.maximum(h + sc, 0), ns


def resnet(depth: int = 50, num_classes: int = 1000, dtype=jnp.float32,
           compute_dtype=None, image_size: int = 224,
           norm: str = "batch") -> Model:
    """Factory: ``resnet(50)`` is the flagship ResNet-50 v1.5.

    ``norm="none"`` builds the norm-free SkipInit variant (De & Smith
    2020: zero-init scalar branch gains replace BN's start-as-identity
    role; convs carry biases): no batch statistics exist at all, so the
    ~50% of step time the r3 profile attributed to BN channel reductions
    (docs/PERF.md) is simply absent, and there is no cross-replica
    stats sync.  The accuracy trade is the literature's, not re-verified
    here; the bench reports both variants so the throughput delta is
    measured, not assumed."""
    if depth not in _DEPTHS:
        raise ValueError(f"depth must be one of {sorted(_DEPTHS)}")
    if norm not in ("batch", "none"):
        raise ValueError(f"norm must be 'batch' or 'none', got {norm!r}")
    blocks = _DEPTHS[depth]

    use_bn = norm == "batch"

    def init(key):
        keys = random.split(key, 2 + sum(blocks))
        params, state = {}, {}
        params["conv_stem"] = nn.conv2d_init(keys[0], 3, 64, 7, 7, dtype,
                                             bias=not use_bn, init="he")
        if use_bn:
            params["bn_stem"], state["bn_stem"] = nn.batchnorm_init(64,
                                                                    dtype)
        in_ch, ki = 64, 1
        for si, (width, n_blocks) in enumerate(zip(_WIDTHS, blocks)):
            for bi in range(n_blocks):
                downsample = (bi == 0)
                name = f"stage{si + 1}_block{bi + 1}"
                params[name], state[name] = _bottleneck_init(
                    keys[ki], in_ch, width, dtype, downsample, norm=norm)
                in_ch = width * _EXPANSION
                ki += 1
        params["fc"] = nn.dense_init(keys[ki], in_ch, num_classes, dtype)
        return params, state

    def apply(params, state, x, train=True, rng=None, axis_name=None,
              bn_weight=None):
        new_state = {}
        h = nn.conv2d(params["conv_stem"], x, stride=(2, 2),
                      padding=((3, 3), (3, 3)), compute_dtype=compute_dtype)
        if use_bn:
            h, new_state["bn_stem"] = nn.batchnorm(
                params["bn_stem"], state["bn_stem"], h, train=train,
                eps=1e-5, momentum=0.1, axis_name=axis_name,
                weight=bn_weight)
        h = jnp.maximum(h, 0)
        h = nn.max_pool2d(h, window=(3, 3), stride=(2, 2),
                          padding=((1, 1), (1, 1)))
        for si, (width, n_blocks) in enumerate(zip(_WIDTHS, blocks)):
            for bi in range(n_blocks):
                stride = 2 if (bi == 0 and si > 0) else 1
                name = f"stage{si + 1}_block{bi + 1}"
                h, new_state[name] = _bottleneck_apply(
                    params[name], state[name], h, stride, train, axis_name,
                    bn_weight, compute_dtype)
        h = jnp.mean(h, axis=(1, 2))          # global average pool
        logits = nn.dense(params["fc"], h, compute_dtype=compute_dtype)
        return nn.log_softmax(logits.astype(dtype)), new_state

    return Model(init=init, apply=apply, name=f"resnet{depth}",
                 input_shape=(image_size, image_size, 3),
                 num_classes=num_classes)


def resnet50(num_classes: int = 1000, dtype=jnp.float32, compute_dtype=None,
             image_size: int = 224, norm: str = "batch") -> Model:
    return resnet(50, num_classes, dtype, compute_dtype, image_size,
                  norm=norm)
