"""Model container: the functional equivalent of the reference's
``{params, f, df}`` export (examples/Model.lua:81-85).

A :class:`Model` bundles ``init`` (params + mutable state from a PRNG key) and
``apply`` (pure forward).  ``loss_fn`` mirrors the reference's ``f`` returning
``(loss, prediction)`` (examples/Model.lua:57-61); gradients come from
``jax.value_and_grad`` — the ``df = grad(f, ...)`` equivalent, with
``stableGradients`` buffer pinning unnecessary under XLA's functional model.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp

from distlearn_tpu.models import nn

PyTree = Any


class Model(NamedTuple):
    """``init(key) -> (params, state)``;
    ``apply(params, state, x, train, rng, axis_name) -> (logits, new_state)``.

    ``state`` carries batch-norm running stats (empty dict when none);
    ``axis_name`` enables cross-replica (sync) batchnorm statistics.
    """
    init: Callable[..., tuple[PyTree, PyTree]]
    apply: Callable[..., tuple[jax.Array, PyTree]]
    name: str
    input_shape: tuple[int, ...]   # per-example, e.g. (32, 32, 1)
    num_classes: int


def loss_fn(model: Model, params: PyTree, state: PyTree, x, y,
            train: bool = True, rng=None, axis_name: str | None = None,
            bn_weight=None):
    """NLL loss over log-softmax outputs (ref examples/Model.lua:50-61).

    Returns ``(loss, (log_probs, new_state))`` — shaped for
    ``jax.value_and_grad(..., has_aux=True)``.
    """
    log_probs, new_state = model.apply(params, state, x, train=train, rng=rng,
                                       axis_name=axis_name, bn_weight=bn_weight)
    loss = nn.nll_loss(log_probs, y)
    return loss, (log_probs, new_state)


def param_count(params: PyTree) -> int:
    return sum(int(jnp.size(p)) for p in jax.tree_util.tree_leaves(params))
