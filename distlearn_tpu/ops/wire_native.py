"""Optional native (SIMD C) backend for the host wire codec.

The blocked-numpy route in :mod:`wire_kernels` is pass-count-bound: numpy
cannot fuse ``div -> rint -> cast -> mul -> sub`` into one walk, so the
int8 encode floor is ~5 separate ufunc passes (~2.1x the reference, not
the 3x the wire budget targets).  This module closes the gap with a
~40-line C kernel compiled by the SYSTEM compiler at first use: one
single pass per leaf computes ``q = rint(d/scale)`` and the
error-feedback residual ``r = d - q*scale`` together, auto-vectorized
(the bench host emits 64-byte AVX-512 vectors).  Measured on that host:
3.3x lower encode ns/byte than the reference numpy path over the CIFAR
leaf set, 4.1x on the single 13 MB conv kernel (bench.py
``wire_cpu_bench``; docs/PERF.md carries the table).

Strictly optional and silently degradable: no compiler, a failed
compile, a failed load, or ``DISTLEARN_TPU_WIREC=0`` all fall back to
the blocked-numpy route — nothing is installed and no third-party
package is required.  :func:`why_unavailable` reports the reason.

Bitwise parity with the numpy reference is load-bearing (the 50-round
EASGD trajectory tests run with this backend active by default):

* compiled ``-ffp-contract=off`` so ``r = d - q*scale`` stays two IEEE
  ops (no FMA), exactly like numpy's separate ``multiply``/``subtract``;
* division, ``rintf`` (round-half-to-even, the x86 default rounding
  mode) and the float->int8 cast of an already-integral value are all
  exact IEEE singles, so q/scale/r match numpy bit for bit — including
  subnormal scales (no FTZ/DAZ: the MXCSR is left alone);
* only the amax MAX-reduction is compiled with relaxed NaN/signed-zero
  semantics (gcc will not vectorize it otherwise) — safe because max
  over finite ``|x|`` is exact under any association, callers reject
  non-finite input first via :func:`bad` (a strict-IEEE scan where
  ``!(|x| <= FLT_MAX)`` catches inf AND NaN), and an all-zero amax hits
  the python-level ``scale == 0`` special case where ``-0.0 == 0.0``.

The in-place apply has its own entry point (``t += q*scale``): the
restrict-qualified out-of-place kernel must not be called with
``out`` aliasing ``t``.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import threading

import numpy as np

from distlearn_tpu.utils import flags

__all__ = [
    "available", "why_unavailable", "usable_quant", "usable_apply",
    "amax_checked", "quant_ef_f32", "dequant_add_f32",
]

_SRC = r"""
#include <stdint.h>
#include <stddef.h>
#include <math.h>
#include <float.h>

/* Non-finite scan: !(|x| <= FLT_MAX) is true for inf AND NaN, and the
   int OR-reduction vectorizes under strict IEEE flags. */
int wirec_bad_f32(const float *x, size_t n) {
    int bad = 0;
    for (size_t i = 0; i < n; i++)
        bad |= !(fabsf(x[i]) <= FLT_MAX);
    return bad;
}

/* MAX reduction; relaxed NaN/signed-zero semantics ONLY here (callers
   scan with wirec_bad_f32 first — see module docstring). */
__attribute__((optimize("finite-math-only", "no-signed-zeros")))
float wirec_amax_f32(const float *x, size_t n) {
    float m = 0.0f;
    for (size_t i = 0; i < n; i++) {
        float a = fabsf(x[i]);
        m = a > m ? a : m;
    }
    return m;
}

/* The fused encode: q = rint(d/scale); r = d - q*scale, one pass.
   -ffp-contract=off keeps mul+sub as two IEEE ops (numpy parity). */
void wirec_quant_ef_f32(const float *restrict d, float scale,
                        int8_t *restrict q, float *restrict r, size_t n) {
    for (size_t i = 0; i < n; i++) {
        float s = rintf(d[i] / scale);
        q[i] = (int8_t)s;
        float dq = s * scale;
        r[i] = d[i] - dq;
    }
}

/* Fused dequantize + elastic apply, out must NOT alias t. */
void wirec_dequant_add_f32(const float *restrict t, const int8_t *restrict q,
                           float scale, float *restrict out, size_t n) {
    for (size_t i = 0; i < n; i++) {
        float dq = (float)q[i] * scale;
        out[i] = t[i] + dq;
    }
}

/* Exact-overlap variant (the serial server's in-place apply). */
void wirec_dequant_add_inplace_f32(float *t, const int8_t *restrict q,
                                   float scale, size_t n) {
    for (size_t i = 0; i < n; i++) {
        float dq = (float)q[i] * scale;
        t[i] = t[i] + dq;
    }
}
"""

#: -march=native: the cached .so is host-specific (keyed into the cache
#: name); -fno-math-errno/-fno-trapping-math unblock vectorization of
#: rintf and the compare reductions without changing any finite result.
_CFLAGS = ("-O3", "-march=native", "-ffp-contract=off", "-fno-math-errno",
           "-fno-trapping-math", "-shared", "-fPIC")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False
_why: str | None = None


def _cache_dir() -> str:
    d = os.environ.get("DISTLEARN_TPU_WIREC_CACHE")
    if not d:
        d = os.path.join(tempfile.gettempdir(),
                         f"distlearn-wirec-{os.getuid()}")
    os.makedirs(d, mode=0o700, exist_ok=True)
    return d


def _compiler() -> str | None:
    import shutil
    for cc in (os.environ.get("CC"), "cc", "gcc", "clang"):
        if cc and shutil.which(cc):
            return cc
    return None


def _build() -> tuple[ctypes.CDLL | None, str | None]:
    cc = _compiler()
    if cc is None:
        return None, "no C compiler on PATH (cc/gcc/clang)"
    try:
        import platform
        key = hashlib.sha256(
            (_SRC + "\0" + " ".join(_CFLAGS) + "\0" + cc + "\0"
             + platform.machine()).encode()).hexdigest()[:16]
        cache = _cache_dir()
        so = os.path.join(cache, f"wirec_{key}.so")
        if not os.path.exists(so):
            src = os.path.join(cache, f"wirec_{key}.c")
            with open(src, "w") as fh:
                fh.write(_SRC)
            tmp = f"{so}.tmp{os.getpid()}"
            proc = subprocess.run([cc, *_CFLAGS, "-o", tmp, src],
                                  capture_output=True, text=True,
                                  timeout=120)
            if proc.returncode != 0:
                return None, f"{cc} failed: {proc.stderr.strip()[:400]}"
            os.replace(tmp, so)       # atomic vs concurrent builders
        lib = ctypes.CDLL(so)
    except (OSError, subprocess.SubprocessError, ValueError) as e:
        return None, f"{type(e).__name__}: {e}"
    lib.wirec_bad_f32.restype = ctypes.c_int
    lib.wirec_bad_f32.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
    lib.wirec_amax_f32.restype = ctypes.c_float
    lib.wirec_amax_f32.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
    lib.wirec_quant_ef_f32.argtypes = [
        ctypes.c_void_p, ctypes.c_float, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_size_t]
    lib.wirec_dequant_add_f32.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_float, ctypes.c_void_p,
        ctypes.c_size_t]
    lib.wirec_dequant_add_inplace_f32.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_float, ctypes.c_size_t]
    return lib, None


def _get() -> ctypes.CDLL | None:
    global _lib, _tried, _why
    if not _tried:
        with _lock:
            if not _tried:
                _lib, _why = _build()
                _tried = True
    return _lib


def _enabled() -> bool:
    # consulted per call (cheap env read) so tests can pin the
    # blocked-numpy route with monkeypatch.setenv without reimporting
    env = flags.env_truthy("DISTLEARN_TPU_WIREC")
    return True if env is None else env


def available() -> bool:
    """True when the native backend is compiled, loadable, and enabled."""
    return _enabled() and _get() is not None


def why_unavailable() -> str | None:
    if not _enabled():
        return "disabled via DISTLEARN_TPU_WIREC"
    if _get() is None:
        return _why
    return None


def _f32c(a: np.ndarray) -> bool:
    return a.dtype == np.float32 and a.flags.c_contiguous


def usable_quant(d: np.ndarray, q: np.ndarray, r: np.ndarray) -> bool:
    """Native route preconditions for the fused encode: f32 delta and
    residual, int8 q, all C-contiguous (the kernels take flat views —
    reshape(-1) of a non-contiguous array would silently copy and drop
    the q/r writes)."""
    return (available() and _f32c(d) and _f32c(r)
            and q.dtype == np.int8 and q.flags.c_contiguous)


def usable_apply(t: np.ndarray, wirebuf: np.ndarray,
                 out: np.ndarray) -> bool:
    return (available() and _f32c(t) and _f32c(out)
            and wirebuf.dtype == np.int8 and wirebuf.flags.c_contiguous)


def amax_checked(flat: np.ndarray) -> float:
    """``float(np.max(np.abs(flat)))`` with the reference's non-finite
    convention: returns ``nan`` when any element is inf/NaN (the caller's
    ``isfinite`` gate raises, message unchanged)."""
    lib = _get()
    n = flat.size
    if lib.wirec_bad_f32(flat.ctypes.data, n):
        return float("nan")
    return lib.wirec_amax_f32(flat.ctypes.data, n)


def quant_ef_f32(flat: np.ndarray, st: np.float32, qf: np.ndarray,
                 rf: np.ndarray) -> None:
    """One fused pass: ``qf = rint(flat/st)`` (int8), ``rf = flat -
    qf*st``.  Caller guarantees finite input and ``st != 0``."""
    _get().wirec_quant_ef_f32(flat.ctypes.data, ctypes.c_float(st),
                              qf.ctypes.data, rf.ctypes.data, flat.size)


def dequant_add_f32(tf: np.ndarray, wf: np.ndarray, st: np.float32,
                    of: np.ndarray) -> bool:
    """``of = tf + wf*st`` fused; picks the in-place kernel on exact
    aliasing, refuses (returns False -> caller falls back to numpy) on
    partial overlap, which would break the restrict contract."""
    lib = _get()
    if of.ctypes.data == tf.ctypes.data and of.nbytes == tf.nbytes:
        lib.wirec_dequant_add_inplace_f32(tf.ctypes.data, wf.ctypes.data,
                                          ctypes.c_float(st), tf.size)
        return True
    if np.may_share_memory(tf, of):
        return False
    lib.wirec_dequant_add_f32(tf.ctypes.data, wf.ctypes.data,
                              ctypes.c_float(st), of.ctypes.data, of.size)
    return True
