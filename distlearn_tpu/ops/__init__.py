"""Pallas TPU kernels for the hot ops (fused updates; flat/bucket packing)."""

from distlearn_tpu.ops.flatten import (Bucket, BucketSpec, FlatSpec,
                                       make_bucket_spec, make_spec, pack,
                                       pack_buckets, unpack, unpack_buckets)
from distlearn_tpu.ops.fused_update import (elastic_round_buckets,
                                            fused_elastic, fused_enabled,
                                            fused_sgd, sgd_update_buckets)

__all__ = ["Bucket", "BucketSpec", "FlatSpec", "make_bucket_spec",
           "make_spec", "pack", "pack_buckets", "unpack", "unpack_buckets",
           "elastic_round_buckets", "fused_elastic", "fused_enabled",
           "fused_sgd", "sgd_update_buckets"]
