"""Pallas TPU kernels for the hot ops (fused updates; flat packing)."""

from distlearn_tpu.ops.flatten import FlatSpec, make_spec, pack, unpack
from distlearn_tpu.ops.fused_update import fused_sgd, fused_elastic

__all__ = ["FlatSpec", "make_spec", "pack", "unpack",
           "fused_sgd", "fused_elastic"]
