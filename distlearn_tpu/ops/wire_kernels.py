"""Fused wire-codec kernels — the device↔wire hot path (ROADMAP item 5).

The packed wire (comm/wire.py) quantizes on the host with numpy: int8
encode walks the delta ~6 times (abs, max, div, rint, clip, astype) and
the client's error-feedback residual then *decodes the frame it just
encoded* (another alloc + 2 walks), so a sync's codec cost is ~13
full-buffer memory passes.  On emulated 25 MB/s links the link hides
that; on real DCN the pack/unpack becomes the bound — the QSGD/1-bit-SGD
lesson that quantizer *cost*, not just quantizer ratio, decides
end-to-end throughput (Alistarh et al. 2017; Seide et al. 2014).

Two fused codec ops, each in two backend flavors behind one dispatch
(mirroring ops/fused_update.py):

* ``quantize_ef_into`` — int8 quantize + error-feedback residual in ONE
  pass: ``q = clip(rint(d/scale)); r = d - q*scale`` with ``scale =
  max|d|/127``.  d is read twice (amax + codec), q and r written once —
  the minimum traffic for the round's codec math.
* ``dequant_add`` — dequantize + elastic apply fused: ``c' = c + q*scale``
  without ever materializing the decoded f32 copy the receive path used
  to allocate per sync.

Backends:

* **TPU** — Pallas kernels (:func:`quantize_ef_jax`,
  :func:`dequant_add_jax`), so a device-resident delta quantizes on the
  VPU and only int8 crosses D2H (4x fewer staging bytes).  On non-TPU
  backends the same kernels run in Pallas interpret mode — that is how
  the CPU test mesh proves them against the numpy reference.
* **host native (CPU)** — a tiny single-pass SIMD C kernel
  (:mod:`wire_native`), compiled by the system compiler at first use and
  silently absent when there is no compiler.  This is the CPU production
  route: ~4x lower int8 encode ns/byte than the reference numpy path on
  the bench host (`bench.py wire_cpu_bench`).
* **host blocked (CPU fallback)** — a cache-blocked numpy implementation
  working in L2-resident chunks through one reusable thread-local
  scratch buffer (~2x vs the reference; numpy cannot fuse the 5 ufunc
  passes any further).  Measured on the 1-core bench host, XLA-CPU is
  the wrong tool for this op: every ``jit`` call pays a device_put input
  copy (~2 passes) and its reductions run ~7x slower than numpy's, so
  the interpret/XLA route *loses* to plain numpy.  docs/PERF.md
  "zero-copy wire" carries the numbers.

Bitwise parity with comm/wire.py's reference codec is load-bearing (the
tier-1 EASGD trajectory tests assert it at 50 rounds, S=1 and S=4):

* the chunked amax uses ``max(max(c), -min(c))`` per chunk — max is an
  exact, order-insensitive reduction, so the result equals the
  reference's ``np.max(np.abs(d))`` bit for bit;
* ``scale`` uses the reference's own formula (python-float ``amax/127.0``
  then a cast to the leaf dtype) — double rounding and all;
* the blocked path skips the reference's ``np.clip``: after the
  non-finite amax check every ``|d| <= amax``, so ``|d/scale| <=
  amax/scale <= 127/(1 - 2**-24) < 127.5`` and ``rint`` lands in
  [-127, 127] already — dropping the clip cannot change a single output
  (np.clip is the single most expensive op in the reference walk);
* ``r = d - q*scale`` is evaluated as separate mul + sub (no FMA
  contraction in numpy), matching ``decoded()`` + ``np.subtract``.
"""

from __future__ import annotations

import functools
import math
import threading

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from distlearn_tpu.ops import wire_native
from distlearn_tpu.ops.flatten import LANE
from distlearn_tpu.utils import flags

__all__ = [
    "wirek_enabled", "quantize_ef_into", "fp16_ef_into", "dequant_add",
    "fp16_add", "quantize_ef_jax", "dequant_add_jax", "encode_ef_into",
]


def wirek_enabled(override: bool | None = None) -> bool:
    """Resolve whether the wire path takes the fused codec kernels.

    Priority: explicit ``override`` > ``DISTLEARN_TPU_WIREK`` env (0/1) >
    on by default (the host-blocked path wins on every host measured;
    the env switch exists so the parity tests — and a paranoid operator —
    can pin the original numpy reference path)."""
    if override is not None:
        return bool(override)
    env = flags.env_truthy("DISTLEARN_TPU_WIREK")
    if env is not None:
        return env
    return True


# ---------------------------------------------------------------------------
# Host path: cache-blocked numpy (the CPU production route)
# ---------------------------------------------------------------------------

#: Elements per chunk — 128k f32 = 512 KB keeps chunk + scratch L2-resident
#: (bench.py sweep; below 32k the per-call numpy overhead dominates).
_CHUNK = 1 << 17

_scratch = threading.local()


def _chunk_scratch(dtype: np.dtype) -> np.ndarray:
    """One reusable per-thread chunk buffer per dtype — stripe appliers on
    different server threads must not share it."""
    bufs = getattr(_scratch, "bufs", None)
    if bufs is None:
        bufs = _scratch.bufs = {}
    buf = bufs.get(dtype)       # dtype-keyed: no per-call .name string
    if buf is None:
        buf = bufs[dtype] = np.empty(_CHUNK, dtype)
    return buf


def _amax_blocked(flat: np.ndarray) -> float:
    """``float(np.max(np.abs(flat)))`` without the |x| temporary: chunked
    ``max(max, -min)`` — exact for every float ordering, NaN-propagating
    (a NaN chunk max poisons the python-level max comparisons into
    keeping NaN via the ``!=`` trick below)."""
    amax = -math.inf
    nan = False
    for lo in range(0, flat.size, _CHUNK):
        c = flat[lo:lo + _CHUNK]
        hi = float(c.max())
        neg = -float(c.min())
        if hi != hi or neg != neg:
            nan = True
            break
        if hi > amax:
            amax = hi
        if neg > amax:
            amax = neg
    return math.nan if nan else amax


def quantize_ef_into(d: np.ndarray, q: np.ndarray, r: np.ndarray) -> float:
    """Fused int8 quantize + error-feedback residual, blocked.

    Writes ``q`` (int8, same shape) and ``r = d - dequant(q)`` (same
    dtype/shape — the caller's residual carry), returns the python-float
    ``scale`` for the manifest.  Bitwise-identical to
    ``wire._encode_leaf(d, "int8")`` + ``decoded()`` + ``np.subtract``.
    Raises ``ValueError`` on non-finite input, exactly like the
    reference (the center must never take a poisoned delta)."""
    flat = d.reshape(-1)
    qf = q.reshape(-1)
    rf = r.reshape(-1)
    native = wire_native.usable_quant(d, q, r) and flat.size
    if native:
        amax = wire_native.amax_checked(flat)
    else:
        amax = _amax_blocked(flat) if flat.size else 0.0
    if not math.isfinite(amax):
        raise ValueError(
            "int8 wire codec cannot encode non-finite values (inf/nan leaf)")
    scale = amax / 127.0
    if scale == 0.0:
        qf[...] = 0
        rf[...] = flat          # q decodes to 0 => the whole delta carries
        return scale
    st = d.dtype.type(scale)
    if native:
        wire_native.quant_ef_f32(flat, st, qf, rf)
        return scale
    for lo in range(0, flat.size, _CHUNK):
        c = flat[lo:lo + _CHUNK]
        s = _chunk_scratch(d.dtype)[:c.size]
        np.divide(c, st, out=s)
        np.rint(s, out=s)       # |c/st| <= 127.0000076 -> clip-free (doc top)
        qc = qf[lo:lo + _CHUNK]
        np.copyto(qc, s, casting="unsafe")    # integral values: exact
        # dequant from s, not qc: s holds the same integral values the
        # int8 cast preserved, so s*st == f32(qc)*st bitwise — and reads
        # the hot f32 scratch instead of re-widening int8 (~2.5x faster)
        np.multiply(s, st, out=s)
        np.subtract(c, s, out=rf[lo:lo + _CHUNK])
    return scale


def fp16_ef_into(d: np.ndarray, h: np.ndarray, r: np.ndarray) -> None:
    """Fused fp16 downcast + residual: ``h = f16(d); r = d - widen(h)``,
    blocked through the chunk scratch (the reference decodes the f16
    frame into a fresh full-size f32 array first)."""
    flat = d.reshape(-1)
    hf = h.reshape(-1)
    rf = r.reshape(-1)
    for lo in range(0, flat.size, _CHUNK):
        c = flat[lo:lo + _CHUNK]
        hc = hf[lo:lo + _CHUNK]
        np.copyto(hc, c, casting="unsafe")    # round-to-nearest-even cast
        s = _chunk_scratch(d.dtype)[:c.size]
        np.copyto(s, hc, casting="unsafe")    # widen back (exact)
        np.subtract(c, s, out=rf[lo:lo + _CHUNK])


def dequant_add(t: np.ndarray, wirebuf: np.ndarray, scale: float | None,
                out: np.ndarray | None = None) -> np.ndarray:
    """Fused dequantize + elastic apply: ``out = t + dequant(wirebuf)``
    without materializing the decoded copy.  ``scale`` selects int8
    (float) vs fp16 (None).  ``out`` may alias ``t`` (the serial server's
    in-place apply) or be a fresh buffer (the concurrent server's
    immutable publish); default allocates."""
    if out is None:
        out = np.empty_like(t)
    tf = t.reshape(-1)
    wf = wirebuf.reshape(-1)
    of = out.reshape(-1)
    st = t.dtype.type(scale) if scale is not None else None
    if (st is not None and tf.size
            and wire_native.usable_apply(t, wirebuf, out)
            and wire_native.dequant_add_f32(tf, wf, st, of)):
        return out
    for lo in range(0, tf.size, _CHUNK):
        wc = wf[lo:lo + _CHUNK]
        s = _chunk_scratch(t.dtype)[:wc.size]
        if st is None:
            np.copyto(s, wc, casting="unsafe")      # fp16 widen
        else:
            np.multiply(wc, st, out=s)              # int8 dequant
        np.add(tf[lo:lo + _CHUNK], s, out=of[lo:lo + _CHUNK])
    return out


def fp16_add(t: np.ndarray, wirebuf: np.ndarray,
             out: np.ndarray | None = None) -> np.ndarray:
    return dequant_add(t, wirebuf, None, out=out)


# ---------------------------------------------------------------------------
# Device path: Pallas kernels (TPU production route; interpret on CPU)
# ---------------------------------------------------------------------------

#: int8 min tile is (32, 128) — pad flats to 32*128 elements so one grid
#: covers f32 and int8 refs alike (fused_update pads to the f32 tile only).
_TILE_Q = 32 * LANE

_BLOCK_ROWS = 256      # rows of 128 lanes per grid step, % 32 == 0


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _grid_for(n: int) -> tuple[int, tuple[int, int]]:
    rows = n // LANE
    block_rows = min(_BLOCK_ROWS, rows)
    while rows % block_rows:
        block_rows -= 32            # rows % 32 == 0 by _TILE_Q padding
    return rows // block_rows, (block_rows, LANE)


def _quant_ef_kernel(x_ref, s_ref, q_ref, r_ref):
    x = x_ref[:]
    st = s_ref[0, 0].astype(x.dtype)
    q = jnp.rint(x / st).astype(jnp.int8)
    q_ref[:] = q
    r_ref[:] = x - q.astype(x.dtype) * st


@jax.jit
def _quant_ef_call(x2d: jax.Array, st: jax.Array):
    n = x2d.shape[0] * LANE
    grid, block = _grid_for(n)
    spec = pl.BlockSpec(block, lambda i: (i, 0))
    return pl.pallas_call(
        _quant_ef_kernel,
        out_shape=(jax.ShapeDtypeStruct(x2d.shape, jnp.int8),
                   jax.ShapeDtypeStruct(x2d.shape, x2d.dtype)),
        grid=(grid,),
        in_specs=[spec, pl.BlockSpec((1, 1), lambda i: (0, 0))],
        out_specs=(spec, spec),
        interpret=_interpret(),
    )(x2d, st)


@jax.jit
def _amax_call(x2d: jax.Array) -> jax.Array:
    return jnp.max(jnp.abs(x2d))


def _pad2d(flat: np.ndarray) -> tuple[jax.Array, int]:
    n = flat.size
    padded = -(-max(n, 1) // _TILE_Q) * _TILE_Q
    x = jnp.asarray(flat)
    if padded != n:
        x = jnp.pad(x, (0, padded - n))
    return x.reshape(padded // LANE, LANE), n


def quantize_ef_jax(d: np.ndarray | jax.Array
                    ) -> tuple[np.ndarray, float, np.ndarray]:
    """The Pallas route of :func:`quantize_ef_into` — one fused kernel
    producing ``(q, scale, r)``.  The scale division happens on the HOST
    in python floats (the reference's exact formula), so the kernel is
    purely elementwise and the manifest scale matches numpy bit for bit.
    Inside the kernel ``r`` may be contracted to an FMA by the backend —
    q and scale (the wire-visible outputs) are bitwise-stable; r can
    differ from the reference by <= 1 ulp (tests pin exactly that)."""
    arr = np.asarray(d) if not isinstance(d, jax.Array) else d
    shape = arr.shape
    flat = arr.reshape(-1)
    if flat.size == 0:
        return (np.zeros(shape, np.int8), 0.0,
                np.zeros(shape, np.asarray(arr).dtype))
    x2d, n = _pad2d(flat)
    amax = float(_amax_call(x2d))
    if not math.isfinite(amax):
        raise ValueError(
            "int8 wire codec cannot encode non-finite values (inf/nan leaf)")
    scale = amax / 127.0
    dt = x2d.dtype
    if scale == 0.0:
        return (np.zeros(shape, np.int8), 0.0,
                np.asarray(flat, dtype=dt).reshape(shape).copy())
    st = jnp.asarray(np.array([[dt.type(scale)]], dtype=dt))
    q2d, r2d = _quant_ef_call(x2d, st)
    q = np.asarray(q2d).reshape(-1)[:n].reshape(shape)
    r = np.asarray(r2d).reshape(-1)[:n].reshape(shape)
    return q, scale, r


def _dequant_add_kernel(c_ref, q_ref, s_ref, o_ref):
    c = c_ref[:]
    st = s_ref[0, 0].astype(c.dtype)
    o_ref[:] = c + q_ref[:].astype(c.dtype) * st


@jax.jit
def _dequant_add_call(c2d: jax.Array, q2d: jax.Array, st: jax.Array):
    n = c2d.shape[0] * LANE
    grid, block = _grid_for(n)
    spec = pl.BlockSpec(block, lambda i: (i, 0))
    return pl.pallas_call(
        _dequant_add_kernel,
        out_shape=jax.ShapeDtypeStruct(c2d.shape, c2d.dtype),
        grid=(grid,),
        in_specs=[spec, spec, pl.BlockSpec((1, 1), lambda i: (0, 0))],
        out_specs=spec,
        interpret=_interpret(),
    )(c2d, q2d, st)


def dequant_add_jax(t: np.ndarray | jax.Array, q: np.ndarray,
                    scale: float) -> np.ndarray:
    """The Pallas route of :func:`dequant_add` (int8): the center slice
    and int8 wire bytes meet on the VPU; only the applied result comes
    back.  Used by the device-pinned concurrent server, where it also
    quarters the H2D staging bytes (int8 up instead of decoded f32)."""
    arr = np.asarray(t) if not isinstance(t, jax.Array) else t
    shape = arr.shape
    flat = arr.reshape(-1)
    if flat.size == 0:
        return np.zeros(shape, np.asarray(arr).dtype)
    c2d, n = _pad2d(flat)
    q2d, _ = _pad2d(np.asarray(q).reshape(-1))
    st = jnp.asarray(np.array([[c2d.dtype.type(scale)]], dtype=c2d.dtype))
    o2d = _dequant_add_call(c2d, q2d, st)
    return np.asarray(o2d).reshape(-1)[:n].reshape(shape)


# ---------------------------------------------------------------------------
# Payload assembly: fused encode into a (reusable) frame buffer
# ---------------------------------------------------------------------------

def _use_device_route(x) -> bool:
    """Device-resident leaves on a TPU backend quantize on-device; every
    other combination takes the blocked host route (measured faster on
    CPU than interpret-mode Pallas by an order of magnitude)."""
    return isinstance(x, jax.Array) and jax.default_backend() == "tpu"


def encode_ef_into(leaves, residuals, codec: str, out=None):
    """Fused-codec replacement for the client's encode-then-decode walk:
    one pass per leaf produces the wire bytes AND the error-feedback
    residual (``residuals[i]`` is overwritten with the new carry; raw
    leaves carry a zero residual, matching ``d - decoded() == 0``).

    ``out`` is an optional :class:`wire.FrameBuffer`: wire bytes land in
    one preallocated contiguous region (reused across syncs), so
    ``Conn.send_packed`` ships a single iovec instead of a per-leaf
    gather and steady-state syncs allocate nothing.  Returns a
    ``wire.PackedPayload`` whose manifest is byte-identical to
    ``wire.encode_leaves``'s for the same inputs."""
    from distlearn_tpu.comm import wire

    if codec not in ("fp16", "int8"):
        raise ValueError(
            f"encode_ef_into is for lossy codecs, got {codec!r}")
    arrs = []
    for x in leaves:
        if _use_device_route(x):
            arrs.append(x)
            continue
        a = np.asarray(x)
        if not a.flags.c_contiguous:
            a = np.ascontiguousarray(a)
        arrs.append(a)
    if out is not None:
        total = sum(wire.encoded_nbytes(np.dtype(a.dtype), int(a.size),
                                        codec)
                    for a in arrs)
        out.reserve(total)
    entries, bufs = [], []
    offset = logical = 0
    for a, r in zip(arrs, residuals):
        dtype = np.dtype(a.dtype)
        shape = tuple(a.shape)
        size = int(a.size)
        extra: dict = {}
        if codec == "int8" and dtype.kind == "f":
            enc = "int8"
            if out is not None:
                buf = out.view(offset, size, np.dtype(np.int8), shape)
            else:
                buf = np.empty(shape, np.int8)
            if _use_device_route(a):
                q, scale, rr = quantize_ef_jax(a)
                np.copyto(buf, q)
                np.copyto(r, rr)
            else:
                scale = quantize_ef_into(a, buf, r)
            extra = {"scale": scale}
        elif (codec == "fp16" and dtype.kind == "f"
              and dtype.itemsize > 2):
            enc = "fp16"
            if out is not None:
                buf = out.view(offset, 2 * size, np.dtype(np.float16),
                               shape)
            else:
                buf = np.empty(shape, np.float16)
            if _use_device_route(a):
                a = np.asarray(jax.device_get(a))
            fp16_ef_into(a, buf, r)
        else:
            enc = "raw"
            if _use_device_route(a):
                a = np.asarray(jax.device_get(a))
            if out is not None:
                buf = out.view(offset, a.nbytes, dtype, shape)
                np.copyto(buf, a)
            else:
                buf = a
            if r is not None:
                r[...] = 0          # raw decodes to itself: zero carry
        entry = {"dtype": dtype.name, "shape": list(shape),
                 "enc": enc, "offset": offset, "nbytes": buf.nbytes}
        entry.update(extra)
        entries.append(entry)
        bufs.append(buf)
        offset += buf.nbytes
        logical += size * dtype.itemsize
    manifest = {"v": wire.WIRE_V, "codec": codec, "leaves": entries}
    payload = wire.PackedPayload(manifest, bufs, codec, offset, logical)
    if out is not None:
        payload.frame = out.frame(offset)
    return payload
