"""Pallas TPU kernels for the hot elementwise updates.

Two fused updates (the framework's per-step HBM-bound tail after the
matmul-heavy backward pass):

* :func:`fused_sgd` — ``p' = p - lr * g`` over the packed flat buffer:
  one kernel launch for the whole model instead of one XLA op per leaf.

* :func:`fused_elastic` — the EASGD local move (lua/AllReduceEA.lua:35-39,
  lua/AllReduceEA.md:12-24): ``delta = (p - c) * alpha; p' = p - delta``
  producing both outputs in a single pass over HBM (p and c are each read
  once; p' and delta written once — the minimum possible traffic for the
  round's local math; the psum of delta and the center add ride on XLA
  around the kernel).

On non-TPU backends the kernels run in Pallas interpret mode, so tests and
the CPU mesh exercise the identical code path.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from distlearn_tpu.ops import flatten as flatten_lib
from distlearn_tpu.utils import flags
from distlearn_tpu.ops.flatten import LANE, SUBLANE

PyTree = Any


def fused_enabled(override: bool | None = None) -> bool:
    """Resolve whether trainers take the fused-kernel path.

    Priority: explicit ``override`` > ``DISTLEARN_TPU_FUSED`` env (0/1) >
    on-by-default on TPU, off elsewhere (interpret-mode Pallas on CPU is
    correct but slower than XLA's own fusion, so it is opt-in there)."""
    if override is not None:
        return bool(override)
    env = flags.env_truthy("DISTLEARN_TPU_FUSED")
    if env is not None:
        return env
    return jax.default_backend() == "tpu"

_BLOCK_ROWS = 256  # rows of 128 lanes per grid step (128 KiB f32 per ref)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _grid_for(n: int) -> tuple[int, tuple[int, int]]:
    rows = n // LANE
    block_rows = min(_BLOCK_ROWS, rows)
    # rows is a multiple of SUBLANE by construction (padded to TILE)
    while rows % block_rows:
        block_rows -= SUBLANE
    return rows // block_rows, (block_rows, LANE)


def _sgd_kernel(lr: float, p_ref, g_ref, o_ref):
    p = p_ref[:]
    o_ref[:] = p - jnp.asarray(lr, p.dtype) * g_ref[:].astype(p.dtype)


@functools.partial(jax.jit, static_argnames=("lr",))
def fused_sgd(p_flat: jax.Array, g_flat: jax.Array, lr: float) -> jax.Array:
    """One-launch SGD over packed params (shape [padded], padded % 1024 == 0)."""
    n = p_flat.shape[0]
    grid, block = _grid_for(n)
    shape2d = (n // LANE, LANE)
    spec = pl.BlockSpec(block, lambda i: (i, 0))
    out = pl.pallas_call(
        functools.partial(_sgd_kernel, lr),
        out_shape=jax.ShapeDtypeStruct(shape2d, p_flat.dtype),
        grid=(grid,),
        in_specs=[spec, spec],
        out_specs=spec,
        interpret=_interpret(),
    )(p_flat.reshape(shape2d), g_flat.reshape(shape2d))
    return out.reshape(n)


def _elastic_kernel(alpha: float, p_ref, c_ref, o_ref, d_ref):
    p = p_ref[:]
    d = (p - c_ref[:].astype(p.dtype)) * jnp.asarray(alpha, p.dtype)
    d_ref[:] = d
    o_ref[:] = p - d


@functools.partial(jax.jit, static_argnames=("alpha",))
def fused_elastic(p_flat: jax.Array, c_flat: jax.Array, alpha: float
                  ) -> tuple[jax.Array, jax.Array]:
    """One-launch elastic move: returns ``(new_p, delta)`` (both [padded])."""
    n = p_flat.shape[0]
    grid, block = _grid_for(n)
    shape2d = (n // LANE, LANE)
    spec = pl.BlockSpec(block, lambda i: (i, 0))
    new_p, delta = pl.pallas_call(
        functools.partial(_elastic_kernel, alpha),
        out_shape=(jax.ShapeDtypeStruct(shape2d, p_flat.dtype),
                   jax.ShapeDtypeStruct(shape2d, p_flat.dtype)),
        grid=(grid,),
        in_specs=[spec, spec],
        out_specs=(spec, spec),
        interpret=_interpret(),
    )(p_flat.reshape(shape2d), c_flat.reshape(shape2d))
    return new_p.reshape(n), delta.reshape(n)


# ---------------------------------------------------------------------------
# Pytree-level wrappers over bucketed flat buffers (trainer hot path)
# ---------------------------------------------------------------------------

def sgd_update_buckets(spec: flatten_lib.BucketSpec,
                       params: PyTree, grad_flats: list[jax.Array],
                       lr: float) -> PyTree:
    """Apply ``p' = p - lr*g`` where gradients are already packed (post-psum)
    flat buckets; params are packed, updated by one kernel launch per bucket,
    and unpacked.  Replaces the reference's per-tensor walkTable update loop
    (examples/mnist.lua:112-116) with a few large streaming passes."""
    p_flats = flatten_lib.pack_buckets(spec, params)
    new = [fused_sgd(p, g, lr) for p, g in zip(p_flats, grad_flats)]
    return flatten_lib.unpack_buckets(spec, new)


def elastic_round_buckets(params: PyTree, center: PyTree, alpha: float,
                          axis_name: str,
                          max_bucket_bytes: int | None = None
                          ) -> tuple[PyTree, PyTree]:
    """The full EASGD round (lua/AllReduceEA.lua:35-45) on flat buckets:
    one fused kernel produces (p', delta) per bucket, ONE psum per bucket
    reduces the deltas (vs one per leaf), center moves on the flat buffer.
    Returns ``(new_params, new_center)``."""
    from jax import lax
    spec = flatten_lib.make_bucket_spec(params, max_bucket_bytes)
    p_flats = flatten_lib.pack_buckets(spec, params)
    c_flats = flatten_lib.pack_buckets(spec, center)
    new_p, new_c = [], []
    for p, c in zip(p_flats, c_flats):
        np_, d = fused_elastic(p, c, alpha)
        new_p.append(np_)
        new_c.append(c + lax.psum(d, axis_name))
    return (flatten_lib.unpack_buckets(spec, new_p),
            flatten_lib.unpack_buckets(spec, new_c))
