"""D2H staging: device shard-sums -> one contiguous host frame.

The hybrid hierarchical allreduce (:class:`distlearn_tpu.comm.backend.
HybridBackend`) ends its in-mesh reduce-scatter with each local device
holding a distinct flat shard-sum.  Before the host TCP leg those shards
must become ONE contiguous host vector per dtype group — the buffer the
tree/ring reduction folds into and :meth:`Conn.send_packed` ships as a
single iovec.  :func:`stage_into` does that hop with the same
no-per-sync-allocation discipline as the wire codec kernels: the
destination is a reusable :class:`~distlearn_tpu.comm.wire.FrameBuffer`
grown once to the round's wire size, each device shard copies straight
into its typed window (``np.copyto`` of a device array's host view —
on CPU meshes effectively a memcpy, on TPU the D2H transfer), and the
returned views alias the frame, so the host leg reduces in place with
zero gather copies.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np


def stage_into(fb, arrays: Sequence, dtypes: Sequence[np.dtype]
               ) -> list[np.ndarray]:
    """Stage flat device arrays into ``fb``; return per-array host views.

    Args:
      fb: a :class:`~distlearn_tpu.comm.wire.FrameBuffer`; reserved
        (grow-never-shrink) to the total byte size, then each array's
        addressable shards copy into a typed window at its offset.
      arrays: flat (1-D) global jax.Arrays — e.g. one reduce-scattered
        vector per dtype group, sharded along their only axis.  Every
        shard this process addresses lands at its global index; with a
        fully-addressable mesh (single process) the views come back
        complete.
      dtypes: target dtype per array (the wire dtype of its group).

    Returns:
      One writable 1-D ``np.ndarray`` view per input, all aliasing
      ``fb.buf`` back-to-back — mutating them (e.g. the tree reduction's
      ``reduce_inplace``) mutates the frame that ships.
    """
    if len(arrays) != len(dtypes):
        raise ValueError(f"{len(arrays)} arrays vs {len(dtypes)} dtypes")
    dtypes = [np.dtype(dt) for dt in dtypes]
    sizes, offsets, total = [], [], 0
    for arr, dt in zip(arrays, dtypes):
        if len(arr.shape) != 1:
            raise ValueError(f"stage_into takes flat vectors, got shape "
                             f"{tuple(arr.shape)}")
        total += (-total) % 16  # keep every typed window 16B-aligned
        offsets.append(total)
        sizes.append(int(arr.shape[0]))
        total += sizes[-1] * dt.itemsize
    fb.reserve(total)
    views = []
    for arr, dt, off, size in zip(arrays, dtypes, offsets, sizes):
        dst = fb.view(off, size * dt.itemsize, dt, (size,))
        shards = getattr(arr, "addressable_shards", None)
        if shards is None:  # plain host array (tests / degenerate paths)
            np.copyto(dst, np.asarray(arr), casting="same_kind")
        else:
            for sh in shards:
                np.copyto(dst[sh.index], np.asarray(sh.data),
                          casting="same_kind")
        views.append(dst)
    return views
