"""Pytree <-> flat-buffer packing for single-launch fused updates.

The reference applies its SGD/EA updates tensor-by-tensor through walkTable
(lua/AllReduceSGD.lua:24, lua/AllReduceEA.lua:35-39) — dozens of tiny
elementwise ops.  On TPU the same math wants to stream the ENTIRE parameter
set through the VPU once: pack all leaves into one padded flat buffer, run
one Pallas kernel over it, unpack.  Packing layout is computed once per
pytree structure (static), so under jit the pack/unpack are pure reshapes and
concats XLA fuses away.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

PyTree = Any

LANE = 128
SUBLANE = 8
TILE = LANE * SUBLANE  # f32 min tile elements


class FlatSpec(NamedTuple):
    treedef: Any
    shapes: tuple[tuple[int, ...], ...]
    dtypes: tuple[Any, ...]
    sizes: tuple[int, ...]
    offsets: tuple[int, ...]
    padded: int           # total flat length, multiple of TILE


def make_spec(tree: PyTree) -> FlatSpec:
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    shapes = tuple(tuple(l.shape) for l in leaves)
    dtypes = tuple(l.dtype for l in leaves)
    sizes = tuple(int(np.prod(s)) if s else 1 for s in shapes)
    offsets = tuple(int(x) for x in np.cumsum((0,) + sizes[:-1]))
    total = int(sum(sizes))
    padded = ((total + TILE - 1) // TILE) * TILE
    return FlatSpec(treedef, shapes, dtypes, sizes, offsets, padded)


def pack(spec: FlatSpec, tree: PyTree, dtype=jnp.float32) -> jax.Array:
    """Concatenate every leaf (cast to ``dtype``) into one [padded] vector."""
    leaves = jax.tree_util.tree_leaves(tree)
    flat = jnp.concatenate(
        [jnp.ravel(l).astype(dtype) for l in leaves] +
        ([jnp.zeros(spec.padded - sum(spec.sizes), dtype)]
         if spec.padded > sum(spec.sizes) else []))
    return flat


def unpack(spec: FlatSpec, flat: jax.Array) -> PyTree:
    leaves = []
    for shape, dt, size, off in zip(spec.shapes, spec.dtypes, spec.sizes,
                                    spec.offsets):
        leaves.append(jax.lax.dynamic_slice_in_dim(flat, off, size)
                      .astype(dt).reshape(shape))
    return jax.tree_util.tree_unflatten(spec.treedef, leaves)


# ---------------------------------------------------------------------------
# Dtype-grouped buckets — gradient bucketing for big models
# ---------------------------------------------------------------------------

class Bucket(NamedTuple):
    """One contiguous flat buffer holding a run of same-dtype leaves."""
    dtype: Any
    idx: tuple[int, ...]                  # leaf indices (flatten order)
    shapes: tuple[tuple[int, ...], ...]
    sizes: tuple[int, ...]
    offsets: tuple[int, ...]
    padded: int                           # bucket length, multiple of TILE


class BucketSpec(NamedTuple):
    treedef: Any
    n_leaves: int
    buckets: tuple[Bucket, ...]


def make_bucket_spec(tree: PyTree,
                     max_bucket_bytes: int | None = None) -> BucketSpec:
    """Plan packing of a pytree into per-dtype flat buckets.

    Where the reference walks the parameter table tensor-by-tensor
    (lua/AllReduceSGD.lua:24 walkTable update loop), the TPU path packs
    leaves into a few large contiguous buffers so the gradient psum and the
    fused update each stream once over HBM.  ``max_bucket_bytes`` caps a
    bucket (ResNet-50-sized pytrees want several buckets so XLA can overlap
    the psum of one with the update of another); ``None`` = one bucket per
    dtype.  Mixed-dtype trees never share a bucket (no casting — bitwise
    parity with the per-leaf path).
    """
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    groups: dict[Any, list[int]] = {}
    for i, l in enumerate(leaves):
        groups.setdefault(jnp.asarray(l).dtype, []).append(i)
    buckets = []
    for dt, idxs in groups.items():
        itemsize = np.dtype(dt).itemsize
        cap = None if max_bucket_bytes is None else \
            max(1, int(max_bucket_bytes) // itemsize)
        chunk: list[int] = []
        total = 0
        for i in idxs + [None]:           # None = flush sentinel
            size = None if i is None else \
                int(np.prod(leaves[i].shape)) if leaves[i].shape else 1
            if i is None or (chunk and cap is not None
                             and total + size > cap):
                if chunk:
                    sizes = tuple(
                        int(np.prod(leaves[j].shape)) if leaves[j].shape else 1
                        for j in chunk)
                    offsets = tuple(int(x) for x in np.cumsum((0,) + sizes[:-1]))
                    padded = ((sum(sizes) + TILE - 1) // TILE) * TILE
                    buckets.append(Bucket(
                        dtype=dt, idx=tuple(chunk),
                        shapes=tuple(tuple(leaves[j].shape) for j in chunk),
                        sizes=sizes, offsets=offsets, padded=padded))
                chunk, total = [], 0
            if i is not None:
                chunk.append(i)
                total += size
    return BucketSpec(treedef=treedef, n_leaves=len(leaves),
                      buckets=tuple(buckets))


def pack_buckets(spec: BucketSpec, tree: PyTree) -> list[jax.Array]:
    """Pack a pytree into the bucket buffers (one [padded] array each)."""
    leaves = jax.tree_util.tree_leaves(tree)
    flats = []
    for b in spec.buckets:
        parts = [jnp.ravel(jnp.asarray(leaves[j])) for j in b.idx]
        used = sum(b.sizes)
        if b.padded > used:
            parts.append(jnp.zeros(b.padded - used, b.dtype))
        flats.append(jnp.concatenate(parts))
    return flats


def unpack_buckets(spec: BucketSpec, flats: Sequence[jax.Array]) -> PyTree:
    leaves: list = [None] * spec.n_leaves
    for b, flat in zip(spec.buckets, flats):
        for j, shape, size, off in zip(b.idx, b.shapes, b.sizes, b.offsets):
            leaves[j] = jax.lax.dynamic_slice_in_dim(flat, off, size) \
                .reshape(shape)
    return jax.tree_util.tree_unflatten(spec.treedef, leaves)
