"""Pytree <-> flat-buffer packing for single-launch fused updates.

The reference applies its SGD/EA updates tensor-by-tensor through walkTable
(lua/AllReduceSGD.lua:24, lua/AllReduceEA.lua:35-39) — dozens of tiny
elementwise ops.  On TPU the same math wants to stream the ENTIRE parameter
set through the VPU once: pack all leaves into one padded flat buffer, run
one Pallas kernel over it, unpack.  Packing layout is computed once per
pytree structure (static), so under jit the pack/unpack are pure reshapes and
concats XLA fuses away.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

PyTree = Any

LANE = 128
SUBLANE = 8
TILE = LANE * SUBLANE  # f32 min tile elements


class FlatSpec(NamedTuple):
    treedef: Any
    shapes: tuple[tuple[int, ...], ...]
    dtypes: tuple[Any, ...]
    sizes: tuple[int, ...]
    offsets: tuple[int, ...]
    padded: int           # total flat length, multiple of TILE


def make_spec(tree: PyTree) -> FlatSpec:
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    shapes = tuple(tuple(l.shape) for l in leaves)
    dtypes = tuple(l.dtype for l in leaves)
    sizes = tuple(int(np.prod(s)) if s else 1 for s in shapes)
    offsets = tuple(int(x) for x in np.cumsum((0,) + sizes[:-1]))
    total = int(sum(sizes))
    padded = ((total + TILE - 1) // TILE) * TILE
    return FlatSpec(treedef, shapes, dtypes, sizes, offsets, padded)


def pack(spec: FlatSpec, tree: PyTree, dtype=jnp.float32) -> jax.Array:
    """Concatenate every leaf (cast to ``dtype``) into one [padded] vector."""
    leaves = jax.tree_util.tree_leaves(tree)
    flat = jnp.concatenate(
        [jnp.ravel(l).astype(dtype) for l in leaves] +
        ([jnp.zeros(spec.padded - sum(spec.sizes), dtype)]
         if spec.padded > sum(spec.sizes) else []))
    return flat


def unpack(spec: FlatSpec, flat: jax.Array) -> PyTree:
    leaves = []
    for shape, dt, size, off in zip(spec.shapes, spec.dtypes, spec.sizes,
                                    spec.offsets):
        leaves.append(jax.lax.dynamic_slice_in_dim(flat, off, size)
                      .astype(dt).reshape(shape))
    return jax.tree_util.tree_unflatten(spec.treedef, leaves)
