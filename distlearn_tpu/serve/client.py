"""Minimal request driver for the serving protocol (client side of
``serve.server``): dial, send one ``'G'`` frame, iterate ``'R'`` chunks
until ``done``.  Used by ``examples/lm_client.py`` and the e2e tests;
deliberately synchronous — concurrency is the SERVER's job (continuous
batching), a load generator just opens more connections.
"""

from __future__ import annotations

import time

from distlearn_tpu.comm import transport


class ServeError(RuntimeError):
    """Server rejected or aborted the request (``error`` field, or a
    terminal reason other than ``complete``/``eos``)."""


class ServeClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 retries: int = 60):
        self.conn = transport.connect(host, port, retries=retries)

    def ping(self, timeout: float = 5.0) -> dict:
        """Control round-trip ('J' frame): returns the server's health
        snapshot (queue depth, active slots, draining flag)."""
        self.conn.send_msg({"q": "stats"})
        return self.conn.recv_msg(deadline=time.monotonic() + timeout)

    def generate(self, prompt, max_new: int, *, rid: str | None = None,
                 deadline_s: float | None = None, eos: int | None = None,
                 timeout: float = 60.0, on_chunk=None) -> dict:
        """Run one request to completion.  Returns
        ``{"rid", "tokens", "reason"}``; raises :class:`ServeError` on a
        server-side rejection/abort and :class:`TimeoutError` when no
        chunk lands within ``timeout``.  ``on_chunk(tokens)`` streams
        partial output as it arrives."""
        msg = {"prompt": [int(t) for t in prompt], "max_new": int(max_new)}
        if rid is not None:
            msg["rid"] = rid
        if deadline_s is not None:
            msg["deadline_s"] = float(deadline_s)
        if eos is not None:
            msg["eos"] = int(eos)
        self.conn.send_gen(msg)
        tokens: list[int] = []
        while True:
            kind, chunk = self.conn.recv_serve(
                deadline=time.monotonic() + timeout)
            if kind != "R":
                raise transport.ProtocolError(
                    f"expected stream chunk, got kind {kind!r}")
            if rid is not None and chunk.get("rid") not in (rid, ""):
                continue      # chunk for another request on a shared conn
            if chunk.get("error"):
                raise ServeError(chunk["error"])
            got = chunk.get("tokens") or []
            tokens.extend(int(t) for t in got)
            if got and on_chunk is not None:
                on_chunk(got)
            if chunk.get("done"):
                reason = chunk.get("reason", "complete")
                if reason not in ("complete", "eos"):
                    raise ServeError(f"request ended: {reason}")
                return {"rid": chunk.get("rid"), "tokens": tokens,
                        "reason": reason}

    def close(self):
        self.conn.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
