"""Minimal request driver for the serving protocol (client side of
``serve.server``): dial, send one ``'G'`` frame, iterate ``'R'`` chunks
until ``done``.  Used by ``examples/lm_client.py``, ``serve.router``
and the e2e tests; deliberately synchronous — concurrency is the
SERVER's job (continuous batching), a load generator just opens more
connections.

Failure classification is typed so the router and bare clients agree:

* :class:`ReplicaDead` (a ``ConnectionError``) — the replica went away
  under us: the dial exhausted its deadline, or the stream hit a FIN /
  reset mid-request (``transport.PeerClosed`` rewrapped).  Retrying on
  a DIFFERENT replica is the right move; the router does exactly that
  for requests that haven't produced a token yet.
* :class:`ServeError` — the replica is alive and said no (rejection or
  abort).  A shed rejection carries ``retry_after`` + ``queue_depth``;
  :meth:`ServeClient.generate` honors the hint with jittered backoff
  for ``shed_retries`` attempts before surfacing it.
"""

from __future__ import annotations

import random
import time

from distlearn_tpu.comm import transport
from distlearn_tpu.comm.errors import PeerClosed


class ServeError(RuntimeError):
    """Server rejected or aborted the request (``error`` field, or a
    terminal reason other than ``complete``/``eos``).  ``retry_after``
    and ``queue_depth`` carry the shed hint when the rejection was an
    admission-queue overflow (None otherwise)."""

    def __init__(self, msg: str, *, retry_after: float | None = None,
                 queue_depth: int | None = None):
        super().__init__(msg)
        self.retry_after = retry_after
        self.queue_depth = queue_depth


class ReplicaDead(ConnectionError):
    """The serving replica died under us — dial failed or the stream
    was cut (clean FIN or reset) before the terminal chunk."""


class ServeClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 retries: int = 60, deadline_s: float | None = None):
        try:
            self.conn = transport.connect(host, port, retries=retries,
                                          deadline_s=deadline_s)
        except ConnectionError as e:
            raise ReplicaDead(f"dial {host}:{port} failed: {e}") from e

    def ping(self, timeout: float = 5.0) -> dict:
        """Control round-trip ('J' frame): returns the server's health
        snapshot (queue depth, active slots, draining flag, epoch)."""
        try:
            self.conn.send_msg({"q": "stats"})
            return self.conn.recv_msg(deadline=time.monotonic() + timeout)
        except (PeerClosed, ConnectionResetError, BrokenPipeError) as e:
            raise ReplicaDead(f"replica died during ping: {e!r}") from e

    def generate(self, prompt, max_new: int, *, rid: str | None = None,
                 deadline_s: float | None = None, eos: int | None = None,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 0.0, seed: int = 0, speculate: bool = True,
                 timeout: float = 60.0, on_chunk=None,
                 shed_retries: int = 3) -> dict:
        """Run one request to completion.  Returns
        ``{"rid", "tokens", "reason", "epoch", "accepted",
        "cached_tokens"}``; raises
        :class:`ServeError` on a server-side rejection/abort,
        :class:`ReplicaDead` when the connection dies mid-stream, and
        :class:`TimeoutError` when no chunk lands within ``timeout``.
        ``on_chunk(tokens)`` streams partial output as it arrives.
        ``temperature``/``top_k``/``top_p``/``seed`` select sampled
        decoding (``temperature == 0`` is exact greedy);
        ``speculate=False`` opts a greedy stream out of speculative
        decoding.

        A shed rejection (``retry_after`` in the error chunk) is retried
        on the SAME connection up to ``shed_retries`` times with full
        jitter over a doubling multiple of the hint, then surfaced."""
        msg = {"prompt": [int(t) for t in prompt], "max_new": int(max_new)}
        if rid is not None:
            msg["rid"] = rid
        if deadline_s is not None:
            msg["deadline_s"] = float(deadline_s)
        if eos is not None:
            msg["eos"] = int(eos)
        # non-default only: the plain greedy frame stays byte-identical
        if temperature:
            msg["temperature"] = float(temperature)
        if top_k:
            msg["top_k"] = int(top_k)
        if top_p:
            msg["top_p"] = float(top_p)
        if seed:
            msg["seed"] = int(seed)
        if not speculate:
            msg["speculate"] = False
        for attempt in range(max(0, int(shed_retries)) + 1):
            try:
                return self._stream(msg, rid, timeout, on_chunk)
            except ServeError as e:
                if e.retry_after is None or attempt >= shed_retries:
                    raise
                # full jitter over a doubling multiple of the hint: the
                # shed herd decorrelates instead of re-arriving together.
                time.sleep(random.uniform(
                    0.0, min(30.0, e.retry_after * (2 ** attempt))))
        raise AssertionError("unreachable")  # pragma: no cover

    def _stream(self, msg: dict, rid: str | None, timeout: float,
                on_chunk) -> dict:
        try:
            self.conn.send_gen(msg)
        except (PeerClosed, ConnectionResetError, BrokenPipeError) as e:
            raise ReplicaDead(f"replica died on submit: {e!r}") from e
        tokens: list[int] = []
        epoch = None
        accepted = 0        # speculative drafts the server accepted
        cached = 0          # prompt tokens served from the prefix cache
        while True:
            try:
                kind, chunk = self.conn.recv_serve(
                    deadline=time.monotonic() + timeout)
            except (PeerClosed, ConnectionResetError, BrokenPipeError) as e:
                raise ReplicaDead(
                    f"replica died mid-stream after {len(tokens)} "
                    f"token(s): {e!r}") from e
            if kind != "R":
                raise transport.ProtocolError(
                    f"expected stream chunk, got kind {kind!r}")
            if rid is not None and chunk.get("rid") not in (rid, ""):
                continue      # chunk for another request on a shared conn
            if chunk.get("epoch") is not None:
                epoch = chunk["epoch"]
            if chunk.get("error"):
                raise ServeError(chunk["error"],
                                 retry_after=chunk.get("retry_after"),
                                 queue_depth=chunk.get("queue_depth"))
            if chunk.get("accepted"):
                accepted += int(chunk["accepted"])
            if chunk.get("cached_tokens"):
                cached = int(chunk["cached_tokens"])
            got = chunk.get("tokens") or []
            tokens.extend(int(t) for t in got)
            if got and on_chunk is not None:
                on_chunk(got)
            if chunk.get("done"):
                reason = chunk.get("reason", "complete")
                if reason not in ("complete", "eos"):
                    raise ServeError(f"request ended: {reason}")
                return {"rid": chunk.get("rid"), "tokens": tokens,
                        "reason": reason, "epoch": epoch,
                        "accepted": accepted, "cached_tokens": cached}

    def close(self):
        self.conn.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
