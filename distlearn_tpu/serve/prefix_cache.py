"""Radix-tree KV prefix cache — shared-system-prompt traffic prefills
once (RadixAttention, Zheng et al. 2023; SGLang).

The serving fleet's dominant traffic shape is a few long system
prompts fanned out under millions of distinct user suffixes.  Without
sharing, every request prefills its whole prompt from token 0; with
this cache, the K/V pages computed for a prompt's leading WHOLE pages
are retained after the request finishes and handed — by reference, not
copy — to every later request whose prompt starts with the same
tokens, so a 90%-overlap prompt prefills only its suffix.

Structure
---------
A radix tree over token sequences at PAGE granularity: every edge
label is a whole number of pages of tokens, children are keyed by
their edge's first page (one page of tokens compared at once), and
each node owns the pool pages backing exactly its own edge — a node's
full prefix is the concatenation of the edges (and pages) on its root
path.  Page granularity is what makes sharing safe without copies:

* **Lookup** (:meth:`match`) returns the longest cached prefix as a
  page-aligned token count plus the page ids backing it, capped one
  token short of the prompt (the engine must prefill at least the last
  prompt position itself to produce the first-token logits).
* **Sharing** is reference counting in :class:`~distlearn_tpu.serve.
  kv_cache.PagedKVCache`: an admitted slot installs the matched pages
  as its leading block-table rows (``admit(shared=...)``), each node
  holds its own reference, and a page returns to the free list only
  when the last holder lets go.
* **Copy-on-write discipline is structural.**  A slot only ever writes
  positions ``>= cached_len``; those land in pages the slot allocated
  privately, never in a shared page, so there is no write to trap and
  no copy to make (docs/SERVING.md).  The reserved trash page 0 keeps
  absorbing masked-lane scatters exactly as before — it is never
  cached, never shared, never refcounted.
* **Eviction** (:meth:`evict`) walks least-recently-matched LEAF nodes
  under page pressure (a child's prefix needs its parent's pages, so
  interior nodes only become evictable after their subtree).  Dropping
  a node drops its references; pages shared with a still-running slot
  survive until that slot finishes.  :meth:`clear` drops the whole
  tree — the hot-weight-swap path: cached K/V was computed under the
  outgoing epoch, so the epoch fence (docs/SERVING.md) invalidates the
  cache before any new-epoch request can match stale pages.

The tree is host-side bookkeeping only (a few dict walks per request);
the device never sees it.  Single-threaded by design, like the
scheduler that drives it.
"""

from __future__ import annotations

from typing import Sequence

from distlearn_tpu import obs
from distlearn_tpu.serve.kv_cache import PagedKVCache


class _Node:
    """One radix-tree node: ``edge`` tokens (a whole number of pages)
    extending the parent's prefix, the pool pages backing exactly that
    edge, and children keyed by their edge's first page of tokens."""

    __slots__ = ("edge", "pages", "children", "parent", "stamp")

    def __init__(self, edge: tuple, pages: list, parent):
        self.edge = edge
        self.pages = pages
        self.children: dict[tuple, _Node] = {}
        self.parent = parent
        self.stamp = 0


class RadixPrefixCache:
    """Page-granular radix cache over one engine's :class:`PagedKVCache`.

    ``max_pages`` caps how many pool pages the cache may retain (default
    half the pool): the cache accelerates admission, it must never
    starve it.  ``clock`` is a logical LRU counter, not wall time —
    deterministic under test.
    """

    def __init__(self, kv: PagedKVCache, *, max_pages: int | None = None):
        self.kv = kv
        self.page = kv.page
        self.max_pages = (int(max_pages) if max_pages is not None
                          else max(1, (kv.num_pages - 1) // 2))
        self.root = _Node((), [], None)
        self.pages_held = 0
        self._stamp = 0
        self._c_hits = obs.counter(
            "serve_prefix_cache_hits_total",
            "admissions that reused at least one cached prefix page")
        self._c_miss = obs.counter(
            "serve_prefix_cache_misses_total",
            "admissions that found no cached prefix")
        self._c_evict = obs.counter(
            "serve_prefix_cache_evictions_total",
            "radix nodes dropped (LRU pressure or epoch invalidation)")
        self._g_pages = obs.gauge(
            "serve_prefix_cache_pages",
            "pool pages currently retained by the prefix cache")

    # -- introspection ------------------------------------------------------
    def stats(self) -> dict:
        return {"pages_held": self.pages_held, "max_pages": self.max_pages,
                "nodes": sum(1 for _ in self._walk())}

    def _walk(self):
        stack = [self.root]
        while stack:
            node = stack.pop()
            if node is not self.root:
                yield node
            stack.extend(node.children.values())

    def _touch(self, node: _Node):
        self._stamp += 1
        # the whole root path is "used": a child match keeps its parents
        while node is not None and node is not self.root:
            node.stamp = self._stamp
            node = node.parent

    # -- lookup -------------------------------------------------------------
    def cacheable_len(self, prompt_len: int) -> int:
        """Longest sharable prefix of a ``prompt_len`` prompt: whole
        pages only, and at least one token left for the suffix prefill."""
        return max(0, (int(prompt_len) - 1) // self.page) * self.page

    def match(self, tokens) -> tuple[int, list[int]]:
        """Longest cached page-aligned prefix of ``tokens``.  Returns
        ``(cached_len, pages)`` — ``cached_len`` tokens covered by
        ``pages`` (``cached_len // page`` of them), both possibly 0.
        Counts a hit/miss and refreshes the matched path's LRU stamps;
        the caller installs the pages via ``kv.admit(shared=pages)``
        (which takes the references) in the same scheduling round."""
        toks = tuple(int(t) for t in tokens)
        cap = self.cacheable_len(len(toks))
        node, depth, pages = self.root, 0, []
        while depth + self.page <= cap:
            child = node.children.get(toks[depth:depth + self.page])
            if child is None:
                break
            el = len(child.edge)
            # longest whole-page agreement between the edge and the
            # prompt, clipped to the cacheable cap
            m = 0
            while (m + self.page <= el and depth + m + self.page <= cap
                   and child.edge[m:m + self.page]
                   == toks[depth + m:depth + m + self.page]):
                m += self.page
            if m == 0:
                break
            pages += child.pages[:m // self.page]
            depth += m
            self._touch(child)
            if m < el:
                break               # diverged (or capped) inside the edge
            node = child
        (self._c_hits if depth else self._c_miss).inc()
        return depth, pages

    # -- insert -------------------------------------------------------------
    def insert(self, tokens, pages: Sequence[int]) -> int:
        """Retain the prefix ``tokens[:cacheable_len]`` backed by the
        slot's leading ``pages`` (one per whole page of tokens, freshly
        written by that slot's prefill or adopted from an earlier
        match).  New coverage takes one reference per page; already-
        cached spans keep their existing pages (first writer wins — the
        duplicate pages stay owned by their slot alone and free with
        it).  Returns the number of newly retained pages."""
        toks = tuple(int(t) for t in tokens)
        cap = self.cacheable_len(len(toks))
        pages = [int(p) for p in pages[:cap // self.page]]
        node, depth, i, added = self.root, 0, 0, 0
        while depth < cap:
            child = node.children.get(toks[depth:depth + self.page])
            if child is None:
                take = self._budget_pages(len(pages) - i)
                if take == 0:
                    break
                edge = toks[depth:depth + take * self.page]
                new = _Node(edge, pages[i:i + take], node)
                self.kv.share(new.pages)
                self.pages_held += take
                added += take
                node.children[edge[:self.page]] = new
                self._touch(new)
                break
            el = len(child.edge)
            m = 0
            while (m + self.page <= el and depth + m < cap
                   and child.edge[m:m + self.page]
                   == toks[depth + m:depth + m + self.page]):
                m += self.page
            if m == 0:
                break               # same first page bytes can't differ —
                                    # cap must have run out exactly here
            if m < el:
                # split the edge at the divergence (page boundary) so the
                # shared span becomes a real node the new branch can join
                mid = _Node(child.edge[:m], child.pages[:m // self.page],
                            node)
                mid.stamp = child.stamp
                child.edge = child.edge[m:]
                child.pages = child.pages[m // self.page:]
                child.parent = mid
                mid.children[child.edge[:self.page]] = child
                node.children[mid.edge[:self.page]] = mid
                child = mid
            depth += m
            i += m // self.page
            node = child
            self._touch(node)
        self._g_pages.set(self.pages_held)
        return added

    def _budget_pages(self, want: int) -> int:
        """How many of ``want`` new pages the cache may retain, evicting
        LRU nodes to make room up to ``max_pages``."""
        room = self.max_pages - self.pages_held
        if room < want:
            self.evict_nodes(want - room)
            room = self.max_pages - self.pages_held
        return max(0, min(want, room))

    # -- eviction -----------------------------------------------------------
    def evict_nodes(self, pages_needed: int) -> int:
        """Drop least-recently-matched leaf nodes until at least
        ``pages_needed`` retained pages were let go (or nothing is left
        to evict).  Returns pages released by the CACHE — pages still
        shared with running slots free later, when those slots do."""
        released = 0
        while released < pages_needed:
            leaves = [n for n in self._walk() if not n.children]
            if not leaves:
                break
            victim = min(leaves, key=lambda n: n.stamp)
            released += self._drop(victim)
        self._g_pages.set(self.pages_held)
        return released

    def evict_for_free(self, pages_short: int) -> int:
        """Admission-pressure hook: the pool is ``pages_short`` free
        pages short, release cache references until the FREE LIST grew
        by that much (or the tree is empty).  Returns pages actually
        freed to the pool."""
        freed = 0
        while freed < pages_short:
            leaves = [n for n in self._walk() if not n.children]
            if not leaves:
                break
            victim = min(leaves, key=lambda n: n.stamp)
            held = len(victim.pages)
            before = self.kv.free_pages()
            self._drop(victim)
            freed += self.kv.free_pages() - before
            del held
        self._g_pages.set(self.pages_held)
        return freed

    def _drop(self, node: _Node) -> int:
        self.kv.unref(node.pages)
        released = len(node.pages)
        self.pages_held -= released
        del node.parent.children[node.edge[:self.page]]
        self._c_evict.inc()
        return released

    def clear(self) -> int:
        """Invalidate everything (epoch fence: new weights make every
        cached K/V page stale).  Returns pages released."""
        released = 0
        for node in list(self._walk()):
            self.kv.unref(node.pages)
            released += len(node.pages)
            self._c_evict.inc()
        self.root = _Node((), [], None)
        self.pages_held = 0
        self._g_pages.set(0)
        return released

    # -- invariants (test hook) ---------------------------------------------
    def check(self):
        """Tree/refcount conservation: every node's page count matches
        its edge length, no page is retained by two nodes, every
        retained page has a live reference, and ``pages_held`` is
        exact.  Composes with ``kv.check()`` for pool conservation."""
        seen: set[int] = set()
        held = 0
        for node in self._walk():
            if len(node.edge) % self.page:
                raise AssertionError(f"edge length {len(node.edge)} not "
                                     "page-aligned")
            if len(node.pages) * self.page != len(node.edge):
                raise AssertionError("edge/pages length mismatch")
            for p in node.pages:
                if p in seen:
                    raise AssertionError(f"page {p} retained twice")
                if p <= 0:
                    raise AssertionError("trash page in the tree")
                if self.kv.ref[p] < 1:
                    raise AssertionError(f"retained page {p} has no ref")
                seen.add(p)
            held += len(node.pages)
            if node.children and not all(
                    c.parent is node for c in node.children.values()):
                raise AssertionError("child with a stale parent link")
        if held != self.pages_held:
            raise AssertionError(f"pages_held {self.pages_held} != "
                                 f"{held} counted")
        self.kv.check()
