"""Slot-addressed decode engine — ``greedy_generate``'s prefill/decode
internals refactored for continuous batching (Orca, Yu et al. OSDI '22).

:func:`distlearn_tpu.models.transformer.greedy_generate` fuses prefill +
decode into one program over one batch that lives and dies together.  A
SERVICE can't do that: requests arrive and finish at different times, so
the engine splits the two phases into separately compiled programs over
a persistent paged K/V pool (:mod:`distlearn_tpu.serve.kv_cache`):

* :meth:`DecodeEngine.admit` runs the PREFILL program for one request —
  a full causal pass over its (bucket-padded) prompt whose K/V scatter
  lands in the slot's pages — and returns the first generated token.
* :meth:`DecodeEngine.tick` runs the DECODE program: every active slot
  advances one token in a single dispatch, each slot gathering its own
  K/V through its block-table row.  A request admitted between ticks
  prefills into slot k while the other slots' cached state just sits in
  the pool — nothing is recomputed or rolled back.

Both programs are built from the SAME block math as training and
``greedy_generate`` (``attn_qkv`` / ``attn_out`` / ``ffn_apply`` /
``decode_attend``), so continuous-batched decoding is token-identical
to N isolated ``greedy_generate`` calls — a tier-1-tested invariant
(tests/test_serve.py).

Tensor parallelism: pass ``mesh``/``tp_axis`` and both programs wrap
their body in ``shard_map`` inside ``jax.jit`` (the mesh-wrapped compile
pattern): weights shard per ``param_specs``, the K/V pools shard over
the heads axis, and ``attn_out``/``ffn_apply`` insert the two psums per
block exactly as the training step does.
"""

from __future__ import annotations

import time
from typing import Any

import numpy as np

from distlearn_tpu import obs
from distlearn_tpu.models.transformer import (_rmsnorm, attn_out, attn_qkv,
                                              decode_attend, ffn_apply,
                                              generate_params, param_specs)
from distlearn_tpu.serve.kv_cache import CacheFull, PagedKVCache

PyTree = Any

__all__ = ["DecodeEngine", "CacheFull", "PrefillJob"]


def _sample_token(jax, jnp, lg, temp, tk, tp_, seed, position):
    """Sample one token from a ``[V]`` float32 logits row.

    ``temp == 0`` returns the plain argmax — the SAME expression the
    greedy path always computed, selected by ``where``, so greedy
    decoding stays bitwise-identical with sampling compiled in.
    ``temp > 0`` draws from the temperature-scaled distribution after
    top-k (``tk > 0``) and nucleus top-p (``0 < tp_``) filtering; the
    key is ``fold_in(PRNGKey(seed), position)`` where ``position`` is
    the sequence position the sampled token will occupy — the draw
    depends only on (seed, position), never on batch composition, cache
    hits, or chunking, so a request replays identically anywhere."""
    V = lg.shape[-1]
    greedy = jnp.argmax(lg).astype(jnp.int32)
    scaled = lg / jnp.where(temp > 0, temp, 1.0).astype(jnp.float32)
    srt = jnp.sort(scaled)[::-1]
    kk = jnp.clip(jnp.where(tk > 0, tk, V), 1, V)
    k_thr = srt[kk - 1]
    probs = jax.nn.softmax(srt)
    # keep a sorted token while the mass STRICTLY BEFORE it is < top_p:
    # the head token always survives, so the filter never empties.
    keep = (jnp.cumsum(probs) - probs) < jnp.where(tp_ > 0, tp_, 1.0)
    p_thr = jnp.min(jnp.where(keep, srt, jnp.inf))
    filt = jnp.where(scaled >= jnp.maximum(k_thr, p_thr), scaled,
                     -jnp.inf)
    key = jax.random.fold_in(jax.random.PRNGKey(seed), position)
    samp = jax.random.categorical(key, filt).astype(jnp.int32)
    return jnp.where(temp > 0, samp, greedy)


class PrefillJob:
    """Resumable prefill state for one admitted request: the slot, the
    prompt, and the next position to prefill (``pos`` starts at the
    prefix-cache ``cached`` length).  Drive with
    :meth:`DecodeEngine.prefill_step` until ``done``; ``first`` then
    holds the request's first generated token."""

    __slots__ = ("slot", "prompt", "pos", "cached", "done", "first")

    def __init__(self, slot: int, prompt: np.ndarray, cached: int):
        self.slot = slot
        self.prompt = prompt
        self.pos = int(cached)
        self.cached = int(cached)
        self.done = False
        self.first: int | None = None


def _buckets(max_len: int) -> tuple[int, ...]:
    """Prompt-length compile buckets: powers of two up to ``max_len``
    (inclusive as the last bucket) — prompts pad up to the next bucket
    so the prefill program retraces O(log max_len) times, not once per
    distinct prompt length."""
    out = []
    b = 8
    while b < max_len:
        out.append(b)
        b *= 2
    out.append(max_len)
    return tuple(out)


class DecodeEngine:
    """Continuous-batching decode engine over a fixed-slot paged cache.

    ``params`` is a dense :func:`transformer_lm` tree (per-block or
    scanned layout; MoE rejected).  ``num_slots`` bounds concurrent
    requests; ``max_len`` bounds ``prompt + generated`` per request and
    sizes the page pool (every slot can hold a full-length request).
    """

    def __init__(self, params: PyTree, *, num_slots: int = 4,
                 max_len: int | None = None, page: int = 16,
                 compute_dtype=None, mesh=None, tp_axis: str | None = None,
                 donate: bool = True, spec_k: int = 4,
                 num_pages: int | None = None):
        import jax
        import jax.numpy as jnp
        from distlearn_tpu.utils.compile_cache import enable_compile_cache
        enable_compile_cache()   # warm starts skip the first-tick compile
        self._jax, self._jnp = jax, jnp
        params, self.depth = generate_params(params)
        self.params = params
        self.cd = compute_dtype or params["embed"].dtype
        self.max_len = int(max_len or params["pos"].shape[0])
        if self.max_len > params["pos"].shape[0]:
            raise ValueError(f"max_len={self.max_len} exceeds the model's "
                             f"positional table {params['pos'].shape[0]}")
        wq = params["block0"]["wq"]
        self.heads, self.head_dim = wq.shape[1], wq.shape[2]
        if (mesh is None) != (tp_axis is None):
            raise ValueError("mesh and tp_axis come together (both or "
                             "neither)")
        if tp_axis is not None and self.heads % mesh.shape[tp_axis]:
            raise ValueError(
                f"{self.heads} heads not divisible by the {tp_axis} axis "
                f"({mesh.shape[tp_axis]})")
        self.mesh, self.tp_axis = mesh, tp_axis
        if spec_k < 1:
            raise ValueError(f"spec_k={spec_k} must be >= 1")
        self.spec_k = int(spec_k)
        self.cache = PagedKVCache(num_slots, page, self.max_len,
                                  num_pages=num_pages)
        self.buckets = _buckets(self.max_len)
        # per-slot sampling state (set at begin/admit): temp == 0 means
        # greedy; fixed dtypes so the tick signature never drifts (DL207)
        self._temp = np.zeros((num_slots,), np.float32)
        self._topk = np.zeros((num_slots,), np.int32)
        self._topp = np.zeros((num_slots,), np.float32)
        self._seed = np.zeros((num_slots,), np.int32)
        shape = (self.depth, self.cache.num_pages, page,
                 self.heads, self.head_dim)
        self._k = jnp.zeros(shape, self.cd)
        self._v = jnp.zeros(shape, self.cd)
        if mesh is not None:
            from jax.sharding import NamedSharding
            self._kv_spec = self._pspec(None, None, None, tp_axis)
            sh = NamedSharding(mesh, self._kv_spec)
            self._k = jax.device_put(self._k, sh)
            self._v = jax.device_put(self._v, sh)
        self._tick_fn = self._build_tick(donate)
        self._prefill_fn = self._build_prefill(donate)
        self._chunk_fn = self._build_chunk(donate)
        self._verify_fn = self._build_verify(donate)
        self._m_ticks = obs.counter("serve_engine_ticks_total",
                                    "decode ticks dispatched")
        self._m_prefills = obs.counter("serve_engine_prefills_total",
                                       "prefill programs dispatched")
        self._m_chunks = obs.counter("serve_engine_prefill_chunks_total",
                                     "resumable prefill chunks dispatched")
        self._m_verifies = obs.counter("serve_engine_verifies_total",
                                       "speculative verify ticks dispatched")
        self._h_tick = obs.histogram("serve_tick_seconds",
                                     "one decode tick: dispatch to tokens "
                                     "on host")
        self._h_accept = obs.histogram(
            "serve_spec_accepted_tokens",
            "tokens emitted per slot per verify tick (accepted drafts + "
            "the bonus token; 1 == plain-tick throughput)",
            buckets=(1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 8.0))

    # -- program construction ----------------------------------------------
    def _pspec(self, *names):
        from jax.sharding import PartitionSpec as P
        return P(*names)

    def _map(self, body, in_specs, out_specs):
        """shard_map(body) under TP, the body itself otherwise — the
        mesh is captured at build time so callers never need a mesh
        context.  Sampling stays OUTSIDE the mapped region (see
        ``_build_tick``): the builders compose it around this."""
        if self.mesh is None:
            return body
        from distlearn_tpu.utils.compat import shard_map
        return shard_map(body, mesh=self.mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)

    def _wrap(self, body, in_specs, out_specs, donate):
        """jit(shard_map(body)) under TP, plain jit otherwise."""
        jax = self._jax
        return jax.jit(self._map(body, in_specs, out_specs),
                       donate_argnums=(1, 2) if donate else ())

    def _build_tick(self, donate):
        jax, jnp = self._jax, self._jnp
        params, depth, cd, tp = self.params, self.depth, self.cd, self.tp_axis
        page = self.cache.page
        T = self.cache.pages_per_slot * page

        def tick_core(p, kpool, vpool, bt, lens, toks, active):
            S = toks.shape[0]
            pos = lens                                    # position written
            x = p["embed"][toks].astype(cd)[:, None]      # [S,1,E]
            x = x + p["pos"][pos].astype(cd)[:, None]
            # inactive slots write to the trash page (their block-table
            # rows are all 0 already, but pos//page could index past the
            # row for a stale pos — clamp through where)
            row = jnp.clip(pos // page, 0, bt.shape[1] - 1)
            pages = jnp.where(active, bt[jnp.arange(S), row], 0)
            offs = jnp.where(active, pos % page, 0)
            for i in range(depth):
                blk = p[f"block{i}"]
                q, k1, v1 = attn_qkv(blk, x, cd, tp)      # [S,1,H,D]
                kpool = kpool.at[i, pages, offs].set(k1[:, 0])
                vpool = vpool.at[i, pages, offs].set(v1[:, 0])
                # paged gather: each slot's block-table row pulls its
                # pages from the pool -> a contiguous [S,T,H,D] view
                ck = kpool[i][bt].reshape(S, T, k1.shape[2], k1.shape[3])
                cv = vpool[i][bt].reshape(S, T, v1.shape[2], v1.shape[3])
                live = (jnp.arange(T)[None] <= pos[:, None])[:, None, None]
                x = attn_out(blk, x, decode_attend(q, ck, cv, live, cd),
                             cd, tp)
                x = ffn_apply(blk, x, cd, tp_axis=tp)
            x = _rmsnorm(p["out_norm"], x)
            lg = (x[:, 0] @ p["embed"].T.astype(cd)).astype(jnp.float32)
            return kpool, vpool, lg

        P_ = self._pspec
        specs_in = (param_specs(params, self.tp_axis), self._kv_spec,
                    self._kv_spec, P_(), P_(), P_(), P_()) \
            if self.mesh is not None else None
        specs_out = (self._kv_spec, self._kv_spec, P_()) \
            if self.mesh is not None else None
        core = self._map(tick_core, specs_in, specs_out)

        # sampling runs OUTSIDE the mapped region: the logits leave the
        # tp psum replicated, so every device draws the identical token
        # — and the PRNG key is consumed at the single-logical-device
        # level, never inside SPMD with a replicated key (DL003).
        def tick(p, kpool, vpool, bt, lens, toks, active,
                 temp, topk, topp, seed):
            kpool, vpool, lg = core(p, kpool, vpool, bt, lens, toks,
                                    active)
            # the sampled token occupies position lens + 1 next dispatch
            # — that index keys its draw (see _sample_token)
            nxt = jax.vmap(
                lambda r, t, k_, pp, sd, po:
                _sample_token(jax, jnp, r, t, k_, pp, sd, po))(
                lg, temp, topk, topp, seed, lens + 1)
            return kpool, vpool, nxt

        return jax.jit(tick, donate_argnums=(1, 2) if donate else ())

    def _build_prefill(self, donate):
        jax, jnp = self._jax, self._jnp
        lax = jax.lax
        from distlearn_tpu.parallel.sequence import local_attention
        params, depth, cd, tp = self.params, self.depth, self.cd, self.tp_axis
        page = self.cache.page

        def prefill_core(p, kpool, vpool, btrow, tokens, plen):
            # tokens [1, Pb] RIGHT-padded to the bucket: causal attention
            # means positions < plen never see the garbage tail, and the
            # tail's K/V scatter is routed to the trash page below.
            Pb = tokens.shape[1]
            x = p["embed"][tokens].astype(cd)
            x = x + p["pos"][:Pb].astype(cd)[None]
            posn = jnp.arange(Pb)
            valid = posn < plen
            pages = jnp.where(valid, btrow[posn // page], 0)
            offs = jnp.where(valid, posn % page, 0)
            for i in range(depth):
                blk = p[f"block{i}"]
                q, k, v = attn_qkv(blk, x, cd, tp)
                kpool = kpool.at[i, pages, offs].set(k[0])
                vpool = vpool.at[i, pages, offs].set(v[0])
                att = local_attention(q, k, v, causal=True)
                x = attn_out(blk, x, att, cd, tp)
                x = ffn_apply(blk, x, cd, tp_axis=tp)
            x = _rmsnorm(p["out_norm"], x)
            last = lax.dynamic_index_in_dim(x[0], plen - 1, 0,
                                            keepdims=False)
            lg = (last @ p["embed"].T.astype(cd)).astype(jnp.float32)
            return kpool, vpool, lg

        P_ = self._pspec
        specs_in = (param_specs(params, self.tp_axis), self._kv_spec,
                    self._kv_spec, P_(), P_(), P_()) \
            if self.mesh is not None else None
        specs_out = (self._kv_spec, self._kv_spec, P_()) \
            if self.mesh is not None else None
        core = self._map(prefill_core, specs_in, specs_out)

        def prefill(p, kpool, vpool, btrow, tokens, plen,
                    temp, topk, topp, seed):
            kpool, vpool, lg = core(p, kpool, vpool, btrow, tokens, plen)
            # first generated token occupies position plen; sampling sits
            # outside the mapped region (see _build_tick)
            tok = _sample_token(jax, jnp, lg, temp, topk, topp, seed,
                                plen)
            return kpool, vpool, tok

        return jax.jit(prefill, donate_argnums=(1, 2) if donate else ())

    def _build_chunk(self, donate):
        """Resumable-prefill chunk: the causal pass over prompt positions
        ``[p0, p0 + clen)`` of ONE slot, attending through the slot's
        block-table row into the pool — earlier positions (a cached
        prefix, or chunks already run) are READ from their pages, never
        recomputed.  The full-prompt program (:meth:`_build_prefill`)
        stays the ``p0 == 0`` single-dispatch fast path; this one powers
        prefix-cache resume and decode-interleaved chunking."""
        jax, jnp = self._jax, self._jnp
        lax = jax.lax
        params, depth, cd, tp = self.params, self.depth, self.cd, self.tp_axis
        page = self.cache.page
        T = self.cache.pages_per_slot * page
        L = self.max_len

        def chunk_core(p, kpool, vpool, btrow, tokens, p0, clen):
            # tokens [1, Cb] RIGHT-padded; absolute positions p0 + j.
            Cb = tokens.shape[1]
            j = jnp.arange(Cb)
            posn = p0 + j
            x = p["embed"][tokens].astype(cd)
            x = x + p["pos"][jnp.clip(posn, 0, L - 1)].astype(cd)[None]
            valid = j < clen
            pages = jnp.where(
                valid, btrow[jnp.clip(posn // page, 0,
                                      btrow.shape[0] - 1)], 0)
            offs = jnp.where(valid, posn % page, 0)
            for i in range(depth):
                blk = p[f"block{i}"]
                q, k, v = attn_qkv(blk, x, cd, tp)        # [1,Cb,H,D]
                kpool = kpool.at[i, pages, offs].set(k[0])
                vpool = vpool.at[i, pages, offs].set(v[0])
                ck = kpool[i][btrow].reshape(1, T, k.shape[2], k.shape[3])
                cv = vpool[i][btrow].reshape(1, T, v.shape[2], v.shape[3])
                # query at absolute position p0+j sees cache t <= p0+j:
                # the cached prefix, earlier chunks, and this chunk's own
                # causal prefix (scattered above, same layer)
                live = (jnp.arange(T)[None] <= posn[:, None])[None, None]
                x = attn_out(blk, x, decode_attend(q, ck, cv, live, cd),
                             cd, tp)
                x = ffn_apply(blk, x, cd, tp_axis=tp)
            x = _rmsnorm(p["out_norm"], x)
            last = lax.dynamic_index_in_dim(x[0], clen - 1, 0,
                                            keepdims=False)
            lg = (last @ p["embed"].T.astype(cd)).astype(jnp.float32)
            return kpool, vpool, lg

        P_ = self._pspec
        specs_in = (param_specs(params, self.tp_axis), self._kv_spec,
                    self._kv_spec, P_(), P_(), P_(), P_()) \
            if self.mesh is not None else None
        specs_out = (self._kv_spec, self._kv_spec, P_()) \
            if self.mesh is not None else None
        core = self._map(chunk_core, specs_in, specs_out)

        def chunk(p, kpool, vpool, btrow, tokens, p0, clen,
                  temp, topk, topp, seed):
            kpool, vpool, lg = core(p, kpool, vpool, btrow, tokens, p0,
                                    clen)
            # only the FINAL chunk's output is consumed: the first
            # generated token, occupying position p0 + clen == plen;
            # sampling sits outside the mapped region (see _build_tick)
            tok = _sample_token(jax, jnp, lg, temp, topk, topp, seed,
                                p0 + clen)
            return kpool, vpool, tok

        return jax.jit(chunk, donate_argnums=(1, 2) if donate else ())

    def _build_verify(self, donate):
        """Speculative verify: every participating slot scores K = 1 +
        spec_k positions in one dispatch — lane 0 carries the slot's
        ``last_tok`` (exactly what the plain tick would process), lanes
        1..ndraft carry the drafts.  Output is the model argmax at every
        lane; the host accepts the leading run of drafts matching it
        (greedy equivalence is exact — every emitted token IS the
        argmax at its position).  Rejected lanes scattered K/V past the
        accepted length; that is dead state, not damage: lengths never
        advance over it, attention masks it, later writes overwrite it
        (the implicit-rollback invariant, docs/SERVING.md)."""
        jax, jnp = self._jax, self._jnp
        params, depth, cd, tp = self.params, self.depth, self.cd, self.tp_axis
        page = self.cache.page
        T = self.cache.pages_per_slot * page
        L = self.max_len

        def verify(p, kpool, vpool, bt, lens, toks, active, ndraft):
            S, K = toks.shape
            j = jnp.arange(K)
            pos = lens[:, None] + j[None]                 # [S,K]
            valid = active[:, None] & (j[None] <= ndraft[:, None])
            x = p["embed"][toks].astype(cd)               # [S,K,E]
            x = x + p["pos"][jnp.clip(pos, 0, L - 1)].astype(cd)
            row = jnp.clip(pos // page, 0, bt.shape[1] - 1)
            pages = jnp.where(valid,
                              jnp.take_along_axis(bt, row, axis=1), 0)
            offs = jnp.where(valid, pos % page, 0)
            for i in range(depth):
                blk = p[f"block{i}"]
                q, k, v = attn_qkv(blk, x, cd, tp)        # [S,K,H,D]
                kpool = kpool.at[i, pages, offs].set(k)
                vpool = vpool.at[i, pages, offs].set(v)
                ck = kpool[i][bt].reshape(S, T, k.shape[2], k.shape[3])
                cv = vpool[i][bt].reshape(S, T, v.shape[2], v.shape[3])
                live = (jnp.arange(T)[None, None]
                        <= pos[:, :, None])[:, None]      # [S,1,K,T]
                x = attn_out(blk, x, decode_attend(q, ck, cv, live, cd),
                             cd, tp)
                x = ffn_apply(blk, x, cd, tp_axis=tp)
            x = _rmsnorm(p["out_norm"], x)
            lg = (x @ p["embed"].T.astype(cd)).astype(jnp.float32)
            return kpool, vpool, jnp.argmax(lg, axis=-1).astype(jnp.int32)

        P_ = self._pspec
        specs_in = (param_specs(params, self.tp_axis), self._kv_spec,
                    self._kv_spec, P_(), P_(), P_(), P_(), P_()) \
            if self.mesh is not None else None
        specs_out = (self._kv_spec, self._kv_spec, P_()) \
            if self.mesh is not None else None
        return self._wrap(verify, specs_in, specs_out, donate)

    # -- capacity -----------------------------------------------------------
    def has_capacity(self, prompt_len: int, max_new: int,
                     shared_pages: int = 0) -> bool:
        return self.cache.can_admit(int(prompt_len) + int(max_new),
                                    shared_pages=shared_pages)

    def active_slots(self) -> list[int]:
        return np.flatnonzero(self.cache.active).tolist()

    def bucket_for(self, plen: int) -> int:
        for b in self.buckets:
            if plen <= b:
                return b
        raise ValueError(f"prompt length {plen} exceeds max_len "
                         f"{self.max_len}")

    # -- request lifecycle --------------------------------------------------
    def begin(self, prompt: np.ndarray, max_new: int, *, shared=(),
              temperature: float = 0.0, top_k: int = 0,
              top_p: float = 0.0, seed: int = 0) -> PrefillJob:
        """Claim a slot for ``prompt`` and return a resumable
        :class:`PrefillJob` — no compute happens here.  ``shared`` is a
        list of prefix-cache pages covering the prompt's leading whole
        pages (installed by reference; the job prefills only the
        suffix).  Sampling knobs are per-request: ``temperature == 0``
        (default) is exact greedy.  Raises :class:`CacheFull` when no
        slot/pages fit and ``ValueError`` for an impossible request."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        plen = len(prompt)
        if plen < 1:
            raise ValueError("empty prompt")
        if max_new < 1:
            raise ValueError(f"max_new={max_new} must be >= 1")
        if not 0.0 <= float(temperature):
            raise ValueError(f"temperature={temperature} must be >= 0")
        if not 0.0 <= float(top_p) <= 1.0:
            raise ValueError(f"top_p={top_p} outside [0, 1]")
        total = plen + int(max_new)
        if total > self.max_len:
            raise ValueError(f"prompt({plen}) + max_new({max_new}) = "
                             f"{total} exceeds max_len {self.max_len}")
        shared = [int(p) for p in shared]
        cached = len(shared) * self.cache.page
        if cached >= plen:
            raise ValueError(f"{len(shared)} shared pages cover the whole "
                             f"{plen}-token prompt — at least the last "
                             "position must prefill (it makes the logits)")
        slot = self.cache.admit(total, shared=shared)
        self._temp[slot] = float(temperature)
        self._topk[slot] = int(top_k)
        self._topp[slot] = float(top_p)
        self._seed[slot] = int(seed)
        return PrefillJob(slot, prompt, cached)

    def prefill_step(self, job: PrefillJob,
                     chunk: int | None = None) -> int | None:
        """Run ONE compiled prefill dispatch for ``job`` — at most
        ``chunk`` prompt positions (whole remainder when ``None``) —
        and return the first generated token once the prompt is fully
        prefilled (``job.done``), else ``None``.  An uncached job with
        no chunk bound takes the original single-dispatch full-prompt
        program (the bitwise-parity path); resumed or chunked jobs go
        through the chunk program."""
        if job.done:
            raise ValueError("prefill_step on a finished job")
        jnp = self._jnp
        plen = len(job.prompt)
        remaining = plen - job.pos
        if job.pos == 0 and (chunk is None or chunk >= plen):
            bucket = self.bucket_for(plen)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :plen] = job.prompt
            with obs.span("serve.prefill", slot=job.slot, bucket=bucket):
                self._k, self._v, first = self._prefill_fn(
                    self.params, self._k, self._v,
                    jnp.asarray(self.cache.block_table[job.slot]),
                    jnp.asarray(padded), jnp.int32(plen),
                    jnp.float32(self._temp[job.slot]),
                    jnp.int32(self._topk[job.slot]),
                    jnp.float32(self._topp[job.slot]),
                    jnp.int32(self._seed[job.slot]))
                first = int(first)
            self._m_prefills.inc()
        else:
            take = remaining if chunk is None else min(int(chunk),
                                                       remaining)
            bucket = self.bucket_for(take)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :take] = job.prompt[job.pos:job.pos + take]
            with obs.span("serve.prefill_chunk", slot=job.slot,
                          bucket=bucket, p0=job.pos):
                self._k, self._v, first = self._chunk_fn(
                    self.params, self._k, self._v,
                    jnp.asarray(self.cache.block_table[job.slot]),
                    jnp.asarray(padded), jnp.int32(job.pos),
                    jnp.int32(take),
                    jnp.float32(self._temp[job.slot]),
                    jnp.int32(self._topk[job.slot]),
                    jnp.float32(self._topp[job.slot]),
                    jnp.int32(self._seed[job.slot]))
            self._m_chunks.inc()
            job.pos += take
            if job.pos < plen:
                return None
            first = int(first)
        job.pos = plen
        job.done = True
        job.first = first
        self.cache.lengths[job.slot] = plen
        self.cache.last_tok[job.slot] = first
        return first

    def abort_prefill(self, job: PrefillJob):
        """Release a job that will never finish (deadline/cancel
        mid-prefill): frees the slot and drops its page references."""
        job.done = True
        self.cache.release(job.slot)

    def admit(self, prompt: np.ndarray, max_new: int,
              **kw) -> tuple[int, int]:
        """Prefill ``prompt`` (1-D int array) into a free slot in one
        call; returns ``(slot, first_token)``.  The non-resumable
        wrapper over :meth:`begin` + :meth:`prefill_step`; keyword
        options pass through to :meth:`begin`."""
        job = self.begin(prompt, max_new, **kw)
        first = self.prefill_step(job)
        while first is None:            # cached prefix -> chunk resume
            first = self.prefill_step(job)
        return job.slot, first

    def tick(self, include=None) -> dict[int, int]:
        """Advance every active slot one token in ONE dispatch; returns
        ``{slot: next_token}``.  Slots whose cache allocation is spent
        (``length == limit``) are skipped — the scheduler should have
        finished them; skipping keeps a late finish from scattering past
        the slot's pages.  ``include`` (a slot list) restricts the
        advance to a subset — the scheduler's split when some slots went
        through a speculative verify dispatch this round instead.
        Slots mid-prefill (active with ``length == 0``) are not runnable:
        they have no last token to feed the tick yet."""
        jnp = self._jnp
        c = self.cache
        runnable = c.active & (c.lengths > 0) & (c.lengths < c.limit)
        if include is not None:
            sel = np.zeros((c.num_slots,), bool)
            sel[[int(s) for s in include]] = True
            runnable = runnable & sel
        if not runnable.any():
            return {}
        t0 = time.perf_counter()
        with obs.span("serve.tick", slots=int(runnable.sum())):
            self._k, self._v, nxt = self._tick_fn(
                self.params, self._k, self._v,
                jnp.asarray(c.block_table), jnp.asarray(c.lengths),
                jnp.asarray(c.last_tok), jnp.asarray(runnable),
                jnp.asarray(self._temp), jnp.asarray(self._topk),
                jnp.asarray(self._topp), jnp.asarray(self._seed))
            nxt = np.asarray(nxt)
        self._h_tick.observe(time.perf_counter() - t0)
        self._m_ticks.inc()
        out = {}
        for slot in np.flatnonzero(runnable):
            slot = int(slot)
            c.lengths[slot] += 1            # last_tok's K/V is now cached
            c.last_tok[slot] = int(nxt[slot])
            out[slot] = int(nxt[slot])
        return out

    def verify(self, drafts: dict[int, list]) -> dict[int, list[int]]:
        """Speculative advance: one batched verify dispatch over the
        ``drafts`` slots (slot -> proposed next tokens, possibly empty)
        returning ``{slot: emitted tokens}`` — the leading drafts that
        matched the model's argmax plus the model's own token at the
        first mismatch (1..len(drafts)+1 tokens, never 0: with every
        draft rejected the slot still advances exactly like a plain
        tick).  Greedy slots only; drafts are clipped to ``spec_k`` and
        to the slot's remaining page allocation."""
        jnp = self._jnp
        c = self.cache
        K = self.spec_k + 1
        toks = np.zeros((c.num_slots, K), np.int32)
        nd = np.zeros((c.num_slots,), np.int32)
        part = np.zeros((c.num_slots,), bool)
        for slot, d in drafts.items():
            slot = int(slot)
            if not (c.active[slot] and 0 < c.lengths[slot]
                    < c.limit[slot]):
                continue
            room = int(c.limit[slot]) - int(c.lengths[slot]) - 1
            d = [int(t) for t in d][:min(self.spec_k, max(0, room))]
            part[slot] = True
            nd[slot] = len(d)
            toks[slot, 0] = c.last_tok[slot]
            if d:
                toks[slot, 1:1 + len(d)] = d
        if not part.any():
            return {}
        t0 = time.perf_counter()
        with obs.span("serve.verify", slots=int(part.sum()),
                      drafted=int(nd.sum())):
            self._k, self._v, out = self._verify_fn(
                self.params, self._k, self._v,
                jnp.asarray(c.block_table), jnp.asarray(c.lengths),
                jnp.asarray(toks), jnp.asarray(part), jnp.asarray(nd))
            out = np.asarray(out)
        self._h_tick.observe(time.perf_counter() - t0)
        self._m_verifies.inc()
        res: dict[int, list[int]] = {}
        for slot in np.flatnonzero(part):
            slot = int(slot)
            k = int(nd[slot])
            row = out[slot]
            acc = 0                 # leading drafts matching the argmax
            while acc < k and int(row[acc]) == int(toks[slot, acc + 1]):
                acc += 1
            emitted = [int(t) for t in toks[slot, 1:1 + acc]]
            emitted.append(int(row[acc]))   # bonus: argmax after prefix
            c.lengths[slot] += acc + 1
            c.last_tok[slot] = emitted[-1]
            self._h_accept.observe(float(acc + 1))
            res[slot] = emitted
        return res

    def finish(self, slot: int):
        """Release the slot's pages (request done or evicted)."""
        self.cache.release(slot)

    def swap_params(self, params: PyTree) -> None:
        """Hot-swap the served weights between ticks (the zero-downtime
        deployment path — ``serve.server`` fences admissions around the
        call).  The compiled tick/prefill programs take ``params`` as
        argument 0 and close over nothing, so replacing the tree is
        visible on the next dispatch with NO retrace — provided the new
        tree matches the compiled signature exactly; structure, shape
        and dtype are validated here so a layout drift fails the swap,
        not the next request."""
        jax, jnp = self._jax, self._jnp
        new, depth = generate_params(params)
        if depth != self.depth:
            raise ValueError(f"swap depth {depth} != engine depth "
                             f"{self.depth}")
        old_leaves, old_def = jax.tree_util.tree_flatten(self.params)
        new_leaves, new_def = jax.tree_util.tree_flatten(new)
        if old_def != new_def:
            raise ValueError("swap param tree structure differs from the "
                             "compiled one")
        for o, n in zip(old_leaves, new_leaves):
            if tuple(o.shape) != tuple(n.shape) or o.dtype != n.dtype:
                raise ValueError(
                    f"swap leaf mismatch: {n.shape}/{n.dtype} where the "
                    f"engine compiled {o.shape}/{o.dtype}")
        self.params = jax.tree_util.tree_map(jnp.asarray, new)

    # -- lint/bench hooks ---------------------------------------------------
    def tick_args(self):
        """Abstract args for the decode-tick program (distlint's cost
        pass compiles the identical program the service runs)."""
        jax, c = self._jax, self.cache
        sd = jax.ShapeDtypeStruct
        kv = sd(self._k.shape, self._k.dtype)
        return (self.params, kv, kv,
                sd(c.block_table.shape, "int32"),
                sd(c.lengths.shape, "int32"),
                sd(c.last_tok.shape, "int32"),
                sd(c.active.shape, "bool"),
                sd((c.num_slots,), "float32"),
                sd((c.num_slots,), "int32"),
                sd((c.num_slots,), "float32"),
                sd((c.num_slots,), "int32"))

    def _sampling_scalar_args(self, sd):
        return (sd((), "float32"), sd((), "int32"),
                sd((), "float32"), sd((), "int32"))

    def prefill_args(self, bucket: int | None = None):
        jax, c = self._jax, self.cache
        sd = jax.ShapeDtypeStruct
        kv = sd(self._k.shape, self._k.dtype)
        b = bucket or self.buckets[0]
        return (self.params, kv, kv,
                sd((c.pages_per_slot,), "int32"),
                sd((1, b), "int32"), sd((), "int32"),
                *self._sampling_scalar_args(sd))

    def chunk_args(self, bucket: int | None = None):
        """Abstract args for one resumable-prefill chunk lowering."""
        jax, c = self._jax, self.cache
        sd = jax.ShapeDtypeStruct
        kv = sd(self._k.shape, self._k.dtype)
        b = bucket or self.buckets[0]
        return (self.params, kv, kv,
                sd((c.pages_per_slot,), "int32"),
                sd((1, b), "int32"), sd((), "int32"), sd((), "int32"),
                *self._sampling_scalar_args(sd))

    def verify_args(self):
        """Abstract args for the speculative verify program."""
        jax, c = self._jax, self.cache
        sd = jax.ShapeDtypeStruct
        kv = sd(self._k.shape, self._k.dtype)
        return (self.params, kv, kv,
                sd(c.block_table.shape, "int32"),
                sd(c.lengths.shape, "int32"),
                sd((c.num_slots, self.spec_k + 1), "int32"),
                sd(c.active.shape, "bool"),
                sd((c.num_slots,), "int32"))

    @property
    def tick_program(self):
        return self._tick_fn

    @property
    def prefill_program(self):
        return self._prefill_fn

    @property
    def chunk_program(self):
        return self._chunk_fn

    @property
    def verify_program(self):
        return self._verify_fn
