"""Slot-addressed decode engine — ``greedy_generate``'s prefill/decode
internals refactored for continuous batching (Orca, Yu et al. OSDI '22).

:func:`distlearn_tpu.models.transformer.greedy_generate` fuses prefill +
decode into one program over one batch that lives and dies together.  A
SERVICE can't do that: requests arrive and finish at different times, so
the engine splits the two phases into separately compiled programs over
a persistent paged K/V pool (:mod:`distlearn_tpu.serve.kv_cache`):

* :meth:`DecodeEngine.admit` runs the PREFILL program for one request —
  a full causal pass over its (bucket-padded) prompt whose K/V scatter
  lands in the slot's pages — and returns the first generated token.
* :meth:`DecodeEngine.tick` runs the DECODE program: every active slot
  advances one token in a single dispatch, each slot gathering its own
  K/V through its block-table row.  A request admitted between ticks
  prefills into slot k while the other slots' cached state just sits in
  the pool — nothing is recomputed or rolled back.

Both programs are built from the SAME block math as training and
``greedy_generate`` (``attn_qkv`` / ``attn_out`` / ``ffn_apply`` /
``decode_attend``), so continuous-batched decoding is token-identical
to N isolated ``greedy_generate`` calls — a tier-1-tested invariant
(tests/test_serve.py).

Tensor parallelism: pass ``mesh``/``tp_axis`` and both programs wrap
their body in ``shard_map`` inside ``jax.jit`` (the mesh-wrapped compile
pattern): weights shard per ``param_specs``, the K/V pools shard over
the heads axis, and ``attn_out``/``ffn_apply`` insert the two psums per
block exactly as the training step does.
"""

from __future__ import annotations

import time
from typing import Any

import numpy as np

from distlearn_tpu import obs
from distlearn_tpu.models.transformer import (_rmsnorm, attn_out, attn_qkv,
                                              decode_attend, ffn_apply,
                                              generate_params, param_specs)
from distlearn_tpu.serve.kv_cache import CacheFull, PagedKVCache

PyTree = Any

__all__ = ["DecodeEngine", "CacheFull"]


def _buckets(max_len: int) -> tuple[int, ...]:
    """Prompt-length compile buckets: powers of two up to ``max_len``
    (inclusive as the last bucket) — prompts pad up to the next bucket
    so the prefill program retraces O(log max_len) times, not once per
    distinct prompt length."""
    out = []
    b = 8
    while b < max_len:
        out.append(b)
        b *= 2
    out.append(max_len)
    return tuple(out)


class DecodeEngine:
    """Continuous-batching decode engine over a fixed-slot paged cache.

    ``params`` is a dense :func:`transformer_lm` tree (per-block or
    scanned layout; MoE rejected).  ``num_slots`` bounds concurrent
    requests; ``max_len`` bounds ``prompt + generated`` per request and
    sizes the page pool (every slot can hold a full-length request).
    """

    def __init__(self, params: PyTree, *, num_slots: int = 4,
                 max_len: int | None = None, page: int = 16,
                 compute_dtype=None, mesh=None, tp_axis: str | None = None,
                 donate: bool = True):
        import jax
        import jax.numpy as jnp
        self._jax, self._jnp = jax, jnp
        params, self.depth = generate_params(params)
        self.params = params
        self.cd = compute_dtype or params["embed"].dtype
        self.max_len = int(max_len or params["pos"].shape[0])
        if self.max_len > params["pos"].shape[0]:
            raise ValueError(f"max_len={self.max_len} exceeds the model's "
                             f"positional table {params['pos'].shape[0]}")
        wq = params["block0"]["wq"]
        self.heads, self.head_dim = wq.shape[1], wq.shape[2]
        if (mesh is None) != (tp_axis is None):
            raise ValueError("mesh and tp_axis come together (both or "
                             "neither)")
        if tp_axis is not None and self.heads % mesh.shape[tp_axis]:
            raise ValueError(
                f"{self.heads} heads not divisible by the {tp_axis} axis "
                f"({mesh.shape[tp_axis]})")
        self.mesh, self.tp_axis = mesh, tp_axis
        self.cache = PagedKVCache(num_slots, page, self.max_len)
        self.buckets = _buckets(self.max_len)
        shape = (self.depth, self.cache.num_pages, page,
                 self.heads, self.head_dim)
        self._k = jnp.zeros(shape, self.cd)
        self._v = jnp.zeros(shape, self.cd)
        if mesh is not None:
            from jax.sharding import NamedSharding
            self._kv_spec = self._pspec(None, None, None, tp_axis)
            sh = NamedSharding(mesh, self._kv_spec)
            self._k = jax.device_put(self._k, sh)
            self._v = jax.device_put(self._v, sh)
        self._tick_fn = self._build_tick(donate)
        self._prefill_fn = self._build_prefill(donate)
        self._m_ticks = obs.counter("serve_engine_ticks_total",
                                    "decode ticks dispatched")
        self._m_prefills = obs.counter("serve_engine_prefills_total",
                                       "prefill programs dispatched")
        self._h_tick = obs.histogram("serve_tick_seconds",
                                     "one decode tick: dispatch to tokens "
                                     "on host")

    # -- program construction ----------------------------------------------
    def _pspec(self, *names):
        from jax.sharding import PartitionSpec as P
        return P(*names)

    def _wrap(self, body, in_specs, out_specs, donate):
        """jit(shard_map(body)) under TP, plain jit otherwise — the
        mesh-wrapped compile pattern: the mesh is captured at build time
        so callers never need a mesh context."""
        jax = self._jax
        if self.mesh is None:
            return jax.jit(body, donate_argnums=(1, 2) if donate else ())
        from distlearn_tpu.utils.compat import shard_map
        mapped = shard_map(body, mesh=self.mesh, in_specs=in_specs,
                           out_specs=out_specs, check_vma=False)
        return jax.jit(mapped, donate_argnums=(1, 2) if donate else ())

    def _build_tick(self, donate):
        jnp = self._jnp
        params, depth, cd, tp = self.params, self.depth, self.cd, self.tp_axis
        page = self.cache.page
        T = self.cache.pages_per_slot * page

        def tick(p, kpool, vpool, bt, lens, toks, active):
            S = toks.shape[0]
            pos = lens                                    # position written
            x = p["embed"][toks].astype(cd)[:, None]      # [S,1,E]
            x = x + p["pos"][pos].astype(cd)[:, None]
            # inactive slots write to the trash page (their block-table
            # rows are all 0 already, but pos//page could index past the
            # row for a stale pos — clamp through where)
            row = jnp.clip(pos // page, 0, bt.shape[1] - 1)
            pages = jnp.where(active, bt[jnp.arange(S), row], 0)
            offs = jnp.where(active, pos % page, 0)
            for i in range(depth):
                blk = p[f"block{i}"]
                q, k1, v1 = attn_qkv(blk, x, cd, tp)      # [S,1,H,D]
                kpool = kpool.at[i, pages, offs].set(k1[:, 0])
                vpool = vpool.at[i, pages, offs].set(v1[:, 0])
                # paged gather: each slot's block-table row pulls its
                # pages from the pool -> a contiguous [S,T,H,D] view
                ck = kpool[i][bt].reshape(S, T, k1.shape[2], k1.shape[3])
                cv = vpool[i][bt].reshape(S, T, v1.shape[2], v1.shape[3])
                live = (jnp.arange(T)[None] <= pos[:, None])[:, None, None]
                x = attn_out(blk, x, decode_attend(q, ck, cv, live, cd),
                             cd, tp)
                x = ffn_apply(blk, x, cd, tp_axis=tp)
            x = _rmsnorm(p["out_norm"], x)
            lg = (x[:, 0] @ p["embed"].T.astype(cd)).astype(jnp.float32)
            return kpool, vpool, jnp.argmax(lg, axis=-1).astype(jnp.int32)

        P_ = self._pspec
        specs_in = (param_specs(params, self.tp_axis), self._kv_spec,
                    self._kv_spec, P_(), P_(), P_(), P_()) \
            if self.mesh is not None else None
        specs_out = (self._kv_spec, self._kv_spec, P_()) \
            if self.mesh is not None else None
        return self._wrap(tick, specs_in, specs_out, donate)

    def _build_prefill(self, donate):
        jnp = self._jnp
        lax = self._jax.lax
        from distlearn_tpu.parallel.sequence import local_attention
        params, depth, cd, tp = self.params, self.depth, self.cd, self.tp_axis
        page = self.cache.page

        def prefill(p, kpool, vpool, btrow, tokens, plen):
            # tokens [1, Pb] RIGHT-padded to the bucket: causal attention
            # means positions < plen never see the garbage tail, and the
            # tail's K/V scatter is routed to the trash page below.
            Pb = tokens.shape[1]
            x = p["embed"][tokens].astype(cd)
            x = x + p["pos"][:Pb].astype(cd)[None]
            posn = jnp.arange(Pb)
            valid = posn < plen
            pages = jnp.where(valid, btrow[posn // page], 0)
            offs = jnp.where(valid, posn % page, 0)
            for i in range(depth):
                blk = p[f"block{i}"]
                q, k, v = attn_qkv(blk, x, cd, tp)
                kpool = kpool.at[i, pages, offs].set(k[0])
                vpool = vpool.at[i, pages, offs].set(v[0])
                att = local_attention(q, k, v, causal=True)
                x = attn_out(blk, x, att, cd, tp)
                x = ffn_apply(blk, x, cd, tp_axis=tp)
            x = _rmsnorm(p["out_norm"], x)
            last = lax.dynamic_index_in_dim(x[0], plen - 1, 0,
                                            keepdims=False)
            lg = (last @ p["embed"].T.astype(cd)).astype(jnp.float32)
            return kpool, vpool, jnp.argmax(lg).astype(jnp.int32)

        P_ = self._pspec
        specs_in = (param_specs(params, self.tp_axis), self._kv_spec,
                    self._kv_spec, P_(), P_(), P_()) \
            if self.mesh is not None else None
        specs_out = (self._kv_spec, self._kv_spec, P_()) \
            if self.mesh is not None else None
        return self._wrap(prefill, specs_in, specs_out, donate)

    # -- capacity -----------------------------------------------------------
    def has_capacity(self, prompt_len: int, max_new: int) -> bool:
        return self.cache.can_admit(int(prompt_len) + int(max_new))

    def active_slots(self) -> list[int]:
        return np.flatnonzero(self.cache.active).tolist()

    def bucket_for(self, plen: int) -> int:
        for b in self.buckets:
            if plen <= b:
                return b
        raise ValueError(f"prompt length {plen} exceeds max_len "
                         f"{self.max_len}")

    # -- request lifecycle --------------------------------------------------
    def admit(self, prompt: np.ndarray, max_new: int) -> tuple[int, int]:
        """Prefill ``prompt`` (1-D int array) into a free slot; returns
        ``(slot, first_token)``.  Raises :class:`CacheFull` when no
        slot/pages fit (gate on :meth:`has_capacity`) and ``ValueError``
        when ``prompt + max_new`` exceeds ``max_len``."""
        jnp = self._jnp
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        plen = len(prompt)
        if plen < 1:
            raise ValueError("empty prompt")
        if max_new < 1:
            raise ValueError(f"max_new={max_new} must be >= 1")
        total = plen + int(max_new)
        if total > self.max_len:
            raise ValueError(f"prompt({plen}) + max_new({max_new}) = "
                             f"{total} exceeds max_len {self.max_len}")
        slot = self.cache.admit(total)
        bucket = self.bucket_for(plen)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :plen] = prompt
        with obs.span("serve.prefill", slot=slot, bucket=bucket):
            self._k, self._v, first = self._prefill_fn(
                self.params, self._k, self._v,
                jnp.asarray(self.cache.block_table[slot]),
                jnp.asarray(padded), jnp.int32(plen))
            first = int(first)
        self._m_prefills.inc()
        self.cache.lengths[slot] = plen
        self.cache.last_tok[slot] = first
        return slot, first

    def tick(self) -> dict[int, int]:
        """Advance every active slot one token in ONE dispatch; returns
        ``{slot: next_token}``.  Slots whose cache allocation is spent
        (``length == limit``) are skipped — the scheduler should have
        finished them; skipping keeps a late finish from scattering past
        the slot's pages."""
        jnp = self._jnp
        c = self.cache
        runnable = c.active & (c.lengths < c.limit)
        if not runnable.any():
            return {}
        t0 = time.perf_counter()
        with obs.span("serve.tick", slots=int(runnable.sum())):
            self._k, self._v, nxt = self._tick_fn(
                self.params, self._k, self._v,
                jnp.asarray(c.block_table), jnp.asarray(c.lengths),
                jnp.asarray(c.last_tok), jnp.asarray(runnable))
            nxt = np.asarray(nxt)
        self._h_tick.observe(time.perf_counter() - t0)
        self._m_ticks.inc()
        out = {}
        for slot in np.flatnonzero(runnable):
            slot = int(slot)
            c.lengths[slot] += 1            # last_tok's K/V is now cached
            c.last_tok[slot] = int(nxt[slot])
            out[slot] = int(nxt[slot])
        return out

    def finish(self, slot: int):
        """Release the slot's pages (request done or evicted)."""
        self.cache.release(slot)

    def swap_params(self, params: PyTree) -> None:
        """Hot-swap the served weights between ticks (the zero-downtime
        deployment path — ``serve.server`` fences admissions around the
        call).  The compiled tick/prefill programs take ``params`` as
        argument 0 and close over nothing, so replacing the tree is
        visible on the next dispatch with NO retrace — provided the new
        tree matches the compiled signature exactly; structure, shape
        and dtype are validated here so a layout drift fails the swap,
        not the next request."""
        jax, jnp = self._jax, self._jnp
        new, depth = generate_params(params)
        if depth != self.depth:
            raise ValueError(f"swap depth {depth} != engine depth "
                             f"{self.depth}")
        old_leaves, old_def = jax.tree_util.tree_flatten(self.params)
        new_leaves, new_def = jax.tree_util.tree_flatten(new)
        if old_def != new_def:
            raise ValueError("swap param tree structure differs from the "
                             "compiled one")
        for o, n in zip(old_leaves, new_leaves):
            if tuple(o.shape) != tuple(n.shape) or o.dtype != n.dtype:
                raise ValueError(
                    f"swap leaf mismatch: {n.shape}/{n.dtype} where the "
                    f"engine compiled {o.shape}/{o.dtype}")
        self.params = jax.tree_util.tree_map(jnp.asarray, new)

    # -- lint/bench hooks ---------------------------------------------------
    def tick_args(self):
        """Abstract args for the decode-tick program (distlint's cost
        pass compiles the identical program the service runs)."""
        jax, c = self._jax, self.cache
        sd = jax.ShapeDtypeStruct
        kv = sd(self._k.shape, self._k.dtype)
        return (self.params, kv, kv,
                sd(c.block_table.shape, "int32"),
                sd(c.lengths.shape, "int32"),
                sd(c.last_tok.shape, "int32"),
                sd(c.active.shape, "bool"))

    def prefill_args(self, bucket: int | None = None):
        jax, c = self._jax, self.cache
        sd = jax.ShapeDtypeStruct
        kv = sd(self._k.shape, self._k.dtype)
        b = bucket or self.buckets[0]
        return (self.params, kv, kv,
                sd((c.pages_per_slot,), "int32"),
                sd((1, b), "int32"), sd((), "int32"))

    @property
    def tick_program(self):
        return self._tick_fn

    @property
    def prefill_program(self):
        return self._prefill_fn
