"""Continuous-batching scheduler — admission, ticking, eviction policy.

Orca-style (Yu et al., OSDI '22) iteration-level scheduling: the unit
of work is one engine tick, and the request mix is re-decided between
ticks.  :meth:`Scheduler.step` runs one round —

1. **Expire**: queued or running requests past their deadline are
   dropped/evicted (the bounded-latency promise: a stuck client cannot
   pin a slot forever).
2. **Admit**: FIFO head-of-line from the bounded queue into free engine
   slots while pages last.  Head-of-line (rather than best-fit over the
   whole queue) keeps ordering fair — a large request at the head is
   never starved by small ones slipping past it.
3. **Tick**: one compiled decode step advances every active slot; each
   emitted token becomes a ``token`` event, and slots that hit their
   ``max_new`` budget or the eos token finish.

The scheduler is deliberately free of sockets and metrics: it consumes
an engine and emits :class:`Event` records, so tests drive it
synchronously and ``serve.server`` maps events to wire frames and
gauges.  The admission queue is BOUNDED — :meth:`submit` raises
:class:`QueueFull` instead of buffering unboundedly, pushing backpressure
to the client where it belongs.
"""

from __future__ import annotations

import itertools
import time
from collections import deque
from dataclasses import dataclass, field

import numpy as np

from distlearn_tpu.serve.engine import DecodeEngine, PrefillJob
from distlearn_tpu.serve.prefix_cache import RadixPrefixCache
from distlearn_tpu.serve.speculate import NGramDrafter


class QueueFull(RuntimeError):
    """Admission queue at capacity — client should back off and retry.

    Carries enough context for the rejection chunk to be actionable:
    ``queue_depth`` (how far behind the server is) and ``retry_after``
    (a seconds hint; ``None`` means "don't retry here" — e.g. the
    server is draining and will never admit again)."""

    def __init__(self, msg: str, *, queue_depth: int | None = None,
                 retry_after: float | None = None):
        super().__init__(msg)
        self.queue_depth = queue_depth
        self.retry_after = retry_after


_RIDS = itertools.count(1)


@dataclass
class Request:
    rid: str
    prompt: np.ndarray
    max_new: int
    deadline: float | None          # absolute clock() value, or None
    eos: int | None
    submitted: float                # clock() at submit, for queue-wait spans
    slot: int | None = None         # engine slot once admitted
    emitted: int = 0                # tokens emitted so far (incl. first)
    tokens: list[int] = field(default_factory=list)
    temperature: float = 0.0        # 0 == greedy (the default path)
    top_k: int = 0                  # 0 == no top-k filter
    top_p: float = 0.0              # 0 == no nucleus filter
    seed: int = 0                   # sampling key seed (temp > 0 only)
    speculate: bool = True          # drafter may speculate (greedy only)
    cached: int = 0                 # prompt tokens adopted from the cache
    job: PrefillJob | None = None   # in-flight resumable prefill
    waited: float | None = None     # queue-wait seconds, fixed at slot grant


@dataclass(frozen=True)
class Event:
    """One scheduling outcome, consumed by the server loop.

    ``kind`` is ``"token"`` (one more token for ``rid``; ``first`` marks
    the prefill-produced token, i.e. the TTFT edge) or ``"finish"``
    (``reason`` in ``complete`` / ``eos`` / ``deadline`` / ``cancelled``).
    ``waited`` rides the first-token event only: seconds the request sat
    in the admission queue before its slot — the server turns it into
    the ``serve.queue_wait`` span, so TTFT splits into queue wait vs
    prefill without the scheduler touching metrics.  ``accepted`` rides
    the bonus-token event of a speculative verify round: how many draft
    tokens the model accepted ahead of it (the 'R' ``accepted`` field).
    ``cached`` rides the first-token event: prompt tokens adopted from
    the prefix cache instead of prefilled ('R' ``cached_tokens``).
    """
    kind: str
    rid: str
    token: int | None = None
    first: bool = False
    reason: str | None = None
    waited: float | None = None
    accepted: int | None = None
    cached: int | None = None


class Scheduler:
    def __init__(self, engine: DecodeEngine, *, max_queue: int = 32,
                 clock=time.monotonic,
                 prefix_cache: RadixPrefixCache | None = None,
                 drafter: NGramDrafter | None = None,
                 prefill_chunk: int | None = None):
        """``prefix_cache`` (optional) is consulted at admission — a
        prompt sharing a cached prefix prefills only its suffix — and
        fed back after every completed prefill.  ``drafter`` (optional)
        enables speculative decoding for greedy streams.
        ``prefill_chunk`` bounds how many prompt positions one
        scheduling round may prefill WHILE other streams are decoding
        (their TPOT budget; default: the engine's smallest bucket) —
        with no running streams a prefill runs straight to completion,
        there is nobody to stall."""
        self.engine = engine
        self.max_queue = int(max_queue)
        self.clock = clock
        self.prefix_cache = prefix_cache
        self.drafter = drafter
        self.prefill_chunk = int(prefill_chunk or engine.buckets[0])
        self._queue: deque[Request] = deque()
        self._running: dict[str, Request] = {}    # rid -> Request
        self._prefilling: dict[str, Request] = {} # rid -> Request (chunking)
        self._by_slot: dict[int, Request] = {}
        #: admissions fence: while True, queued requests stay queued
        #: (submit still accepts up to max_queue).  The server raises it
        #: around an epoch swap so no request prefills under outgoing
        #: params while survivors of the old epoch drain.
        self.hold = False

    # -- introspection (server gauges) --------------------------------------
    def queue_depth(self) -> int:
        return len(self._queue)

    def active_count(self) -> int:
        return len(self._running) + len(self._prefilling)

    def idle(self) -> bool:
        return (not self._queue and not self._running
                and not self._prefilling)

    def requests(self) -> list[Request]:
        return (list(self._queue) + list(self._prefilling.values())
                + list(self._running.values()))

    def _live(self, rid: str) -> bool:
        return (rid in self._running or rid in self._prefilling
                or any(r.rid == rid for r in self._queue))

    def retry_after_hint(self) -> float:
        """Seconds a rejected client should wait before retrying HERE.
        A coarse backlog proxy — per-request service time isn't known
        at admission, so the hint only needs to scale with how far
        behind the server is, clamped to [0.05s, 5s] so it neither
        thundering-herds nor parks clients forever."""
        backlog = len(self._queue) + len(self._running)
        return min(5.0, max(0.05, 0.05 * backlog))

    # -- client-facing ------------------------------------------------------
    def submit(self, prompt, max_new: int, *, rid: str | None = None,
               deadline_s: float | None = None,
               eos: int | None = None, temperature: float = 0.0,
               top_k: int = 0, top_p: float = 0.0, seed: int = 0,
               speculate: bool = True) -> str:
        """Enqueue one request; returns its id.  Raises
        :class:`QueueFull` at capacity and ``ValueError`` for requests
        the engine could NEVER run (too long even with an empty cache) —
        those must be rejected here, not left to rot at the queue head —
        and for a ``rid`` already queued or running: the bookkeeping is
        rid-keyed, so a second live request under the same id would
        overwrite the first's entry and corrupt event routing (a rid
        becomes reusable once its request finishes)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        max_new = int(max_new)
        if prompt.size < 1 or max_new < 1:
            raise ValueError(f"prompt len {prompt.size} and max_new "
                             f"{max_new} must be >= 1")
        if prompt.size + max_new > self.engine.max_len:
            raise ValueError(
                f"prompt+max_new = {prompt.size + max_new} exceeds engine "
                f"max_len {self.engine.max_len}")
        temperature = float(temperature)
        top_p = float(top_p)
        if temperature < 0:
            raise ValueError(f"temperature={temperature} must be >= 0")
        if not 0.0 <= top_p <= 1.0:
            raise ValueError(f"top_p={top_p} outside [0, 1]")
        if int(top_k) < 0:
            raise ValueError(f"top_k={top_k} must be >= 0")
        if len(self._queue) >= self.max_queue:
            raise QueueFull(
                f"admission queue at capacity ({self.max_queue})",
                queue_depth=len(self._queue),
                retry_after=self.retry_after_hint())
        if rid is None:
            rid = str(next(_RIDS))
            while self._live(rid):      # a client squatted on this numeral
                rid = str(next(_RIDS))
        elif self._live(rid):
            raise ValueError(f"duplicate rid {rid!r}: already queued or "
                             "running")
        now = self.clock()
        req = Request(rid=rid, prompt=prompt, max_new=max_new,
                      deadline=(now + deadline_s) if deadline_s is not None
                      else None,
                      eos=eos, submitted=now, temperature=temperature,
                      top_k=int(top_k), top_p=top_p, seed=int(seed),
                      speculate=bool(speculate))
        self._queue.append(req)
        return rid

    def cancel(self, rid: str) -> bool:
        """Drop a request wherever it is (client disconnected).  Returns
        False when the rid is unknown / already finished."""
        for i, req in enumerate(self._queue):
            if req.rid == rid:
                del self._queue[i]
                return True
        req = self._prefilling.pop(rid, None)
        if req is not None:
            del self._by_slot[req.slot]
            self.engine.abort_prefill(req.job)
            return True
        req = self._running.pop(rid, None)
        if req is None:
            return False
        del self._by_slot[req.slot]
        self.engine.finish(req.slot)
        return True

    # -- one scheduling round ----------------------------------------------
    def step(self) -> list[Event]:
        events: list[Event] = []
        now = self.clock()
        self._expire(now, events)
        self._admit(events)
        self._tick(events)
        return events

    def _expire(self, now: float, events: list[Event]):
        # queued requests past deadline never got a slot: drop silently
        # from the queue but loudly to the client.
        kept = deque()
        for req in self._queue:
            if req.deadline is not None and now >= req.deadline:
                events.append(Event("finish", req.rid, reason="deadline"))
            else:
                kept.append(req)
        self._queue = kept
        for req in [r for r in self._prefilling.values()
                    if r.deadline is not None and now >= r.deadline]:
            del self._prefilling[req.rid]
            del self._by_slot[req.slot]
            self.engine.abort_prefill(req.job)
            events.append(Event("finish", req.rid, reason="deadline"))
        for req in [r for r in self._running.values()
                    if r.deadline is not None and now >= r.deadline]:
            del self._running[req.rid]
            del self._by_slot[req.slot]
            self.engine.finish(req.slot)
            events.append(Event("finish", req.rid, reason="deadline"))

    def _admit(self, events: list[Event]):
        # in-flight prefills advance FIRST, even under hold: the epoch
        # fence must be able to drain them (active_count counts them),
        # it only stops NEW admissions below.
        self._advance_prefills(events)
        if self.hold:
            return
        while self._queue:
            req = self._queue[0]
            total = int(req.prompt.size) + req.max_new
            kv = self.engine.cache
            cached_len, pages = (self.prefix_cache.match(req.prompt)
                                 if self.prefix_cache is not None
                                 else (0, []))
            short = (kv.pages_for(total) - len(pages)) - kv.free_pages()
            if short > 0 and self.prefix_cache is not None:
                # eviction can reclaim pages the match itself returned
                # (match takes no references) — evict, then RE-match:
                # the matched path was just stamped MRU, so it is the
                # last thing evict_for_free lets go.
                self.prefix_cache.evict_for_free(short)
                cached_len, pages = self.prefix_cache.match(req.prompt)
            if not kv.can_admit(total, shared_pages=len(pages)):
                break
            self._queue.popleft()
            req.waited = self.clock() - req.submitted
            req.job = self.engine.begin(
                req.prompt, req.max_new, shared=pages,
                temperature=req.temperature, top_k=req.top_k,
                top_p=req.top_p, seed=req.seed)
            req.slot = req.job.slot
            req.cached = req.job.cached
            self._prefilling[req.rid] = req
            self._by_slot[req.slot] = req
            # pump the fresh job in the SAME round — an idle engine runs
            # it straight to the first token, so TTFT never pays an
            # extra scheduling round for the chunking machinery.
            self._pump_prefill(req, events)

    def _advance_prefills(self, events: list[Event]):
        for req in list(self._prefilling.values()):
            self._pump_prefill(req, events)

    def _pump_prefill(self, req: Request, events: list[Event]):
        """One prefill advance: a single bounded chunk while decode
        streams are running (their TPOT budget), straight to completion
        otherwise — with nobody decoding there is nobody to stall."""
        chunk = self.prefill_chunk if self._running else None
        first = self.engine.prefill_step(req.job, chunk=chunk)
        if first is None:
            return
        del self._prefilling[req.rid]
        self._running[req.rid] = req
        if self.prefix_cache is not None:
            # retain the finished prompt's whole pages for later
            # admissions (already-cached spans are skipped inside)
            self.prefix_cache.insert(
                req.prompt, self.engine.cache.block_table[req.slot])
        self._emit(req, int(first), events, first_tok=True,
                   waited=req.waited, cached=req.cached or None)

    def _tick(self, events: list[Event]):
        if not self._running:
            return
        drafts: dict[int, list[int]] = {}
        if self.drafter is not None:
            kv = self.engine.cache
            for req in self._running.values():
                if req.temperature > 0 or not req.speculate:
                    continue        # sampling does not follow argmax
                budget = min(self.drafter.k,
                             req.max_new - req.emitted - 1,
                             int(kv.limit[req.slot])
                             - int(kv.lengths[req.slot]) - 1)
                if budget < 1:
                    continue
                d = self.drafter.propose(
                    [int(t) for t in req.prompt] + req.tokens, k=budget)
                if d:
                    drafts[req.slot] = d
        if not drafts:
            # the plain tick IS today's path, bit for bit
            for slot, tok in self.engine.tick().items():
                req = self._by_slot.get(slot)
                if req is not None and req.rid in self._running:
                    self._emit(req, int(tok), events)
            return
        for slot, toks in self.engine.verify(drafts).items():
            req = self._by_slot.get(slot)
            if req is None:
                continue
            accepted = len(toks) - 1
            for i, tok in enumerate(toks):
                if req.rid not in self._running:
                    break           # eos mid-run: rest are discarded
                self._emit(req, int(tok), events,
                           accepted=accepted if i == len(toks) - 1
                           else None)
        rest = [s for s, r in self._by_slot.items()
                if r.rid in self._running and s not in drafts]
        if rest:
            for slot, tok in self.engine.tick(include=rest).items():
                req = self._by_slot.get(slot)
                if req is not None and req.rid in self._running:
                    self._emit(req, int(tok), events)

    def _emit(self, req: Request, tok: int, events: list[Event],
              first_tok: bool = False, waited: float | None = None,
              accepted: int | None = None, cached: int | None = None):
        req.emitted += 1
        req.tokens.append(tok)
        events.append(Event("token", req.rid, token=tok, first=first_tok,
                            waited=waited, accepted=accepted,
                            cached=cached))
        done_eos = req.eos is not None and tok == req.eos
        if req.emitted >= req.max_new or done_eos:
            del self._running[req.rid]
            del self._by_slot[req.slot]
            self.engine.finish(req.slot)
            events.append(Event("finish", req.rid,
                                reason="eos" if done_eos else "complete"))
