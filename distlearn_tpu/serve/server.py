"""Decode service front-end: transport loop, telemetry, graceful drain.

Wires the three serving layers to the rest of the repo:

* **Wire**: accepts connections on a ``comm.transport.Server`` and
  speaks the serving frames — ``'G'`` in (generate request JSON),
  ``'R'`` out (one token-stream chunk per scheduling round, ``done``
  flag on the last).  A ``'J'`` control frame answers with a stats
  snapshot, so health probes share the port.
* **Telemetry**: ``serve_queue_depth`` / ``serve_active_slots`` gauges,
  ``serve_ttft_seconds`` / ``serve_tpot_seconds`` histograms (with
  matching ``serve.ttft`` / ``serve.tpot`` spans in the JSONL trail for
  ``tools/diststat.py`` percentiles), ``serve_requests_total{outcome}``
  and ``serve_tokens_total`` counters, and a ``/healthz`` source for the
  existing obs export thread.
* **Drain**: :meth:`ServeServer.checkpoint_now` implements the
  ``ha.install_signal_flush`` contract — on SIGTERM the handler stops
  admissions, lets in-flight requests decode to completion (bounded by
  ``drain_timeout``), then lets the signal's prior disposition run.  No
  new flush machinery: serving reuses the HA hook verbatim.
* **Hot swap**: with ``ckpt_dir`` set, a :class:`WeightTailer` tails the
  training center's checkpoint directory (the ``ha.StandbyCenter``
  watch pattern, serving side) and the loop swaps params between ticks
  — epoch-fenced: admissions hold while old-epoch streams drain, then
  the new weights install atomically, so no stream ever observes two
  center epochs.  The serving epoch rides ``/healthz`` and every 'R'
  chunk, which is what ``serve.router`` asserts on.

The request loop runs in ONE thread (foreground ``serve_forever`` or
background ``start``): sockets are select-ed, the scheduler steps, and
events fan out to clients.  Reads never block — frames reassemble
per-connection from whatever bytes are available
(``Conn.recv_serve_nowait``), so a peer that half-sends a frame cannot
head-of-line block the decode loop; ``frame_timeout`` bounds how long a
partial frame may linger before the trickler is dropped.  A client that
disconnects mid-stream is detected on the failed send and its request
cancelled — its slot frees on the next round, never leaking pages.
"""

from __future__ import annotations

import select
import threading
import time
import traceback

import numpy as np

from distlearn_tpu import obs
from distlearn_tpu.comm import transport
from distlearn_tpu.comm.transport import ProtocolError
from distlearn_tpu.obs import trace as obs_trace
from distlearn_tpu.serve.engine import DecodeEngine
from distlearn_tpu.serve.prefix_cache import RadixPrefixCache
from distlearn_tpu.serve.scheduler import QueueFull, Scheduler
from distlearn_tpu.serve.speculate import NGramDrafter
from distlearn_tpu.utils.checkpoint import latest_step, restore_checkpoint
from distlearn_tpu.utils.logging import print_server

#: TTFT/TPOT buckets (seconds): wider than the wire-latency default —
#: a prefill at batch-1 on CPU lands in the 10ms..1s decades.
_LAT_BUCKETS = (.0005, .001, .0025, .005, .01, .025, .05, .1, .25, .5,
                1.0, 2.5, 5.0, 10.0)


class WeightTailer:
    """Tail a checkpoint directory for new weights to serve — the
    ``ha.StandbyCenter`` watch-probe pattern pointed at serving instead
    of promotion.  :meth:`maybe_load` is polled from the request loop;
    at most one ``latest_step`` stat per ``poll`` seconds, and a load
    only when an unseen step appears.

    Both tree layouts the repo writes are accepted: params-shaped
    checkpoints (``save_checkpoint(dir, step, params)``) and the HA
    center layout ``{"center": {"<i>": leaf}}`` that the training
    center's ``_checkpoint_locked`` produces (tried second, via
    ``ha.restore_center``)."""

    def __init__(self, directory: str, like, *, poll: float = 0.25):
        self.directory = str(directory)
        self.like = like
        self.poll = float(poll)
        self._last_step: int | None = None
        self._warned_step: int | None = None
        self._next_poll = 0.0

    def poll_step(self) -> int | None:
        return latest_step(self.directory)

    def maybe_load(self, now: float):
        """``(params, meta)`` for an unseen newest step, else ``None``.
        A torn or foreign file is skipped (warned once) and re-tried
        next poll — a checkpoint racing its own rename completes soon."""
        if now < self._next_poll:
            return None
        self._next_poll = now + self.poll
        step = self.poll_step()
        if step is None or step == self._last_step:
            return None
        try:
            tree, meta = self._restore(step)
        except (OSError, KeyError, ValueError) as e:
            if step != self._warned_step:
                self._warned_step = step
                print_server(f"weight tailer: step {step} unreadable, "
                             f"will retry: {e!r}")
            return None
        self._last_step = step
        return tree, meta

    def _restore(self, step: int):
        try:
            return restore_checkpoint(self.directory, self.like, step=step)
        except (KeyError, ValueError):
            from distlearn_tpu.parallel.ha import restore_center
            return restore_center(self.directory, self.like, step=step)


class ServeServer:
    def __init__(self, engine: DecodeEngine, *, host: str = "127.0.0.1",
                 port: int = 0, max_queue: int = 32,
                 default_max_new: int = 32, frame_timeout: float = 5.0,
                 idle_wait: float = 0.05, drain_timeout: float = 30.0,
                 ckpt_dir: str | None = None, ckpt_poll: float = 0.25,
                 ckpt_like=None, epoch: int | None = None,
                 prefix_cache: bool = False, spec_k: int | None = None,
                 prefill_chunk: int | None = None):
        """Raw-speed knobs (all default OFF — the plain serve path stays
        byte-identical): ``prefix_cache`` retains finished prompts' K/V
        pages in a :class:`RadixPrefixCache` so shared-prefix traffic
        prefills only its suffix; ``spec_k`` enables n-gram speculative
        decoding with that many draft tokens per verify; ``prefill_chunk``
        bounds prompt positions prefilled per round while streams decode
        (chunked prefill — long prompts stop stalling TPOT)."""
        self.engine = engine
        self.prefix_cache = (RadixPrefixCache(engine.cache)
                             if prefix_cache else None)
        self.sched = Scheduler(
            engine, max_queue=max_queue, prefix_cache=self.prefix_cache,
            drafter=NGramDrafter(k=spec_k) if spec_k else None,
            prefill_chunk=prefill_chunk)
        self.default_max_new = int(default_max_new)
        self.frame_timeout = float(frame_timeout)
        self.idle_wait = float(idle_wait)
        self.drain_timeout = float(drain_timeout)
        self._lst = transport.Server(host, port)
        self.host, self.port = self._lst.host, self._lst.port
        self._conn_of: dict[str, transport.Conn] = {}   # rid -> client conn
        self._t_submit: dict[str, float] = {}           # rid -> perf_counter
        self._tc_of: dict[str, dict] = {}               # rid -> trace ctx
        self._t_last: dict[str, float] = {}             # rid -> last token t
        self._rx_since: dict[transport.Conn, float] = {}  # partial-frame age
        self._failed: str | None = None                 # loop death, if any
        self._stop = threading.Event()
        self._drained = threading.Event()
        self._draining = False
        self._thread: threading.Thread | None = None
        self._g_queue = obs.gauge(
            "serve_queue_depth", "requests waiting for a decode slot")
        self._g_active = obs.gauge(
            "serve_active_slots", "requests currently decoding")
        self._h_ttft = obs.histogram(
            "serve_ttft_seconds",
            "time-to-first-token: 'G' frame decoded to first 'R' sent",
            buckets=_LAT_BUCKETS)
        self._h_tpot = obs.histogram(
            "serve_tpot_seconds",
            "per-output-token latency after the first token",
            buckets=_LAT_BUCKETS)
        self._c_reqs = obs.counter(
            "serve_requests_total", "requests by terminal outcome",
            labels=("outcome",))
        self._c_toks = obs.counter(
            "serve_tokens_total", "tokens streamed to clients")
        #: epoch of the params being served (None until known); bumped
        #: by the tailer from checkpoint metadata ("epoch" key, falling
        #: back to the step for plain params checkpoints).  epoch /
        #: ckpt_step / _swap_pending are written only by the serve loop;
        #: health() readers on other threads take GIL-atomic snapshots
        #: of int/tuple attributes — a probe racing a swap sees either
        #: epoch, both valid ("telemetry tolerates a torn view").
        self.epoch = epoch
        self.ckpt_step: int | None = None
        self._tailer = (WeightTailer(ckpt_dir,
                                     engine.params if ckpt_like is None
                                     else ckpt_like, poll=ckpt_poll)
                        if ckpt_dir else None)
        self._swap_pending: tuple | None = None   # (params, meta) loaded
        self._c_swaps = obs.counter(
            "serve_weight_swaps_total",
            "hot weight swaps applied between ticks")
        self._g_epoch = obs.gauge(
            "serve_center_epoch", "center epoch of the params being served")
        if epoch is not None:
            self._g_epoch.set(epoch)
        obs.set_health_source(self.health)

    # -- health / introspection --------------------------------------------
    def health(self) -> dict:
        return {"serving": not self._stop.is_set(),
                "failed": self._failed,
                "draining": self._draining,
                "queue_depth": self.sched.queue_depth(),
                "active": self.sched.active_count(),
                "free_pages": self.engine.cache.free_pages(),
                "cached_pages": (self.prefix_cache.pages_held
                                 if self.prefix_cache is not None else 0),
                "epoch": self.epoch,
                "ckpt_step": self.ckpt_step,
                "swap_pending": self._swap_pending is not None}

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "ServeServer":
        """Run the request loop in a background thread (so the main
        thread stays free for signal handlers — the signal module only
        delivers to the main thread)."""
        self._thread = threading.Thread(target=self.serve_forever,
                                        name="serve-loop", daemon=True)
        self._thread.start()
        return self

    def checkpoint_now(self, wait: bool = True):
        """Graceful drain under the ``ha.install_signal_flush`` name:
        the serving analogue of "write one last durable checkpoint" is
        "finish every admitted request".  Stops admissions immediately;
        with ``wait`` blocks until in-flight requests complete (or
        ``drain_timeout`` passes), then stops the loop."""
        self._draining = True
        if wait:
            self._drained.wait(self.drain_timeout)
        self._stop.set()

    def stop(self):
        """Immediate shutdown: stop the loop, close every socket.  Safe
        to call twice and after ``checkpoint_now``."""
        self._stop.set()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(10.0)
        self._thread = None
        self._lst.close()
        self._g_queue.set(0)
        self._g_active.set(0)

    # -- request loop -------------------------------------------------------
    def serve_forever(self):
        try:
            while not self._stop.is_set():
                try:
                    self._poll_io()
                    self._maybe_swap()
                    events = self.sched.step()
                    self._dispatch(events)
                    self._g_queue.set(self.sched.queue_depth())
                    self._g_active.set(self.sched.active_count())
                    if self._draining and self.sched.idle():
                        self._drained.set()
                        break
                except Exception as e:  # noqa: BLE001 — death must be seen
                    # an unexpected scheduler/engine error must not kill
                    # this thread silently while health() keeps saying
                    # serving=True and clients hang to their timeouts:
                    # record it, flip health, fail the clients fast.
                    self._failed = repr(e)
                    print_server("serve loop died:",
                                 traceback.format_exc())
                    self._stop.set()
                    for c in list(self._lst.conns):
                        c.close()
        finally:
            self._drained.set()
            self._g_queue.set(0)
            self._g_active.set(0)

    def _maybe_swap(self):
        """Epoch-fenced hot weight swap, between ticks.  Two phases: on
        a new checkpoint, raise the admissions hold (queued requests
        wait, nothing new prefills); once the active set drains, install
        the new params and release the hold.  In-flight streams thus
        finish entirely under their admission epoch and every stream
        admitted after the swap runs entirely under the new one — no
        stream ever observes two epochs (the 'R'-chunk echo that
        ``serve.router`` fences on).  The wait is bounded by the longest
        in-flight ``max_new`` budget, never a queue's worth."""
        if self._tailer is None:
            return
        if self._swap_pending is None:
            got = self._tailer.maybe_load(time.monotonic())
            if got is None:
                return
            self._swap_pending = got
            self.sched.hold = True
        if self.sched.active_count():
            return                      # old-epoch streams still decoding
        tree, meta = self._swap_pending
        self._swap_pending = None
        self.sched.hold = False
        try:
            self.engine.swap_params(tree)
        except ValueError as e:
            # layout drift (wrong depth/shape): refuse the swap, keep
            # serving the old weights — availability over freshness (and
            # the prefix cache stays valid: the old params still serve).
            print_server(f"hot swap refused: {e}")
            return
        if self.prefix_cache is not None:
            # every cached K/V page was computed under the outgoing
            # epoch: a new-epoch stream matching one would splice stale
            # attention state into its prefix.  Invalidate before any
            # post-swap admission can run.
            stale = self.prefix_cache.clear()
            if stale:
                print_server(f"prefix cache invalidated across epoch "
                             f"fence ({stale} pages)")
        self.ckpt_step = meta.get("step")
        self.epoch = int(meta.get("epoch", self.ckpt_step or 0))
        self._c_swaps.inc()
        self._g_epoch.set(self.epoch)
        print_server(f"hot-swapped params (step {self.ckpt_step}, "
                     f"epoch {self.epoch})")

    def _poll_io(self):
        self._lst.prune_closed()
        socks = {self._lst.sock: None}
        for c in self._lst.conns:
            socks[c.sock] = c
        # busy (requests decoding) -> poll without blocking between
        # ticks; idle -> sleep in select until a frame or stop.
        wait = 0.0 if not self.sched.idle() else self.idle_wait
        try:
            ready, _, _ = select.select(list(socks), [], [], wait)
        except OSError:      # a peer closed between prune and select
            return
        for sock in ready:
            conn = socks[sock]
            if conn is None:
                try:
                    self._lst.accept(timeout=0.0)
                except (TimeoutError, OSError):
                    pass
                continue
            self._serve_conn(conn)
        self._reap_stalled()

    def _serve_conn(self, conn: transport.Conn):
        """Drain the connection WITHOUT blocking and handle every frame
        that completed: select only proves some bytes arrived, so a
        blocking whole-frame read here would let one half-sent frame
        stall scheduling for every in-flight request (head-of-line
        blocking).  Partial frames stay buffered on the Conn; a peer
        that leaves one buffered longer than ``frame_timeout`` is
        dropped by :meth:`_reap_stalled`."""
        try:
            frames = conn.recv_serve_nowait()
        except (OSError, ProtocolError, ValueError):
            # PeerClosed (clean FIN), a torn frame, a non-serve kind, or
            # undecodable JSON: the stream cannot be resumed.
            self._drop_conn(conn)
            return
        if conn.rx_pending():
            self._rx_since.setdefault(conn, time.monotonic())
        else:
            self._rx_since.pop(conn, None)
        for kind, msg in frames:
            if conn.sock.fileno() < 0:   # dropped handling an earlier frame
                return
            if kind == "J":  # control: health probe / stats over the wire
                try:
                    conn.send_msg({"ok": True, **self.health()})
                except OSError:
                    self._drop_conn(conn)
                    return
            elif kind == "G":
                self._submit(conn, msg)
            else:            # 'R' is server->client only
                self._drop_conn(conn)
                return

    def _reap_stalled(self):
        """Drop connections whose partial frame has been sitting in the
        reassembly buffer longer than ``frame_timeout`` — the trickler
        wedge class the old blocking deadline killed, now enforced
        without letting the trickler block anyone."""
        if not self._rx_since:
            return
        now = time.monotonic()
        for conn in [c for c, t0 in self._rx_since.items()
                     if now - t0 > self.frame_timeout]:
            self._drop_conn(conn)

    def _submit(self, conn: transport.Conn, msg):
        rid = str(msg.get("rid") or "")
        try:
            if self._draining:
                # no retry_after: a draining server never admits again —
                # the client/router should go elsewhere, not wait here.
                raise QueueFull("server draining",
                                queue_depth=self.sched.queue_depth())
            prompt = np.asarray(msg["prompt"], np.int32)
            rid = self.sched.submit(
                prompt, int(msg.get("max_new", self.default_max_new)),
                rid=rid or None,
                deadline_s=msg.get("deadline_s"),
                eos=msg.get("eos"),
                temperature=float(msg.get("temperature", 0.0)),
                top_k=int(msg.get("top_k", 0)),
                top_p=float(msg.get("top_p", 0.0)),
                seed=int(msg.get("seed", 0)),
                speculate=bool(msg.get("speculate", True)))
        except (QueueFull, ValueError, KeyError, TypeError) as e:
            self._c_reqs.labels(outcome="rejected").inc()
            chunk = {"rid": rid, "error": str(e) or type(e).__name__,
                     "done": True, "epoch": self.epoch}
            if isinstance(e, QueueFull):
                chunk["queue_depth"] = (
                    e.queue_depth if e.queue_depth is not None
                    else self.sched.queue_depth())
                if e.retry_after is not None:
                    chunk["retry_after"] = e.retry_after
            try:
                conn.send_stream(chunk)
            except OSError:
                self._drop_conn(conn)
            return
        self._conn_of[rid] = conn
        self._t_submit[rid] = time.perf_counter()
        # optional trace context from the 'G' frame (router/client root
        # span): TTFT/TPOT/queue-wait spans for this rid re-enter it, so
        # the whole request stitches into one cross-process trace.
        # Malformed or absent degrades to untraced, never rejects.
        tc = msg.get(obs_trace.TRACE_KEY)
        if obs_trace.valid_context(tc):
            self._tc_of[rid] = tc

    def _dispatch(self, events):
        # one 'R' frame per request per round: {"rid", "tokens", "epoch",
        # "done"[, "reason"]} — streaming granularity is the tick,
        # matching TTFT.  The epoch echo is the hot-swap fence witness:
        # swaps only happen with zero active streams, so every chunk of
        # one stream carries the same value.
        out: dict[str, dict] = {}
        now = time.perf_counter()
        for ev in events:
            chunk = out.setdefault(ev.rid, {"rid": ev.rid, "tokens": [],
                                            "done": False,
                                            "epoch": self.epoch})
            if ev.kind == "token":
                chunk["tokens"].append(ev.token)
                if ev.accepted is not None:
                    # draft tokens the verify accepted ahead of this one
                    # (speculative decode observability, summed per chunk)
                    chunk["accepted"] = (chunk.get("accepted", 0)
                                         + ev.accepted)
                if ev.cached is not None:
                    # prompt tokens adopted from the prefix cache instead
                    # of prefilled (rides the first chunk only)
                    chunk["cached_tokens"] = ev.cached
                self._c_toks.inc()
                with obs_trace.use_context(self._tc_of.get(ev.rid)):
                    if ev.first:
                        t0 = self._t_submit.get(ev.rid)
                        if t0 is not None:
                            self._h_ttft.observe(now - t0)
                            obs.record_span("serve.ttft", now - t0,
                                            rid=ev.rid)
                        if ev.waited is not None:
                            # queue-wait attribution: how much of TTFT was
                            # spent parked in the admission queue vs
                            # decoding (the critical-path split
                            # tools/tracecat.py reports)
                            obs.record_span("serve.queue_wait", ev.waited,
                                            rid=ev.rid)
                    else:
                        tl = self._t_last.get(ev.rid)
                        if tl is not None:
                            self._h_tpot.observe(now - tl)
                            obs.record_span("serve.tpot", now - tl,
                                            rid=ev.rid)
                self._t_last[ev.rid] = now
            else:
                chunk["done"] = True
                chunk["reason"] = ev.reason
                outcome = ev.reason or "complete"
                self._c_reqs.labels(outcome=outcome).inc()
        for rid, chunk in out.items():
            conn = self._conn_of.get(rid)
            if conn is not None and conn.sock.fileno() >= 0:
                try:
                    conn.send_stream(chunk)
                except OSError:
                    self._drop_conn(conn)
            if chunk["done"]:
                self._forget(rid)

    def _drop_conn(self, conn: transport.Conn):
        """Client went away: cancel every request it owns (queued or
        decoding) so its slot/pages free on the next round."""
        for rid in [r for r, c in self._conn_of.items() if c is conn]:
            if self.sched.cancel(rid):
                self._c_reqs.labels(outcome="cancelled").inc()
            self._forget(rid)
        self._rx_since.pop(conn, None)
        conn.close()

    def _forget(self, rid: str):
        self._conn_of.pop(rid, None)
        self._t_submit.pop(rid, None)
        self._t_last.pop(rid, None)
        self._tc_of.pop(rid, None)
