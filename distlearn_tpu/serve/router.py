"""Fault-tolerant serving fleet router — health-routed dispatch over N
:class:`~distlearn_tpu.serve.server.ServeServer` replicas.

Shared-nothing by construction: a :class:`Router` is a client-side
library object holding nothing but a dial list and a health cache, so
any number of router instances front the same fleet without
coordination — the HA design (docs/HA.md) applied to serving.  One
request's lifecycle:

1. **Dispatch** — pick the least-loaded live replica
   (``queue_depth + active`` from its '/healthz'-over-'J' snapshot,
   cached ``health_ttl`` seconds), open a fresh connection, send the
   'G' frame.  Streams are sticky: chunks for a request only ever come
   from the replica that admitted it.
2. **Shed** — before dispatch, aggregate queue depth across live
   replicas; at or past ``shed_watermark`` the router refuses with
   :class:`RouterBusy` carrying a ``retry_after`` hint instead of
   letting the request time out in a queue (graceful degradation).
3. **Retry on death** — a replica that dies before producing the
   request's first token (dial failure, FIN/reset, i.e. the request was
   queued-not-yet-prefilled) is safe to retry: the router resubmits to
   a survivor with exponential backoff + full jitter (the
   ``transport.connect`` policy), at most once per replica.  A death
   AFTER tokens flowed cannot be retried without duplicating output —
   the caller gets a clean terminal ``reason="failed"`` result with the
   partial tokens instead of a hang.
4. **Hedge** — a request stuck with no first token for ``hedge_after``
   seconds (deadline-aware: never later than half its own
   ``deadline_s``) behind a sick-but-alive replica is cancelled there
   (closing the connection cancels the queued copy server-side — this
   is what keeps execution at-most-once per replica) and resubmitted to
   the next-best untried replica.
5. **Epoch fence** — every 'R' chunk echoes the replica's center epoch
   (hot weight swap, ``serve.server``).  The first chunk pins the
   stream's epoch; a later chunk with a different value is a fence
   violation and the stream is terminated (``reason="failed"``) rather
   than splicing two model versions into one completion.

The dispatch/retry/shed/fence state machine is model-checked
exhaustively in ``lint/model.py`` (``router_model``: deadlock-free,
at-most-once per replica, fence holds — DL301/DL302/DL303), and the
chaos scenarios in ``tools/chaos.py`` (replica_kill / slow_replica /
overload_shed / swap_during_traffic) drive the real fleet through the
same transitions.
"""

from __future__ import annotations

import random
import threading
import time

from distlearn_tpu import obs
from distlearn_tpu.comm import transport
from distlearn_tpu.comm.errors import PeerClosed
from distlearn_tpu.obs import trace as obs_trace
from distlearn_tpu.serve.client import ReplicaDead, ServeError

#: same decades as the server's TTFT/TPOT buckets — failover and hedge
#: recoveries land in the same 1ms..10s range.
_LAT_BUCKETS = (.0005, .001, .0025, .005, .01, .025, .05, .1, .25, .5,
                1.0, 2.5, 5.0, 10.0)


class RouterBusy(ServeError):
    """Router-level admission control: the fleet's aggregate queue is
    past the watermark (or every replica shed) — retry after
    ``retry_after`` seconds."""


class _Replica:
    """One fleet member: address, cached health, down-backoff state and
    the persistent probe connection (streams use their own)."""

    __slots__ = ("host", "port", "name", "conn", "health", "polled",
                 "down_until", "fails")

    def __init__(self, host: str, port: int):
        self.host, self.port = host, int(port)
        self.name = f"{host}:{port}"
        self.conn = None
        self.health = None          # last snapshot, None when unreachable
        self.polled = 0.0           # clock() of last probe
        self.down_until = 0.0       # no dials/probes before this
        self.fails = 0              # consecutive probe failures

    def score(self):
        """Load for least-loaded dispatch: waiting + decoding."""
        h = self.health or {}
        return int(h.get("queue_depth", 0)) + int(h.get("active", 0))


class Router:
    def __init__(self, replicas, *, shed_watermark: int | None = None,
                 health_ttl: float = 0.25, dial_deadline: float = 2.0,
                 probe_timeout: float = 2.0, retry_interval: float = 0.05,
                 max_interval: float = 2.0, max_attempts: int = 10,
                 hedge_after: float | None = None, export_health: bool = False,
                 clock=time.monotonic, sleep=time.sleep):
        """``replicas`` is a list of ``(host, port)``.  ``hedge_after``
        of ``None`` disables hedging; ``shed_watermark`` of ``None``
        disables router-level shedding (replica-level ``QueueFull``
        still sheds).  ``export_health`` wires :meth:`health` into the
        obs '/healthz' exporter — leave off when a server in the same
        process already owns it."""
        if not replicas:
            raise ValueError("router needs at least one replica")
        self._replicas = [_Replica(h, p) for h, p in replicas]
        if len({r.name for r in self._replicas}) != len(self._replicas):
            raise ValueError("duplicate replica address")
        self.shed_watermark = shed_watermark
        self.health_ttl = float(health_ttl)
        self.dial_deadline = float(dial_deadline)
        self.probe_timeout = float(probe_timeout)
        self.retry_interval = float(retry_interval)
        self.max_interval = float(max_interval)
        self.max_attempts = int(max_attempts)
        self.hedge_after = hedge_after
        self._clock = clock
        self._sleep = sleep
        self._lock = threading.Lock()    # health cache + probe conns
        self._c_dispatch = obs.counter(
            "router_dispatch_total", "requests dispatched, per replica",
            labels=("replica",))
        self._c_retry = obs.counter(
            "router_retries_total",
            "queued-not-prefilled resubmissions, per failed replica",
            labels=("replica",))
        self._c_shed = obs.counter(
            "router_shed_total", "requests shed by router admission control")
        self._c_hedge = obs.counter(
            "router_hedges_total",
            "hedged resubmissions, per replica hedged away from",
            labels=("replica",))
        self._c_fence = obs.counter(
            "router_fence_violations_total",
            "streams terminated for observing two center epochs")
        self._h_failover = obs.histogram(
            "router_failover_seconds",
            "replica death/timeout to first token on a survivor",
            buckets=_LAT_BUCKETS)
        self._h_hedge = obs.histogram(
            "router_hedge_seconds",
            "hedge fire to first token on the hedged replica",
            buckets=_LAT_BUCKETS)
        self._g_live = obs.gauge(
            "router_replicas_live", "replicas serving per last probe")
        self._g_rq = obs.gauge(
            "router_replica_queue_depth", "per-replica queue depth",
            labels=("replica",))
        self._g_up = obs.gauge(
            "router_replica_up", "1 when the replica answered its last probe",
            labels=("replica",))
        if export_health:
            obs.set_health_source(self.health)

    # -- health cache -------------------------------------------------------
    def _probe(self, rep: _Replica, now: float):
        try:
            if rep.conn is None:
                rep.conn = transport.connect(
                    rep.host, rep.port, retries=1,
                    deadline_s=self.dial_deadline)
            rep.conn.send_msg({"q": "stats"})
            rep.health = rep.conn.recv_msg(
                deadline=now + self.probe_timeout)
            rep.fails = 0
            rep.down_until = 0.0
        except (OSError, transport.ProtocolError, ValueError):
            if rep.conn is not None:
                rep.conn.close()
                rep.conn = None
            rep.health = None
            rep.fails += 1
            # full-jitter backoff on the probe, the transport.connect
            # policy: down replicas get cheaper to skip, not hammered.
            cap = min(self.max_interval,
                      self.retry_interval * (2 ** (rep.fails - 1)))
            rep.down_until = now + random.uniform(0.0, cap)
        rep.polled = now

    def _refresh(self, now: float, force: bool = False):
        with self._lock:
            for rep in self._replicas:
                due = force or now - rep.polled >= self.health_ttl
                if due and now >= rep.down_until:
                    self._probe(rep, now)
                self._g_rq.labels(replica=rep.name).set(
                    (rep.health or {}).get("queue_depth", 0))
                self._g_up.labels(replica=rep.name).set(
                    1 if rep.health is not None else 0)
            self._g_live.set(sum(1 for r in self._replicas
                                 if self._live(r, now)))

    @staticmethod
    def _live(rep: _Replica, now: float) -> bool:
        h = rep.health
        return (h is not None and bool(h.get("serving"))
                and not h.get("failed") and not h.get("draining")
                and now >= rep.down_until)

    def _pick(self, tried: set, now: float):
        """Least-loaded live replica not yet tried for this request."""
        with self._lock:
            live = [r for r in self._replicas
                    if r.name not in tried and self._live(r, now)]
            return min(live, key=_Replica.score) if live else None

    def _has_alternative(self, tried: set) -> bool:
        now = self._clock()
        with self._lock:
            return any(r.name not in tried and self._live(r, now)
                       for r in self._replicas)

    # -- fleet introspection ------------------------------------------------
    def health(self) -> dict:
        """Aggregate fleet snapshot (a '/healthz' source: the fleet is
        serving while ANY replica is)."""
        now = self._clock()
        self._refresh(now)
        reps = []
        with self._lock:
            for r in self._replicas:
                reps.append({"replica": r.name,
                             "up": r.health is not None,
                             "live": self._live(r, now),
                             **{k: (r.health or {}).get(k)
                                for k in ("queue_depth", "active",
                                          "draining", "epoch")}})
        live = [r for r in reps if r["live"]]
        return {"serving": bool(live),
                "replicas": reps,
                "live": len(live),
                "queue_depth": sum(r["queue_depth"] or 0 for r in live),
                "active": sum(r["active"] or 0 for r in live),
                "epochs": sorted({r["epoch"] for r in live
                                  if r["epoch"] is not None})}

    # -- dynamic membership (the autoscaler's actuation surface) ------------
    def add_replica(self, host: str, port: int) -> str:
        """Grow the fleet in place: the new member is probed on the next
        refresh and picks up dispatch as soon as it answers live.
        Idempotent on an address already present.  Returns the replica
        name (``host:port``)."""
        rep = _Replica(host, int(port))
        with self._lock:
            if all(r.name != rep.name for r in self._replicas):
                # copy-on-write: generate()'s lock-free availability scan
                # only ever sees a complete list
                self._replicas = self._replicas + [rep]
        return rep.name

    def remove_replica(self, name: str) -> bool:
        """Retire one member by name.  New dispatch stops immediately;
        streams already running against it finish on their own
        connections.  Refuses to empty the fleet (the constructor
        invariant); returns False for an unknown name."""
        with self._lock:
            gone = [r for r in self._replicas if r.name == name]
            if not gone:
                return False
            keep = [r for r in self._replicas if r.name != name]
            if not keep:
                raise ValueError("cannot remove the last replica")
            self._replicas = keep
        for r in gone:
            if r.conn is not None:
                r.conn.close()
        return True

    def replica_names(self) -> list[str]:
        with self._lock:
            return [r.name for r in self._replicas]

    # -- admission control --------------------------------------------------
    def _check_shed(self, now: float):
        if self.shed_watermark is None:
            return
        with self._lock:
            agg = sum(r.score() for r in self._replicas
                      if self._live(r, now))
        if agg >= self.shed_watermark:
            self._c_shed.inc()
            hint = min(5.0, max(0.05, 0.05 * agg))
            raise RouterBusy(
                f"fleet queue depth {agg} at/over watermark "
                f"{self.shed_watermark}", retry_after=hint,
                queue_depth=agg)

    # -- the request path ---------------------------------------------------
    def generate(self, prompt, max_new: int, *, rid: str | None = None,
                 deadline_s: float | None = None, eos: int | None = None,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 0.0, seed: int = 0, speculate: bool = True,
                 timeout: float = 60.0, on_chunk=None) -> dict:
        """Run one request against the fleet.  Returns ``{"rid",
        "tokens", "reason", "epoch", "replica", "accepted",
        "cached_tokens"}``; ``reason`` is ``"failed"`` (with an
        ``"error"`` note and the partial tokens) when the owning replica
        died mid-stream or fenced.  Sampling knobs travel on the 'G'
        frame (``temperature == 0`` is exact greedy; ``seed`` makes a
        sampled stream reproducible); ``speculate=False`` opts a greedy
        stream out of speculative decoding.  Raises
        :class:`RouterBusy` on shed, :class:`ReplicaDead` when every
        replica was tried or attempts ran out, :class:`ServeError` on a
        non-retryable rejection, ``TimeoutError`` past ``timeout``."""
        kw = dict(rid=rid, deadline_s=deadline_s, eos=eos,
                  temperature=temperature, top_k=top_k, top_p=top_p,
                  seed=seed, speculate=speculate, timeout=timeout,
                  on_chunk=on_chunk)
        if not obs_trace.propagate_enabled():
            return self._generate(prompt, max_new, **kw)
        # one trace per request: this root span is the parent the
        # replica's scheduler/engine spans stitch to (the 'G' frame
        # carries the context) along with the failover/hedge spans here
        with obs_trace.use_context(obs_trace.new_trace()), \
                obs.span("router.generate", rid=rid or ""):
            return self._generate(prompt, max_new, **kw)

    def _generate(self, prompt, max_new: int, *, rid, deadline_s, eos,
                  temperature, top_k, top_p, seed, speculate,
                  timeout, on_chunk) -> dict:
        start = self._clock()
        overall = start + float(timeout)
        self._refresh(start)
        self._check_shed(start)
        msg = {"prompt": [int(t) for t in prompt], "max_new": int(max_new)}
        tc = obs_trace.wire_context()
        if tc is not None:
            msg[obs_trace.TRACE_KEY] = tc
        if rid is not None:
            msg["rid"] = rid
        if deadline_s is not None:
            msg["deadline_s"] = float(deadline_s)
        if eos is not None:
            msg["eos"] = int(eos)
        # sampling fields ride only when non-default, so the plain
        # greedy 'G' frame stays byte-identical to the pre-sampling wire
        if temperature:
            msg["temperature"] = float(temperature)
        if top_k:
            msg["top_k"] = int(top_k)
        if top_p:
            msg["top_p"] = float(top_p)
        if seed:
            msg["seed"] = int(seed)
        if not speculate:
            msg["speculate"] = False
        hedge_after = self.hedge_after
        if hedge_after is not None and deadline_s is not None:
            hedge_after = min(hedge_after, 0.5 * float(deadline_s))
        tried: set[str] = set()
        shed_hints: list[float] = []
        failover_t0 = hedge_t0 = None
        waits = 0
        while True:
            now = self._clock()
            if now >= overall:
                raise TimeoutError(f"no replica completed the request "
                                   f"within {timeout}s")
            rep = self._pick(tried, now)
            if rep is None:
                if not any(r.name not in tried for r in self._replicas):
                    if shed_hints:
                        self._c_shed.inc()
                        raise RouterBusy("every replica shed the request",
                                         retry_after=max(shed_hints))
                    raise ReplicaDead(
                        f"all {len(self._replicas)} replicas tried and "
                        "dead — no survivor to resubmit to")
                waits += 1
                if waits > self.max_attempts:
                    raise ReplicaDead(
                        f"no live replica after {waits - 1} waits")
                cap = min(self.max_interval,
                          self.retry_interval * (2 ** (waits - 1)))
                self._sleep(random.uniform(0.0, cap))
                self._refresh(self._clock(), force=True)
                continue
            tried.add(rep.name)
            self._c_dispatch.labels(replica=rep.name).inc()
            hedge_at = (None if hedge_after is None
                        else self._clock() + hedge_after)
            status, payload = self._run_stream(
                rep, msg, rid, overall, hedge_at, on_chunk, tried,
                failover_t0, hedge_t0)
            if status == "done":
                return payload
            if status == "dead":
                # queued-not-yet-prefilled on a dead replica: safe to
                # resubmit — backoff with full jitter, walk survivors.
                self._c_retry.labels(replica=rep.name).inc()
                failover_t0 = failover_t0 or self._clock()
                with self._lock:
                    rep.health = None
                    rep.fails += 1
                    rep.down_until = self._clock() + random.uniform(
                        0.0, min(self.max_interval,
                                 self.retry_interval * (2 ** rep.fails)))
                self._sleep(random.uniform(0.0, min(
                    self.max_interval,
                    self.retry_interval * (2 ** len(tried)))))
                self._refresh(self._clock(), force=True)
                continue
            if status == "hedge":
                self._c_hedge.labels(replica=rep.name).inc()
                hedge_t0 = self._clock()
                continue                # no sleep: hedging chases latency
            if status == "rejected":
                chunk = payload
                if chunk.get("retry_after") is None:
                    # not load: the request itself is bad (too long,
                    # duplicate rid) — every replica would say the same.
                    raise ServeError(chunk.get("error", "rejected"),
                                     queue_depth=chunk.get("queue_depth"))
                shed_hints.append(float(chunk["retry_after"]))
                continue                # shed here; try the next replica
            # "failed" / "fence": tokens already flowed — resubmitting
            # would duplicate output.  Clean terminal instead of a hang.
            tokens, epoch, err = payload
            return {"rid": rid, "tokens": tokens, "reason": "failed",
                    "error": err, "epoch": epoch, "replica": rep.name,
                    "accepted": 0, "cached_tokens": 0}

    def _run_stream(self, rep: _Replica, msg: dict, rid: str | None,
                    overall: float, hedge_at: float | None, on_chunk,
                    tried: set, failover_t0, hedge_t0):
        """One sticky stream against one replica.  Returns
        ``(status, payload)``: ``done``/``dead``/``failed``/``hedge``/
        ``rejected`` (see :meth:`generate`)."""
        try:
            conn = transport.connect(rep.host, rep.port, retries=1,
                                     deadline_s=self.dial_deadline)
        except ConnectionError as e:
            return "dead", e
        tokens: list[int] = []
        epoch = None
        first_seen = False
        accepted = 0            # speculative drafts the replica accepted
        cached = 0              # prompt tokens served from its prefix cache
        try:
            conn.send_gen(msg)
        except OSError as e:
            conn.close()
            return "dead", e
        while True:
            now = self._clock()
            if now >= overall:
                conn.close()            # cancels the server-side copy
                raise TimeoutError(
                    f"stream on {rep.name} exceeded its budget "
                    f"({len(tokens)} token(s) in)")
            deadline = overall
            if not first_seen and hedge_at is not None:
                deadline = min(deadline, hedge_at)
            try:
                kind, chunk = conn.recv_serve(deadline=deadline)
            except TimeoutError:
                if not first_seen and hedge_at is not None:
                    if self._has_alternative(tried):
                        # cancel the queued copy before re-dispatching:
                        # dropping the conn cancels it server-side, so
                        # execution stays at-most-once per replica.
                        conn.close()
                        return "hedge", tokens
                    hedge_at = None     # nobody to hedge to: disarm
                continue
            except (PeerClosed, ConnectionResetError,
                    BrokenPipeError) as e:
                conn.close()
                if first_seen:
                    return "failed", (tokens, epoch,
                                      f"replica died mid-stream: {e!r}")
                return "dead", e
            if kind != "R":
                conn.close()
                raise transport.ProtocolError(
                    f"expected stream chunk, got kind {kind!r}")
            if rid is not None and chunk.get("rid") not in (rid, ""):
                continue
            ep = chunk.get("epoch")
            if ep is not None:
                if epoch is None:
                    epoch = ep
                elif ep != epoch:
                    self._c_fence.inc()
                    conn.close()
                    return "failed", (tokens, epoch,
                                      f"epoch fence: chunk epoch {ep} "
                                      f"after stream pinned {epoch}")
            if chunk.get("error"):
                conn.close()
                return "rejected", chunk
            if chunk.get("accepted"):
                accepted += int(chunk["accepted"])
            if chunk.get("cached_tokens"):
                cached = int(chunk["cached_tokens"])
            got = chunk.get("tokens") or []
            if got:
                if not first_seen:
                    first_seen = True
                    if failover_t0 is not None:
                        d = self._clock() - failover_t0
                        self._h_failover.observe(d)
                        obs.record_span("router.failover", d,
                                        replica=rep.name)
                    if hedge_t0 is not None:
                        d = self._clock() - hedge_t0
                        self._h_hedge.observe(d)
                        obs.record_span("router.hedge", d,
                                        replica=rep.name)
                tokens.extend(int(t) for t in got)
                if on_chunk is not None:
                    on_chunk(got)
            if chunk.get("done"):
                reason = chunk.get("reason", "complete")
                conn.close()
                if reason not in ("complete", "eos"):
                    raise ServeError(f"request ended: {reason}")
                return "done", {"rid": chunk.get("rid"), "tokens": tokens,
                                "reason": reason, "epoch": epoch,
                                "replica": rep.name, "accepted": accepted,
                                "cached_tokens": cached}

    def close(self):
        with self._lock:
            for rep in self._replicas:
                if rep.conn is not None:
                    rep.conn.close()
                    rep.conn = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
