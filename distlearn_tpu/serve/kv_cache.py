"""Fixed-slot paged KV cache — the serving engine's memory manager.

vLLM/PagedAttention (Kwon et al., SOSP '23) decouples a request's
logical K/V sequence from physical storage: the device holds one page
POOL per layer (``[depth, num_pages, page, H, D]``) and each of the
``num_slots`` request slots owns a BLOCK TABLE row mapping its logical
pages to pool pages.  Admission allocates exactly the pages a request
can ever touch (``prompt_len + max_new`` positions, rounded up to whole
pages); finish/evict returns them to the free list.  Slots are the unit
of batching: the decode tick (``serve.engine``) advances every ACTIVE
slot by one token in a single compiled program, gathering each slot's
K/V through its block-table row.

Admit/evict/finish happen BETWEEN ticks, on the host, in plain Python —
this module never imports the compiled side.  It owns three invariants
the tests pin down (tests/test_serve.py):

* **No stale reads.**  Freed pages are returned to the pool without
  zeroing.  A new request can only read cache positions below its own
  current length, and every one of those positions was freshly written
  by its OWN prefill scatter or decode ticks — so recycled bytes are
  never observable (the parity test decodes through heavy slot reuse
  and must stay token-identical to isolated runs).
* **Page 0 is the trash page.**  It is never allocated; freed block
  table rows reset to 0 and unallocated tail entries stay 0, so masked
  lanes (inactive slots, padded prefill tails) scatter there instead of
  into live data.
* **Exact accounting.**  Every allocatable page is either on the free
  list (refcount 0) or referenced (refcount >= 1):
  ``free_pages + pages-with-ref > 0 == num_pages - 1`` at all times;
  double-free and double-admit raise instead of corrupting the pool.

Pages are REFERENCE COUNTED so one physical page can back the same
logical prefix position of several slots at once (and be retained by
the radix prefix cache, :mod:`distlearn_tpu.serve.prefix_cache`, after
every owning request finished).  Sharing is restricted to WHOLE pages
strictly before a request's first self-written position, which makes
the copy-on-write discipline structural: a slot only ever writes cache
positions ``>= cached_len`` (its shared-page count times the page
size), so a write into a shared page cannot be expressed — there is
nothing to copy because the writer's pages and the shared pages are
disjoint rows of its block table by construction.
"""

from __future__ import annotations

from typing import Iterable, Sequence

import numpy as np


class CacheFull(RuntimeError):
    """No free slot, or not enough free pages for the request."""


class PagedKVCache:
    """Host-side slot/page bookkeeping for the serving engine.

    The device arrays (the pools themselves) live in the engine; this
    class owns the integer state the compiled tick consumes: the block
    tables, per-slot lengths, last-emitted tokens, and the active mask.
    """

    def __init__(self, num_slots: int, page: int, max_len: int,
                 num_pages: int | None = None):
        if num_slots < 1 or page < 1 or max_len < 1:
            raise ValueError(f"num_slots={num_slots}, page={page}, "
                             f"max_len={max_len} must all be >= 1")
        self.num_slots = int(num_slots)
        self.page = int(page)
        self.max_len = int(max_len)
        #: logical pages a slot can address (the gather width of the tick)
        self.pages_per_slot = -(-self.max_len // self.page)
        # default pool: every slot can hold a full-length request, plus
        # the reserved trash page 0
        if num_pages is None:
            num_pages = self.num_slots * self.pages_per_slot + 1
        if num_pages < 2:
            raise ValueError(f"num_pages={num_pages} leaves no allocatable "
                             "page beyond the reserved trash page 0")
        self.num_pages = int(num_pages)
        self._free: list[int] = list(range(self.num_pages - 1, 0, -1))
        #: per-page reference count: 0 = free (or the trash page),
        #: 1 = one owner (a slot row or a prefix-cache node), >1 shared.
        self.ref = np.zeros((self.num_pages,), np.int32)
        # block_table[s, j] = pool page backing slot s's logical page j
        # (0 = trash: unallocated)
        self.block_table = np.zeros((self.num_slots, self.pages_per_slot),
                                    np.int32)
        self.lengths = np.zeros((self.num_slots,), np.int32)
        self.last_tok = np.zeros((self.num_slots,), np.int32)
        self.active = np.zeros((self.num_slots,), bool)
        #: per-slot hard cap (prompt_len + max_new) — the engine stops a
        #: slot before it writes past its allocation
        self.limit = np.zeros((self.num_slots,), np.int32)

    # -- capacity queries ---------------------------------------------------
    def pages_for(self, total_len: int) -> int:
        return -(-int(total_len) // self.page)

    def free_pages(self) -> int:
        return len(self._free)

    def free_slots(self) -> int:
        return int((~self.active).sum())

    def can_admit(self, total_len: int, shared_pages: int = 0) -> bool:
        """True when a request needing ``total_len`` cache positions has
        both a free slot and enough free pages; ``shared_pages`` leading
        pages come from the prefix cache and cost nothing."""
        return (self.free_slots() > 0
                and self.pages_for(total_len) - int(shared_pages)
                <= len(self._free)
                and total_len <= self.max_len)

    # -- page reference counting (prefix-cache sharing) ---------------------
    def share(self, pages: Iterable[int]):
        """Take one more reference on each (already-allocated) page —
        a prefix-cache node retaining them, or a slot adopting a cached
        prefix.  Sharing a free page or the trash page is a bug."""
        for p in pages:
            p = int(p)
            if p <= 0 or p >= self.num_pages:
                raise ValueError(f"page {p} outside the pool")
            if self.ref[p] < 1:
                raise ValueError(f"page {p} is free — cannot share")
            self.ref[p] += 1

    def unref(self, pages: Iterable[int]) -> int:
        """Drop one reference per page; pages reaching refcount 0 return
        to the free list.  Returns how many pages were actually freed."""
        freed = 0
        for p in pages:
            p = int(p)
            if p <= 0 or p >= self.num_pages or self.ref[p] < 1:
                raise ValueError(f"page {p} is not allocated (double "
                                 "unref?)")
            self.ref[p] -= 1
            if self.ref[p] == 0:
                self._free.append(p)
                freed += 1
        return freed

    # -- slot lifecycle -----------------------------------------------------
    def admit(self, total_len: int,
              shared: Sequence[int] = ()) -> int:
        """Claim a free slot and allocate pages for ``total_len`` cache
        positions.  ``shared`` (optional) is a list of already-written
        pages from the prefix cache installed as the slot's leading
        block-table rows — each gains a reference instead of an
        allocation, so a 90%-overlap prompt only allocates its suffix.
        Returns the slot index; raises :class:`CacheFull` when capacity
        is short (callers gate on :meth:`can_admit`)."""
        total_len = int(total_len)
        if total_len < 1 or total_len > self.max_len:
            raise ValueError(f"total_len={total_len} outside "
                             f"[1, max_len={self.max_len}]")
        need = self.pages_for(total_len)
        shared = [int(p) for p in shared]
        if len(shared) >= need:
            raise ValueError(
                f"{len(shared)} shared pages cover all {need} pages of "
                f"total_len={total_len}: the request must prefill at "
                "least its last position itself")
        if need - len(shared) > len(self._free):
            raise CacheFull(f"{need - len(shared)} pages needed, "
                            f"{len(self._free)} free")
        free = np.flatnonzero(~self.active)
        if not len(free):
            raise CacheFull("all slots busy")
        self.share(shared)      # validates before any state is touched
        slot = int(free[0])
        for j, p in enumerate(shared):
            self.block_table[slot, j] = p
        for j in range(len(shared), need):
            p = self._free.pop()
            self.block_table[slot, j] = p
            self.ref[p] = 1
        self.lengths[slot] = 0
        self.last_tok[slot] = 0
        self.limit[slot] = total_len
        self.active[slot] = True
        return slot

    def release(self, slot: int):
        """Finish/evict: drop the slot's page references and reset its
        block-table row to trash.  Pages still referenced elsewhere (a
        prefix-cache node, another slot sharing the prefix) survive;
        the rest return to the pool.  Page contents are NOT zeroed —
        the no-stale-reads invariant (module docstring) makes that
        unnecessary, and skipping it keeps eviction O(pages) host work."""
        slot = int(slot)
        if not self.active[slot]:
            raise ValueError(f"slot {slot} is not active (double release?)")
        row = [int(p) for p in self.block_table[slot] if p]
        self.unref(row)
        self.block_table[slot] = 0
        self.lengths[slot] = 0
        self.last_tok[slot] = 0
        self.limit[slot] = 0
        self.active[slot] = False

    def check(self):
        """Assert the exact-accounting invariant (test hook)."""
        held = int((self.ref > 0).sum())
        if held + len(self._free) != self.num_pages - 1:
            raise AssertionError(
                f"page leak: {held} referenced + {len(self._free)} free "
                f"!= {self.num_pages - 1} allocatable")
        if len(set(self._free)) != len(self._free):
            raise AssertionError("duplicate page in free list")
        if self.ref[0] != 0:
            raise AssertionError("the trash page grew a reference")
        live = set(self.block_table[self.block_table > 0].tolist())
        if live & set(self._free):
            raise AssertionError("page both allocated and free")
        for p in live:
            if self.ref[p] < 1:
                raise AssertionError(f"page {p} in a block table with "
                                     f"refcount {self.ref[p]}")
        # each slot row must hold at least as many references as it has
        # pointers to the page (shared prefixes push the count higher)
        counts = np.bincount(self.block_table.reshape(-1),
                             minlength=self.num_pages)
        counts[0] = 0
        if (counts > self.ref).any():
            bad = np.flatnonzero(counts > self.ref).tolist()
            raise AssertionError(f"pages {bad} pointed to by more rows "
                                 "than their refcount")
