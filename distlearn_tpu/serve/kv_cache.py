"""Fixed-slot paged KV cache — the serving engine's memory manager.

vLLM/PagedAttention (Kwon et al., SOSP '23) decouples a request's
logical K/V sequence from physical storage: the device holds one page
POOL per layer (``[depth, num_pages, page, H, D]``) and each of the
``num_slots`` request slots owns a BLOCK TABLE row mapping its logical
pages to pool pages.  Admission allocates exactly the pages a request
can ever touch (``prompt_len + max_new`` positions, rounded up to whole
pages); finish/evict returns them to the free list.  Slots are the unit
of batching: the decode tick (``serve.engine``) advances every ACTIVE
slot by one token in a single compiled program, gathering each slot's
K/V through its block-table row.

Admit/evict/finish happen BETWEEN ticks, on the host, in plain Python —
this module never imports the compiled side.  It owns three invariants
the tests pin down (tests/test_serve.py):

* **No stale reads.**  Freed pages are returned to the pool without
  zeroing.  A new request can only read cache positions below its own
  current length, and every one of those positions was freshly written
  by its OWN prefill scatter or decode ticks — so recycled bytes are
  never observable (the parity test decodes through heavy slot reuse
  and must stay token-identical to isolated runs).
* **Page 0 is the trash page.**  It is never allocated; freed block
  table rows reset to 0 and unallocated tail entries stay 0, so masked
  lanes (inactive slots, padded prefill tails) scatter there instead of
  into live data.
* **Exact accounting.**  ``free_pages + pages-in-tables == num_pages-1``
  at all times; double-free and double-admit raise instead of
  corrupting the pool.
"""

from __future__ import annotations

import numpy as np


class CacheFull(RuntimeError):
    """No free slot, or not enough free pages for the request."""


class PagedKVCache:
    """Host-side slot/page bookkeeping for the serving engine.

    The device arrays (the pools themselves) live in the engine; this
    class owns the integer state the compiled tick consumes: the block
    tables, per-slot lengths, last-emitted tokens, and the active mask.
    """

    def __init__(self, num_slots: int, page: int, max_len: int,
                 num_pages: int | None = None):
        if num_slots < 1 or page < 1 or max_len < 1:
            raise ValueError(f"num_slots={num_slots}, page={page}, "
                             f"max_len={max_len} must all be >= 1")
        self.num_slots = int(num_slots)
        self.page = int(page)
        self.max_len = int(max_len)
        #: logical pages a slot can address (the gather width of the tick)
        self.pages_per_slot = -(-self.max_len // self.page)
        # default pool: every slot can hold a full-length request, plus
        # the reserved trash page 0
        if num_pages is None:
            num_pages = self.num_slots * self.pages_per_slot + 1
        if num_pages < 2:
            raise ValueError(f"num_pages={num_pages} leaves no allocatable "
                             "page beyond the reserved trash page 0")
        self.num_pages = int(num_pages)
        self._free: list[int] = list(range(self.num_pages - 1, 0, -1))
        # block_table[s, j] = pool page backing slot s's logical page j
        # (0 = trash: unallocated)
        self.block_table = np.zeros((self.num_slots, self.pages_per_slot),
                                    np.int32)
        self.lengths = np.zeros((self.num_slots,), np.int32)
        self.last_tok = np.zeros((self.num_slots,), np.int32)
        self.active = np.zeros((self.num_slots,), bool)
        #: per-slot hard cap (prompt_len + max_new) — the engine stops a
        #: slot before it writes past its allocation
        self.limit = np.zeros((self.num_slots,), np.int32)

    # -- capacity queries ---------------------------------------------------
    def pages_for(self, total_len: int) -> int:
        return -(-int(total_len) // self.page)

    def free_pages(self) -> int:
        return len(self._free)

    def free_slots(self) -> int:
        return int((~self.active).sum())

    def can_admit(self, total_len: int) -> bool:
        """True when a request needing ``total_len`` cache positions has
        both a free slot and enough free pages."""
        return (self.free_slots() > 0
                and self.pages_for(total_len) <= len(self._free)
                and total_len <= self.max_len)

    # -- slot lifecycle -----------------------------------------------------
    def admit(self, total_len: int) -> int:
        """Claim a free slot and allocate pages for ``total_len`` cache
        positions.  Returns the slot index; raises :class:`CacheFull`
        when capacity is short (callers gate on :meth:`can_admit`)."""
        total_len = int(total_len)
        if total_len < 1 or total_len > self.max_len:
            raise ValueError(f"total_len={total_len} outside "
                             f"[1, max_len={self.max_len}]")
        need = self.pages_for(total_len)
        if need > len(self._free):
            raise CacheFull(f"{need} pages needed, {len(self._free)} free")
        free = np.flatnonzero(~self.active)
        if not len(free):
            raise CacheFull("all slots busy")
        slot = int(free[0])
        for j in range(need):
            self.block_table[slot, j] = self._free.pop()
        self.lengths[slot] = 0
        self.last_tok[slot] = 0
        self.limit[slot] = total_len
        self.active[slot] = True
        return slot

    def release(self, slot: int):
        """Finish/evict: return the slot's pages to the pool and reset
        its block-table row to trash.  Page contents are NOT zeroed —
        the no-stale-reads invariant (module docstring) makes that
        unnecessary, and skipping it keeps eviction O(pages) host work."""
        slot = int(slot)
        if not self.active[slot]:
            raise ValueError(f"slot {slot} is not active (double release?)")
        for j in range(self.pages_per_slot):
            p = int(self.block_table[slot, j])
            if p:
                self._free.append(p)
            self.block_table[slot, j] = 0
        self.lengths[slot] = 0
        self.last_tok[slot] = 0
        self.limit[slot] = 0
        self.active[slot] = False

    def check(self):
        """Assert the exact-accounting invariant (test hook)."""
        held = int((self.block_table > 0).sum())
        if held + len(self._free) != self.num_pages - 1:
            raise AssertionError(
                f"page leak: {held} in tables + {len(self._free)} free "
                f"!= {self.num_pages - 1} allocatable")
        if len(set(self._free)) != len(self._free):
            raise AssertionError("duplicate page in free list")
        live = set(self.block_table[self.block_table > 0].tolist())
        if live & set(self._free):
            raise AssertionError("page both allocated and free")
