"""Prompt-lookup speculative drafting — >1 accepted tokens per verify
tick with NO second model (Leviathan et al. 2022; Saxena's prompt
lookup decoding).

Speculative decoding splits token generation into a cheap DRAFT and an
exact VERIFY: some oracle proposes ``k`` next tokens, one batched
engine dispatch scores all ``k`` positions at once, and the leading run
of drafts that match the model's own argmax is accepted — plus the
model's token at the first mismatch position as a free "bonus".  The
output token sequence is EXACTLY the sequence greedy decoding would
have produced (every accepted token equals the model argmax at its
position, and the bonus token is the model argmax after the accepted
prefix), so speculation is a pure latency trade: fewer dispatches for
the same tokens.  With all drafts rejected, the verify tick still
yields its position-0 token — the plain tick's output — so the
worst case is exactly one token per dispatch, never less
(tests/test_serve_speed.py pins this greedy equivalence).

The drafter here is the degenerate-but-effective one for the traffic
LLM services actually see: **n-gram prompt lookup**.  Generated text
constantly re-quotes its own context (code completion echoes
identifiers, summaries echo their source, chat echoes the system
prompt), so "find the longest suffix of what we've emitted somewhere
earlier in the sequence, and draft whatever followed it there" wins
real acceptance at zero model cost.  No weights, no state, O(context)
host work per proposal.

Rollback is the engine's job and is IMPLICIT: rejected drafts' K/V
were scattered into the slot's own pages at positions past the
accepted length, the slot's length only advances over accepted
positions, attention masks by length, and later writes overwrite the
stale positions — no copy, no restore (docs/SERVING.md).  Sampling
(``temperature > 0``) disables drafting for the slot: verify compares
against argmax, which a sampled stream does not follow.
"""

from __future__ import annotations

from typing import Sequence

__all__ = ["NGramDrafter"]


class NGramDrafter:
    """Draft up to ``k`` tokens by n-gram lookup over the request's own
    context (prompt + generated so far).

    Matching tries the longest suffix first (``n_max`` down to
    ``n_min`` tokens) and takes the MOST RECENT earlier occurrence —
    recency beats frequency for self-quoting text.  Returns ``[]``
    when the context never repeats; the scheduler then just ticks.
    """

    def __init__(self, *, k: int = 4, n_max: int = 3, n_min: int = 1):
        if not 1 <= n_min <= n_max:
            raise ValueError(f"need 1 <= n_min={n_min} <= n_max={n_max}")
        if k < 1:
            raise ValueError(f"k={k} must be >= 1")
        self.k = int(k)
        self.n_max = int(n_max)
        self.n_min = int(n_min)

    def propose(self, context: Sequence[int], k: int | None = None
                ) -> list[int]:
        """Up to ``min(k, self.k)`` draft tokens continuing ``context``
        (the full token ids so far, prompt included)."""
        budget = self.k if k is None else min(int(k), self.k)
        ctx = [int(t) for t in context]
        if budget < 1 or len(ctx) < self.n_min + 1:
            return []
        for n in range(min(self.n_max, len(ctx) - 1), self.n_min - 1, -1):
            tail = ctx[-n:]
            # most recent earlier occurrence of the suffix n-gram; the
            # match may not end at the very tail (that IS the suffix)
            for start in range(len(ctx) - n - 1, -1, -1):
                if ctx[start:start + n] == tail:
                    follow = ctx[start + n:start + n + budget]
                    if follow:
                        return follow
        return []
