"""distserve — continuous-batched, sharded, observable decode service.

The inference half of the north star: the trained transformer behind a
socket.  Orca-style continuous batching (requests join and leave the
running batch between decode ticks) over a vLLM-style fixed-slot paged
KV cache, with the decode tick compiled ONCE as a jit/shard_map program
over tp-sharded weights.

Layers, bottom up:

* ``serve.kv_cache`` — host-side slot/page bookkeeping
  (:class:`PagedKVCache`): block tables, lengths, admit/release, the
  no-stale-reads + trash-page + exact-accounting invariants.
* ``serve.engine`` — :class:`DecodeEngine`: bucketed prefill and the
  batched slot-addressed decode tick, token-identical to
  ``models.transformer.greedy_generate``.
* ``serve.scheduler`` — :class:`Scheduler`: bounded admission queue,
  FIFO admit, deadline eviction; emits events, owns no sockets.
* ``serve.server`` / ``serve.client`` — :class:`ServeServer` wires the
  scheduler to ``comm.transport`` ('G'/'R' frames), ``obs`` (gauges,
  TTFT/TPOT histograms + spans, ``/healthz``) and SIGTERM drain via
  ``ha.install_signal_flush``, and hot-swaps weights from a tailed
  checkpoint directory (:class:`WeightTailer`, epoch-fenced);
  :class:`ServeClient` is the matching one-request driver with typed
  failure classification (:class:`ReplicaDead`) and shed-hint backoff.
* ``serve.router`` — :class:`Router`: shared-nothing fleet front —
  least-loaded health-routed dispatch, retry-on-replica-death for
  queued-not-prefilled requests, load shedding (:class:`RouterBusy`
  with ``retry_after``), deadline-aware hedging, and the epoch fence
  over the hot-swap echo.

Demo: ``examples/lm.py --serve`` + ``examples/lm_client.py``; fleet
demo ``examples/serve_fleet.py``; protocol and runbook in
docs/SERVING.md.
"""

from distlearn_tpu.serve.client import ReplicaDead, ServeClient, ServeError
from distlearn_tpu.serve.engine import DecodeEngine
from distlearn_tpu.serve.kv_cache import CacheFull, PagedKVCache
from distlearn_tpu.serve.router import Router, RouterBusy
from distlearn_tpu.serve.scheduler import Event, QueueFull, Request, Scheduler
from distlearn_tpu.serve.server import ServeServer, WeightTailer

__all__ = [
    "CacheFull",
    "DecodeEngine",
    "Event",
    "PagedKVCache",
    "QueueFull",
    "ReplicaDead",
    "Request",
    "Router",
    "RouterBusy",
    "Scheduler",
    "ServeClient",
    "ServeError",
    "ServeServer",
    "WeightTailer",
]
