"""distlearn_tpu — a TPU-native distributed learning framework.

A ground-up JAX/XLA rebuild of the capabilities of ``shanlior/torch-distlearn``
(Torch7/Lua): synchronous data-parallel **AllReduceSGD**, synchronous elastic
averaging **AllReduceEA** expressed as a single fused collective, and
asynchronous client/server **AsyncEA** (EASGD parameter server).

Where the reference delegates communication to torch-ipc's C++ TCP tree
(reference: lua/AllReduceSGD.lua, lua/AllReduceEA.lua, lua/AsyncEA.lua), this
framework uses an ICI device mesh: parameters and gradients are XLA device
buffers, ``all_reduce``/``scatter`` lower to ``lax.psum``/broadcast inside
jitted step functions, and the AsyncEA push-pull runs over a host-side TCP
control plane (native C++ transport with a pure-Python fallback) against a
pinned center variable.

Layout (mirrors SURVEY.md §7's proposed layout):
  parallel/  — MeshTree (the ``tree`` replacement), AllReduceSGD, AllReduceEA,
               AsyncEA, tensor/sequence-parallel extensions
  comm/      — host-side transport: native C++ TCP sockets + tree allreduce
  models/    — functional model zoo (MNIST CNN, CIFAR convnet, ResNet-50)
  ops/       — Pallas TPU kernels for the hot fused updates
  data/      — partitioned datasets, samplers, device prefetch
  train/     — fused train-step builders (the TPU hot path)
  utils/     — flags, metrics, logging, checkpointing, profiling
"""

__version__ = "0.1.0"

# Publish ``jax.shard_map`` on old jax pins (< 0.7) before anything —
# package-internal or user code written against the modern spelling —
# touches it.  A real ``jax.shard_map`` is never overwritten.
from distlearn_tpu.utils import compat as _compat

_compat.install()

from distlearn_tpu.parallel.mesh import MeshTree, all_reduce, broadcast_from, node_index
from distlearn_tpu.parallel.allreduce_sgd import AllReduceSGD
from distlearn_tpu.parallel.allreduce_ea import AllReduceEA
from distlearn_tpu.parallel.async_ea import (AsyncEAClient, AsyncEAServer,
                                             AsyncEATester)

__all__ = [
    "MeshTree",
    "AllReduceSGD",
    "AllReduceEA",
    "AsyncEAServer",
    "AsyncEAClient",
    "AsyncEATester",
    "all_reduce",
    "broadcast_from",
    "node_index",
    "__version__",
]
