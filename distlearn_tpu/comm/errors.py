"""Transport error taxonomy shared by the Python and native IO paths.

Lives in its own module (rather than comm/transport.py) because the
native ctypes shim (comm/native.py) must raise the same types while
transport.py imports native.py — a shared leaf module breaks the cycle.
"""

from __future__ import annotations


class PeerClosed(ConnectionError):
    """Clean FIN on a frame boundary: the peer finished its stream and
    closed the socket with no frame in flight.  Distinct from
    ``ConnectionResetError`` (FIN/RST mid-frame — a torn frame) so drop
    policy (``Server.recv_any``) can classify the shutdown by type
    instead of string-matching the message."""
