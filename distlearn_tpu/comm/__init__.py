"""Host-side transport: native C++ TCP framing + tree collectives over DCN —
the torch-ipc replacement (SURVEY.md §2b row 1).  The TPU data plane uses XLA
ICI collectives (distlearn_tpu.parallel.mesh); this package is the control
plane for the asynchronous parameter-server path and multi-host side-channel.
"""

from distlearn_tpu.comm import wire
from distlearn_tpu.comm.errors import PeerClosed
from distlearn_tpu.comm.faults import FaultInjected, FaultPlan
from distlearn_tpu.comm.transport import Conn, Server, connect, ProtocolError
from distlearn_tpu.comm.ring import LocalhostRing, Ring

__all__ = ["Conn", "Server", "connect", "PeerClosed", "ProtocolError", "Ring",
           "LocalhostRing", "wire", "FaultPlan", "FaultInjected"]
