"""Host-side transport: native C++ TCP framing + tree collectives over DCN —
the torch-ipc replacement (SURVEY.md §2b row 1).  The TPU data plane uses XLA
ICI collectives (distlearn_tpu.parallel.mesh); this package is the control
plane for the asynchronous parameter-server path and multi-host side-channel.
:mod:`distlearn_tpu.comm.backend` unifies the two behind one
:class:`CollectiveBackend` protocol (host TCP, device SPMD, or the hybrid
hierarchical allreduce).
"""

from distlearn_tpu.comm import wire
from distlearn_tpu.comm.backend import (CollectiveBackend, HostBackend,
                                        HybridBackend, MeshBackend)
from distlearn_tpu.comm.errors import PeerClosed
from distlearn_tpu.comm.faults import FaultInjected, FaultPlan
from distlearn_tpu.comm.transport import Conn, Server, connect, ProtocolError
from distlearn_tpu.comm.ring import LocalhostRing, Ring
from distlearn_tpu.comm.tree import LocalhostTree, Tree, tree_map_spawn

__all__ = ["Conn", "Server", "connect", "PeerClosed", "ProtocolError", "Ring",
           "LocalhostRing", "Tree", "LocalhostTree", "tree_map_spawn",
           "wire", "FaultPlan", "FaultInjected", "CollectiveBackend",
           "HostBackend", "MeshBackend", "HybridBackend"]
