"""Ring allreduce over TCP — the bandwidth-optimal host collective.

The reference's torch-ipc tree moves the FULL payload up and down every
link, giving the documented ``T*log2(N)`` latency (lua/AllReduceEA.md:26-30)
but ``~4T`` of traffic through the base-2 root's NIC (two children, payload
up AND down each) regardless of N.  A ring reduce-scatter + allgather
(Baidu/NCCL style) puts ``2T*(N-1)/N`` out + the same in through every
rank's NIC — ``3T`` at N=4, approaching ``2T`` as N grows, vs the root's
fixed ``4T``.  Measured, not just claimed: at N=4, T=16 MB the bench
records 67.1 MB through the tree root's NIC vs 50.3 MB through a ring
rank's (bench.py host_allreduce, ``*_max_nic_bytes``).

WHEN each wins (measured — docs/PERF.md): per-link bandwidth must be the
bottleneck for the ring's advantage to show in wall clock.  On this
1-core localhost host both backends push the same TOTAL bytes through one
shared CPU, so the tree's fewer rounds win or tie (0.86-1.0x observed).
With every link paced to an emulated 200 MB/s NIC (CPU unsaturated — the
multi-host regime this backend is FOR), the ring runs **~1.4x faster**
at N=4, T=16 MB (and the gap widens with N: the root's 4T is fixed while
its subtree count grows the serialization).  Latency is ``2(N-1)`` hops vs the tree's ``2*log2(N)``,
so for tiny control-plane payloads the tree wins everywhere; the
framework offers both (``comm.tree.Tree`` for scalars, ``Ring`` for
bulk), the choice the reference never had.

:class:`Ring` exposes the same collective surface as :class:`Tree`
(``all_reduce``/``all_reduce_ex`` with contributor + rider semantics,
``scatter``, ``walk``, ``barrier``, ``node_index``/``num_nodes``), so every
host algorithm (distlearn_tpu.parallel.host_algorithms) runs on either
backend unchanged.

Topology/bootstrap: rank 0 runs the same register-then-address coordinator
as the tree; each rank then dials its successor ``(rank+1) % N`` and accepts
its predecessor, closing the ring.  Each collective step sends to the
successor while receiving from the predecessor — full duplex via a
per-connection sender worker, so large chunks cannot deadlock on TCP
buffers.  Byte moving uses the shared framed transport (C++ hot path when
built — src/comm/distcomm.cpp).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable

import numpy as np

try:  # pytree walking without importing all of jax at module import
    import jax.tree_util as _jtu
except Exception:  # pragma: no cover
    _jtu = None

from distlearn_tpu.comm import native
from distlearn_tpu.comm.backend import HostCollectiveBase, _identity
from distlearn_tpu.comm.transport import Conn, Server, connect

PyTree = Any


class _Sender:
    """Ordered async sender for one connection: ``put`` enqueues a tensor
    send, ``flush`` waits until the wire has taken everything.  Lets a ring
    step send chunk A to the successor while the main thread blocks
    receiving chunk B from the predecessor (full duplex)."""

    def __init__(self, conn: Conn):
        self._conn = conn
        self._q: queue.Queue = queue.Queue()
        self._done = threading.Event()
        self._err: list[BaseException] = []
        self._t = threading.Thread(target=self._loop, daemon=True)
        self._t.start()

    def _loop(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            kind, payload = item
            try:
                if kind == "T":
                    self._conn.send_tensor(payload)
                elif kind == "P":
                    self._conn.send_tensors(payload)
                else:
                    self._conn.send_msg(payload)
            except BaseException as e:  # noqa: BLE001 — surfaced in flush
                self._err.append(e)
            finally:
                self._q.task_done()

    def put_tensor(self, arr: np.ndarray):
        self._q.put(("T", arr))

    def put_tensors(self, leaves: list):
        """Enqueue a whole leaf list as ONE packed 'P' frame."""
        self._q.put(("P", leaves))

    def put_msg(self, msg):
        self._q.put(("J", msg))

    def check(self):
        """Raise a send error already known locally WITHOUT waiting for the
        queue to drain — called before blocking on the predecessor recv so a
        dead successor surfaces immediately instead of wedging the
        collective until (op_)timeout."""
        if self._err:
            raise self._err[0]

    def flush(self):
        self._q.join()
        if self._err:
            raise self._err[0]

    def close(self):
        self._q.put(None)
        self._t.join(timeout=5.0)


class Ring(HostCollectiveBase):
    """One rank's handle on the ring (construct one per process/thread).

    Same constructor contract as :class:`distlearn_tpu.comm.tree.Tree`:
    ``host``/``port`` name the rank-0 coordinator; multi-host ranks pass
    ``listen_host``/``advertise_host``; ``op_timeout`` arms per-link failure
    detection (a dead neighbor raises :class:`TimeoutError` instead of
    wedging — the reference wedges, SURVEY.md §5).

    A send failure the sender worker has already observed is raised before
    each blocking predecessor recv (``_Sender.check``), but a successor
    that dies mid-recv can still only be detected by the recv deadline —
    set ``op_timeout`` in production deployments.
    """

    def __init__(self, rank: int, num_nodes: int, host: str, port: int,
                 timeout: float = 60.0,
                 listen_host: str | None = None,
                 advertise_host: str | None = None,
                 op_timeout: float | None = None,
                 fault_plan=None, fault_link: str = "ring"):
        if not 0 <= rank < num_nodes:
            raise ValueError(f"rank {rank} out of range for {num_nodes} nodes")
        self.rank = rank
        self.num_nodes = num_nodes
        self._pred: Conn | None = None
        self._succ: Conn | None = None
        self._sender: _Sender | None = None

        if num_nodes == 1:
            self.set_op_timeout(op_timeout)
            return

        bind_host = listen_host if listen_host is not None else host
        adv_host = advertise_host if advertise_host is not None else (
            listen_host if listen_host not in (None, "0.0.0.0", "::") else host)

        # Every rank listens for its predecessor.
        pred_server = Server(bind_host, 0)

        if rank == 0:
            coord = Server(bind_host, port)
            regs: dict[int, Conn] = {}
            addrs = {0: (adv_host, pred_server.port)}
            for _ in range(num_nodes - 1):
                c = coord.accept(1, timeout=timeout)[0]
                msg = c.recv_msg()
                r = int(msg["rank"])
                regs[r] = c
                addrs[r] = tuple(c.recv_msg()["listen"])
            for r, c in regs.items():
                c.send_msg({"succ": list(addrs[(r + 1) % num_nodes])})
            for c in regs.values():
                c.close()
            coord.close()
            succ_addr = addrs[1 % num_nodes]
        else:
            c = connect(host, port, retries=int(timeout * 4))
            c.send_msg({"rank": rank})
            c.send_msg({"listen": [adv_host, pred_server.port]})
            succ_addr = tuple(c.recv_msg()["succ"])
            c.close()

        # Dial the successor, accept the predecessor (order-independent:
        # the dial retries while the peer's listener is already up).
        self._succ = connect(succ_addr[0], int(succ_addr[1]),
                             retries=int(timeout * 4))
        self._succ.send_msg({"pred": rank})
        self._pred = pred_server.accept(1, timeout=timeout)[0]
        hello = self._pred.recv_msg()
        expect = (rank - 1) % num_nodes
        if int(hello["pred"]) != expect:
            raise RuntimeError(
                f"ring miswired: rank {rank} accepted predecessor "
                f"{hello['pred']}, expected {expect}")
        pred_server.conns.clear()   # detach _pred: close only the listener
        pred_server.close()
        if fault_plan is not None:
            self._pred = fault_plan.wrap(self._pred, fault_link)
            self._succ = fault_plan.wrap(self._succ, fault_link)
        self._sender = _Sender(self._succ)
        self.set_op_timeout(op_timeout)

    def _links(self) -> list[Conn]:
        return [c for c in (self._pred, self._succ) if c is not None]

    # -- collectives ---------------------------------------------------------
    def all_reduce_ex(self, value: PyTree, op: str = "sum",
                      contrib: bool = True, rider: int = 0,
                      codec: str = "raw") -> tuple[PyTree, int, int]:
        """:meth:`all_reduce` plus the out-of-band integer ``rider`` summed
        across ALL ranks regardless of ``contrib`` (round metadata for the
        uneven-step protocol — see Tree.all_reduce_ex).

        The ring's chunked per-tensor frames have nowhere to carry a
        quantization scale, so only ``codec="raw"`` is supported (the
        tree host leg carries the lossy codecs)."""
        if codec != "raw":
            raise ValueError(
                f"Ring.all_reduce_ex is raw-only (got codec={codec!r}); "
                "use the tree transport for lossy host legs")
        leaves = [np.ascontiguousarray(np.asarray(x))
                  for x in _jtu.tree_leaves(value)]
        if not contrib:
            flats = [np.full(x.size, _identity(x.dtype, op), x.dtype)
                     for x in leaves]
        else:
            flats = [x.reshape(-1).copy() for x in leaves]
        # meta chunk: [n_contributors, rider] always sum-reduced
        meta = np.array([1 if contrib else 0, int(rider)], np.int64)

        if self.num_nodes > 1:
            self._ring_allreduce_meta(meta)
            # Pack same-dtype leaves into one flat buffer each: one ring pass
            # per dtype group instead of per leaf (latency: 2(N-1) hops per
            # group).
            groups: dict[np.dtype, list[int]] = {}
            for i, f in enumerate(flats):
                groups.setdefault(f.dtype, []).append(i)
            for dt, idxs in groups.items():
                if len(idxs) == 1:
                    buf = flats[idxs[0]]
                    self._ring_allreduce_flat(buf, op)
                    flats[idxs[0]] = buf
                else:
                    buf = np.concatenate([flats[i] for i in idxs])
                    self._ring_allreduce_flat(buf, op)
                    off = 0
                    for i in idxs:
                        n_el = flats[i].size
                        flats[i] = buf[off:off + n_el]
                        off += n_el

        out = [f.reshape(x.shape) for f, x in zip(flats, leaves)]
        treedef = _jtu.tree_structure(value)
        return (_jtu.tree_unflatten(treedef, out),
                int(meta[0]), int(meta[1]))

    def _ring_allreduce_meta(self, meta: np.ndarray):
        """Tiny scalar metadata (contributor count + rider): circulate every
        rank's original vector once around the ring; each rank accumulates
        the N-1 tokens it sees.  In-place sum into ``meta``."""
        tok = meta.copy()
        total = meta.copy()
        for _ in range(self.num_nodes - 1):
            self._sender.put_msg({"m": tok.tolist()})
            self._sender.check()
            tok = np.asarray(self._pred.recv_msg()["m"], np.int64)
            total += tok
            self._sender.flush()
        meta[:] = total

    def _ring_allreduce_flat(self, buf: np.ndarray, op: str):
        """In-place ring allreduce of a 1-D array: reduce-scatter then
        allgather, N-1 steps each, full duplex per step."""
        n, rank = self.num_nodes, self.rank
        bounds = np.linspace(0, buf.size, n + 1).astype(np.int64)
        chunk = lambda i: buf[bounds[i % n]:bounds[i % n + 1]]  # noqa: E731

        # reduce-scatter: after step s, chunk (rank - s - 1) holds the sum of
        # s+2 ranks' contributions; after n-1 steps chunk (rank+1) is final.
        for s in range(n - 1):
            self._sender.put_tensor(chunk(rank - s))
            self._sender.check()
            part = self._pred.recv_tensor()
            c = chunk(rank - s - 1)
            native.reduce_inplace(c, part.astype(c.dtype, copy=False), op)
            self._sender.flush()
        # allgather: circulate each finalized chunk n-1 hops.
        for s in range(n - 1):
            self._sender.put_tensor(chunk(rank + 1 - s))
            self._sender.check()
            part = self._pred.recv_tensor(out=chunk(rank - s))
            self._sender.flush()

    def scatter(self, value: PyTree) -> PyTree:
        """Rank 0's values broadcast to every rank (ref ``tree.scatter``):
        the whole leaf list travels as ONE packed frame per hop, forwarded
        around the ring by each rank."""
        leaves = [np.asarray(x) for x in _jtu.tree_leaves(value)]
        last = self.num_nodes - 1
        if self.num_nodes == 1:
            out = [np.array(a, copy=True, order="C") for a in leaves]
        elif self.rank == 0:
            bufs = [np.ascontiguousarray(a) for a in leaves]
            self._sender.put_tensors(bufs)
            self._sender.flush()
            out = [np.array(b, copy=True, order="C") for b in bufs]
        else:
            out = self._pred.recv_tensors(
                out=[np.empty(a.shape, a.dtype) for a in leaves])
            if self.rank != last:
                self._sender.put_tensors(out)
                self._sender.flush()
        treedef = _jtu.tree_structure(value)
        return _jtu.tree_unflatten(treedef, out)

    def close(self):
        if self._sender is not None:
            self._sender.close()
        for conn in (self._pred, self._succ):
            if conn is not None:
                conn.close()


def LocalhostRing(rank: int, num_nodes: int, port: int, **kwargs) -> Ring:
    """Single-host convenience, mirroring :func:`comm.tree.LocalhostTree`."""
    return Ring(rank, num_nodes, "127.0.0.1", port, **kwargs)
