"""Base-b TCP tree collectives — the torch-ipc ``ipc.Tree`` /
``ipc.LocalhostTree`` rebuild (reference construction sites:
examples/mnist.lua:16, examples/client_remote.lua:41; claimed cost
``T*log_b(N)`` — lua/AllReduceEA.md:26-30).

Role in the TPU framework: the **DCN/host side-channel**.  On-chip
collectives go through XLA/ICI (distlearn_tpu.parallel.mesh); this tree
carries host-side traffic that must cross processes or hosts outside a jitted
program — multi-host bootstrap, control-plane reductions, metric aggregation
for processes not sharing a mesh, and the per-host leg of the hybrid
hierarchical allreduce (distlearn_tpu.comm.backend.HybridBackend).  The
byte-moving and reduction inner loops run in native C++ (distcomm framing +
elementwise kernels).

Topology: complete base-``b`` tree over 0-based ranks in level order —
``parent(i) = (i-1)//b``, ``children(i) = i*b+1 .. i*b+b``.  Bootstrap: every
rank registers with rank 0, receives its parent's address, then connects to
its parent (so data flows parent↔child directly, never relayed through the
root).

API parity with the reference ``tree`` handle: ``all_reduce`` (+ contributor
count and zero-contribution flush semantics — lua/AllReduceSGD.lua:12,37),
``scatter`` (root broadcast), ``walk`` (walkTable), ``node_index``,
``num_nodes``.  The topology-independent pieces (walk, node_index, op-timeout
arming, the ``all_reduce``/``barrier`` derivations, NIC accounting) live on
the shared :class:`~distlearn_tpu.comm.backend.HostCollectiveBase` so the
ring and any future host topology reuse one surface.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable

import numpy as np

try:  # pytree walking without importing all of jax at module import
    import jax.tree_util as _jtu
except Exception:  # pragma: no cover
    _jtu = None

from distlearn_tpu.comm import native
from distlearn_tpu.comm.backend import HostCollectiveBase, _identity  # noqa: F401 — _identity re-exported for compat
from distlearn_tpu.comm.transport import Conn, Server, connect

PyTree = Any


def _parent(rank: int, base: int) -> int:
    return (rank - 1) // base


def _children(rank: int, base: int, n: int) -> list[int]:
    return [c for c in range(rank * base + 1, rank * base + base + 1)
            if c < n]


class Tree(HostCollectiveBase):
    """One rank's handle on the tree (construct one per process/thread).

    ``rank`` is 0-based (the reference's nodeIndex is 1-based; the examples
    translate).  Rank 0 is the root and must be constructed with the
    coordinator address it listens on; other ranks connect to it.
    """

    def __init__(self, rank: int, num_nodes: int, host: str, port: int,
                 base: int = 2, timeout: float = 60.0,
                 listen_host: str | None = None,
                 advertise_host: str | None = None,
                 op_timeout: float | None = None,
                 fault_plan=None, fault_link: str = "tree"):
        """``host``/``port``: the coordinator (rank 0) address every rank
        dials for bootstrap.  Multi-host ranks must also say where THEY can
        be reached: ``listen_host`` is the local bind address for this rank's
        child-listener (default: ``host``, correct only when all ranks share
        it, e.g. localhost; use ``"0.0.0.0"`` on a multi-host deployment) and
        ``advertise_host`` is the address other ranks should dial to reach
        this rank (default: ``listen_host`` if set and routable, else
        ``host``).

        ``op_timeout``: failure detection for collectives.  The reference
        blocks forever when a node dies mid-reduce (SURVEY.md §5 "a dead
        node hangs the tree"); with ``op_timeout`` set, any collective that
        waits longer than this many seconds on one peer raises
        :class:`TimeoutError` instead of wedging the job.  ``None`` keeps
        the reference's block-forever semantics (collectives may
        legitimately wait on slow ranks).

        ``fault_plan``: optional :class:`~distlearn_tpu.comm.faults.
        FaultPlan`; every data-plane link (parent + children) is wrapped
        onto ``fault_link`` after bootstrap, so injected partitions/delays
        hit the collectives with the handle's normal error semantics
        (``op_timeout`` → :class:`TimeoutError`) — the same surface whether
        the tree is used raw or behind a
        :class:`~distlearn_tpu.comm.backend.HybridBackend` host leg."""
        if not 0 <= rank < num_nodes:
            raise ValueError(f"rank {rank} out of range for {num_nodes} nodes")
        if base < 1:
            raise ValueError("base must be >= 1")
        self.rank = rank
        self.num_nodes = num_nodes
        self.base = base
        self._kids: list[Conn] = []
        self._parent: Conn | None = None
        self._codec_fb = None
        self._codec_scratch: list[np.ndarray] | None = None
        kid_ranks = _children(rank, base, num_nodes)

        bind_host = listen_host if listen_host is not None else host
        adv_host = advertise_host if advertise_host is not None else (
            listen_host if listen_host not in (None, "0.0.0.0", "::") else host)

        # Every rank (incl. root) listens for its children first.
        self._kid_server = Server(bind_host, 0) if kid_ranks else None

        if rank == 0:
            if num_nodes > 1:
                coord = Server(bind_host, port)
                regs: dict[int, Conn] = {}
                for _ in range(num_nodes - 1):
                    c = coord.accept(1, timeout=timeout)[0]
                    msg = c.recv_msg()
                    regs[int(msg["rank"])] = c
                # Tell each rank its parent's address.
                addrs = {0: (adv_host, self._kid_server.port)}
                # collect every rank's child-listener address
                for r, c in regs.items():
                    addrs[r] = tuple(regs[r].recv_msg()["listen"])
                for r, c in regs.items():
                    p = _parent(r, base)
                    c.send_msg({"parent": list(addrs[p])})
                for c in regs.values():
                    c.close()
                coord.close()
        else:
            c = connect(host, port, retries=int(timeout * 4))
            c.send_msg({"rank": rank})
            listen = (adv_host, self._kid_server.port) if self._kid_server \
                else (adv_host, 0)
            c.send_msg({"listen": list(listen)})
            p_host, p_port = c.recv_msg()["parent"]
            self._parent = connect(p_host, int(p_port), retries=int(timeout * 4))
            self._parent.send_msg({"child": rank})
            c.close()

        # Accept child connections in child-rank order.
        if self._kid_server is not None:
            by_rank: dict[int, Conn] = {}
            for _ in kid_ranks:
                conn = self._kid_server.accept(1, timeout=timeout)[0]
                hello = conn.recv_msg()
                by_rank[int(hello["child"])] = conn
            self._kids = [by_rank[r] for r in sorted(by_rank)]
        if fault_plan is not None:
            if self._parent is not None:
                self._parent = fault_plan.wrap(self._parent, fault_link)
            self._kids = [fault_plan.wrap(k, fault_link) for k in self._kids]
        self.set_op_timeout(op_timeout)

    def _links(self) -> list[Conn]:
        return ([self._parent] if self._parent else []) + self._kids

    # -- collectives ---------------------------------------------------------
    def _send_reduced(self, conn: Conn, leaves: list[np.ndarray], codec: str):
        """Ship a reduced leaf list one hop.  ``raw`` is the exact path;
        lossy codecs quantize per hop (no cross-round error carry — the
        residual the fused kernel produces is scratch here), through the
        fused encode-into-FrameBuffer kernels when built so steady state
        allocates nothing and the frame leaves as one iovec."""
        if codec == "raw":
            conn.send_tensors(leaves)
            return
        from distlearn_tpu.ops import wire_kernels
        if wire_kernels.wirek_enabled():
            from distlearn_tpu.comm import wire
            if self._codec_fb is None:
                self._codec_fb = wire.FrameBuffer()
            if (self._codec_scratch is None
                    or len(self._codec_scratch) != len(leaves)
                    or any(s.shape != a.shape or s.dtype != a.dtype
                           for s, a in zip(self._codec_scratch, leaves))):
                self._codec_scratch = [np.zeros(a.shape, a.dtype)
                                       for a in leaves]
            else:
                for s in self._codec_scratch:
                    s[...] = 0      # one-hop quantize: no residual carry
            payload = wire_kernels.encode_ef_into(
                leaves, self._codec_scratch, codec, out=self._codec_fb)
            conn.send_packed(payload)
        else:
            conn.send_tensors(leaves, codec=codec)

    def all_reduce_ex(self, value: PyTree, op: str = "sum",
                      contrib: bool = True, rider: int = 0,
                      codec: str = "raw") -> tuple[PyTree, int, int]:
        """:meth:`all_reduce` plus an out-of-band integer ``rider`` summed
        across ALL ranks regardless of ``contrib`` — carries round metadata
        (e.g. how many participants are in flush mode, the uneven-step
        protocol of distlearn_tpu.parallel.host_algorithms).

        ``codec``: wire codec per hop (``raw``/``fp16``/``int8``).  Float
        leaves quantize on every link they cross under a lossy codec —
        bandwidth for accuracy, the HybridBackend host-leg knob."""
        leaves = [np.ascontiguousarray(np.asarray(x))
                  for x in _jtu.tree_leaves(value)]
        if not contrib:
            acc = [np.full_like(x, _identity(x.dtype, op)) for x in leaves]
        else:
            acc = [x.copy() for x in leaves]
        n = 1 if contrib else 0
        r = int(rider)
        # Up phase: fold children into acc.
        for kid in self._kids:
            hdr = kid.recv_msg()
            n += int(hdr["n"])
            r += int(hdr["r"])
            # One packed frame per child per phase (recv_tensors also
            # accepts a legacy per-leaf stream, auto-detected).
            parts = kid.recv_tensors(n=len(acc))
            for a, part in zip(acc, parts):
                if part.dtype != a.dtype:
                    # One framework, one policy: the AsyncEA server evicts
                    # on dtype skew (parallel/async_ea.py _check_delta);
                    # silently astype-ing a child's f64/int payload into
                    # the accumulator here would hide the same config skew.
                    raise ValueError(
                        f"all_reduce dtype skew: child contributed "
                        f"{part.dtype} against local {a.dtype} — "
                        "rank model/config mismatch (all ranks must "
                        "reduce identical dtypes)")
                native.reduce_inplace(a, part, op)
        # Send to parent; receive final result down.
        if self._parent is not None:
            self._parent.send_msg({"n": n, "r": r})
            self._send_reduced(self._parent, acc, codec)
            down = self._parent.recv_msg()
            total, r_total = int(down["n"]), int(down["r"])
            final = self._parent.recv_tensors(out=acc)
        else:
            total, r_total, final = n, r, acc
        # Down phase: forward result to children.
        for kid in self._kids:
            kid.send_msg({"n": total, "r": r_total})
            self._send_reduced(kid, final, codec)
        treedef = _jtu.tree_structure(value)
        return _jtu.tree_unflatten(treedef, final), total, r_total

    def scatter(self, value: PyTree) -> PyTree:
        """Root's values broadcast to every rank (ref ``tree.scatter``,
        lua/AllReduceSGD.lua:52)."""
        # Receiving ranks fill fresh buffers — aliasing the caller's arrays
        # would silently overwrite its input (ADVICE r1).  Root copies so the
        # returned tree is detached from the caller's too.
        if self._parent is not None:
            leaves = self._parent.recv_tensors(
                out=[np.empty(a.shape, a.dtype)
                     for a in map(np.asarray, _jtu.tree_leaves(value))])
        else:
            leaves = [np.array(x, copy=True, order="C")
                      for x in _jtu.tree_leaves(value)]
        for kid in self._kids:
            kid.send_tensors(leaves)
        treedef = _jtu.tree_structure(value)
        return _jtu.tree_unflatten(treedef, leaves)

    def close(self):
        if self._parent:
            self._parent.close()
        for k in self._kids:
            k.close()
        if self._kid_server:
            self._kid_server.close()


def LocalhostTree(rank: int, num_nodes: int, port: int, base: int = 2,
                  **kwargs) -> Tree:
    """Single-host convenience (ref ``ipc.LocalhostTree(nodeIndex, numNodes)``,
    examples/mnist.lua:16).  All ranks must pass the same ``port``; extra
    kwargs (``timeout``, ``op_timeout``) forward to :class:`Tree`."""
    return Tree(rank, num_nodes, "127.0.0.1", port, base=base, **kwargs)


def tree_map_spawn(fn: Callable, n: int, *args, timeout: float = 120.0
                   ) -> list:
    """``ipc.map(n, fn, args...)`` parity (test/test_AllReduceSGD.lua:27):
    run ``fn(rank, *args)`` on ``n`` Python threads, join, return results
    in rank order.  (Threads, like the reference's fresh-Lua-state threads,
    share the process; the transport is real localhost TCP either way.)"""
    results: list = [None] * n
    errors: list = []

    def _run(i):
        try:
            results[i] = fn(i, *args)
        except Exception as e:  # noqa: BLE001 — surface in main thread
            errors.append((i, e))

    threads = [threading.Thread(target=_run, args=(i,), daemon=True)
               for i in range(n)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + timeout
    for t in threads:
        t.join(max(0.0, deadline - time.monotonic()))
    stuck = [i for i, t in enumerate(threads) if t.is_alive()]
    if errors:
        raise errors[0][1] if len(errors) == 1 else RuntimeError(
            "; ".join(f"rank {i}: {e!r}" for i, e in sorted(errors)))
    if stuck:
        raise TimeoutError(f"ranks {stuck} still running after {timeout}s")
    return results
