"""ctypes loader for the native transport core (src/comm/distcomm.cpp).

Mirrors how the reference keeps its hot communication path native (torch-ipc
C++) under a thin scripting binding.  The library is compiled on first use
with g++ (cached next to the package); if no toolchain is available the
transport transparently falls back to pure-Python socket IO.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

from distlearn_tpu.comm.errors import PeerClosed

_lib = None
_tried = False
_lock = threading.Lock()

_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "src", "comm", "distcomm.cpp")
_SO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_distcomm.so")


def _build() -> str | None:
    if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return _SO
    # Compile to a per-process temp path then atomically rename: concurrent
    # launchers (asyncEASGD.sh starts 4 processes at once) must never dlopen
    # a half-written .so.
    tmp = f"{_SO}.{os.getpid()}.tmp"
    try:
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", tmp],
            check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return _SO
    except (OSError, subprocess.SubprocessError):
        return None
    finally:
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass


def _load():
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if os.environ.get("DISTLEARN_TPU_NO_NATIVE"):
            return None
        path = _build()
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            return None
        lib.dc_send_frame.argtypes = [ctypes.c_int, ctypes.c_uint8,
                                      ctypes.c_char_p, ctypes.c_uint64]
        lib.dc_send_frame.restype = ctypes.c_int
        lib.dc_send_frame2.argtypes = [ctypes.c_int, ctypes.c_uint8,
                                       ctypes.c_char_p, ctypes.c_uint64,
                                       ctypes.c_void_p, ctypes.c_uint64]
        lib.dc_send_frame2.restype = ctypes.c_int
        lib.dc_recv_exact.argtypes = [ctypes.c_int, ctypes.c_void_p,
                                      ctypes.c_uint64]
        lib.dc_recv_exact.restype = ctypes.c_int
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


import errno as _errno

_TIMEOUT_ERRNOS = {_errno.EAGAIN, _errno.EWOULDBLOCK, _errno.ETIMEDOUT}


def _check_rc(rc: int, what: str) -> None:
    if rc == -1:
        raise PeerClosed("peer closed connection")
    if rc == -2:
        # FIN landed after partial progress: a torn frame, not a finished
        # peer — surfaced as the reset subclass so drop-policy code
        # (transport.Server.recv_any) treats it as abnormal
        raise ConnectionResetError("peer closed connection mid-frame")
    if rc != 0:
        if -rc in _TIMEOUT_ERRNOS:
            # SO_RCVTIMEO/SO_SNDTIMEO expired mid-operation (the per-handshake
            # timeout of the AsyncEA server) — distinct from a dead peer.
            raise TimeoutError(f"{what} timed out (socket timeout)")
        raise ConnectionError(f"{what} failed: {os.strerror(-rc)}")


def send_frame(fd: int, kind: int, payload) -> None:
    lib = _load()
    buf = payload if isinstance(payload, bytes) else bytes(payload)
    _check_rc(lib.dc_send_frame(fd, kind, buf, len(buf)), "dc_send_frame")


def send_tensor_frame(fd: int, kind: int, meta: bytes, arr: np.ndarray) -> None:
    """Zero-copy tensor send: meta (length-prefixed JSON header) from Python
    bytes, raw data straight from the numpy buffer — one writev in C++."""
    lib = _load()
    _check_rc(lib.dc_send_frame2(fd, kind, meta, len(meta),
                                 arr.ctypes.data, arr.nbytes),
              "dc_send_frame2")


def recv_exact(fd: int, buf: memoryview, n: int) -> None:
    if n == 0:
        return
    if n < 0 or n > buf.nbytes:
        raise ValueError(f"recv_exact: {n} bytes into a {buf.nbytes}-byte "
                         "buffer")
    lib = _load()
    addr = ctypes.addressof(ctypes.c_char.from_buffer(buf))
    _check_rc(lib.dc_recv_exact(fd, addr, n), "dc_recv_exact")


def reduce_inplace(dst: np.ndarray, src: np.ndarray, op: str = "sum") -> None:
    """Native elementwise reduction dst op= src (tree-reduce inner loop)."""
    lib = _load()
    opc = {"sum": 0, "max": 1, "min": 2}[op]
    fn = {
        np.dtype(np.float32): lib.dc_reduce_float,
        np.dtype(np.float64): lib.dc_reduce_double,
        np.dtype(np.int32): lib.dc_reduce_int32_t,
        np.dtype(np.int64): lib.dc_reduce_int64_t,
    }.get(dst.dtype)
    if fn is None or not (dst.flags.c_contiguous and src.flags.c_contiguous):
        if op == "sum":
            np.add(dst, src, out=dst)
        elif op == "max":
            np.maximum(dst, src, out=dst)
        else:
            np.minimum(dst, src, out=dst)
        return
    fn.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
                   ctypes.c_int]
    fn(dst.ctypes.data, src.ctypes.data, dst.size, opc)
