"""Deterministic network fault injection for the host transport.

A :class:`FaultPlan` owns a set of named LINKS.  Wrapping a
:class:`~distlearn_tpu.comm.transport.Conn` binds it to a link; every
byte the conn moves then passes through a :class:`_FaultSocket` proxy
that consults the link's state — so tests and the chaos scenario driver
(tools/chaos.py) can express one-way partitions, heals, per-direction
delay and bandwidth, mid-frame cuts, and flaky dials WITHOUT any hook in
the production code paths beyond ``Conn.force_py_io`` (the native C++
IO loops operate on the raw fd and would bypass the proxy).

Fault semantics, chosen so every injected failure maps onto an error
class the stack already survives (docs/HA.md):

* ``partition(link, "send")`` — **blackhole**: sends report success but
  no byte leaves.  The peer's recv then starves and its handshake
  timeout fires, exactly like a one-way network partition.  Pretending
  success (rather than blocking) keeps the sender's own thread alive —
  real one-way partitions don't stall the sender until the TCP window
  fills either.
* ``partition(link, "recv")`` — **hold**: reads park without consuming
  from the kernel buffer, so the byte stream is intact after ``heal``
  and the conn can resume mid-protocol.  A parked read honors the
  socket's effective timeout (``settimeout`` or SO_RCVTIMEO) and raises
  the same ``BlockingIOError``/``socket.timeout`` the kernel would, so
  ``Conn`` translates it into its normal :class:`TimeoutError`.
* ``cut_after(link, n)`` — allow ``n`` more sent bytes, then close the
  real socket and raise ``ConnectionResetError``: a deterministic
  mid-frame cut at an exact byte offset.
* ``delay`` / ``bandwidth`` — per-direction; the send direction rides
  the existing ``Conn.throttle_bps`` pacing machinery, the recv
  direction is paced in the proxy.
* ``fail_dials(link, k)`` / ``flaky_dials(link, p)`` — the next ``k``
  ``plan.connect`` dials on the link fail, or each dial fails with
  seeded probability ``p`` (``random.Random(seed)`` per link, so the
  SAME seed yields the SAME accept/refuse sequence — unit-testable
  determinism).  ``wrap_server`` applies the same budgets to accepts.

Every decision is appended to ``plan.log`` as a ``(link, event)`` pair;
two plans built from the same seed and driven through the same call
sequence produce identical logs (the determinism contract pinned by
tests/test_elastic.py).
"""

from __future__ import annotations

import random
import socket
import struct
import threading
import time
from typing import Any

from distlearn_tpu.comm import transport

__all__ = ["FaultPlan", "FaultInjected"]

#: poll period of a held (partitioned) read — coarse enough to be cheap,
#: fine enough that heal() unblocks promptly.
_POLL_S = 0.01


class FaultInjected(ConnectionError):
    """Raised for failures the plan injected (flaky dial, scheduled
    refuse) so tests can tell an injected fault from a real one."""


class _LinkState:
    """Shared fault state of one named link (all conns wrapped under the
    same name see the same state)."""

    def __init__(self, name: str, rng: random.Random):
        self.name = name
        self.rng = rng
        self.send_blocked = False
        self.recv_blocked = False
        self.send_delay_s = 0.0
        self.recv_delay_s = 0.0
        self.recv_bps: float | None = None
        self.cut_after: int | None = None     # sent bytes until the cut
        self.fail_dials = 0                    # scheduled dial failures
        self.flaky_p = 0.0                     # per-dial failure probability
        self.dropped_bytes = 0                 # blackholed send bytes


class _FaultSocket:
    """Socket proxy implementing exactly the surface the pure-Python
    ``Conn`` paths use (``sendmsg``/``recv_into``/``recv``/timeouts),
    consulting the link state before every syscall.  Everything else
    passes through to the real socket."""

    def __init__(self, sock: socket.socket, state: _LinkState,
                 lock: threading.Lock):
        self._sock = sock
        self._state = state
        self._lock = lock
        self._timeout: float | None = None    # effective recv timeout

    # -- plumbing -----------------------------------------------------------
    def __getattr__(self, name: str) -> Any:
        return getattr(self._sock, name)

    def fileno(self) -> int:
        return self._sock.fileno()

    def settimeout(self, t):
        self._timeout = t
        self._sock.settimeout(t)

    def gettimeout(self):
        return self._timeout

    def setblocking(self, flag: bool):
        self._timeout = None if flag else 0.0
        self._sock.setblocking(flag)

    def setsockopt(self, level, opt, value):
        # Learn the effective kernel recv timeout Conn.set_timeout packs
        # so a held read times out when the caller expects it to.
        if level == socket.SOL_SOCKET and opt == socket.SO_RCVTIMEO \
                and isinstance(value, (bytes, bytearray)):
            sec, usec = struct.unpack("ll", value)
            t = sec + usec / 1e6
            self._timeout = t if t > 0 else None
        return self._sock.setsockopt(level, opt, value)

    def close(self):
        return self._sock.close()

    # -- send direction -----------------------------------------------------
    def _pre_send(self, nbytes: int) -> int:
        """Returns how many of ``nbytes`` may actually leave; the link
        lock is NOT held across the syscall, only across the decision."""
        st = self._state
        with self._lock:
            delay = st.send_delay_s
            blocked = st.send_blocked
            cut = st.cut_after
        if delay:
            time.sleep(delay)
        if blocked:
            with self._lock:
                st.dropped_bytes += nbytes
            return 0
        if cut is not None:
            allowed = min(nbytes, cut)
            with self._lock:
                st.cut_after = max(0, cut - allowed)
            return allowed
        return nbytes

    def _post_cut(self):
        st = self._state
        with self._lock:
            tripped = st.cut_after is not None and st.cut_after <= 0
        if tripped:
            try:
                self._sock.close()
            except OSError:
                pass
            raise ConnectionResetError(
                f"fault injection: link {st.name!r} cut mid-stream")

    def sendmsg(self, bufs):
        total = sum(b.nbytes if isinstance(b, memoryview) else len(b)
                    for b in bufs)
        allowed = self._pre_send(total)
        if allowed == 0:
            return total            # blackhole: pretend the bytes left
        if allowed < total:
            # ship exactly the allowed prefix, then cut
            flat = b"".join(bytes(b) for b in bufs)[:allowed]
            self._sock.sendall(flat)
            self._post_cut()
            return allowed          # not reached: _post_cut raises
        sent = self._sock.sendmsg(bufs)
        self._post_cut()
        return sent

    def sendall(self, data):
        total = len(data)
        allowed = self._pre_send(total)
        if allowed == 0:
            return None
        self._sock.sendall(data[:allowed] if allowed < total else data)
        self._post_cut()
        return None

    def send(self, data):
        total = len(data)
        allowed = self._pre_send(total)
        if allowed == 0:
            return total
        sent = self._sock.send(data[:allowed] if allowed < total else data)
        self._post_cut()
        return sent

    # -- recv direction -----------------------------------------------------
    def _hold_recv(self):
        """Park while the recv direction is partitioned, honoring the
        effective timeout.  Returns when the link heals; raises the same
        error class the kernel timeout would."""
        st = self._state
        t0 = time.monotonic()
        while True:
            with self._lock:
                if not st.recv_blocked:
                    return
            if self._timeout is not None \
                    and time.monotonic() - t0 >= self._timeout:
                # BlockingIOError is what EVERY pure-Python Conn recv
                # path treats as a kernel timeout (SO_RCVTIMEO -> EAGAIN),
                # including the non-blocking serve drain
                raise BlockingIOError(
                    f"fault injection: link {st.name!r} recv partitioned")
            time.sleep(_POLL_S)

    def _pre_recv(self):
        st = self._state
        with self._lock:
            delay = st.recv_delay_s
        if delay:
            time.sleep(delay)
        self._hold_recv()

    def _pace_recv(self, nbytes: int, t0: float):
        bps = self._state.recv_bps
        if bps:
            left = nbytes / bps - (time.monotonic() - t0)
            if left > 0:
                time.sleep(left)

    def recv_into(self, buf, nbytes=0):
        self._pre_recv()
        t0 = time.monotonic()
        r = self._sock.recv_into(buf, nbytes)
        self._pace_recv(r, t0)
        return r

    def recv(self, bufsize, flags=0):
        self._pre_recv()
        t0 = time.monotonic()
        data = self._sock.recv(bufsize, flags)
        self._pace_recv(len(data), t0)
        return data


class FaultPlan:
    """A seeded, deterministic fault scenario over named links.

    Typical use (tests / tools/chaos.py)::

        plan = FaultPlan(seed=7)
        conn = plan.connect(host, port, link="c1")      # flaky-dial aware
        plan.wrap(conn, "c1")                           # byte-level faults
        plan.partition("c1", "send")                    # one-way blackhole
        ...
        plan.heal("c1")

    All mutators are thread-safe; wrapped conns see changes on their next
    IO operation.
    """

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._links: dict[str, _LinkState] = {}
        self._conns: dict[str, list[transport.Conn]] = {}
        self.log: list[tuple[str, str]] = []

    # -- link bookkeeping ---------------------------------------------------
    def _link(self, name: str) -> _LinkState:
        with self._lock:
            st = self._links.get(name)
            if st is None:
                # per-link RNG stream derived from (seed, name): decisions
                # on one link don't perturb another's sequence
                st = _LinkState(name, random.Random(f"{self.seed}:{name}"))
                self._links[name] = st
            return st

    def _note(self, link: str, event: str):
        with self._lock:
            self.log.append((link, event))

    # -- wrapping -----------------------------------------------------------
    def wrap(self, conn: transport.Conn, link: str) -> transport.Conn:
        """Bind ``conn`` to ``link``: force the pure-Python IO path and
        interpose the fault proxy over its socket.  Idempotent per conn."""
        st = self._link(link)
        if isinstance(conn.sock, _FaultSocket):
            return conn
        conn.force_py_io = True
        conn.sock = _FaultSocket(conn.sock, st, self._lock)
        with self._lock:
            self._conns.setdefault(link, []).append(conn)
        self._note(link, "wrap")
        return conn

    def wrap_server(self, server: transport.Server, link: str
                    ) -> transport.Server:
        """Make ``server.accept`` flaky-accept aware: each accepted conn
        consumes the link's dial budgets; a conn the plan refuses is
        closed immediately (the peer sees a reset after connect — the
        'flaky accept' failure mode) and does not count toward ``n``.
        Surviving conns are wrapped onto ``link``."""
        st = self._link(link)
        plan = self
        real_accept = server.accept

        def accept(n: int = 1, timeout: float | None = None):
            out: list[transport.Conn] = []
            deadline = None if timeout is None else time.monotonic() + timeout
            while len(out) < n:
                left = (None if deadline is None
                        else max(0.0, deadline - time.monotonic()))
                got = real_accept(n - len(out), left)
                for c in got:
                    if plan._take_dial_failure(st):
                        plan._note(link, "accept_refused")
                        c.close()
                        server.conns.remove(c)
                        continue
                    plan._note(link, "accept")
                    out.append(plan.wrap(c, link))
            return out

        server.accept = accept  # type: ignore[method-assign]
        return server

    # -- dials --------------------------------------------------------------
    def _take_dial_failure(self, st: _LinkState) -> bool:
        with self._lock:
            if st.fail_dials > 0:
                st.fail_dials -= 1
                return True
            if st.flaky_p > 0.0:
                return st.rng.random() < st.flaky_p
        return False

    def connect(self, host: str, port: int, link: str = "default",
                **kw) -> transport.Conn:
        """``transport.connect`` behind the link's dial budgets: a
        scheduled or flaky failure raises :class:`FaultInjected` without
        touching the network; a surviving dial is wrapped onto the
        link."""
        st = self._link(link)
        if self._take_dial_failure(st):
            self._note(link, "dial_refused")
            raise FaultInjected(
                f"fault injection: dial on link {link!r} refused")
        self._note(link, "dial")
        return self.wrap(transport.connect(host, port, **kw), link)

    # -- fault mutators -----------------------------------------------------
    def partition(self, link: str, direction: str = "both"):
        """One-way (or two-way) partition: ``"send"`` blackholes the
        wrapped side's sends, ``"recv"`` holds its reads (stream intact
        for :meth:`heal`)."""
        st = self._link(link)
        with self._lock:
            if direction in ("send", "both"):
                st.send_blocked = True
            if direction in ("recv", "both"):
                st.recv_blocked = True
        self._note(link, f"partition:{direction}")

    def heal(self, link: str):
        """Lift every partition/delay/bandwidth fault on the link (cuts
        are not healable — the socket is gone)."""
        st = self._link(link)
        with self._lock:
            st.send_blocked = st.recv_blocked = False
            st.send_delay_s = st.recv_delay_s = 0.0
            st.recv_bps = None
            conns = list(self._conns.get(link, []))
        for c in conns:
            c.throttle_bps = None
        self._note(link, "heal")

    def delay(self, link: str, seconds: float, direction: str = "both"):
        st = self._link(link)
        with self._lock:
            if direction in ("send", "both"):
                st.send_delay_s = float(seconds)
            if direction in ("recv", "both"):
                st.recv_delay_s = float(seconds)
        self._note(link, f"delay:{direction}:{seconds}")

    def bandwidth(self, link: str, bps: float, direction: str = "both"):
        """Pace the link to ``bps`` bytes/second.  The send direction
        rides ``Conn.throttle_bps`` (the machinery docs/EA_CONVERGENCE.md
        benches with); the recv direction is paced in the proxy."""
        st = self._link(link)
        if direction in ("send", "both"):
            with self._lock:
                conns = list(self._conns.get(link, []))
            for c in conns:
                c.throttle_bps = float(bps)
        if direction in ("recv", "both"):
            with self._lock:
                st.recv_bps = float(bps)
        self._note(link, f"bandwidth:{direction}:{bps}")

    def cut_after(self, link: str, nbytes: int):
        """Deterministic mid-stream cut: the link's sends deliver exactly
        ``nbytes`` more bytes, then the socket closes and the sender sees
        ``ConnectionResetError`` — a frame torn at a known offset."""
        st = self._link(link)
        with self._lock:
            st.cut_after = int(nbytes)
        self._note(link, f"cut_after:{nbytes}")

    def fail_dials(self, link: str, k: int):
        """Schedule the next ``k`` dials/accepts on the link to fail."""
        st = self._link(link)
        with self._lock:
            st.fail_dials += int(k)
        self._note(link, f"fail_dials:{k}")

    def flaky_dials(self, link: str, p: float):
        """Each subsequent dial/accept fails with probability ``p``,
        drawn from the link's seeded RNG stream."""
        st = self._link(link)
        with self._lock:
            st.flaky_p = float(p)
        self._note(link, f"flaky_dials:{p}")

    # -- introspection ------------------------------------------------------
    def dropped_bytes(self, link: str) -> int:
        """Bytes blackholed on the link's send direction so far."""
        return self._link(link).dropped_bytes

    def decisions(self) -> list[tuple[str, str]]:
        """The ordered decision/audit log — two same-seed plans driven
        through the same call sequence produce identical lists."""
        with self._lock:
            return list(self.log)
