"""Topology-aware collective backends — ONE sync API over host TCP and
device SPMD.

The reference framework is an L1/L2 split: thin sync algorithms
(lua/AllReduceSGD.lua, lua/AllReduceEA.lua) over a swappable native
transport — torch-ipc's ``tree`` handle — and the algorithms never see a
socket.  This module rebuilds that split for the TPU port, where "node"
can mean an OS process on DCN (``comm.tree.Tree`` / ``comm.ring.Ring``)
*or* a device on an ICI mesh (``parallel.mesh.MeshTree``) — or BOTH at
once, a pod slice of L devices behind one host NIC.

:class:`CollectiveBackend` is the protocol (``all_reduce`` /
``all_reduce_ex`` / ``scatter`` / ``barrier`` / ``node_index`` /
``num_nodes`` / ``close``); three implementations ship:

* :class:`HostBackend` — behavior-preserving adapter over an existing
  TCP :class:`~distlearn_tpu.comm.tree.Tree` or
  :class:`~distlearn_tpu.comm.ring.Ring` handle (one logical node per
  OS process, plain per-node pytrees on the wire).
* :class:`MeshBackend` — the collective as a jitted ``shard_map``
  ``psum`` over the device mesh; values are *stacked node arrays*
  (leading ``num_nodes`` axis, one row per device), extending
  :class:`~distlearn_tpu.parallel.mesh.MeshTree` with the protocol
  extras (``all_reduce_ex`` riders, ``barrier``, ``close``).
* :class:`HybridBackend` — the hierarchical allreduce: in-mesh
  ``psum_scatter`` leaves each local device holding a distinct
  shard-sum, the shards D2H-stage into ONE
  :class:`~distlearn_tpu.comm.wire.FrameBuffer`-backed flat vector
  (``ops.staging``), ONE host TCP leg per host reduces that vector
  across hosts (``Conn.send_packed`` single-iovec frames, optional
  fused int8/fp16 codec), and an in-mesh ``all_gather`` fans the
  result back over the slice.  Host-leg bytes per host drop by the
  local device count L versus running L per-device TCP ranks — the
  classic hierarchical-allreduce bandwidth win (measured:
  bench.py ``host_sync_bench``, docs/PERF.md).

Value conventions (``stacked_nodes`` tells callers which one a backend
speaks):

* ``stacked_nodes is None`` — plain per-node pytrees, one logical node
  per handle (HostBackend; the reference's process-per-node shape).
* ``stacked_nodes == k`` — every leaf carries a leading ``[k]`` node
  axis; the handle drives logical nodes ``node_offset ..
  node_offset+k-1``.  After ``all_reduce`` every row holds the global
  reduction (the in-place torch semantics, per row).

The shared TCP-collective plumbing (``walk`` / ``node_index`` /
``set_op_timeout`` / ``barrier`` / reduction identities) that
``comm/tree.py`` and ``comm/ring.py`` used to copy-paste lives here as
:class:`HostCollectiveBase`, so the adapter wraps a single surface.
This module imports neither jax nor the concrete transports at module
scope — host-only deployments can build a :class:`HostBackend` without
touching jax, and tree/ring import the base from here without a cycle.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Protocol, Sequence, runtime_checkable

import numpy as np

try:  # pytree walking without importing all of jax at module import
    import jax.tree_util as _jtu
except Exception:  # pragma: no cover
    _jtu = None

from distlearn_tpu import obs

PyTree = Any


def _identity(dtype: np.dtype, op: str):
    """Reduction identity for a non-contributing rank's slot."""
    if op == "sum":
        return 0
    if op == "max":
        return -np.inf if np.issubdtype(dtype, np.floating) \
            else np.iinfo(dtype).min
    if op == "min":
        return np.inf if np.issubdtype(dtype, np.floating) \
            else np.iinfo(dtype).max
    raise ValueError(f"unknown op {op!r}")


# ---------------------------------------------------------------------------
# Telemetry (docs/OBSERVABILITY.md "sync" catalog): one family each,
# labelled by backend, shared by every handle in the process.
# ---------------------------------------------------------------------------

def _sync_rounds():
    return obs.counter("sync_rounds_total",
                       "collective rounds completed, by backend",
                       labels=("backend",))


def _sync_host_bytes():
    return obs.counter("sync_host_leg_bytes_total",
                       "TCP bytes this handle moved during collective "
                       "rounds (NIC in+out), by backend",
                       labels=("backend",))


def _sync_logical_bytes():
    return obs.counter("sync_logical_bytes_total",
                       "logical payload bytes reduced per round, "
                       "by backend", labels=("backend",))


def _sync_seconds():
    return obs.histogram("sync_seconds",
                         "one collective round wall time, by backend",
                         labels=("backend",))


# ---------------------------------------------------------------------------
# Shared host-collective base (the tree/ring dedup target)
# ---------------------------------------------------------------------------

class HostCollectiveBase:
    """Everything a TCP collective handle shares regardless of topology.

    Subclasses (:class:`~distlearn_tpu.comm.tree.Tree`,
    :class:`~distlearn_tpu.comm.ring.Ring`) provide ``rank``,
    ``num_nodes``, ``_links()`` (their live data-plane conns) and
    ``all_reduce_ex``; the walkTable parity, op-timeout arming, NIC
    accounting, and the ``all_reduce``/``barrier`` derivations live
    here once.
    """

    rank: int
    num_nodes: int

    def _links(self) -> list:
        """Live data-plane conns of this handle (subclass hook)."""
        raise NotImplementedError

    # -- walkTable parity ---------------------------------------------------
    @staticmethod
    def walk(tree: PyTree, fn: Callable) -> PyTree:
        return _jtu.tree_map(fn, tree)

    @property
    def node_index(self) -> int:
        return self.rank

    def set_op_timeout(self, seconds: float | None):
        """(Re)arm failure detection on every live link: any collective
        that waits longer than this many seconds on one peer raises
        :class:`TimeoutError` instead of wedging the job (the reference
        blocks forever — SURVEY.md §5).  ``None`` restores the
        reference's block-forever semantics."""
        self.op_timeout = seconds
        for conn in self._links():
            conn.set_timeout(seconds)

    def nic_bytes(self) -> int:
        """Total TCP payload bytes this handle has moved (in + out over
        every live link) — the per-NIC traffic number the bench and the
        ``sync_*`` metrics report (docs/PERF.md)."""
        return sum(c.bytes_sent + c.bytes_received for c in self._links())

    # -- derived collectives ------------------------------------------------
    def all_reduce(self, value: PyTree, op: str = "sum",
                   contrib: bool = True) -> tuple[PyTree, int]:
        """Allreduce; returns ``(reduced, n_contributors)``.

        ``contrib=False`` reproduces the reference's zero-contribution
        flush (lua/AllReduceSGD.lua:37): this rank's values count as the
        reduction identity and it is excluded from ``n`` — but it still
        serves the reduction for the rest of the topology, which is how
        stopped nodes keep stragglers' reductions alive.  ``None`` means
        "contributes" (the protocol-wide default, matching the mesh
        backends' all-contribute convention).
        """
        reduced, n, _ = self.all_reduce_ex(
            value, op=op, contrib=(True if contrib is None else contrib))
        return reduced, n

    def all_reduce_ex(self, value: PyTree, op: str = "sum",
                      contrib: bool = True, rider: int = 0
                      ) -> tuple[PyTree, int, int]:
        raise NotImplementedError

    def barrier(self):
        """All ranks rendezvous (reduce of a scalar)."""
        self.all_reduce(np.zeros((), np.int32))


# ---------------------------------------------------------------------------
# The protocol
# ---------------------------------------------------------------------------

@runtime_checkable
class CollectiveBackend(Protocol):
    """What a sync algorithm (:class:`~distlearn_tpu.parallel.
    allreduce_sgd.AllReduceSGD`, :class:`~distlearn_tpu.parallel.
    allreduce_ea.AllReduceEA`, the host algorithms, the AsyncEA client's
    slice reduction) may assume about its transport — the torch-ipc
    ``tree`` handle surface, topology-neutral.

    ``num_nodes`` counts LOGICAL nodes; ``stacked_nodes``/``node_offset``
    say how many of them this handle drives and which (module
    docstring).  ``rider`` in :meth:`all_reduce_ex` is an out-of-band
    integer summed **per logical node** across the whole topology — a
    handle driving k nodes contributes ``rider * k`` — carrying round
    metadata for the uneven-step flush protocol
    (distlearn_tpu.parallel.host_algorithms).
    """

    num_nodes: int
    stacked_nodes: int | None
    node_offset: int

    @property
    def node_index(self) -> int: ...

    def all_reduce(self, value: PyTree, op: str = "sum",
                   contrib=True) -> tuple[PyTree, int]: ...

    def all_reduce_ex(self, value: PyTree, op: str = "sum",
                      contrib=True, rider: int = 0
                      ) -> tuple[PyTree, int, int]: ...

    def scatter(self, value: PyTree, src: int = 0) -> PyTree: ...

    def barrier(self) -> None: ...

    def set_op_timeout(self, seconds: float | None) -> None: ...

    def close(self) -> None: ...


# ---------------------------------------------------------------------------
# HostBackend — adapter over Tree / Ring
# ---------------------------------------------------------------------------

class HostBackend:
    """Behavior-preserving adapter over a TCP :class:`Tree` or
    :class:`Ring` handle: one logical node per process, plain per-node
    pytrees, every collective delegating to the wrapped handle — the
    existing ctors and semantics (op_timeout, fault injection, dtype
    skew errors) survive unchanged, the algorithms just stop naming the
    concrete class.

    The one protocol method the raw handles lack is ``scatter(value,
    src != 0)`` (torch-ipc scatter is root-broadcast only): it is
    derived as a masked allreduce — ``src`` contributes its values,
    everyone else the additive identity — the same bitwise-exact winner
    broadcast the reference's ``synchronizeParameters`` performs
    (lua/AllReduceSGD.lua:44-50).
    """

    stacked_nodes: int | None = None

    def __init__(self, handle: HostCollectiveBase):
        self.handle = handle
        self.num_nodes = handle.num_nodes
        self.node_offset = handle.rank
        self._c_rounds = _sync_rounds()
        self._c_bytes = _sync_host_bytes()
        self._c_logical = _sync_logical_bytes()
        self._h_secs = _sync_seconds()

    @classmethod
    def create(cls, rank: int, num_nodes: int, host: str, port: int,
               transport: str = "tree", **kw) -> "HostBackend":
        """Build the underlying handle too (lazy imports keep this
        module transport-agnostic).  ``transport``: ``"tree"`` (extra
        kwarg ``base``) or ``"ring"``; remaining kwargs forward to the
        handle ctor (``timeout``, ``op_timeout``, ``listen_host``,
        ``advertise_host``, ``fault_plan`` ...)."""
        if transport == "tree":
            from distlearn_tpu.comm.tree import Tree
            return cls(Tree(rank, num_nodes, host, port, **kw))
        if transport == "ring":
            from distlearn_tpu.comm.ring import Ring
            return cls(Ring(rank, num_nodes, host, port, **kw))
        raise ValueError(f"unknown host transport {transport!r} "
                         "(supported: tree, ring)")

    # -- protocol -----------------------------------------------------------
    @property
    def node_index(self) -> int:
        return self.handle.node_index

    @staticmethod
    def walk(tree: PyTree, fn: Callable) -> PyTree:
        return _jtu.tree_map(fn, tree)

    def all_reduce(self, value: PyTree, op: str = "sum",
                   contrib: bool = True) -> tuple[PyTree, int]:
        reduced, n, _ = self.all_reduce_ex(value, op=op, contrib=contrib)
        return reduced, n

    def all_reduce_ex(self, value: PyTree, op: str = "sum",
                      contrib: bool = True, rider: int = 0
                      ) -> tuple[PyTree, int, int]:
        contrib = True if contrib is None else bool(contrib)
        t0 = time.perf_counter()
        b0 = self.handle.nic_bytes()
        out = self.handle.all_reduce_ex(value, op=op, contrib=contrib,
                                        rider=rider)
        self._c_rounds.labels(backend="host").inc()
        self._c_bytes.labels(backend="host").inc(
            self.handle.nic_bytes() - b0)
        self._c_logical.labels(backend="host").inc(
            sum(np.asarray(x).nbytes for x in _jtu.tree_leaves(value)))
        self._h_secs.labels(backend="host").observe(
            time.perf_counter() - t0)
        return out

    def scatter(self, value: PyTree, src: int = 0) -> PyTree:
        if src == 0:
            return self.handle.scatter(value)
        if not 0 <= src < self.num_nodes:
            raise ValueError(
                f"src={src} out of range for {self.num_nodes} nodes")
        mine = value if self.handle.rank == src else _jtu.tree_map(
            lambda x: np.zeros_like(np.asarray(x)), value)
        out, _ = self.handle.all_reduce(mine, contrib=(
            self.handle.rank == src))
        return out

    def barrier(self):
        self.handle.barrier()

    def set_op_timeout(self, seconds: float | None):
        self.handle.set_op_timeout(seconds)

    def close(self):
        self.handle.close()


# ---------------------------------------------------------------------------
# MeshBackend — the collective as a jitted shard_map psum
# ---------------------------------------------------------------------------

class MeshBackend:
    """Device-mesh implementation of the protocol: one process drives
    ALL ``num_nodes`` logical nodes as devices of a
    :class:`~distlearn_tpu.parallel.mesh.MeshTree`; values are stacked
    node arrays and every collective is a cached jitted ``shard_map``
    over ICI (the multi-process pjit idiom).  Only ``op="sum"`` lowers
    to a psum; max/min control-plane reductions stay on the host
    backends.

    ``barrier``/``close``/``set_op_timeout`` are no-ops: a single
    gang-scheduled XLA program has nothing to rendezvous or tear down,
    and there is no socket to time out — kept so algorithm code is
    backend-oblivious.
    """

    def __init__(self, num_nodes: int | None = None,
                 devices: Sequence | None = None,
                 axis_name: str = "data",
                 mesh_tree=None):
        from distlearn_tpu.parallel.mesh import MeshTree
        self.mesh_tree = mesh_tree if mesh_tree is not None else MeshTree(
            num_nodes=num_nodes, devices=devices, axis_name=axis_name)
        self.num_nodes = self.mesh_tree.num_nodes
        self.stacked_nodes: int | None = self.num_nodes
        self.node_offset = 0
        self.axis_name = self.mesh_tree.axis_name
        self.mesh = self.mesh_tree.mesh
        self.op_timeout: float | None = None
        self._c_rounds = _sync_rounds()
        self._c_logical = _sync_logical_bytes()
        self._h_secs = _sync_seconds()

    # -- MeshTree passthrough (so AllReduceEA's fused spmd path and the
    # trainers keep working against a MeshBackend) --------------------------
    @property
    def node_sharding(self):
        return self.mesh_tree.node_sharding

    def node_spec(self):
        return self.mesh_tree.node_spec()

    def spmd(self, fn, in_specs, out_specs, static_argnums=()):
        return self.mesh_tree.spmd(fn, in_specs, out_specs,
                                   static_argnums=static_argnums)

    def put_per_node(self, tree: PyTree) -> PyTree:
        return self.mesh_tree.put_per_node(tree)

    def replicate(self, tree: PyTree) -> PyTree:
        return self.mesh_tree.replicate(tree)

    def node_slice(self, tree: PyTree, i: int) -> PyTree:
        return self.mesh_tree.node_slice(tree, i)

    # -- protocol -----------------------------------------------------------
    @property
    def node_index(self) -> int:
        """First logical node this handle drives (it drives them all)."""
        return 0

    @staticmethod
    def walk(tree: PyTree, fn: Callable) -> PyTree:
        return _jtu.tree_map(fn, tree)

    def _contrib_vec(self, contrib):
        """Normalize the protocol's ``contrib`` (bool | per-node vector |
        None) onto MeshTree's per-node mask vector (or None = all)."""
        if contrib is None or contrib is True:
            return None
        if contrib is False:
            return np.zeros(self.num_nodes, np.int32)
        return np.asarray(contrib)

    def all_reduce(self, value: PyTree, op: str = "sum",
                   contrib=True) -> tuple[PyTree, int]:
        if op != "sum":
            raise NotImplementedError(
                f"MeshBackend lowers only op='sum' to a psum (got {op!r});"
                " use a host backend for control-plane max/min")
        t0 = time.perf_counter()
        out, n = self.mesh_tree.all_reduce(
            value, contrib=self._contrib_vec(contrib))
        self._c_rounds.labels(backend="mesh").inc()
        self._c_logical.labels(backend="mesh").inc(
            sum(int(np.prod(x.shape[1:], dtype=np.int64))
                * np.dtype(x.dtype).itemsize
                for x in _jtu.tree_leaves(value)))
        self._h_secs.labels(backend="mesh").observe(
            time.perf_counter() - t0)
        return out, int(n)

    def all_reduce_ex(self, value: PyTree, op: str = "sum",
                      contrib=True, rider: int = 0
                      ) -> tuple[PyTree, int, int]:
        """Rider is per logical node: one whole-mesh handle contributes
        ``rider`` for each of its ``num_nodes`` rows (so a draining mesh
        reports every node flushing, matching ``n_flush == num_nodes``
        checks in the host algorithms)."""
        out, n = self.all_reduce(value, op=op, contrib=contrib)
        return out, n, int(rider) * self.num_nodes

    def scatter(self, value: PyTree, src: int = 0) -> PyTree:
        return self.mesh_tree.scatter(value, src=src)

    def barrier(self):
        pass

    def set_op_timeout(self, seconds: float | None):
        self.op_timeout = seconds

    def close(self):
        pass


# ---------------------------------------------------------------------------
# HybridBackend — in-mesh reduce-scatter + one host TCP leg per host
# ---------------------------------------------------------------------------

def plan_chunks(total: int, parts: int) -> tuple[int, list[tuple[int, int]]]:
    """Even flat-element chunking for the hybrid reduce-scatter: pad
    ``total`` elements up to a multiple of ``parts`` and return
    ``(padded_total, [(lo, hi), ...])`` — ``parts`` equal half-open
    ranges.  ``psum_scatter`` requires equal shards; the pad is zeros
    and never leaves the device side."""
    if parts < 1:
        raise ValueError("parts must be >= 1")
    if total < 0:
        raise ValueError("total must be >= 0")
    pad = (-total) % parts
    padded = total + pad
    per = padded // parts
    return padded, [(i * per, (i + 1) * per) for i in range(parts)]


class HybridBackend:
    """Hierarchical allreduce: L local device-nodes behind ONE host TCP
    rank (the "client is a whole pod slice" deployment, ROADMAP item 1).

    ``all_reduce`` runs three phases:

    1. **In-mesh reduce-scatter** — one jitted ``shard_map``: each leaf's
       local rows flatten + concatenate per dtype group, and
       ``lax.psum_scatter`` leaves device ``i`` holding the local sum of
       chunk ``i`` (:func:`plan_chunks` bounds).
    2. **One host TCP leg over only its shard-sums** — the per-device
       shards D2H-stage straight into a reusable
       :class:`~distlearn_tpu.comm.wire.FrameBuffer`
       (:func:`distlearn_tpu.ops.staging.stage_into`), and the wrapped
       :class:`Tree`/:class:`Ring` reduces that ONE flat vector across
       hosts — ``Conn.send_packed`` single-iovec frames, optionally
       through the fused int8/fp16 codec kernels (``codec=``).  Per-host
       host-leg traffic is ~1 payload instead of the L payloads that L
       per-device TCP ranks would move.
    3. **In-mesh all-gather** — the reduced vector H2D-shards back one
       chunk per device and a jitted ``all_gather`` leaves every row of
       the stacked result holding the global sum.

    Values are stacked node arrays with leading axis
    ``stacked_nodes == L`` (this host's slice); ``num_nodes = H * L``.
    Lossless by default (``codec="raw"`` — the host leg moves exact
    dtypes); int8/fp16 quantize per hop with no cross-round error
    feedback, the same tradeoff as the AsyncEA wire codecs.

    ``num_hosts=1`` skips the TCP leg but keeps the reduce-scatter /
    all-gather pair (the degenerate single-host case — also what the
    ``sync`` lint family compiles and budgets).  ``op_timeout`` and
    fault injection (``fault_plan``) forward to the host leg, so a
    partition mid-collective surfaces the same typed error as the raw
    tree path (tests/test_backend.py).
    """

    def __init__(self, rank: int = 0, num_hosts: int = 1,
                 host: str | None = None, port: int | None = None, *,
                 devices: Sequence | None = None, num_devices: int | None = None,
                 axis_name: str = "data", transport: str = "tree",
                 base: int = 2, timeout: float = 60.0,
                 listen_host: str | None = None,
                 advertise_host: str | None = None,
                 op_timeout: float | None = None,
                 codec: str = "raw",
                 fault_plan=None, fault_link: str = "hybrid"):
        from distlearn_tpu.comm import wire
        from distlearn_tpu.parallel.mesh import MeshTree
        if not 0 <= rank < num_hosts:
            raise ValueError(f"rank {rank} out of range for {num_hosts} hosts")
        if codec not in wire.CODECS:
            raise ValueError(f"unknown wire codec {codec!r} "
                             f"(supported: {', '.join(wire.CODECS)})")
        self.mesh_tree = MeshTree(num_nodes=num_devices, devices=devices,
                                  axis_name=axis_name)
        self.rank = rank
        self.num_hosts = int(num_hosts)
        self.local_nodes = self.mesh_tree.num_nodes
        self.stacked_nodes: int | None = self.local_nodes
        self.num_nodes = self.num_hosts * self.local_nodes
        self.node_offset = rank * self.local_nodes
        self.axis_name = self.mesh_tree.axis_name
        self.codec = codec
        self._fb = wire.FrameBuffer()
        self._jit_cache: dict = {}
        self.host_leg = None
        if num_hosts > 1:
            if host is None or port is None:
                raise ValueError(
                    "num_hosts > 1 needs the coordinator host/port")
            if transport == "tree":
                from distlearn_tpu.comm.tree import Tree
                self.host_leg = Tree(
                    rank, num_hosts, host, port, base=base, timeout=timeout,
                    listen_host=listen_host, advertise_host=advertise_host,
                    op_timeout=op_timeout, fault_plan=fault_plan,
                    fault_link=fault_link)
            elif transport == "ring":
                from distlearn_tpu.comm.ring import Ring
                if codec != "raw":
                    raise ValueError(
                        "ring host leg is raw-only (chunked per-tensor "
                        "frames have nowhere to carry a scale)")
                self.host_leg = Ring(
                    rank, num_hosts, host, port, timeout=timeout,
                    listen_host=listen_host, advertise_host=advertise_host,
                    op_timeout=op_timeout, fault_plan=fault_plan,
                    fault_link=fault_link)
            else:
                raise ValueError(f"unknown host transport {transport!r}")
        self.op_timeout = op_timeout
        self._c_rounds = _sync_rounds()
        self._c_bytes = _sync_host_bytes()
        self._c_logical = _sync_logical_bytes()
        self._h_secs = _sync_seconds()

    # -- protocol surface ---------------------------------------------------
    @property
    def node_index(self) -> int:
        """First logical node of this host's slice."""
        return self.node_offset

    @staticmethod
    def walk(tree: PyTree, fn: Callable) -> PyTree:
        return _jtu.tree_map(fn, tree)

    def set_op_timeout(self, seconds: float | None):
        self.op_timeout = seconds
        if self.host_leg is not None:
            self.host_leg.set_op_timeout(seconds)

    def barrier(self):
        if self.host_leg is not None:
            self.host_leg.barrier()

    def close(self):
        if self.host_leg is not None:
            self.host_leg.close()

    # -- data movement parity ----------------------------------------------
    def put_per_node(self, tree: PyTree) -> PyTree:
        """Place this host's slice (leading axis == local_nodes)."""
        return self.mesh_tree.put_per_node(tree)

    def replicate(self, tree: PyTree) -> PyTree:
        return self.mesh_tree.replicate(tree)

    def node_slice(self, tree: PyTree, i: int) -> PyTree:
        """Local row ``i`` (0-based within this host's slice)."""
        return self.mesh_tree.node_slice(tree, i)

    # -- the hierarchical allreduce ----------------------------------------
    def _plan(self, value: PyTree):
        """Static layout for one stacked pytree: per-dtype leaf groups,
        flat sizes, chunk bounds — the jit cache key."""
        import jax
        leaves, treedef = jax.tree_util.tree_flatten(value)
        shapes, dtypes, sizes = [], [], []
        for x in leaves:
            shape = tuple(x.shape)
            if len(shape) < 1 or shape[0] != self.local_nodes:
                raise ValueError(
                    f"hybrid values are stacked node arrays: leaf shape "
                    f"{shape} does not lead with local_nodes="
                    f"{self.local_nodes}")
            shapes.append(shape)
            dtypes.append(np.dtype(x.dtype))
            sizes.append(int(np.prod(shape[1:], dtype=np.int64)))
        groups: dict[np.dtype, list[int]] = {}
        for i, dt in enumerate(dtypes):
            groups.setdefault(dt, []).append(i)
        gplans = []
        for dt, idxs in sorted(groups.items(), key=lambda kv: kv[0].name):
            total = sum(sizes[i] for i in idxs)
            padded, chunks = plan_chunks(total, self.local_nodes)
            gplans.append((dt, tuple(idxs), total, padded, chunks))
        key = (treedef, tuple(shapes), tuple(dt.name for dt in dtypes))
        return key, treedef, shapes, dtypes, sizes, gplans

    def _programs(self, key, treedef, shapes, dtypes, sizes, gplans):
        """The jitted reduce-scatter and all-gather shard_maps for one
        layout (cached; steady state compiles once per pytree shape)."""
        if key in self._jit_cache:
            return self._jit_cache[key]
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import PartitionSpec as P
        axis = self.axis_name
        L = self.local_nodes

        def _rs(t, c):
            # per-device view: leaves [1, *shape], contrib row [1]
            leaves = jax.tree_util.tree_leaves(t)
            cr = jnp.squeeze(c, 0)
            outs = []
            for dt, idxs, total, padded, _chunks in gplans:
                flats = [jnp.reshape(leaves[i] * cr.astype(leaves[i].dtype),
                                     (-1,)) for i in idxs]
                if padded > total:
                    flats.append(jnp.zeros((padded - total,), dt))
                flat = flats[0] if len(flats) == 1 else jnp.concatenate(flats)
                # device i ends holding sum-over-local-rows of chunk i
                outs.append(lax.psum_scatter(flat, axis,
                                             scatter_dimension=0,
                                             tiled=True))
            n = lax.psum(cr.astype(jnp.int32), axis)
            return tuple(outs), n[None]

        rs = jax.jit(self.mesh_tree.spmd(
            _rs,
            in_specs=(P(axis), P(axis)),
            out_specs=(tuple(P(axis) for _ in gplans), P(axis))))

        def _ag(*gflats):
            # per-device view: one [padded // L] chunk per dtype group
            full = {}
            for (dt, idxs, total, padded, _chunks), chunk in zip(gplans,
                                                                 gflats):
                full[dt.name] = lax.all_gather(chunk, axis, tiled=True)
            out, off = [None] * len(shapes), {}
            for dt, idxs, total, padded, _chunks in gplans:
                o = 0
                for i in idxs:
                    piece = lax.dynamic_slice_in_dim(full[dt.name], o,
                                                     sizes[i], 0)
                    out[i] = jnp.reshape(piece, (1,) + shapes[i][1:])
                    o += sizes[i]
            return jax.tree_util.tree_unflatten(treedef, out)

        ag = jax.jit(self.mesh_tree.spmd(
            _ag,
            in_specs=tuple(P(axis) for _ in gplans),
            out_specs=P(axis)))
        self._jit_cache[key] = (rs, ag)
        return rs, ag

    def all_reduce(self, value: PyTree, op: str = "sum",
                   contrib=True) -> tuple[PyTree, int]:
        reduced, n, _ = self.all_reduce_ex(value, op=op, contrib=contrib)
        return reduced, n

    def all_reduce_ex(self, value: PyTree, op: str = "sum",
                      contrib=True, rider: int = 0
                      ) -> tuple[PyTree, int, int]:
        """Hierarchical allreduce of a stacked slice; ``contrib`` is a
        bool for the whole slice or a per-local-row mask ``[L]``; the
        contributor count and rider cross the host leg as extra int64
        leaves of the SAME reduction, so the count stays exact without a
        second round trip."""
        import jax
        from distlearn_tpu.ops import staging
        if op != "sum":
            raise NotImplementedError(
                f"HybridBackend reduces op='sum' only (got {op!r}); use a "
                "host backend for control-plane max/min")
        t0 = time.perf_counter()
        key, treedef, shapes, dtypes, sizes, gplans = self._plan(value)
        rs, ag = self._programs(key, treedef, shapes, dtypes, sizes, gplans)
        if contrib is True or contrib is None:
            cvec = np.ones(self.local_nodes, np.int32)
        elif contrib is False:
            cvec = np.zeros(self.local_nodes, np.int32)
        else:
            cvec = np.asarray(contrib, np.int32)
            if cvec.shape != (self.local_nodes,):
                raise ValueError(
                    f"contrib mask shape {cvec.shape} != "
                    f"({self.local_nodes},)")
        shard_sums, n_local = rs(value, cvec)
        n_local = int(np.asarray(jax.device_get(n_local))[0])
        r_local = int(rider) * self.local_nodes

        # D2H: every device's shard-sum lands in ONE contiguous
        # FrameBuffer-backed flat vector per dtype group (ops.staging).
        host_flats = staging.stage_into(self._fb, shard_sums,
                                        [dt for dt, *_ in gplans])
        logical = sum(v.nbytes for v in host_flats)
        if self.host_leg is not None:
            b0 = self.host_leg.nic_bytes()
            hv = {"g": host_flats,
                  "n": np.asarray(n_local, np.int64),
                  "r": np.asarray(r_local, np.int64)}
            red, _, _ = self.host_leg.all_reduce_ex(
                hv, op="sum", contrib=True, rider=0, codec=self.codec)
            host_flats = red["g"]
            # the tree folds into 0-d buffers but may hand back [1] views
            n_total = int(np.asarray(red["n"]).reshape(()))
            r_total = int(np.asarray(red["r"]).reshape(()))
            self._c_bytes.labels(backend="hybrid").inc(
                self.host_leg.nic_bytes() - b0)
        else:
            n_total, r_total = n_local, r_local

        # H2D one chunk per device + in-mesh all-gather back to rows.
        from jax.sharding import NamedSharding, PartitionSpec as P
        sh = NamedSharding(self.mesh_tree.mesh, P(self.axis_name))
        dev_flats = []
        for flat in host_flats:
            arr = np.ascontiguousarray(flat)
            dev_flats.append(jax.make_array_from_callback(
                arr.shape, sh, lambda idx, a=arr: a[idx]))
        out = ag(*dev_flats)
        self._c_rounds.labels(backend="hybrid").inc()
        self._c_logical.labels(backend="hybrid").inc(logical)
        self._h_secs.labels(backend="hybrid").observe(
            time.perf_counter() - t0)
        return out, n_total, r_total

    def scatter(self, value: PyTree, src: int = 0) -> PyTree:
        """Logical node ``src``'s row broadcast to every row of every
        host: the owning host extracts the row, a masked host-leg
        allreduce moves it across hosts (additive identity elsewhere —
        bitwise the owner's values), and every host replicates it over
        its slice."""
        if not 0 <= src < self.num_nodes:
            raise ValueError(
                f"src={src} out of range for {self.num_nodes} nodes")
        h, row = divmod(src, self.local_nodes)
        if self.rank == h:
            mine = self.node_slice(value, row)
        else:
            mine = _jtu.tree_map(
                lambda x: np.zeros(tuple(x.shape[1:]), np.dtype(x.dtype)),
                value)
        if self.host_leg is not None:
            mine, _ = self.host_leg.all_reduce(mine,
                                               contrib=(self.rank == h))
        return self.replicate(mine)
