"""Packed tensor-list wire codec — the coalesced frame format behind
``Conn.send_tensors``/``recv_tensors`` (kind ``'P'`` in comm/transport.py).

The reference syncs a model as one frame per pytree leaf; at 18 leaves per
CIFAR convnet that is 18 header round-trips of kernel/syscall overhead per
direction per sync.  A packed frame ships the whole leaf list as ONE frame:

    payload := hlen:u32le | manifest[hlen] | data bytes
    manifest = JSON {"v": 1, "codec": str, "leaves": [entry...]}
    entry    = {"dtype": str, "shape": [int...], "enc": str,
                "offset": int, "nbytes": int, ("scale": float)}

``offset``/``nbytes`` describe each leaf's slice of the data region in
WIRE bytes (post-encoding); ``dtype``/``shape`` are the logical tensor.
Per-leaf ``enc`` lets one frame mix encodings: non-float leaves ride raw
inside an fp16/int8 frame.

Codecs (QSGD, Alistarh et al. 2017; 1-bit SGD, Seide et al. 2014 — the
error-feedback residual lives in parallel/async_ea.py, client side):

* ``raw``  — pass-through; zero-copy views of the caller's arrays.
* ``fp16`` — float leaves cast to float16 (half the bytes).
* ``int8`` — float leaves scaled per leaf by ``max|x|/127`` and rounded
  to int8 (quarter the bytes of f32); ``scale`` rides in the manifest.

Everything here is transport-agnostic and side-effect free; framing,
metrics, and stream-alignment-on-error live in comm/transport.py.
"""

from __future__ import annotations

import math

import numpy as np

#: Codec ids a peer may request/advertise.  Order is preference order.
CODECS = ("raw", "fp16", "int8")

#: Manifest schema version (bumped on incompatible manifest changes).
WIRE_V = 1

_ENC_WIRE_DTYPE = {"fp16": np.dtype(np.float16), "int8": np.dtype(np.int8)}


class PackedPayload:
    """One encoded leaf list, ready for ``Conn.send_packed``.

    ``bufs[i]`` is the wire-format array for ``manifest["leaves"][i]`` —
    the original array itself for raw leaves (zero copy), a fresh
    fp16/int8 array for encoded ones.  ``frame`` is non-None when every
    wire byte lives in ONE contiguous staging region (a
    :class:`FrameBuffer`): the transport then ships a single iovec
    instead of a per-leaf gather.
    """

    __slots__ = ("manifest", "bufs", "codec", "wire_nbytes",
                 "logical_nbytes", "frame")

    def __init__(self, manifest: dict, bufs: list, codec: str,
                 wire_nbytes: int, logical_nbytes: int,
                 frame: np.ndarray | None = None):
        self.manifest = manifest
        self.bufs = bufs
        self.codec = codec
        self.wire_nbytes = wire_nbytes
        self.logical_nbytes = logical_nbytes
        self.frame = frame

    def decoded(self) -> list[np.ndarray]:
        """What the receiver will reconstruct — the error-feedback residual
        is ``sent_value - decoded()`` (raw leaves decode to themselves).
        Allocates fresh arrays per call; steady-state paths use
        :meth:`decoded_into`."""
        out = []
        for entry, buf in zip(self.manifest["leaves"], self.bufs):
            if entry["enc"] == "raw":
                out.append(buf)
            else:
                dec = np.empty(tuple(entry["shape"]),
                               np.dtype(entry["dtype"]))
                decode_into(entry, buf, dec)
                out.append(dec)
        return out

    def decoded_into(self, out: list[np.ndarray]) -> list[np.ndarray]:
        """:meth:`decoded` into preallocated logical-dtype buffers — the
        residual/apply hot paths reuse one scratch list across syncs so a
        steady-state sync allocates nothing.  Raw leaves are returned as
        the zero-copy wire buffer itself (``out[i]`` untouched) unless
        they alias it already."""
        res = []
        for entry, buf, o in zip(self.manifest["leaves"], self.bufs, out):
            if entry["enc"] == "raw":
                res.append(buf)
            else:
                decode_into(entry, buf, o)
                res.append(o)
        return res


class FrameBuffer:
    """Reusable contiguous staging for one packed frame's data region.

    One per stripe, grown to the stripe's wire size on first use and
    reused for every later sync (stripe wire sizes are fixed by the leaf
    schedule, so steady state never reallocates).  Fused codec kernels
    write their wire bytes straight into :meth:`view` windows; the
    transport ships :meth:`frame` as a single iovec — no per-leaf gather,
    no per-sync allocation."""

    __slots__ = ("buf",)

    def __init__(self, nbytes: int = 0):
        self.buf = np.empty(int(nbytes), np.uint8)

    def reserve(self, nbytes: int) -> None:
        """Grow (never shrink) the staging region to ``nbytes``."""
        if self.buf.nbytes < nbytes:
            self.buf = np.empty(int(nbytes), np.uint8)

    def view(self, offset: int, nbytes: int, dtype: np.dtype,
             shape: tuple) -> np.ndarray:
        """A zero-copy typed window ``[offset, offset+nbytes)`` of the
        staging region (kernels write wire bytes through it)."""
        return self.buf[offset:offset + nbytes].view(dtype).reshape(shape)

    def frame(self, nbytes: int) -> np.ndarray:
        """The first ``nbytes`` of the staging region — the whole packed
        data region as ONE buffer for a single-iovec send."""
        return self.buf[:nbytes]


def encoded_nbytes(dtype: np.dtype, size: int, codec: str) -> int:
    """WIRE bytes one leaf of ``dtype``/``size`` occupies under ``codec``
    — the same per-leaf encoding decision as :func:`_encode_leaf`, used
    to size a :class:`FrameBuffer` before any kernel runs."""
    if codec == "fp16" and dtype.kind == "f" and dtype.itemsize > 2:
        return 2 * size
    if codec == "int8" and dtype.kind == "f":
        return size
    return size * dtype.itemsize


def _encode_leaf(arr: np.ndarray, codec: str) -> tuple[str, np.ndarray, dict]:
    """Pick the per-leaf encoding: quantizers only apply to float leaves
    wider than the wire format; everything else rides raw."""
    if codec == "fp16" and arr.dtype.kind == "f" and arr.dtype.itemsize > 2:
        return "fp16", arr.astype(np.float16), {}
    if codec == "int8" and arr.dtype.kind == "f":
        amax = float(np.max(np.abs(arr))) if arr.size else 0.0
        if not math.isfinite(amax):
            raise ValueError(
                "int8 wire codec cannot encode non-finite values "
                "(inf/nan leaf)")
        scale = amax / 127.0
        if scale == 0.0:
            q = np.zeros(arr.shape, np.int8)
        else:
            q = np.clip(np.rint(arr / arr.dtype.type(scale)),
                        -127, 127).astype(np.int8)
        return "int8", q, {"scale": scale}
    return "raw", arr, {}


def encode_leaves(leaves, codec: str = "raw") -> PackedPayload:
    """Encode a tensor list into one packed payload.  Raw leaves are
    zero-copy views; the caller must not mutate them until the frame is
    sent (the AsyncEA overlap path hands ownership to the sender)."""
    if codec not in CODECS:
        raise ValueError(f"unknown wire codec {codec!r} "
                         f"(supported: {', '.join(CODECS)})")
    entries, bufs = [], []
    offset = logical = 0
    for x in leaves:
        arr = np.asarray(x)
        if not arr.flags.c_contiguous:
            arr = np.ascontiguousarray(arr)
        enc, buf, extra = _encode_leaf(arr, codec)
        entry = {"dtype": arr.dtype.name, "shape": list(arr.shape),
                 "enc": enc, "offset": offset, "nbytes": buf.nbytes}
        entry.update(extra)
        entries.append(entry)
        bufs.append(buf)
        offset += buf.nbytes
        logical += arr.nbytes
    manifest = {"v": WIRE_V, "codec": codec, "leaves": entries}
    return PackedPayload(manifest, bufs, codec, offset, logical)


def plan_stripes(nbytes: list[int], shards: int) -> list[tuple[int, int]]:
    """Partition a leaf list into at most ``shards`` contiguous,
    byte-balanced stripes (Dean et al. 2012 parameter-server sharding,
    applied to a pytree leaf schedule).

    Returns ``[(lo, hi), ...]`` half-open index ranges covering
    ``[0, len(nbytes))`` in order.  Greedy walk: each stripe takes leaves
    until adding the next one would move it FURTHER from the ideal
    remaining-bytes/remaining-stripes share than stopping; every stripe
    takes at least one leaf, so the effective stripe count is
    ``min(shards, len(nbytes))``.  Deterministic in the leaf schedule —
    but the AsyncEA handshake still ships the explicit ranges so a
    version skew in this planner can never desync two peers.
    """
    n = len(nbytes)
    if n == 0:
        return [(0, 0)]
    shards = max(1, min(int(shards), n))
    total = sum(nbytes)
    stripes: list[tuple[int, int]] = []
    lo, remaining = 0, total
    for s in range(shards):
        want = remaining / (shards - s)
        hi, size = lo, 0
        max_hi = n - (shards - s - 1)       # leave >=1 leaf per later stripe
        while hi < max_hi:
            nb = nbytes[hi]
            if hi > lo and abs(size + nb - want) > abs(size - want):
                break
            size += nb
            hi += 1
        stripes.append((lo, hi))
        lo, remaining = hi, remaining - size
    lo_last, _ = stripes[-1]
    stripes[-1] = (lo_last, n)              # tail always closes the range
    return stripes


def plan_splits(nbytes: list[int], nelems: list[int],
                shards: int) -> list[int]:
    """Per-leaf split counts for sub-leaf striping: any leaf bigger than
    the ideal per-stripe byte share is cut into that many equal-element
    chunks BEFORE stripe planning, so a single oversized kernel (e.g. a
    convnet's last conv holding 3/4 of the bytes) cannot Amdahl-bound
    the sharded pipeline — the reason the classic parameter servers
    split large tensors across shards (Dean et al. 2012 §4.1).

    Returns one ``parts`` count per leaf (1 = unsplit); all 1 when
    ``shards <= 1``.  Deterministic in (sizes, shards) — but like the
    stripe ranges, the AsyncEA handshake ships the split table
    explicitly so planner skew can never desync two peers."""
    n = len(nbytes)
    if int(shards) <= 1 or n == 0:
        return [1] * n
    target = sum(nbytes) / int(shards)
    if target <= 0:
        return [1] * n
    return [1 if nb <= target or ne <= 1
            else min(ne, -(-nb // max(1, int(target))))
            for nb, ne in zip(nbytes, nelems)]


def _split_bounds(n: int, parts: int) -> list[tuple[int, int]]:
    """Half-open element ranges cutting ``n`` elements into ``parts``
    near-equal chunks (the first ``n % parts`` chunks take the extra
    element) — the ONE place the chunk arithmetic lives, shared by both
    peers' view builders so their layouts agree by construction."""
    base, rem = divmod(n, parts)
    bounds, lo = [], 0
    for i in range(parts):
        hi = lo + base + (1 if i < rem else 0)
        bounds.append((lo, hi))
        lo = hi
    return bounds


def split_views(leaves: list[np.ndarray], splits: list[int]
                ) -> list[np.ndarray]:
    """The VIRTUAL leaf list striping operates over: unsplit leaves pass
    through with their real shapes; split leaves become contiguous flat
    chunk views (zero-copy — writes through a view land in the real
    leaf).  Both AsyncEA peers derive this from the same split table, so
    per-chunk wire frames line up index-for-index."""
    out: list[np.ndarray] = []
    for t, p in zip(leaves, splits):
        if p <= 1:
            out.append(t)
        else:
            flat = t.reshape(-1)
            out.extend(flat[lo:hi] for lo, hi in _split_bounds(t.size, p))
    return out


def merge_views(vleaves: list[np.ndarray], splits: list[int],
                shapes: list[tuple]) -> list[np.ndarray]:
    """Rebuild the real leaf list from a virtual one (inverse of
    :func:`split_views`): split leaves concatenate their chunks back to
    ``shapes`` (copying only those), unsplit leaves pass through."""
    out, i = [], 0
    for shape, p in zip(shapes, splits):
        if p <= 1:
            out.append(vleaves[i])
            i += 1
        else:
            flat = np.concatenate([np.ravel(c) for c in vleaves[i:i + p]])
            out.append(flat.reshape(shape))
            i += p
    return out


def wire_dtype(entry: dict) -> np.dtype:
    """The dtype of a leaf's bytes ON THE WIRE (its logical dtype for raw
    leaves, the quantized dtype otherwise)."""
    if entry["enc"] == "raw":
        return np.dtype(entry["dtype"])
    return _ENC_WIRE_DTYPE[entry["enc"]]


def decode_into(entry: dict, wirebuf: np.ndarray, out: np.ndarray) -> None:
    """Dequantize one encoded leaf into a preallocated logical-dtype
    buffer (raw leaves never come through here — the transport reads them
    straight into the target)."""
    enc = entry["enc"]
    if enc == "fp16":
        out[...] = wirebuf
    elif enc == "int8":
        np.multiply(wirebuf, out.dtype.type(entry["scale"]), out=out)
    else:
        raise ValueError(f"decode_into on {enc!r} leaf")


def parse_manifest(raw: bytes, data_nbytes: int,
                   expect_n: int | None = None) -> tuple[str, list[dict]]:
    """Validate a received manifest against the frame's data-region size.

    Raises ``ValueError`` on ANY structural problem — wrong JSON, unknown
    codec/encoding, negative/overflowing shapes, offsets that do not tile
    the data region, leaf count mismatch.  The transport converts that to
    ``ProtocolError`` after draining the announced payload, so a corrupt
    manifest never desyncs the stream.
    """
    import json
    try:
        doc = json.loads(raw)
    except ValueError as e:
        raise ValueError(f"undecodable packed manifest: {e}") from None
    if not isinstance(doc, dict) or not isinstance(doc.get("leaves"), list):
        raise ValueError("packed manifest is not {codec, leaves} shaped")
    codec = doc.get("codec")
    if codec not in CODECS:
        raise ValueError(f"unknown wire codec {codec!r} in manifest")
    entries = doc["leaves"]
    if expect_n is not None and len(entries) != expect_n:
        raise ValueError(
            f"packed frame carries {len(entries)} leaves, receiver "
            f"expects {expect_n} — sender and receiver disagree on the "
            "tensor schedule")
    offset = 0
    for i, entry in enumerate(entries):
        if not isinstance(entry, dict):
            raise ValueError(f"leaf {i}: manifest entry is not an object")
        try:
            dtype = np.dtype(entry["dtype"])
            shape = tuple(int(s) for s in entry["shape"])
            enc = entry["enc"]
            nbytes = int(entry["nbytes"])
            off = int(entry["offset"])
        except (KeyError, TypeError, ValueError) as e:
            raise ValueError(f"leaf {i}: bad manifest entry: {e}") from None
        if any(s < 0 for s in shape):
            raise ValueError(f"leaf {i}: negative dimension in {shape}")
        if enc not in ("raw",) + tuple(_ENC_WIRE_DTYPE):
            raise ValueError(f"leaf {i}: unknown encoding {enc!r}")
        if enc != "raw" and dtype.kind != "f":
            raise ValueError(
                f"leaf {i}: {enc} encoding on non-float dtype {dtype}")
        if enc == "int8":
            try:
                scale = float(entry["scale"])
            except (KeyError, TypeError, ValueError):
                raise ValueError(f"leaf {i}: int8 leaf missing scale") \
                    from None
            if not math.isfinite(scale):
                raise ValueError(f"leaf {i}: non-finite int8 scale {scale}")
        wdt = np.dtype(dtype) if enc == "raw" else _ENC_WIRE_DTYPE[enc]
        # Python-int product: immune to C-long overflow from a hostile
        # header (same hardening as recv_tensor).
        expect = math.prod(shape) * wdt.itemsize
        if nbytes != expect:
            raise ValueError(
                f"leaf {i}: wire payload {nbytes} bytes != {expect} "
                f"expected for {enc}-encoded {dtype}{shape}")
        if off != offset:
            raise ValueError(
                f"leaf {i}: offset {off} does not tile the data region "
                f"(expected {offset})")
        offset += nbytes
    if offset != data_nbytes:
        raise ValueError(
            f"manifest leaves cover {offset} bytes but the frame carries "
            f"{data_nbytes}")
    return codec, entries
