"""Host-side TCP transport — the torch-ipc socket layer rebuilt
(reference consumers: ipc.server/client/recvAny — lua/AsyncEA.lua:87-220,
examples/EASGD_server.lua:67-77).

Wire protocol (shared with the native C++ backend in src/comm/distcomm.cpp):

    frame   := kind:u8 | length:u64le | payload[length]
    kind 'J': payload is UTF-8 JSON (control messages)
    kind 'T': payload is hlen:u32le | header[hlen] | raw tensor bytes,
              header = JSON {"dtype": str, "shape": [int...]}
    kind 'P': payload is hlen:u32le | manifest[hlen] | packed leaf bytes —
              a whole tensor LIST in one frame (manifest schema and the
              raw/fp16/int8 leaf codecs: distlearn_tpu.comm.wire)
    kind 'G': payload is UTF-8 JSON — a GENERATE request (prompt in):
              {"id", "prompt": [ints], "max_new", ...} (docs/SERVING.md)
    kind 'R': payload is UTF-8 JSON — one token-stream RESPONSE chunk
              (tokens out): {"id", "tokens": [ints], "done", ...}

JSON frames ('J' admission announces, 'G' requests) MAY carry an
optional "tc" field — the cross-process trace context {"t": trace-id
hex, "s": parent span-id hex, "f": 0|1} (obs/trace.py, docs/
OBSERVABILITY.md).  The field only appears when DISTLEARN_TRACE_PROP is
on; absent, frames are bitwise identical to pre-trace peers', and a
receiver treats a malformed value as "no trace" — never an error.

Connection management (listen/accept/connect/poll) stays in Python; the
byte-moving hot path (frame assembly, big-buffer send/recv loops) dispatches
to the native library when built (distlearn_tpu.comm.native), falling back to
pure-Python socket IO.  ``recv_tensor(out=...)`` reuses a preallocated buffer
— the reference's ``client:recv(buffer)`` semantics (lua/AsyncEA.lua:100-103).
"""

from __future__ import annotations

import errno
import itertools
import json
import math
import random
import select
import socket
import struct
import time
from typing import Any

import numpy as np

from distlearn_tpu import obs
from distlearn_tpu.comm import native, wire
from distlearn_tpu.comm.errors import PeerClosed

_HDR = struct.Struct("<BQ")   # kind, payload length
_THDR = struct.Struct("<I")   # tensor header length

# sendmsg iovec fan-in cap, kept well under every Linux IOV_MAX (1024);
# longer buffer lists loop.
_IOV_MAX = 512

#: recv_serve_nowait frame-size cap — serve payloads are small JSON, so
#: anything bigger is a desynced or hostile peer.
SERVE_MAX_FRAME = 1 << 20

_CONN_IDS = itertools.count()


def _drops():
    return obs.counter("transport_drops_total",
                       "connections dropped by recv_any, by cause",
                       labels=("reason",))


def _timeouts():
    return obs.counter("transport_timeouts_total",
                       "transport operations that hit a timeout/deadline",
                       labels=("op",))


def _wire_frames():
    return obs.counter("wire_packed_frames_total",
                       "packed 'P' tensor-list frames sent, by codec",
                       labels=("codec",))


def _wire_bytes():
    return obs.counter("wire_packed_bytes_total",
                       "wire bytes of packed frames sent "
                       "(frame header + manifest + data), by codec",
                       labels=("codec",))


def _wire_logical():
    return obs.counter("wire_logical_bytes_total",
                       "pre-encoding logical tensor bytes shipped in "
                       "packed frames, by codec",
                       labels=("codec",))


def _wire_ratio():
    return obs.gauge("wire_compression_ratio",
                     "logical/wire byte ratio of the most recent packed "
                     "frame, by codec",
                     labels=("codec",))


def _wire_pack_secs():
    return obs.histogram("wire_pack_seconds",
                         "time to encode one packed frame "
                         "(manifest build + quantization)")


def _wire_zero_copy():
    return obs.counter("wire_zero_copy_total",
                       "packed-frame sends by staging outcome: hit = one "
                       "contiguous frame-buffer iovec (fused kernels wrote "
                       "wire bytes in place), miss = per-leaf gather",
                       labels=("result",))


class Conn:
    """A framed connection over one TCP socket.

    ``bytes_sent`` / ``bytes_received`` count payload bytes (frames +
    tensors) — the per-link traffic evidence behind the tree-vs-ring
    bandwidth analysis (docs/PERF.md).  ``throttle_bps`` (None = off)
    paces SENDS to that many bytes/second: localhost benches use it to
    emulate bandwidth-limited NIC links on a host whose loopback is
    CPU-bound (the regime the ring allreduce is designed for), by
    sleeping out the remainder of each send's wire-time budget."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._fd = sock.fileno()
        self.bytes_sent = 0
        self.bytes_received = 0
        self.throttle_bps: float | None = None
        # Force the pure-Python socket path for this conn even when the
        # native backend is built.  The native loops do IO on the raw fd,
        # which bypasses any proxy installed over ``self.sock`` — the
        # fault-injection layer (comm/faults.py) flips this so its socket
        # wrapper actually sees every byte.
        self.force_py_io = False
        self._rx = bytearray()        # recv_serve_nowait partial-frame buffer
        self._rx_eof = False
        # Telemetry handles resolve once per connection (obs.NULL when the
        # kill switch is off, so the hot path stays a no-op method call).
        # Counters mirror bytes_sent/bytes_received exactly: both are
        # updated by the single thread that does IO on this Conn.
        self.conn_id = str(next(_CONN_IDS))
        self._obs = obs.enabled()
        per_conn = {"labels": ("conn",), "max_children": 256}
        self._m_sent = obs.counter(
            "transport_bytes_sent_total",
            "wire bytes sent per connection (frames + tensor payloads)",
            **per_conn).labels(conn=self.conn_id)
        self._m_recv = obs.counter(
            "transport_bytes_received_total",
            "wire bytes received per connection",
            **per_conn).labels(conn=self.conn_id)
        lat = obs.histogram(
            "transport_frame_recv_seconds",
            "whole-frame receive latency (header to last payload byte)",
            labels=("kind",))
        self._h_ctrl = lat.labels(kind="control")
        self._h_tensor = lat.labels(kind="tensor")
        self._h_serve = lat.labels(kind="serve")

    def _pace(self, nbytes: int, t0: float):
        if self.throttle_bps:
            budget = nbytes / self.throttle_bps
            left = budget - (time.perf_counter() - t0)
            if left > 0:
                time.sleep(left)

    def set_timeout(self, seconds: float | None):
        """Kernel-level send/recv timeout (SO_RCVTIMEO/SO_SNDTIMEO) so that a
        dead or hung peer turns a blocking IO into :class:`TimeoutError`
        instead of a wedge.  Set at the fd level (not ``settimeout``) so the
        native C++ recv/send loops honor it too.  ``None`` disables."""
        if seconds is None:
            tv = struct.pack("ll", 0, 0)
        else:
            if seconds <= 0:
                raise ValueError("timeout must be positive or None")
            tv = struct.pack("ll", int(seconds),
                             int((seconds - int(seconds)) * 1e6))
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVTIMEO, tv)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDTIMEO, tv)

    # -- low-level framing --------------------------------------------------
    def _sendv(self, bufs: list):
        """Vectored full-send of a buffer list via ``sendmsg`` — the frame
        header and payload(s) leave in ONE syscall (and, with TCP_NODELAY,
        one packet when they fit): two back-to-back ``send()`` calls ship
        the 9-byte header as its own packet per control message.  Handles
        partial sends by slicing the straddled view and continuing."""
        vs = []
        for b in bufs:
            v = b if isinstance(b, memoryview) else memoryview(b)
            if v.format != "B" or v.ndim != 1:
                v = v.cast("B")
            if v.nbytes:
                vs.append(v)
        i = 0
        while i < len(vs):
            sent = self.sock.sendmsg(vs[i:i + _IOV_MAX])
            while i < len(vs) and sent >= vs[i].nbytes:
                sent -= vs[i].nbytes
                i += 1
            if sent:
                vs[i] = vs[i][sent:]

    def _send_frame(self, kind: int, payload: bytes | memoryview):
        t0 = time.perf_counter()
        try:
            if native.available() and not self.force_py_io:
                native.send_frame(self._fd, kind, payload)
            else:
                self._sendv([_HDR.pack(kind, len(payload)), payload])
        except (BlockingIOError, InterruptedError) as e:
            _timeouts().labels(op="send").inc()
            raise TimeoutError("send timed out (socket timeout)") from e
        self.bytes_sent += _HDR.size + len(payload)
        self._m_sent.inc(_HDR.size + len(payload))
        self._pace(_HDR.size + len(payload), t0)

    def _recv_exact(self, n: int, out: memoryview | None = None,
                    mid_frame: bool = False,
                    deadline: float | None = None) -> memoryview:
        """Read exactly ``n`` bytes.  A peer FIN raises
        :class:`PeerClosed` ONLY when it lands
        before any byte of a fresh frame (a finished peer); a FIN after
        partial progress — or anywhere once ``mid_frame`` marks this read
        as continuing an already-started frame — raises
        :class:`ConnectionResetError`, so drop-policy code can tell a
        torn frame from a clean goodbye.

        ``deadline`` (``time.monotonic()`` value) bounds the WHOLE read:
        a kernel SO_RCVTIMEO re-arms on every successful ``recv``, so a
        peer trickling one byte per timeout-epsilon never trips it — the
        wedge class the frame deadline exists to kill.  Deadline reads
        take the Python loop (bypassing the native batch recv; they are
        used for small control frames where throughput is irrelevant)."""
        buf = out if out is not None else memoryview(bytearray(n))
        if deadline is not None:
            prev = self.sock.gettimeout()
            got = 0
            try:
                while got < n:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        _timeouts().labels(op="recv_deadline").inc()
                        raise TimeoutError(
                            "recv deadline exceeded (peer trickling or "
                            "stalled mid-frame)")
                    self.sock.settimeout(remaining)
                    try:
                        r = self.sock.recv_into(buf[got:], n - got)
                    except (socket.timeout, BlockingIOError) as e:
                        _timeouts().labels(op="recv_deadline").inc()
                        raise TimeoutError(
                            "recv deadline exceeded (peer trickling or "
                            "stalled mid-frame)") from e
                    if r == 0:
                        if got or mid_frame:
                            raise ConnectionResetError(
                                "peer closed connection mid-frame")
                        raise PeerClosed("peer closed connection")
                    got += r
            finally:
                try:
                    self.sock.settimeout(prev)
                except OSError:
                    pass
            self.bytes_received += n
            self._m_recv.inc(n)
            return buf
        try:
            if native.available() and not self.force_py_io:
                try:
                    native.recv_exact(self._fd, buf, n)
                except PeerClosed as e:
                    if mid_frame:
                        raise ConnectionResetError(
                            "peer closed connection mid-frame") from e
                    raise
                self.bytes_received += n
                self._m_recv.inc(n)
                return buf
            got = 0
            while got < n:
                r = self.sock.recv_into(buf[got:], n - got)
                if r == 0:
                    if got or mid_frame:
                        raise ConnectionResetError(
                            "peer closed connection mid-frame")
                    raise PeerClosed("peer closed connection")
                got += r
        except BlockingIOError as e:   # SO_RCVTIMEO expired -> EAGAIN
            _timeouts().labels(op="recv").inc()
            raise TimeoutError("recv timed out (socket timeout)") from e
        self.bytes_received += n
        self._m_recv.inc(n)
        return buf

    def _recv_frame_header(self, deadline: float | None = None
                           ) -> tuple[int, int]:
        hdr = bytes(self._recv_exact(_HDR.size, deadline=deadline))
        return _HDR.unpack(hdr)

    # -- control messages ---------------------------------------------------
    def send_msg(self, msg: Any):
        """Send a JSON-serializable control message (ref ``client:send({q=...})``)."""
        self._send_frame(ord("J"), json.dumps(msg).encode())

    def recv_msg(self, deadline: float | None = None) -> Any:
        t0 = time.perf_counter() if self._obs else 0.0
        kind, length = self._recv_frame_header(deadline)
        payload = bytes(self._recv_exact(length, mid_frame=True,
                                         deadline=deadline))
        if kind != ord("J"):
            raise ProtocolError(f"expected control message, got kind {chr(kind)!r}")
        if self._obs:
            self._h_ctrl.observe(time.perf_counter() - t0)
        return json.loads(payload)

    # -- serving frames (kinds 'G'/'R', distlearn_tpu.serve) ----------------
    def send_gen(self, msg: Any):
        """Send one generate REQUEST (kind ``'G'``): prompt in.  Payload
        is JSON like a ``'J'`` frame; the distinct kind lets a serving
        endpoint reject control traffic (and vice versa) without parsing
        — a training client dialing a serve port desyncs loudly."""
        self._send_frame(ord("G"), json.dumps(msg).encode())

    def send_stream(self, msg: Any):
        """Send one token-stream RESPONSE chunk (kind ``'R'``): tokens
        out.  One frame per tick keeps time-to-first-token at one
        decode tick, not one full generation."""
        self._send_frame(ord("R"), json.dumps(msg).encode())

    def recv_serve(self, deadline: float | None = None) -> tuple[str, Any]:
        """Receive one serving-protocol frame: returns ``(kind, msg)``
        with ``kind`` in ``'G'``/``'R'``/``'J'`` (``'J'`` stays legal so
        control pings — health probes, drain notices — share the
        connection).  Tensor frames raise :class:`ProtocolError`."""
        t0 = time.perf_counter() if self._obs else 0.0
        kind, length = self._recv_frame_header(deadline)
        payload = bytes(self._recv_exact(length, mid_frame=True,
                                         deadline=deadline))
        if kind not in (ord("G"), ord("R"), ord("J")):
            raise ProtocolError(
                f"expected serve frame (G/R/J), got kind {chr(kind)!r}")
        if self._obs:
            self._h_serve.observe(time.perf_counter() - t0)
        return chr(kind), json.loads(payload)

    def rx_pending(self) -> int:
        """Bytes of a partial serve frame buffered by
        :meth:`recv_serve_nowait` — nonzero means the peer has a frame in
        flight, so a server loop can time out tricklers without ever
        blocking on them."""
        return len(self._rx)

    def recv_serve_nowait(self) -> list[tuple[str, Any]]:
        """Drain whatever bytes the socket holds RIGHT NOW — never
        blocking — reassemble them, and return every COMPLETE serve
        frame as ``(kind, msg)`` pairs (possibly none).  A partial frame
        stays buffered on the connection until the peer's next bytes
        arrive.

        The single-threaded-server counterpart of :meth:`recv_serve`:
        select only proves SOME bytes are readable, and a blocking
        whole-frame read there lets one half-sent frame stall every
        other in-flight request (head-of-line blocking).  Raises
        :class:`PeerClosed` on EOF at a frame boundary,
        :class:`ConnectionResetError` on EOF mid-frame, and
        :class:`ProtocolError` on a non-serve kind or a frame larger
        than :data:`SERVE_MAX_FRAME` (buffering an attacker-announced
        length would hand the peer a memory lever)."""
        got = 0
        self.sock.setblocking(False)
        try:
            while True:
                try:
                    chunk = self.sock.recv(1 << 16)
                except (BlockingIOError, InterruptedError):
                    break
                if not chunk:
                    self._rx_eof = True
                    break
                self._rx += chunk
                got += len(chunk)
        finally:
            try:
                self.sock.setblocking(True)
            except OSError:
                pass
        if got:
            self.bytes_received += got
            self._m_recv.inc(got)
        frames: list[tuple[str, Any]] = []
        while len(self._rx) >= _HDR.size:
            kind, length = _HDR.unpack_from(self._rx)
            if kind not in (ord("G"), ord("R"), ord("J")):
                raise ProtocolError(
                    f"expected serve frame (G/R/J), got kind {chr(kind)!r}")
            if length > SERVE_MAX_FRAME:
                raise ProtocolError(f"serve frame too large: {length} bytes")
            if len(self._rx) < _HDR.size + length:
                break
            payload = bytes(self._rx[_HDR.size:_HDR.size + length])
            del self._rx[:_HDR.size + length]
            frames.append((chr(kind), json.loads(payload)))
        if self._rx_eof and not frames:
            if self._rx:
                raise ConnectionResetError("peer closed connection mid-frame")
            raise PeerClosed("peer closed connection")
        return frames

    # -- tensors ------------------------------------------------------------
    def send_tensor(self, arr: np.ndarray):
        # copy ONLY when the buffer is not already contiguous — an
        # unconditional ascontiguousarray would still be cheap, but this
        # makes the zero-copy contract explicit for the 100 MB-leaf syncs
        if not (isinstance(arr, np.ndarray) and arr.flags.c_contiguous):
            arr = np.ascontiguousarray(arr)
        header = json.dumps({"dtype": arr.dtype.name,
                             "shape": list(arr.shape)}).encode()
        meta = _THDR.pack(len(header)) + header
        nbytes = _HDR.size + len(meta) + arr.nbytes
        t0 = time.perf_counter()
        try:
            if native.available() and not self.force_py_io:
                # zero-copy: numpy buffer goes straight into the writev
                native.send_tensor_frame(self._fd, ord("T"), meta, arr)
                self.bytes_sent += nbytes
                self._m_sent.inc(nbytes)
                self._pace(nbytes, t0)
                return
            self._sendv([_HDR.pack(ord("T"), len(meta) + arr.nbytes),
                         meta, memoryview(arr).cast("B")])
        except (BlockingIOError, InterruptedError) as e:
            _timeouts().labels(op="send").inc()
            raise TimeoutError("send timed out (socket timeout)") from e
        self.bytes_sent += nbytes
        self._m_sent.inc(nbytes)
        self._pace(nbytes, t0)

    def recv_tensor(self, out: np.ndarray | None = None,
                    deadline: float | None = None) -> np.ndarray:
        """Receive one tensor frame.  ``deadline`` (``time.monotonic()``
        value) bounds the WHOLE frame read, exactly like ``recv_msg`` —
        a handshake peer that sends the tensor header and then trickles
        payload bytes must trip :class:`TimeoutError`, not re-arm the
        kernel timeout forever (the same wedge class the control-frame
        deadline closes)."""
        t0 = time.perf_counter() if self._obs else 0.0
        kind, length = self._recv_frame_header(deadline)
        if kind != ord("T"):
            raise ProtocolError(f"expected tensor, got kind {chr(kind)!r}")
        return self._recv_tensor_body(length, out, deadline, t0)

    def _recv_tensor_body(self, length: int, out: np.ndarray | None,
                          deadline: float | None, t0: float) -> np.ndarray:
        """Body of one ``'T'`` frame whose header was already consumed
        (shared by :meth:`recv_tensor` and the legacy per-leaf branch of
        :meth:`recv_tensors`)."""
        if length < _THDR.size:
            raise ProtocolError(f"tensor frame too short: {length} bytes")
        hlen = _THDR.unpack(bytes(self._recv_exact(
            _THDR.size, mid_frame=True, deadline=deadline)))[0]
        if _THDR.size + hlen > length:
            raise ProtocolError(
                f"tensor header length {hlen} exceeds frame length {length}")
        raw = bytes(self._recv_exact(hlen, mid_frame=True,
                                     deadline=deadline))
        nbytes = length - _THDR.size - hlen
        try:
            header = json.loads(raw)
            dtype = np.dtype(header["dtype"])
            shape = tuple(int(s) for s in header["shape"])
        except (ValueError, KeyError, TypeError) as e:
            raise ProtocolError(f"bad tensor header: {e}") from None
        if any(s < 0 for s in shape):
            raise ProtocolError(f"negative dimension in shape {shape}")
        # Python-int product: immune to C-long overflow/wraparound from a
        # hostile header; the nbytes equality below then rejects it.
        expect = math.prod(shape) * dtype.itemsize
        if nbytes != expect:
            # A desynced/corrupt peer must produce a protocol error, never an
            # under/overrun of the receive buffer (ADVICE r1: the native
            # recv path writes nbytes raw bytes into the target buffer).
            raise ProtocolError(
                f"tensor payload {nbytes} bytes != {expect} expected for "
                f"{dtype}{shape}")
        if out is not None:
            if out.dtype != dtype or out.shape != shape:
                # Drain the announced payload BEFORE raising: leaving nbytes
                # unread would desync the stream, and the next recv on this
                # connection would parse tensor data as a frame header.
                self._recv_exact(nbytes, mid_frame=True, deadline=deadline)
                raise ProtocolError(
                    f"recv buffer mismatch: caller expects "
                    f"{out.dtype}{out.shape} but the wire header announces "
                    f"{dtype}{shape} — sender and receiver disagree on the "
                    "tensor schedule (rank model/config skew)")
            if not (out.flags.c_contiguous and out.flags.writeable):
                tmp = np.empty(shape, dtype)
                self._recv_exact(nbytes, memoryview(tmp).cast("B"),
                                 mid_frame=True, deadline=deadline)
                out[...] = tmp
                if self._obs:
                    self._h_tensor.observe(time.perf_counter() - t0)
                return out
            self._recv_exact(nbytes, memoryview(out).cast("B"),
                             mid_frame=True, deadline=deadline)
            if self._obs:
                self._h_tensor.observe(time.perf_counter() - t0)
            return out
        arr = np.empty(shape, dtype)
        if nbytes:
            self._recv_exact(nbytes, memoryview(arr).cast("B"),
                             mid_frame=True, deadline=deadline)
        if self._obs:
            self._h_tensor.observe(time.perf_counter() - t0)
        return arr

    # -- packed tensor lists (kind 'P', distlearn_tpu.comm.wire) ------------
    def send_tensors(self, leaves, codec: str = "raw", packed: bool = True):
        """Ship a whole tensor list.  ``packed=True`` coalesces it into ONE
        ``'P'`` frame (O(1) frames per sync); ``packed=False`` degrades to
        the legacy per-leaf ``'T'`` frames for peers that never advertised
        packed support (quantized codecs require the packed frame — the
        ``'T'`` header has nowhere to carry a scale)."""
        if not packed:
            if codec not in (None, "raw"):
                raise ValueError(
                    f"codec {codec!r} requires the packed frame; legacy "
                    "per-leaf frames are raw-only")
            for a in leaves:
                self.send_tensor(a)
            return
        if not len(leaves):
            return    # zero leaves = zero frames, matching the legacy path
        t0 = time.perf_counter() if self._obs else 0.0
        payload = wire.encode_leaves(leaves, codec)
        if self._obs:
            _wire_pack_secs().observe(time.perf_counter() - t0)
        self.send_packed(payload)

    def send_packed(self, payload: "wire.PackedPayload"):
        """Send one pre-encoded packed frame (see ``wire.encode_leaves``;
        the AsyncEA client pre-encodes so the error-feedback residual can
        be computed before the frame leaves).  Pacing budgets the WHOLE
        frame, not per leaf — under ``throttle_bps`` a packed sync sleeps
        out the same wire-time a per-leaf sync would."""
        manifest = json.dumps(payload.manifest).encode()
        meta = _THDR.pack(len(manifest)) + manifest
        total = len(meta) + payload.wire_nbytes
        t0 = time.perf_counter()
        try:
            if payload.frame is not None:
                # frame-buffer staging (wire.FrameBuffer): the fused
                # codec kernels already wrote every wire byte into ONE
                # contiguous region — ship it as a single iovec
                data = [memoryview(payload.frame).cast("B")]
            else:
                # one vectored send: frame header + manifest + every leaf
                # buffer (raw leaves are zero-copy views of the caller's
                # arrays; no staging copy of the data region is built)
                data = [memoryview(b).cast("B")
                        for b in payload.bufs if b.nbytes]
            self._sendv([_HDR.pack(ord("P"), total), meta] + data)
        except (BlockingIOError, InterruptedError) as e:
            _timeouts().labels(op="send").inc()
            raise TimeoutError("send timed out (socket timeout)") from e
        nbytes = _HDR.size + total
        self.bytes_sent += nbytes
        self._m_sent.inc(nbytes)
        if self._obs:
            _wire_frames().labels(codec=payload.codec).inc()
            _wire_bytes().labels(codec=payload.codec).inc(nbytes)
            _wire_logical().labels(codec=payload.codec).inc(
                payload.logical_nbytes)
            _wire_ratio().labels(codec=payload.codec).set(
                payload.logical_nbytes / nbytes if nbytes else 0.0)
            _wire_zero_copy().labels(
                result="hit" if payload.frame is not None else "miss").inc()
        self._pace(nbytes, t0)

    def recv_tensors(self, out: list | None = None, n: int | None = None,
                     deadline: float | None = None) -> list[np.ndarray]:
        """Receive a tensor list: ONE packed ``'P'`` frame or ``n`` legacy
        per-leaf ``'T'`` frames — auto-detected from the first frame
        header, so a receiver negotiated down to the legacy wire needs no
        separate code path.  ``out`` reuses preallocated buffers (logical
        dtype — quantized leaves are decoded into it); ``n`` is required
        when ``out`` is None.  ``deadline`` bounds the WHOLE list read."""
        if out is not None:
            want = len(out)
        elif n is not None:
            want = int(n)
        else:
            raise ValueError("recv_tensors needs out= buffers or n=")
        if want == 0:
            return []
        t0 = time.perf_counter() if self._obs else 0.0
        kind, length = self._recv_frame_header(deadline)
        if kind == ord("T"):
            # legacy peer: first frame header is already consumed
            res = [self._recv_tensor_body(
                length, None if out is None else out[0], deadline, t0)]
            for i in range(1, want):
                res.append(self.recv_tensor(
                    out=None if out is None else out[i], deadline=deadline))
            return res
        if kind != ord("P"):
            raise ProtocolError(
                f"expected tensor list, got kind {chr(kind)!r}")
        return self._recv_packed_body(length, out, want, deadline, t0)

    def recv_payload(self, n: int, deadline: float | None = None
                     ) -> "wire.PackedPayload":
        """Receive a tensor list WITHOUT decoding — wire-dtype buffers plus
        the manifest, as a :class:`wire.PackedPayload`.  The fused-apply
        path (``ops/wire_kernels.dequant_add``) consumes quantized bytes
        directly, so decoding here would materialize the f32 copy the
        fused kernels exist to avoid.  Legacy per-leaf ``'T'`` frames are
        wrapped as a raw payload, so callers need no separate path."""
        want = int(n)
        if want == 0:
            return wire.PackedPayload(
                {"v": wire.WIRE_V, "codec": "raw", "leaves": []},
                [], "raw", 0, 0)
        t0 = time.perf_counter() if self._obs else 0.0
        kind, length = self._recv_frame_header(deadline)
        if kind == ord("T"):
            arrs = [self._recv_tensor_body(length, None, deadline, t0)]
            for _ in range(1, want):
                arrs.append(self.recv_tensor(deadline=deadline))
            entries, offset = [], 0
            for a in arrs:
                entries.append({"dtype": a.dtype.name,
                                "shape": list(a.shape), "enc": "raw",
                                "offset": offset, "nbytes": a.nbytes})
                offset += a.nbytes
            return wire.PackedPayload(
                {"v": wire.WIRE_V, "codec": "raw", "leaves": entries},
                arrs, "raw", offset, offset)
        if kind != ord("P"):
            raise ProtocolError(
                f"expected tensor list, got kind {chr(kind)!r}")
        return self._recv_packed_body(length, None, want, deadline, t0,
                                      decode=False)

    def _recv_packed_body(self, length: int, out: list | None, want: int,
                          deadline: float | None, t0: float,
                          decode: bool = True):
        if length < _THDR.size:
            self._recv_exact(length, mid_frame=True, deadline=deadline)
            raise ProtocolError(f"packed frame too short: {length} bytes")
        hlen = _THDR.unpack(bytes(self._recv_exact(
            _THDR.size, mid_frame=True, deadline=deadline)))[0]
        if _THDR.size + hlen > length:
            raise ProtocolError(
                f"packed manifest length {hlen} exceeds frame length "
                f"{length}")
        raw = bytes(self._recv_exact(hlen, mid_frame=True,
                                     deadline=deadline))
        data_nbytes = length - _THDR.size - hlen

        def _drain_and_fail(msg):
            # leaving the data region unread would desync the stream — the
            # next recv would parse tensor bytes as a frame header
            self._recv_exact(data_nbytes, mid_frame=True, deadline=deadline)
            raise ProtocolError(msg)

        try:
            codec, entries = wire.parse_manifest(raw, data_nbytes,
                                                 expect_n=want)
        except ValueError as e:
            _drain_and_fail(str(e))
        if not decode:
            # read each leaf's WIRE bytes verbatim (no dequantization) —
            # the caller applies straight from the quantized buffers
            bufs, logical = [], 0
            for entry in entries:
                wbuf = np.empty(tuple(entry["shape"]),
                                wire.wire_dtype(entry))
                if entry["nbytes"]:
                    self._recv_exact(entry["nbytes"],
                                     memoryview(wbuf).cast("B"),
                                     mid_frame=True, deadline=deadline)
                bufs.append(wbuf)
                logical += (math.prod(entry["shape"])
                            * np.dtype(entry["dtype"]).itemsize)
            if self._obs:
                self._h_tensor.observe(time.perf_counter() - t0)
            return wire.PackedPayload(
                {"v": wire.WIRE_V, "codec": codec, "leaves": entries},
                bufs, codec, data_nbytes, logical)
        if out is not None:
            for i, (entry, o) in enumerate(zip(entries, out)):
                if (o.dtype != np.dtype(entry["dtype"])
                        or tuple(o.shape) != tuple(entry["shape"])):
                    _drain_and_fail(
                        f"recv buffer mismatch at leaf {i}: caller expects "
                        f"{o.dtype}{tuple(o.shape)} but the manifest "
                        f"announces {entry['dtype']}{tuple(entry['shape'])}"
                        " — sender and receiver disagree on the tensor "
                        "schedule (rank model/config skew)")
        res = []
        for i, entry in enumerate(entries):
            dtype = np.dtype(entry["dtype"])
            shape = tuple(entry["shape"])
            nbytes = entry["nbytes"]
            o = out[i] if out is not None else None
            if entry["enc"] == "raw":
                target = o if (o is not None and o.flags.c_contiguous
                               and o.flags.writeable) \
                    else np.empty(shape, dtype)
                if nbytes:
                    self._recv_exact(nbytes, memoryview(target).cast("B"),
                                     mid_frame=True, deadline=deadline)
                if o is not None and target is not o:
                    o[...] = target
                    target = o
            else:
                wbuf = np.empty(shape, wire.wire_dtype(entry))
                if nbytes:
                    self._recv_exact(nbytes, memoryview(wbuf).cast("B"),
                                     mid_frame=True, deadline=deadline)
                target = o if (o is not None and o.flags.writeable) \
                    else np.empty(shape, dtype)
                wire.decode_into(entry, wbuf, target)
            res.append(target)
        if self._obs:
            self._h_tensor.observe(time.perf_counter() - t0)
        return res

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass


class ProtocolError(RuntimeError):
    pass


class Server:
    """Listening endpoint (ref ``ipc.server(host, port)``)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind((host, port))
        self.sock.listen(128)
        self.host, self.port = self.sock.getsockname()
        self.conns: list[Conn] = []

    def accept(self, n: int = 1, timeout: float | None = None) -> list[Conn]:
        """Accept ``n`` connections (ref ``server:clients(n, fn)`` accept side)."""
        new = []
        deadline = None if timeout is None else time.monotonic() + timeout
        try:
            for _ in range(n):
                if deadline is not None:
                    self.sock.settimeout(max(0.0, deadline - time.monotonic()))
                try:
                    c, _ = self.sock.accept()
                except (socket.timeout, BlockingIOError):
                    # settimeout(0.0) = non-blocking -> BlockingIOError
                    _timeouts().labels(op="accept").inc()
                    raise TimeoutError(
                        f"accept timed out after {len(new)} of {n} "
                        "connections") from None
                conn = Conn(c)
                self.conns.append(conn)
                new.append(conn)
        finally:
            self.sock.settimeout(None)
        return new

    def prune_closed(self) -> dict[int, int]:
        """Drop closed conns from the registry (``accept`` only appends,
        so a server whose peers come and go — e.g. rejoin dials — grows
        without bound otherwise).  Returns ``{old_index: new_index}`` for
        the survivors so callers can remap any stored indices."""
        mapping: dict[int, int] = {}
        new: list[Conn] = []
        for i, c in enumerate(self.conns):
            if c.sock.fileno() >= 0:
                mapping[i] = len(new)
                new.append(c)
        self.conns = new
        return mapping

    def recv_any(self, timeout: float | None = None,
                 frame_timeout: float | None = None,
                 on_drop=None) -> tuple[int, Any]:
        """Wait for a control message from ANY accepted connection — the
        server's select-like wait (ref ``serverBroadcast:recvAny()``,
        lua/AsyncEA.lua:168).  Returns ``(conn_index, msg)``.

        Peers that have closed (EOF) are dropped and the wait continues with
        the remaining connections — a client finishing its epochs must not
        wedge the server while other clients still sync.

        ``frame_timeout`` bounds the read of the SELECTED frame: select
        only proves one byte is pending, and ``recv_msg`` blocks until the
        frame is complete — a peer that sends half a header and stalls
        would otherwise wedge the whole wait (VERDICT r4 weak #4).  A peer
        that trips it is dropped like any other desynced peer and the wait
        resumes; the select-level ``timeout`` still raises
        :class:`TimeoutError` as before.  ``on_drop(conn_index, exc)`` is
        called after any ABNORMAL drop — frame timeout, connection reset,
        protocol desync — so the caller can record WHICH peer was cut
        (e.g. evict it so it may later rejoin); a clean EOF (the peer
        finished and closed) stays silent, as before.  After ``on_drop``
        fires, :class:`TimeoutError` is raised instead of resuming the
        wait, handing control back to the caller's loop — the caller's
        view of the peer set just changed (an eviction may now warrant
        sliced polling for rejoiners), and only the caller knows.
        """
        while True:
            live = {c.sock: i for i, c in enumerate(self.conns)
                    if c.sock.fileno() >= 0}
            if not live:
                raise RuntimeError("no open connections")
            ready, _, _ = select.select(list(live), [], [], timeout)
            if not ready:
                raise TimeoutError("recv_any timed out")
            for sock in ready:
                i = live[sock]
                c = self.conns[i]
                dl = (None if frame_timeout is None
                      else time.monotonic() + frame_timeout)
                try:
                    return i, c.recv_msg(deadline=dl)
                except TimeoutError as e:
                    # partial frame then stall: the stream can't be
                    # resumed mid-frame — drop the peer, keep serving.
                    c.close()
                    _drops().labels(reason="frame_timeout").inc()
                    if on_drop is not None:
                        on_drop(i, e)
                        raise TimeoutError(
                            "peer dropped mid-frame (reported via "
                            "on_drop)") from e
                except (ConnectionError, ProtocolError, ValueError) as e:
                    # EOF, a non-control frame, or undecodable bytes: that
                    # peer is broken/desynced (its stream can't be resumed) —
                    # drop it and keep serving the rest.
                    c.close()
                    # both the python and native recv paths raise PeerClosed
                    # for a clean FIN; resets/desyncs surface as other
                    # ConnectionError subclasses or ProtocolError/ValueError
                    clean_eof = isinstance(e, PeerClosed)
                    _drops().labels(
                        reason="eof" if clean_eof else "desync").inc()
                    if on_drop is not None and not clean_eof:
                        on_drop(i, e)
                        raise TimeoutError(
                            "peer dropped abnormally (reported via "
                            "on_drop)") from e

    def close(self):
        for c in self.conns:
            c.close()
        self.sock.close()


def _dial_failure_reason(e: OSError) -> str:
    """Classify a failed dial for the connect-retry counter's `reason`
    label — lets diststat separate "server not up yet" (refused) from a
    partitioned/overloaded standby during failover."""
    if isinstance(e, ConnectionRefusedError):
        return "refused"
    if isinstance(e, (TimeoutError, socket.timeout)):
        return "timeout"
    if getattr(e, "errno", None) in (errno.EHOSTUNREACH, errno.ENETUNREACH):
        return "unreachable"
    return "other"


def connect(host: str, port: int, retries: int = 60,
            retry_interval: float = 0.25,
            max_interval: float = 5.0,
            deadline_s: float | None = None) -> Conn:
    """Client-side connect with retry — the reference launch scripts start
    server and clients concurrently, so clients must tolerate a not-yet-
    listening server (examples/AsyncEASGD.sh backgrounds everything).

    Retries back off exponentially from ``retry_interval`` with FULL
    jitter (sleep ~ U[0, min(max_interval, retry_interval * 2**k)]): a
    whole fleet failing over to a standby otherwise re-dials in
    lockstep and thundering-herds the freshly promoted center.

    ``deadline_s`` bounds the WHOLE retry walk in wall-clock seconds:
    each dial is capped to the remaining budget and no sleep outlives
    it.  Without it, ``retries=60`` against a blackholed host can pin a
    ``failover()`` dial for minutes before the next center is tried.
    """
    last: Exception | None = None
    deadline = (None if deadline_s is None
                else time.monotonic() + float(deadline_s))
    for attempt in range(retries):
        remaining = None
        if deadline is not None:
            remaining = deadline - time.monotonic()
            if remaining <= 0 and attempt:
                break
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            if remaining is not None:
                # bound the dial itself too: a SYN into a partition
                # otherwise blocks for the kernel's connect timeout
                s.settimeout(max(0.01, remaining))
            s.connect((host, port))
            s.settimeout(None)
            return Conn(s)
        except OSError as e:
            # Close the failed socket before sleeping: each refused dial
            # otherwise leaks an fd for the lifetime of the retry loop
            # (60 retries x N clients = real fd pressure).
            s.close()
            last = e
            obs.counter("transport_connect_retries_total",
                        "failed connect() dial attempts",
                        labels=("reason",)).labels(
                            reason=_dial_failure_reason(e)).inc()
            cap = min(max_interval, retry_interval * (2.0 ** attempt))
            sleep = random.uniform(0.0, cap)
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                sleep = min(sleep, remaining)
            time.sleep(sleep)
    raise ConnectionError(f"could not connect to {host}:{port}: {last}")
