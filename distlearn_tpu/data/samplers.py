"""Batch samplers — parity with torch-dataset's ``sampledBatcher`` samplers as
used by the reference:

* ``permutation`` — fresh shuffle each epoch (examples/mnist.lua:31-40).
* ``label-uniform`` — each draw picks a uniformly random label, then a random
  example of that label (examples/cifar10.lua:53-72, examples/Data.lua:21) —
  class-balanced batches regardless of label skew in the shard.

Samplers yield index arrays; the batcher gathers and (optionally) runs a
``processor`` transform — the reference's clean-env processor fn becomes a
plain Python callable here.
"""

from __future__ import annotations

from typing import Callable, Iterator

import numpy as np


class PermutationSampler:
    """Epoch = one pass over a fresh permutation (ref examples/mnist.lua:31-40)."""

    def __init__(self, n: int, seed: int = 0):
        self.n = n
        self._rng = np.random.RandomState(seed)

    def epoch(self, batch_size: int) -> Iterator[np.ndarray]:
        perm = self._rng.permutation(self.n)
        for i in range(0, self.n - batch_size + 1, batch_size):
            yield perm[i:i + batch_size]


class LabelUniformSampler:
    """Label-balanced draws (ref examples/Data.lua:21 'label-uniform').

    An "epoch" is size//batch_size batches, matching the reference's epoch
    accounting (torch-dataset keeps epoch length = shard size / batch)."""

    def __init__(self, labels: np.ndarray, seed: int = 0):
        self.labels = np.asarray(labels)
        self.n = len(self.labels)
        self.classes = np.unique(self.labels)
        # Ragged per-class index table, padded square for vectorized gathers.
        by_class = [np.flatnonzero(self.labels == c) for c in self.classes]
        self._lens = np.array([len(ix) for ix in by_class])
        pad = self._lens.max()
        self._table = np.stack([np.pad(ix, (0, pad - len(ix)), mode="wrap")
                                for ix in by_class])
        self._rng = np.random.RandomState(seed)

    def epoch(self, batch_size: int) -> Iterator[np.ndarray]:
        for _ in range(self.n // batch_size):
            cpos = self._rng.randint(len(self.classes), size=batch_size)
            j = (self._rng.random(batch_size) * self._lens[cpos]).astype(np.int64)
            yield self._table[cpos, j]


def make_sampler(kind: str, labels: np.ndarray, seed: int = 0):
    """Factory keyed by the reference's sampler-name strings."""
    if kind == "permutation":
        return PermutationSampler(len(labels), seed)
    if kind in ("label-uniform", "label_uniform"):
        return LabelUniformSampler(labels, seed)
    raise ValueError(f"unknown sampler kind: {kind!r}")
