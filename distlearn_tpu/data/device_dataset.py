"""Device-resident datasets — batches gathered ON the accelerator.

The reference's torch-dataset has a ``cuda`` batcher flag that lands each
batch directly in GPU memory (examples/Data.lua:27, consumed by the EASGD
trio).  The TPU-native upgrade goes further: upload the WHOLE dataset to
device memory once, then each step transfers only the batch's int32 index
vector (a few hundred bytes) and gathers the batch with an on-device
``jnp.take``.  On a remote-attached chip this removes the per-step
megabytes-over-the-wire that otherwise dominate small-model step time
(measured on the CIFAR-10 example: per-step host batch upload capped it at
~8 steps/s while the compute-bound rate is ~300).

Fits-in-HBM datasets only (MNIST/CIFAR-scale: tens to hundreds of MB);
streaming sets keep using the host prefetch pipeline (data/prefetch.py).
"""

from __future__ import annotations

from typing import Iterator

import numpy as np

import jax
import jax.numpy as jnp


class DeviceDataset:
    """(x, y) resident in device memory; ``gather`` batches by index.

    ``sharding``: optional ``jax.sharding.Sharding`` for the RESIDENT
    copies (default: single-device / replicated placement as jax chooses).
    ``out_sharding``: sharding for gathered BATCHES — pass the data-axis
    sharding of the train step so the gathered batch lands pre-sharded.
    """

    def __init__(self, x: np.ndarray, y: np.ndarray, num_classes: int,
                 sharding=None, out_sharding=None):
        # device_put straight from host numpy: one transfer, already in the
        # resident sharding (no intermediate default-device copy)
        put = (lambda a: jax.device_put(a, sharding)) if sharding is not None \
            else jax.device_put
        self.x = put(np.ascontiguousarray(x))
        self.y = put(np.ascontiguousarray(y))
        self.num_classes = num_classes
        out = (out_sharding, out_sharding) if out_sharding is not None \
            else None
        self._gather = jax.jit(
            lambda xs, ys, idx: (jnp.take(xs, idx, axis=0),
                                 jnp.take(ys, idx, axis=0)),
            out_shardings=out)

    @property
    def size(self) -> int:
        return int(self.y.shape[0])

    def batches_per_epoch(self, batch_size: int) -> int:
        return self.size // batch_size

    def gather(self, idx: np.ndarray):
        """One batch in ONE dispatch: host→device transfer is just the
        index vector."""
        idx_dev = jax.device_put(np.ascontiguousarray(idx, np.int32))
        return self._gather(self.x, self.y, idx_dev)

    def batches(self, sampler, batch_size: int) -> Iterator[tuple]:
        """One epoch of device-resident batches via a data/samplers.py
        sampler (permutation, label-uniform, ...)."""
        for idx in sampler.epoch(batch_size):
            yield self.gather(idx)
