"""Partitioned in-memory datasets.

Reference semantics being reproduced (torch-dataset as consumed by the
examples):

* ``partition / partitions`` — each node owns an equal contiguous shard of the
  index space (examples/mnist.lua:26-29: ``partition = opt.nodeIndex,
  partitions = opt.numNodes``).
* per-node batch size ``ceil(batchSize / numNodes)`` (examples/cifar10.lua:36).
* the dataset hands out batches via a sampler (see samplers.py).

TPU-native: a partition is keyed by ``jax.process_index()`` on multi-host, or
an explicit ``partition`` arg for single-host multi-node simulation.  Data
stays in host numpy; batches stream to device via prefetch.py.

No-egress environment: loaders accept local ``.npz`` files; ``synthetic_*``
generators provide MNIST/CIFAR-shaped data with a *learnable* class signal so
convergence tests and benchmarks are meaningful without downloads.
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass

import numpy as np


@dataclass
class Dataset:
    """An in-memory partition of (x, y) examples.

    ``x``: float32 [n, ...] features (NHWC for images); ``y``: int32 [n].
    """
    x: np.ndarray
    y: np.ndarray
    num_classes: int

    @property
    def size(self) -> int:
        return len(self.y)

    def batches_per_epoch(self, batch_size: int) -> int:
        return self.size // batch_size


def make_dataset(x: np.ndarray, y: np.ndarray, num_classes: int,
                 partition: int = 0, partitions: int = 1) -> Dataset:
    """Slice out this node's contiguous shard (ref: torch-dataset
    ``partition``/``partitions``, examples/mnist.lua:26-29).  0-based
    ``partition`` (the reference's nodeIndex is 1-based)."""
    if not 0 <= partition < partitions:
        raise ValueError(f"partition={partition} out of range [0,{partitions})")
    n = len(y)
    per = n // partitions
    lo = partition * per
    hi = n if partition == partitions - 1 else lo + per
    return Dataset(x=np.asarray(x[lo:hi], np.float32),
                   y=np.asarray(y[lo:hi], np.int32),
                   num_classes=num_classes)


def per_node_batch_size(global_batch: int, num_nodes: int) -> int:
    """ceil(B/N) — examples/cifar10.lua:36."""
    return math.ceil(global_batch / num_nodes)


def load_npz(path: str, x_key: str = "x", y_key: str = "y",
             num_classes: int | None = None) -> tuple[np.ndarray, np.ndarray, int]:
    """Load a dataset from a local .npz (no-egress replacement for the
    reference's $HOME-prefixed dataset files, examples/Data.lua:7-8)."""
    with np.load(os.path.expanduser(path)) as z:
        x = np.asarray(z[x_key], np.float32)
        y = np.asarray(z[y_key], np.int32)
    if num_classes is None:
        num_classes = int(y.max()) + 1
    return x, y, num_classes


def _smooth_templates(trng, num: int, shape: tuple[int, ...]) -> np.ndarray:
    """``num`` spatially-smooth unit-RMS templates (coarse noise upsampled
    4x) — shared by the easy class-template set and the hard two-factor
    set so "same smooth-template recipe" stays true by construction."""
    h, w = shape[0], shape[1]
    rest = shape[2:]
    coarse = trng.randn(num, max(1, -(-h // 4)), max(1, -(-w // 4)),
                        *rest).astype(np.float32)
    t = np.repeat(np.repeat(coarse, 4, axis=1), 4, axis=2)[:, :h, :w]
    return t / np.sqrt((t ** 2).mean(axis=tuple(range(1, t.ndim)),
                                     keepdims=True))


def _synthetic_classification(n: int, shape: tuple[int, ...], num_classes: int,
                              seed: int, signal: float = 8.0):
    """Class-conditional Gaussian images: each class has a fixed random
    template; examples are template*signal + noise.

    Templates are SPATIALLY SMOOTH (low-frequency blobs: coarse noise
    upsampled 4x), not per-pixel white noise — white-noise class signal is
    near-invisible to a conv+pool architecture (pooling destroys the phase
    the matched filter needs), so examples would train without learning.
    Smooth blobs make the set image-like: convnets demonstrably learn it,
    and it stays non-trivial under noise.
    """
    rng = np.random.RandomState(seed)
    # Templates come from a FIXED seed, independent of the sampling seed:
    # train and test draws (different seeds) must share the same class
    # structure or held-out accuracy is structurally stuck at chance.
    trng = np.random.RandomState(0x5EED ^ num_classes ^ (shape[0] << 8))
    templates = _smooth_templates(trng, num_classes, shape)
    y = rng.randint(0, num_classes, size=n).astype(np.int32)
    x = templates[y] * (signal / np.sqrt(np.prod(shape))) \
        + rng.randn(n, *shape).astype(np.float32) * 0.5
    return x.astype(np.float32), y


def synthetic_mnist(n: int = 4096, seed: int = 0):
    """MNIST-shaped [n,32,32,1] synthetic set (torch MNIST ships 32x32 —
    the reference reshapes to 1x32x32, examples/mnist.lua:53)."""
    x, y = _synthetic_classification(n, (32, 32, 1), 10, seed)
    return x, y, 10


def synthetic_cifar10(n: int = 4096, seed: int = 0):
    """CIFAR-shaped [n,32,32,3] synthetic set."""
    x, y = _synthetic_classification(n, (32, 32, 3), 10, seed)
    return x, y, 10


def synthetic_hard(n: int, shape: tuple[int, ...] = (32, 32, 3),
                   num_classes: int = 10, seed: int = 0,
                   signal: float = 8.0, label_noise: float = 0.05,
                   return_latents: bool = False):
    """A synthetic set that is NOT linearly separable by construction —
    the honest companion to :func:`_synthetic_classification`, whose
    class-conditional Gaussians a matched filter solves to ~1.0 accuracy
    (so every accuracy row looks perfect regardless of training quality).

    Each example composes TWO latent smooth templates: factor ``a`` and
    factor ``b`` (``num_classes`` choices each), and the label is
    ``(a + b) mod num_classes``.  Every class therefore mixes
    ``num_classes`` modes whose MEAN is identical across classes (each
    factor value appears in every class equally often), so any linear
    model — matched filter, logistic regression on pixels — sits at
    chance; decoding requires recovering both factors and combining them
    nonlinearly, which a convnet does.  ``label_noise`` flips that
    fraction of labels uniformly, making the best reachable accuracy
    ``~(1 - label_noise * (C-1)/C)`` — a visible, meaningful ceiling
    below 1.0.

    Returns ``(x, y)`` (+ ``(a, b)`` latents with ``return_latents`` for
    tests).  Same smooth-template recipe as the easy set, so convnets
    remain the right architecture class.
    """
    rng = np.random.RandomState(seed)
    C = num_classes
    h, w = shape[0], shape[1]
    rest = shape[2:]

    def make_templates(tag):
        return _smooth_templates(
            np.random.RandomState(0xA5EED ^ tag ^ C ^ (h << 8)), C, shape)

    ta, tb = make_templates(1), make_templates(2)
    a = rng.randint(0, C, size=n).astype(np.int32)
    b = rng.randint(0, C, size=n).astype(np.int32)
    y = ((a + b) % C).astype(np.int32)
    amp = signal / np.sqrt(np.prod(shape))
    x = (ta[a] + tb[b]) * amp \
        + rng.randn(n, *shape).astype(np.float32) * 0.5
    if label_noise > 0:
        flip = rng.rand(n) < label_noise
        y = np.where(flip, rng.randint(0, C, size=n).astype(np.int32), y)
    if return_latents:
        return x.astype(np.float32), y, a, b
    return x.astype(np.float32), y


def synthetic_hard_cifar10(n: int = 4096, seed: int = 0,
                           label_noise: float = 0.05):
    """CIFAR-shaped non-separable synthetic set (see
    :func:`synthetic_hard`)."""
    x, y = synthetic_hard(n, (32, 32, 3), 10, seed,
                          label_noise=label_noise)
    return x, y, 10


def synthetic_imagenet(n: int = 256, image_size: int = 224,
                       num_classes: int = 1000, seed: int = 0):
    """ImageNet-shaped [n,S,S,3] synthetic set for the ResNet-50 stretch
    config (BASELINE.md row 5; no dataset downloads in a zero-egress env)."""
    x, y = _synthetic_classification(n, (image_size, image_size, 3),
                                     num_classes, seed)
    return x, y, num_classes
