"""Partitioned datasets, samplers, and device prefetch — the TPU-native
replacement for torch-dataset (reference call sites: examples/mnist.lua:26-40,
examples/cifar10.lua:53-72, examples/Data.lua)."""

from distlearn_tpu.data.dataset import (Dataset, make_dataset, load_npz,
                                        synthetic_hard,
                                        synthetic_hard_cifar10,
                                        synthetic_mnist, synthetic_cifar10,
                                        synthetic_imagenet)
from distlearn_tpu.data.samplers import (PermutationSampler, LabelUniformSampler,
                                         make_sampler)
from distlearn_tpu.data.prefetch import prefetch_to_device, batch_iterator
from distlearn_tpu.data.device_dataset import DeviceDataset

__all__ = [
    "Dataset", "make_dataset", "load_npz", "synthetic_mnist",
    "synthetic_cifar10", "synthetic_imagenet", "synthetic_hard",
    "synthetic_hard_cifar10",
    "PermutationSampler", "LabelUniformSampler", "make_sampler",
    "prefetch_to_device", "batch_iterator", "DeviceDataset",
]
