"""Host→device batch streaming with async prefetch.

The reference's torch-dataset runs a native thread pool that stages batches
(and can land them directly on GPU via the ``cuda`` batcher flag,
examples/Data.lua:27).  TPU-native equivalent: ``jax.device_put`` is async —
it returns immediately with the transfer in flight — so a depth-k prefetch
queue overlaps host batch assembly + PCIe/infeed with device compute.
"""

from __future__ import annotations

import collections
from typing import Any, Callable, Iterator

import jax
import numpy as np

from distlearn_tpu import obs


def batch_iterator(dataset, sampler, batch_size: int,
                   processor: Callable | None = None) -> Iterator[tuple]:
    """Yield (x, y) numpy batches for one epoch (gather + optional processor —
    the reference's sampledBatcher processor fn, examples/cifar10.lua:58-66)."""
    for idx in sampler.epoch(batch_size):
        x, y = dataset.x[idx], dataset.y[idx]
        if processor is not None:
            x, y = processor(x, y)
        yield x, y


def prefetch_to_device(it: Iterator, size: int = 2, sharding=None) -> Iterator:
    """Wrap a host batch iterator with a depth-``size`` device prefetch queue.

    ``sharding``: optional jax sharding applied on transfer (e.g. batch axis
    split over the data mesh axis so each device receives only its shard).
    """
    queue = collections.deque()
    # depth as seen at each yield: a gauge stuck at 0 means the consumer
    # is outrunning batch assembly (compute is starved on infeed)
    depth = obs.gauge("data_prefetch_depth",
                      "batches in flight in the device prefetch queue")

    def _put(batch):
        if sharding is None:
            return jax.tree_util.tree_map(jax.device_put, batch)
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(x, sharding), batch)

    for batch in it:
        queue.append(_put(batch))
        if len(queue) >= size:
            depth.set(len(queue) - 1)
            yield queue.popleft()
    while queue:
        depth.set(len(queue) - 1)
        yield queue.popleft()
