"""Env-gated persistent XLA compilation cache.

The serve path's steady-state dispatch overhead is budgeted statically
(DL207, docs/LINT.md), but a fresh process still pays the full XLA
compile of every tick/prefill/train program on its FIRST dispatch —
tens of seconds of single-core work that dwarfs any per-dispatch win.
Pointing ``DISTLEARN_TPU_COMPILE_CACHE`` at a directory persists the
compiled executables across process restarts: a warm start deserializes
instead of recompiling, cutting the first-dispatch tail to load time
(measured numbers next to the DL207 estimate in docs/LINT.md).

Opt-in by environment variable rather than default-on because the cache
directory is a shared mutable resource: concurrent first-runs race
benignly (last write wins) but tests that assert compile counts, and
sandboxes with read-only checkouts, must be able to leave it off.
"""

from __future__ import annotations

import os

ENV_VAR = "DISTLEARN_TPU_COMPILE_CACHE"

_enabled: str | None = None


def enable_compile_cache(path: str | None = None) -> str | None:
    """Turn on jax's persistent compile cache when ``path`` (or the
    ``DISTLEARN_TPU_COMPILE_CACHE`` env var) names a directory.

    Returns the cache directory in effect, or ``None`` when unset or
    when jax refuses the config (the cache is an optimization only —
    never an error).  Idempotent: repeat calls with the same resolved
    path are no-ops, so every entry point (examples ``setup_platform``,
    ``DecodeEngine``) can call it unconditionally.
    """
    global _enabled
    path = path or os.environ.get(ENV_VAR)
    if not path:
        return None
    path = os.path.abspath(path)
    if _enabled == path:
        return path
    try:
        import jax
        jax.config.update("jax_compilation_cache_dir", path)
        # persist everything, however fast the compile: the CPU test
        # programs compile in <1s yet still dominate a cold example run
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        # 1, not 0: the cache treats 0 as "unset" and substitutes its
        # own (larger) default at initialization
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 1)
        # the cache module latches enabled/disabled at the FIRST
        # compile; if anything already compiled (model init before the
        # engine ctor), the config update alone is inert — reset back
        # to pristine so the next compile re-initializes with the dir
        from jax.experimental.compilation_cache import (
            compilation_cache as _cc)
        _cc.reset_cache()
    except Exception:  # noqa: BLE001 — optimization only
        return None
    _enabled = path
    return path
