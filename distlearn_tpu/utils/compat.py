"""JAX version compatibility shims.

The framework targets the modern JAX surface (``jax.shard_map`` with
``check_vma``, promoted in jax 0.7); CI containers may pin older releases
where ``shard_map`` still lives in ``jax.experimental.shard_map`` and the
replication-check knob is called ``check_rep``.  Every internal call site
imports :func:`shard_map` from here so the whole library runs on either
API without scattering version branches through the builders.

``install()`` additionally publishes the shim as ``jax.shard_map`` when the
attribute is missing, so reference-style scripts and tests written against
the modern spelling keep working on an old pin.  It never overwrites a real
``jax.shard_map``.
"""

from __future__ import annotations

import jax

__all__ = ["shard_map", "axis_size", "install"]

# Resolve the underlying implementation ONCE at import: after install()
# publishes the shim as ``jax.shard_map``, a late getattr would find the
# shim itself and recurse.
_NATIVE = getattr(jax, "shard_map", None)
if _NATIVE is None:
    from jax.experimental.shard_map import shard_map as _LEGACY
else:
    _LEGACY = None


def shard_map(f=None, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` with the modern signature on any supported jax.

    On jax >= 0.7 this is a passthrough; on older releases it adapts to
    ``jax.experimental.shard_map.shard_map`` (``check_vma`` -> ``check_rep``).
    Supports the same partial-application style as the real API
    (``shard_map(mesh=..., ...)`` returning a decorator).
    """
    if f is None:
        return lambda g: shard_map(g, mesh=mesh, in_specs=in_specs,
                                   out_specs=out_specs, check_vma=check_vma)
    if _NATIVE is not None:
        return _NATIVE(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                       check_vma=check_vma)
    return _LEGACY(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=check_vma)


def axis_size(axis_name) -> int:
    """Static size of a bound mesh axis (``lax.axis_size`` on modern jax).

    Old releases have no ``lax.axis_size``; there ``lax.psum(1, axis)`` of a
    Python scalar constant-folds to the static axis size, which is what the
    callers need (they branch on it in Python control flow)."""
    from jax import lax
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def install() -> None:
    """Publish the shim as ``jax.shard_map`` if (and only if) absent."""
    if not hasattr(jax, "shard_map"):
        jax.shard_map = shard_map
