"""JAX version compatibility shims.

The framework targets the modern JAX surface (``jax.shard_map`` with
``check_vma``, promoted in jax 0.7); CI containers may pin older releases
where ``shard_map`` still lives in ``jax.experimental.shard_map`` and the
replication-check knob is called ``check_rep``.  Every internal call site
imports :func:`shard_map` from here so the whole library runs on either
API without scattering version branches through the builders.

``install()`` additionally publishes the shim as ``jax.shard_map`` when the
attribute is missing, so reference-style scripts and tests written against
the modern spelling keep working on an old pin.  It never overwrites a real
``jax.shard_map``.
"""

from __future__ import annotations

import jax

__all__ = ["shard_map", "axis_size", "install", "lower_compiled",
           "compiled_cost_analysis", "compiled_memory_stats"]

# Resolve the underlying implementation ONCE at import: after install()
# publishes the shim as ``jax.shard_map``, a late getattr would find the
# shim itself and recurse.
_NATIVE = getattr(jax, "shard_map", None)
if _NATIVE is None:
    from jax.experimental.shard_map import shard_map as _LEGACY
else:
    _LEGACY = None


def shard_map(f=None, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` with the modern signature on any supported jax.

    On jax >= 0.7 this is a passthrough; on older releases it adapts to
    ``jax.experimental.shard_map.shard_map`` (``check_vma`` -> ``check_rep``).
    Supports the same partial-application style as the real API
    (``shard_map(mesh=..., ...)`` returning a decorator).
    """
    if f is None:
        return lambda g: shard_map(g, mesh=mesh, in_specs=in_specs,
                                   out_specs=out_specs, check_vma=check_vma)
    if _NATIVE is not None:
        return _NATIVE(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                       check_vma=check_vma)
    return _LEGACY(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=check_vma)


def axis_size(axis_name) -> int:
    """Static size of a bound mesh axis (``lax.axis_size`` on modern jax).

    Old releases have no ``lax.axis_size``; there ``lax.psum(1, axis)`` of a
    Python scalar constant-folds to the static axis size, which is what the
    callers need (they branch on it in Python control flow)."""
    from jax import lax
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def install() -> None:
    """Publish the shim as ``jax.shard_map`` if (and only if) absent."""
    if not hasattr(jax, "shard_map"):
        jax.shard_map = shard_map


# --- compiled-executable introspection (lint/cost.py) -----------------------
#
# The Compiled surface moved around across jax releases: ``cost_analysis``
# returns a list of dicts on some jaxlib versions and a bare dict on others,
# ``memory_analysis`` may be missing entirely on exotic backends, and old
# wrappers spell ``lower`` differently for non-jit callables.  The cost
# analyzer goes through these three helpers so it never touches the raw
# surface.

def lower_compiled(fn, args):
    """Lower ``fn(*args)`` and compile it; wraps bare callables in jit.

    Returns ``(lowered, compiled)``.  ``args`` may be abstract
    (:class:`jax.ShapeDtypeStruct`) — nothing is executed.
    """
    lower = getattr(fn, "lower", None)
    if lower is None:
        lower = jax.jit(fn).lower
    lowered = lower(*args)
    return lowered, lowered.compile()


def compiled_cost_analysis(compiled) -> dict:
    """``compiled.cost_analysis()`` normalized to one flat dict.

    jaxlib <= 0.4.x returns a single-element list of dicts (one per
    partition, all identical under SPMD); newer releases return the dict
    directly.  Returns ``{}`` when the backend offers no analysis."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca) if ca else {}


def compiled_memory_stats(compiled) -> dict | None:
    """Byte-level memory stats of a compiled executable, or None.

    Normalizes ``compiled.memory_analysis()`` (a ``CompiledMemoryStats``
    object on XLA backends) to a plain dict with ``argument``, ``output``,
    ``temp``, ``alias``, ``generated_code`` byte counts plus a derived
    ``peak`` (arguments + outputs + temporaries, minus donated aliases —
    the live-at-once footprint the budget lockfiles gate)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    get = lambda attr: int(getattr(ma, attr + "_size_in_bytes", 0) or 0)
    stats = {
        "argument": get("argument"),
        "output": get("output"),
        "temp": get("temp"),
        "alias": get("alias"),
        "generated_code": get("generated_code"),
    }
    if not any(stats.values()):
        return None
    stats["peak"] = max(0, stats["argument"] + stats["output"]
                        + stats["temp"] - stats["alias"])
    return stats
