"""Role-colored structured logging — colorPrint parity
(lua/colorPrint.lua: printServer red, printClient blue+node id), plus the
root-only-print pattern (examples/mnist.lua:20-23: non-root nodes silence
print/progress) and a CSV/JSONL metrics logger replacing optim.Logger +
gnuplot (examples/EASGD_tester.lua:47,161-165).
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, IO

_RED = "\033[31m"
_BLUE = "\033[34m"
_GREEN = "\033[32m"
_RESET = "\033[0m"

_verbose = True


def set_verbose(on: bool):
    """colorPrint stubs to no-ops when --verbose unset
    (examples/EASGD_server.lua:52-56)."""
    global _verbose
    _verbose = on


def _tty(stream: IO) -> bool:
    return hasattr(stream, "isatty") and stream.isatty()


def _emit(color: str, tag: str, *args):
    if not _verbose:
        return
    msg = " ".join(str(a) for a in args)
    if _tty(sys.stdout):
        print(f"{color}{tag}{_RESET} {msg}")
    else:
        print(f"{tag} {msg}")


def print_server(*args):
    """Ref ``printServer`` (lua/colorPrint.lua:3-9)."""
    _emit(_RED, "[server]", *args)


def print_client(node: int, *args):
    """Ref ``printClient`` (lua/colorPrint.lua:11-17)."""
    _emit(_BLUE, f"[client {node}]", *args)


def print_tester(*args):
    _emit(_GREEN, "[tester]", *args)


def root_print(node_index: int):
    """Return a print fn that is a no-op off the root node
    (ref examples/mnist.lua:20-23 overwrite of ``print``)."""
    if node_index == 0:
        return print
    return lambda *a, **k: None


class MetricsLogger:
    """JSONL metrics log — optim.Logger replacement
    (ref examples/EASGD_tester.lua:40-47,161-165; plots become a JSONL any
    tool can render)."""

    def __init__(self, path: str | None = None, names: tuple = ()):
        self.path = path
        self._fh = None
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._fh = open(path, "a")
        self.names = names

    def add(self, **metrics: Any):
        rec = {"ts": time.time(), **metrics}
        if self._fh:
            self._fh.write(json.dumps(rec) + "\n")
            self._fh.flush()
        return rec

    def close(self):
        if self._fh:
            self._fh.close()
            self._fh = None
