"""Backend pinning helpers.

Session environments may pre-import jax pinned to an attached TPU (a
sitecustomize .pth hook), which makes ``JAX_PLATFORMS`` env vars a no-op;
and ``XLA_FLAGS`` may already carry a stale
``xla_force_host_platform_device_count``.  Every entry point that needs a
virtual CPU mesh (tests, examples, bench probes, the driver's multichip
dryrun) therefore needs the same two steps, centralized here: replace the
flag, then force the platform through the config knob.  Call BEFORE any
device query.
"""

from __future__ import annotations

import os


def set_host_device_count(n: int) -> None:
    """Set ``--xla_force_host_platform_device_count=n``, replacing any
    existing value (a pre-set flag must not silently override the caller's
    requested count)."""
    flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append(f"--xla_force_host_platform_device_count={n}")
    os.environ["XLA_FLAGS"] = " ".join(flags)


def force_cpu(num_devices: int | None = None) -> None:
    """Pin the CPU backend (reliably, via the config knob), optionally with
    ``num_devices`` virtual devices."""
    if num_devices is not None:
        set_host_device_count(num_devices)
    import jax
    jax.config.update("jax_platforms", "cpu")
