"""Training metrics — TPU-native rebuild of optim.ConfusionMatrix as the
reference uses it (examples/mnist.lua:95,110,120-125, cifar10.lua:203,234):
a device-side [C,C] count matrix updated inside the jitted step and made
globally consistent by summing across nodes (the reference allreduces
``confusionMatrix.mat`` every 1000 steps — examples/mnist.lua:122).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def init_confusion(num_classes: int) -> jax.Array:
    return jnp.zeros((num_classes, num_classes), jnp.int32)


def update_confusion(cm: jax.Array, log_probs: jax.Array, labels: jax.Array
                     ) -> jax.Array:
    """cm[target, prediction] += 1 per example (optim.ConfusionMatrix
    convention: rows = targets, cols = predictions).  Pure; jit-safe."""
    preds = jnp.argmax(log_probs, axis=-1)
    num_classes = cm.shape[0]
    idx = labels * num_classes + preds
    flat = jnp.zeros(num_classes * num_classes, cm.dtype).at[idx].add(1)
    return cm + flat.reshape(num_classes, num_classes)


def all_reduce_confusion(cm: jax.Array, axis_name: str) -> jax.Array:
    """Global matrix across nodes (ref examples/mnist.lua:122)."""
    return lax.psum(cm, axis_name)


def total_valid(cm: np.ndarray) -> float:
    """optim.ConfusionMatrix ``totalValid``: trace / total — global accuracy."""
    cm = np.asarray(cm)
    tot = cm.sum()
    return float(np.trace(cm) / tot) if tot else 0.0


def average_valid(cm: np.ndarray) -> float:
    """optim.ConfusionMatrix ``averageValid``: mean per-class recall."""
    cm = np.asarray(cm, np.float64)
    row = cm.sum(axis=1)
    recalls = np.divide(np.diag(cm), row, out=np.zeros_like(row), where=row > 0)
    present = row > 0
    return float(recalls[present].mean()) if present.any() else 0.0


def format_confusion(cm: np.ndarray) -> str:
    """Human-readable summary (stand-in for torch's __tostring__ table)."""
    return (f"ConfusionMatrix: acc={total_valid(cm) * 100:.2f}% "
            f"avg-class={average_valid(cm) * 100:.2f}% n={int(np.asarray(cm).sum())}")
