"""Tracing / profiling — the reference has none beyond xlua.progress bars
(SURVEY.md §5); here: ``jax.profiler`` trace capture plus lightweight
per-step wall-clock timers suitable for the bench harness.
"""

from __future__ import annotations

import contextlib
import time

import jax
import numpy as np


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture an XLA profiler trace viewable in TensorBoard/Perfetto."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StepTimer:
    """Wall-clock step timing with warmup discard.

    Call ``tick()`` around synchronized step boundaries (the caller is
    responsible for ``block_until_ready`` on the final step of a window —
    async dispatch means intermediate ticks measure dispatch, which is the
    desired steady-state number).
    """

    def __init__(self, warmup: int = 2):
        self.warmup = warmup
        self._times: list[float] = []
        self._last: float | None = None

    def tick(self, steps: int = 1):
        """``steps``: how many training steps the interval since the last
        tick covered (>1 for the scanned multi-step trainers); the recorded
        interval is normalized to per-step time."""
        now = time.perf_counter()
        if self._last is not None:
            self._times.append((now - self._last) / max(1, steps))
        self._last = now

    def reset_window(self):
        """Drop the in-progress interval — call after an out-of-band
        ``block_until_ready`` (checkpoint, profiler boundary) so the queue
        drain isn't recorded as one giant step."""
        self._last = None

    @property
    def steps(self) -> int:
        return max(0, len(self._times) - self.warmup)

    def mean(self) -> float:
        xs = self._times[self.warmup:]
        return float(np.mean(xs)) if xs else float("nan")

    def p50(self) -> float:
        xs = self._times[self.warmup:]
        return float(np.median(xs)) if xs else float("nan")

    def steps_per_sec(self) -> float:
        m = self.mean()
        return 1.0 / m if m and m == m and m > 0 else float("nan")


class Progress:
    """xlua.progress stand-in: single-line progress meter on the root node."""

    def __init__(self, total: int, enabled: bool = True, width: int = 30):
        self.total, self.enabled, self.width = total, enabled, width

    def update(self, i: int, suffix: str = ""):
        if not self.enabled or self.total <= 0:
            return
        frac = min(1.0, (i + 1) / self.total)
        filled = int(self.width * frac)
        bar = "=" * filled + ">" + "." * (self.width - filled - 1)
        end = "\n" if i + 1 >= self.total else "\r"
        print(f" [{bar[:self.width]}] {i + 1}/{self.total} {suffix}",
              end=end, flush=True)
