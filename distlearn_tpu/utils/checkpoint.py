"""Checkpoint / resume — a first-class feature the reference only sketches
(all its checkpoint code is commented out: examples/EASGD_server.lua:37-48,
examples/EASGD_tester.lua:36-47; SURVEY.md §5 calls for params+center+step
checkpointing as first-class).

Format: one ``.npz`` per checkpoint holding every pytree leaf (flattened
key-path names) + a JSON sidecar with the treedef and scalar metadata.
Self-contained, dependency-free, works for params / EA center / optimizer
state alike.  Writes are atomic (tmp + rename) so a preempted TPU job never
sees a torn checkpoint.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any

import jax
import numpy as np

PyTree = Any

_SEP = "/"


def _flatten(tree: PyTree) -> dict[str, np.ndarray]:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = _SEP.join(_path_elem(p) for p in path)
        flat[key] = np.asarray(jax.device_get(leaf))
    return flat


def _path_elem(p) -> str:
    if hasattr(p, "key"):
        return str(p.key)
    if hasattr(p, "idx"):
        return str(p.idx)
    if hasattr(p, "name"):
        return str(p.name)
    return str(p)


def _vdtype_names(flat: dict[str, np.ndarray]) -> dict[str, str]:
    """npz round-trips only NATIVE numpy dtypes: an ml_dtypes leaf
    (bfloat16, float8_*) loads back as raw void (``|V2``).  Record the
    true dtype name per affected key so restore can view the bytes back
    — without this, bf16 train states (mixed-precision working params)
    fail restore with a ``|V2 != bfloat16`` mismatch.  Structured
    (record) dtypes are also kind 'V' but round-trip npz natively —
    only field-less extension dtypes are recorded."""
    return {k: a.dtype.name for k, a in flat.items()
            if a.dtype.kind == "V" and a.dtype.fields is None}


def _review_vdtype(arr: np.ndarray, want: np.dtype) -> np.ndarray:
    """Bytes-preserving view of a void-loaded array back to its true
    extension dtype (same itemsize — a pure reinterpretation).
    Structured arrays pass through untouched."""
    want = np.dtype(want)
    if arr.dtype == want or arr.dtype.kind != "V" \
            or arr.dtype.fields is not None \
            or arr.dtype.itemsize != want.itemsize:
        return arr
    return arr.view(want)


def _atomic_savez(directory: str, path: str, meta: dict,
                  flat: dict[str, np.ndarray]) -> None:
    """tmp-write + rename so a preempted job never sees a torn file."""
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as fh:
            np.savez(fh, __meta__=json.dumps(meta), **flat)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def save_checkpoint(directory: str, step: int, tree: PyTree,
                    metadata: dict | None = None, keep: int = 3) -> str:
    """Write ``{directory}/ckpt_{step}.npz`` atomically; prune to ``keep``
    newest.  Returns the checkpoint path."""
    os.makedirs(directory, exist_ok=True)
    flat = _flatten(tree)
    # computed entries LAST: user metadata must not clobber the keys
    # restore correctness depends on (step, keys, vdtypes)
    meta = {**(metadata or {}), "step": int(step),
            "keys": sorted(flat), "vdtypes": _vdtype_names(flat)}
    path = os.path.join(directory, f"ckpt_{step}.npz")
    _atomic_savez(directory, path, meta, flat)
    _prune(directory, keep)
    return path


def _prune(directory: str, keep: int):
    ckpts = sorted(_list_steps(directory))
    for step in ckpts[:-keep] if keep > 0 else []:
        os.unlink(os.path.join(directory, f"ckpt_{step}.npz"))


def _list_steps(directory: str) -> list[int]:
    steps = []
    for name in os.listdir(directory):
        if name.startswith("ckpt_") and name.endswith(".npz"):
            try:
                steps.append(int(name[5:-4]))
            except ValueError:
                pass
    return steps


def latest_step(directory: str) -> int | None:
    steps = _list_steps(directory) if os.path.isdir(directory) else []
    return max(steps) if steps else None


def _index_spec(index, shape) -> list:
    """Serialize an addressable-shard index (tuple of slices) as
    ``[[start, stop], ...]`` with the full extent made explicit."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def save_sharded_checkpoint(directory: str, step: int, tree: PyTree,
                            metadata: dict | None = None, keep: int = 3,
                            process_index: int | None = None) -> str:
    """Pod-scale checkpoint: each process writes ONLY its addressable
    shards to ``ckpt_{step}.shard{process}.npz`` — required for state no
    single host holds (ZeRO-1 optimizer shards, parameter-sharded runs),
    and it parallelizes the write across hosts.  Replicated leaves appear
    in every process's file (assembly overwrites identically).

    Use :func:`restore_sharded_checkpoint` (any host, or offline) to
    reassemble the global arrays.  Pruning runs on process 0 only, skips
    the ``keep`` newest steps, and additionally leaves files younger than
    ``_PRUNE_GRACE_SECS`` untouched so a straggler host mid-write of an
    older step does not lose its peers' files from under it.
    """
    if process_index is None:
        process_index = jax.process_index()
    os.makedirs(directory, exist_ok=True)
    flat: dict[str, np.ndarray] = {}
    shard_meta: dict[str, dict] = {}
    for pathspec, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = _SEP.join(_path_elem(p) for p in pathspec)
        if hasattr(leaf, "addressable_shards"):
            gshape = tuple(int(d) for d in leaf.shape)
            seen_regions: set[tuple] = set()
            k = 0
            for s in leaf.addressable_shards:
                region = _index_spec(s.index, gshape)
                rkey = tuple(map(tuple, region))
                if rkey in seen_regions:
                    continue   # replicated across local devices: store once
                seen_regions.add(rkey)
                skey = f"{key}#{k}"
                flat[skey] = np.asarray(jax.device_get(s.data))
                shard_meta[skey] = {"leaf": key, "index": region}
                k += 1
            shard_meta[f"{key}!"] = {"shape": list(gshape),
                                     "dtype": str(np.dtype(leaf.dtype))}
        else:   # host numpy leaf: whole array, full-extent index
            arr = np.asarray(leaf)
            flat[f"{key}#0"] = arr
            shard_meta[f"{key}#0"] = {
                "leaf": key, "index": _index_spec(
                    tuple(slice(None) for _ in arr.shape), arr.shape)}
            shard_meta[f"{key}!"] = {"shape": list(arr.shape),
                                     "dtype": str(arr.dtype)}
    # computed entries LAST: user metadata must not clobber the keys
    # reassembly depends on (step, process, shards)
    meta = {**(metadata or {}), "step": int(step),
            "process": int(process_index), "shards": shard_meta}
    path = os.path.join(directory, f"ckpt_{step}.shard{process_index}.npz")
    _atomic_savez(directory, path, meta, flat)
    if process_index == 0 and keep > 0:
        import time as _time
        now = _time.time()
        for old in _list_sharded_steps(directory)[:-keep]:
            for name in os.listdir(directory):
                if name.startswith(f"ckpt_{old}.shard") \
                        and name.endswith(".npz"):
                    full = os.path.join(directory, name)
                    try:
                        if now - os.path.getmtime(full) > _PRUNE_GRACE_SECS:
                            os.unlink(full)
                    except OSError:
                        pass   # another process may prune concurrently
    return path


_PRUNE_GRACE_SECS = 300.0   # see save_sharded_checkpoint docstring


def _list_sharded_steps(directory: str) -> list[int]:
    steps = set()
    for name in os.listdir(directory):
        if name.startswith("ckpt_") and ".shard" in name \
                and name.endswith(".npz"):
            try:
                steps.add(int(name[5:name.index(".shard")]))
            except ValueError:
                pass
    return sorted(steps)


def restore_sharded_checkpoint(directory: str, like: PyTree,
                               step: int | None = None
                               ) -> tuple[PyTree, dict]:
    """Reassemble global host arrays from every process's shard file.
    ``like`` supplies the pytree structure (shapes/dtypes validated against
    the recorded globals).  Returns ``(tree_of_numpy, metadata_of_proc0)``.
    """
    if step is None:
        steps = _list_sharded_steps(directory)
        if not steps:
            raise FileNotFoundError(f"no sharded checkpoints in {directory}")
        step = steps[-1]
    files = sorted(name for name in os.listdir(directory)
                   if name.startswith(f"ckpt_{step}.shard")
                   and name.endswith(".npz"))
    if not files:
        raise FileNotFoundError(f"no shard files for step {step}")
    assembled: dict[str, np.ndarray] = {}
    # written regions per leaf (lists of index tuples).  Coverage is
    # validated element-exactly below with a bool mask built ONE leaf at a
    # time (peak extra memory = largest leaf, not the whole tree):
    # replicated regions count once, partially overlapping regions (e.g. a
    # save retried under a different shard layout) cannot double-count,
    # and a genuinely missing shard file always leaves unset bits
    regions: dict[str, list] = {}
    meta0: dict = {}
    for name in files:
        with np.load(os.path.join(directory, name),
                     allow_pickle=False) as z:
            meta = json.loads(str(z["__meta__"]))
            if meta.get("process") == 0:
                meta0 = {k: v for k, v in meta.items() if k != "shards"}
            sm = meta["shards"]
            for skey in z.files:
                if skey == "__meta__" or skey not in sm:
                    continue
                info = sm[skey]
                leaf_key = info["leaf"]
                glob = sm[f"{leaf_key}!"]
                if leaf_key not in assembled:
                    assembled[leaf_key] = np.empty(
                        tuple(glob["shape"]), np.dtype(glob["dtype"]))
                    regions[leaf_key] = []
                idx = tuple(slice(a, b) for a, b in info["index"])
                # extension-dtype shards load as void: view back to the
                # recorded global dtype before assignment
                assembled[leaf_key][idx] = _review_vdtype(
                    z[skey], assembled[leaf_key].dtype)
                regions[leaf_key].append(idx)
    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(like)
    new_leaves = []
    for pathspec, leaf in leaves_with_path:
        key = _SEP.join(_path_elem(p) for p in pathspec)
        if key not in assembled:
            raise KeyError(f"sharded checkpoint missing leaf {key!r}")
        arr = assembled[key]
        mask = np.zeros(arr.shape, np.bool_)
        for idx in regions[key]:
            mask[idx] = True
        covered = int(np.count_nonzero(mask))
        del mask
        if covered < arr.size:
            raise ValueError(
                f"leaf {key!r}: shard files cover {covered} of "
                f"{arr.size} elements — a process's file is missing")
        want_shape = tuple(int(d) for d in np.shape(leaf))
        if arr.shape != want_shape:
            raise ValueError(f"leaf {key!r}: checkpoint shape {arr.shape} "
                             f"!= {want_shape}")
        want_dtype = np.dtype(getattr(leaf, "dtype", None)
                              or np.asarray(leaf).dtype)
        if arr.dtype != want_dtype:
            raise ValueError(
                f"leaf {key!r}: checkpoint dtype {arr.dtype} != "
                f"{want_dtype} (restore into a matching-dtype template, "
                "or cast explicitly)")
        new_leaves.append(arr)
    return jax.tree_util.tree_unflatten(treedef, new_leaves), meta0


class AsyncCheckpointer:
    """Non-blocking checkpointing: snapshot device state to host, then write
    the npz on a worker thread so the train loop never stalls on filesystem
    IO (the orbax ``async_checkpointer`` shape, dependency-free).

    Semantics:

    * :meth:`save` blocks only for the device→host transfer (the snapshot is
      taken at call time — later param updates cannot tear it), then returns;
      the atomic write + prune run on the worker.
    * one in-flight write at a time: a second :meth:`save` first waits for
      the previous write (backpressure rather than unbounded queueing);
    * :meth:`wait` blocks until the last write is durable and re-raises any
      worker error — call it before reading ``latest_step`` or exiting;
    * use as a context manager to guarantee the final wait.
    """

    def __init__(self, directory: str, keep: int = 3):
        import threading
        self.directory = directory
        self.keep = keep
        self._thread: "threading.Thread | None" = None
        self._err: list[BaseException] = []

    def save(self, step: int, tree: PyTree,
             metadata: dict | None = None) -> None:
        import threading
        self.wait()                      # backpressure + surface prior error
        def _snapshot(x):
            # device leaves: device_get already materializes a fresh host
            # array; host numpy leaves come back as-is and must be copied
            # or they would alias the caller's buffer and tear on mutation
            a = jax.device_get(x)
            return np.array(a) if a is x else np.asarray(a)

        host_tree = jax.tree_util.tree_map(_snapshot, tree)

        def _write():
            try:
                save_checkpoint(self.directory, step, host_tree,
                                metadata=metadata, keep=self.keep)
            except BaseException as e:  # noqa: BLE001 — re-raised in wait()
                self._err.append(e)

        self._thread = threading.Thread(target=_write, daemon=True)
        self._thread.start()

    def wait(self) -> None:
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._err:
            raise self._err.pop(0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.wait()
        return False


def restore_checkpoint(directory: str, like: PyTree, step: int | None = None
                       ) -> tuple[PyTree, dict]:
    """Restore into the structure of ``like`` (shape/dtype validated leaf by
    leaf).  ``step=None`` -> newest.  Returns ``(tree, metadata)``."""
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {directory}")
    path = os.path.join(directory, f"ckpt_{step}.npz")
    with np.load(path, allow_pickle=False) as z:
        meta = json.loads(str(z["__meta__"]))
        flat = {k: z[k] for k in z.files if k != "__meta__"}
    for k, name in meta.get("vdtypes", {}).items():
        if k in flat:
            flat[k] = _review_vdtype(flat[k], np.dtype(name))

    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(like)
    new_leaves = []
    for pathspec, leaf in leaves_with_path:
        key = _SEP.join(_path_elem(p) for p in pathspec)
        if key not in flat:
            raise KeyError(f"checkpoint {path} missing leaf {key!r}")
        want = np.asarray(jax.device_get(leaf))
        arr = flat[key]
        if (arr.dtype.kind == "V" and arr.dtype.fields is None
                and key not in meta.get("vdtypes", {})
                and want.dtype == np.dtype("bfloat16")):
            # pre-vdtypes checkpoints carry no record; bfloat16 is the
            # only 2-byte extension dtype, so the view is unambiguous —
            # 1-byte voids (float8 family) stay a LOUD mismatch rather
            # than a silent cross-dtype bit reinterpretation
            arr = _review_vdtype(arr, want.dtype)
        if arr.shape != want.shape:
            raise ValueError(
                f"leaf {key!r}: checkpoint shape {arr.shape} != {want.shape}")
        if arr.dtype != want.dtype:
            raise ValueError(
                f"leaf {key!r}: checkpoint dtype {arr.dtype} != {want.dtype} "
                "(restore into a matching-dtype template, or cast explicitly)")
        new_leaves.append(arr)
    return jax.tree_util.tree_unflatten(treedef, new_leaves), meta
