"""Checkpoint / resume — a first-class feature the reference only sketches
(all its checkpoint code is commented out: examples/EASGD_server.lua:37-48,
examples/EASGD_tester.lua:36-47; SURVEY.md §5 calls for params+center+step
checkpointing as first-class).

Format: one ``.npz`` per checkpoint holding every pytree leaf (flattened
key-path names) + a JSON sidecar with the treedef and scalar metadata.
Self-contained, dependency-free, works for params / EA center / optimizer
state alike.  Writes are atomic (tmp + rename) so a preempted TPU job never
sees a torn checkpoint.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any

import jax
import numpy as np

PyTree = Any

_SEP = "/"


def _flatten(tree: PyTree) -> dict[str, np.ndarray]:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = _SEP.join(_path_elem(p) for p in path)
        flat[key] = np.asarray(jax.device_get(leaf))
    return flat


def _path_elem(p) -> str:
    if hasattr(p, "key"):
        return str(p.key)
    if hasattr(p, "idx"):
        return str(p.idx)
    if hasattr(p, "name"):
        return str(p.name)
    return str(p)


def save_checkpoint(directory: str, step: int, tree: PyTree,
                    metadata: dict | None = None, keep: int = 3) -> str:
    """Write ``{directory}/ckpt_{step}.npz`` atomically; prune to ``keep``
    newest.  Returns the checkpoint path."""
    os.makedirs(directory, exist_ok=True)
    flat = _flatten(tree)
    meta = {"step": int(step), "keys": sorted(flat), **(metadata or {})}
    path = os.path.join(directory, f"ckpt_{step}.npz")
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as fh:
            np.savez(fh, __meta__=json.dumps(meta), **flat)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    _prune(directory, keep)
    return path


def _prune(directory: str, keep: int):
    ckpts = sorted(_list_steps(directory))
    for step in ckpts[:-keep] if keep > 0 else []:
        os.unlink(os.path.join(directory, f"ckpt_{step}.npz"))


def _list_steps(directory: str) -> list[int]:
    steps = []
    for name in os.listdir(directory):
        if name.startswith("ckpt_") and name.endswith(".npz"):
            try:
                steps.append(int(name[5:-4]))
            except ValueError:
                pass
    return steps


def latest_step(directory: str) -> int | None:
    steps = _list_steps(directory) if os.path.isdir(directory) else []
    return max(steps) if steps else None


def restore_checkpoint(directory: str, like: PyTree, step: int | None = None
                       ) -> tuple[PyTree, dict]:
    """Restore into the structure of ``like`` (shape/dtype validated leaf by
    leaf).  ``step=None`` -> newest.  Returns ``(tree, metadata)``."""
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {directory}")
    path = os.path.join(directory, f"ckpt_{step}.npz")
    with np.load(path, allow_pickle=False) as z:
        meta = json.loads(str(z["__meta__"]))
        flat = {k: z[k] for k in z.files if k != "__meta__"}

    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(like)
    new_leaves = []
    for pathspec, leaf in leaves_with_path:
        key = _SEP.join(_path_elem(p) for p in pathspec)
        if key not in flat:
            raise KeyError(f"checkpoint {path} missing leaf {key!r}")
        arr = flat[key]
        want = np.asarray(jax.device_get(leaf))
        if arr.shape != want.shape:
            raise ValueError(
                f"leaf {key!r}: checkpoint shape {arr.shape} != {want.shape}")
        if arr.dtype != want.dtype:
            raise ValueError(
                f"leaf {key!r}: checkpoint dtype {arr.dtype} != {want.dtype} "
                "(restore into a matching-dtype template, or cast explicitly)")
        new_leaves.append(arr)
    return jax.tree_util.tree_unflatten(treedef, new_leaves), meta
