"""Checkpoint / resume — a first-class feature the reference only sketches
(all its checkpoint code is commented out: examples/EASGD_server.lua:37-48,
examples/EASGD_tester.lua:36-47; SURVEY.md §5 calls for params+center+step
checkpointing as first-class).

Format: one ``.npz`` per checkpoint holding every pytree leaf (flattened
key-path names) + a JSON sidecar with the treedef and scalar metadata.
Self-contained, dependency-free, works for params / EA center / optimizer
state alike.  Writes are atomic (tmp + rename) so a preempted TPU job never
sees a torn checkpoint.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any

import jax
import numpy as np

PyTree = Any

_SEP = "/"


def _flatten(tree: PyTree) -> dict[str, np.ndarray]:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = _SEP.join(_path_elem(p) for p in path)
        flat[key] = np.asarray(jax.device_get(leaf))
    return flat


def _path_elem(p) -> str:
    if hasattr(p, "key"):
        return str(p.key)
    if hasattr(p, "idx"):
        return str(p.idx)
    if hasattr(p, "name"):
        return str(p.name)
    return str(p)


def save_checkpoint(directory: str, step: int, tree: PyTree,
                    metadata: dict | None = None, keep: int = 3) -> str:
    """Write ``{directory}/ckpt_{step}.npz`` atomically; prune to ``keep``
    newest.  Returns the checkpoint path."""
    os.makedirs(directory, exist_ok=True)
    flat = _flatten(tree)
    meta = {"step": int(step), "keys": sorted(flat), **(metadata or {})}
    path = os.path.join(directory, f"ckpt_{step}.npz")
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as fh:
            np.savez(fh, __meta__=json.dumps(meta), **flat)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    _prune(directory, keep)
    return path


def _prune(directory: str, keep: int):
    ckpts = sorted(_list_steps(directory))
    for step in ckpts[:-keep] if keep > 0 else []:
        os.unlink(os.path.join(directory, f"ckpt_{step}.npz"))


def _list_steps(directory: str) -> list[int]:
    steps = []
    for name in os.listdir(directory):
        if name.startswith("ckpt_") and name.endswith(".npz"):
            try:
                steps.append(int(name[5:-4]))
            except ValueError:
                pass
    return steps


def latest_step(directory: str) -> int | None:
    steps = _list_steps(directory) if os.path.isdir(directory) else []
    return max(steps) if steps else None


class AsyncCheckpointer:
    """Non-blocking checkpointing: snapshot device state to host, then write
    the npz on a worker thread so the train loop never stalls on filesystem
    IO (the orbax ``async_checkpointer`` shape, dependency-free).

    Semantics:

    * :meth:`save` blocks only for the device→host transfer (the snapshot is
      taken at call time — later param updates cannot tear it), then returns;
      the atomic write + prune run on the worker.
    * one in-flight write at a time: a second :meth:`save` first waits for
      the previous write (backpressure rather than unbounded queueing);
    * :meth:`wait` blocks until the last write is durable and re-raises any
      worker error — call it before reading ``latest_step`` or exiting;
    * use as a context manager to guarantee the final wait.
    """

    def __init__(self, directory: str, keep: int = 3):
        import threading
        self.directory = directory
        self.keep = keep
        self._thread: "threading.Thread | None" = None
        self._err: list[BaseException] = []

    def save(self, step: int, tree: PyTree,
             metadata: dict | None = None) -> None:
        import threading
        self.wait()                      # backpressure + surface prior error
        def _snapshot(x):
            # device leaves: device_get already materializes a fresh host
            # array; host numpy leaves come back as-is and must be copied
            # or they would alias the caller's buffer and tear on mutation
            a = jax.device_get(x)
            return np.array(a) if a is x else np.asarray(a)

        host_tree = jax.tree_util.tree_map(_snapshot, tree)

        def _write():
            try:
                save_checkpoint(self.directory, step, host_tree,
                                metadata=metadata, keep=self.keep)
            except BaseException as e:  # noqa: BLE001 — re-raised in wait()
                self._err.append(e)

        self._thread = threading.Thread(target=_write, daemon=True)
        self._thread.start()

    def wait(self) -> None:
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._err:
            raise self._err.pop(0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.wait()
        return False


def restore_checkpoint(directory: str, like: PyTree, step: int | None = None
                       ) -> tuple[PyTree, dict]:
    """Restore into the structure of ``like`` (shape/dtype validated leaf by
    leaf).  ``step=None`` -> newest.  Returns ``(tree, metadata)``."""
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {directory}")
    path = os.path.join(directory, f"ckpt_{step}.npz")
    with np.load(path, allow_pickle=False) as z:
        meta = json.loads(str(z["__meta__"]))
        flat = {k: z[k] for k in z.files if k != "__meta__"}

    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(like)
    new_leaves = []
    for pathspec, leaf in leaves_with_path:
        key = _SEP.join(_path_elem(p) for p in pathspec)
        if key not in flat:
            raise KeyError(f"checkpoint {path} missing leaf {key!r}")
        arr = flat[key]
        want = np.asarray(jax.device_get(leaf))
        if arr.shape != want.shape:
            raise ValueError(
                f"leaf {key!r}: checkpoint shape {arr.shape} != {want.shape}")
        if arr.dtype != want.dtype:
            raise ValueError(
                f"leaf {key!r}: checkpoint dtype {arr.dtype} != {want.dtype} "
                "(restore into a matching-dtype template, or cast explicitly)")
        new_leaves.append(arr)
    return jax.tree_util.tree_unflatten(treedef, new_leaves), meta
