"""Declarative CLI flags — the lapp replacement.

The reference declares flags as a lapp heredoc per script
(examples/mnist.lua:1-6, examples/cifar10.lua:1-10,
examples/EASGD_server.lua:1-23).  Here: a tiny declarative layer over
argparse keeping the same flag names, with ``--tpu`` replacing ``--cuda``
(BASELINE.json north star: examples run unmodified modulo that flag).
"""

from __future__ import annotations

import argparse
import os
from typing import Any, Sequence

#: Spellings that turn a DISTLEARN_TPU_* switch off; everything else that
#: is set (including "1", "true", "yes", even "maybe") counts as on.
_FALSY = ("0", "false", "off", "")


def env_truthy(name: str) -> bool | None:
    """Tri-state truthiness of an env switch: ``None`` when unset (caller
    applies its own default), else the shared 0/false/off/empty rule.

    The ONE parser for the framework's feature toggles
    (``DISTLEARN_TPU_FUSED``, ``DISTLEARN_TPU_FLASH``, ...) — the fused
    kernels and the attention dispatch previously each had a copy, which
    is exactly how the accepted spellings drift apart."""
    value = os.environ.get(name)
    if value is None:
        return None
    return value.lower() not in _FALSY


def _flag(parser: argparse.ArgumentParser, name: str, default, help_: str):
    if isinstance(default, bool):
        parser.add_argument(f"--{name}", action="store_true", default=default,
                            help=help_)
    else:
        parser.add_argument(f"--{name}", type=type(default), default=default,
                            help=help_)


def parse_flags(description: str, spec: dict[str, tuple[Any, str]],
                argv: Sequence[str] | None = None) -> argparse.Namespace:
    """``spec``: {flag_name: (default, help)} — mirrors a lapp block.

    Example (the mnist.lua:1-6 block)::

        opt = parse_flags("Train an MNIST handwritten digit classifier.", {
            "nodeIndex": (1, "node index"),
            "numNodes": (1, "number of nodes"),
        })
    """
    p = argparse.ArgumentParser(description=description)
    for name, (default, help_) in spec.items():
        _flag(p, name, default, help_)
    return p.parse_args(argv)


# Flag groups shared by the example scripts (same names as the reference).

NODE_FLAGS = {
    "nodeIndex": (1, "1-based node index (reference convention)"),
    "numNodes": (1, "number of nodes (devices on the mesh)"),
}

TRAIN_FLAGS = {
    "batchSize": (32, "global batch size (per-node = ceil(B/N), cifar10.lua:36)"),
    "learningRate": (0.1, "learning rate"),
    "numEpochs": (10, "number of epochs"),
    "tpu": (False, "run on the TPU backend (replaces the reference --cuda)"),
    "seed": (0, "init seed (reference: torch.manualSeed(0))"),
}

CKPT_FLAGS = {
    "save": ("", "checkpoint dir (empty = off; SURVEY.md §5 first-class "
                 "checkpoint/resume)"),
    "resume": (False, "resume from newest checkpoint in --save"),
}

EA_FLAGS = {
    "communicationTime": (10, "tau — steps between elastic rounds"),
    "alpha": (0.2, "elastic moving rate"),
}

ASYNC_FLAGS = {
    "host": ("127.0.0.1", "server host"),
    "port": (8080, "server base port"),
    "verbose": (False, "protocol logging (colorPrint parity)"),
    "testTime": (10, "server-side syncs between test pushes"),
    "save": ("", "checkpoint directory (empty = no checkpointing)"),
    "wireCodec": ("raw", "sync wire codec: raw (packed fp32), fp16, int8 "
                         "(quantized deltas with error feedback), or "
                         "legacy (per-leaf frames, pre-packed peers)"),
    "overlapSync": (False, "overlap local steps with the delta transmit "
                           "(background sender, depth-1 queue)"),
    "shards": (1, "server: stripe the center across this many shard "
                  "channels (clients sync stripes in parallel); "
                  "client: 0 opts out of sharded syncs even when the "
                  "server advertises a stripe plan (see docs/PERF.md)"),
}

OBS_FLAGS = {
    "obsLog": ("", "telemetry JSONL path: spans spill live, one registry "
                   "snapshot on exit (empty = off; see docs/OBSERVABILITY.md)"),
    "obsPort": (0, "serve /metrics + /healthz on 127.0.0.1:PORT "
                   "(0 = off)"),
    "obsTrace": (0, "1 = stamp trace context onto outgoing wire frames "
                    "so one sync/request is one cross-process trace "
                    "(tools/tracecat.py); 0 = legacy bitwise-identical "
                    "frames (same as DISTLEARN_TRACE_PROP)"),
}
