"""Cross-cutting utilities: metrics, logging, flags, checkpointing, profiling
(reference equivalents: optim.ConfusionMatrix / optim.Logger / lapp /
colorPrint — SURVEY.md §5)."""
