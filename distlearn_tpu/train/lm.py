"""Fused LM train step over a (data, seq, model) mesh — the 3D-parallel
composition: data parallelism (gradient psum), sequence parallelism (ring
attention + shifted targets), and tensor parallelism (Megatron-style sharded
projections) in ONE jitted shard_map program.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from distlearn_tpu.models.core import Model
from distlearn_tpu.models.transformer import lm_loss, param_specs


def build_lm_step(model: Model, mesh: Mesh, params_template, lr: float,
                  data_axis: str = "data", seq_axis: str | None = "seq",
                  tp_axis: str | None = "model",
                  ep_axis: str | None = None, accum_steps: int = 1,
                  donate: bool = True) -> Callable:
    """``step(params, tokens) -> (params, loss)``.

    ``tokens``: [global_B, global_L] int32, sharded (data, seq).
    ``params``: sharded per :func:`param_specs` over ``tp_axis`` (replicated
    across data/seq).  Gradients are psum'd over data+seq axes (params are
    replicated there); TP-sharded leaves need no gradient collective — each
    device owns its slice.

    ``ep_axis`` (MoE models): the mesh axis the expert-stacked leaves are
    sharded over — normally ``data_axis`` itself (EP group == DP group,
    one expert per data-parallel device).  Expert leaves are EXCLUDED from
    the data-axis gradient psum: each device owns a distinct expert slice,
    and the transposed all-to-all already accumulated every replica's
    contribution to it; summing across the axis would mix different
    experts' gradients.  They still reduce over ``seq_axis`` (each
    sequence shard routes its own tokens) and share the 1/dp objective
    scaling.

    ``accum_steps=k`` splits each device's batch rows into ``k``
    microbatches scanned sequentially (live activation memory drops ~k-
    fold — composes with the model's ``remat``); the averaged gradient
    feeds the same single reduction + update, so the effective batch is
    unchanged and dense models match the single-shot step exactly (the
    transformer has no dropout state).  MoE models are the exception:
    expert capacity is computed per ROUTING CALL, so microbatching rounds
    bucket sizes and decides overflow drops per microbatch — training is
    still correct, but not bit-identical to the single-shot step.
    """
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    axes = tuple(a for a in (data_axis, seq_axis) if a is not None)
    # expert leaves reduce over every replicated axis EXCEPT the one that
    # shards them — summing across ep_axis would mix different experts
    ep_grad_axes = tuple(a for a in axes if a != ep_axis)
    pspecs = param_specs(params_template, tp_axis, ep_axis)
    is_ep_leaf = jax.tree_util.tree_map(
        lambda s: ep_axis is not None and ep_axis in s, pspecs)

    def step(params, tokens):
        # differentiate the LOCAL loss share (reduce=False): see lm_loss —
        # psum transposes to psum under shard_map, so the global psum'd loss
        # must not sit inside the differentiated function
        def local_grad(toks):
            return jax.value_and_grad(
                lambda p: lm_loss(model, p, toks, seq_axis=seq_axis,
                                  tp_axis=tp_axis, ep_axis=ep_axis,
                                  reduce=False))(params)

        if accum_steps == 1:
            local_loss, grads = local_grad(tokens)
        else:
            if tokens.shape[0] % accum_steps:
                raise ValueError(
                    f"per-device batch {tokens.shape[0]} not divisible by "
                    f"accum_steps={accum_steps}")
            micro = tokens.reshape((accum_steps, -1) + tokens.shape[1:])

            def body(carry, toks):
                acc_l, acc_g = carry
                li, gi = local_grad(toks)
                return (acc_l + li,
                        jax.tree_util.tree_map(jnp.add, acc_g, gi)), None

            zero = jax.tree_util.tree_map(jnp.zeros_like, params)
            (acc_l, acc_g), _ = lax.scan(
                body, (jnp.zeros((), jnp.float32), zero), micro)
            local_loss = acc_l / jnp.float32(accum_steps)
            grads = jax.tree_util.tree_map(
                lambda g: g / jnp.asarray(accum_steps, g.dtype), acc_g)
        loss = lax.psum(local_loss, seq_axis) if seq_axis else local_loss
        # Sum partial grads over seq (params replicated there, each shard
        # holds part of the chain) and AVERAGE over data (the global
        # objective is the mean of per-replica losses — matching
        # allreduce_sgd's 1/n convention).  TP leaves need no collective:
        # the f/g pattern leaves each slice's gradient exact.
        dp = lax.psum(1, data_axis)

        def reduce_grad(g, is_ep):
            gaxes = ep_grad_axes if is_ep else axes
            if gaxes:
                g = lax.psum(g, gaxes)
            return g / jnp.asarray(dp, g.dtype)

        grads = jax.tree_util.tree_map(reduce_grad, grads, is_ep_leaf)
        new_params = jax.tree_util.tree_map(
            lambda p, g: p - jnp.asarray(lr, p.dtype) * g.astype(p.dtype),
            params, grads)
        return new_params, lax.pmean(loss, data_axis)

    tok_spec = P(data_axis, seq_axis) if seq_axis else P(data_axis)
    mapped = jax.shard_map(step, mesh=mesh,
                           in_specs=(pspecs, tok_spec),
                           out_specs=(pspecs, P()),
                           check_vma=False)
    return jax.jit(mapped, donate_argnums=(0,) if donate else ())
