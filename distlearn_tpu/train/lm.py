"""Fused LM train step over a (data, seq, model) mesh — the 3D-parallel
composition: data parallelism (gradient psum), sequence parallelism (ring
attention + shifted targets), and tensor parallelism (Megatron-style sharded
projections) in ONE jitted shard_map program.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from distlearn_tpu.utils.compat import shard_map

from distlearn_tpu.models.core import Model
from distlearn_tpu.models.transformer import (_rmsnorm, block_apply, lm_loss,
                                              param_specs,
                                              stack_block_params,
                                              unstack_block_params)
from distlearn_tpu.parallel.pp import pipeline_apply


def lm_local_grads(model: Model, params, tokens, *, seq_axis, tp_axis,
                   ep_axis=None, accum_steps: int = 1,
                   moe_balance_weight: float = 0.0,
                   seq_layout: str = "contig"):
    """``(local_loss_share, grads)`` of the LM objective on THIS device's
    shard — the gradient machinery shared by every LM step builder
    (:func:`build_lm_step`, ``optim.build_lm_optax_step``).

    Differentiates the LOCAL loss share (``lm_loss(reduce=False)``): psum
    transposes to psum under shard_map, so the global psum'd loss must
    not sit inside the differentiated function.  ``accum_steps=k`` scans
    k microbatches and averages — memory lever, same effective batch.
    """
    def local_grad(toks):
        return jax.value_and_grad(
            lambda p: lm_loss(model, p, toks, seq_axis=seq_axis,
                              tp_axis=tp_axis, ep_axis=ep_axis,
                              reduce=False,
                              moe_balance_weight=moe_balance_weight,
                              seq_layout=seq_layout)
            )(params)

    if accum_steps == 1:
        return local_grad(tokens)
    if tokens.shape[0] % accum_steps:
        raise ValueError(
            f"per-device batch {tokens.shape[0]} not divisible by "
            f"accum_steps={accum_steps}")
    micro = tokens.reshape((accum_steps, -1) + tokens.shape[1:])

    def body(carry, toks):
        acc_l, acc_g = carry
        li, gi = local_grad(toks)
        return (acc_l + li,
                jax.tree_util.tree_map(jnp.add, acc_g, gi)), None

    zero = jax.tree_util.tree_map(jnp.zeros_like, params)
    (acc_l, acc_g), _ = lax.scan(
        body, (jnp.zeros((), jnp.float32), zero), micro)
    return (acc_l / jnp.float32(accum_steps),
            jax.tree_util.tree_map(
                lambda g: g / jnp.asarray(accum_steps, g.dtype), acc_g))


def build_lm_step(model: Model, mesh: Mesh, params_template, lr: float,
                  data_axis: str = "data", seq_axis: str | None = "seq",
                  tp_axis: str | None = "model",
                  ep_axis: str | None = None, accum_steps: int = 1,
                  moe_balance_weight: float = 0.0,
                  fused: bool | None = None,
                  max_bucket_bytes: int | None = None,
                  donate: bool = True,
                  seq_layout: str = "contig") -> Callable:
    """``step(params, tokens) -> (params, loss)``.

    ``tokens``: [global_B, global_L] int32, sharded (data, seq).
    ``params``: sharded per :func:`param_specs` over ``tp_axis`` (replicated
    across data/seq).  Gradients are psum'd over data+seq axes (params are
    replicated there); TP-sharded leaves need no gradient collective — each
    device owns its slice.

    ``ep_axis`` (MoE models): the mesh axis the expert-stacked leaves are
    sharded over — normally ``data_axis`` itself (EP group == DP group,
    one expert per data-parallel device).  Expert leaves are EXCLUDED from
    the data-axis gradient psum: each device owns a distinct expert slice,
    and the transposed all-to-all already accumulated every replica's
    contribution to it; summing across the axis would mix different
    experts' gradients.  They still reduce over ``seq_axis`` (each
    sequence shard routes its own tokens) and share the 1/dp objective
    scaling.

    ``accum_steps=k`` splits each device's batch rows into ``k``
    microbatches scanned sequentially (live activation memory drops ~k-
    fold — composes with the model's ``remat``); the averaged gradient
    feeds the same single reduction + update, so the effective batch is
    unchanged and dense models match the single-shot step exactly (the
    transformer has no dropout state).  MoE models are the exception:
    expert capacity is computed per ROUTING CALL, so microbatching rounds
    bucket sizes and decides overflow drops per microbatch — training is
    still correct, but not bit-identical to the single-shot step.

    ``fused=True`` routes the SGD update through the Pallas packed-bucket
    kernel.  DEFAULT OFF for the LM family — measured on the v5e it is a
    LOSS here (dim 4096: 0.335 vs 0.580 MFU; dim 1024: 0.303 vs 0.341),
    the opposite of the classifier result (1.43x win): packing a
    ~800M-param tree into flat buckets costs two multi-GB concatenate
    passes, while XLA's per-leaf update fusions consume each gradient
    where it is produced with no extra materialization.  Kept as an
    option because the crossover favors packing for small trees
    (docs/PERF.md "fused update" note).  Applies only when every grad
    leaf's dtype matches its param leaf; falls back per-leaf otherwise.
    """
    from distlearn_tpu.ops import flatten as flatten_lib
    from distlearn_tpu.ops import fused_update
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    use_fused = bool(fused) if fused is not None else False
    axes = tuple(a for a in (data_axis, seq_axis) if a is not None)
    # expert leaves reduce over every replicated axis EXCEPT the one that
    # shards them — summing across ep_axis would mix different experts
    ep_grad_axes = tuple(a for a in axes if a != ep_axis)
    pspecs = param_specs(params_template, tp_axis, ep_axis)
    is_ep_leaf = jax.tree_util.tree_map(
        lambda s: ep_axis is not None and ep_axis in s, pspecs)

    def step(params, tokens):
        local_loss, grads = lm_local_grads(
            model, params, tokens, seq_axis=seq_axis, tp_axis=tp_axis,
            ep_axis=ep_axis, accum_steps=accum_steps,
            moe_balance_weight=moe_balance_weight, seq_layout=seq_layout)
        loss = lax.psum(local_loss, seq_axis) if seq_axis else local_loss
        # Sum partial grads over seq (params replicated there, each shard
        # holds part of the chain) and AVERAGE over data (the global
        # objective is the mean of per-replica losses — matching
        # allreduce_sgd's 1/n convention).  TP leaves need no collective:
        # the f/g pattern leaves each slice's gradient exact.
        dp = lax.psum(1, data_axis)

        def reduce_grad(g, is_ep):
            gaxes = ep_grad_axes if is_ep else axes
            if gaxes:
                g = lax.psum(g, gaxes)
            return g / jnp.asarray(dp, g.dtype)

        grads = jax.tree_util.tree_map(reduce_grad, grads, is_ep_leaf)
        gl = jax.tree_util.tree_leaves(grads)
        pl = jax.tree_util.tree_leaves(params)
        if use_fused and all(g.dtype == p.dtype for g, p in zip(gl, pl)):
            spec = flatten_lib.make_bucket_spec(grads, max_bucket_bytes)
            g_flats = flatten_lib.pack_buckets(spec, grads)
            new_params = fused_update.sgd_update_buckets(spec, params,
                                                         g_flats, lr)
        else:
            new_params = jax.tree_util.tree_map(
                lambda p, g: p - jnp.asarray(lr, p.dtype) * g.astype(p.dtype),
                params, grads)
        return new_params, lax.pmean(loss, data_axis)

    tok_spec = P(data_axis, seq_axis) if seq_axis else P(data_axis)
    mapped = shard_map(step, mesh=mesh,
                           in_specs=(pspecs, tok_spec),
                           out_specs=(pspecs, P()),
                           check_vma=False)
    return jax.jit(mapped, donate_argnums=(0,) if donate else ())


def build_lm_moe_metrics(model: Model, mesh: Mesh, params_template,
                         data_axis: str = "data",
                         seq_axis: str | None = "seq",
                         tp_axis: str | None = "model",
                         ep_axis: str | None = None) -> Callable:
    """``metrics(params, tokens) -> {"moe_balance_loss", "moe_dropped_frac"}``
    — routing-health monitor for MoE LMs (forward only, no grads): the mean
    Switch balance loss (1.0 = perfectly balanced router) and the fraction
    of routing assignments dropped by expert capacity.  Same mesh/sharding
    contract as :func:`build_lm_step`; values are averaged over the
    data/seq axes.  Run at report cadence, not every step."""
    pspecs = param_specs(params_template, tp_axis, ep_axis)
    axes = tuple(a for a in (data_axis, seq_axis) if a is not None)

    def metrics(params, tokens):
        _, st = model.apply(params, {}, tokens, train=True,
                            seq_axis=seq_axis, tp_axis=tp_axis,
                            ep_axis=ep_axis)
        if "moe_balance_loss" not in st:
            raise ValueError("model returned no MoE routing metrics — "
                             "build it with moe_experts > 0")
        out = {"moe_balance_loss": st["moe_balance_loss"],
               "moe_dropped_frac": st["moe_dropped_frac"]}
        return {k: lax.pmean(v, axes) if axes else v
                for k, v in out.items()}

    tok_spec = P(data_axis, seq_axis) if seq_axis else P(data_axis)
    return jax.jit(shard_map(
        metrics, mesh=mesh, in_specs=(pspecs, tok_spec),
        out_specs={"moe_balance_loss": P(), "moe_dropped_frac": P()},
        check_vma=False))


def stack_blocks(params, depth: int):
    """Split a :func:`transformer_lm` param pytree into
    ``(shared, stacked_blocks)``: the embed/pos/out_norm leaves, and the
    per-block leaves stacked along a new leading ``[depth]`` axis (the
    pipeline-stage axis — shard it ``P(pipe_axis)``).  Thin split over
    :func:`distlearn_tpu.models.transformer.stack_block_params` (the
    ``scan_blocks`` layout) so the two layouts share one stacking
    implementation."""
    both = stack_block_params(params, depth)
    stacked = both.pop("blocks")
    return both, stacked


def unstack_blocks(shared, stacked, depth: int):
    """Inverse of :func:`stack_blocks` (back to the apply() layout)."""
    return unstack_block_params(dict(shared, blocks=stacked), depth)


def build_lm_pp_step(mesh: Mesh, shared_template, stacked_template,
                     lr: float, num_microbatches: int,
                     compute_dtype=None, data_axis: str = "data",
                     pipe_axis: str = "pipe", remat: bool = False,
                     unroll: bool | int = False,
                     donate: bool = True) -> Callable:
    """Pipeline-parallel LM train step over a ``(data, pipe)`` mesh:
    ``step(shared, stacked, tokens) -> (shared, stacked, loss)``.

    ``k = depth / n_stages`` transformer blocks per pipeline stage (depth
    must divide evenly; sharding the stacked ``[depth, ...]`` block axis
    over ``pipe`` hands each stage its k contiguous blocks, scanned in
    order inside the stage fn — ``remat=True`` checkpoints each block so
    only one block's activations per in-flight microbatch stay live).
    Microbatches stream through the stages via
    :func:`distlearn_tpu.parallel.pp.pipeline_apply`, so the whole GPipe
    schedule — all ticks, forward and backward — is one XLA program, and
    the microbatch count doubles as the gradient-accumulation lever.
    ``unroll=True`` inlines the tick scan (measured 1.68x on the one-chip
    GPipe bench — see pipeline_apply; program size grows ~T-fold, so keep
    it for small microbatch counts).

    Each microbatch's loss share is folded ON the last rank as it emerges
    from the pipeline (``consume_fn``) — only a scalar psum crosses the
    pipe axis, not the [B, L, D] activation broadcast, and head gradients
    seed solely on the last rank (masked elsewhere), so no 1/S rescaling
    is needed.  Embedding/positional/head leaves (``shared``) are
    replicated over both axes; their partial grads (rank 0 ingests, last
    rank computes the head) are SUMMED over pipe to reassemble and
    averaged over data.  Block leaves are sharded k-per-device over
    ``pipe`` (grads reduce over data only).  Composes with data
    parallelism; TP/SP/MoE stay with :func:`build_lm_step` — the two
    factorizations cover different model regimes (PP for deep dense
    stacks whose params exceed one chip).
    """
    n_stages = mesh.shape[pipe_axis]
    depth = jax.tree_util.tree_leaves(stacked_template)[0].shape[0]
    if depth % n_stages:
        raise ValueError(
            f"stacked blocks hold {depth} layers but the {pipe_axis!r} "
            f"axis has {n_stages} devices — depth must divide into an "
            "equal number of blocks per stage")
    for need in ("embed", "pos", "out_norm"):
        if need not in shared_template:
            raise ValueError(f"shared params missing {need!r} — pass the "
                             "(shared, stacked) pair from stack_blocks()")

    def step(shared, stacked, tokens):
        # local stacked leaves: [k, ...] — this stage's k contiguous blocks
        B, L = tokens.shape
        M = num_microbatches
        if B % M:
            raise ValueError(f"per-replica batch {B} not divisible into "
                             f"{M} microbatches")
        toks_mb = tokens.reshape(M, B // M, L)

        def local_loss(shared, blk_local):
            cd = compute_dtype or shared["embed"].dtype
            x = shared["embed"][tokens].astype(cd)
            x = x + shared["pos"][:L].astype(cd)[None]

            one = lambda bp, h: block_apply(bp, h, cd)   # noqa: E731
            if remat:
                one = jax.checkpoint(one)

            def stage(bp_stack, h):
                h, _ = lax.scan(lambda hh, bp: (one(bp, hh), None),
                                h, bp_stack)
                return h

            def consume(out_mb, m):
                hh = _rmsnorm(shared["out_norm"], out_mb)
                logits = (hh @ shared["embed"].T.astype(cd)
                          ).astype(jnp.float32)
                lp = jax.nn.log_softmax(logits[:, :-1])
                tgt = lax.dynamic_index_in_dim(toks_mb, m, 0,
                                               keepdims=False)[:, 1:]
                nll = -jnp.take_along_axis(lp, tgt[..., None], -1)[..., 0]
                # this microbatch's share of the global batch-mean loss
                return nll.sum() / jnp.float32(B * (L - 1))

            return pipeline_apply(stage, blk_local, x, M,
                                  axis_name=pipe_axis, consume_fn=consume,
                                  unroll=unroll)

        local_share, (g_shared, g_blk) = jax.value_and_grad(
            local_loss, argnums=(0, 1))(shared, stacked)
        # the share is nonzero only on the last rank: psum restores the loss
        loss = lax.psum(local_share, pipe_axis)
        dp = lax.psum(1, data_axis)
        # shared leaves: partial grads live on the pipe ranks that touched
        # them — SUM over pipe reassembles; average over data (1/n as in
        # allreduce_sgd)
        g_shared = jax.tree_util.tree_map(
            lambda g: lax.psum(g, (data_axis, pipe_axis))
            / jnp.asarray(dp, g.dtype), g_shared)
        g_blk = jax.tree_util.tree_map(
            lambda g: lax.psum(g, data_axis) / jnp.asarray(dp, g.dtype),
            g_blk)
        shared = jax.tree_util.tree_map(
            lambda p, g: p - jnp.asarray(lr, p.dtype) * g.astype(p.dtype),
            shared, g_shared)
        stacked_new = jax.tree_util.tree_map(
            lambda p, g: p - jnp.asarray(lr, p.dtype) * g.astype(p.dtype),
            stacked, g_blk)
        return shared, stacked_new, lax.pmean(loss, data_axis)

    mapped = shard_map(
        step, mesh=mesh,
        in_specs=(P(), P(pipe_axis), P(data_axis)),
        out_specs=(P(), P(pipe_axis), P()),
        check_vma=False)
    return jax.jit(mapped, donate_argnums=(0, 1) if donate else ())


def build_lm_pp_1f1b_step(mesh: Mesh, shared_template, stacked_template,
                          lr: float, num_microbatches: int,
                          compute_dtype=None, data_axis: str = "data",
                          pipe_axis: str = "pipe", remat: bool = False,
                          donate: bool = True) -> Callable:
    """1F1B-scheduled pipeline-parallel LM train step — same contract,
    sharding, and gradient semantics as :func:`build_lm_pp_step`
    (``step(shared, stacked, tokens) -> (shared, stacked, loss)``), but
    each microbatch's backward starts the moment it leaves the last
    stage (:func:`distlearn_tpu.parallel.pp.pipeline_1f1b`), so live
    activation memory is O(S) stage-inputs instead of GPipe's O(M)
    autodiff residuals — the schedule to use when the microbatch count
    is cranked up for bubble amortization.  ``remat`` checkpoints each
    block inside the stage fn (the per-tick backward already recomputes
    the stage forward from its input; block-level remat additionally
    bounds the recompute graph's own liveness for k-block stages).

    Embedding/positional gradients flow through the returned ``g_x``
    (rank 0), head/out-norm gradients through the explicit consume
    params (last rank); both reassemble with the same pipe-axis psum as
    the GPipe builder, so the two schedules are drop-in interchangeable
    (equivalence is tested).
    """
    from distlearn_tpu.parallel.pp import pipeline_1f1b
    n_stages = mesh.shape[pipe_axis]
    depth = jax.tree_util.tree_leaves(stacked_template)[0].shape[0]
    if depth % n_stages:
        raise ValueError(
            f"stacked blocks hold {depth} layers but the {pipe_axis!r} "
            f"axis has {n_stages} devices — depth must divide into an "
            "equal number of blocks per stage")
    for need in ("embed", "pos", "out_norm"):
        if need not in shared_template:
            raise ValueError(f"shared params missing {need!r} — pass the "
                             "(shared, stacked) pair from stack_blocks()")

    def step(shared, stacked, tokens):
        B, L = tokens.shape
        M = num_microbatches
        if B % M:
            raise ValueError(f"per-replica batch {B} not divisible into "
                             f"{M} microbatches")
        toks_mb = tokens.reshape(M, B // M, L)
        cd = compute_dtype or shared["embed"].dtype

        def embed_fn(sh):
            x = sh["embed"][tokens].astype(cd)
            return x + sh["pos"][:L].astype(cd)[None]

        x, embed_vjp = jax.vjp(embed_fn,
                               {"embed": shared["embed"],
                                "pos": shared["pos"]})

        one = lambda bp, h: block_apply(bp, h, cd)   # noqa: E731
        if remat:
            one = jax.checkpoint(one)

        def stage(bp_stack, h):
            h, _ = lax.scan(lambda hh, bp: (one(bp, hh), None), h, bp_stack)
            return h

        def consume(cp, out_mb, m):
            hh = _rmsnorm(cp["out_norm"], out_mb)
            logits = (hh @ cp["embed"].T.astype(cd)).astype(jnp.float32)
            lp = jax.nn.log_softmax(logits[:, :-1])
            tgt = lax.dynamic_index_in_dim(toks_mb, m, 0,
                                           keepdims=False)[:, 1:]
            nll = -jnp.take_along_axis(lp, tgt[..., None], -1)[..., 0]
            return nll.sum() / jnp.float32(B * (L - 1))

        cp = {"out_norm": shared["out_norm"], "embed": shared["embed"]}
        local_share, g_blk, g_cp, g_x = pipeline_1f1b(
            stage, stacked, consume, cp, x, M, axis_name=pipe_axis)
        (g_embed,) = embed_vjp(g_x.astype(x.dtype))

        loss = lax.psum(local_share, pipe_axis)
        dp = lax.psum(1, data_axis)
        # reassemble shared grads: embedding side (rank 0) + head side
        # (last rank); embed appears in both
        g_shared = {"embed": g_embed["embed"] + g_cp["embed"],
                    "pos": g_embed["pos"],
                    "out_norm": g_cp["out_norm"]}
        g_shared = jax.tree_util.tree_map(
            lambda g: lax.psum(g, (data_axis, pipe_axis))
            / jnp.asarray(dp, g.dtype), g_shared)
        g_blk = jax.tree_util.tree_map(
            lambda g: lax.psum(g, data_axis) / jnp.asarray(dp, g.dtype),
            g_blk)
        shared = jax.tree_util.tree_map(
            lambda p, g: p - jnp.asarray(lr, p.dtype) * g.astype(p.dtype),
            shared, g_shared)
        stacked_new = jax.tree_util.tree_map(
            lambda p, g: p - jnp.asarray(lr, p.dtype) * g.astype(p.dtype),
            stacked, g_blk)
        return shared, stacked_new, lax.pmean(loss, data_axis)

    mapped = shard_map(
        step, mesh=mesh,
        in_specs=(P(), P(pipe_axis), P(data_axis)),
        out_specs=(P(), P(pipe_axis), P()),
        check_vma=False)
    return jax.jit(mapped, donate_argnums=(0, 1) if donate else ())


class LMMixedState(NamedTuple):
    """Mixed-precision LM train state: ``params`` is the bf16 WORKING copy
    every matmul reads (2 bytes/param — halves the weight-read traffic of
    the f32-param step across forward, dgrad, and wgrad), ``master`` the
    f32 copy the update applies to (bf16's 8-bit mantissa underflows
    ``p - lr*g`` when ``lr*g`` is ~256x smaller than ``p``; the master
    keeps SGD exact).  Invariant: ``params == master.astype(bf16)``."""
    params: Any
    master: Any


def init_lm_mixed_state(params, param_dtype=jnp.bfloat16) -> LMMixedState:
    """Master := the f32 init; working copy := its ``param_dtype`` cast."""
    cast = jax.tree_util.tree_map(
        lambda p: p.astype(param_dtype), params)
    return LMMixedState(params=cast, master=params)


def build_lm_mixed_step(model: Model, mesh: Mesh, params_template, lr: float,
                        data_axis: str = "data",
                        seq_axis: str | None = "seq",
                        tp_axis: str | None = "model",
                        ep_axis: str | None = None, accum_steps: int = 1,
                        moe_balance_weight: float = 0.0,
                        grad_dtype=jnp.float32,
                        donate: bool = True,
                        seq_layout: str = "contig") -> Callable:
    """:func:`build_lm_step` with bf16 working params + f32 masters:
    ``step(st, tokens) -> (st, loss)`` on :class:`LMMixedState`.

    Motivation (measured, docs/PERF.md): the f32-param step spends ~21%
    of the dim-4096 step in the f32 ``p - lr*g`` elementwise update and
    reads 4-byte weights in every matmul even though the MXU computes in
    bf16 (the convert fuses into the matmul but the HBM read does not
    shrink).  Storing the working copy in bf16 halves the weight bytes
    the three matmul passes pull per step; the f32 master confines f32
    elementwise traffic to the update itself.  Same mesh/sharding
    contract as :func:`build_lm_step` (``params_template`` may be either
    precision — only shapes matter for the specs).

    ``grad_dtype`` is the dtype gradients are REDUCED and applied in
    (default f32: bf16 grads from the bf16-param backward are upcast
    before the data/seq psum, so the cross-replica sum accumulates full
    precision; pass ``jnp.bfloat16`` to halve gradient ICI bytes when
    the replica count is small enough for bf16 accumulation).
    """
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    axes = tuple(a for a in (data_axis, seq_axis) if a is not None)
    ep_grad_axes = tuple(a for a in axes if a != ep_axis)
    pspecs = param_specs(params_template, tp_axis, ep_axis)
    is_ep_leaf = jax.tree_util.tree_map(
        lambda s: ep_axis is not None and ep_axis in s, pspecs)

    def step(st: LMMixedState, tokens):
        local_loss, grads = lm_local_grads(
            model, st.params, tokens, seq_axis=seq_axis, tp_axis=tp_axis,
            ep_axis=ep_axis, accum_steps=accum_steps,
            moe_balance_weight=moe_balance_weight, seq_layout=seq_layout)
        loss = lax.psum(local_loss, seq_axis) if seq_axis else local_loss
        dp = lax.psum(1, data_axis)

        def reduce_grad(g, is_ep):
            g = g.astype(grad_dtype)
            gaxes = ep_grad_axes if is_ep else axes
            if gaxes:
                g = lax.psum(g, gaxes)
            return g / jnp.asarray(dp, g.dtype)

        grads = jax.tree_util.tree_map(reduce_grad, grads, is_ep_leaf)
        master = jax.tree_util.tree_map(
            lambda m, g: m - jnp.asarray(lr, m.dtype) * g.astype(m.dtype),
            st.master, grads)
        params = jax.tree_util.tree_map(
            lambda p, m: m.astype(p.dtype), st.params, master)
        return (LMMixedState(params, master),
                lax.pmean(loss, data_axis))

    tok_spec = P(data_axis, seq_axis) if seq_axis else P(data_axis)
    spec = LMMixedState(params=pspecs, master=pspecs)
    mapped = shard_map(step, mesh=mesh, in_specs=(spec, tok_spec),
                           out_specs=(spec, P()), check_vma=False)
    return jax.jit(mapped, donate_argnums=(0,) if donate else ())


class LMEAState(NamedTuple):
    """Per-node elastic-averaging state for LM training: every leaf has a
    leading ``[num_nodes]`` axis sharded over the data mesh axis (replicas
    deliberately diverge between rounds — lua/AllReduceEA.lua semantics on
    the transformer family the reference never had)."""
    params: Any
    center: Any
    vel: Any


def init_lm_ea_state(model: Model, tree, key) -> LMEAState:
    """Identical init on every node, center := params, zero momentum
    (mirrors distlearn_tpu.train.trainer.init_ea_state for classifiers)."""
    params, _ = model.init(key)
    n = tree.num_nodes
    stack = lambda t: tree.put_per_node(jax.tree_util.tree_map(  # noqa: E731
        lambda x: jnp.broadcast_to(x[None], (n,) + x.shape), t))
    return LMEAState(params=stack(params), center=stack(params),
                     vel=stack(jax.tree_util.tree_map(jnp.zeros_like,
                                                      params)))


def build_lm_ea_steps(model: Model, tree, lr: float, alpha: float,
                      momentum: float = 0.0, donate: bool = True,
                      fused: bool | None = None,
                      max_bucket_bytes: int | None = None):
    """EASGD for the transformer LM over a data mesh axis: returns
    ``(local_step, ea_round)`` with the same contract as
    :func:`distlearn_tpu.train.trainer.build_ea_steps` — τ−1 of every τ
    steps run with ZERO collectives (the host owns the τ cadence), then
    one fused elastic round couples the replicas through the center
    (lua/AllReduceEA.lua:25-47 recast; ``momentum`` adds the paper's
    EAMSGD local rule).

    ``local_step(state, tokens) -> (state, losses[num_nodes])`` — tokens
    ``[global_B, L]`` sharded over the data axis; each node trains its own
    replica on its shard.  ``ea_round(state) -> state``.
    """
    from distlearn_tpu.parallel.mesh import expand_node, squeeze_node
    from distlearn_tpu.train.trainer import (apply_elastic_round,
                                             local_update)
    axis = tree.axis_name

    def local_step(st: LMEAState, tokens):
        p = squeeze_node(st.params)
        loss, grads = jax.value_and_grad(
            lambda q: lm_loss(model, q, tokens, seq_axis=None,
                              tp_axis=None))(p)
        p, v = local_update(p, grads, squeeze_node(st.vel), lr, momentum)
        vel = expand_node(v) if momentum else st.vel
        return (LMEAState(expand_node(p), st.center, vel),
                loss[None] if loss.ndim == 0 else loss)

    def ea_round(st: LMEAState):
        p, c = apply_elastic_round(squeeze_node(st.params),
                                   squeeze_node(st.center), alpha, axis,
                                   fused, max_bucket_bytes)
        return LMEAState(expand_node(p), expand_node(c), st.vel)

    spec = LMEAState(params=P(axis), center=P(axis), vel=P(axis))
    local = jax.jit(
        shard_map(local_step, mesh=tree.mesh,
                      in_specs=(spec, P(axis)),
                      out_specs=(spec, P(axis)), check_vma=False),
        donate_argnums=(0,) if donate else ())
    rnd = jax.jit(
        shard_map(ea_round, mesh=tree.mesh, in_specs=(spec,),
                      out_specs=spec, check_vma=False),
        donate_argnums=(0,) if donate else ())
    return local, rnd
