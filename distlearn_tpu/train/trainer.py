"""Fused train-step builders — the TPU hot path.

The reference's hot loop is: dataset batch → autograd fwd+bwd →
``tree.allReduce`` over TCP → manual SGD update (call stack SURVEY.md §3.1,
examples/mnist.lua:99-116).  Every stage is a separate host-driven operation
crossing the process boundary.  The TPU-native design collapses the entire
step — forward, backward, gradient psum, normalization, SGD update, metric
update — into ONE jitted ``shard_map`` program per mesh, so XLA overlaps the
ICI collective with backprop compute and fuses the elementwise update into the
gradient producers.  This is the BASELINE.json north-star structure.

Two families:

* :func:`build_sgd_step` — AllReduceSGD training.  Params REPLICATED across
  the mesh (spec ``P()``), batch sharded along the data axis.  Gradients are
  psum'd and contributor-normalized (lua/AllReduceSGD.lua:18-30 semantics)
  inside the step.

* :func:`build_ea_steps` — AllReduceEA training.  Params are PER-NODE (stacked
  leading node axis, spec ``P(axis)``) because EASGD nodes intentionally
  diverge between averaging rounds.  Returns a collective-free local step and
  a fused elastic-round step; the host calls the round every ``tau`` steps
  (τ−1 of τ steps run with zero communication — the point of EASGD,
  lua/AllReduceEA.lua:31).
"""

from __future__ import annotations

import time
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax, random
from jax.sharding import PartitionSpec as P

from distlearn_tpu import obs
from distlearn_tpu.utils.compat import shard_map

from distlearn_tpu.models.core import Model, loss_fn
from distlearn_tpu.ops import flatten as flatten_lib
from distlearn_tpu.ops import fused_update
from distlearn_tpu.parallel import allreduce_ea, allreduce_sgd
from distlearn_tpu.parallel import mesh as mesh_lib
from distlearn_tpu.parallel.mesh import MeshTree
from distlearn_tpu.utils import metrics as metrics_lib

PyTree = Any


class _TimedStep:
    """Telemetry shim around a jitted step: times each host dispatch
    (async — the wall time to ENQUEUE the program, which is what the
    scan/cycle builders exist to amortize, not device compute) and counts
    calls.  ``__getattr__`` forwards everything else to the jitted
    callable so ``.lower()`` consumers — bench.py, the distcost budget
    gate — see the unwrapped object and compiled HLO stays identical."""

    def __init__(self, fn, name: str):
        self._fn = fn
        lat = obs.histogram(
            "train_step_dispatch_seconds",
            "host-side dispatch wall time per jitted step call",
            labels=("step",))
        cnt = obs.counter("train_steps_total", "jitted step dispatches",
                          labels=("step",))
        self._h = lat.labels(step=name)
        self._c = cnt.labels(step=name)

    def __call__(self, *a, **kw):
        t0 = time.perf_counter()
        out = self._fn(*a, **kw)
        self._c.inc()
        self._h.observe(time.perf_counter() - t0)
        return out

    def __getattr__(self, name):
        return getattr(self._fn, name)


def _timed(fn, name: str):
    """Wrap a builder's result for telemetry; the raw jitted fn comes back
    untouched when the kill switch is off (zero indirection disabled)."""
    if not obs.enabled():
        return fn
    return _TimedStep(fn, name)


class TrainState(NamedTuple):
    """Carried through the jitted SGD step (all donated).

    ``cm`` is a stacked per-node confusion matrix ``[num_nodes, C, C]``
    sharded over the data axis (each node counts its own shard's
    predictions; sum at report time — ref examples/mnist.lua:120-125).
    """
    params: PyTree
    model_state: PyTree      # batchnorm running stats (sync-BN: replicated)
    sync: allreduce_sgd.SGDSyncState   # my_steps stacked [num_nodes], sharded
    cm: jax.Array            # [num_nodes, C, C] device-side confusion matrix
    rng: jax.Array


def _sgd_update(params: PyTree, grads: PyTree, lr) -> PyTree:
    """Manual SGD — the reference's update loop (examples/mnist.lua:112-116)."""
    return jax.tree_util.tree_map(
        lambda p, g: p - jnp.asarray(lr, p.dtype) * g.astype(p.dtype),
        params, grads)


def local_update(params: PyTree, grads: PyTree, vel: PyTree, lr: float,
                 momentum: float) -> tuple[PyTree, PyTree]:
    """The EA-family local optimizer, shared by the classifier and LM
    paths: plain SGD (``momentum=0``, velocity untouched) or heavy-ball
    EAMSGD (arXiv:1412.6651 §3: ``v = μ·v + g; p -= lr·v``)."""
    if not momentum:
        return _sgd_update(params, grads, lr), vel
    vel = jax.tree_util.tree_map(
        lambda v, g: jnp.asarray(momentum, v.dtype) * v + g.astype(v.dtype),
        vel, grads)
    params = jax.tree_util.tree_map(
        lambda p, v: p - jnp.asarray(lr, p.dtype) * v.astype(p.dtype),
        params, vel)
    return params, vel


def apply_elastic_round(params: PyTree, center: PyTree, alpha: float,
                        axis: str, fused: bool | None = None,
                        max_bucket_bytes: int | None = None
                        ) -> tuple[PyTree, PyTree]:
    """One fused elastic round on LOCAL (per-node) pytrees, shared by the
    classifier and LM paths: Pallas packed buckets when enabled (one psum
    per bucket), per-leaf XLA round otherwise."""
    if fused_update.fused_enabled(fused):
        return fused_update.elastic_round_buckets(params, center, alpha,
                                                  axis, max_bucket_bytes)
    st = allreduce_ea.EAState(center=center, step=jnp.zeros((), jnp.int32))
    params, st = allreduce_ea.elastic_round(params, st, alpha,
                                            axis_name=axis)
    return params, st.center


def init_common(model: Model, tree: MeshTree, key: jax.Array,
                num_classes: int):
    """Shared data-parallel state init: identical params on every node, a
    per-node step counter (ref ``stepsPerNode``), a per-node confusion
    matrix, and the training rng.  Returns
    ``(params, model_state, sync, cm, rng)`` — the common fields of every
    replicated-params TrainState flavor (SGD / optax / ZeRO)."""
    init_key, train_key = random.split(key)
    params, mstate = model.init(init_key)
    n = tree.num_nodes
    sync = allreduce_sgd.SGDSyncState(
        my_steps=tree.put_per_node(jnp.zeros((n,), jnp.int32)))
    cm = tree.put_per_node(jnp.zeros((n, num_classes, num_classes),
                                     jnp.int32))
    return params, mstate, sync, cm, train_key


def init_train_state(model: Model, tree: MeshTree, key: jax.Array,
                     num_classes: int) -> TrainState:
    params, mstate, sync, cm, rng = init_common(model, tree, key,
                                                num_classes)
    return TrainState(params=params, model_state=mstate, sync=sync, cm=cm,
                      rng=rng)


def build_sgd_step(model: Model, tree: MeshTree, lr: float,
                   donate: bool = True, with_contrib: bool = False,
                   fused: bool | None = None,
                   max_bucket_bytes: int | None = None) -> Callable:
    """One fused AllReduceSGD step: ``step(ts, x, y) -> (ts, loss)``.

    ``x``/``y`` are GLOBAL batches (leading axis = global batch) sharded over
    the data axis; params/state replicated.  Inside: local fwd+bwd on the
    node's shard, psum+normalize grads (contributor semantics of
    lua/AllReduceSGD.lua:18-30), SGD update, confusion-matrix update, loss
    pmean.  Sync batchnorm: stats pmean'd across nodes, so the
    replicated-params invariant holds bitwise.

    ``with_contrib=True`` adds a 4th argument: a per-node 0/1 vector
    ``[num_nodes]`` (sharded over the axis) marking which nodes contribute
    this step — the uneven-data-partition case (lua/AllReduceSGD.lua:22-27).
    Non-contributors' grads are masked out, their params still receive the
    identical psum'd update (keeping params replicated), their step counter
    and confusion matrix do not advance; pair with :func:`build_sync_step`
    for the end-of-epoch winner-takes-all sync.

    ``fused`` (default: on when running on TPU, see
    :func:`distlearn_tpu.ops.fused_update.fused_enabled`) routes the gradient
    psum and the SGD update through packed flat buckets: one collective and
    one Pallas kernel launch per bucket instead of one XLA op per parameter
    leaf — the per-tensor walkTable loop of the reference
    (lua/AllReduceSGD.lua:24) collapsed into a few HBM streaming passes.
    ``max_bucket_bytes`` splits huge models into several buckets.
    """
    axis = tree.axis_name
    _body = _make_sgd_body(model, tree, lr, fused, max_bucket_bytes)

    specs_ts = TrainState(params=P(), model_state=P(), sync=P(axis),
                          cm=P(axis), rng=P())
    if with_contrib:
        def step(ts, x, y, contrib):
            return _body(ts, x, y, jnp.squeeze(contrib, 0))
        in_specs = (specs_ts, P(axis), P(axis), P(axis))
    else:
        def step(ts, x, y):
            return _body(ts, x, y, None)
        in_specs = (specs_ts, P(axis), P(axis))
    mapped = shard_map(step, mesh=tree.mesh,
                           in_specs=in_specs,
                           out_specs=(specs_ts, P()),
                           check_vma=False)
    return _timed(jax.jit(mapped, donate_argnums=(0,) if donate else ()),
                  "sgd")


def _make_sgd_body(model: Model, tree: MeshTree, lr: float,
                   fused: bool | None, max_bucket_bytes: int | None):
    """The per-node body of one fused AllReduceSGD step (shared by the
    per-call and the scanned builders)."""
    axis = tree.axis_name
    use_fused = fused_update.fused_enabled(fused)

    def _body(ts: TrainState, x, y, contrib):
        rng, dropout_rng = random.split(ts.rng)
        dropout_rng = random.fold_in(dropout_rng, lax.axis_index(axis))

        def _loss(p):
            return loss_fn(model, p, ts.model_state, x, y, train=True,
                           rng=dropout_rng, axis_name=axis, bn_weight=contrib)

        (loss, (log_probs, mstate)), grads = \
            jax.value_and_grad(_loss, has_aux=True)(ts.params)
        sync_local = mesh_lib.squeeze_node(ts.sync)
        if use_fused:
            spec = flatten_lib.make_bucket_spec(grads, max_bucket_bytes)
            g_flats, sync_local, n = allreduce_sgd.sum_and_normalize_gradients(
                flatten_lib.pack_buckets(spec, grads), sync_local,
                contrib=contrib, axis_name=axis)
            params = fused_update.sgd_update_buckets(spec, ts.params,
                                                     g_flats, lr)
        else:
            grads, sync_local, n = allreduce_sgd.sum_and_normalize_gradients(
                grads, sync_local, contrib=contrib, axis_name=axis)
            params = _sgd_update(ts.params, grads, lr)
        sync = mesh_lib.expand_node(sync_local)
        cm_new = metrics_lib.update_confusion(jnp.squeeze(ts.cm, 0),
                                              log_probs, y)
        if contrib is not None:
            keep = contrib.astype(jnp.bool_)
            cm_new = jnp.where(keep, cm_new, jnp.squeeze(ts.cm, 0))
            denom = jnp.maximum(n, 1).astype(loss.dtype)
            mean_loss = lax.psum(loss * contrib.astype(loss.dtype), axis) / denom
        else:
            mean_loss = lax.pmean(loss, axis)
        return TrainState(params, mstate, sync, cm_new[None], rng), mean_loss

    return _body


def build_sgd_scan_step(model: Model, tree: MeshTree, lr: float,
                        donate: bool = True, fused: bool | None = None,
                        max_bucket_bytes: int | None = None,
                        with_contrib: bool = False) -> Callable:
    """K chained AllReduceSGD steps as ONE XLA program:
    ``steps(ts, xs, ys) -> (ts, losses)`` with ``xs``/``ys`` carrying a
    leading ``[K]`` step axis (replicated) over the normal data-sharded batch
    axes, ``losses`` shaped ``[K]``.

    Semantically identical to calling :func:`build_sgd_step`'s step K times
    (same psum/normalize/update per step, state threads through a
    ``lax.scan``), but the host dispatches ONCE per K steps.  On a
    remote-attached chip the per-call dispatch round trip can exceed the
    step's compute (measured ~3 ms dispatch vs ~1.3 ms compute for the
    CIFAR-10 headline step) — the reference has the same structure cost in
    every ``tree.allReduce`` socket round trip (SURVEY.md §3.1), which this
    design removes entirely.  K is read from the input shape at trace time.

    ``with_contrib=True`` adds a 4th argument ``[K, num_nodes]`` of 0/1
    participation flags (sharded over the axis), one row per chained step —
    the per-call step's uneven-data-partition masking
    (lua/AllReduceSGD.lua:22-27) on the scanned hot path: each step's row
    masks grads/steps/metrics exactly as :func:`build_sgd_step`'s
    ``with_contrib`` does per call.
    """
    axis = tree.axis_name
    _body = _make_sgd_body(model, tree, lr, fused, max_bucket_bytes)

    specs_ts = TrainState(params=P(), model_state=P(), sync=P(axis),
                          cm=P(axis), rng=P())
    if with_contrib:
        def steps(ts, xs, ys, contribs):
            def scan_body(carry, xyc):
                x, y, c = xyc
                new_ts, loss = _body(carry, x, y, jnp.squeeze(c, 0))
                return new_ts, loss
            ts, losses = lax.scan(scan_body, ts, (xs, ys, contribs))
            return ts, losses
        in_specs = (specs_ts, P(None, axis), P(None, axis), P(None, axis))
    else:
        def steps(ts, xs, ys):
            def scan_body(carry, xy):
                x, y = xy
                new_ts, loss = _body(carry, x, y, None)
                return new_ts, loss
            ts, losses = lax.scan(scan_body, ts, (xs, ys))
            return ts, losses
        in_specs = (specs_ts, P(None, axis), P(None, axis))
    mapped = shard_map(steps, mesh=tree.mesh,
                           in_specs=in_specs,
                           out_specs=(specs_ts, P()),
                           check_vma=False)
    return _timed(jax.jit(mapped, donate_argnums=(0,) if donate else ()),
                  "sgd_scan")


def build_sync_step(tree: MeshTree, donate: bool = False) -> Callable:
    """End-of-epoch winner-takes-all parameter sync over a :class:`TrainState`
    (ref ``synchronizeParameters``, lua/AllReduceSGD.lua:33-54): the node with
    the most contributing steps this epoch wins; its params broadcast to all;
    step counters reset.  Only meaningful after uneven-participation steps —
    under full participation params are already replicated."""
    axis = tree.axis_name

    def step(ts: TrainState):
        params, sync_local = allreduce_sgd.synchronize_parameters(
            ts.params, mesh_lib.squeeze_node(ts.sync), axis_name=axis)
        return ts._replace(params=params,
                           sync=mesh_lib.expand_node(sync_local))

    specs_ts = TrainState(params=P(), model_state=P(), sync=P(axis),
                          cm=P(axis), rng=P())
    mapped = shard_map(step, mesh=tree.mesh, in_specs=(specs_ts,),
                           out_specs=specs_ts, check_vma=False)
    return _timed(jax.jit(mapped, donate_argnums=(0,) if donate else ()),
                  "sync")


def build_eval_step(model: Model, tree: MeshTree) -> Callable:
    """Fused eval step: ``eval_step(params, mstate, cm, x, y) -> (cm, loss)``.
    Confusion matrix stays per-node (spec ``P(axis)``); reduce with
    :func:`reduce_confusion` at report time (ref allreduces the matrix —
    examples/mnist.lua:122, cifar10.lua:234)."""
    axis = tree.axis_name

    def step(params, mstate, cm, x, y):
        loss, (log_probs, _) = loss_fn(model, params, mstate, x, y,
                                       train=False, axis_name=axis)
        cm = metrics_lib.update_confusion(jnp.squeeze(cm, 0), log_probs, y)
        return cm[None], lax.pmean(loss, axis)

    mapped = shard_map(step, mesh=tree.mesh,
                           in_specs=(P(), P(), P(axis), P(axis), P(axis)),
                           out_specs=(P(axis), P()),
                           check_vma=False)
    return _timed(jax.jit(mapped, donate_argnums=(2,)), "eval")


def reduce_confusion(cm: jax.Array):
    """Sum stacked per-node confusion matrices ``[N, C, C]`` into one global
    ``[C, C]`` (host-level; ref examples/mnist.lua:120-125)."""
    import numpy as np
    return np.asarray(jax.device_get(cm)).sum(axis=0)


# ---------------------------------------------------------------------------
# Elastic averaging (EASGD) steps
# ---------------------------------------------------------------------------

class EATrainState(NamedTuple):
    """Per-node training state for EASGD — every leaf has a leading
    ``num_nodes`` axis sharded over the data mesh axis (nodes diverge).
    ``vel`` is the per-node momentum buffer (EAMSGD, arXiv:1412.6651 §3);
    zeros and untouched when the local optimizer is plain SGD."""
    params: PyTree
    model_state: PyTree
    center: PyTree
    vel: PyTree
    cm: jax.Array
    rng: jax.Array


def init_ea_state(model: Model, tree: MeshTree, key: jax.Array,
                  num_classes: int) -> EATrainState:
    """Identical init on every node (ref seed-0 + initial scatter —
    examples/mnist-ea.lua:63), center := params (lua/AllReduceEA.lua:11-22),
    zero momentum."""
    init_key, train_key = random.split(key)
    params, mstate = model.init(init_key)
    n = tree.num_nodes
    stack = lambda t: tree.put_per_node(jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(x[None], (n,) + x.shape), t))
    params_n = stack(params)
    rngs = random.split(train_key, n)
    return EATrainState(
        params=params_n, model_state=stack(mstate),
        center=stack(params),
        vel=stack(jax.tree_util.tree_map(jnp.zeros_like, params)),
        cm=tree.put_per_node(jnp.zeros((n, num_classes, num_classes), jnp.int32)),
        rng=tree.put_per_node(rngs))


def build_ea_steps(model: Model, tree: MeshTree, lr: float, alpha: float,
                   donate: bool = True, fused: bool | None = None,
                   max_bucket_bytes: int | None = None,
                   momentum: float = 0.0) -> tuple[Callable, Callable]:
    """Returns ``(local_step, ea_round)``.

    ``local_step(ts, x, y) -> (ts, losses)`` — grad + local SGD, ZERO
    collectives (the τ−1 quiet steps; ref examples/mnist-ea.lua:100-107).
    BN stats stay per-node (nodes diverge anyway — matches reference, where
    running stats are process-local buffers).

    ``ea_round(ts) -> ts`` — the fused elastic round (delta, psum, center
    move) — lua/AllReduceEA.lua:35-45 as ONE XLA program.  With ``fused``
    (default on TPU) the round runs on packed flat buckets: one Pallas
    kernel produces (p', delta) and ONE psum per bucket carries the deltas,
    instead of a collective per parameter leaf.

    ``momentum > 0`` switches the local optimizer to heavy-ball SGD —
    **EAMSGD** from the EASGD paper (arXiv:1412.6651 §3, the variant the
    reference never implemented): ``v = μ·v + g; p -= lr·v`` per quiet
    step, elastic round unchanged.  (torch-optim parameterization; the
    paper's ``v = δv − ηg; x += v`` is the same update with ``v`` rescaled
    by ``−η``.)
    """
    local_step, ea_round = _make_ea_bodies(model, tree, lr, alpha, fused,
                                           max_bucket_bytes, momentum)
    axis = tree.axis_name
    spec_ts = EATrainState(params=P(axis), model_state=P(axis), center=P(axis),
                           vel=P(axis), cm=P(axis), rng=P(axis))
    local = jax.jit(
        shard_map(local_step, mesh=tree.mesh,
                      in_specs=(spec_ts, P(axis), P(axis)),
                      out_specs=(spec_ts, P(axis)), check_vma=False),
        donate_argnums=(0,) if donate else ())
    rnd = jax.jit(
        shard_map(ea_round, mesh=tree.mesh, in_specs=(spec_ts,),
                      out_specs=spec_ts, check_vma=False),
        donate_argnums=(0,) if donate else ())
    return _timed(local, "ea_local"), _timed(rnd, "ea_round")


def _make_ea_bodies(model: Model, tree: MeshTree, lr: float, alpha: float,
                    fused: bool | None, max_bucket_bytes: int | None,
                    momentum: float = 0.0):
    """Per-node (local_step, ea_round) bodies shared by the per-call and the
    scanned EASGD builders."""
    axis = tree.axis_name
    use_fused = fused_update.fused_enabled(fused)
    _sq, _ex = mesh_lib.squeeze_node, mesh_lib.expand_node

    def local_step(ts: EATrainState, x, y):
        params, mstate, rng = _sq(ts.params), _sq(ts.model_state), _sq(ts.rng)
        cm = _sq(ts.cm)
        rng, dropout_rng = random.split(rng)

        def _loss(p):
            return loss_fn(model, p, mstate, x, y, train=True,
                           rng=dropout_rng, axis_name=None)

        (loss, (log_probs, mstate)), grads = \
            jax.value_and_grad(_loss, has_aux=True)(params)
        params, v = local_update(params, grads, _sq(ts.vel), lr, momentum)
        vel = _ex(v) if momentum else ts.vel
        cm = metrics_lib.update_confusion(cm, log_probs, y)
        new_ts = EATrainState(_ex(params), _ex(mstate), ts.center, vel,
                              _ex(cm), _ex(rng))
        return new_ts, loss[None] if loss.ndim == 0 else loss

    def ea_round(ts: EATrainState):
        params, center = apply_elastic_round(
            _sq(ts.params), _sq(ts.center), alpha, axis, use_fused,
            max_bucket_bytes)
        return EATrainState(_ex(params), ts.model_state, _ex(center),
                            ts.vel, ts.cm, ts.rng)

    return local_step, ea_round


def build_ea_cycle(model: Model, tree: MeshTree, lr: float, alpha: float,
                   donate: bool = True, fused: bool | None = None,
                   max_bucket_bytes: int | None = None,
                   momentum: float = 0.0) -> Callable:
    """One full EASGD cycle — τ collective-free local steps then the fused
    elastic round — as ONE XLA program: ``cycle(ts, xs, ys) -> (ts, losses)``
    with ``xs``/``ys`` carrying a leading ``[tau]`` step axis and ``losses``
    shaped ``[tau, num_nodes]``.

    This is the EASGD communication structure itself (τ−1 quiet steps per
    round, lua/AllReduceEA.lua:31 / examples/mnist-ea.lua:110) compiled into
    a single dispatch: the host talks to the device once per *round*, not
    once per step, and XLA schedules the round's psum right after the last
    local update.  τ is read from the input shape at trace time.
    """
    local_step, ea_round = _make_ea_bodies(model, tree, lr, alpha, fused,
                                           max_bucket_bytes, momentum)
    axis = tree.axis_name

    def cycle(ts, xs, ys):
        def scan_body(carry, xy):
            x, y = xy
            new_ts, loss = local_step(carry, x, y)
            return new_ts, loss
        ts, losses = lax.scan(scan_body, ts, (xs, ys))
        return ea_round(ts), losses

    spec_ts = EATrainState(params=P(axis), model_state=P(axis), center=P(axis),
                           vel=P(axis), cm=P(axis), rng=P(axis))
    mapped = shard_map(cycle, mesh=tree.mesh,
                           in_specs=(spec_ts, P(None, axis), P(None, axis)),
                           out_specs=(spec_ts, P(None, axis)),
                           check_vma=False)
    return _timed(jax.jit(mapped, donate_argnums=(0,) if donate else ()),
                  "ea_cycle")
