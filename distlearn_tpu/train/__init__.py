"""Fused train-step builders — the TPU hot path (SURVEY.md §3.1's hot loop
collapsed into single XLA programs)."""

from distlearn_tpu.train.trainer import (TrainState, EATrainState,
                                         init_train_state, init_ea_state,
                                         build_sgd_step, build_sgd_scan_step,
                                         build_sync_step,
                                         build_eval_step, build_ea_steps,
                                         build_ea_cycle, reduce_confusion)
from distlearn_tpu.train.lm import (LMEAState, LMMixedState,
                                    build_lm_ea_steps,
                                    build_lm_mixed_step,
                                    build_lm_moe_metrics,
                                    build_lm_pp_1f1b_step,
                                    build_lm_pp_step, build_lm_step,
                                    init_lm_ea_state, init_lm_mixed_state,
                                    stack_blocks, unstack_blocks)
from distlearn_tpu.train.optim import (LMMixedOptaxState, LMOptaxState,
                                       LMZeroState,
                                       OptaxTrainState, ZeroTrainState,
                                       build_lm_mixed_optax_step,
                                       build_lm_optax_step,
                                       build_lm_zero_mesh_step,
                                       build_lm_zero_step,
                                       build_optax_step,
                                       build_zero_optax_step,
                                       init_lm_mixed_optax_state,
                                       init_lm_zero_mesh_state,
                                       init_lm_zero_state, init_optax_state,
                                       init_zero_state)

__all__ = [
    "TrainState", "EATrainState", "init_train_state", "init_ea_state",
    "build_sgd_step", "build_sgd_scan_step", "build_sync_step",
    "build_eval_step", "build_ea_steps", "build_ea_cycle",
    "reduce_confusion", "build_lm_step", "build_lm_moe_metrics",
    "build_lm_pp_step", "build_lm_pp_1f1b_step", "stack_blocks",
    "unstack_blocks",
    "LMEAState", "build_lm_ea_steps", "init_lm_ea_state",
    "OptaxTrainState", "build_optax_step", "init_optax_state",
    "ZeroTrainState", "build_zero_optax_step", "init_zero_state",
    "LMZeroState", "build_lm_zero_step", "init_lm_zero_state",
    "build_lm_zero_mesh_step", "init_lm_zero_mesh_state",
    "LMOptaxState", "build_lm_optax_step",
    "LMMixedState", "build_lm_mixed_step", "init_lm_mixed_state",
    "LMMixedOptaxState", "build_lm_mixed_optax_step",
    "init_lm_mixed_optax_state",
]
