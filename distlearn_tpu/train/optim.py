"""Optax-backed fused train step — the reference's ``optim`` library slot.

The reference's examples hand-roll SGD (examples/mnist.lua:112-116) but its
ecosystem slot for optimizers is the external ``optim`` package (sgd with
momentum, adagrad, ... — SURVEY.md §2b "optim/xlua/lapp" row).  The
TPU-native equivalent is optax: any ``GradientTransformation`` drops into
the same fused AllReduceSGD step — forward, backward, gradient psum with
contributor normalization, optimizer update, metrics — still ONE XLA
program per step.  :func:`build_sgd_step` stays the bare-SGD hot path
(reference parity + the Pallas fused-update route); this builder is the
general-optimizer variant.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax, random
from jax.sharding import PartitionSpec as P

from distlearn_tpu.utils.compat import shard_map

from distlearn_tpu.models.core import Model, loss_fn
from distlearn_tpu.ops import flatten as flatten_lib
from distlearn_tpu.parallel import allreduce_sgd
from distlearn_tpu.parallel import mesh as mesh_lib
from distlearn_tpu.parallel.mesh import MeshTree
from distlearn_tpu.utils import metrics as metrics_lib

PyTree = Any


class OptaxTrainState(NamedTuple):
    """Like trainer.TrainState plus the optimizer state (replicated — it is
    a deterministic function of the replicated params/grads)."""
    params: PyTree
    model_state: PyTree
    opt_state: PyTree
    sync: Any
    cm: jax.Array
    rng: jax.Array


def init_optax_state(model: Model, tree: MeshTree, tx, key: jax.Array,
                     num_classes: int) -> OptaxTrainState:
    from distlearn_tpu.train.trainer import init_common
    params, mstate, sync, cm, rng = init_common(model, tree, key,
                                                num_classes)
    return OptaxTrainState(params=params, model_state=mstate,
                           opt_state=tx.init(params), sync=sync, cm=cm,
                           rng=rng)


def build_optax_step(model: Model, tree: MeshTree, tx,
                     accum_steps: int = 1, donate: bool = True) -> Callable:
    """One fused data-parallel step with an optax optimizer:
    ``step(ts, x, y) -> (ts, loss)``.

    Same collective structure as :func:`~distlearn_tpu.train.build_sgd_step`
    (params replicated, batch sharded, grads psum'd + contributor-
    normalized before the update), with ``tx.update`` in place of the bare
    SGD rule — e.g. ``optax.sgd(lr, momentum=0.9)``, ``optax.adamw(lr)``.
    The optimizer state stays bitwise-replicated because every replica
    applies the identical psum'd gradient.

    ``accum_steps=k`` runs gradient accumulation: each device's shard is
    split into ``k`` microbatches processed by a ``lax.scan`` (live
    activation memory drops by ~k) whose averaged gradient feeds ONE
    psum + optimizer update — the effective batch is unchanged.  For
    batchnorm models the running stats are those of the LAST microbatch
    (the standard approximation); the loss/gradient math is exact for
    per-example losses.
    """
    axis = tree.axis_name
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")

    def step(ts: OptaxTrainState, x, y):
        rng, dropout_rng = random.split(ts.rng)
        dropout_rng = random.fold_in(dropout_rng, lax.axis_index(axis))

        if accum_steps == 1:
            def _loss(p):
                return loss_fn(model, p, ts.model_state, x, y, train=True,
                               rng=dropout_rng, axis_name=axis)

            (loss, (log_probs, mstate)), grads = \
                jax.value_and_grad(_loss, has_aux=True)(ts.params)
        else:
            if x.shape[0] % accum_steps:
                raise ValueError(
                    f"per-device batch {x.shape[0]} not divisible by "
                    f"accum_steps={accum_steps}")
            xm = x.reshape((accum_steps, -1) + x.shape[1:])
            ym = y.reshape((accum_steps, -1) + y.shape[1:])

            def micro(carry, inp):
                acc_g, acc_l, mstate, i = carry
                xi, yi = inp
                mb_rng = random.fold_in(dropout_rng, i)

                def _loss(p):
                    return loss_fn(model, p, mstate, xi, yi, train=True,
                                   rng=mb_rng, axis_name=axis)

                (li, (lp, mstate)), gi = \
                    jax.value_and_grad(_loss, has_aux=True)(ts.params)
                acc_g = jax.tree_util.tree_map(jnp.add, acc_g, gi)
                return (acc_g, acc_l + li, mstate, i + 1), lp

            zero_g = jax.tree_util.tree_map(jnp.zeros_like, ts.params)
            (acc_g, acc_l, mstate, _), lps = lax.scan(
                micro, (zero_g, jnp.zeros((), jnp.float32), ts.model_state,
                        jnp.zeros((), jnp.int32)), (xm, ym))
            # per-leaf dtype division: a strongly-typed f32 scalar would
            # silently promote bf16 grads (and then the optimizer state)
            grads = jax.tree_util.tree_map(
                lambda g: g / jnp.asarray(accum_steps, g.dtype), acc_g)
            loss = acc_l / jnp.float32(accum_steps)
            log_probs = lps.reshape((x.shape[0],) + lps.shape[2:])
        sync_local = mesh_lib.squeeze_node(ts.sync)
        grads, sync_local, _ = allreduce_sgd.sum_and_normalize_gradients(
            grads, sync_local, axis_name=axis)
        updates, opt_state = tx.update(grads, ts.opt_state, ts.params)
        params = jax.tree_util.tree_map(
            lambda p, u: p + u.astype(p.dtype), ts.params, updates)
        cm_new = metrics_lib.update_confusion(jnp.squeeze(ts.cm, 0),
                                              log_probs, y)
        new_ts = OptaxTrainState(params, mstate, opt_state,
                                 mesh_lib.expand_node(sync_local),
                                 cm_new[None], rng)
        return new_ts, lax.pmean(loss, axis)

    specs = OptaxTrainState(params=P(), model_state=P(), opt_state=P(),
                            sync=P(axis), cm=P(axis), rng=P())
    mapped = shard_map(step, mesh=tree.mesh, in_specs=(specs, P(axis),
                                                           P(axis)),
                           out_specs=(specs, P()), check_vma=False)
    return jax.jit(mapped, donate_argnums=(0,) if donate else ())


# ---------------------------------------------------------------------------
# ZeRO-1: optimizer state sharded over the data axis
# ---------------------------------------------------------------------------

class ZeroTrainState(NamedTuple):
    """Params replicated; OPTIMIZER STATE SHARDED — each device holds the
    state for only its 1/N slice of the flattened parameters (ZeRO stage 1:
    with Adam that cuts the 2x-params state memory by the data-axis size).
    ``opt_state`` leaves are stacked node arrays ``[N, ...]`` over the
    axis, like the EA per-node state."""
    params: PyTree
    model_state: PyTree
    opt_state: PyTree
    sync: Any
    cm: jax.Array
    rng: jax.Array


def _zero_layout(params: PyTree, n: int):
    """(FlatSpec, shard-divisible flat length, per-device chunk)."""
    for leaf in jax.tree_util.tree_leaves(params):
        if jnp.asarray(leaf).dtype != jnp.float32:
            raise ValueError(
                "ZeRO sharding packs params into one f32 buffer; got a "
                f"{jnp.asarray(leaf).dtype} leaf (use build_optax_step for "
                "mixed-dtype trees)")
    spec = flatten_lib.make_spec(params)
    total = ((spec.padded + n - 1) // n) * n
    return spec, total, total // n


def _pack_padded(spec, tree, total: int) -> jax.Array:
    flat = flatten_lib.pack(spec, tree)
    if total > spec.padded:
        flat = jnp.concatenate([flat, jnp.zeros(total - spec.padded,
                                                flat.dtype)])
    return flat


def _check_elementwise(tx, n: int):
    """Probe that ``tx`` commutes with sharding: updating a vector in one
    piece must equal updating its N chunks independently.  Catches
    slice-coupling transforms (e.g. ``clip_by_global_norm``) that would
    otherwise make ZeRO training silently diverge from the replicated-state
    step — each shard would see only its own norm."""
    # Multiple steps with DIRECTION-varying gradients: a one-step probe
    # cannot catch e.g. clip_by_global_norm->adam (adam cancels any
    # per-step uniform scale); across steps the shard-vs-full clip ratios
    # vary and the divergence shows.
    m = 8 * n
    p = jnp.linspace(-1.0, 1.0, m, dtype=jnp.float32)
    gs = [jnp.sin(jnp.arange(m, dtype=jnp.float32) * (0.3 + t))
          * (2.0 + 3.0 * t) for t in range(3)]
    state, pf = tx.init(p), p
    for g in gs:
        u, state = tx.update(g, state, pf)
        pf = pf + u
    shards = []
    for i in range(n):
        sl = slice(i * 8, (i + 1) * 8)
        s, pi = tx.init(p[sl]), p[sl]
        for g in gs:
            u, s = tx.update(g[sl], s, pi)
            pi = pi + u
        shards.append(pi)
    if not jnp.allclose(pf, jnp.concatenate(shards), rtol=1e-6, atol=1e-6):
        raise ValueError(
            "optimizer is not elementwise (its update couples parameter "
            "slices, e.g. a global-norm clip), so ZeRO sharding would "
            "silently change the training math — use build_optax_step")


def init_zero_state(model: Model, tree: MeshTree, tx, key: jax.Array,
                    num_classes: int) -> ZeroTrainState:
    from distlearn_tpu.train.trainer import init_common
    params, mstate, sync, cm, rng = init_common(model, tree, key,
                                                num_classes)
    n = tree.num_nodes
    _check_elementwise(tx, n)
    spec, total, chunk = _zero_layout(params, n)
    slices = _pack_padded(spec, params, total).reshape(n, chunk)
    per_dev = [tx.init(slices[i]) for i in range(n)]
    opt = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *per_dev)
    return ZeroTrainState(params=params, model_state=mstate,
                          opt_state=tree.put_per_node(opt), sync=sync,
                          cm=cm, rng=rng)


class LMZeroState(NamedTuple):
    """ZeRO-1 state for the LM family.  ``params`` replicated in the model
    dtype (f32 or bf16 — mixed trees allowed); ``master`` is the sharded
    FP32 MASTER COPY of the packed parameters (``[N, chunk]`` over the data
    axis) the optimizer actually updates — the mixed-precision recipe: bf16
    forward/backward, f32 update, params re-materialized from the master
    each step.  ``opt_state`` is the optimizer state over the f32 chunks,
    sharded the same way (ZeRO-1: Adam's 2x-params memory / N, plus the
    1x f32 master / N)."""
    params: PyTree
    master: jax.Array
    opt_state: PyTree


def _lm_zero_layout(params: PyTree, n: int):
    for leaf in jax.tree_util.tree_leaves(params):
        dt = getattr(leaf, "dtype", None) or jnp.asarray(leaf).dtype
        if not jnp.issubdtype(dt, jnp.floating):
            raise ValueError(
                f"ZeRO master copy requires floating leaves, got {dt}")
    spec = flatten_lib.make_spec(params)
    total = ((spec.padded + n - 1) // n) * n
    return spec, total, total // n


def init_lm_zero_state(params: PyTree, tree: MeshTree, tx) -> LMZeroState:
    """Shard the f32 master + optimizer state over the data axis.  ``tx``
    must be elementwise (same probe as :func:`init_zero_state`)."""
    n = tree.num_nodes
    _check_elementwise(tx, n)
    spec, total, chunk = _lm_zero_layout(params, n)
    slices = _pack_padded(spec, params, total).reshape(n, chunk)
    per_dev = [tx.init(slices[i]) for i in range(n)]
    opt = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *per_dev)
    return LMZeroState(params=params,
                       master=tree.put_per_node(slices),
                       opt_state=tree.put_per_node(opt))


def build_lm_zero_step(model: Model, tree: MeshTree, tx,
                       moe_balance_weight: float = 0.0,
                       donate: bool = True) -> Callable:
    """ZeRO-1 train step for the transformer-LM family:
    ``step(st, tokens) -> (st, loss)`` over the data mesh axis.

    Same comm recipe as :func:`build_zero_optax_step` — pack local grads
    flat (cast f32), **reduce-scatter** so each device receives only the
    summed 1/N chunk its optimizer state covers, sliced elementwise
    ``tx.update`` against the sharded F32 MASTER slice, one tiled
    ``all_gather`` re-materializes the replicated params — applied to the
    model family where optimizer-state memory actually matters, with
    mixed-precision support the classifier variant rejects: bf16 (or
    mixed) param trees train against f32 master copies, cut N-ways across
    the axis.  Data parallelism only on this builder; the TP/SP-composed
    variant over a (data, seq, model) mesh is
    :func:`build_lm_zero_mesh_step`.  From the reference's
    viewpoint this is the ``optim``-slot upgrade of lua/AllReduceSGD.lua's
    hot loop: allreduce-equivalent bandwidth, state memory / N.
    """
    from distlearn_tpu.models.transformer import lm_loss
    axis = tree.axis_name
    n = tree.num_nodes

    def step(st: LMZeroState, tokens):
        spec, total, chunk = _lm_zero_layout(st.params, n)
        loss, grads = jax.value_and_grad(
            lambda p: lm_loss(model, p, tokens, seq_axis=None, tp_axis=None,
                              moe_balance_weight=moe_balance_weight)
            )(st.params)
        gslice = lax.psum_scatter(
            _pack_padded(spec, grads, total), axis,
            scatter_dimension=0, tiled=True) / jnp.float32(n)
        master_local = jnp.squeeze(st.master, 0)          # [chunk] f32
        opt_local = mesh_lib.squeeze_node(st.opt_state)
        updates, opt_local = tx.update(gslice, opt_local, master_local)
        master_local = master_local + updates
        flat_new = lax.all_gather(master_local, axis, tiled=True)  # [total]
        params = flatten_lib.unpack(spec, flat_new)   # casts to leaf dtypes
        return (LMZeroState(params, master_local[None],
                            mesh_lib.expand_node(opt_local)),
                lax.pmean(loss, axis))

    specs = LMZeroState(params=P(), master=P(axis), opt_state=P(axis))
    mapped = shard_map(step, mesh=tree.mesh, in_specs=(specs, P(axis)),
                           out_specs=(specs, P()), check_vma=False)
    return jax.jit(mapped, donate_argnums=(0,) if donate else ())


class LMOptaxState(NamedTuple):
    """Replicated-state optax training for the LM family."""
    params: PyTree
    opt_state: PyTree


def build_lm_optax_step(model: Model, mesh, tx,
                        data_axis: str = "data",
                        seq_axis: str | None = "seq",
                        accum_steps: int = 1,
                        moe_balance_weight: float = 0.0,
                        donate: bool = True,
                        seq_layout: str = "contig") -> Callable:
    """Any optax optimizer on the transformer-LM family over a
    ``(data, seq)`` mesh: ``step(st, tokens) -> (st, loss)`` with
    ``st = LMOptaxState(params, opt_state)``, both replicated (every
    replica applies the identical psum'd gradient, so the state stays
    bitwise-replicated — the ``build_optax_step`` recipe on the model
    family the reference never had).  Initialize with
    ``LMOptaxState(params, tx.init(params))``.

    Tensor-parallel or expert-sharded leaves would need sharded optimizer
    state; pass ``tp_axis`` work to :func:`build_lm_zero_mesh_step`
    (sharded f32 masters) instead — this builder rejects nothing because
    it simply never shards params.  MoE models run with all experts
    resident (``ep_axis=None``); ``moe_balance_weight`` folds the Switch
    auxiliary loss in.  ``accum_steps`` microbatches the per-device rows
    exactly as :func:`distlearn_tpu.train.lm.build_lm_step` does.
    """
    from distlearn_tpu.train.lm import lm_local_grads
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    axes = tuple(a for a in (data_axis, seq_axis) if a is not None)

    def step(st: LMOptaxState, tokens):
        local_loss, grads = lm_local_grads(
            model, st.params, tokens, seq_axis=seq_axis, tp_axis=None,
            accum_steps=accum_steps,
            moe_balance_weight=moe_balance_weight, seq_layout=seq_layout)
        loss = lax.psum(local_loss, seq_axis) if seq_axis else local_loss
        dp = lax.psum(1, data_axis)
        grads = jax.tree_util.tree_map(
            lambda g: lax.psum(g, axes) / jnp.asarray(dp, g.dtype), grads)
        updates, opt_state = tx.update(grads, st.opt_state, st.params)
        params = jax.tree_util.tree_map(
            lambda p, u: p + u.astype(p.dtype), st.params, updates)
        return (LMOptaxState(params, opt_state),
                lax.pmean(loss, data_axis))

    tok_spec = P(data_axis, seq_axis) if seq_axis else P(data_axis)
    spec = LMOptaxState(params=P(), opt_state=P())
    mapped = shard_map(step, mesh=mesh, in_specs=(spec, tok_spec),
                           out_specs=(spec, P()), check_vma=False)
    return jax.jit(mapped, donate_argnums=(0,) if donate else ())


class LMMixedOptaxState(NamedTuple):
    """Mixed-precision optax LM training: bf16 working ``params`` (what
    the matmuls read), f32 ``master`` (what the optimizer walks), and the
    optimizer state over the master (see
    :class:`distlearn_tpu.train.lm.LMMixedState` for the traffic
    analysis)."""
    params: PyTree
    master: PyTree
    opt_state: PyTree


def init_lm_mixed_optax_state(params, tx,
                              param_dtype=jnp.bfloat16
                              ) -> LMMixedOptaxState:
    """Master := the f32 init, working copy := its cast, optimizer state
    over the MASTER (moments accumulate in f32)."""
    cast = jax.tree_util.tree_map(lambda p: p.astype(param_dtype), params)
    return LMMixedOptaxState(params=cast, master=params,
                             opt_state=tx.init(params))


def build_lm_mixed_optax_step(model: Model, mesh, tx,
                              data_axis: str = "data",
                              seq_axis: str | None = "seq",
                              accum_steps: int = 1,
                              moe_balance_weight: float = 0.0,
                              grad_dtype=jnp.float32,
                              donate: bool = True,
                              seq_layout: str = "contig") -> Callable:
    """:func:`build_lm_optax_step` with bf16 working params + f32 masters
    (``step(st, tokens) -> (st, loss)`` on :class:`LMMixedOptaxState`):
    gradients come off the bf16-param backward, are upcast to
    ``grad_dtype`` for the cross-replica psum, feed ``tx.update`` against
    the f32 master, and the new master re-casts into the working copy —
    the f32 elementwise traffic is confined to the optimizer itself while
    every matmul pass reads 2-byte weights.  Initialize with
    :func:`init_lm_mixed_optax_state`."""
    from distlearn_tpu.train.lm import lm_local_grads
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    axes = tuple(a for a in (data_axis, seq_axis) if a is not None)

    def step(st: LMMixedOptaxState, tokens):
        local_loss, grads = lm_local_grads(
            model, st.params, tokens, seq_axis=seq_axis, tp_axis=None,
            accum_steps=accum_steps,
            moe_balance_weight=moe_balance_weight, seq_layout=seq_layout)
        loss = lax.psum(local_loss, seq_axis) if seq_axis else local_loss
        dp = lax.psum(1, data_axis)
        grads = jax.tree_util.tree_map(
            lambda g: lax.psum(g.astype(grad_dtype), axes)
            / jnp.asarray(dp, grad_dtype), grads)
        updates, opt_state = tx.update(grads, st.opt_state, st.master)
        master = jax.tree_util.tree_map(
            lambda m, u: m + u.astype(m.dtype), st.master, updates)
        params = jax.tree_util.tree_map(
            lambda p, m: m.astype(p.dtype), st.params, master)
        return (LMMixedOptaxState(params, master, opt_state),
                lax.pmean(loss, data_axis))

    tok_spec = P(data_axis, seq_axis) if seq_axis else P(data_axis)
    spec = LMMixedOptaxState(params=P(), master=P(), opt_state=P())
    mapped = shard_map(step, mesh=mesh, in_specs=(spec, tok_spec),
                           out_specs=(spec, P()), check_vma=False)
    return jax.jit(mapped, donate_argnums=(0,) if donate else ())


def fsdp_param_specs(params: PyTree, mesh,
                     data_axis: str = "data") -> PyTree:
    """ZeRO-3 / FSDP shardings: every leaf sharded over ``data_axis``
    along its LARGEST evenly-divisible dimension (balanced slices);
    leaves with no divisible dimension stay replicated.  Unlike
    :func:`distlearn_tpu.models.transformer.param_specs` (which encodes
    the TP/EP math), these specs carry no algebra — they are pure
    storage partitioning for the compiler-driven composition below."""
    n = mesh.shape[data_axis]

    def spec_for(leaf):
        shape = tuple(jnp.shape(leaf))
        for i, _ in sorted(enumerate(shape), key=lambda t: -t[1]):
            if shape[i] >= n and shape[i] % n == 0:
                return P(*([None] * i + [data_axis]))
        return P()

    return jax.tree_util.tree_map(spec_for, params)


def init_lm_fsdp_params(params: PyTree, mesh,
                        data_axis: str = "data") -> PyTree:
    """Place params fully sharded (1/N of the model resident per device
    for every divisible leaf) for :func:`build_lm_fsdp_step`."""
    from jax.sharding import NamedSharding
    return jax.device_put(params, jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s),
        fsdp_param_specs(params, mesh, data_axis)))


def build_lm_fsdp_step(model: Model, mesh, params_template, lr: float,
                       data_axis: str = "data", accum_steps: int = 1,
                       donate: bool = True) -> Callable:
    """Fully-sharded data parallelism (ZeRO-3) for the LM family —
    ``step(params, tokens) -> (params, loss)`` with parameters LIVING
    sharded over the data axis, completing the ZeRO ladder next to the
    ZeRO-1 builders (sharded optimizer state, replicated params).

    This is deliberately the OTHER TPU idiom from the shard_map
    builders: a plain ``jit`` over the GLOBAL computation with sharding
    annotations on inputs/outputs and ``with_sharding_constraint`` on
    gradients/updates — XLA's SPMD partitioner inserts the weight
    all-gathers before each use (forward and backward), reduce-scatters
    each gradient back to its owner shard, and runs the update on the
    local 1/N slice.  Annotate, let the compiler place collectives —
    the composition recipe the explicit-collective builders complement.
    Batch semantics match ``build_lm_step`` at ``sp=tp=1``: the global
    batch shards over ``data_axis`` and the loss is the global mean, so
    the two steps are numerically interchangeable (tested).

    ``accum_steps=k`` scans k equal microbatches of the global batch
    and averages — the same memory lever (and exact-equivalence
    semantics) as ``build_lm_step``'s.  Dense models; place params with
    :func:`init_lm_fsdp_params`."""
    from jax.sharding import NamedSharding
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    specs = fsdp_param_specs(params_template, mesh, data_axis)
    shardings = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs)
    tok_sharding = NamedSharding(mesh, P(data_axis))
    from distlearn_tpu.models.transformer import lm_loss as _lm_loss

    def loss_and_grads(params, tokens):
        if accum_steps == 1:
            return jax.value_and_grad(
                lambda p: _lm_loss(model, p, tokens))(params)
        if tokens.shape[0] % accum_steps:
            raise ValueError(
                f"global batch {tokens.shape[0]} not divisible by "
                f"accum_steps={accum_steps}")
        micro = tokens.reshape((accum_steps, -1) + tokens.shape[1:])

        def body(carry, toks):
            acc_l, acc_g = carry
            li, gi = jax.value_and_grad(
                lambda p: _lm_loss(model, p, toks))(params)
            return (acc_l + li,
                    jax.tree_util.tree_map(jnp.add, acc_g, gi)), None

        zero = jax.tree_util.tree_map(jnp.zeros_like, params)
        (l, g), _ = lax.scan(body, (jnp.zeros((), jnp.float32), zero),
                             micro)
        # equal microbatches: the mean of per-micro means IS the global
        # mean, and likewise for the gradients
        return (l / jnp.float32(accum_steps),
                jax.tree_util.tree_map(
                    lambda x: x / jnp.asarray(accum_steps, x.dtype), g))

    def step(params, tokens):
        loss, grads = loss_and_grads(params, tokens)
        # the ONE load-bearing constraint: gradients owned shard-wise
        # forces GSPMD's reduce-scatter here and a sharded update below
        # (out_shardings pins the returned params' layout)
        grads = jax.lax.with_sharding_constraint(grads, shardings)
        new = jax.tree_util.tree_map(
            lambda p, g: p - jnp.asarray(lr, p.dtype) * g.astype(p.dtype),
            params, grads)
        return new, loss

    return jax.jit(step, in_shardings=(shardings, tok_sharding),
                   out_shardings=(shardings, NamedSharding(mesh, P())),
                   donate_argnums=(0,) if donate else ())


def _local_template(params: PyTree, pspecs: PyTree, mesh) -> PyTree:
    """ShapeDtypeStructs of each leaf's LOCAL shard under ``pspecs``."""
    def shrink(leaf, spec):
        shape = list(jnp.shape(leaf))
        for i, ax in enumerate(tuple(spec)):
            if ax is not None:
                axes = (ax,) if isinstance(ax, str) else tuple(ax)
                for a in axes:
                    shape[i] //= mesh.shape[a]
        return jax.ShapeDtypeStruct(tuple(shape),
                                    jnp.asarray(leaf).dtype)
    return jax.tree_util.tree_map(shrink, params, pspecs)


def init_lm_zero_mesh_state(params, mesh, tx, data_axis: str = "data",
                            tp_axis: str | None = "model") -> LMZeroState:
    """ZeRO-1 state over a multi-axis mesh: the f32 master + optimizer
    state cover each device's LOCAL (TP-sharded) parameters, cut
    ``data``-ways across the data axis — ZeRO composed with tensor (and
    sequence) parallelism.  ``params`` must already be placed with
    :func:`distlearn_tpu.models.transformer.param_specs` shardings.
    Master layout: ``[n_data, n_tp, chunk]`` sharded ``P(data, tp)`` —
    unspecified mesh axes (e.g. seq) are replicated, so no seq argument
    is needed here; every seq rank holds and updates the same slice.
    """
    from distlearn_tpu.models.transformer import param_specs
    n = mesh.shape[data_axis]
    _check_elementwise(tx, n)
    pspecs = param_specs(params, tp_axis)
    local_t = _local_template(params, pspecs, mesh)
    spec, total, chunk = _lm_zero_layout(local_t, n)

    def init(params_local):
        flat = _pack_padded(spec, params_local, total)
        my = lax.axis_index(data_axis)
        mine = lax.dynamic_slice_in_dim(flat, my * chunk, chunk)
        opt = tx.init(mine)
        exp = lambda a: jnp.asarray(a)[None, None]      # noqa: E731
        return (exp(mine),
                jax.tree_util.tree_map(exp, opt))

    out_spec = P(data_axis, tp_axis) if tp_axis else P(data_axis, None)
    master, opt = jax.jit(shard_map(
        init, mesh=mesh, in_specs=(pspecs,),
        out_specs=(out_spec,
                   jax.tree_util.tree_map(lambda _: out_spec,
                                          tx.init(jnp.zeros((chunk,),
                                                            jnp.float32)))),
        check_vma=False))(params)
    return LMZeroState(params=params, master=master, opt_state=opt)


def build_lm_zero_mesh_step(model: Model, mesh, params_template, tx,
                            data_axis: str = "data",
                            seq_axis: str | None = "seq",
                            tp_axis: str | None = "model",
                            moe_balance_weight: float = 0.0,
                            donate: bool = True) -> Callable:
    """ZeRO-1 LM step composed with tensor + sequence parallelism over a
    ``(data, seq, model)`` mesh: ``step(st, tokens) -> (st, loss)``.

    Per device: grads of the local loss share (ring attention over
    ``seq_axis``, Megatron TP over ``tp_axis`` — the
    :func:`build_lm_step` math), packed flat in f32; the seq-axis psum
    runs on the packed buffer (every leaf — TP shards included — reduces
    over seq exactly as in ``build_lm_step``), the data-axis reduction is
    the ZeRO **reduce-scatter**, the sliced elementwise update runs
    against the sharded f32 master, and one data-axis ``all_gather``
    re-materializes the local params.  Optimizer-state memory: local
    params (already /TP for the sharded leaves) further cut /data.
    MoE/EP is not supported here (expert leaves must not reduce over
    their own axis); use :func:`build_lm_step` for MoE models.
    """
    from distlearn_tpu.models.transformer import lm_loss, param_specs
    n = mesh.shape[data_axis]
    pspecs = param_specs(params_template, tp_axis)
    local_t = _local_template(params_template, pspecs, mesh)
    spec, total, chunk = _lm_zero_layout(local_t, n)

    def step(st: LMZeroState, tokens):
        params = st.params
        loss, grads = jax.value_and_grad(
            lambda p: lm_loss(model, p, tokens, seq_axis=seq_axis,
                              tp_axis=tp_axis, reduce=False,
                              moe_balance_weight=moe_balance_weight)
            )(params)
        loss = lax.psum(loss, seq_axis) if seq_axis else loss
        flat = _pack_padded(spec, grads, total)
        if seq_axis:
            flat = lax.psum(flat, seq_axis)
        gslice = lax.psum_scatter(flat, data_axis, scatter_dimension=0,
                                  tiled=True) / jnp.float32(n)
        master_local = jnp.squeeze(st.master, (0, 1))     # [chunk] f32
        opt_local = jax.tree_util.tree_map(
            lambda a: jnp.squeeze(a, (0, 1)), st.opt_state)
        updates, opt_local = tx.update(gslice, opt_local, master_local)
        master_local = master_local + updates
        flat_new = lax.all_gather(master_local, data_axis, tiled=True)
        new_params = flatten_lib.unpack(spec, flat_new)
        exp = lambda a: jnp.asarray(a)[None, None]        # noqa: E731
        return (LMZeroState(new_params, exp(master_local),
                            jax.tree_util.tree_map(exp, opt_local)),
                lax.pmean(loss, data_axis))

    zspec = P(data_axis, tp_axis) if tp_axis else P(data_axis, None)
    st_spec = LMZeroState(
        params=pspecs, master=zspec,
        opt_state=jax.tree_util.tree_map(
            lambda _: zspec, tx.init(jnp.zeros((chunk,), jnp.float32))))
    tok_spec = P(data_axis, seq_axis) if seq_axis else P(data_axis)
    mapped = shard_map(step, mesh=mesh, in_specs=(st_spec, tok_spec),
                           out_specs=(st_spec, P()), check_vma=False)
    return jax.jit(mapped, donate_argnums=(0,) if donate else ())


def build_zero_optax_step(model: Model, tree: MeshTree, tx,
                          donate: bool = True) -> Callable:
    """ZeRO-1 fused step: ``step(ts, x, y) -> (ts, loss)``.

    Comm structure (the ZeRO-1 recipe): local gradients are packed flat
    and **reduce-scattered** — each device receives only the summed 1/N
    chunk its optimizer state covers (~P bytes over the ring vs ~2P for
    the non-sharded path's full allreduce) — the sliced elementwise
    ``tx.update`` runs against the sharded state, and ONE tiled
    ``all_gather`` reassembles the updated parameters (replicated again
    for the next step).  Net: allreduce-equivalent bandwidth
    (reduce-scatter + all-gather) with the optimizer-state memory cut by
    N.  Restricted to ELEMENTWISE optimizers (adam, momentum, rmsprop...):
    a transform that couples slices, e.g. ``clip_by_global_norm``, would
    see only its shard's norm.  Full participation each step (uneven-step
    accounting keeps the reference cadence via the sync counter).
    """
    axis = tree.axis_name
    n = tree.num_nodes

    def step(ts: ZeroTrainState, x, y):
        spec, total, chunk = _zero_layout(ts.params, n)
        rng, dropout_rng = random.split(ts.rng)
        dropout_rng = random.fold_in(dropout_rng, lax.axis_index(axis))

        def _loss(p):
            return loss_fn(model, p, ts.model_state, x, y, train=True,
                           rng=dropout_rng, axis_name=axis)

        (loss, (log_probs, mstate)), grads = \
            jax.value_and_grad(_loss, has_aux=True)(ts.params)
        sync_local = mesh_lib.squeeze_node(ts.sync)
        sync_local = allreduce_sgd.SGDSyncState(
            my_steps=sync_local.my_steps + 1)

        # reduce-scatter the packed LOCAL grads: arrives pre-sliced +
        # summed; normalize by the (full-participation) node count
        my = lax.axis_index(axis)
        gslice = lax.psum_scatter(
            _pack_padded(spec, grads, total), axis,
            scatter_dimension=0, tiled=True) / jnp.float32(n)
        pslice = lax.dynamic_slice_in_dim(
            _pack_padded(spec, ts.params, total), my * chunk, chunk)
        opt_local = mesh_lib.squeeze_node(ts.opt_state)
        updates, opt_local = tx.update(gslice, opt_local, pslice)
        new_slice = pslice + updates
        flat_new = lax.all_gather(new_slice, axis, tiled=True)   # [total]
        params = flatten_lib.unpack(spec, flat_new)

        cm_new = metrics_lib.update_confusion(jnp.squeeze(ts.cm, 0),
                                              log_probs, y)
        new_ts = ZeroTrainState(params, mstate,
                                mesh_lib.expand_node(opt_local),
                                mesh_lib.expand_node(sync_local),
                                cm_new[None], rng)
        return new_ts, lax.pmean(loss, axis)

    specs = ZeroTrainState(params=P(), model_state=P(), opt_state=P(axis),
                           sync=P(axis), cm=P(axis), rng=P())
    mapped = shard_map(step, mesh=tree.mesh, in_specs=(specs, P(axis),
                                                           P(axis)),
                           out_specs=(specs, P()), check_vma=False)
    return jax.jit(mapped, donate_argnums=(0,) if donate else ())
