"""Optax-backed fused train step — the reference's ``optim`` library slot.

The reference's examples hand-roll SGD (examples/mnist.lua:112-116) but its
ecosystem slot for optimizers is the external ``optim`` package (sgd with
momentum, adagrad, ... — SURVEY.md §2b "optim/xlua/lapp" row).  The
TPU-native equivalent is optax: any ``GradientTransformation`` drops into
the same fused AllReduceSGD step — forward, backward, gradient psum with
contributor normalization, optimizer update, metrics — still ONE XLA
program per step.  :func:`build_sgd_step` stays the bare-SGD hot path
(reference parity + the Pallas fused-update route); this builder is the
general-optimizer variant.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax, random
from jax.sharding import PartitionSpec as P

from distlearn_tpu.models.core import Model, loss_fn
from distlearn_tpu.parallel import allreduce_sgd
from distlearn_tpu.parallel import mesh as mesh_lib
from distlearn_tpu.parallel.mesh import MeshTree
from distlearn_tpu.utils import metrics as metrics_lib

PyTree = Any


class OptaxTrainState(NamedTuple):
    """Like trainer.TrainState plus the optimizer state (replicated — it is
    a deterministic function of the replicated params/grads)."""
    params: PyTree
    model_state: PyTree
    opt_state: PyTree
    sync: Any
    cm: jax.Array
    rng: jax.Array


def init_optax_state(model: Model, tree: MeshTree, tx, key: jax.Array,
                     num_classes: int) -> OptaxTrainState:
    init_key, train_key = random.split(key)
    params, mstate = model.init(init_key)
    n = tree.num_nodes
    return OptaxTrainState(
        params=params, model_state=mstate, opt_state=tx.init(params),
        sync=allreduce_sgd.SGDSyncState(
            my_steps=tree.put_per_node(jnp.zeros((n,), jnp.int32))),
        cm=tree.put_per_node(jnp.zeros((n, num_classes, num_classes),
                                       jnp.int32)),
        rng=train_key)


def build_optax_step(model: Model, tree: MeshTree, tx,
                     donate: bool = True) -> Callable:
    """One fused data-parallel step with an optax optimizer:
    ``step(ts, x, y) -> (ts, loss)``.

    Same collective structure as :func:`~distlearn_tpu.train.build_sgd_step`
    (params replicated, batch sharded, grads psum'd + contributor-
    normalized before the update), with ``tx.update`` in place of the bare
    SGD rule — e.g. ``optax.sgd(lr, momentum=0.9)``, ``optax.adamw(lr)``.
    The optimizer state stays bitwise-replicated because every replica
    applies the identical psum'd gradient.
    """
    axis = tree.axis_name

    def step(ts: OptaxTrainState, x, y):
        rng, dropout_rng = random.split(ts.rng)
        dropout_rng = random.fold_in(dropout_rng, lax.axis_index(axis))

        def _loss(p):
            return loss_fn(model, p, ts.model_state, x, y, train=True,
                           rng=dropout_rng, axis_name=axis)

        (loss, (log_probs, mstate)), grads = \
            jax.value_and_grad(_loss, has_aux=True)(ts.params)
        sync_local = mesh_lib.squeeze_node(ts.sync)
        grads, sync_local, _ = allreduce_sgd.sum_and_normalize_gradients(
            grads, sync_local, axis_name=axis)
        updates, opt_state = tx.update(grads, ts.opt_state, ts.params)
        params = jax.tree_util.tree_map(
            lambda p, u: p + u.astype(p.dtype), ts.params, updates)
        cm_new = metrics_lib.update_confusion(jnp.squeeze(ts.cm, 0),
                                              log_probs, y)
        new_ts = OptaxTrainState(params, mstate, opt_state,
                                 mesh_lib.expand_node(sync_local),
                                 cm_new[None], rng)
        return new_ts, lax.pmean(loss, axis)

    specs = OptaxTrainState(params=P(), model_state=P(), opt_state=P(),
                            sync=P(axis), cm=P(axis), rng=P())
    mapped = jax.shard_map(step, mesh=tree.mesh, in_specs=(specs, P(axis),
                                                           P(axis)),
                           out_specs=(specs, P()), check_vma=False)
    return jax.jit(mapped, donate_argnums=(0,) if donate else ())
