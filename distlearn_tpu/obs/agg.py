"""Fleet aggregation + SLO engine — the central half of the
observability plane.

Every process keeps its own registry (``obs.core``); this module merges
their ``snapshot_record()``s into ONE fleet view and evaluates
declarative service-level objectives against it:

* :class:`FleetRegistry` — latest snapshot per *source* (a process),
  merged on demand: counters and gauges sum across sources,
  fixed-bucket histograms merge bucket-by-bucket.  Histogram merging is
  EXACT if and only if the bucket bounds are identical — mismatched
  bounds raise :class:`MergeError` rather than silently mis-binning
  (tests/test_obs.py property-tests both directions).  Re-ingesting a
  source REPLACES its contribution (snapshots are cumulative per
  process), so polling twice never double-counts.
* :class:`Collector` — pulls ``/snapshot`` from each process's export
  HTTP endpoint (obs/export.py) and/or tails JSONL trails (the
  ``--obsLog`` files), ingesting into a fleet registry.  A dead
  endpoint is skipped and counted (``obs_agg_poll_failures_total``),
  never fatal — the aggregation plane must outlive any one member.
* :class:`SLOEngine` — declarative rules (docs/OBSERVABILITY.md "SLO
  rule schema"): ``quantile`` (a histogram's estimated p95/p99 against
  a target) and ``burn_rate`` (bad/total ratio over a rolling window
  against an error budget).  Breaches and recoveries are first-class
  obs events: ``slo_ok{slo}`` / ``slo_value{slo}`` gauges,
  ``slo_breaches_total{slo}`` / ``slo_recoveries_total{slo}`` counters,
  and ``slo.breach`` / ``slo.recover`` span records in the trail — so
  ``diststat`` shows them and ``tools/autoscaler.py`` acts on them.

Everything here is consumer-side: ingesting a snapshot never touches
the process-local registry, and the module stays dependency-free
(stdlib ``urllib`` for the pull).
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
import urllib.request

from distlearn_tpu.obs import core, trace

__all__ = ["MergeError", "FleetRegistry", "Collector", "SLOEngine",
           "merge_histograms", "estimate_quantile"]


class MergeError(ValueError):
    """Two histogram samples with different bucket bounds cannot be
    merged exactly — refusing beats silently mis-binning."""


def merge_histograms(a: dict, b: dict) -> dict:
    """Merge two histogram samples (``{"sum", "count", "buckets":
    {bound: n}, "inf": n}``) bucket-by-bucket.  Exact when bounds match
    (bucket counts and totals add); raises :class:`MergeError` when
    they don't."""
    ab, bb = list(a.get("buckets", {})), list(b.get("buckets", {}))
    if ab != bb:
        raise MergeError(
            f"histogram bucket bounds differ: {ab!r} vs {bb!r} — "
            "merging would mis-bin; re-bucket at the source instead")
    return {"sum": a.get("sum", 0.0) + b.get("sum", 0.0),
            "count": a.get("count", 0) + b.get("count", 0),
            "buckets": {k: a["buckets"][k] + b["buckets"][k] for k in ab},
            "inf": a.get("inf", 0) + b.get("inf", 0)}


def estimate_quantile(sample: dict, q: float) -> float:
    """Estimate quantile ``q`` (0..1) from a histogram sample by linear
    interpolation inside the target bucket (the Prometheus
    ``histogram_quantile`` rule).  ``nan`` on an empty histogram; the
    highest finite bound when the target lands in +Inf."""
    count = sample.get("count", 0)
    if count <= 0:
        return float("nan")
    bounds = [float(k) for k in sample.get("buckets", {})]
    counts = list(sample.get("buckets", {}).values())
    target = q * count
    cum = 0.0
    for i, (bound, n) in enumerate(zip(bounds, counts)):
        if cum + n >= target and n > 0:
            lo = bounds[i - 1] if i > 0 else 0.0
            frac = (target - cum) / n
            return lo + (bound - lo) * frac
        cum += n
    return bounds[-1] if bounds else float("nan")


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in (labels or {}).items()))


def _matches(labels: dict, match: dict | None) -> bool:
    if not match:
        return True
    labels = labels or {}
    return all(str(labels.get(k)) == str(v) for k, v in match.items())


class FleetRegistry:
    """Latest snapshot per source, merged on demand.  Thread-safe: the
    collector ingests from its poll loop while the SLO engine and the
    autoscaler read."""

    def __init__(self):
        self._lock = threading.Lock()
        self._by_source: dict[str, list] = {}
        self._ts: dict[str, float] = {}

    def ingest(self, rec: dict, source: str):
        """Adopt one process's ``snapshot_record()``.  A later ingest
        from the same ``source`` replaces the earlier one — per-process
        snapshots are cumulative, so replace-not-add is what keeps the
        fleet totals exact."""
        if not isinstance(rec, dict) or rec.get("type") != "snapshot":
            raise ValueError(f"not a snapshot record: {rec!r}")
        with self._lock:
            self._by_source[str(source)] = rec.get("metrics", [])
            self._ts[str(source)] = float(rec.get("ts", 0.0))

    def forget(self, source: str):
        """Drop a source's contribution (a retired fleet member)."""
        with self._lock:
            self._by_source.pop(str(source), None)
            self._ts.pop(str(source), None)

    def sources(self) -> dict[str, float]:
        """source -> snapshot timestamp of the current contribution."""
        with self._lock:
            return dict(self._ts)

    # -- merged views --------------------------------------------------------
    def merged(self) -> dict[str, dict]:
        """name -> ``{"kind", "help", "labelnames", "samples": [...]}``
        with every source's samples merged per label set.  Raises
        :class:`MergeError` on kind or histogram-bound skew between
        sources — config skew is an error, not an average."""
        with self._lock:
            items = list(self._by_source.items())
        out: dict[str, dict] = {}
        for _source, metrics in items:
            for fam in metrics:
                name, kind = fam["name"], fam["kind"]
                dst = out.get(name)
                if dst is None:
                    dst = out[name] = {"name": name, "kind": kind,
                                       "help": fam.get("help", ""),
                                       "labelnames":
                                           list(fam.get("labelnames", [])),
                                       "samples": {}}
                elif dst["kind"] != kind:
                    raise MergeError(
                        f"metric {name!r} is a {dst['kind']} on one "
                        f"source and a {kind} on another")
                for s in fam.get("samples", []):
                    key = _label_key(s.get("labels", {}))
                    have = dst["samples"].get(key)
                    if have is None:
                        dst["samples"][key] = dict(s)
                    elif kind == "histogram":
                        merged = merge_histograms(have, s)
                        merged["labels"] = have.get("labels", {})
                        dst["samples"][key] = merged
                    else:
                        have["value"] = have.get("value", 0) + s.get(
                            "value", 0)
        for fam in out.values():
            fam["samples"] = list(fam["samples"].values())
        return out

    def total(self, name: str, match: dict | None = None) -> float:
        """Fleet-wide sum of a counter/gauge over the label sets that
        carry every ``match`` pair (0.0 when absent)."""
        total = 0.0
        fam = self.merged().get(name)
        for s in (fam or {}).get("samples", []):
            if _matches(s.get("labels"), match):
                total += s.get("value", 0)
        return total

    def histogram(self, name: str, match: dict | None = None) -> dict | None:
        """Fleet-merged histogram sample for ``name`` (label sets that
        match fold together), or ``None`` when no source reports it."""
        fam = self.merged().get(name)
        if not fam or fam["kind"] != "histogram":
            return None
        acc = None
        for s in fam["samples"]:
            if not _matches(s.get("labels"), match):
                continue
            acc = dict(s) if acc is None else merge_histograms(acc, s)
        return acc

    def breakdown(self, name: str, match: dict | None = None
                  ) -> dict[str, float]:
        """source -> that process's contribution to ``total(name)`` —
        the per-process column ``diststat merge`` prints."""
        with self._lock:
            items = list(self._by_source.items())
        out: dict[str, float] = {}
        for source, metrics in items:
            v = 0.0
            seen = False
            for fam in metrics:
                if fam["name"] != name:
                    continue
                for s in fam.get("samples", []):
                    if _matches(s.get("labels"), match):
                        if fam["kind"] == "histogram":
                            v += s.get("count", 0)
                        else:
                            v += s.get("value", 0)
                        seen = True
            if seen:
                out[source] = v
        return out


def read_trail_snapshot(path: str) -> dict | None:
    """The LAST snapshot record in one JSONL trail (the cumulative
    registry state), or ``None`` when the trail has none."""
    last = None
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue        # torn tail line of a live run
                if rec.get("type") == "snapshot":
                    last = rec
    except OSError:
        return None
    return last


class Collector:
    """Pull-based fleet ingestion: HTTP ``/snapshot`` endpoints and/or
    JSONL trails into one :class:`FleetRegistry`.

    Endpoint membership is mutated by the actuator/operator thread while
    :meth:`poll` runs on the autoscaler loop, so the source lists are
    guarded by ``_lock``; poll iterates a snapshot taken under it."""

    def __init__(self, endpoints=(), trails=(), *, timeout: float = 2.0,
                 fleet: FleetRegistry | None = None):
        """``endpoints``: ``(host, port)`` pairs or full URLs;
        ``trails``: JSONL paths (source = file basename)."""
        self._lock = threading.Lock()
        self.endpoints = [e if isinstance(e, str)
                          else f"http://{e[0]}:{int(e[1])}"
                          for e in endpoints]
        self.trails = [str(t) for t in trails]
        self.timeout = float(timeout)
        self.fleet = fleet if fleet is not None else FleetRegistry()
        self._c_polls = core.counter(
            "obs_agg_polls_total", "collector poll rounds completed")
        self._c_fail = core.counter(
            "obs_agg_poll_failures_total",
            "per-source ingest failures (endpoint down / trail torn)",
            labels=("source",))

    def add_endpoint(self, host: str, port: int):
        url = f"http://{host}:{int(port)}"
        with self._lock:
            if url not in self.endpoints:
                self.endpoints.append(url)

    def remove_endpoint(self, host: str, port: int):
        url = f"http://{host}:{int(port)}"
        with self._lock:
            if url not in self.endpoints:
                return
            self.endpoints.remove(url)
        # FleetRegistry has its own lock; don't nest it under ours
        self.fleet.forget(url)

    def poll(self) -> FleetRegistry:
        """One ingest round over every endpoint and trail.  Failures
        skip the source (its previous contribution stands) and count —
        the fleet view degrades gracefully while a member restarts."""
        with self._lock:
            endpoints = list(self.endpoints)
            trails = list(self.trails)
        for url in endpoints:
            try:
                with urllib.request.urlopen(url + "/snapshot",
                                            timeout=self.timeout) as resp:
                    rec = json.loads(resp.read().decode())
                self.fleet.ingest(rec, source=url)
            except (OSError, ValueError):
                self._c_fail.labels(source=url).inc()
        for path in trails:
            rec = read_trail_snapshot(path)
            if rec is None:
                self._c_fail.labels(source=os.path.basename(path)).inc()
                continue
            self.fleet.ingest(rec, source=os.path.basename(path))
        self._c_polls.inc()
        return self.fleet


_RULE_KINDS = ("quantile", "burn_rate")


class SLOEngine:
    """Evaluate declarative SLO rules against a fleet registry.

    Rule schema (a list of dicts, validated at construction):

    * ``{"name", "kind": "quantile", "metric", "q", "target"[,
      "match", "window_s"]}`` — estimated quantile ``q`` of histogram
      ``metric`` must be ≤ ``target``.  With ``window_s``, the quantile
      is taken over the trailing window only (bucket-wise difference of
      the cumulative histogram — ``histogram_quantile(rate(...))`` in
      PromQL terms), so a past burst stops breaching once it leaves the
      window; without it, over everything ever observed.
    * ``{"name", "kind": "burn_rate", "total", "bad", "budget",
      "window_s", "max_burn"[, "match_total", "match_bad"]}`` — over
      the trailing ``window_s``, ``(Δbad/Δtotal) / budget`` must be ≤
      ``max_burn`` (the SRE error-budget burn rate; 1.0 = burning
      exactly the budget).

    A rule with no data yet (empty histogram, zero traffic) evaluates
    OK — absence of evidence never pages.
    """

    def __init__(self, rules: list[dict], *, clock=time.time):
        self.rules = []
        for r in rules:
            if not isinstance(r, dict) or not r.get("name"):
                raise ValueError(f"SLO rule needs a name: {r!r}")
            kind = r.get("kind")
            if kind not in _RULE_KINDS:
                raise ValueError(
                    f"SLO rule {r['name']!r}: kind {kind!r} not in "
                    f"{_RULE_KINDS}")
            if kind == "quantile":
                missing = [k for k in ("metric", "q", "target")
                           if k not in r]
            else:
                missing = [k for k in ("total", "bad", "budget",
                                       "window_s", "max_burn")
                           if k not in r]
            if missing:
                raise ValueError(
                    f"SLO rule {r['name']!r} is missing {missing}")
            self.rules.append(dict(r))
        self._clock = clock
        self._ok: dict[str, bool] = {}
        self._hist: dict[str, collections.deque] = {
            r["name"]: collections.deque() for r in self.rules
            if r["kind"] == "burn_rate" or "window_s" in r}
        self._g_ok = core.gauge(
            "slo_ok", "1 while the rule holds, 0 in breach",
            labels=("slo",)) if core.enabled() else core.NULL
        self._g_val = core.gauge(
            "slo_value", "last measured value of the rule's objective",
            labels=("slo",)) if core.enabled() else core.NULL
        self._c_breach = core.counter(
            "slo_breaches_total", "ok -> breach transitions, per rule",
            labels=("slo",)) if core.enabled() else core.NULL
        self._c_recover = core.counter(
            "slo_recoveries_total", "breach -> ok transitions, per rule",
            labels=("slo",)) if core.enabled() else core.NULL

    def _eval_quantile(self, rule: dict, fleet: FleetRegistry,
                       now: float):
        sample = fleet.histogram(rule["metric"], rule.get("match"))
        if sample and "window_s" in rule:
            sample = self._windowed(rule, sample, now)
        if not sample or not sample.get("count"):
            return True, float("nan")
        v = estimate_quantile(sample, float(rule["q"]))
        return (not v > float(rule["target"])), v

    def _windowed(self, rule: dict, sample: dict, now: float) -> dict:
        """Trailing-window view of a cumulative histogram: keep a
        history of merged samples and return current minus the oldest
        one still inside ``window_s``.  A shrinking count (a source
        restarted) resets the history — the current cumulative sample
        IS the window then, same as a Prometheus counter reset."""
        hist = self._hist[rule["name"]]
        if hist and sample["count"] < hist[-1][1]["count"]:
            hist.clear()
        hist.append((now, sample))
        horizon = now - float(rule["window_s"])
        while len(hist) > 1 and hist[1][0] <= horizon:
            hist.popleft()
        base = hist[0][1]
        if base is sample:
            return sample
        try:
            return {"sum": sample["sum"] - base["sum"],
                    "count": sample["count"] - base["count"],
                    "inf": sample["inf"] - base["inf"],
                    "buckets": {k: sample["buckets"][k] - v
                                for k, v in base["buckets"].items()}}
        except KeyError:
            # bucket bounds changed under us (process restart with a
            # different config): fall back to the raw cumulative view
            return sample

    def _eval_burn(self, rule: dict, fleet: FleetRegistry, now: float):
        bad = fleet.total(rule["bad"], rule.get("match_bad"))
        total = fleet.total(rule["total"], rule.get("match_total"))
        hist = self._hist[rule["name"]]
        hist.append((now, bad, total))
        horizon = now - float(rule["window_s"])
        while len(hist) > 1 and hist[1][0] <= horizon:
            hist.popleft()
        t0, bad0, total0 = hist[0]
        dbad, dtotal = bad - bad0, total - total0
        if dtotal <= 0:
            return True, 0.0
        burn = (dbad / dtotal) / float(rule["budget"])
        return (not burn > float(rule["max_burn"])), burn

    def evaluate(self, fleet: FleetRegistry, now: float | None = None
                 ) -> list[dict]:
        """One evaluation round.  Returns one event per rule:
        ``{"slo", "kind", "ok", "value", "target", "changed"}`` —
        ``changed`` marks a breach/recovery TRANSITION, which is what
        increments the counters and lands ``slo.breach`` /
        ``slo.recover`` records in the span trail."""
        now = self._clock() if now is None else now
        events = []
        for rule in self.rules:
            name = rule["name"]
            if rule["kind"] == "quantile":
                ok, value = self._eval_quantile(rule, fleet, now)
                target = float(rule["target"])
            else:
                ok, value = self._eval_burn(rule, fleet, now)
                target = float(rule["max_burn"])
            prev = self._ok.get(name)
            changed = prev is not None and prev != ok
            self._ok[name] = ok
            self._g_ok.labels(slo=name).set(1 if ok else 0)
            if value == value:      # skip NaN (no data yet)
                self._g_val.labels(slo=name).set(value)
            if changed and not ok:
                self._c_breach.labels(slo=name).inc()
                trace.record_span("slo.breach", 0.0, slo=name,
                                  value=value, target=target)
            elif changed and ok:
                self._c_recover.labels(slo=name).inc()
                trace.record_span("slo.recover", 0.0, slo=name,
                                  value=value, target=target)
            elif prev is None and not ok:
                # first evaluation already in breach: still a breach
                # event — the fleet came up violating its objective.
                self._c_breach.labels(slo=name).inc()
                trace.record_span("slo.breach", 0.0, slo=name,
                                  value=value, target=target)
                changed = True
            events.append({"slo": name, "kind": rule["kind"], "ok": ok,
                           "value": value, "target": target,
                           "changed": changed})
        return events

    def breached(self) -> list[str]:
        """Names of the rules currently in breach."""
        return [n for n, ok in self._ok.items() if not ok]
