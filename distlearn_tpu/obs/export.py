"""Telemetry export: JSONL snapshots and an opt-in ``/metrics`` +
``/healthz`` HTTP endpoint.

Two consumers, two formats:

* **JSONL** — :func:`write_snapshot` appends one ``{"type":
  "snapshot", ...}`` record (the full registry) to a run log; together
  with the span records ``obs.trace`` spills to the same file this is
  the trail ``tools/diststat.py`` summarizes and diffs.
* **HTTP** — :func:`start_http_server` runs a daemon thread serving
  Prometheus text on ``/metrics``, a JSON liveness document on
  ``/healthz``, and the full registry as one JSON ``snapshot`` record
  on ``/snapshot`` (the pull side of the fleet aggregation plane —
  ``obs.agg.Collector`` polls it and merges every process's registry
  into the fleet view).  The health payload comes from a pluggable
  source (:func:`set_health_source`) — the concurrent AsyncEA server
  registers ``{live_clients, inflight, drained}`` on ``start()``, so an
  external prober can distinguish "serving", "draining", and "dead"
  without parsing logs.

Everything is opt-in and honors the ``DISTLEARN_OBS`` kill switch:
disabled, :func:`write_snapshot` writes nothing and
:func:`start_http_server` returns ``None``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable

from distlearn_tpu.obs import core

_health_lock = threading.Lock()
_health_source: Callable[[], dict] | None = None


def set_health_source(fn: Callable[[], dict] | None):
    """Install (or clear, with ``None``) the ``/healthz`` payload
    provider.  The callable must be cheap and thread-safe — it runs on
    the HTTP serving thread."""
    global _health_source
    with _health_lock:
        _health_source = fn


def health() -> dict:
    """The current health document (also used by ``/healthz``)."""
    with _health_lock:
        src = _health_source
    doc = {"ok": True, "ts": time.time()}
    if src is not None:
        try:
            doc.update(src())
        except Exception as e:  # a dying server must still answer probes
            doc["ok"] = False
            doc["error"] = repr(e)
    return doc


def write_snapshot(path: str) -> dict | None:
    """Append one full-registry snapshot record to ``path`` (JSONL).
    Returns the record, or ``None`` (and writes nothing) when the kill
    switch is off."""
    if not core.enabled():
        return None
    rec = core.snapshot_record()
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "a") as fh:
        fh.write(json.dumps(rec) + "\n")
    return rec


class _Handler(BaseHTTPRequestHandler):
    def _reply(self, code: int, body: bytes, ctype: str):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802  (http.server API)
        path = self.path.split("?", 1)[0]
        if path == "/metrics":
            body = core.REGISTRY.render_prometheus().encode()
            self._reply(200, body, "text/plain; version=0.0.4")
        elif path == "/healthz":
            doc = health()
            self._reply(200 if doc.get("ok") else 503,
                        (json.dumps(doc) + "\n").encode(),
                        "application/json")
        elif path == "/snapshot":
            rec = core.snapshot_record()
            self._reply(200, (json.dumps(rec) + "\n").encode(),
                        "application/json")
        else:
            self._reply(404, b"not found\n", "text/plain")

    def log_message(self, fmt, *args):
        pass  # probes every few seconds must not spam the training logs


class ObsHTTPServer:
    """Handle for the background endpoint: ``.port`` and ``.close()``."""

    def __init__(self, host: str, port: int):
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True,
                                        name="distlearn-obs-http")
        self._thread.start()

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)


def start_http_server(port: int = 0, host: str = "127.0.0.1"
                      ) -> ObsHTTPServer | None:
    """Serve ``/metrics`` and ``/healthz`` on a daemon thread.
    ``port=0`` binds an OS-assigned port (read it back from
    ``.port``).  Returns ``None`` when the kill switch is off."""
    if not core.enabled():
        return None
    return ObsHTTPServer(host, port)
