"""distobs — dependency-free runtime telemetry for distlearn_tpu.

The runtime organ beside the static pair (distlint: jaxpr/protocol
rules, distcost: compiled-HLO budgets): counters, gauges, fixed-bucket
histograms (``obs.core``), spans with an in-memory ring + JSONL spill
(``obs.trace``), and JSONL/Prometheus export with a ``/healthz``
liveness endpoint (``obs.export``).  ``tools/diststat.py`` aggregates
the JSONL trail into p50/p95/p99 tables and run diffs.

Instrumented layers: ``comm/transport.py`` (per-conn wire bytes, frame
latency, timeout/drop/desync counters), ``parallel/async_ea.py``
(syncs, handshake spans, evictions/rejoins, inflight, center-apply
time), ``train/trainer.py`` (step dispatch timing),
``data/prefetch.py`` (queue depth), and the decode service
``serve/`` (TTFT/TPOT histograms, queue/slot gauges, request
outcomes, tick/prefill spans — docs/SERVING.md).

Kill switch: ``DISTLEARN_OBS=0`` makes every factory return a no-op
sink; the catalog of metric and span names lives in
docs/OBSERVABILITY.md.
"""

from distlearn_tpu.obs.core import (NULL, REGISTRY, configure, counter,
                                    enabled, gauge, histogram,
                                    snapshot_record)
from distlearn_tpu.obs.export import (set_health_source, start_http_server,
                                      write_snapshot)
from distlearn_tpu.obs.trace import (record_span, set_spill, span, spans,
                                     traced)

__all__ = [
    "NULL",
    "REGISTRY",
    "configure",
    "counter",
    "enabled",
    "gauge",
    "histogram",
    "snapshot_record",
    "set_health_source",
    "start_http_server",
    "write_snapshot",
    "record_span",
    "set_spill",
    "span",
    "spans",
    "traced",
]
