"""distobs — dependency-free runtime telemetry for distlearn_tpu.

The runtime organ beside the static pair (distlint: jaxpr/protocol
rules, distcost: compiled-HLO budgets): counters, gauges, fixed-bucket
histograms (``obs.core``), spans with an in-memory ring + JSONL spill
(``obs.trace``), JSONL/Prometheus export with ``/healthz`` liveness and
``/snapshot`` pull endpoints (``obs.export``), and the fleet half —
cross-process trace context on the wire (``obs.trace``), snapshot
aggregation with mergeable histograms and a declarative SLO engine
(``obs.agg``).  ``tools/diststat.py`` aggregates one trail (or a merged
fleet of them) into p50/p95/p99 tables and run diffs;
``tools/tracecat.py`` stitches multi-process trails into per-trace
waterfalls; ``tools/autoscaler.py`` closes the loop from SLO breach to
scaling action.

Instrumented layers: ``comm/transport.py`` (per-conn wire bytes, frame
latency, timeout/drop/desync counters), ``parallel/async_ea.py``
(syncs, handshake spans, evictions/rejoins, inflight, center-apply
time), ``train/trainer.py`` (step dispatch timing),
``data/prefetch.py`` (queue depth), and the decode service
``serve/`` (TTFT/TPOT histograms, queue/slot gauges, request
outcomes, tick/prefill spans — docs/SERVING.md).

Kill switch: ``DISTLEARN_OBS=0`` makes every factory return a no-op
sink; the catalog of metric and span names lives in
docs/OBSERVABILITY.md.
"""

from distlearn_tpu.obs.agg import (Collector, FleetRegistry, MergeError,
                                   SLOEngine)
from distlearn_tpu.obs.core import (NULL, REGISTRY, configure, counter,
                                    enabled, gauge, histogram,
                                    snapshot_record)
from distlearn_tpu.obs.export import (set_health_source, start_http_server,
                                      write_snapshot)
from distlearn_tpu.obs.trace import (TRACE_KEY, new_trace, record_span,
                                     set_process, set_propagate, set_spill,
                                     span, spans, traced, use_context,
                                     wire_context)

__all__ = [
    "NULL",
    "REGISTRY",
    "configure",
    "counter",
    "enabled",
    "gauge",
    "histogram",
    "snapshot_record",
    "set_health_source",
    "start_http_server",
    "write_snapshot",
    "Collector",
    "FleetRegistry",
    "MergeError",
    "SLOEngine",
    "TRACE_KEY",
    "new_trace",
    "record_span",
    "set_process",
    "set_propagate",
    "set_spill",
    "span",
    "spans",
    "traced",
    "use_context",
    "wire_context",
]
