"""Span API — monotonic start/duration records for the host-side hot
paths (handshakes, step dispatch, rejoin cycles).

A span is one timed region: ``with obs.span("async_ea.handshake",
cid=3):`` or ``@obs.traced("data.load")``.  Completed spans land in an
in-memory ring buffer (bounded; the newest ``ring_size`` survive) and,
when a spill path is set, are appended as JSONL — the machine-readable
trail ``tools/diststat.py`` aggregates into p50/p95/p99 tables.

jax bridge: when jax is already imported (this module never imports it
— obs stays dependency-free), each span also opens a
``jax.profiler.TraceAnnotation`` so host spans line up with device
timelines in a captured profile.  The annotation is a cheap no-op while
no trace is active.

Kill switch: with ``DISTLEARN_OBS=0`` :func:`span` returns a shared
null context manager — no record, no timing calls, no allocation.
"""

from __future__ import annotations

import collections
import functools
import json
import os
import sys
import threading
import time

from distlearn_tpu.obs import core

_ring: collections.deque = collections.deque(maxlen=4096)
_spill_lock = threading.Lock()
_spill_fh = None
_spill_path: str | None = None
#: set False to skip the jax.profiler.TraceAnnotation bridge even when
#: jax is loaded (micro-bench isolation).
bridge_jax = True


def set_ring_size(n: int):
    """Resize the in-memory span ring (keeps the newest records)."""
    global _ring
    _ring = collections.deque(_ring, maxlen=int(n))


def set_spill(path: str | None):
    """Append completed spans to ``path`` as JSONL (``None`` closes).
    A no-op while the kill switch is off — a disabled run creates no
    file."""
    global _spill_fh, _spill_path
    with _spill_lock:
        if _spill_fh is not None:
            _spill_fh.close()
            _spill_fh = None
        _spill_path = None
        if path and core.enabled():
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
            _spill_fh = open(path, "a")
            _spill_path = path


def spill_path() -> str | None:
    return _spill_path


def spans() -> list[dict]:
    """Snapshot of the in-memory ring (oldest first)."""
    return list(_ring)


def clear():
    _ring.clear()


def _record(rec: dict):
    _ring.append(rec)
    if _spill_fh is not None:
        line = json.dumps(rec) + "\n"
        with _spill_lock:
            if _spill_fh is not None:
                _spill_fh.write(line)
                _spill_fh.flush()


class _Span:
    __slots__ = ("name", "labels", "_t0", "_ann")

    def __init__(self, name: str, labels: dict):
        self.name = name
        self.labels = labels
        self._ann = None

    def __enter__(self):
        if bridge_jax and "jax" in sys.modules:
            try:
                jax = sys.modules["jax"]
                self._ann = jax.profiler.TraceAnnotation(self.name)
                self._ann.__enter__()
            except Exception:
                self._ann = None
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = time.perf_counter() - self._t0
        if self._ann is not None:
            try:
                self._ann.__exit__(exc_type, exc, tb)
            except Exception:
                pass
        rec = {"type": "span", "name": self.name, "ts": time.time(),
               "dur": dur}
        if self.labels:
            rec["labels"] = self.labels
        if exc_type is not None:
            rec["err"] = exc_type.__name__
        _record(rec)
        return False


class _NullSpan:
    """Shared disabled-path span: no timing, no record, reusable."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


def span(name: str, **labels):
    """Context manager timing one region.  Labels become the span's
    ``labels`` dict in the JSONL record; exceptions are recorded as an
    ``err`` field and re-raised."""
    if not core.enabled():
        return NULL_SPAN
    return _Span(name, labels)


def record_span(name: str, dur: float, **labels):
    """Record a span whose duration was measured by the caller.

    For intervals that don't map to one ``with`` block — e.g. a serving
    request's time-to-first-token spans submit → first stream frame
    across scheduler and engine code that never holds both endpoints.
    The record shape matches :class:`_Span` so trail consumers
    (``tools/diststat.py``) need no special case."""
    if not core.enabled():
        return
    rec = {"type": "span", "name": name, "ts": time.time(),
           "dur": float(dur)}
    if labels:
        rec["labels"] = labels
    _record(rec)


def traced(name: str | None = None):
    """Decorator form: ``@traced()`` uses the function's qualname."""

    def deco(fn):
        label = name or fn.__qualname__

        @functools.wraps(fn)
        def wrapped(*a, **kw):
            with span(label):
                return fn(*a, **kw)

        return wrapped

    return deco
