"""Span API — monotonic start/duration records for the host-side hot
paths (handshakes, step dispatch, rejoin cycles) — plus the
cross-process trace context those spans can ride.

A span is one timed region: ``with obs.span("async_ea.handshake",
cid=3):`` or ``@obs.traced("data.load")``.  Completed spans land in an
in-memory ring buffer (bounded; the newest ``ring_size`` survive; ring
evictions are counted in ``obs_spans_dropped_total`` so a truncated
trail reads as truncated, not quiet) and, when a spill path is set, are
appended as JSONL — the machine-readable trail ``tools/diststat.py``
aggregates into p50/p95/p99 tables and ``tools/tracecat.py`` stitches
into per-trace waterfalls.

Trace context (docs/OBSERVABILITY.md "trace-context wire format"): a
compact dict ``{"t": <trace-id hex>, "s": <parent span-id hex>, "f":
0|1}`` carried under the :data:`TRACE_KEY` field of existing JSON wire
messages (the AsyncEA ``Enter?`` announce, the serving 'G' frame).
Each thread keeps a context *stack*: entering :func:`span` under an
active context allocates a fresh span id and pushes it, so nested spans
record ``trace``/``span``/``parent`` fields and multi-process trails
stitch into one tree.  Threads do not inherit the stack — fan-out legs
re-enter the parent's context explicitly with :func:`use_context`.

Propagation is OFF by default (``DISTLEARN_TRACE_PROP``, the shared
``env_truthy`` spelling): with it off no wire message gains the
:data:`TRACE_KEY` field, so frames are bitwise identical to a
pre-trace peer's — mixed fleets interop unchanged.  Local span
*recording* is governed only by the ``DISTLEARN_OBS`` kill switch.

jax bridge: when jax is already imported (this module never imports it
— obs stays dependency-free), each span also opens a
``jax.profiler.TraceAnnotation`` so host spans line up with device
timelines in a captured profile.  The annotation is a cheap no-op while
no trace is active.

Kill switch: with ``DISTLEARN_OBS=0`` :func:`span` returns a shared
null context manager — no record, no timing calls, no allocation.
"""

from __future__ import annotations

import collections
import contextlib
import functools
import json
import os
import sys
import threading
import time

from distlearn_tpu.obs import core
from distlearn_tpu.utils.flags import env_truthy

#: The JSON-message field the trace context rides under.  The DL310
#: conformance audit (lint/conformance.py) pins the schedules' view of
#: the wire to this constant — rename it here and conformance fires.
TRACE_KEY = "tc"

#: Propagation kill switch (separate from ``DISTLEARN_OBS``): unset or
#: falsy = no wire message carries :data:`TRACE_KEY` (bitwise-legacy
#: frames); truthy = opt in.
PROP_SWITCH = "DISTLEARN_TRACE_PROP"

_ring: collections.deque = collections.deque(maxlen=4096)
_spill_lock = threading.Lock()
_spill_fh = None
_spill_path: str | None = None
_propagate: bool | None = None
_proc: str | None = None
_tls = threading.local()
#: set False to skip the jax.profiler.TraceAnnotation bridge even when
#: jax is loaded (micro-bench isolation).
bridge_jax = True


def set_ring_size(n: int):
    """Resize the in-memory span ring (keeps the newest records)."""
    global _ring
    _ring = collections.deque(_ring, maxlen=int(n))


def set_spill(path: str | None):
    """Append completed spans to ``path`` as JSONL (``None`` closes).
    A no-op while the kill switch is off — a disabled run creates no
    file."""
    global _spill_fh, _spill_path
    with _spill_lock:
        if _spill_fh is not None:
            _spill_fh.close()
            _spill_fh = None
        _spill_path = None
        if path and core.enabled():
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
            _spill_fh = open(path, "a")
            _spill_path = path


def spill_path() -> str | None:
    return _spill_path


def spans() -> list[dict]:
    """Snapshot of the in-memory ring (oldest first)."""
    return list(_ring)


def clear():
    _ring.clear()


def set_process(name: str | None):
    """Stamp every span record this process emits with ``proc: name``
    (``None`` clears).  Multi-trail consumers (``tools/tracecat.py``,
    ``diststat merge``) use it to attribute spans to fleet members even
    when trails are concatenated."""
    global _proc
    _proc = str(name) if name else None


def process_name() -> str | None:
    return _proc


def _dropped_counter():
    # no module-level cache: Registry.reset() (tests) strands live
    # handles, and the get-or-create here is one dict lookup
    return core.REGISTRY.counter(
        "obs_spans_dropped_total",
        "span records evicted from the in-memory ring (ring full); "
        "spilled JSONL is unaffected")


def _record(rec: dict):
    if _proc is not None:
        rec["proc"] = _proc
    if _ring.maxlen is not None and len(_ring) >= _ring.maxlen:
        # the deque evicts its oldest record on this append: the ring
        # view truncates.  Count it — a diststat over the ring (or a
        # trail cut from it) must be able to say "N spans missing".
        _dropped_counter().inc()
    _ring.append(rec)
    if _spill_fh is not None:
        line = json.dumps(rec) + "\n"
        with _spill_lock:
            if _spill_fh is not None:
                _spill_fh.write(line)
                _spill_fh.flush()


# -- trace context -----------------------------------------------------------

def _gen_id(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


def new_trace(sampled: bool = True) -> dict:
    """A fresh root trace context: 64-bit trace id, no parent span yet.
    Enter it with :func:`use_context`; the first :func:`span` under it
    becomes the trace's root span."""
    return {"t": _gen_id(8), "s": "", "f": 1 if sampled else 0}


def valid_context(tc) -> bool:
    """Structural check for a wire-received context — a malformed or
    adversarial ``tc`` field must degrade to "no trace", never raise."""
    if not isinstance(tc, dict):
        return False
    t, s, f = tc.get("t"), tc.get("s", ""), tc.get("f", 1)
    try:
        return (isinstance(t, str) and 0 < len(t) <= 32
                and int(t, 16) >= 0
                and isinstance(s, str) and len(s) <= 32
                and (s == "" or int(s, 16) >= 0)
                and f in (0, 1))
    except ValueError:
        return False


def _stack() -> list:
    st = getattr(_tls, "ctx", None)
    if st is None:
        st = _tls.ctx = []
    return st


def current() -> dict | None:
    """The innermost active context on THIS thread, or ``None``."""
    st = _stack()
    return st[-1] if st else None


def wire_context() -> dict | None:
    """The context to put on an outgoing wire message: current trace id
    with the current span as parent.  ``None`` when no trace is active
    or propagation is disabled — callers simply omit the field then."""
    if not propagate_enabled():
        return None
    cur = current()
    if cur is None:
        return None
    return {"t": cur["t"], "s": cur["s"], "f": 1}


@contextlib.contextmanager
def use_context(tc):
    """Enter a trace context (from :func:`new_trace` or a wire
    message's :data:`TRACE_KEY` field) on this thread.  Invalid,
    ``None``, or unsampled (``f == 0``) contexts are a no-op — the
    block still runs, spans just stay trace-less."""
    if not core.enabled() or not valid_context(tc) or not tc.get("f", 1):
        yield None
        return
    st = _stack()
    st.append({"t": tc["t"], "s": tc.get("s", ""), "f": 1})
    try:
        yield tc
    finally:
        st.pop()


def propagate_enabled() -> bool:
    """Resolved propagation-switch state (cached after the first read);
    implies the obs kill switch is on."""
    global _propagate
    if _propagate is None:
        v = env_truthy(PROP_SWITCH)
        _propagate = False if v is None else v
    return _propagate and core.enabled()


def set_propagate(on: bool | None):
    """Override the propagation switch (tests / tools), or re-read the
    env with ``None``."""
    global _propagate
    _propagate = on


class _Span:
    __slots__ = ("name", "labels", "_t0", "_ann", "_tc")

    def __init__(self, name: str, labels: dict):
        self.name = name
        self.labels = labels
        self._ann = None
        self._tc = None

    def __enter__(self):
        st = _stack()
        if st:
            parent = st[-1]
            sid = _gen_id(4)
            self._tc = (parent["t"], sid, parent["s"])
            st.append({"t": parent["t"], "s": sid, "f": 1})
        if bridge_jax and "jax" in sys.modules:
            try:
                jax = sys.modules["jax"]
                self._ann = jax.profiler.TraceAnnotation(self.name)
                self._ann.__enter__()
            except Exception:
                self._ann = None
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = time.perf_counter() - self._t0
        if self._ann is not None:
            try:
                self._ann.__exit__(exc_type, exc, tb)
            except Exception:
                pass
        rec = {"type": "span", "name": self.name, "ts": time.time(),
               "dur": dur}
        if self.labels:
            rec["labels"] = self.labels
        if exc_type is not None:
            rec["err"] = exc_type.__name__
        if self._tc is not None:
            _stack().pop()
            t, sid, parent = self._tc
            rec["trace"], rec["span"] = t, sid
            if parent:
                rec["parent"] = parent
        _record(rec)
        return False


class _NullSpan:
    """Shared disabled-path span: no timing, no record, reusable."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


def span(name: str, **labels):
    """Context manager timing one region.  Labels become the span's
    ``labels`` dict in the JSONL record; exceptions are recorded as an
    ``err`` field and re-raised.  Under an active trace context the
    record also carries ``trace``/``span``/``parent`` ids and the span
    becomes the context for anything nested in the block."""
    if not core.enabled():
        return NULL_SPAN
    return _Span(name, labels)


def record_span(name: str, dur: float, **labels):
    """Record a span whose duration was measured by the caller.

    For intervals that don't map to one ``with`` block — e.g. a serving
    request's time-to-first-token spans submit → first stream frame
    across scheduler and engine code that never holds both endpoints.
    The record shape matches :class:`_Span` so trail consumers
    (``tools/diststat.py``) need no special case; an active trace
    context stamps it the same way."""
    if not core.enabled():
        return
    rec = {"type": "span", "name": name, "ts": time.time(),
           "dur": float(dur)}
    if labels:
        rec["labels"] = labels
    cur = current()
    if cur is not None:
        rec["trace"], rec["span"] = cur["t"], _gen_id(4)
        if cur["s"]:
            rec["parent"] = cur["s"]
    _record(rec)


def traced(name: str | None = None):
    """Decorator form: ``@traced()`` uses the function's qualname."""

    def deco(fn):
        label = name or fn.__qualname__

        @functools.wraps(fn)
        def wrapped(*a, **kw):
            with span(label):
                return fn(*a, **kw)

        return wrapped

    return deco
