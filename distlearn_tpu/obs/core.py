"""Process-global runtime telemetry registry — counters, gauges, and
fixed-bucket histograms.

The reference's entire observability story is ``colorPrint``
(lua/colorPrint.lua via ``utils/logging.py``); every performance or
robustness number in docs/PERF.md was recomputed by hand from ad-hoc
prints or attributes like ``Conn.bytes_sent``.  This module is the
runtime counterpart of the static analyzers (distlint/distcost): the
framework reports what it actually did — wire bytes per connection,
handshake latencies, eviction churn, step timing — in one process-global
registry that ``obs.export`` can snapshot to JSONL or serve as
Prometheus text.

Design constraints (they shape every API here):

* **Dependency-free.**  Standard library only; no jax import (the span
  bridge in ``obs.trace`` attaches to jax lazily and only when jax is
  already loaded for other reasons).
* **One-branch kill switch.**  ``DISTLEARN_OBS=0`` (parsed with the
  shared ``utils.flags.env_truthy`` rule) turns the whole subsystem off.
  Disabled, the factory functions return the shared :data:`NULL`
  sink whose methods are no-ops — instrumentation sites pay one
  no-op method call, never a per-event ``if``.  Callers that must skip
  work the null object cannot absorb (e.g. ``time.perf_counter()``
  pairs) branch once on :func:`enabled` at *object construction*, not
  per event.
* **Lock-cheap increments.**  Counter/gauge writes are plain attribute
  updates — no lock.  The framework's hot writers are single-threaded
  per metric child (one thread does IO on a ``Conn``), so counts are
  exact where exactness is claimed (wire bytes); for genuinely shared
  counters the worst case under the GIL is a lost increment at
  thread-switch granularity, which telemetry tolerates.  Histograms
  update several fields per observation and take a small per-child
  lock; they sit on coarse paths (handshakes, steps), not per-frame.
* **Bounded label cardinality.**  A metric family accepts at most
  ``max_children`` distinct label sets (default 64; per-conn byte
  counters use a higher bound); past that, new label sets collapse
  into one ``__overflow__`` child, so a rejoin-churning client or a
  port-scanning peer cannot grow the registry without bound.
"""

from __future__ import annotations

import bisect
import re
import threading
import time
from typing import Any

from distlearn_tpu.utils.flags import env_truthy

#: The subsystem kill switch.  Unset or truthy = on; ``0``/``false``/
#: ``off``/empty = off (the shared ``env_truthy`` spelling rule).
KILL_SWITCH = "DISTLEARN_OBS"

_enabled: bool | None = None
_lock = threading.Lock()          # registry + child creation only


def enabled() -> bool:
    """Resolved kill-switch state (cached after the first read)."""
    global _enabled
    if _enabled is None:
        v = env_truthy(KILL_SWITCH)
        _enabled = True if v is None else v
    return _enabled


def configure(on: bool | None = None):
    """Override the kill switch (tests), or re-read the env with ``None``.

    Only affects metric handles created AFTER the call — instrumented
    objects resolve their sinks at construction time, so flip this
    before building the server/conn/iterator under test."""
    global _enabled
    _enabled = on


class _Null:
    """Shared no-op sink: every metric/label operation on the disabled
    path lands here.  Methods allocate nothing (asserted by the tier-1
    overhead test)."""

    __slots__ = ()

    def inc(self, n=1):
        pass

    def dec(self, n=1):
        pass

    def set(self, v):
        pass

    def observe(self, v):
        pass

    def labels(self, **kv):
        return self


NULL = _Null()

#: Default histogram buckets (seconds): spans frame receives (~10us on
#: loopback) through multi-second handshakes.
LATENCY_BUCKETS = (1e-5, 1e-4, 1e-3, 5e-3, 0.025, 0.1, 0.5, 1.0, 5.0, 30.0)


class _Counter:
    kind = "counter"
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0

    def inc(self, n=1):
        self.value += n

    def sample(self):
        return {"value": self.value}


class _Gauge:
    kind = "gauge"
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0

    def set(self, v):
        self.value = v

    def inc(self, n=1):
        self.value += n

    def dec(self, n=1):
        self.value -= n

    def sample(self):
        return {"value": self.value}


class _Histogram:
    kind = "histogram"
    __slots__ = ("buckets", "counts", "sum", "count", "_hlock")

    def __init__(self, buckets=LATENCY_BUCKETS):
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self.counts = [0] * (len(self.buckets) + 1)   # last = +Inf
        self.sum = 0.0
        self.count = 0
        self._hlock = threading.Lock()

    def observe(self, v):
        v = float(v)
        i = bisect.bisect_left(self.buckets, v)
        with self._hlock:
            self.counts[i] += 1
            self.sum += v
            self.count += 1

    def sample(self):
        with self._hlock:
            counts = list(self.counts)
            return {"sum": self.sum, "count": self.count,
                    "buckets": {str(b): c
                                for b, c in zip(self.buckets, counts)},
                    "inf": counts[-1]}


_OVERFLOW = "__overflow__"


class Family:
    """One named metric with labeled children.  ``labels()`` resolves a
    child (creating it under the registry lock on first use — cache the
    returned child on hot paths); families declared without label names
    proxy the metric operations to their single default child."""

    def __init__(self, cls, name: str, help: str = "",
                 labelnames: tuple = (), max_children: int = 64, **kw):
        self._cls, self._kw = cls, kw
        self.name, self.help = name, help
        self.labelnames = tuple(labelnames)
        self.max_children = max_children
        self.kind = cls.kind
        self._children: dict[tuple, Any] = {}
        if not self.labelnames:
            self._children[()] = cls(**kw)

    def labels(self, **kv):
        key = tuple(str(kv.get(k, "")) for k in self.labelnames)
        child = self._children.get(key)
        if child is None:
            with _lock:
                child = self._children.get(key)
                if child is None:
                    if len(self._children) >= self.max_children:
                        key = (_OVERFLOW,) * len(self.labelnames)
                        child = self._children.get(key)
                        if child is None:
                            child = self._cls(**self._kw)
                            self._children[key] = child
                    else:
                        child = self._cls(**self._kw)
                        self._children[key] = child
        return child

    # unlabeled families act as the metric itself
    def inc(self, n=1):
        self._children[()].inc(n)

    def dec(self, n=1):
        self._children[()].dec(n)

    def set(self, v):
        self._children[()].set(v)

    def observe(self, v):
        self._children[()].observe(v)

    @property
    def value(self):
        return self._children[()].value

    def sample(self):
        with _lock:
            items = list(self._children.items())
        return [{"labels": dict(zip(self.labelnames, key)), **c.sample()}
                for key, c in items]


class Registry:
    """Name -> :class:`Family`.  One process-global instance
    (:data:`REGISTRY`); tests may build private ones."""

    def __init__(self):
        self._families: dict[str, Family] = {}

    def _get(self, cls, name, help, labelnames, **kw) -> Family:
        fam = self._families.get(name)
        if fam is None:
            with _lock:
                fam = self._families.get(name)
                if fam is None:
                    fam = Family(cls, name, help, labelnames, **kw)
                    self._families[name] = fam
        if fam.kind != cls.kind or fam.labelnames != tuple(labelnames):
            raise ValueError(
                f"metric {name!r} re-registered as {cls.kind} with labels "
                f"{tuple(labelnames)!r} (was {fam.kind} {fam.labelnames!r})")
        return fam

    def counter(self, name, help="", labels=(), **kw) -> Family:
        return self._get(_Counter, name, help, labels, **kw)

    def gauge(self, name, help="", labels=(), **kw) -> Family:
        return self._get(_Gauge, name, help, labels, **kw)

    def histogram(self, name, help="", labels=(), buckets=LATENCY_BUCKETS,
                  **kw) -> Family:
        return self._get(_Histogram, name, help, labels, buckets=buckets,
                         **kw)

    def snapshot(self) -> list[dict]:
        """All families as plain dicts (the JSONL ``snapshot`` payload)."""
        with _lock:
            fams = list(self._families.values())
        return [{"name": f.name, "kind": f.kind, "help": f.help,
                 "labelnames": list(f.labelnames), "samples": f.sample()}
                for f in fams]

    def render_prometheus(self) -> str:
        """Prometheus text exposition format (the ``/metrics`` body).
        Metric/label names are sanitized (stable: same input, same
        output), label values escaped, HELP text escaped — so a scraper
        round-trips whatever instrumentation names reach the registry."""
        out = []
        for fam in self.snapshot():
            name = _sane_name(fam["name"])
            if fam["help"]:
                out.append(f"# HELP {name} {_escape_help(fam['help'])}")
            out.append(f"# TYPE {name} {fam['kind']}")
            for s in fam["samples"]:
                lbl = _fmt_labels(s["labels"])
                if fam["kind"] == "histogram":
                    cum = 0
                    for b, c in s["buckets"].items():
                        cum += c
                        out.append(f"{name}_bucket"
                                   f"{_fmt_labels(s['labels'], le=b)} {cum}")
                    out.append(f"{name}_bucket"
                               f"{_fmt_labels(s['labels'], le='+Inf')} "
                               f"{s['count']}")
                    out.append(f"{name}_sum{lbl} {s['sum']}")
                    out.append(f"{name}_count{lbl} {s['count']}")
                else:
                    out.append(f"{name}{lbl} {s['value']}")
        return "\n".join(out) + "\n"

    def reset(self):
        """Drop every family (tests only — live handles go stale)."""
        with _lock:
            self._families.clear()


def _fmt_labels(labels: dict, **extra) -> str:
    kv = {**labels, **{k: str(v) for k, v in extra.items()}}
    if not kv:
        return ""
    body = ",".join(f'{_sane_label(k)}="{_escape(v)}"'
                    for k, v in kv.items())
    return "{" + body + "}"


def _escape(v: str) -> str:
    return str(v).replace("\\", r"\\").replace('"', r'\"').replace(
        "\n", r"\n")


def _escape_help(v: str) -> str:
    # HELP lines escape backslash and newline only (quotes stay literal
    # — the exposition format, not the label-value rule).
    return str(v).replace("\\", r"\\").replace("\n", r"\n")


def _sane_name(name: str) -> str:
    """Map an arbitrary metric name onto ``[a-zA-Z_:][a-zA-Z0-9_:]*``
    deterministically (each invalid char becomes ``_``) so one registry
    name always renders as one exposition name."""
    name = re.sub(r"[^a-zA-Z0-9_:]", "_", str(name))
    return name if name and not name[0].isdigit() else "_" + name


def _sane_label(name: str) -> str:
    """Label names additionally exclude ``:`` (reserved for recording
    rules on the Prometheus side)."""
    name = re.sub(r"[^a-zA-Z0-9_]", "_", str(name))
    return name if name and not name[0].isdigit() else "_" + name


REGISTRY = Registry()


# -- module-level factories (the instrumentation surface) -------------------

def counter(name, help="", labels=(), **kw):
    """A counter family, or :data:`NULL` when the kill switch is off."""
    if not enabled():
        return NULL
    return REGISTRY.counter(name, help, labels, **kw)


def gauge(name, help="", labels=(), **kw):
    if not enabled():
        return NULL
    return REGISTRY.gauge(name, help, labels, **kw)


def histogram(name, help="", labels=(), buckets=LATENCY_BUCKETS, **kw):
    if not enabled():
        return NULL
    return REGISTRY.histogram(name, help, labels, buckets=buckets, **kw)


def snapshot_record() -> dict:
    """One JSONL ``snapshot`` record of the whole registry."""
    return {"type": "snapshot", "ts": time.time(),
            "metrics": REGISTRY.snapshot()}
