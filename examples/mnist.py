#!/usr/bin/env python
"""Distributed MNIST training with AllReduceSGD — the TPU-native counterpart
of the reference's minimum end-to-end path (examples/mnist.lua via mnist.sh).

Reference cadence reproduced (SURVEY.md §3.1): identical init + initial sync
(mnist.lua:47,72), per-step gradient allreduce + normalize (mnist.lua:109) +
SGD update (mnist.lua:112-116) — all fused into one XLA program per step —
confusion matrix allreduced and printed every ``--reportEvery`` steps
(mnist.lua:120-125), end-of-epoch parameter sync (mnist.lua:129).

Run:  python examples/mnist.py --numNodes 4 [--tpu] [--data mnist.npz]
"""

from __future__ import annotations

from common import setup_platform, resolve_num_nodes, device_stream
from distlearn_tpu.utils.flags import (parse_flags, NODE_FLAGS, TRAIN_FLAGS)


def main():
    opt = parse_flags("Train an MNIST handwritten digit classifier.", {
        **NODE_FLAGS,
        **TRAIN_FLAGS,
        "learningRate": (0.01, "learning rate (mnist.lua:112)"),
        "data": ("", "path to .npz with x [N,32,32,1]/y (default: synthetic)"),
        "numExamples": (4096, "synthetic dataset size"),
        "reportEvery": (100, "steps between confusion-matrix reports"),
        "parity": (False, "print a final JSON accuracy line "
                          "(BASELINE.md accuracy-parity harness)"),
        "optimizer": ("sgd", "sgd (reference parity, fused Pallas path) | "
                             "momentum | adam | adam-zero1 (optimizer "
                             "state sharded over the nodes)"),
        "lrSchedule": ("constant", "constant | cosine | warmup-cosine — "
                                   "optax schedule for the optax "
                                   "optimizers (--optimizer != sgd; the "
                                   "sgd path keeps the reference's fixed "
                                   "lr)"),
        "deviceData": (False, "dataset resident in device memory, batches "
                              "gathered on-device (see cifar10.py)"),
    })
    setup_platform(opt.numNodes, opt.tpu)

    import jax
    import numpy as np
    from jax import random

    from distlearn_tpu.data import (DeviceDataset, PermutationSampler,
                                    load_npz, make_dataset, synthetic_mnist)
    from distlearn_tpu.models import mnist_cnn
    from distlearn_tpu.parallel.mesh import MeshTree
    from distlearn_tpu.train import (build_sgd_step, build_sync_step,
                                     init_train_state, reduce_confusion)
    from distlearn_tpu.utils import metrics as M
    from distlearn_tpu.utils.logging import root_print
    from distlearn_tpu.utils.profiling import StepTimer

    log = root_print(0)
    tree = MeshTree(num_nodes=resolve_num_nodes(opt.numNodes, opt.tpu))
    log(f"mesh: {tree.num_nodes} nodes on {jax.devices()[0].platform}")

    if opt.data:
        x, y, nc = load_npz(opt.data)
    else:
        x, y, nc = synthetic_mnist(opt.numExamples, seed=opt.seed)
    ds = make_dataset(x, y, nc)
    if opt.deviceData:
        from jax.sharding import NamedSharding, PartitionSpec as P
        dds = DeviceDataset(
            ds.x, ds.y, nc, sharding=NamedSharding(tree.mesh, P()),
            out_sharding=NamedSharding(tree.mesh, P(tree.axis_name)))

    def train_stream(sampler):
        if opt.deviceData:
            return dds.batches(sampler, opt.batchSize)
        return device_stream(tree, ds, sampler, opt.batchSize)

    model = mnist_cnn()
    _SCHEDULES = ("constant", "cosine", "warmup-cosine")
    if opt.lrSchedule not in _SCHEDULES:
        raise SystemExit(f"unknown --lrSchedule {opt.lrSchedule!r} "
                         f"(choose {', '.join(_SCHEDULES)})")
    if opt.optimizer == "sgd" and opt.lrSchedule != "constant":
        raise SystemExit("--lrSchedule needs an optax optimizer "
                         "(--optimizer momentum|adam|adam-zero1); the sgd "
                         "path keeps the reference's fixed lr")
    if opt.optimizer == "sgd":      # reference cadence (mnist.lua:112-116)
        ts = init_train_state(model, tree, random.PRNGKey(opt.seed), nc)
        step = build_sgd_step(model, tree, lr=opt.learningRate)
    else:                           # the reference's `optim` slot -> optax
        import optax

        from distlearn_tpu.train import (build_optax_step,
                                         build_zero_optax_step,
                                         init_optax_state, init_zero_state)
        total_steps = max(1, opt.numEpochs * (ds.size // opt.batchSize))
        schedules = {
            "constant": lambda: opt.learningRate,
            "cosine": lambda: optax.cosine_decay_schedule(
                opt.learningRate, decay_steps=total_steps),
            "warmup-cosine": lambda: optax.warmup_cosine_decay_schedule(
                0.0, opt.learningRate,
                warmup_steps=max(1, total_steps // 10),
                decay_steps=total_steps),
        }
        lr = schedules[opt.lrSchedule]()
        txs = {"momentum": lambda: optax.sgd(lr, momentum=0.9),
               "adam": lambda: optax.adam(lr),
               "adam-zero1": lambda: optax.adam(lr)}
        if opt.optimizer not in txs:
            raise SystemExit(f"unknown --optimizer {opt.optimizer!r} "
                             f"(choose sgd, {', '.join(txs)})")
        tx = txs[opt.optimizer]()
        if opt.optimizer == "adam-zero1":
            ts = init_zero_state(model, tree, tx, random.PRNGKey(opt.seed), nc)
            step = build_zero_optax_step(model, tree, tx)
        else:
            ts = init_optax_state(model, tree, tx, random.PRNGKey(opt.seed), nc)
            step = build_optax_step(model, tree, tx)
    # winner-takes-all epoch sync is the uneven-participation repair; these
    # full-participation runs keep params replicated, so it is an identity
    # for the optax paths (and their state shape differs from TrainState)
    sync = build_sync_step(tree) if opt.optimizer == "sgd" else (lambda s: s)

    timer = StepTimer()
    global_step = 0
    final_acc = 0.0
    for epoch in range(1, opt.numEpochs + 1):
        sampler = PermutationSampler(ds.size, seed=opt.seed + epoch)
        timer.reset_window()   # epoch-boundary sync/report time is not a step
        for bx, by in train_stream(sampler):
            timer.tick()
            ts, loss = step(ts, bx, by)
            global_step += 1
            if global_step % opt.reportEvery == 0:
                cm = reduce_confusion(ts.cm)
                log(f"step {global_step} loss {float(loss):.4f} "
                    f"{M.format_confusion(cm)}")
        ts = sync(ts)  # end-of-epoch sync (mnist.lua:129)
        cm = reduce_confusion(ts.cm)
        log(f"epoch {epoch}: {M.format_confusion(cm)} "
            f"({timer.steps_per_sec():.1f} steps/s)")
        final_acc = M.total_valid(cm)
        ts = ts._replace(cm=jax.tree_util.tree_map(lambda c: c * 0, ts.cm))
    jax.block_until_ready(ts.params)
    if opt.parity:
        import json
        print(json.dumps({
            "example": "mnist", "epochs": opt.numEpochs,
            "data": "npz" if opt.data else "synthetic",
            "global_batch": opt.batchSize, "nodes": tree.num_nodes,
            "train_acc": round(final_acc, 4),
        }))
    log("done")


if __name__ == "__main__":
    main()
