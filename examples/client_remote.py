#!/usr/bin/env python
"""Multi-host training over the TCP tree backend — the working counterpart
of the reference's ``examples/client_remote.lua``.

The reference's script wires an explicit multi-host topology — node 1 runs
``ipc.server``, every other host dials it, all build ``ipc.Tree`` over TCP
(client_remote.lua:34-41) — but is stale: it calls AsyncEA with
AllReduceEA's API (client_remote.lua:43,158-236 vs lua/AsyncEA.lua:294-303),
so it documents the intended topology without running (SURVEY.md §2a row
"client_remote").  This is that intent, working: each PROCESS (one per
host) trains locally — on its own accelerator with ``--tpu``, else CPU —
and synchronizes elastically through distlearn_tpu.comm.tree over DCN,
with the reference's AllReduceEA semantics (host_algorithms).

Single machine (two "hosts" as processes — client_remote.sh):

    python examples/client_remote.py --nodeIndex 1 --numNodes 2 &
    python examples/client_remote.py --nodeIndex 2 --numNodes 2 &

Across real machines: run node 1 on the coordinator host, point the others
at it, and tell each rank how it can be reached::

    host-a$ python examples/client_remote.py --nodeIndex 1 --numNodes 2 \
                --host 0.0.0.0 --advertiseHost host-a --port 9090
    host-b$ python examples/client_remote.py --nodeIndex 2 --numNodes 2 \
                --host host-a --listenHost 0.0.0.0 --advertiseHost host-b

(For pod-scale SPMD over a shared XLA runtime use
``distlearn_tpu.parallel.init`` / ``jax.distributed.initialize`` instead —
this script is the socket-tree deployment shape.)
"""

from __future__ import annotations

import hashlib

from common import setup_platform
from distlearn_tpu.utils.flags import (parse_flags, EA_FLAGS, NODE_FLAGS,
                                       TRAIN_FLAGS)


def main():
    opt = parse_flags("Multi-host elastic-averaging training (TCP tree).", {
        **NODE_FLAGS,
        **TRAIN_FLAGS,
        **EA_FLAGS,
        "host": ("127.0.0.1", "rank-0 coordinator address every node dials "
                              "(client_remote.lua:8,34-39)"),
        "port": (9090, "coordinator port (client_remote.lua:9)"),
        "base": (2, "tree fan-out (client_remote.lua:12)"),
        "backend": ("tree", "host collective: tree (reference topology, "
                            "latency-optimal) | ring (bandwidth-optimal — "
                            "comm/ring.py)"),
        "listenHost": ("", "local bind address for this rank's child "
                           "listener (multi-host: 0.0.0.0)"),
        "advertiseHost": ("", "address other ranks dial to reach this rank"),
        "learningRate": (0.01, "local SGD learning rate"),
        "numExamples": (2048, "synthetic dataset size (global)"),
        "data": ("", "path to .npz with x [N,32,32,1]/y (default: synthetic)"),
    })
    # One process == one node here (the reference's process-per-host shape):
    # no virtual device mesh, just this host's backend.
    setup_platform(1, opt.tpu)

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import random, value_and_grad

    from distlearn_tpu.comm.ring import Ring
    from distlearn_tpu.comm.tree import Tree
    from distlearn_tpu.data import PermutationSampler, load_npz, make_dataset, \
        synthetic_mnist
    from distlearn_tpu.data.dataset import per_node_batch_size
    from distlearn_tpu.models import mnist_cnn
    from distlearn_tpu.models.core import loss_fn
    from distlearn_tpu.parallel.host_algorithms import TreeAllReduceEA
    from distlearn_tpu.utils.logging import root_print

    rank = opt.nodeIndex - 1            # reference nodeIndex is 1-based
    log = root_print(rank)
    if opt.backend == "ring":
        tree = Ring(rank, opt.numNodes, opt.host, opt.port,
                    listen_host=opt.listenHost or None,
                    advertise_host=opt.advertiseHost or None)
    elif opt.backend == "tree":
        tree = Tree(rank, opt.numNodes, opt.host, opt.port, base=opt.base,
                    listen_host=opt.listenHost or None,
                    advertise_host=opt.advertiseHost or None)
    else:
        raise SystemExit(f"unknown --backend {opt.backend!r} (tree | ring)")
    log(f"{opt.backend} up: {opt.numNodes} nodes, "
        f"platform {jax.devices()[0].platform}")

    if opt.data:
        x, y, nc = load_npz(opt.data)
    else:
        x, y, nc = synthetic_mnist(opt.numExamples, seed=opt.seed)
    ds = make_dataset(x, y, nc, partition=rank, partitions=opt.numNodes)
    per_node = per_node_batch_size(opt.batchSize, opt.numNodes)

    model = mnist_cnn()
    params, mstate = model.init(random.PRNGKey(opt.seed))  # same seed: same init
    ea = TreeAllReduceEA(tree, tau=opt.communicationTime, alpha=opt.alpha)
    params = ea.synchronize_parameters(params)   # initial scatter (lua :63-ish)

    @jax.jit
    def local_step(p, s, bx, by):
        (loss, (_, s)), grads = value_and_grad(
            lambda q: loss_fn(model, q, s, bx, by, train=True),
            has_aux=True)(p)
        p = jax.tree_util.tree_map(
            lambda w, g: w - jnp.asarray(opt.learningRate, w.dtype) * g, p, grads)
        return p, s, loss

    for epoch in range(1, opt.numEpochs + 1):
        sampler = PermutationSampler(ds.size, seed=opt.seed + epoch + rank)
        losses = []
        for idx in sampler.epoch(per_node):
            params, mstate, loss = local_step(
                params, mstate, ds.x[idx], ds.y[idx])
            losses.append(float(loss))
            # elastic round every tau-th step, zero comm otherwise
            params = ea.average_parameters(jax.device_get(params))
        params = ea.synchronize_center(jax.device_get(params))
        log(f"epoch {epoch}: mean loss {np.mean(losses):.4f}")

    params = ea.synchronize_parameters(jax.device_get(params))
    flat = np.concatenate([np.asarray(l).ravel()
                           for l in jax.tree_util.tree_leaves(params)])
    digest = hashlib.sha256(flat.tobytes()).hexdigest()[:16]
    # identical on every node — the reference's own sync oracle
    # (test_AllReduceSGD.lua:38)
    print(f"[node {opt.nodeIndex}] final params digest {digest}")
    tree.close()


if __name__ == "__main__":
    main()
