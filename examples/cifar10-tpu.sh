#!/bin/bash
# Reference parity: examples/cifar10-cuda.sh (4 nodes, one GPU each) ->
# TPU mesh. On a single-chip host this runs 1 node; on a pod slice the mesh
# spans all local chips.
cd "$(dirname "$0")"
python cifar10.py --numNodes ${NUM_NODES:-1} --tpu --batchSize 256 "$@"
